"""Sequence-to-sequence transduction, end to end: text → BPE → seq2seq.

The full text-pipeline walkthrough for the encoder-decoder family — the
chain a translation-style user runs:

1. `data.tokenizer.ByteBPETokenizer`: train byte-BPE on the raw corpus
   (saved next to the checkpoints for serving-side reuse);
2. `models.seq2seq.Seq2SeqTransformer`: teacher-forced training through
   the Trainer's dict-batch feeding (pytree-aware end to end), on any
   mesh — data×model (Megatron TP over the cross projections too) or
   data×seq (all three attention families as ring collectives);
3. `make_seq2seq_generate_fn`: encode once + BOS prefill + the decode
   scan as ONE compiled program, with the per-layer cross-K/V cache.

The task is synthetic string REVERSAL at the word level ("alpha beta
gamma" → "gamma beta alpha") — zero-egress stand-in for translation with
the same shape: content must flow through cross-attention (the output
vocabulary is the input's, but the ALIGNMENT is position-reversed, so
copying fails and attention must learn the reversal).

Run:
    python examples/seq2seq_translation.py
    HVT_MESH="data=4,model=2" python examples/seq2seq_translation.py
    HVT_MESH="data=2,seq=4"  python examples/seq2seq_translation.py

Knobs: DOCS, DRIVE_EPOCHS, DMODEL, PS_MODEL_PATH.
"""

import os

try:
    import horovod_tpu  # noqa: F401
except ModuleNotFoundError:
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np
import optax

import horovod_tpu as hvt
from horovod_tpu.data.tokenizer import ByteBPETokenizer
from horovod_tpu.models.seq2seq import (
    Seq2SeqTransformer,
    make_seq2seq_generate_fn,
    param_specs,
)
from horovod_tpu.models.transformer import ShardingConfig
from horovod_tpu.parallel import mesh as mesh_lib

PAD, BOS, EOS = 0, 1, 2
WORDS = [
    "alpha", "beta", "gamma", "delta", "epsilon", "zeta", "eta", "theta",
    "iota", "kappa", "lambda", "mu", "nu", "xi",
]


def corpus(n: int, seed: int = 0):
    """(source sentence, word-reversed target) string pairs."""
    rng = np.random.RandomState(seed)
    pairs = []
    for _ in range(n):
        ws = list(rng.choice(WORDS, size=rng.randint(2, 6)))
        pairs.append((" ".join(ws), " ".join(reversed(ws))))
    return pairs


def encode_pairs(tok, pairs, max_len: int):
    """Fixed-shape (src, tgt_in, labels) arrays; EOS-terminated, PAD-tailed.
    Label PAD positions are trained to PAD — harmless for the accuracy of
    the real positions and keeps the Trainer's plain CE loss usable."""
    n = len(pairs)
    src = np.full((n, max_len), PAD, np.int32)
    tgt_in = np.full((n, max_len), PAD, np.int32)
    labels = np.full((n, max_len), PAD, np.int32)
    for i, (s, t) in enumerate(pairs):
        se = (tok.encode(s) + [tok.special_id("<eos>")])[:max_len]
        te = (tok.encode(t) + [tok.special_id("<eos>")])[:max_len]
        src[i, : len(se)] = se
        labels[i, : len(te)] = te
        tgt_in[i, 0] = BOS
        tgt_in[i, 1 : len(te)] = te[:-1]
    return src, tgt_in, labels


def main() -> None:
    hvt.init()
    mesh = mesh_lib.build_mesh(
        mesh_lib.MeshSpec.from_string(os.environ.get("HVT_MESH"))
    )
    model_dir = os.path.join(
        os.environ.get("PS_MODEL_PATH", "./models"), "seq2seq-reversal"
    )
    os.makedirs(model_dir, exist_ok=True)

    n_docs = int(os.environ.get("DOCS", 8192))
    pairs = corpus(n_docs)
    # Merge budget sized so every word collapses to ~1 token (≈6 bytes/word
    # × 14 words needs ~100 merges); with too few merges 4-5-word sentences
    # overflow max_len and silent truncation drops the EOS and the source
    # tail — the words the reversed target must BEGIN with.
    tok = ByteBPETokenizer.train(
        (s for p in pairs for s in p), vocab_size=256 + 128 + 1,
        specials=("<eos>",),
    )
    tok.save(os.path.join(model_dir, "tokenizer.json"))
    max_len = 16
    # Refuse (don't truncate) pairs that can't fit: a clipped pair is
    # unanswerable by construction and silently poisons the accuracy gate.
    fit_pairs = [
        p for p in pairs
        if max(len(tok.encode(p[0])), len(tok.encode(p[1]))) < max_len
    ]
    if hvt.is_primary() and len(fit_pairs) < len(pairs):
        print(f"dropped {len(pairs) - len(fit_pairs)} overlong pairs")
    pairs = fit_pairs
    src, tgt_in, labels = encode_pairs(tok, pairs, max_len)
    if hvt.is_primary():
        print(
            f"byte-BPE vocab {tok.vocab_size}; {n_docs} pairs at "
            f"max_len {max_len}"
        )

    # Model vocab padded up to a multiple of 8: the column-parallel lm_head
    # shards its vocab dim over the `model` axis, so it must divide evenly
    # (tokenizer vocab sizes are data-dependent and can land odd). Unused
    # ids never appear in labels and cost nothing.
    model_vocab = -(-tok.vocab_size // 8) * 8
    model = Seq2SeqTransformer(
        vocab_size=model_vocab,
        d_model=int(os.environ.get("DMODEL", 96)),
        n_heads=4,
        n_enc_layers=2,
        n_dec_layers=2,
        dropout=0.0,
        pad_id=PAD,
        sharding=ShardingConfig(mesh=mesh),
    )
    # LR scales by the DATA-parallel degree, not total chips: with a live
    # `model` (TP) axis the global batch grows only with dp, and the linear
    # -scaling rule (tensorflow2_keras_mnist.py:55) follows the batch. On a
    # pure-DP mesh this equals the reference's hvt.scale_lr.
    dp = mesh.shape.get(mesh_lib.DATA_AXIS, 1) * mesh.shape.get(
        mesh_lib.FSDP_AXIS, 1
    )
    trainer = hvt.Trainer(
        model,
        hvt.DistributedOptimizer(optax.adam(1e-3 * dp)),
        loss="sparse_categorical_crossentropy",
        mesh=mesh,
        param_specs=param_specs,
    )
    epochs = int(os.environ.get("DRIVE_EPOCHS", 6))
    hist = trainer.fit(
        x={"src": src, "tgt": tgt_in}, y=labels,
        epochs=epochs, batch_size=16,
        callbacks=[
            hvt.callbacks.BroadcastGlobalVariablesCallback(0),
            # The reference's large-batch recipe (scale_lr needs its
            # warmup, tensorflow2_keras_mnist.py:78-82): the scaled LR
            # from a cold start can land this task in a copy-instead-of-
            # reverse local minimum on wide data-parallel meshes.
            hvt.callbacks.LearningRateWarmupCallback(
                warmup_epochs=2, world_size=dp
            ),
        ],
        verbose=1,
    )

    # Held-out generation: greedy decode must produce the reversal.
    test_pairs = [
        p for p in corpus(48, seed=999)
        if max(len(tok.encode(p[0])), len(tok.encode(p[1]))) < max_len
    ][:32]
    tsrc, _, tlabels = encode_pairs(tok, test_pairs, max_len)
    gen = make_seq2seq_generate_fn(
        model.clone(sharding=ShardingConfig()),  # decode: no seq axis
        max_new_tokens=max_len, bos_id=BOS, eos_id=tok.special_id("<eos>"),
    )
    params = jax.device_get(trainer.state.params)
    out = np.asarray(gen(params, tsrc, jax.random.PRNGKey(0)))
    # Token accuracy over the real (non-PAD) label positions.
    mask = tlabels != PAD
    acc = float((out[mask == True] == tlabels[mask]).mean())  # noqa: E712
    if hvt.is_primary():
        eos = tok.special_id("<eos>")
        for i in range(2):
            row = list(out[i])
            row = row[: row.index(eos)] if eos in row else row
            print("src:", test_pairs[i][0])
            print("out:", tok.decode([t for t in row if t > EOS]))
        print(f"held-out reversal token accuracy: {acc:.3f}")
        print("REVERSAL " + ("LEARNED" if acc > 0.8 else "NOT LEARNED"))


if __name__ == "__main__":
    main()
