"""Service-fed training — the hvt-data dispatcher's acceptance workload.

A deliberately small fit whose ENTIRE data path runs through the
distributed data service (`horovod_tpu.data.service` +
`data.client.ServiceClient`): each process builds the same npz-backed
source spec, admits it to the dispatcher named by ``HVT_DATA_SERVICE``,
and consumes served batches through the anchored-batches hook. With
``HVT_DATA_SERVICE`` unset the client is a pure local passthrough — the
SAME script is the uninterrupted locally-fed control the chaos e2e
compares against, because served and local streams are byte-identical
by construction (one `build_source` recipe, one ``(seed, epoch, pass)``
derivation).

What the chaos e2e (tests/test_data_service_e2e.py) drives through it:

* dispatcher SIGKILLed mid-run → the client's bounded retries
  (`HVT_DATA_RETRIES` × `HVT_DATA_BACKOFF_S`) ride out the outage or
  degrade to rank-local feeding from the same cursor;
* dispatcher restarted on the same ``--dir`` → journal recovery; the
  client re-attaches SPEC-LESS at the next epoch boundary (the
  recovery proof);
* ``HVT_FAULT=RANK:EPOCH:netdrop:MS`` → one rank's connection drops on
  every fetch of that epoch; that rank degrades, feeds itself locally,
  re-attaches — and the final checkpoint still matches the control
  byte for byte.

``DIGEST_LOG=<path>`` appends one JSONL record per CONSUMED batch —
``{"epoch", "step", "rank", "world", "sha256"}`` — the per-batch
byte-identity audit (the packed-LM soak's DigestTee, on the served
path). The client's failover audit trail (degrade/re-attach events)
lands at ``$PS_MODEL_PATH/client-events.rank<R>.jsonl``.

Smoke knobs: N_ROWS, BATCH, DRIVE_STEPS, DRIVE_EPOCHS, SEED_DATA,
DIGEST_LOG.
"""

import hashlib
import json
import os
import time

try:
    import horovod_tpu  # noqa: F401 — installed (`pip install -e .`)
except ModuleNotFoundError:  # bare source checkout
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import flax.linen as nn
import numpy as np
import optax

import horovod_tpu as hvt
from horovod_tpu import checkpoint
from horovod_tpu.data.client import ServiceClient, build_source


def ensure_corpus(root: str, n_rows: int, rank: int) -> str:
    """Materialize the deterministic npz corpus exactly once, atomically
    (tmp + os.replace); losers/waiters poll for the file."""
    path = os.path.join(root, "corpus.npz")
    if not os.path.exists(path) and rank == 0:
        rng = np.random.RandomState(0)
        x = rng.rand(n_rows, 8).astype(np.float32)
        y = (np.arange(n_rows) % 4).astype(np.int64)
        os.makedirs(root, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}.npz"
        np.savez(tmp, x=x, y=y)
        os.replace(tmp, path)
    deadline = time.time() + 60
    while not os.path.exists(path):
        if time.time() > deadline:
            raise RuntimeError(f"corpus never appeared at {path}")
        time.sleep(0.05)
    return path


class Tiny(nn.Module):
    @nn.compact
    def __call__(self, x, train=False):
        return nn.Dense(4)(x)


class DigestTee:
    """Append a sha256 per CONSUMED batch to a JSONL — the byte-identity
    audit trail (same record shape as packed_lm_pretrain.py's). Exposes
    the anchored ``batches(skip=, start_epoch=, batches_per_epoch=)``
    hook, passing the fast-forward straight through."""

    def __init__(self, inner, path: str, rank: int, world: int):
        self.inner = inner
        self.path = path
        self.rank, self.world = rank, world

    def batches(self, skip: int = 0, *, start_epoch: int = 0,
                batches_per_epoch: int | None = None):
        epoch, step = int(start_epoch), int(skip)
        for x, y in self.inner.batches(
            skip=skip, start_epoch=start_epoch,
            batches_per_epoch=batches_per_epoch,
        ):
            sha = hashlib.sha256()
            sha.update(np.ascontiguousarray(x).tobytes())
            sha.update(np.ascontiguousarray(y).tobytes())
            with open(self.path, "a") as f:  # append-only audit stream
                f.write(json.dumps({
                    "epoch": epoch, "step": step, "rank": self.rank,
                    "world": self.world, "sha256": sha.hexdigest(),
                }) + "\n")
            step += 1
            if batches_per_epoch and step >= batches_per_epoch:
                epoch, step = epoch + 1, 0
            yield x, y

    def __iter__(self):
        return self.batches()


def main() -> None:
    hvt.init()
    root = os.environ.get("PS_MODEL_PATH", "./models")
    model_dir = os.path.join(root, "service-fed")
    rank, world = hvt.process_rank(), hvt.process_count()

    corpus = ensure_corpus(root, int(os.environ.get("N_ROWS", 256)), rank)
    batch = int(os.environ.get("BATCH", 8))
    spec = {
        "source": "npz", "path": corpus, "keys": ["x", "y"],
        "batch_size": batch, "seed": int(os.environ.get("SEED_DATA", 11)),
        "shuffle_buffer": 0,  # full permutation per epoch
        "shard": [rank, world],
    }
    # The client owns a LOCAL copy of the exact source the dispatcher
    # serves from — its degraded mode is byte-identical by construction.
    client = ServiceClient(build_source(spec), spec, shard=(rank, world))
    stream = client
    digest_log = os.environ.get("DIGEST_LOG")
    if digest_log:
        stream = DigestTee(client, f"{digest_log}.rank{rank}", rank, world)

    trainer = hvt.Trainer(
        Tiny(), hvt.DistributedOptimizer(optax.adam(1e-2)), seed=7
    )
    sample_x = np.zeros((batch, 8), np.float32)
    sample_y = np.zeros((batch,), np.int64)
    trainer.build(sample_x, sample_y)
    trainer.state, e0, s0 = checkpoint.restore_latest_and_broadcast(
        model_dir, trainer.state, mesh=trainer.mesh, with_step=True
    )
    print(f"RESUME epoch={e0} step={s0}", flush=True)

    callbacks = []
    if rank == 0:
        callbacks.append(hvt.callbacks.ModelCheckpoint(
            os.path.join(model_dir, "checkpoint-{epoch}.msgpack"),
            save_every_steps=1,
        ))
    steps = int(os.environ.get("DRIVE_STEPS", 4))
    epochs = int(os.environ.get("DRIVE_EPOCHS", 5))
    trainer.fit(
        stream,
        steps_per_epoch=steps,
        epochs=epochs,
        initial_epoch=e0,
        initial_step=s0,
        callbacks=callbacks,
        verbose=0,
    )
    client.close()

    # The failover audit trail the chaos e2e asserts on.
    events_path = os.path.join(root, f"client-events.rank{rank}.jsonl")
    with open(events_path, "a") as f:
        for ev in client.events:
            f.write(json.dumps(ev) + "\n")
    print("TRAINING COMPLETE", flush=True)


if __name__ == "__main__":
    main()
