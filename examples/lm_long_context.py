"""Long-context LM training with sequence + tensor parallelism.

The capability demo the reference has no analogue for (SURVEY.md §5.7 —
sequence axis entirely absent there): a decoder-only transformer whose
activations are sharded along the mesh ``seq`` axis, attention running as a
ring (or Ulysses all-to-all) collective, QKV/MLP weights tensor-parallel
over ``model``, batch data-parallel — all in one jitted step.

The task is long-range recall (data.datasets.copy_task): the second half of
every sequence repeats the first half, so a model can only drive
second-half loss toward 0 by attending across the sequence shards.
The final report prints the recall-half loss — the functional proof that
cross-shard attention works.

Mesh shape via HVT_MESH, e.g.:

    HVT_MESH="data=2,seq=4" python examples/lm_long_context.py
    HVT_MESH="data=2,seq=2,model=2" python examples/lm_long_context.py

Knobs: DRIVE_STEPS, DRIVE_EPOCHS, SEQ_LEN, VOCAB, DMODEL, NLAYERS, ATTN
(ring|ulysses), REMAT=1 (block rematerialization), LOGITS=bf16 (16-bit
logits; the loss upcasts to f32 on the fly), FUSED_CE=<n_chunks> (fused
chunked-CE head: full logits never materialized — the stronger long-context
memory knob), MOE_EVERY (0=dense; k = MoE MLP every k-th block), N_EXPERTS. MoE composes with the mesh's ``expert``
axis, e.g.:

    HVT_MESH="data=2,expert=4" MOE_EVERY=2 python examples/lm_long_context.py

Pipeline parallelism: a ``pipe`` axis switches to the pipelined model
(GPipe microbatch schedule, models/pipelined_lm.py):

    HVT_MESH="data=2,pipe=4" N_MICRO=8 python examples/lm_long_context.py
    HVT_MESH="data=2,pipe=2,model=2" SCHEDULE=1f1b python examples/lm_long_context.py
    HVT_MESH="data=2,pipe=2,seq=2"  python examples/lm_long_context.py  # PP x SP
"""

import os

try:
    import horovod_tpu  # noqa: F401 — installed (`pip install -e .`)
except ModuleNotFoundError:  # bare source checkout: make the repo importable
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvt
from horovod_tpu import metrics
from horovod_tpu.data import datasets
from horovod_tpu.models.transformer import (
    ShardingConfig,
    TransformerLM,
    param_specs,
)
from horovod_tpu.parallel import mesh as mesh_lib


def main() -> None:
    hvt.init()
    metrics.init()

    mesh = mesh_lib.build_mesh(
        mesh_lib.MeshSpec.from_string(os.environ.get("HVT_MESH"))
    )
    seq_len = int(os.environ.get("SEQ_LEN", 512))
    vocab = int(os.environ.get("VOCAB", 64))
    attn = os.environ.get("ATTN", "ring")

    if mesh.shape.get(mesh_lib.PIPE_AXIS, 1) > 1:
        # pipe > 1 switches to the pipeline-parallel model: per-layer
        # parameter stacks sharded over `pipe`, GPipe (or SCHEDULE=1f1b
        # staggered-backward) microbatch schedule, Megatron TP inside each
        # stage when `model` > 1 AND ring-flash sequence parallelism inside
        # each stage when `seq` > 1 (models/pipelined_lm.py) — e.g.
        # HVT_MESH="data=2,pipe=2,seq=2". Use TransformerLM for the expert
        # axis.
        from horovod_tpu.models import pipelined_lm

        model = pipelined_lm.PipelinedLM(
            vocab_size=vocab,
            d_model=int(os.environ.get("DMODEL", 256)),
            n_heads=8,
            n_layers=int(os.environ.get("NLAYERS", 4)),
            n_micro=int(os.environ.get("N_MICRO", 4)),
            mesh=mesh,
            schedule=os.environ.get("SCHEDULE", "gpipe"),
        )
        batch_spec = P(
            (mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS), mesh_lib.SEQ_AXIS
        )
        trainer = hvt.Trainer(
            model,
            hvt.DistributedOptimizer(optax.adam(3e-3)),
            loss="sparse_categorical_crossentropy",
            mesh=mesh,
            param_specs=pipelined_lm.param_specs,
            batch_specs=(batch_spec, batch_spec),
        )
    else:
        model = TransformerLM(
            vocab_size=vocab,
            d_model=int(os.environ.get("DMODEL", 256)),
            n_heads=8,
            n_layers=int(os.environ.get("NLAYERS", 4)),
            dropout=0.0,
            sharding=ShardingConfig(mesh=mesh, attn=attn),
            moe_every=int(os.environ.get("MOE_EVERY", 0)),
            n_experts=int(os.environ.get("N_EXPERTS", 8)),
            # Memory knobs for extreme context (REMAT=1, LOGITS=bf16):
            # together they take one 16 GB chip from OOM to training at
            # seq 131,072 (BASELINE.md context-envelope row).
            remat=hvt.runtime.env_flag("REMAT"),
            logits_dtype=jnp.bfloat16
            if os.environ.get("LOGITS", "") == "bf16"
            else jnp.float32,
            # FUSED_CE=<n_chunks>: the fused chunked-CE head — f32-accurate
            # loss with the [B, T, vocab] logits never materialized
            # (ops/fused_ce.py); supersedes LOGITS=bf16 for long context.
            fused_head_chunks=int(os.environ.get("FUSED_CE", 0)),
        )
        batch_spec = P(
            (mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS), mesh_lib.SEQ_AXIS
        )
        trainer = hvt.Trainer(
            model,
            hvt.DistributedOptimizer(optax.adam(3e-3)),
            loss="module"
            if int(os.environ.get("FUSED_CE", 0))
            else "sparse_categorical_crossentropy",
            mesh=mesh,
            param_specs=param_specs,
            batch_specs=(batch_spec, batch_spec),
        )

    x, y = datasets.copy_task(4096, seq_len, vocab_size=vocab, seed=0)
    epochs = int(os.environ.get("DRIVE_EPOCHS", 0)) or 4
    steps = int(os.environ.get("DRIVE_STEPS", 0)) or 64

    # HVT_DEVICE_CACHE=1: HBM-resident dataset, one dispatch per epoch
    # (pure-GSPMD meshes only — the seq-sharded batch layout needs the
    # streamed path's batch_specs handling).
    device_cache = hvt.runtime.env_flag(
        "HVT_DEVICE_CACHE"
    ) and not mesh_lib.has_live_model_axes(mesh)
    if device_cache:
        fit_kwargs = {"cache": "device"}
        if int(os.environ.get("DRIVE_STEPS", 0)):  # honor an explicit budget
            fit_kwargs["steps_per_epoch"] = steps
    else:
        fit_kwargs = {"steps_per_epoch": steps}
    trainer.fit(
        x=x, y=y,
        batch_size=max(1, 16 // mesh_lib.dp_size(mesh)),
        epochs=epochs,
        callbacks=[
            hvt.callbacks.BroadcastGlobalVariablesCallback(0),
            hvt.callbacks.MetricAverageCallback(),
            hvt.callbacks.MetricsPushCallback(),
        ],
        verbose=1 if hvt.rank() == 0 else 0,
        **fit_kwargs,
    )

    # Recall-half report on held-out sequences.
    xt, yt = datasets.copy_task(64, seq_len, vocab_size=vocab, seed=99)
    probs = trainer.predict(xt, batch_size=8)
    ll = np.log(np.take_along_axis(probs, yt[..., None], axis=-1)[..., 0] + 1e-9)
    half = seq_len // 2
    recall_loss = float(-ll[:, half:].mean())
    context_loss = float(-ll[:, : half - 2].mean())
    metrics.push("recall_loss", recall_loss)
    if hvt.rank() == 0:
        print(f"first-half (irreducible) loss: {context_loss:.4f}")
        print(f"recall-half loss:              {recall_loss:.4f}")
        print("long-range recall:", "LEARNED" if recall_loss < 0.5 * context_loss
              else "not yet (train longer)")

    # Generation proof (TransformerLM only): greedy KV-cache decode from the
    # first-half prompt must literally reproduce the repeated half — the
    # same recall the loss measures, exercised end-to-end through the
    # compiled prefill + decode loop (models/decoding.py).
    if (
        isinstance(trainer.module, TransformerLM)
        and half > 1
        and jax.process_count() == 1  # multi-proc params aren't addressable here
    ):
        from horovod_tpu.models.decoding import generate

        gen_model = trainer.module.clone(sharding=ShardingConfig(mesh=None))
        prompt = jnp.asarray(xt[:8, : half + 1])  # [BOS, first_half]
        out = np.asarray(generate(
            gen_model, trainer.state.params, prompt,
            max_new_tokens=half - 1, include_prompt=False,
        ))
        exact = float((out == xt[:8, half + 1 :]).mean())
        metrics.push("decode_exact_match", exact)
        if hvt.rank() == 0:
            print(f"greedy-decode recall exact-match: {exact:.3f}")


if __name__ == "__main__":
    main()
