"""Elastic MNIST data-parallel training — the Horovod Elastic capability,
TPU-native (`horovod_tpu.elastic`).

Same training recipe as `tf2_style_mnist.py`, restructured into the
elastic idiom: everything world-size-dependent (trainer, optimizer LR
scale, dataset shard, steps-per-epoch) is built INSIDE the per-generation
train function, committed state rides an `ElasticState`, and
`elastic.run` re-invokes the function whenever the fleet rendezvous
settles a new world. A member that is preempted (SIGTERM) or injected
with the ``leave`` fault departs cleanly at the next epoch boundary —
survivors keep training from the last commit without a process restart;
a replacement joining grows the fleet back.

Launch (the supervisor owns the rendezvous coordinator):

    python -m horovod_tpu.launch run --nprocs 3 --elastic \
        --min-ranks 2 -- python examples/elastic_mnist.py

or via the job spec `horovod_tpu/launch/jobs/mnist-elastic-2proc.yaml`.
Unlaunched (no HVT_ELASTIC_COORDINATOR), it degrades to a plain
single-process run through a local one-member rendezvous.

``ELASTIC_ZERO1=1`` turns on ZeRO-1 cross-replica weight-update sharding
(`Trainer(shard_update=True)`): the optimizer state is then sharded
ACROSS processes, exercising the per-shard elastic commit path — commits
snapshot each process's own optimizer shards, the membership boundary
reassembles them, and checkpoints use the sharded directory format
(which is why `ModelCheckpoint` below runs on EVERY rank: `save_state`
self-gates to the primary for single-file checkpoints, but the sharded
format needs every process's shard file). The checkpoint fallback passes
``reshard=True`` so a sharded checkpoint saved by a 3-rank generation
restores onto a 2-rank world. `jobs/mnist-elastic-sharded-2proc.yaml` is
the CI form.

Smoke-test env knobs: DRIVE_STEPS, DRIVE_EPOCHS.
"""

import os

try:
    import horovod_tpu  # noqa: F401 — installed (`pip install -e .`)
except ModuleNotFoundError:  # bare source checkout: make the repo importable
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import optax

import horovod_tpu as hvt
from horovod_tpu import checkpoint, elastic, metrics
from horovod_tpu.data import datasets
from horovod_tpu.data.loader import ArrayDataset
from horovod_tpu.models.cnn import MnistCNN


def train(state: "elastic.ElasticState", world: "elastic.WorldInfo") -> None:
    model_dir = os.path.join(
        os.environ.get("PS_MODEL_PATH", "./models"), "elastic-mnist"
    )
    metrics.init(sync_tensorboard=True)
    if world.rank == 0:
        print(
            f"elastic-mnist: generation {world.generation} — "
            f"{world.size} rank(s), resuming at epoch {state.epoch} "
            f"step {state.step}",
            flush=True,
        )

    (x_train, y_train), _ = datasets.mnist(path=f"mnist-{world.rank}.npz")
    x_train = (x_train.astype(np.float32) / 255.0)[..., None]
    y_train = y_train.astype(np.int64)

    # The data pipeline re-shards per generation: shard(rank, size) of the
    # FULL dataset, so the new world again partitions every example once
    # per epoch (ArrayDataset.reshard is the equivalent hook for a kept
    # pipeline object). Per-worker batch is fixed (Horovod semantics) —
    # the global batch and the LR scale below both track world.size.
    world_procs = hvt.process_count()
    per_process_batch = 128 * hvt.size() // world_procs
    dataset = (
        ArrayDataset((x_train, y_train))
        .shard(world.rank, world_procs)
        .repeat()
        .shuffle(10000, seed=world.rank)
        .batch(per_process_batch)
    )

    # HVT_BACKWARD_PASSES=K: gradient accumulation (K microbatch passes per
    # optimizer step, one boundary reduction). Elastic commits stay aligned
    # by construction — the K-microbatch scan runs inside the compiled
    # step, so `commit_every_steps` commits (ElasticStateCallback below,
    # cadence via the job spec's elastic: block) can never land
    # mid-accumulation. COMPOSES with ELASTIC_ZERO1 since ISSUE 10: the
    # boundary reduction then reduce-scatters into the sharded update
    # layout (collectives.reduce_gradients(scatter=dp)), so the sharded
    # commit path runs under accumulation too —
    # jobs/mnist-elastic-sharded-2proc.yaml exercises exactly that.
    from horovod_tpu.analysis import registry

    backward_passes = registry.get_int("HVT_BACKWARD_PASSES") or 1
    # HVT_COMPRESSION=bf16/fp16/int8/fp8: wire compression on the boundary
    # reduction; int8/fp8 error-feedback residuals live in opt_state, so
    # elastic commit/sync and the reshard re-cut carry them unchanged.
    compression = registry.get_str("HVT_COMPRESSION") or "none"
    # HVT_COMPRESSION_ICI: the two-hop reduction's ICI-hop wire (inert
    # on single-slice meshes); its error feedback rides opt_state like
    # HVT_COMPRESSION's.
    compression_ici = registry.get_str("HVT_COMPRESSION_ICI") or "none"
    trainer = hvt.Trainer(
        MnistCNN(),
        # lr = 0.001 × size: rebuilt each generation, so the effective LR
        # rescales with the world exactly like Horovod Elastic's
        # reset-on-rescale optimizer.
        hvt.DistributedOptimizer(
            optax.adam(hvt.scale_lr(0.001)),
            backward_passes_per_step=backward_passes,
            compression=compression,
            compression_ici=compression_ici,
        ),
        loss="sparse_categorical_crossentropy",
        # ZeRO-1: optimizer state sharded over the data axis — with one
        # chip per process this is CROSS-PROCESS sharding, the layout the
        # per-shard elastic commit exists for.
        shard_update=hvt.runtime.env_flag("ELASTIC_ZERO1"),
    )
    trainer.build(x_train[:1])

    if state.state is not None:
        # The common rescale path: adopt the committed snapshot (already
        # synced from the freshest member — no checkpoint round-trip).
        trainer.install_state(state.state)
    else:
        # Fresh process (first generation, or a per-rank restart after a
        # hard crash): the checkpoint fallback. reshard=True because a
        # sharded (ZeRO-1) checkpoint may have been saved by a different
        # generation's world size. with_step=True: a mid-epoch manifest
        # resumes at the committed optimizer step, not the epoch start.
        trainer.state, done, done_step = (
            checkpoint.restore_latest_and_broadcast(
                model_dir, trainer.state, mesh=trainer.mesh, reshard=True,
                with_step=True,
            )
        )
        if elastic.progress_marker(done, done_step) > elastic.progress_marker(
            state.epoch, state.step
        ):
            state.epoch, state.step = done, done_step

    callbacks = [
        hvt.callbacks.LearningRateWarmupCallback(warmup_epochs=3),
        # EVERY rank, not just rank 0: save_state self-gates single-file
        # saves to the primary, and the sharded (ZeRO-1) format requires
        # every process to write its own shard file — a rank-0 gate there
        # would tear every sharded checkpoint.
        hvt.callbacks.ModelCheckpoint(
            os.path.join(model_dir, "checkpoint-{epoch}.msgpack")
        ),
    ]
    if world.rank == 0:
        callbacks.append(hvt.callbacks.ScalarLogger(model_dir))
    # LAST in the list: commits the epoch AFTER checkpoints/logs saw it,
    # then runs the membership agreement (and may interrupt the fit).
    callbacks.append(elastic.ElasticStateCallback(state, state.client))

    steps = int(os.environ.get("DRIVE_STEPS", 0)) or hvt.shard_steps(500)
    epochs = int(os.environ.get("DRIVE_EPOCHS", 0)) or 24

    trainer.fit(
        dataset,
        steps_per_epoch=steps,
        epochs=epochs,
        initial_epoch=state.epoch,
        # Mid-epoch commits/rescales (commit_every_steps /
        # rescale_every_steps) resume at the committed OPTIMIZER step:
        # the feeding path fast-forwards the resharded dataset
        # deterministically, so survivors replay zero steps.
        initial_step=state.step,
        callbacks=callbacks,
        verbose=1 if world.rank == 0 else 0,
    )


def main() -> None:
    if os.environ.get(hvt.runtime.ENV_ELASTIC_COORDINATOR):
        elastic.run(train)
    else:
        # Bare mode: a process-local one-member rendezvous, so the same
        # script runs unlaunched (the README.md single-instance contract).
        coord = elastic.Coordinator(min_ranks=1, max_ranks=1).start()
        try:
            elastic.run(train, address=coord.address, member_id="solo")
        finally:
            coord.stop()
    if hvt.rank() == 0:
        print("TRAINING COMPLETE", flush=True)


if __name__ == "__main__":
    main()
