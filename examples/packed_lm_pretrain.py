"""File-backed packed-LM pretraining under the elastic supervisor — the
long-horizon soak for durable stream cursors (ROADMAP item 4).

The workload the reliability spine was built for, end to end:

1. a deterministic document corpus is packed once
   (`packing.pack_documents` → `next_token_pairs`) and written as an
   on-disk shard directory (`filedataset.write_shards`) — the dataset
   lives on disk, the hosts only mmap the rows of the current batch;
2. each elastic generation cuts its per-process stripe with
   `FileDataset.reshard(rank, size)` and feeds
   `FileDataset.pairs_stream(...)` — the resumable view whose
   ``batches(skip=, start_epoch=, batches_per_epoch=)`` hook
   `Trainer.fit` drives, so EVERY recovery path (supervised restart,
   elastic shrink/grow, mid-epoch rescale) resumes the byte stream at
   the exact committed position — including epochs that predate the
   resume call (the anchored-stream contract, `data/stream.py`);
3. faults ride `HVT_FAULT` (kill / leave / corrupt) and the transient-
   read chaos knob `HVT_DATA_FAULT_READS` exercises the bounded
   retry-with-backoff (`HVT_DATA_RETRIES`/`HVT_DATA_BACKOFF_S`).

``DIGEST_LOG=<path>`` appends one JSONL record per CONSUMED batch —
``{"epoch", "step", "rank", "world", "sha256"}`` — the per-batch
byte-identity proof the soak e2e (tests/test_stream_resume_e2e.py)
checks against an uninterrupted control: any replayed, skipped or
re-anchored batch shows up as a digest mismatch.

Launch (CI form: `launch/jobs/packed-lm-soak-2proc.yaml`):

    python -m horovod_tpu.launch run --nprocs 3 --elastic \
        --min-ranks 2 -- python examples/packed_lm_pretrain.py

Unlaunched it degrades to a plain single-process run (local one-member
rendezvous), which is also the kill/relaunch e2e's shape.

Smoke knobs: SEQ_LEN, DOCS, VOCAB, DMODEL, NLAYERS, BATCH, DRIVE_STEPS,
DRIVE_EPOCHS, DIGEST_LOG.
"""

import hashlib
import json
import os
import time

try:
    import horovod_tpu  # noqa: F401 — installed (`pip install -e .`)
except ModuleNotFoundError:  # bare source checkout
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvt
from horovod_tpu import checkpoint, elastic, metrics
from horovod_tpu.data.filedataset import FileDataset, write_shards
from horovod_tpu.data.packing import next_token_pairs, pack_documents
from horovod_tpu.models.transformer import TransformerLM

import flax.linen as nn

SEED = 17  # the data-stream seed every generation anchors to


def synthetic_corpus(n_docs: int, vocab: int, seed: int = 0):
    """Documents of motif repeats: learnable within-document structure."""
    rng = np.random.RandomState(seed)
    docs = []
    for _ in range(n_docs):
        motif = rng.randint(1, vocab, size=rng.randint(4, 12))
        docs.append(np.tile(motif, rng.randint(2, 8)).astype(np.int32))
    return docs


def ensure_corpus_dir(root: str, seq_len: int, vocab: int,
                      n_docs: int, rank: int) -> str:
    """Pack the corpus to disk shards exactly once, atomically: the
    writer builds into a temp dir and renames it into place (the index
    file inside was itself written last, atomically), losers/waiters
    poll for the index. Re-entrant across restarts — a relaunched
    process finds the directory and skips straight to mapping it."""
    path = os.path.join(root, "packed-corpus")
    index = os.path.join(path, "index.json")
    if not os.path.exists(index) and rank == 0:
        docs = synthetic_corpus(n_docs, vocab, seed=0)
        toks, seg, _ = pack_documents(docs, seq_len=seq_len + 1)
        x, y, w = next_token_pairs(toks, seg)
        xs = np.stack([x, seg[:, :-1]], axis=-1)          # [B, T, 2] int32
        ys = np.stack([y, w.astype(np.int32)], axis=-1)   # targets ⊕ weights
        tmp = f"{path}.tmp.{os.getpid()}"
        write_shards({"x": xs, "y": ys}, tmp, shard_size=64)
        try:
            os.rename(tmp, path)
        except OSError:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)  # lost the race
    deadline = time.time() + 120
    while not os.path.exists(index):
        if time.time() > deadline:
            raise RuntimeError(f"corpus never appeared at {path}")
        time.sleep(0.1)
    return path


class PackedLM(nn.Module):
    """TransformerLM with the per-row segment ids carried IN the input
    ([B, T, 2] = tokens ⊕ ids) — the lm_packed_pretraining.py feed."""

    inner: TransformerLM

    @nn.compact
    def __call__(self, xs, *, train: bool = False):
        return self.inner(xs[..., 0], train=train, segment_ids=xs[..., 1])


def masked_ce(logits, y2):
    """Per-row mean CE over real next-token targets (weights channel)."""
    targets = y2[..., 0]
    weights = y2[..., 1].astype(jnp.float32)
    per = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), targets
    )
    return (per * weights).sum(-1) / jnp.maximum(weights.sum(-1), 1.0)


class DigestTee:
    """Wrap a resumable (x, y) stream, appending a sha256 per CONSUMED
    batch to a JSONL — the byte-identity audit trail the soak compares
    across faulted and control runs. Exposes the same ``batches(skip=,
    start_epoch=, batches_per_epoch=)`` hook, so fit's deterministic
    fast-forward passes straight through."""

    def __init__(self, inner, path: str, rank: int, world: int):
        self.inner = inner
        self.path = path
        self.rank, self.world = rank, world

    def batches(self, skip: int = 0, *, start_epoch: int = 0,
                batches_per_epoch: int | None = None):
        epoch, step = int(start_epoch), int(skip)
        for x, y in self.inner.batches(
            skip=skip, start_epoch=start_epoch,
            batches_per_epoch=batches_per_epoch,
        ):
            sha = hashlib.sha256()
            sha.update(np.ascontiguousarray(x).tobytes())
            sha.update(np.ascontiguousarray(y).tobytes())
            with open(self.path, "a") as f:  # append-only audit stream
                f.write(json.dumps({
                    "epoch": epoch, "step": step, "rank": self.rank,
                    "world": self.world, "sha256": sha.hexdigest(),
                }) + "\n")
            step += 1
            if batches_per_epoch and step >= batches_per_epoch:
                epoch, step = epoch + 1, 0
            yield x, y

    def __iter__(self):
        return self.batches()


def train(state: "elastic.ElasticState", world: "elastic.WorldInfo") -> None:
    root = os.environ.get("PS_MODEL_PATH", "./models")
    model_dir = os.path.join(root, "packed-lm")
    metrics.init(sync_tensorboard=True)

    seq_len = int(os.environ.get("SEQ_LEN", 32))
    vocab = int(os.environ.get("VOCAB", 64))
    corpus = ensure_corpus_dir(
        root, seq_len, vocab, int(os.environ.get("DOCS", 400)), world.rank
    )
    if world.rank == 0:
        print(
            f"packed-lm: generation {world.generation} — {world.size} "
            f"rank(s), resuming at epoch {state.epoch} step {state.step}",
            flush=True,
        )

    ds = FileDataset(corpus)
    batch = int(os.environ.get("BATCH", 8))
    # Per-generation recut of the per-process stripe from the FULL
    # on-disk row space — the elastic rescale hook on the file path.
    stream = ds.reshard(world.rank, world.size).pairs_stream(
        "x", "y", batch, seed=SEED
    )
    digest_log = os.environ.get("DIGEST_LOG")
    if digest_log:
        stream = DigestTee(
            stream, f"{digest_log}.rank{world.rank}",
            world.rank, world.size,
        )

    trainer = hvt.Trainer(
        PackedLM(inner=TransformerLM(
            vocab_size=vocab,
            d_model=int(os.environ.get("DMODEL", 32)),
            n_heads=2,
            n_layers=int(os.environ.get("NLAYERS", 1)),
            dropout=0.0,
        )),
        hvt.DistributedOptimizer(optax.adamw(hvt.scale_lr(3e-3))),
        loss=masked_ce,
        seed=SEED,
    )
    sample = ds.gather(np.arange(1))
    trainer.build(sample["x"], sample["y"])

    if state.state is not None:
        trainer.install_state(state.state)
    else:
        # Fresh process (first generation or a hard-crash relaunch): the
        # checkpoint fallback, STEP-granular — the progress manifest (and
        # its embedded stream cursor) land the resume mid-epoch.
        trainer.state, done, done_step = (
            checkpoint.restore_latest_and_broadcast(
                model_dir, trainer.state, mesh=trainer.mesh,
                reshard=True, with_step=True,
            )
        )
        if elastic.progress_marker(done, done_step) > elastic.progress_marker(
            state.epoch, state.step
        ):
            state.epoch, state.step = done, done_step

    callbacks = [
        hvt.callbacks.ModelCheckpoint(
            os.path.join(model_dir, "checkpoint-{epoch}.msgpack")
        ),
    ]
    if world.rank == 0:
        # Epoch scalars → the platform metrics sink (the CI gate's feed).
        callbacks.append(hvt.callbacks.ScalarLogger(model_dir))
    # LAST: commits after checkpoints saw the epoch, then may interrupt.
    callbacks.append(elastic.ElasticStateCallback(state, state.client))

    n_rows = ds.num_examples // world.size
    steps = int(os.environ.get("DRIVE_STEPS", 0)) or max(
        1, n_rows // batch
    )
    epochs = int(os.environ.get("DRIVE_EPOCHS", 0)) or 6

    trainer.fit(
        stream,
        steps_per_epoch=steps,
        epochs=epochs,
        initial_epoch=state.epoch,
        initial_step=state.step,
        callbacks=callbacks,
        verbose=1 if world.rank == 0 else 0,
    )


def main() -> None:
    if os.environ.get(hvt.runtime.ENV_ELASTIC_COORDINATOR):
        elastic.run(train)
    else:
        coord = elastic.Coordinator(min_ranks=1, max_ranks=1).start()
        try:
            elastic.run(train, address=coord.address, member_id="solo")
        finally:
            coord.stop()
    if hvt.rank() == 0:
        print("TRAINING COMPLETE", flush=True)


if __name__ == "__main__":
    main()
