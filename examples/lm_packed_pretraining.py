"""Packed-sequence LM pretraining, end to end.

The standard long-context data format: variable-length documents packed into
fixed [B, T] rows. Everything the path needs is first-class here —

1. `data.packing.pack_documents`: best-fit-decreasing packing → static rows
   + segment ids (padding isolated in segment 0);
2. `data.packing.next_token_pairs`: shifted (x, y, loss-weights) whose mask
   stops targets at document boundaries;
3. `TransformerLM(..., segment_ids=...)`: per-document RoPE restart and the
   flash kernel's segment-masked attention (block-level early-out — 4.0×
   over dense-masked at seq 4096, BASELINE.md);
4. a weighted cross-entropy Trainer loss via the callable-loss hook.

The corpus is synthetic (zero-egress environment): each "document" is a
repeated random motif, so a model that attends within documents learns the
motif quickly — falling loss is the functional check.

Run (any mesh; ids shard with the tokens):

    python examples/lm_packed_pretraining.py
    HVT_MESH="data=2,seq=4" python examples/lm_packed_pretraining.py

Knobs: SEQ_LEN, DOCS, DRIVE_EPOCHS, DRIVE_STEPS, VOCAB, DMODEL, NLAYERS.
"""

import os

try:
    import horovod_tpu  # noqa: F401
except ModuleNotFoundError:
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvt
from horovod_tpu.data.packing import (
    next_token_pairs,
    pack_documents,
    packing_efficiency,
)
from horovod_tpu.models.transformer import (
    ShardingConfig,
    TransformerLM,
    param_specs,
)
from horovod_tpu.parallel import mesh as mesh_lib


def synthetic_corpus(n_docs: int, vocab: int, seed: int = 0):
    """Documents of motif repeats: learnable within-document structure."""
    rng = np.random.RandomState(seed)
    docs = []
    for _ in range(n_docs):
        motif = rng.randint(1, vocab, size=rng.randint(4, 12))
        reps = rng.randint(2, 8)
        docs.append(np.tile(motif, reps).astype(np.int32))
    return docs


class PackedLM(nn.Module):
    """TransformerLM + a per-row segment-id channel carried IN the input.

    The Trainer feeds (x, y) arrays; stacking ids as a second input channel
    ([B, T, 2] = tokens ⊕ ids) keeps the packed metadata flowing through
    fit/evaluate without a Trainer-side protocol change."""

    inner: TransformerLM

    @nn.compact
    def __call__(self, xs, *, train: bool = False):
        tokens, seg = xs[..., 0], xs[..., 1]
        return self.inner(tokens, train=train, segment_ids=seg)


def text_corpus(n_docs: int, seed: int = 0):
    """Synthetic TEXT documents (motifs of words) for the TEXT=1 path —
    exercising the full text front-end: ByteBPETokenizer.train → encode →
    pack. Same learnable repeated-motif structure as the token corpus."""
    rng = np.random.RandomState(seed)
    words = [
        "alpha", "beta", "gamma", "delta", "epsilon", "zeta", "eta",
        "theta", "iota", "kappa", "lambda", "mu",
    ]
    docs = []
    for _ in range(n_docs):
        motif = " ".join(rng.choice(words, size=rng.randint(3, 7)))
        docs.append(" ".join([motif] * rng.randint(2, 6)))
    return docs


def main() -> None:
    hvt.init()
    mesh = mesh_lib.build_mesh(
        mesh_lib.MeshSpec.from_string(os.environ.get("HVT_MESH"))
    )
    seq_len = int(os.environ.get("SEQ_LEN", 256))
    vocab = int(os.environ.get("VOCAB", 64))

    if os.environ.get("TEXT"):
        # Full text pipeline: raw strings → trained byte-BPE → token docs.
        from horovod_tpu.data.tokenizer import ByteBPETokenizer

        texts = text_corpus(int(os.environ.get("DOCS", 2000)))
        vocab = int(os.environ.get("VOCAB", 384))
        tokenizer = ByteBPETokenizer.train(texts, vocab_size=vocab)
        vocab = tokenizer.vocab_size  # training may stop below the budget
        docs = tokenizer.encode_corpus(texts)
        if hvt.is_primary():
            path = os.path.join(
                os.environ.get("PS_MODEL_PATH", "./models"), "tokenizer.json"
            )
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tokenizer.save(path)
            raw = sum(len(t.encode()) for t in texts)
            enc = sum(len(d) for d in docs)
            print(
                f"byte-BPE: vocab {vocab}, {raw} bytes -> {enc} tokens "
                f"({raw / enc:.2f} bytes/token), saved {path}"
            )
    else:
        docs = synthetic_corpus(int(os.environ.get("DOCS", 2000)), vocab)
    # Pack at seq_len + 1: the shifted next-token pairs then span exactly
    # seq_len positions — divisible by a live `seq` axis for SP meshes.
    toks, seg, _ = pack_documents(docs, seq_len=seq_len + 1)
    if hvt.is_primary():
        print(
            f"packed {len(docs)} docs -> {toks.shape[0]} rows x "
            f"{toks.shape[1]}, "
            f"occupancy {packing_efficiency(seg):.3f}"
        )
    x, y, w = next_token_pairs(toks, seg)
    seg_x = seg[:, :-1]
    # Tokens ⊕ ids ⊕ loss-weights ride the (x, y) feed: x = [B,T,2] int32,
    # y = [B,T,2] (targets ⊕ weights-as-int-bits is avoidable — weights are
    # 0/1 here, so carry them as an integer channel of y).
    xs = np.stack([x, seg_x], axis=-1)
    ys = np.stack([y, w.astype(np.int32)], axis=-1)

    def masked_ce(logits, y2):
        targets, weights = y2[..., 0], y2[..., 1].astype(jnp.float32)
        per = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), targets
        )
        # Per-example mean with boundary/padding positions zeroed; the
        # Trainer means over the batch, so normalize per row.
        return (per * weights).sum(-1) / jnp.maximum(weights.sum(-1), 1.0)

    model = PackedLM(
        inner=TransformerLM(
            vocab_size=vocab,
            d_model=int(os.environ.get("DMODEL", 128)),
            n_heads=4,
            n_layers=int(os.environ.get("NLAYERS", 2)),
            dropout=0.0,
            compute_dtype=jnp.bfloat16,
            sharding=ShardingConfig(mesh=mesh),
        )
    )
    # Note: the epoch log's generic `accuracy` column is meaningless under
    # the stacked-label format (it argmaxes the 2-channel y); the masked
    # LOSS is the training signal here.
    from jax.sharding import PartitionSpec as P

    batch_spec = P(
        (mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS), mesh_lib.SEQ_AXIS, None
    )
    trainer = hvt.Trainer(
        model,
        hvt.DistributedOptimizer(optax.adamw(hvt.scale_lr(3e-3))),
        loss=masked_ce,
        mesh=mesh,
        # Same layout discipline as lm_long_context.py: tokens⊕ids sharded
        # over (data, seq); Megatron/FSDP parameter rules (path-keyed, so
        # they find the inner model's layers through the PackedLM wrapper).
        param_specs=param_specs,
        batch_specs=(batch_spec, batch_spec),
    )
    rows_needed = 8 * mesh_lib.dp_size(mesh)
    n = (len(xs) // rows_needed) * rows_needed
    if n == 0:
        raise SystemExit(
            f"corpus packs to only {len(xs)} rows but one global batch "
            f"needs {rows_needed} (batch 8 x dp {mesh_lib.dp_size(mesh)}) "
            "- raise DOCS or lower SEQ_LEN"
        )
    history = trainer.fit(
        x=xs[:n], y=ys[:n],
        batch_size=8,
        epochs=int(os.environ.get("DRIVE_EPOCHS", 0)) or 3,
        steps_per_epoch=int(os.environ.get("DRIVE_STEPS", 0)) or 8,
        callbacks=[hvt.callbacks.BroadcastGlobalVariablesCallback(0)],
    )
    if hvt.is_primary():
        first, last = history[0]["loss"], history[-1]["loss"]
        print(f"masked loss: {first:.3f} -> {last:.3f}")
        print("packed pretraining:", "LEARNING" if last < first else "flat")


if __name__ == "__main__":
    main()
