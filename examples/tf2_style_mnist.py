"""MNIST data-parallel training — the TF2-script capability set, TPU-native.

Behavioral mirror of the reference's `tensorflow2_keras_mnist.py` (every
numbered behavior below cites the reference line it reproduces):

* model/checkpoint dirs from ``PS_MODEL_PATH`` (default ``./models``)   :21-22
* runtime bootstrap (the ``hvd.init()`` role; device pinning obsolete)  :25-32
* per-rank dataset cache path avoiding filesystem races                 :34-35
* infinite shuffled per-worker batches of 128                           :37-41
* the 2-conv CNN                                                        :43-52
* Adam with lr = 0.001 × world size                                     :55
* gradient-averaging distributed optimizer                              :58
* sparse categorical cross-entropy + accuracy                           :62-65
* callbacks: broadcast-from-0, metric averaging, 3-epoch LR warmup      :67-83
* rank-0-only per-epoch checkpoints + scalar event log                  :85-92
* fit with steps_per_epoch = 500 // size, 24 epochs, rank-0 verbosity   :96

Run it bare (single chip, no launcher — README.md:49-52), or under the
launcher for multi-host:

    python examples/tf2_style_mnist.py
    python -m horovod_tpu.launch run --nprocs 4 -- python examples/tf2_style_mnist.py

Smoke-test env knobs (used by tests/CI to shorten the run; full reference
budget when unset): DRIVE_STEPS, DRIVE_EPOCHS.
"""

import os

try:
    import horovod_tpu  # noqa: F401 — installed (`pip install -e .`)
except ModuleNotFoundError:  # bare source checkout: make the repo importable
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvt
from horovod_tpu import checkpoint, metrics
from horovod_tpu.data import datasets
from horovod_tpu.data.loader import ArrayDataset
from horovod_tpu.models.cnn import MnistCNN


def main() -> None:
    model_dir = os.path.join(os.environ.get("PS_MODEL_PATH", "./models"), "horovod-mnist")

    # Bootstrap: process/topology init. One call, idempotent, works launched
    # and unlaunched (the reference's hvd.init(), :25).
    hvt.init()
    metrics.init(sync_tensorboard=True)

    # Per-rank cache path: same race-avoidance convention as
    # 'mnist-%d.npz' % hvd.rank() (:34-35).
    (x_train, y_train), _ = datasets.mnist(path=f"mnist-{hvt.rank()}.npz")
    x_train = (x_train.astype(np.float32) / 255.0)[..., None]
    y_train = y_train.astype(np.int64)

    # Input pipeline (:37-41): this process's shard → repeat → shuffle(10000)
    # → per-process batch. Global batch is 128 × world chips; the reference
    # feeds every rank the full dataset, we shard it (SURVEY.md §7.1 data.py
    # improvement) — global work accounting is unchanged.
    world = hvt.process_count()
    per_process_batch = 128 * hvt.size() // world
    dataset = (
        ArrayDataset((x_train, y_train))
        .shard(hvt.process_rank(), world)
        .repeat()
        .shuffle(10000, seed=hvt.process_rank())
        .batch(per_process_batch)
    )

    # HVT_BACKWARD_PASSES=K (job-spec env surface): Horovod's gradient
    # accumulation — K microbatch passes per optimizer update, one
    # cross-worker reduction per K passes (effective batch K×128/worker).
    from horovod_tpu.analysis import registry

    backward_passes = registry.get_int("HVT_BACKWARD_PASSES") or 1
    # HVT_COMPRESSION=bf16/fp16/int8/fp8: gradient wire compression on the
    # boundary reduction (int8/fp8 carry error-feedback residuals in the
    # optimizer state — they ride the checkpoints below for free).
    compression = registry.get_str("HVT_COMPRESSION") or "none"
    # HVT_COMPRESSION_ICI: wire for the two-hop reduction's ICI hop
    # (inert on single-slice meshes, where dcn == 1).
    compression_ici = registry.get_str("HVT_COMPRESSION_ICI") or "none"
    trainer = hvt.Trainer(
        MnistCNN(compute_dtype=jnp.bfloat16),
        # Adam(0.001 × size) (:55) wrapped for gradient averaging (:58).
        hvt.DistributedOptimizer(
            optax.adam(hvt.scale_lr(0.001)),
            backward_passes_per_step=backward_passes,
            compression=compression,
            compression_ici=compression_ici,
        ),
        loss="sparse_categorical_crossentropy",  # :63
    )

    callbacks = [
        # Broadcast initial model+optimizer variables from rank 0 (:67-71).
        hvt.callbacks.BroadcastGlobalVariablesCallback(0),
        # Average metrics across workers; keep ahead of consumers (:73-77).
        hvt.callbacks.MetricAverageCallback(),
        # Scale lr ×size over the first 3 epochs (:78-83).
        hvt.callbacks.LearningRateWarmupCallback(warmup_epochs=3, verbose=1),
    ]
    # Epoch scalars reach the platform sink via sync_tensorboard (the
    # metrics.init above) — an explicit MetricsPushCallback here would push
    # every scalar twice.
    # Rank-0-only artifacts (:85-92); other workers would corrupt them.
    if hvt.rank() == 0:
        callbacks.append(
            # HVT_SAVE_EVERY_STEPS (env default) additionally checkpoints
            # every N optimizer steps with an (epoch, step) manifest —
            # the resume below then restarts mid-epoch, not at the
            # epoch boundary.
            hvt.callbacks.ModelCheckpoint(os.path.join(model_dir, "checkpoint-{epoch}.msgpack"))
        )
        callbacks.append(hvt.callbacks.ScalarLogger(model_dir, update_freq="batch"))

    steps_per_epoch = int(os.environ.get("DRIVE_STEPS", 0)) or hvt.shard_steps(500)  # :96
    epochs = int(os.environ.get("DRIVE_EPOCHS", 0)) or 24  # :96

    # Resume: restore the newest checkpoint (primary loads, every process
    # adopts via broadcast) and continue the epoch numbering — the
    # reference's restore contract (tensorflow2_keras_mnist.py:68-71) made
    # explicit, at STEP granularity: a mid-epoch checkpoint's manifest
    # hands back (epoch, step) and fit fast-forwards the data to exactly
    # there. A fresh model_dir starts from epoch 0.
    trainer.build(x_train[:1])
    trainer.state, done_epochs, done_steps = (
        checkpoint.restore_latest_and_broadcast(
            model_dir, trainer.state, mesh=trainer.mesh, with_step=True
        )
    )
    if (done_epochs or done_steps) and hvt.rank() == 0:
        print(
            f"Resuming from checkpoint epoch {done_epochs}"
            + (f" step {done_steps}" if done_steps else "")
        )

    trainer.fit(
        dataset,
        steps_per_epoch=steps_per_epoch,
        epochs=epochs,
        initial_epoch=done_epochs,
        initial_step=done_steps,
        callbacks=callbacks,
        verbose=1 if hvt.rank() == 0 else 0,  # :92
    )


if __name__ == "__main__":
    main()
