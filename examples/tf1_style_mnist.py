"""MNIST train → eval → serving export — the TF1-script capability set.

Behavioral mirror of the reference's `mnist_keras.py` (citations are to that
file): platform metrics init (:22-23), runtime bootstrap (:30-36), epoch-count
work division ``ceil(12 / size)`` (:38-42), full-dataset normalize + one-hot
labels (:48-69), the same CNN (:71-81), Adadelta with lr = 1.0 × size (:84)
wrapped for gradient averaging (:87), categorical cross-entropy (:89-92),
broadcast-from-0 callback only (:94-98), rank-0 checkpoints + event log
(:100-105), per-epoch validation + final all-rank evaluate (:107-113), and the
rank-0 export tail (:116-143): save final model, reload it, export a serving
bundle with an ``input → prob`` signature into a timestamped directory, print
test loss/accuracy (the CI gate's input, config.yaml:8-11).

Smoke-test env knobs: DRIVE_EPOCHS, DRIVE_TRAIN_N, DRIVE_EVAL_N.
"""

import os

try:
    import horovod_tpu  # noqa: F401 — installed (`pip install -e .`)
except ModuleNotFoundError:  # bare source checkout: make the repo importable
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import optax

import horovod_tpu as hvt
from horovod_tpu import checkpoint, metrics
from horovod_tpu.data import datasets
from horovod_tpu.models.cnn import MnistCNN


def main() -> None:
    model_path = os.environ.get("PS_MODEL_PATH", "./models")  # :26-27
    model_dir = os.path.join(model_path, "horovod-mnist")
    export_dir = os.path.join(model_path, "horovod-mnist-export")

    metrics.init(sync_tensorboard=True)  # :22-23
    hvt.init()  # :30

    batch_size = 128  # :39
    num_classes = 10  # :40
    # Work division idiom #2: epoch count ÷ world size (:42).
    epochs = int(os.environ.get("DRIVE_EPOCHS", 0)) or hvt.shard_epochs(12)

    # Full-dataset load + /255 normalize (:48-63); NHWC is the TPU-native
    # layout (the reference's channels_first branch served Theano, :55-60).
    (x_train, y_train), (x_test, y_test) = datasets.mnist()
    x_train = (x_train.astype(np.float32) / 255.0)[..., None]
    x_test = (x_test.astype(np.float32) / 255.0)[..., None]
    if os.environ.get("DRIVE_TRAIN_N"):
        n = int(os.environ["DRIVE_TRAIN_N"])
        x_train, y_train = x_train[:n], y_train[:n]
    if os.environ.get("DRIVE_EVAL_N"):
        n = int(os.environ["DRIVE_EVAL_N"])
        x_test, y_test = x_test[:n], y_test[:n]
    # One-hot labels + categorical CE, exactly the reference pairing (:66-69,:89).
    y_train_oh = np.eye(num_classes, dtype=np.float32)[y_train]
    y_test_oh = np.eye(num_classes, dtype=np.float32)[y_test]

    trainer = hvt.Trainer(
        MnistCNN(num_classes=num_classes),
        # Adadelta(1.0 × size) (:84) + gradient averaging (:87).
        hvt.DistributedOptimizer(optax.adadelta(hvt.scale_lr(1.0))),
        loss="categorical_crossentropy",  # :89
    )

    # Broadcast only, like the reference (:94-98). Epoch scalars reach the
    # platform sink through sync_tensorboard (metrics.init above) — the
    # gradient_utils contract — so no explicit push callback is needed.
    callbacks = [hvt.callbacks.BroadcastGlobalVariablesCallback(0)]
    if hvt.rank() == 0:  # :100-105
        callbacks.append(
            hvt.callbacks.ModelCheckpoint(os.path.join(model_dir, "checkpoint-{epoch}.msgpack"))
        )
        callbacks.append(
            hvt.callbacks.ScalarLogger(os.path.join(model_dir, "eval"), update_freq="batch")
        )

    # Resume from the newest checkpoint, continuing epoch numbering (the
    # reference's implicit restore contract, mnist_keras.py:95-96).
    trainer.build(x_train[:1])
    trainer.state, done_epochs = checkpoint.restore_latest_and_broadcast(
        model_dir, trainer.state, mesh=trainer.mesh
    )
    if done_epochs and hvt.rank() == 0:
        print(f"Resuming from checkpoint epoch {done_epochs}")

    # HVT_DEVICE_CACHE=1: stage the dataset into HBM once and train/validate
    # with one dispatch per epoch (Trainer.fit cache='device') — same math,
    # drastically less host↔device traffic. Off by default to mirror the
    # reference's streaming pipeline.
    fit_kwargs = (
        {"cache": "device"} if hvt.runtime.env_flag("HVT_DEVICE_CACHE") else {}
    )
    trainer.fit(  # :107-112
        x=x_train,
        y=y_train_oh,
        batch_size=batch_size,
        epochs=epochs,
        initial_epoch=done_epochs,
        callbacks=callbacks,
        validation_data=(x_test, y_test_oh),
        verbose=1 if hvt.rank() == 0 else 0,
        **fit_kwargs,
    )

    score = trainer.evaluate(x_test, y_test_oh, batch_size=batch_size)  # :113

    if hvt.rank() == 0:  # :116-140
        # Final model save → reload round-trip (:118-124).
        final_path = os.path.join(model_dir, "keras-sample-model.msgpack")
        checkpoint.save(final_path, trainer.state)
        restored = checkpoint.restore(final_path, trainer.state)
        # Serving export: timestamped dir, input → prob signature (:126-140).
        # HVT_EXPORT_FORMAT=savedmodel emits a TF SavedModel (the
        # reference's exact artifact) via jax2tf; default is the TF-free
        # StableHLO bundle.
        bundle = checkpoint.export_serving(
            export_dir,
            lambda params, x: trainer.module.apply({"params": params}, x, train=False),
            restored.params,
            input_shape=(1, 28, 28, 1),
            format=os.environ.get("HVT_EXPORT_FORMAT", "stablehlo"),
        )
        print("Exported serving bundle:", bundle)

    metrics.push("loss", score["loss"])
    metrics.push("accuracy", score["accuracy"])
    print("Test loss:", score["loss"])  # :142
    print("Test accuracy:", score["accuracy"])  # :143


if __name__ == "__main__":
    main()
