"""CIFAR-10 ResNet-20 data-parallel training — the heavier-gradients config.

BASELINE.json config 4: same capability set as `examples/tf2_style_mnist.py`
(bootstrap, sharded data, gradient-averaging optimizer, broadcast /
metric-average / warmup callbacks, rank-0 I/O — all citing the same
tensorflow2_keras_mnist.py behaviors), but with a model whose gradient
pytree (~270k params across 20 conv layers) exercises the allreduce path the
way real workloads do. BatchNorm runs with global-batch (sync-BN) semantics
inside the SPMD step.

Env knobs: DRIVE_STEPS, DRIVE_EPOCHS, DRIVE_EVAL_N.
"""

import os

try:
    import horovod_tpu  # noqa: F401 — installed (`pip install -e .`)
except ModuleNotFoundError:  # bare source checkout: make the repo importable
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvt
from horovod_tpu import metrics
from horovod_tpu.data import datasets
from horovod_tpu.data.loader import ArrayDataset
from horovod_tpu.models.resnet import ResNetCIFAR
from horovod_tpu.models.vit import ViT


def main() -> None:
    model_dir = os.path.join(os.environ.get("PS_MODEL_PATH", "./models"), "horovod-cifar")

    hvt.init()
    metrics.init(sync_tensorboard=True)

    (x_train, y_train), (x_test, y_test) = datasets.cifar10(
        path=f"cifar10-{hvt.rank()}.npz"
    )
    x_train = x_train.astype(np.float32) / 255.0
    x_test = x_test.astype(np.float32) / 255.0
    y_train = y_train.astype(np.int64)
    y_test = y_test.astype(np.int64)
    if os.environ.get("DRIVE_EVAL_N"):
        n = int(os.environ["DRIVE_EVAL_N"])
        x_test, y_test = x_test[:n], y_test[:n]

    world = hvt.process_count()
    per_process_batch = 128 * hvt.size() // world
    dataset = (
        ArrayDataset((x_train, y_train))
        .shard(hvt.process_rank(), world)
        .repeat()
        .shuffle(10000, seed=hvt.process_rank())
        .batch(per_process_batch)
    )

    # ARCH=vit swaps the conv model for the conv-free ViT (models/vit.py)
    # through the identical training path — architecture is a swappable
    # leaf, and the ViT's matmul shapes reach MFU the CIFAR convs can't
    # (BASELINE.md vit row).
    if os.environ.get("ARCH", "resnet") == "vit":
        module = ViT(
            patch_size=4, d_model=256, n_heads=8, n_layers=6,
            compute_dtype=jnp.bfloat16,
        )
    else:
        module = ResNetCIFAR(depth=20, compute_dtype=jnp.bfloat16)
    trainer = hvt.Trainer(
        module,
        hvt.DistributedOptimizer(optax.adam(hvt.scale_lr(0.001))),
        loss="sparse_categorical_crossentropy",
    )

    callbacks = [
        hvt.callbacks.BroadcastGlobalVariablesCallback(0),
        hvt.callbacks.MetricAverageCallback(),
        hvt.callbacks.LearningRateWarmupCallback(warmup_epochs=3, verbose=1),
    ]
    # Epoch scalars reach the platform sink via sync_tensorboard (metrics.init
    # above); an explicit MetricsPushCallback would push everything twice.
    if hvt.rank() == 0:
        callbacks.append(
            hvt.callbacks.ModelCheckpoint(os.path.join(model_dir, "checkpoint-{epoch}.msgpack"))
        )
        callbacks.append(hvt.callbacks.ScalarLogger(model_dir))

    steps_per_epoch = int(os.environ.get("DRIVE_STEPS", 0)) or hvt.shard_steps(390)
    epochs = int(os.environ.get("DRIVE_EPOCHS", 0)) or 24

    trainer.fit(
        dataset,
        steps_per_epoch=steps_per_epoch,
        epochs=epochs,
        callbacks=callbacks,
        verbose=1 if hvt.rank() == 0 else 0,
    )

    score = trainer.evaluate(x_test, y_test, batch_size=128)
    metrics.push("loss", score["loss"])
    metrics.push("accuracy", score["accuracy"])
    if hvt.rank() == 0:
        print("Test loss:", score["loss"])
        print("Test accuracy:", score["accuracy"])


if __name__ == "__main__":
    main()
