"""Train-then-generate walkthrough: the inference side of the framework.

The reference's serving story ends at a SavedModel export
(mnist_keras.py:116-140); this example shows what a user actually does
with a trained LM here:

1. train a small decoder LM on the copy task (long-range recall — the
   greedy continuation of a copy prompt is the prompt's first half);
2. checkpoint it (process-0 single-writer, msgpack);
3. generate with the KV-cache decode loop (`models/decoding.generate`) —
   greedy, then temperature/top-k/top-p sampling;
4. generate the SAME tokens faster with speculative decoding
   (`models/speculative.py`, prompt-lookup draft) and print the measured
   acceptance + agreement — the exactness contract made visible.

Runs on one chip (or CPU) with no launcher. Knobs: DRIVE_EPOCHS,
DRIVE_STEPS, SEQ_LEN, DMODEL, NLAYERS, KV_HEADS (grouped-query
attention), GAMMA (speculative chunk), TEMPERATURE, TOP_K, TOP_P.
"""

import os
import time

try:
    import horovod_tpu  # noqa: F401 — installed (`pip install -e .`)
except ModuleNotFoundError:  # bare source checkout: make the repo importable
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvt
from horovod_tpu import checkpoint
from horovod_tpu.data import datasets
from horovod_tpu.models.decoding import generate, make_generate_fn
from horovod_tpu.models.speculative import make_speculative_fn
from horovod_tpu.models.transformer import TransformerLM

VOCAB = 64


def main():
    hvt.init()
    seq = int(os.environ.get("SEQ_LEN", 128))
    model = TransformerLM(
        vocab_size=VOCAB,
        d_model=int(os.environ.get("DMODEL", 128)),
        n_heads=8,
        n_kv_heads=int(os.environ.get("KV_HEADS", 0)) or None,
        n_layers=int(os.environ.get("NLAYERS", 4)),
        dropout=0.0,
        compute_dtype=jnp.bfloat16,
    )
    trainer = hvt.Trainer(
        model,
        hvt.DistributedOptimizer(optax.adam(hvt.scale_lr(1e-3))),
        loss="sparse_categorical_crossentropy",
    )

    # 1. train on the copy task: second half of each row repeats the first.
    x, y = datasets.copy_task(2048, seq, vocab_size=VOCAB, seed=3)
    hist = trainer.fit(
        x=x, y=y,
        batch_size=32,
        epochs=int(os.environ.get("DRIVE_EPOCHS", 4)),
        steps_per_epoch=int(os.environ.get("DRIVE_STEPS", 48)),
        verbose=1,
    )
    print(f"final train loss: {hist[-1]['loss']:.4f}")

    # 2. checkpoint (rank-0 single-writer), reference-style per-epoch dirs.
    model_dir = os.path.join(
        os.environ.get("PS_MODEL_PATH", "./models"), "lm-generate"
    )
    if hvt.rank() == 0:
        os.makedirs(model_dir, exist_ok=True)
        checkpoint.save(
            os.path.join(model_dir, "checkpoint-final.msgpack"), trainer.state
        )
        print(f"checkpoint -> {model_dir}/checkpoint-final.msgpack")

    params = trainer.state.params
    xt, _ = datasets.copy_task(2, seq, vocab_size=VOCAB, seed=999)
    prompt = jnp.asarray(xt[:, : seq // 2])
    n_new = seq // 2 - 1

    # 3. greedy + sampled generation through the KV-cache decode loop.
    greedy = generate(model, params, prompt, n_new)
    match = float(
        (np.asarray(greedy[:, seq // 2 :]) == np.asarray(xt[:, seq // 2 : -1]))
        .mean()
    )
    print(f"greedy recall of the copied half: {match:.1%}")

    # 3b. STREAM=1: the same generation through the bounded ring-buffer
    # cache (sliding-window + pinned attention sinks — StreamingLLM). The
    # cache is [B, SINKS + WINDOW] slots however long generation runs.
    if os.environ.get("STREAM"):
        streamer = model.clone(
            window=int(os.environ.get("WINDOW", seq // 4)),
            attention_sinks=int(os.environ.get("SINKS", 4)),
            sliding_cache=True,
        )
        streamed = generate(streamer, params, prompt, n_new)
        # Compare the GENERATED half only — the prompt half is identical
        # by construction and would inflate the agreement number.
        agree = float(
            (np.asarray(streamed[:, seq // 2:])
             == np.asarray(greedy[:, seq // 2:])).mean()
        )
        print(
            f"streamed generation ({streamer.attention_sinks} sinks + "
            f"{streamer.window}-slot ring): {agree:.1%} token agreement "
            "with the full cache (approximate for this densely-trained "
            "model — the recipe keeps it stable past its window)"
        )

    sampled = generate(
        model, params, prompt, n_new,
        temperature=float(os.environ.get("TEMPERATURE", 0.8)),
        top_k=int(os.environ.get("TOP_K", 0)),
        top_p=float(os.environ.get("TOP_P", 0.9)),
        rng=jax.random.PRNGKey(0),
    )
    print("sampled tail:", np.asarray(sampled[0, -8:]).tolist())

    # 4. speculative decoding: same tokens, fewer target passes.
    plain_fn = make_generate_fn(model, max_new_tokens=n_new)
    spec_fn = make_speculative_fn(
        model, max_new_tokens=n_new,
        gamma=int(os.environ.get("GAMMA", 8)), return_stats=True,
    )
    key = jax.random.PRNGKey(0)
    jax.block_until_ready(plain_fn(params, prompt, key))  # compile
    out_spec, stats = spec_fn(params, prompt)
    jax.block_until_ready(out_spec)

    t0 = time.time()
    out_plain = jax.device_get(plain_fn(params, prompt, key))
    t_plain = time.time() - t0
    t0 = time.time()
    out_spec = jax.device_get(spec_fn(params, prompt)[0])
    t_spec = time.time() - t0
    rounds = int(jax.device_get(stats["rounds"]))
    agree = bool(np.array_equal(out_plain, out_spec))
    print(
        f"speculative: {rounds} target passes for {n_new} tokens "
        f"({n_new / rounds:.1f} tok/pass), outputs identical: {agree}, "
        f"wall {t_plain * 1e3:.0f} -> {t_spec * 1e3:.0f} ms (single-call "
        f"timings include the host round-trip; BENCH_MODEL=spec measures "
        f"the honest chained speedup)"
    )
    assert agree, "speculative output diverged from plain greedy"


if __name__ == "__main__":
    main()
