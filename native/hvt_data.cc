// hvt_data — native batch-assembly engine for the input pipeline.
//
// The runtime-layer slot the reference fills with Horovod's C++ core
// (SURVEY.md §2.3): where Horovod's native code coordinates collectives
// (obsolete under SPMD/XLA — the compiler owns that), the host-side cost
// that remains in this framework is batch assembly: per-epoch permutation,
// row gather, and staging, all GIL-bound in pure Python. This library runs
// them in a producer thread writing into a bounded ring of pre-allocated
// slots, overlapping batch assembly with the accelerator step.
//
// Exposed as a tiny C ABI consumed via ctypes (no pybind11 in this image):
//   hvt_loader_create(arr_ptrs, row_bytes, n_arrays, n_examples,
//                     batch, n_slots, seed, shuffle,
//                     start_epoch, batches_per_epoch)  -> handle
//   hvt_loader_next(handle)             -> slot id (blocks until filled)
//   hvt_loader_slot_ptr(handle, slot, array_idx) -> buffer pointer
//   hvt_loader_release(handle, slot)    -> recycle a consumed slot
//   hvt_loader_destroy(handle)
//
// Semantics match the Python ArrayDataset training path: a fresh full
// permutation per epoch (the reference's shuffle(10000)-over-60k behaves
// as one, tensorflow2_keras_mnist.py:40), repeating forever; batches never
// straddle an epoch boundary remainder (drop_remainder=True).
//
// Epoch anchoring (the durable-stream-cursor contract, data/stream.py):
// each pass's permutation is a PURE function of (seed, epoch, pass) — the
// RNG is reseeded via splitmix64 mixing and the permutation reset to
// identity at every pass start — so any position in the infinite stream
// is reconstructible without replaying the stream before it:
//   * start_epoch anchors the stream's first epoch to an absolute number;
//   * batches_per_epoch > 0 cuts epochs at exactly that many batches
//     (passes roll within an epoch when it is longer than one permutation;
//     the unconsumed tail of a pass is discarded at the epoch boundary);
//     0 keeps the historical pass-per-epoch semantics, now anchored.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

// splitmix64 — the seed-mixing primitive (also used inside XorShift128Plus
// seeding); chains (seed, epoch, pass) into one well-distributed word so
// every pass draws an independent, ADDRESSABLE permutation.
inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ULL;
  uint64_t z = x;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
  return z ^ (z >> 31);
}

inline uint64_t mix_seed(uint64_t seed, int64_t epoch, int64_t pass) {
  uint64_t s = splitmix64(seed);
  s = splitmix64(s ^ (static_cast<uint64_t>(epoch) + 0xA5A5A5A5A5A5A5A5ULL));
  s = splitmix64(s ^ (static_cast<uint64_t>(pass) + 0x5A5A5A5A5A5A5A5AULL));
  return s;
}

// xorshift128+ — deterministic, seedable, fast; quality is ample for
// shuffling (this is not a cryptographic context).
struct XorShift128Plus {
  uint64_t s0, s1;
  explicit XorShift128Plus(uint64_t seed) {
    // splitmix64 expansion of the seed into two non-zero words.
    auto next = [&seed]() {
      seed += 0x9E3779B97F4A7C15ULL;
      uint64_t z = seed;
      z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
      z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
      return z ^ (z >> 31);
    };
    s0 = next();
    s1 = next();
    if (s0 == 0 && s1 == 0) s0 = 1;
  }
  uint64_t operator()() {
    uint64_t x = s0;
    const uint64_t y = s1;
    s0 = y;
    x ^= x << 23;
    s1 = x ^ y ^ (x >> 17) ^ (y >> 26);
    return s1 + y;
  }
  // Unbiased bounded sample via rejection.
  uint64_t bounded(uint64_t n) {
    const uint64_t limit = UINT64_MAX - UINT64_MAX % n;
    uint64_t v;
    do {
      v = (*this)();
    } while (v >= limit);
    return v % n;
  }
};

struct Loader {
  std::vector<const uint8_t*> arrays;   // source base pointers (borrowed)
  std::vector<int64_t> row_bytes;       // bytes per example, per array
  int64_t n_examples = 0;
  int64_t batch = 0;
  int n_slots = 0;
  bool shuffle = true;
  int64_t start_epoch = 0;        // absolute epoch the stream starts at
  int64_t batches_per_epoch = 0;  // 0 = one permutation pass per epoch

  // slot_buffers[slot][array] — owned staging buffers.
  std::vector<std::vector<std::vector<uint8_t>>> slots;
  std::vector<int> ready;   // filled slot ids, FIFO
  std::vector<char> free_;  // free_[slot] == 1 → producer may fill it
  std::mutex mu;
  std::condition_variable cv_ready, cv_free, cv_quiesce;
  std::atomic<bool> stop{false};
  int consumers_in_next = 0;  // guarded by mu; destroy waits for 0
  std::thread producer;
  uint64_t seed;

  explicit Loader(uint64_t seed_) : seed(seed_) {}

  void fill(int slot, const std::vector<int64_t>& perm, int64_t offset) {
    for (size_t a = 0; a < arrays.size(); ++a) {
      const int64_t rb = row_bytes[a];
      uint8_t* dst = slots[slot][a].data();
      const uint8_t* src = arrays[a];
      for (int64_t i = 0; i < batch; ++i) {
        std::memcpy(dst + i * rb, src + perm[offset + i] * rb, rb);
      }
    }
  }

  // Reset the permutation to identity and Fisher-Yates it with the rng
  // derived purely from (seed, epoch, pass): the anchoring invariant.
  void reshuffle(std::vector<int64_t>* perm, int64_t epoch, int64_t pass) {
    for (int64_t i = 0; i < n_examples; ++i) (*perm)[i] = i;
    if (!shuffle) return;
    XorShift128Plus rng(mix_seed(seed, epoch, pass));
    for (int64_t i = n_examples - 1; i > 0; --i) {
      const int64_t j = static_cast<int64_t>(rng.bounded(i + 1));
      std::swap((*perm)[i], (*perm)[j]);
    }
  }

  void run() {
    std::vector<int64_t> perm(n_examples);
    int64_t epoch = start_epoch;
    int64_t pass = 0;
    int64_t emitted = 0;          // batches emitted within the epoch
    int64_t cursor = n_examples;  // force a reshuffle on first use
    const int64_t usable = n_examples - n_examples % batch;
    while (!stop.load(std::memory_order_relaxed)) {
      if (batches_per_epoch > 0 && emitted >= batches_per_epoch) {
        // Epoch boundary by batch count: discard the pass tail, advance.
        ++epoch;
        pass = 0;
        emitted = 0;
        cursor = n_examples;  // force the new epoch's first shuffle
      }
      if (cursor >= usable) {
        if (cursor != static_cast<int64_t>(n_examples) ||
            emitted > 0 || pass > 0) {
          // A pass genuinely ran dry (not the initial sentinel): with
          // batch-cut epochs the next pass stays inside this epoch;
          // with pass-per-epoch semantics the pass boundary IS the
          // epoch boundary.
          if (batches_per_epoch > 0) {
            ++pass;
          } else {
            ++epoch;
          }
        }
        reshuffle(&perm, epoch, pass);
        cursor = 0;
      }
      int slot = -1;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_free.wait(lk, [&] {
          if (stop.load(std::memory_order_relaxed)) return true;
          for (int s = 0; s < n_slots; ++s)
            if (free_[s]) return true;
          return false;
        });
        if (stop.load(std::memory_order_relaxed)) return;
        for (int s = 0; s < n_slots; ++s)
          if (free_[s]) { slot = s; break; }
        free_[slot] = 0;
      }
      fill(slot, perm, cursor);
      cursor += batch;
      ++emitted;
      {
        std::lock_guard<std::mutex> lk(mu);
        ready.push_back(slot);
      }
      cv_ready.notify_one();
    }
  }
};

}  // namespace

extern "C" {

// ABI handshake: bumped whenever hvt_loader_create's signature or the
// stream semantics change. The Python binding refuses to use a library
// reporting a different version (or lacking the symbol — a pre-handshake
// build): calling a stale 8-arg library with 10 args would silently
// ignore the anchoring arguments and produce a DIFFERENT byte stream
// than the cursors describe.
//   v2: (seed, epoch, pass)-anchored permutations; start_epoch /
//       batches_per_epoch create arguments.
int hvt_loader_abi_version() { return 2; }

void* hvt_loader_create(const uint8_t** arr_ptrs, const int64_t* row_bytes,
                        int n_arrays, int64_t n_examples, int64_t batch,
                        int n_slots, uint64_t seed, int shuffle,
                        int64_t start_epoch, int64_t batches_per_epoch) {
  if (n_arrays <= 0 || n_examples < batch || batch <= 0 || n_slots < 2 ||
      start_epoch < 0 || batches_per_epoch < 0)
    return nullptr;
  auto* L = new Loader(seed);
  L->arrays.assign(arr_ptrs, arr_ptrs + n_arrays);
  L->row_bytes.assign(row_bytes, row_bytes + n_arrays);
  L->n_examples = n_examples;
  L->batch = batch;
  L->n_slots = n_slots;
  L->shuffle = shuffle != 0;
  L->start_epoch = start_epoch;
  L->batches_per_epoch = batches_per_epoch;
  L->slots.resize(n_slots);
  for (int s = 0; s < n_slots; ++s) {
    L->slots[s].resize(n_arrays);
    for (int a = 0; a < n_arrays; ++a)
      L->slots[s][a].resize(static_cast<size_t>(batch) * row_bytes[a]);
  }
  L->free_.assign(n_slots, 1);
  L->producer = std::thread([L] { L->run(); });
  return L;
}

// Blocks until a slot is filled; returns its id (>= 0), or -1 after destroy.
int hvt_loader_next(void* handle) {
  auto* L = static_cast<Loader*>(handle);
  std::unique_lock<std::mutex> lk(L->mu);
  ++L->consumers_in_next;
  L->cv_ready.wait(lk, [&] {
    return L->stop.load(std::memory_order_relaxed) || !L->ready.empty();
  });
  int slot = -1;
  // Stop wins even if batches are queued: a destroy() in flight is about to
  // free the slot buffers this id would point into.
  if (!L->stop.load(std::memory_order_relaxed) && !L->ready.empty()) {
    slot = L->ready.front();
    L->ready.erase(L->ready.begin());
  }
  --L->consumers_in_next;
  if (L->consumers_in_next == 0 && L->stop.load(std::memory_order_relaxed)) {
    // Notify UNDER the mutex: destroy() cannot re-acquire it (and delete
    // this object) until we return and release — no use-after-free window.
    L->cv_quiesce.notify_all();
  }
  return slot;
}

const uint8_t* hvt_loader_slot_ptr(void* handle, int slot, int array_idx) {
  auto* L = static_cast<Loader*>(handle);
  return L->slots[slot][array_idx].data();
}

void hvt_loader_release(void* handle, int slot) {
  auto* L = static_cast<Loader*>(handle);
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->free_[slot] = 1;
  }
  L->cv_free.notify_one();
}

void hvt_loader_destroy(void* handle) {
  auto* L = static_cast<Loader*>(handle);
  {
    // stop must flip under the mutex: a waiter that has checked its
    // predicate but not yet blocked would otherwise miss the notify and
    // sleep forever.
    std::lock_guard<std::mutex> lk(L->mu);
    L->stop.store(true);
  }
  L->cv_free.notify_all();
  L->cv_ready.notify_all();
  if (L->producer.joinable()) L->producer.join();
  {
    // Wait for any consumer blocked in next() to drain before freeing.
    std::unique_lock<std::mutex> lk(L->mu);
    L->cv_quiesce.wait(lk, [&] { return L->consumers_in_next == 0; });
  }
  delete L;
}

}  // extern "C"
