// hvt_data — native batch-assembly engine for the input pipeline.
//
// The runtime-layer slot the reference fills with Horovod's C++ core
// (SURVEY.md §2.3): where Horovod's native code coordinates collectives
// (obsolete under SPMD/XLA — the compiler owns that), the host-side cost
// that remains in this framework is batch assembly: per-epoch permutation,
// row gather, and staging, all GIL-bound in pure Python. This library runs
// them in a producer thread writing into a bounded ring of pre-allocated
// slots, overlapping batch assembly with the accelerator step.
//
// Exposed as a tiny C ABI consumed via ctypes (no pybind11 in this image):
//   hvt_loader_create(arr_ptrs, row_bytes, n_arrays, n_examples,
//                     batch, n_slots, seed, shuffle)  -> handle
//   hvt_loader_next(handle)             -> slot id (blocks until filled)
//   hvt_loader_slot_ptr(handle, slot, array_idx) -> buffer pointer
//   hvt_loader_release(handle, slot)    -> recycle a consumed slot
//   hvt_loader_destroy(handle)
//
// Semantics match the Python ArrayDataset training path: a fresh full
// permutation per epoch (the reference's shuffle(10000)-over-60k behaves
// as one, tensorflow2_keras_mnist.py:40), repeating forever; batches never
// straddle an epoch boundary remainder (drop_remainder=True).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

// xorshift128+ — deterministic, seedable, fast; quality is ample for
// shuffling (this is not a cryptographic context).
struct XorShift128Plus {
  uint64_t s0, s1;
  explicit XorShift128Plus(uint64_t seed) {
    // splitmix64 expansion of the seed into two non-zero words.
    auto next = [&seed]() {
      seed += 0x9E3779B97F4A7C15ULL;
      uint64_t z = seed;
      z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
      z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
      return z ^ (z >> 31);
    };
    s0 = next();
    s1 = next();
    if (s0 == 0 && s1 == 0) s0 = 1;
  }
  uint64_t operator()() {
    uint64_t x = s0;
    const uint64_t y = s1;
    s0 = y;
    x ^= x << 23;
    s1 = x ^ y ^ (x >> 17) ^ (y >> 26);
    return s1 + y;
  }
  // Unbiased bounded sample via rejection.
  uint64_t bounded(uint64_t n) {
    const uint64_t limit = UINT64_MAX - UINT64_MAX % n;
    uint64_t v;
    do {
      v = (*this)();
    } while (v >= limit);
    return v % n;
  }
};

struct Loader {
  std::vector<const uint8_t*> arrays;   // source base pointers (borrowed)
  std::vector<int64_t> row_bytes;       // bytes per example, per array
  int64_t n_examples = 0;
  int64_t batch = 0;
  int n_slots = 0;
  bool shuffle = true;

  // slot_buffers[slot][array] — owned staging buffers.
  std::vector<std::vector<std::vector<uint8_t>>> slots;
  std::vector<int> ready;   // filled slot ids, FIFO
  std::vector<char> free_;  // free_[slot] == 1 → producer may fill it
  std::mutex mu;
  std::condition_variable cv_ready, cv_free, cv_quiesce;
  std::atomic<bool> stop{false};
  int consumers_in_next = 0;  // guarded by mu; destroy waits for 0
  std::thread producer;
  XorShift128Plus rng;

  Loader(uint64_t seed) : rng(seed) {}

  void fill(int slot, const std::vector<int64_t>& perm, int64_t offset) {
    for (size_t a = 0; a < arrays.size(); ++a) {
      const int64_t rb = row_bytes[a];
      uint8_t* dst = slots[slot][a].data();
      const uint8_t* src = arrays[a];
      for (int64_t i = 0; i < batch; ++i) {
        std::memcpy(dst + i * rb, src + perm[offset + i] * rb, rb);
      }
    }
  }

  void run() {
    std::vector<int64_t> perm(n_examples);
    for (int64_t i = 0; i < n_examples; ++i) perm[i] = i;
    int64_t cursor = n_examples;  // force a reshuffle on first use
    const int64_t usable = n_examples - n_examples % batch;
    while (!stop.load(std::memory_order_relaxed)) {
      if (cursor >= usable) {
        if (shuffle) {
          for (int64_t i = n_examples - 1; i > 0; --i) {
            const int64_t j = static_cast<int64_t>(rng.bounded(i + 1));
            std::swap(perm[i], perm[j]);
          }
        }
        cursor = 0;
      }
      int slot = -1;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_free.wait(lk, [&] {
          if (stop.load(std::memory_order_relaxed)) return true;
          for (int s = 0; s < n_slots; ++s)
            if (free_[s]) return true;
          return false;
        });
        if (stop.load(std::memory_order_relaxed)) return;
        for (int s = 0; s < n_slots; ++s)
          if (free_[s]) { slot = s; break; }
        free_[slot] = 0;
      }
      fill(slot, perm, cursor);
      cursor += batch;
      {
        std::lock_guard<std::mutex> lk(mu);
        ready.push_back(slot);
      }
      cv_ready.notify_one();
    }
  }
};

}  // namespace

extern "C" {

void* hvt_loader_create(const uint8_t** arr_ptrs, const int64_t* row_bytes,
                        int n_arrays, int64_t n_examples, int64_t batch,
                        int n_slots, uint64_t seed, int shuffle) {
  if (n_arrays <= 0 || n_examples < batch || batch <= 0 || n_slots < 2)
    return nullptr;
  auto* L = new Loader(seed);
  L->arrays.assign(arr_ptrs, arr_ptrs + n_arrays);
  L->row_bytes.assign(row_bytes, row_bytes + n_arrays);
  L->n_examples = n_examples;
  L->batch = batch;
  L->n_slots = n_slots;
  L->shuffle = shuffle != 0;
  L->slots.resize(n_slots);
  for (int s = 0; s < n_slots; ++s) {
    L->slots[s].resize(n_arrays);
    for (int a = 0; a < n_arrays; ++a)
      L->slots[s][a].resize(static_cast<size_t>(batch) * row_bytes[a]);
  }
  L->free_.assign(n_slots, 1);
  L->producer = std::thread([L] { L->run(); });
  return L;
}

// Blocks until a slot is filled; returns its id (>= 0), or -1 after destroy.
int hvt_loader_next(void* handle) {
  auto* L = static_cast<Loader*>(handle);
  std::unique_lock<std::mutex> lk(L->mu);
  ++L->consumers_in_next;
  L->cv_ready.wait(lk, [&] {
    return L->stop.load(std::memory_order_relaxed) || !L->ready.empty();
  });
  int slot = -1;
  // Stop wins even if batches are queued: a destroy() in flight is about to
  // free the slot buffers this id would point into.
  if (!L->stop.load(std::memory_order_relaxed) && !L->ready.empty()) {
    slot = L->ready.front();
    L->ready.erase(L->ready.begin());
  }
  --L->consumers_in_next;
  if (L->consumers_in_next == 0 && L->stop.load(std::memory_order_relaxed)) {
    // Notify UNDER the mutex: destroy() cannot re-acquire it (and delete
    // this object) until we return and release — no use-after-free window.
    L->cv_quiesce.notify_all();
  }
  return slot;
}

const uint8_t* hvt_loader_slot_ptr(void* handle, int slot, int array_idx) {
  auto* L = static_cast<Loader*>(handle);
  return L->slots[slot][array_idx].data();
}

void hvt_loader_release(void* handle, int slot) {
  auto* L = static_cast<Loader*>(handle);
  {
    std::lock_guard<std::mutex> lk(L->mu);
    L->free_[slot] = 1;
  }
  L->cv_free.notify_one();
}

void hvt_loader_destroy(void* handle) {
  auto* L = static_cast<Loader*>(handle);
  {
    // stop must flip under the mutex: a waiter that has checked its
    // predicate but not yet blocked would otherwise miss the notify and
    // sleep forever.
    std::lock_guard<std::mutex> lk(L->mu);
    L->stop.store(true);
  }
  L->cv_free.notify_all();
  L->cv_ready.notify_all();
  if (L->producer.joinable()) L->producer.join();
  {
    // Wait for any consumer blocked in next() to drain before freeing.
    std::unique_lock<std::mutex> lk(L->mu);
    L->cv_quiesce.wait(lk, [&] { return L->consumers_in_next == 0; });
  }
  delete L;
}

}  // extern "C"
