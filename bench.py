"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric (BASELINE.json): MNIST training images/sec/chip through the
full distributed-training step — forward, loss, backward, gradient
allreduce-mean (the DistributedOptimizer path), optimizer apply — on the
reference's exact training config: the 2-conv CNN
(tensorflow2_keras_mnist.py:43-52), per-worker batch 128
(tensorflow2_keras_mnist.py:41), Adam (tensorflow2_keras_mnist.py:55).

``vs_baseline`` is the ratio against the measured reference-equivalent
TF2/Keras single-process run on this machine's CPU
(``benchmarks/baseline_measured.json``, produced by
``benchmarks/measure_reference_baseline.py`` — the reference publishes no
numbers of its own, SURVEY.md §6).

``BENCH_MODEL=resnet`` switches to the heavier-gradients config
(BASELINE.json config 4: CIFAR-10 ResNet-20); default is the MNIST headline.
"""

from __future__ import annotations

import json
import os
import time

BATCH = 128
WARMUP_STEPS = 20
MEASURE_STEPS = 400
REPO = os.path.dirname(os.path.abspath(__file__))


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvt
    from horovod_tpu.data import datasets
    from horovod_tpu.models.cnn import MnistCNN
    from horovod_tpu.models.resnet import ResNetCIFAR

    hvt.init()
    n_chips = jax.device_count()
    which = os.environ.get("BENCH_MODEL", "mnist")

    if which == "resnet":
        (x_train, y_train), _ = datasets.cifar10()
        x = x_train.astype(np.float32) / 255.0
        module = ResNetCIFAR(depth=20, compute_dtype=jnp.bfloat16)
        metric = "cifar10_resnet20_train_images_per_sec_per_chip"
    else:
        (x_train, y_train), _ = datasets.mnist()
        x = (x_train.astype(np.float32) / 255.0)[..., None]
        module = MnistCNN(compute_dtype=jnp.bfloat16)
        metric = "mnist_train_images_per_sec_per_chip"
    y = y_train.astype(np.int64)

    trainer = hvt.Trainer(
        module,
        hvt.DistributedOptimizer(optax.adam(hvt.scale_lr(1e-3, n_chips))),
        loss="sparse_categorical_crossentropy",
    )

    global_batch = BATCH * n_chips
    rng = np.random.RandomState(0)
    n_prebatched = 64  # cycle through pre-sliced host batches
    batches = []
    for _ in range(n_prebatched):
        idx = rng.randint(0, len(x), size=global_batch)
        batches.append((x[idx], y[idx]))

    state = trainer.build(batches[0][0])
    state = hvt.broadcast_parameters(state, mesh=trainer.mesh)
    scale = np.float32(1.0)
    acc = {"loss": np.float32(0), "accuracy": np.float32(0)}

    for i in range(WARMUP_STEPS):
        state, metrics, acc = trainer._train_step(
            state, trainer._shard(batches[i % n_prebatched]), scale, acc
        )
    jax.block_until_ready(state)

    t0 = time.perf_counter()
    for i in range(MEASURE_STEPS):
        state, metrics, acc = trainer._train_step(
            state, trainer._shard(batches[i % n_prebatched]), scale, acc
        )
    jax.block_until_ready(state)
    elapsed = time.perf_counter() - t0

    images_per_sec_per_chip = MEASURE_STEPS * global_batch / elapsed / n_chips

    baseline_path = os.path.join(REPO, "benchmarks", "baseline_measured.json")
    vs_baseline = None
    if which == "mnist" and os.path.exists(baseline_path):
        with open(baseline_path) as f:
            baseline = json.load(f)
        vs_baseline = round(images_per_sec_per_chip / baseline["images_per_sec"], 2)

    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(images_per_sec_per_chip, 1),
                "unit": "images/sec/chip",
                "vs_baseline": vs_baseline,
            }
        )
    )


if __name__ == "__main__":
    main()
