"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric (BASELINE.json): MNIST training images/sec/chip through the
full distributed-training step — forward, loss, backward, gradient
allreduce-mean (the DistributedOptimizer path), optimizer apply — on the
reference's exact training config: the 2-conv CNN
(tensorflow2_keras_mnist.py:43-52), per-worker batch 128
(tensorflow2_keras_mnist.py:41), Adam (tensorflow2_keras_mnist.py:55).

``vs_baseline`` is the ratio against the measured reference-equivalent
TF2/Keras single-process run on this machine's CPU
(``benchmarks/baseline_measured.json``, produced by
``benchmarks/measure_reference_baseline.py`` — the reference publishes no
numbers of its own, SURVEY.md §6).

Every run also reports the denominator "match or beat" needs: FLOPs/step from
XLA's cost model on the compiled step, MFU against the chip's peak, and a
step-time breakdown (compute = device-resident batches; input = host slice +
transfer on top of it).

Modes (BENCH_MODEL):
  mnist       (default) reference CNN, per-chip batch 128 bf16
  resnet      CIFAR-10 ResNet-20 — heavier gradients (BASELINE.json config 4)
  transformer decoder LM (d512 x 8L, seq 1024, flash attention) — tokens/sec
  input       host input pipeline A/B: native C++ batch assembly vs Python

HVT_PROFILE=<dir> captures a jax.profiler trace of the measured loop.
"""

from __future__ import annotations

import json
import os
import time

BATCH = 128
WARMUP_STEPS = 20
MEASURE_STEPS = 400
REPO = os.path.dirname(os.path.abspath(__file__))


def _measure(fn, steps, sync):
    t0 = time.perf_counter()
    out = None
    for i in range(steps):
        out = fn(i)
    sync(out)
    return (time.perf_counter() - t0) / steps


def bench_train(which: str) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvt
    from horovod_tpu import trace
    from horovod_tpu.data import datasets

    hvt.init()
    n_chips = jax.device_count()

    if which == "resnet":
        from horovod_tpu.models.resnet import ResNetCIFAR

        (x_train, y_train), _ = datasets.cifar10()
        x = x_train.astype(np.float32) / 255.0
        y = y_train.astype(np.int64)
        module = ResNetCIFAR(depth=20, compute_dtype=jnp.bfloat16)
        metric = "cifar10_resnet20_train_images_per_sec_per_chip"
        per_chip_batch, unit_per_step = BATCH, BATCH * n_chips
        lr = optax.adam(hvt.scale_lr(1e-3))
        loss = "sparse_categorical_crossentropy"
        unit = "images/sec/chip"
    elif which == "transformer":
        from horovod_tpu.models.transformer import TransformerLM

        seq_len = int(os.environ.get("BENCH_SEQ_LEN", 1024))
        per_chip_batch = int(os.environ.get("BENCH_LM_BATCH", 8))
        x_np, y_np = datasets.copy_task(4096, seq_len, vocab_size=8192)
        x, y = x_np, y_np
        module = TransformerLM(
            vocab_size=8192, d_model=512, n_heads=8, n_layers=8,
            compute_dtype=jnp.bfloat16,
        )
        metric = "transformer_lm_train_tokens_per_sec_per_chip"
        # copy_task returns [n, seq_len] next-token pairs: every position is
        # a trained label.
        unit_per_step = per_chip_batch * n_chips * seq_len
        lr = optax.adamw(hvt.scale_lr(3e-4))
        loss = "sparse_categorical_crossentropy"
        unit = "tokens/sec/chip"
    else:
        from horovod_tpu.models.cnn import MnistCNN

        (x_train, y_train), _ = datasets.mnist()
        x = (x_train.astype(np.float32) / 255.0)[..., None]
        y = y_train.astype(np.int64)
        module = MnistCNN(compute_dtype=jnp.bfloat16)
        metric = "mnist_train_images_per_sec_per_chip"
        per_chip_batch, unit_per_step = BATCH, BATCH * n_chips
        lr = optax.adam(hvt.scale_lr(1e-3))
        loss = "sparse_categorical_crossentropy"
        unit = "images/sec/chip"

    trainer = hvt.Trainer(module, hvt.DistributedOptimizer(lr), loss=loss)

    global_batch = per_chip_batch * n_chips
    rng = np.random.RandomState(0)
    n_prebatched = 32  # cycle through pre-sliced host batches
    host_batches = []
    for _ in range(n_prebatched):
        idx = rng.randint(0, len(x), size=global_batch)
        host_batches.append((x[idx], y[idx]))

    state = trainer.build(host_batches[0][0])
    state = hvt.broadcast_parameters(state, mesh=trainer.mesh)
    scale = np.float32(1.0)
    zero_acc = {"loss": np.float32(0), "accuracy": np.float32(0)}

    # FLOPs of ONE compiled step (fwd + bwd + allreduce + optimizer), from
    # XLA's cost model — the MFU numerator. The AOT-compiled object is also
    # what the loops execute, so the step compiles exactly once.
    dev_batches = [trainer._shard(b) for b in host_batches]
    compiled_step = trainer._train_step.lower(
        state, dev_batches[0], scale, zero_acc
    ).compile()
    flops = trace.compiled_cost_flops(compiled_step)

    holder = {"state": state, "acc": zero_acc}

    def step_device(i):
        holder["state"], m, holder["acc"] = compiled_step(
            holder["state"], dev_batches[i % n_prebatched], scale, holder["acc"]
        )
        return m["loss"]

    def step_e2e(i):
        holder["state"], m, holder["acc"] = compiled_step(
            holder["state"], trainer._shard(host_batches[i % n_prebatched]),
            scale, holder["acc"],
        )
        return m["loss"]

    sync = jax.block_until_ready
    _measure(step_device, WARMUP_STEPS, sync)  # compile + warm
    with trace.maybe_trace(trace.profile_dir()):
        compute_s = _measure(step_device, MEASURE_STEPS, sync)
    e2e_s = _measure(step_e2e, MEASURE_STEPS, sync)

    per_sec_per_chip = unit_per_step / e2e_s / n_chips
    return {
        "metric": metric,
        "value": round(per_sec_per_chip, 1),
        "unit": unit,
        "flops_per_step": flops,
        "mfu": round(trace.mfu(flops, compute_s, n_chips), 4)
        if trace.mfu(flops, compute_s, n_chips) is not None
        else None,
        "step_ms": {
            "total": round(e2e_s * 1e3, 3),
            "compute": round(compute_s * 1e3, 3),
            "input": round((e2e_s - compute_s) * 1e3, 3),
        },
        "n_chips": n_chips,
    }


def bench_input() -> dict:
    """Host input-pipeline A/B: native C++ batch assembly vs pure Python.

    Times `training_pipeline` (shuffle + gather + stage) alone — the part the
    native engine (native/hvt_data.cc) owns; no device work."""
    import numpy as np

    from horovod_tpu.data import datasets, native_loader
    from horovod_tpu.data.loader import training_pipeline

    (x_train, y_train), _ = datasets.mnist()
    x = (x_train.astype(np.float32) / 255.0)[..., None]
    arrays = (x, y_train.astype(np.int64))
    steps = 400

    def run(no_native: bool) -> float:
        if no_native:
            os.environ["HVT_NO_NATIVE"] = "1"
        else:
            os.environ.pop("HVT_NO_NATIVE", None)
        it, close = training_pipeline(arrays, BATCH, seed=0)
        try:
            for _ in range(50):  # warm the producer
                next(it)
            t0 = time.perf_counter()
            for _ in range(steps):
                next(it)
            return steps * BATCH / (time.perf_counter() - t0)
        finally:
            close()

    python_ips = run(no_native=True)
    # Without the native engine (no toolchain to build it), the "native" leg
    # would silently rerun Python and publish "no speedup" — label it.
    native = native_loader.available()
    native_ips = run(no_native=False) if native else python_ips
    return {
        "metric": "input_pipeline_images_per_sec",
        "value": round(native_ips, 1),
        "unit": "images/sec",
        "native": native,
        "python_images_per_sec": round(python_ips, 1),
        "vs_baseline": round(native_ips / python_ips, 2) if native else None,
    }


def main() -> None:
    which = os.environ.get("BENCH_MODEL", "mnist")
    if which == "input":
        result = bench_input()
    else:
        result = bench_train(which)
        vs = None
        if which == "mnist":
            baseline_path = os.path.join(
                REPO, "benchmarks", "baseline_measured.json"
            )
            if os.path.exists(baseline_path):
                with open(baseline_path) as f:
                    vs = round(result["value"] / json.load(f)["images_per_sec"], 2)
        result["vs_baseline"] = vs
    print(json.dumps(result))


if __name__ == "__main__":
    main()
