"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric (BASELINE.json): MNIST training images/sec/chip through the
full distributed-training step — forward, loss, backward, gradient
allreduce-mean (the DistributedOptimizer path), optimizer apply — on the
reference's exact training config: the 2-conv CNN
(tensorflow2_keras_mnist.py:43-52), per-worker batch 128
(tensorflow2_keras_mnist.py:41), Adam (tensorflow2_keras_mnist.py:55).

``vs_baseline`` is the ratio against the measured reference-equivalent
TF2/Keras single-process run on this machine's CPU
(``benchmarks/baseline_measured.json``, produced by
``benchmarks/measure_reference_baseline.py`` — the reference publishes no
numbers of its own, SURVEY.md §6).

Every run also reports the denominator "match or beat" needs: FLOPs/step from
XLA's cost model on the compiled step, MFU against the chip's peak, and a
step-time breakdown (compute = device-resident batches; input = host slice +
transfer on top of it).

Modes (BENCH_MODEL):
  mnist       (default) reference CNN, per-chip batch 128 bf16
  resnet      CIFAR-10 ResNet-20 — heavier gradients (BASELINE.json config 4)
  vit         CIFAR-10 Vision Transformer (models/vit.py) — the conv-free
              vision family; images/sec + the MFU the conv shapes can't reach
  transformer decoder LM (d512 x 8L, seq 1024, flash attention) — tokens/sec
  moe         same LM with MoE MLPs every 2nd block (8 experts, top-2) —
              tokens/sec + router drop-rate observability
  seq2seq     encoder-decoder (models/seq2seq.py, d512 x 6enc+6dec, seq
              1024): bidirectional encoder + causal decoder + cross-
              attention (the flash kernel's Tk≠Tq grids) — tokens/sec
  accum       gradient-accumulation A/B on the LM config: K=1 vs
              K=BENCH_ACCUM_K (default 4) backward_passes_per_step —
              tokens/sec plus cross-worker reduction calls per OPTIMIZER
              step counted in the compiled step (the accumulating step
              must show exactly one bucketed boundary reduction)
  decode      autoregressive generation (KV-cache prefill + scan decode
              loop, models/decoding.py) — generated tokens/sec
  spec        speculative decoding A/B (models/speculative.py): trains a
              small LM on the copy task ON-CHIP, then measures plain
              greedy vs speculative (prompt-lookup draft) on copy prompts —
              exact-output speedup + acceptance rate
  input       host input pipeline A/B: native C++ batch assembly vs Python
  serve       serving-tier tail-latency A/B: continuous batching vs the
              legacy coalescing path through the real server
              (launch/serve.py), same open-loop arrival schedule both
              legs — TTFT/TPOT p50/p95/p99; exits 1 unless continuous
              wins p95 TTFT at equal offered load

HVT_PROFILE=<dir> captures a jax.profiler trace of the measured loop.
"""

from __future__ import annotations

import json
import os
import time

BATCH = 128
REPO = os.path.dirname(os.path.abspath(__file__))


def _fused_ce_chunks() -> int:
    """BENCH_FUSED_CE chunk count. Default ON (8 chunks): the fused
    chunked linear-CE head (ops/fused_ce.py) is the bench LM's default
    config — the [B, T, vocab] logits tensor never materializes. Export
    BENCH_FUSED_CE=0 to bench the dense head."""
    return int(os.environ.get("BENCH_FUSED_CE", 8))


def _lm_loss() -> str:
    """Trainer loss matching the fused-CE default: the module computes the
    loss when the fused head is on."""
    return "module" if _fused_ce_chunks() else "sparse_categorical_crossentropy"


def _wire_compression() -> str:
    """HVT_COMPRESSION for the train benches (none/bf16/fp16/int8/fp8 →
    DistributedOptimizer(compression=...))."""
    from horovod_tpu.analysis import registry

    return registry.get_str("HVT_COMPRESSION") or "none"


def _ici_compression() -> str:
    """HVT_COMPRESSION_ICI — the two-hop reduction's ICI-hop wire
    (DistributedOptimizer(compression_ici=...)); inert on single-slice
    meshes."""
    from horovod_tpu.analysis import registry

    return registry.get_str("HVT_COMPRESSION_ICI") or "none"


def _resolve_peak_flops() -> tuple:
    """(per-chip peak FLOP/s, source) for the MFU denominator — every
    BENCH_* row must carry a non-null MFU trend number.

    Resolution order (implemented in `trace.resolve_peak_flops`, which
    the live trainer MFU gauge shares so both surfaces divide by the
    same number): the explicit ``HVT_PEAK_FLOPS`` override (an
    unparseable value exits 2 in main()), the built-in TPU peak table,
    and finally a measured matmul calibration on THIS host (best-of-3
    chained f32 matmuls) — the honest trend denominator for device kinds
    with no published peak, e.g. the CPU CI topology. The calibrated
    value is exported back into ``HVT_PEAK_FLOPS`` so every leg of the
    run divides by the same number."""
    from horovod_tpu import trace

    return trace.resolve_peak_flops(calibrate=True)


def _lm_from_env(*, moe: bool = False):
    """The bench transformer, one source of truth for its env knobs — the
    decode rows must measure the same model the training rows do."""
    import jax.numpy as jnp

    from horovod_tpu import runtime
    from horovod_tpu.models.transformer import TransformerLM

    return TransformerLM(
        vocab_size=8192,
        d_model=int(os.environ.get("BENCH_DMODEL", 512)),
        n_heads=int(os.environ.get("BENCH_HEADS", 8)),
        # Grouped-query attention: 0/unset = MHA. Decode's KV-cache stream
        # shrinks by n_heads/n_kv_heads (the BENCH_MODEL=decode A/B knob).
        n_kv_heads=int(os.environ.get("BENCH_KV_HEADS", 0)) or None,
        n_layers=int(os.environ.get("BENCH_NLAYERS", 8)),
        # BENCH_WINDOW: sliding-window (local) attention — the flash kernel
        # block-skips tiles outside the band, so long-seq steps get
        # proportionally faster (and MFU accounts the executed band only).
        window=int(os.environ.get("BENCH_WINDOW", 0)) or None,
        # BENCH_SINKS (with BENCH_WINDOW): global+local attention — the
        # first S positions ride the kernel's pinned sink tile.
        attention_sinks=int(os.environ.get("BENCH_SINKS", 0)),
        # BENCH_SLIDING=1 (decode mode, needs BENCH_WINDOW): ring-buffer KV
        # cache — O(window) cache reads per generated token instead of
        # O(prompt+new_tokens), the decode-side win of a window.
        sliding_cache=runtime.env_flag("BENCH_SLIDING"),
        compute_dtype=jnp.bfloat16,
        dropout=0.0,  # LM-pretraining norm (and threefry dropout costs
        # ~12%/step — HVT_FAST_RNG=1 makes dropout free when wanted)
        # moe mode: expert-parallel MLP every 2nd block (models/moe.py).
        moe_every=2 if moe else 0,
        n_experts=int(os.environ.get("BENCH_EXPERTS", 8)),
        moe_k=int(os.environ.get("BENCH_MOE_K", 2)),
        capacity_factor=float(os.environ.get("BENCH_CAPACITY", 1.25)),
        # BENCH_MOE_ROUTER=expert_choice: drop-free expert-choice routing
        # (models/moe.py) — observability metric becomes uncovered-rate.
        moe_router=os.environ.get("BENCH_MOE_ROUTER", "top_k"),
        # Long-context memory knobs (BASELINE.md context-envelope rows):
        remat=runtime.env_flag("BENCH_REMAT"),
        logits_dtype=jnp.bfloat16
        if os.environ.get("BENCH_LOGITS", "") == "bf16"
        else jnp.float32,
        # BENCH_FUSED_CE=<n_chunks>: fused chunked linear-CE head
        # (ops/fused_ce.py) — the [B, T, vocab] logits + cotangent are never
        # materialized; the train rows switch to Trainer(loss='module').
        # DEFAULT ON (8 chunks) — export BENCH_FUSED_CE=0 for the dense head.
        fused_head_chunks=_fused_ce_chunks(),
    )


def _timed(fn):
    """Wall time of `fn` with HONEST completion: `fn` must return a device
    scalar, which is fetched to the host before the clock stops.

    On a networked/tunneled TPU runtime, `block_until_ready` on a chain of
    per-step dispatches can return before the device actually finished (the
    ready signal races the tunnel), inflating throughput by orders of
    magnitude — measured here: a dispatch-loop "peak" of 7,000+ TFLOP/s on a
    197 TFLOP/s chip. Fetching a value that data-depends on the whole chain
    cannot lie. Benchmarks therefore time ONE fused scan over many steps
    (plus this fetch), never a Python loop of step dispatches."""
    import jax

    t0 = time.perf_counter()
    out = fn()
    float(jax.device_get(out))
    return time.perf_counter() - t0


def bench_train(which: str) -> dict:
    # TPU hardware RNG by default (runtime.py HVT_FAST_RNG): threefry
    # dropout costs up to 40% of a small step. Export HVT_FAST_RNG="" to
    # bench the bit-reproducible default instead.
    os.environ.setdefault("HVT_FAST_RNG", "1")

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvt
    from horovod_tpu import runtime, trace
    from horovod_tpu.data import datasets

    hvt.init()
    n_chips = jax.device_count()

    if which == "resnet":
        from horovod_tpu.models.resnet import ResNetCIFAR

        (x_train, y_train), _ = datasets.cifar10()
        # Raw uint8 to the device; the model normalizes on-chip (4x less
        # host->device traffic than pre-normalized float32).
        x = x_train
        y = y_train.astype(np.int32)
        module = ResNetCIFAR(depth=20, compute_dtype=jnp.bfloat16)
        metric = "cifar10_resnet20_train_images_per_sec_per_chip"
        # Default 128 = the reference's per-worker batch (honest comparison
        # config); BENCH_BATCH=512 is the measured throughput sweet spot
        # (+38%, benchmarks/conv_profile.py sweep — BASELINE.md conv note).
        per_chip_batch = int(os.environ.get("BENCH_BATCH", BATCH))
        unit_per_step = per_chip_batch * n_chips
        lr = optax.adam(hvt.scale_lr(1e-3))
        loss = "sparse_categorical_crossentropy"
        unit = "images/sec/chip"
        default_steps = 256
    elif which == "vit":
        # The conv-free vision family (models/vit.py): image classification
        # as MXU-shaped matmuls — the TPU-first answer to the conv models'
        # shape-bound MFU ceiling (BASELINE.md conv attribution row).
        from horovod_tpu.models.vit import ViT

        (x_train, y_train), _ = datasets.cifar10()
        x = x_train
        y = y_train.astype(np.int32)
        module = ViT(
            patch_size=int(os.environ.get("BENCH_PATCH", 4)),
            d_model=int(os.environ.get("BENCH_DMODEL", 512)),
            n_heads=int(os.environ.get("BENCH_HEADS", 8)),
            n_layers=int(os.environ.get("BENCH_NLAYERS", 8)),
            dropout=0.0,
            compute_dtype=jnp.bfloat16,
        )
        metric = "cifar10_vit_train_images_per_sec_per_chip"
        per_chip_batch = int(os.environ.get("BENCH_BATCH", BATCH))
        unit_per_step = per_chip_batch * n_chips
        lr = optax.adam(hvt.scale_lr(1e-3))
        loss = "sparse_categorical_crossentropy"
        unit = "images/sec/chip"
        default_steps = 256
    elif which == "seq2seq":
        # Encoder-decoder family (models/seq2seq.py) on a translation-shaped
        # synthetic task (target = copy of the source, teacher-forced). The
        # harness feeds ONE [B, S+T] int array and a thin adapter splits it
        # into the model's {'src','tgt'} dict, so the flat-array bench legs
        # (chunk stacking, device-cached e2e) apply unchanged — the
        # dict-input feeding path itself is covered by tests/test_seq2seq.py.
        import flax.linen as nn

        from horovod_tpu.models.seq2seq import Seq2SeqTransformer

        seq_len = int(os.environ.get("BENCH_SEQ_LEN", 1024))
        per_chip_batch = int(os.environ.get("BENCH_LM_BATCH", 8))
        d_model = int(os.environ.get("BENCH_DMODEL", 512))
        enc_l = int(os.environ.get("BENCH_ENC_LAYERS", 6))
        dec_l = int(os.environ.get("BENCH_DEC_LAYERS", 6))
        heads = int(os.environ.get("BENCH_HEADS", 8))
        rng0 = np.random.RandomState(0)
        src = rng0.randint(3, 8192, size=(4096, seq_len)).astype(np.int32)
        tgt_in = np.concatenate(
            [np.ones((4096, 1), np.int32), src[:, :-1]], axis=1
        )
        inner = Seq2SeqTransformer(
            vocab_size=8192, d_model=d_model, n_heads=heads,
            n_enc_layers=enc_l, n_dec_layers=dec_l, dropout=0.0,
            compute_dtype=jnp.bfloat16, logits_dtype=jnp.bfloat16,
        )

        class _SeqPair(nn.Module):
            inner: Seq2SeqTransformer
            src_len: int

            @nn.compact
            def __call__(self, xy, train: bool = False):
                return self.inner(
                    {"src": xy[:, : self.src_len], "tgt": xy[:, self.src_len:]},
                    train=train,
                )

        module = _SeqPair(inner=inner, src_len=seq_len)
        x = np.concatenate([src, tgt_in], axis=1)
        y = src  # labels: reproduce the source token-for-token
        metric = "seq2seq_train_tokens_per_sec_per_chip"
        unit_per_step = per_chip_batch * n_chips * seq_len  # trained labels
        lr = optax.adamw(hvt.scale_lr(3e-4))
        loss = "sparse_categorical_crossentropy"
        unit = "tokens/sec/chip"
        default_steps = 32
    elif which in ("transformer", "moe"):
        seq_len = int(os.environ.get("BENCH_SEQ_LEN", 1024))
        per_chip_batch = int(os.environ.get("BENCH_LM_BATCH", 8))
        x_np, y_np = datasets.copy_task(4096, seq_len, vocab_size=8192)
        x, y = x_np, y_np
        module = _lm_from_env(moe=which == "moe")
        metric = (
            "moe_lm_train_tokens_per_sec_per_chip"
            if which == "moe"
            else "transformer_lm_train_tokens_per_sec_per_chip"
        )
        n_docs = int(os.environ.get("BENCH_PACK_DOCS", 0))
        if n_docs:
            # Packed-sequence pretraining: each row holds n_docs documents;
            # the flash kernel's segment masking (block-level early-out)
            # keeps cross-document tiles off the MXU. Fixed equal-length
            # packing so the Trainer's (x, y) feed needs no extra channel.
            import flax.linen as nn

            class _PackedLM(nn.Module):
                inner: TransformerLM
                docs: int

                @nn.compact
                def __call__(self, tokens, *, train: bool = False, labels=None):
                    b, t = tokens.shape
                    ids = jnp.repeat(
                        jnp.arange(self.docs, dtype=jnp.int32), t // self.docs
                    )
                    ids = jnp.broadcast_to(ids, (b, t))
                    return self.inner(
                        tokens, train=train, segment_ids=ids, labels=labels
                    )

            module = _PackedLM(inner=module, docs=n_docs)
            metric += "_packed"
        # copy_task returns [n, seq_len] next-token pairs: every position is
        # a trained label.
        unit_per_step = per_chip_batch * n_chips * seq_len
        lr = optax.adamw(hvt.scale_lr(3e-4))
        # Fused chunked-CE head (default on): the module computes the loss
        # (see _lm_from_env's fused_head_chunks knob).
        loss = _lm_loss()
        unit = "tokens/sec/chip"
        default_steps = 48
    else:
        from horovod_tpu.models.cnn import MnistCNN

        (x_train, y_train), _ = datasets.mnist()
        x = x_train[..., None]  # uint8; on-device normalize (see resnet note)
        y = y_train.astype(np.int32)
        module = MnistCNN(compute_dtype=jnp.bfloat16)
        metric = "mnist_train_images_per_sec_per_chip"
        per_chip_batch = int(os.environ.get("BENCH_BATCH", BATCH))
        unit_per_step = per_chip_batch * n_chips
        lr = optax.adam(hvt.scale_lr(1e-3))
        loss = "sparse_categorical_crossentropy"
        unit = "images/sec/chip"
        default_steps = 1024

    peak_flops, peak_src = _resolve_peak_flops()
    compression = _wire_compression()
    trainer = hvt.Trainer(
        module,
        hvt.DistributedOptimizer(
            lr, compression=compression,
            compression_ici=_ici_compression(),
        ),
        loss=loss,
    )

    n_steps = int(os.environ.get("BENCH_STEPS", default_steps))
    global_batch = per_chip_batch * n_chips
    rng = np.random.RandomState(0)

    def draw():
        idx = rng.randint(0, len(x), size=global_batch)
        return x[idx], y[idx]

    sample = draw()
    state = trainer.build(sample[0])
    state = hvt.broadcast_parameters(state, mesh=trainer.mesh)
    scale = np.float32(1.0)
    # Accumulator keys come from the trainer: models may sow extra metrics
    # (e.g. the MoE router drop-rate) that travel with loss/accuracy.
    zero_acc = {k: np.float32(0) for k in trainer.metric_names}

    # --- compute time: ONE fused scan over n_steps (see _timed's note on why
    # a Python loop of dispatches cannot be trusted on tunneled runtimes).
    # Chained BENCH_E2E_REPS times per fetch, exactly like the e2e leg
    # below: the two legs must amortize the tunnel's per-fetch RTT
    # identically, or the RTT difference masquerades as phase time (the
    # r04 `compute > total` accounting bug). ------------------------------
    reps = max(1, int(os.environ.get("BENCH_E2E_REPS", 4)))
    steps = [draw() for _ in range(n_steps)]
    mega = tuple(np.stack([s[i] for s in steps]) for i in range(2))
    dev_mega = trainer._shard_chunk(mega)
    compiled_mega = trainer._train_chunk.lower(
        state, dev_mega, scale, zero_acc
    ).compile()
    # warm (compile already done; first run settles the runtime)
    w_state, _, w_acc = compiled_mega(state, dev_mega, scale, zero_acc)
    float(jax.device_get(w_acc["loss"]))

    # The step donates its input state: always pass the PREVIOUS call's
    # returned state, never a saved one (its buffers are consumed).
    holder = {"state": w_state}

    def run_mega():
        for _ in range(reps):
            holder["state"], m, acc = compiled_mega(
                holder["state"], dev_mega, scale, zero_acc
            )
            holder["acc"] = acc  # last measured pass — extras read it
        return acc["loss"]

    with trace.maybe_trace(trace.profile_dir()):
        compute_s = _timed(run_mega) / (n_steps * reps)

    # --- comm time: the boundary reduction in isolation — the same
    # bucketed/hierarchical/compressed program the step runs (or, on the
    # implicit-SPMD path, its explicit equivalent over the same gradient
    # shapes), chained per fetch like the legs above. On one chip this
    # measures dispatch-amortized psum overhead (≈0); on a real mesh it is
    # the exposed wire time a perfectly-overlapped step would hide. -------
    comm_s = _timed_reduction(trainer, holder["state"].params, reps)

    # Module-sown metrics (e.g. moe_drop_rate), averaged over the MEASURED
    # pass — the steady state the throughput number describes, not warm-up.
    sums = {k: float(v) for k, v in jax.device_get(holder["acc"]).items()}
    extra_metrics = {
        k: round(sums[k] / n_steps, 4)
        for k in trainer.metric_names
        if k not in ("loss", "accuracy")
    }

    # FLOPs of one training step (fwd + bwd + allreduce + optimizer) from
    # XLA's cost model — scan bodies are counted once, so the single-step
    # compile gives the honest per-step count.
    flops = trace.compiled_flops(
        trainer._train_step, w_state, trainer._shard(sample), scale, zero_acc
    )
    if flops and which in ("transformer", "moe"):
        # The pallas flash kernel is a Mosaic custom call — opaque to XLA's
        # cost model, so its matmuls (counted from the kernel's own block
        # structure) are added per layer — but ONLY when the kernel path
        # actually runs: on shapes where `flash_attention` degrades to the
        # dense fallback, XLA's count already includes attention and adding
        # the analytic term would double-count it.
        from horovod_tpu.ops import flash_attention as fa_kernel

        heads = int(os.environ.get("BENCH_HEADS", 8))
        head_dim = int(os.environ.get("BENCH_DMODEL", 512)) // heads
        q_shape = (per_chip_batch * n_chips, seq_len, heads, head_dim)
        seg = bool(n_docs)
        blocks = fa_kernel.pick_blocks(
            seq_len, head_dim, jnp.bfloat16, segmented=seg
        )
        if fa_kernel.supported(
            q_shape, *blocks, dtype=jnp.bfloat16, segmented=seg
        ):
            window = int(os.environ.get("BENCH_WINDOW", 0)) or None
            n_layers = int(os.environ.get("BENCH_NLAYERS", 8))
            if n_docs:
                # Equal-length packed documents: executed score entries are
                # the band ∩ same-document area — each document is its own
                # length-L windowed causal attention (w = min(window, L);
                # no window = the causal triangle), summed over docs. A
                # plain min() of the two discounts would overstate it near
                # window ≈ L (the band crosses doc boundaries, where the
                # segment early-out skips tiles).
                L = seq_len // n_docs
                fa = trace.flash_attention_flops(
                    per_chip_batch * n_chips, L, L, heads, head_dim,
                    window=min(window or L, L),
                ) * n_layers * n_docs
            else:
                fa = trace.flash_attention_flops(
                    per_chip_batch * n_chips, seq_len, seq_len, heads,
                    head_dim, window=window,
                ) * n_layers
            flops += fa
        lm = module.inner if n_docs else module
        if lm.fused_head_chunks > 1:
            # The fused head's chunk scan is likewise undercounted by the
            # cost model (body counted once, executed n_chunks times).
            flops += trace.fused_ce_flops(
                per_chip_batch * n_chips * seq_len,
                lm.d_model, lm.vocab_size, lm.fused_head_chunks,
            )
    elif flops and which == "seq2seq":
        # Three flash calls per step: encoder self (non-causal, segmented),
        # decoder self (causal), cross (non-causal Tk≠Tq grids, segmented) —
        # all opaque to XLA's cost model (BASELINE.md footnote 1).
        from horovod_tpu.ops import flash_attention as fa_kernel

        head_dim = d_model // heads
        B = per_chip_batch * n_chips
        q_shape = (B, seq_len, heads, head_dim)
        fa = 0.0
        blocks_seg = fa_kernel.pick_blocks(
            seq_len, head_dim, jnp.bfloat16, segmented=True
        )
        if fa_kernel.supported(
            q_shape, *blocks_seg, dtype=jnp.bfloat16, segmented=True
        ):
            full = trace.flash_attention_flops(
                B, seq_len, seq_len, heads, head_dim, causal=False
            )
            fa += full * enc_l  # encoder self-attention
            fa += full * dec_l  # cross-attention (Tq == Tk here)
        blocks = fa_kernel.pick_blocks(seq_len, head_dim, jnp.bfloat16)
        if fa_kernel.supported(q_shape, *blocks, dtype=jnp.bfloat16):
            fa += trace.flash_attention_flops(
                B, seq_len, seq_len, heads, head_dim, causal=True
            ) * dec_l  # decoder self-attention
        flops += fa

    # --- end-to-end: training WITH its input pipeline — the device-resident
    # dataset path (`Trainer.fit(cache='device')`): dataset staged into HBM
    # once, then shuffle + gather + train run inside one compiled epoch.
    # e2e - compute = the on-device input pipeline's cost. -------------------
    data, per_shard = trainer._stage_device_dataset(x[: len(y)], y)
    epoch_steps = min(n_steps, per_shard // per_chip_batch)
    seed = jax.random.PRNGKey(7)
    compiled_epoch = trainer._train_epoch.lower(
        w_state, data, seed, scale, zero_acc, epoch_steps, per_chip_batch
    ).compile()

    # Several epochs chain per timed fetch: each epoch's DONATED state feeds
    # the next, so the final fetched loss data-depends on the whole chain
    # (the _timed honesty requirement holds), while the tunnel's per-fetch
    # round-trip — which would otherwise bill RTT/epoch_steps to every step
    # as fake "input" time — is amortized across all of them.
    e2e_reps = max(1, int(os.environ.get("BENCH_E2E_REPS", 4)))

    def run_e2e():
        for _ in range(e2e_reps):
            holder["state"], m, acc = compiled_epoch(
                holder["state"], data, seed, scale, zero_acc
            )
        return acc["loss"]

    # Warm WITH a fetch: un-fetched async work from the warm pass would still
    # be executing when the timed pass starts (same tunnel hazard as _timed).
    # ONE epoch suffices to settle the runtime — no need to burn e2e_reps.
    holder["state"], _, warm_acc = compiled_epoch(
        holder["state"], data, seed, scale, zero_acc
    )
    float(jax.device_get(warm_acc["loss"]))
    e2e_s = _timed(run_e2e) / (epoch_steps * e2e_reps)

    per_sec_per_chip = unit_per_step / e2e_s / n_chips
    # Per-phase breakdown, one consistent accounting: `total` is the
    # end-to-end step (training + on-device input pipeline, the number the
    # throughput headline divides by); `comm` is the isolated boundary
    # reduction; `compute` is the compute leg minus its comm share;
    # `input` is the remainder. Phases are clamped into [0, total] so they
    # sum to exactly `total` — and main() exits non-zero if any reported
    # phase still exceeds it (the r04 regression guard).
    total_s = e2e_s
    comm_clamped = min(comm_s, total_s)
    compute_clamped = min(
        max(compute_s - comm_s, 0.0), total_s - comm_clamped
    )
    input_s = max(0.0, total_s - comm_clamped - compute_clamped)
    # MFU is the HEADLINE: achieved FLOP/s through the full end-to-end
    # step against fleet peak — the "how idle are the chips" number the
    # throughput value can't show. mfu_compute excludes input time (the
    # old headline's denominator, kept for trend comparison).
    mfu_e2e = trace.mfu(flops, total_s, n_chips)
    mfu_compute = trace.mfu(flops, compute_s, n_chips)
    return {
        "mfu": round(mfu_e2e, 4) if mfu_e2e is not None else None,
        "metric": metric,
        "value": round(per_sec_per_chip, 1),
        "unit": unit,
        "flops_per_step": flops,
        "mfu_compute": (
            round(mfu_compute, 4) if mfu_compute is not None else None
        ),
        "step_ms": {
            "total": round(total_s * 1e3, 3),
            "compute": round(compute_clamped * 1e3, 3),
            "comm": round(comm_clamped * 1e3, 3),
            "input": round(input_s * 1e3, 3),
        },
        "overlap_reduction": trainer._overlap,
        "compression": compression,
        "peak_flops_per_chip": peak_flops,
        "peak_flops_source": peak_src,
        "n_chips": n_chips,
        **extra_metrics,
    }


def _reduction_program(trainer, params):
    """(jitted fn, gradient-shaped zeros, lowered text) of the boundary
    gradient reduction in isolation: the same
    `collectives.reduce_gradients` program the explicit step embeds
    (bucketing, order, dcn two-hop, wire dtype, ZeRO-1 scatter — all
    from the trainer)."""
    import jax
    import jax.numpy as jnp

    from horovod_tpu import compat
    from horovod_tpu.parallel import collectives
    from horovod_tpu.parallel import mesh as mesh_lib

    P = jax.sharding.PartitionSpec
    grads = jax.tree.map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params
    )
    scatter = getattr(trainer, "_scatter", 1)

    def red(g):
        out = collectives.reduce_gradients(
            g,
            data_axis=mesh_lib.DATA_AXIS,
            extra_axes=(mesh_lib.FSDP_AXIS,),
            dcn=trainer._dcn,
            wire_dtype=trainer._comm_dtype,
            ici_wire_dtype=getattr(trainer, "_ici_dtype", None),
            bucket_bytes=trainer._bucket_bytes,
            reverse=trainer._bucket_reverse,
            scatter=scatter if scatter > 1 else None,
        )
        # Scalar data-dependency on every reduced bucket (honest fetch).
        t = sum(
            jnp.sum(l.astype(jnp.float32)) for l in jax.tree.leaves(out)
        )
        if scatter > 1:
            # Scattered outputs differ per shard; one scalar psum makes
            # the fetch replicated (excluded from the byte accounting —
            # scalar ops never count as payload).
            t = jax.lax.psum(
                t, (mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS)
            )
        return t

    f = jax.jit(compat.shard_map(
        red, mesh=trainer.mesh, in_specs=(P(),), out_specs=P(),
        check_vma=False,
    ))
    return f, grads, f.lower(grads).as_text()


def _timed_reduction(trainer, params, reps: int) -> float:
    """Per-step wall time of the isolated boundary reduction
    (`_reduction_program`), chained ``reps`` times per honest fetch."""
    import jax
    import jax.numpy as jnp

    f, grads, _ = _reduction_program(trainer, params)
    float(jax.device_get(f(grads)))  # compile + settle

    def chain():
        t = jnp.float32(0)
        for _ in range(reps):
            t = t + f(grads)
        return t

    return _timed(chain) / reps


def _per_bucket_comm_ms(trainer, params, reps: int) -> list:
    """Per-BUCKET wall time + payload bytes of the isolated scatter
    reduction — the step_ms attribution that shows WHICH bucket's wire
    time the overlap has to hide. Only meaningful on the scatter layout
    (leaf-aligned buckets make a single bucket's reduction a
    self-contained program — DCE drops every other leaf); quantized DCN
    wires keep the dense bucket layout, so callers skip this there."""
    import jax
    import jax.numpy as jnp

    from horovod_tpu import compat
    from horovod_tpu.parallel import collectives
    from horovod_tpu.parallel import mesh as mesh_lib

    P = jax.sharding.PartitionSpec
    dp = trainer._scatter
    grads = jax.tree.map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params
    )
    buckets, _spec = collectives.flatten_scatter_buckets(
        grads, dp, trainer._bucket_bytes, reverse=trainer._bucket_reverse
    )
    sizes = [int(b.size) * 4 for b in buckets]
    out = []
    for bi in range(len(buckets)):
        def red(g, bi=bi):
            bs, _s = collectives.flatten_scatter_buckets(
                g, dp, trainer._bucket_bytes,
                reverse=trainer._bucket_reverse,
            )
            loc, _err = collectives._scatter_reduce_bucket(
                bs[bi], mesh_lib.DATA_AXIS, trainer._dcn,
                trainer._comm_dtype, (mesh_lib.FSDP_AXIS,),
                ici_wire_dtype=getattr(trainer, "_ici_dtype", None),
            )
            t = jnp.sum(loc.astype(jnp.float32))
            return jax.lax.psum(
                t, (mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS)
            )

        f = jax.jit(compat.shard_map(
            red, mesh=trainer.mesh, in_specs=(P(),), out_specs=P(),
            check_vma=False,
        ))
        float(jax.device_get(f(grads)))  # compile + settle

        def chain(f=f):
            t = jnp.float32(0)
            for _ in range(reps):
                t = t + f(grads)
            return t

        ms = _timed(chain) / reps * 1e3
        out.append({"bytes": sizes[bi], "ms": round(ms, 3)})
    return out


def _flops_guard(k: int, overlap: bool, flops_micro, cost_k) -> dict:
    """The MFU-denominator drift guard: ``flops_per_opt_step`` is
    derived as K x the K=1 (scan/peel-free) compile's count, so assert
    the K>1 program's OWN cost-model count matches the peel structure.
    The K-program statically counts each UNROLLED microbatch once plus
    the accumulation scan's body once: ``counted = 1 (first microbatch)
    + 1 (the peeled last microbatch, overlap on) + 1 (scan body, when a
    scan remains)``. If the peel silently changed program structure
    (stopped peeling, unrolled everything), cost_k leaves the
    [counted - 0.5, counted + 0.5] x flops_micro band and the bench
    exits non-zero."""
    peel = overlap and k > 1
    n_scan = k - 1 - (1 if peel else 0)
    counted = 1 + (1 if peel else 0) + (1 if n_scan > 0 else 0)
    if not flops_micro or not cost_k or k <= 1:
        return {"counted_microbatches": counted, "cost_flops": cost_k,
                "ok": True, "skipped": True}
    lo = (counted - 0.5) * flops_micro
    hi = (counted + 0.5) * flops_micro
    return {
        "counted_microbatches": counted,
        "cost_flops": cost_k,
        "band": [round(lo), round(hi)],
        "ok": bool(lo <= cost_k <= hi),
        "skipped": False,
    }


def _wire_bytes_per_step(text: str, world: int) -> float:
    """Structural per-device bytes-on-wire of one boundary reduction,
    from its LOWERED program text: every non-scalar collective's payload
    (`hlo_audit.op_bytes`) weighted by its ring transfer factor — an
    all-reduce moves ~2x its payload per device, reduce-scatter ~1x its
    (full, pre-scatter) input, all-gather/all-to-all ~1x the result —
    each x (world-1)/world. Scale gathers and the honest-fetch scalar
    psum are scalar/rank-1-of-world and cost their true (tiny) bytes."""
    from horovod_tpu.analysis import hlo_audit

    ring = (world - 1) / world if world > 1 else 0.0
    total = 0.0
    for op in hlo_audit.collective_ops(text):
        if op.scalar:
            continue
        payload = hlo_audit.op_bytes(op)
        if op.kind == "all-reduce":
            total += 2 * payload * ring
        elif op.kind == "reduce-scatter":
            # op payload is the RESULT (1/world of the input bucket).
            total += payload * world * ring
        else:  # all-gather / all-to-all / collective-permute
            total += payload * ring
    return total


def _reduction_calls(hlo: str) -> int:
    """Cross-worker GRADIENT reduction ops in a compiled step's HLO text.

    Since PR 9 this is `analysis.hlo_audit.gradient_reductions` — the
    ONE implementation of the payload-vs-scale-gather discrimination
    (non-scalar all-reduces plus rank >= 2 payload gathers; the
    quantized wire's rank-1 per-bucket scale gathers stay out), shared
    with the perf-path tests and the `hvt-audit` CLI."""
    from horovod_tpu.analysis import hlo_audit

    return len(hlo_audit.gradient_reductions(hlo))


def bench_accum() -> dict:
    """Gradient-accumulation A/B (Horovod's ``backward_passes_per_step``):
    K=1 vs K=BENCH_ACCUM_K (default 4) on the LM training config.

    Reports tokens/sec/chip for both runs and, the load-bearing number,
    cross-worker reduction calls per OPTIMIZER step from the compiled
    step's HLO: the K=1 step carries XLA's per-step gradient reduction,
    the accumulating step must show exactly the bucket count (one large
    fused reduction at default bucket bytes) regardless of K — gradient
    communication per sample divided by K. Same honesty rules as the
    training benches: one fused scan per timed fetch (_timed)."""
    os.environ.setdefault("HVT_FAST_RNG", "1")

    import jax
    import numpy as np
    import optax

    import horovod_tpu as hvt
    from horovod_tpu import trace
    from horovod_tpu.data import datasets

    hvt.init()
    n_chips = jax.device_count()
    K = max(2, int(os.environ.get("BENCH_ACCUM_K", 4)))
    seq_len = int(os.environ.get("BENCH_SEQ_LEN", 1024))
    per_chip_batch = int(os.environ.get("BENCH_LM_BATCH", 8))
    x, y = datasets.copy_task(4096, seq_len, vocab_size=8192)
    n_steps = int(os.environ.get("BENCH_STEPS", 16))  # optimizer steps
    global_batch = per_chip_batch * n_chips

    peak_flops, peak_src = _resolve_peak_flops()
    compression = _wire_compression()

    def measure(k: int) -> tuple:
        trainer = hvt.Trainer(
            _lm_from_env(),
            hvt.DistributedOptimizer(
                optax.adamw(hvt.scale_lr(3e-4)),
                backward_passes_per_step=k,
                # Mean over the K passes: the effective LR then matches
                # the K=1 leg, so the A/B compares communication, not
                # optimization trajectories.
                average_aggregated_gradients=True,
                compression=compression,
                compression_ici=_ici_compression(),
            ),
            loss=_lm_loss(),
        )
        rng = np.random.RandomState(0)

        def draw():
            idx = rng.randint(0, len(x), size=global_batch)
            return x[idx], y[idx]

        def step_batch():
            # One optimizer step's feed: [G, T] for k=1, a [k, G, T]
            # microbatch stack for the accumulating step.
            if k == 1:
                return draw()
            micro = [draw() for _ in range(k)]
            return tuple(np.stack([m[i] for m in micro]) for i in range(2))

        sample = draw()
        state = trainer.build(sample[0])
        state = hvt.broadcast_parameters(state, mesh=trainer.mesh)
        scale = np.float32(1.0)
        zero_acc = {m: np.float32(0) for m in trainer.metric_names}
        # Reduction count from the compiled SINGLE step (before the mega
        # run donates the state's buffers).
        one = step_batch()
        dev_one = (
            trainer._shard(one) if k == 1 else trainer._shard_chunk(one, 1)
        )
        compiled_one = trainer._train_step.lower(
            state, dev_one, scale, zero_acc
        ).compile()
        reductions = _reduction_calls(compiled_one.as_text())
        # Per-MICROBATCH flops from the single step's cost model (the scan
        # body is counted once, so the k=1 compile is the honest
        # per-microbatch count; the K leg's per-optimizer-step flops are
        # K x this, compute dominating the shared reduction/update tail).
        flops_micro = (
            trace.compiled_cost_flops(compiled_one) if k == 1 else None
        )
        # Timed leg: ONE fused scan over n_steps optimizer steps.
        steps = [step_batch() for _ in range(n_steps)]
        mega = tuple(np.stack([s[i] for s in steps]) for i in range(2))
        dev_mega = trainer._shard_chunk(mega, 2 if k > 1 else 1)
        compiled = trainer._train_chunk.lower(
            state, dev_mega, scale, zero_acc
        ).compile()
        w_state, _, w_acc = compiled(state, dev_mega, scale, zero_acc)
        float(jax.device_get(w_acc["loss"]))
        holder = {"state": w_state}

        def run():
            holder["state"], _, acc = compiled(
                holder["state"], dev_mega, scale, zero_acc
            )
            return acc["loss"]

        sec_per_opt_step = _timed(run) / n_steps
        tokens_per_opt_step = k * global_batch * seq_len
        return (
            tokens_per_opt_step / sec_per_opt_step / n_chips,
            reductions, sec_per_opt_step, flops_micro, trainer,
        )

    tok_k1, red_k1, sec_k1, flops_micro, _ = measure(1)
    tok_kn, red_kn, sec_kn, _, trainer_k = measure(K)
    # Per-optimizer-step flops of the K leg ~= K x the per-microbatch
    # count (see measure); MFU headline-first like the train benches.
    flops_k = flops_micro * K if flops_micro else None
    mfu_k = trace.mfu(flops_k, sec_kn, n_chips) if flops_k else None
    mfu_k1 = trace.mfu(flops_micro, sec_k1, n_chips) if flops_micro else None
    return {
        "mfu": round(mfu_k, 4) if mfu_k is not None else None,
        "metric": "accum_train_tokens_per_sec_per_chip",
        "value": round(tok_kn, 1),
        "unit": "tokens/sec/chip",
        "k": K,
        "k1_tokens_per_sec_per_chip": round(tok_k1, 1),
        "speedup": round(tok_kn / tok_k1, 2),
        "mfu_k1": round(mfu_k1, 4) if mfu_k1 is not None else None,
        "flops_per_opt_step": flops_k,
        # K=1: XLA's implicit reduction, per microbatch == per step.
        # K=N: the single bucketed boundary reduction — per-sample
        # gradient communication divided by N.
        "reduction_calls_per_opt_step": {"k1": red_k1, f"k{K}": red_kn},
        "overlap_reduction": trainer_k._overlap,
        "compression": compression,
        "peak_flops_per_chip": peak_flops,
        "peak_flops_source": peak_src,
        "per_chip_batch": per_chip_batch,
        "seq_len": seq_len,
        "n_chips": n_chips,
    }


def _sampler_overhead(hvt, module, x, y, K, compression, compression_ici,
                      bucket_bytes, global_batch):
    """A/B the live `StepPhaseSampler` (ISSUE 13): its steady-state cost
    must be <= BENCH_SAMPLER_MAX_OVERHEAD_PCT (default 2%) of
    ``step_ms.total`` on the composed zero1 step, at the sampler's real
    cadence (``HVT_METRICS_EVERY``). Two measured components:

    * the per-window drain/publish cost, measured as a wall-clock A/B:
      both legs run the SAME python per-step dispatch loop (one
      sampling window each, so paired legs are temporally adjacent),
      alternating which leg goes first, gated on the MEDIAN of
      per-pair relative differences — differencing two multi-second
      wall-clock quantities to sub-percent precision is drift-limited
      on a shared CPU host, and the median of adjacent-pair ratios is
      the estimator that survives it (min-of-legs compares bests from
      minutes apart and measured the drift, not the sampler);
    * the periodic isolated-reduction re-time (every ``comm_refresh``
      samples — short legs rarely land on a refresh, and min-of-pairs
      would systematically select a refresh-free leg), added
      ANALYTICALLY from the sampler's own measured ``_comm_s`` amortized
      over its true cadence: ``comm_s / (comm_refresh x every)`` per
      step. The sum bounds the steady-state per-step overhead.

    Returns (every, overhead_pct, gate_ok). The sampler's one-time
    warmups (reduction-program compile, step cost analysis, peak
    calibration) run before any timed leg — setup cost, not per-step
    overhead."""
    import jax
    import numpy as np
    import optax

    from horovod_tpu.analysis import registry
    from horovod_tpu.training.trainer import StepPhaseSampler

    every = registry.get_int("HVT_METRICS_EVERY") or 32
    max_pct = float(os.environ.get("BENCH_SAMPLER_MAX_OVERHEAD_PCT", 2.0))
    trainer = hvt.Trainer(
        module,
        hvt.DistributedOptimizer(
            optax.adam(hvt.scale_lr(1e-3)),
            backward_passes_per_step=K,
            average_aggregated_gradients=True,
            compression=compression,
            compression_ici=compression_ici,
        ),
        loss="sparse_categorical_crossentropy",
        shard_update=True,
        bucket_bytes=bucket_bytes,
    )
    rng = np.random.RandomState(7)

    def step_batch():
        micro = [
            (lambda idx: (x[idx], y[idx]))(
                rng.randint(0, len(x), size=global_batch)
            )
            for _ in range(K)
        ]
        return tuple(np.stack([m[i] for m in micro]) for i in range(2))

    state = trainer.build(x[: trainer.dp_size])
    scale = np.float32(1.0)
    zero_acc = {m: np.float32(0) for m in trainer.metric_names}
    dev = trainer._shard_chunk(step_batch(), 1)
    step = trainer._train_step  # non-donating: dev is reused across steps
    state, _, _ = step(state, dev, scale, zero_acc)
    jax.block_until_ready(state)
    sampler = StepPhaseSampler(trainer, global_batch * K, every=every)
    sampler.capture_step_args(step, (state, dev, scale, zero_acc), 1)
    # Two forced samples: the first opens the window and pays every
    # one-time warmup, the second exercises the full sample path once.
    sampler.maybe_sample(state, every)
    sampler.maybe_sample(state, every)
    def leg(with_sampler: bool, n: int) -> float:
        nonlocal state
        jax.block_until_ready(state)
        t0 = time.perf_counter()
        for _ in range(n):
            state, _, _ = step(state, dev, scale, zero_acc)
            if with_sampler:
                sampler.maybe_sample(state, 1)
        jax.block_until_ready(state)
        return time.perf_counter() - t0

    # Legs are WHOLE sampling windows (each ON window carries exactly
    # one drain/publish edge), sized to >= ~4 s of wall clock: the
    # ON/OFF ratio is window-count invariant, and relative timing noise
    # on a shared CPU host only comes down with leg length.
    window_s = leg(False, every)  # settle + window-duration probe
    m = max(1, int(4.0 / max(window_s, 1e-9)) if window_s < 4.0 else 1)
    n = m * every
    leg(True, n)  # settle the sampler path at the final leg length
    pairs_min = max(3, int(os.environ.get("BENCH_SAMPLER_PAIRS", 5)))
    pairs_cap = max(pairs_min, int(os.environ.get(
        "BENCH_SAMPLER_MAX_PAIRS", 9
    )))
    # Paired-leg discipline (alternating order, median of per-pair
    # diffs, MAD-adaptive stop) — extracted to horovod_tpu.tune.probe
    # in PR 19 so the autotuner races candidate configs with the exact
    # machinery this gate was trusted with. A 2% gate needs
    # sub-percent resolution, hence the 0.75% MAD stop.
    from horovod_tpu.tune import probe as tune_probe

    res = tune_probe.paired_compare(
        lambda: leg(False, n), lambda: leg(True, n),
        pairs_min=pairs_min, pairs_cap=pairs_cap, mad_stop_pct=0.75,
    )
    drain_pct = res.median_pct
    # Amortized comm re-time (see docstring): one isolated reduction
    # every comm_refresh x every steps, against the OFF leg's step time.
    sec_per_step = min(res.a_times) / n
    comm_pct = (
        sampler._comm_s / (sampler.comm_refresh * every * sec_per_step)
        * 100.0
    )
    overhead_pct = drain_pct + comm_pct
    return every, round(overhead_pct, 3), overhead_pct <= max_pct


def bench_zero1() -> dict:
    """ZeRO-1 composition A/B (``shard_update`` on/off x K x overlap):
    the sharded weight update composed with accumulation (and, via
    HVT_COMPRESSION / HVT_COMPRESSION_ICI, the quantized wires) against
    the replicated update at the same K, AND against its own serialized
    (overlap-off) form.

    The wall-clock headline (ISSUE 12 — cash in the scatter): the
    overlapped composed leg must beat the serialized composed leg on
    ``step_ms.total`` at the same K — per-bucket backward-overlapped
    scatter issue + fused shard update made wall-clock-visible, not just
    an HLO assertion — and main() exits non-zero on a miss
    (``overlap_gate_ok``). ``overlap_fraction`` reports how much of the
    isolated comm time the overlap hid: (serialized total − overlapped
    total) / isolated comm, clamped to [0, 1]. ``step_ms.comm_buckets``
    attributes the isolated comm per BUCKET (leaf-aligned buckets are
    independently executable programs).

    The byte gate is unchanged from PR 10: structural bytes-on-wire per
    optimizer step of the isolated reduction, scattered strictly below
    replicated at the same K (byte-EQUAL for quantized DCN wires, whose
    dense layout is deliberate). The MFU denominator is guarded
    (`_flops_guard`): flops_per_opt_step = K x the K=1 peel-free
    compile's count, asserted against the K-program's own cost-model
    count so a silent peel-structure change can't drift the headline.
    Every row carries a non-null MFU (`_resolve_peak_flops`)."""
    os.environ.setdefault("HVT_FAST_RNG", "1")
    # A meaningful data-parallel degree on CPU drivers (inert on real
    # accelerators, where the platform is not cpu).
    os.environ.setdefault("HVT_NUM_CPU_DEVICES", "8")

    import flax.linen as nn
    import jax
    import numpy as np
    import optax

    import horovod_tpu as hvt
    from horovod_tpu import trace

    hvt.init()
    n_chips = jax.device_count()
    K = max(2, int(os.environ.get("BENCH_ACCUM_K", 4)))
    per_chip_batch = int(os.environ.get("BENCH_ZERO1_BATCH", 32))
    # hidden=2048 (~25 MB of f32 gradients): comm-heavy enough that the
    # per-bucket overlapped schedule is wall-clock-visible, the config
    # the ISSUE 12 headline runs at. BENCH_ZERO1_HIDDEN=1024 restores
    # the PR 10 shape for trend comparison.
    hidden = int(os.environ.get("BENCH_ZERO1_HIDDEN", 2048))
    # Bucket cap sized so the gradient tree cuts into SEVERAL leaf-
    # aligned buckets — one monolithic bucket has nothing to issue
    # bucket-by-bucket (the per-bucket schedule degenerates and the
    # peel only costs); ~4 MB gives the probe ~7 buckets.
    # BENCH_ZERO1_BUCKET_BYTES pins the probe shape; otherwise a
    # tuner-set HVT_BUCKET_BYTES (hvt-tune writes it into the resolved
    # env) reaches the bench the same way it reaches a real job.
    from horovod_tpu.analysis import registry as _registry

    bucket_bytes = int(
        os.environ.get("BENCH_ZERO1_BUCKET_BYTES", "")
        or _registry.get_int("HVT_BUCKET_BYTES")
        or (4 << 20)
    )
    n_steps = int(os.environ.get("BENCH_STEPS", 8))
    global_batch = per_chip_batch * n_chips
    peak_flops, peak_src = _resolve_peak_flops()
    compression = _wire_compression()
    compression_ici = _ici_compression()

    class Mlp(nn.Module):
        # Dims divisible by any plausible chip count, so every kernel
        # (and its Adam mirrors) shards under the zero1 rule.
        @nn.compact
        def __call__(self, x, *, train: bool = False):
            import jax.numpy as jnp

            x = x.astype(jnp.float32)
            x = nn.relu(nn.Dense(hidden)(x))
            x = nn.relu(nn.Dense(hidden)(x))
            return nn.Dense(16)(x)

    rng = np.random.RandomState(0)
    x = rng.rand(4096, 512).astype(np.float32)
    y = rng.randint(0, 16, 4096).astype(np.int32)

    def fleet_state_bytes(tree):
        total = 0
        for l in jax.tree.leaves(tree):
            if isinstance(l, jax.Array):
                total += sum(
                    int(np.prod(s.data.shape)) * l.dtype.itemsize
                    for s in l.addressable_shards
                )
        return total

    def measure(k: int, zero1: bool, overlap=None,
                buckets: bool = False, defer_timing: bool = False,
                cfg: dict | None = None) -> dict:
        # cfg overrides the ambient tunable values for ONE leg — how the
        # BENCH_TUNE_AB race builds its registry-default opponent.
        cfg = cfg or {}
        leg_bucket_bytes = int(cfg.get("bucket_bytes", bucket_bytes))
        leg_compression = cfg.get("compression", compression)
        leg_compression_ici = cfg.get("compression_ici", compression_ici)
        trainer = hvt.Trainer(
            Mlp(),
            hvt.DistributedOptimizer(
                optax.adam(hvt.scale_lr(1e-3)),
                backward_passes_per_step=k,
                average_aggregated_gradients=True,
                compression=leg_compression,
                compression_ici=leg_compression_ici,
            ),
            loss="sparse_categorical_crossentropy",
            shard_update=zero1,
            overlap_reduction=overlap,
            bucket_bytes=leg_bucket_bytes,
        )

        def draw():
            idx = rng.randint(0, len(x), size=global_batch)
            return x[idx], y[idx]

        def step_batch():
            if k == 1:
                return draw()
            micro = [draw() for _ in range(k)]
            return tuple(
                np.stack([m[i] for m in micro]) for i in range(2)
            )

        state = trainer.build(x[: trainer.dp_size])
        scale = np.float32(1.0)
        zero_acc = {m: np.float32(0) for m in trainer.metric_names}
        one = step_batch()
        dev_one = (
            trainer._shard(one) if k == 1 else trainer._shard_chunk(one, 1)
        )
        compiled_one = trainer._train_step.lower(
            state, dev_one, scale, zero_acc
        ).compile()
        cost_flops = trace.compiled_cost_flops(compiled_one)
        # Per-microbatch flops from the k=1 compile ONLY (bench_accum's
        # rule): the K-leg's program holds the accumulation scan (cost
        # model counts the body once) PLUS the overlap-peeled last
        # microbatch — taking its count x K would double-report. The
        # K-leg count still rides the `_flops_guard` drift check.
        flops_micro = cost_flops if k == 1 else None
        # Structural wire bytes of the isolated boundary reduction (the
        # explicit path exists whenever k > 1 or a wire is set; the k=1
        # uncompressed control reduces implicitly — same program shape
        # as the explicit flat psum, counted identically).
        _, _, red_text = _reduction_program(trainer, state.params)
        wire = _wire_bytes_per_step(red_text, trainer.dp_size)
        # Timed leg: one fused scan over n_steps optimizer steps,
        # best-of-3 (the overlap gate is a wall-clock strict compare —
        # take the floor of the noise, not its mean).
        steps = [step_batch() for _ in range(n_steps)]
        mega = tuple(np.stack([s[i] for s in steps]) for i in range(2))
        dev_mega = trainer._shard_chunk(mega, 2 if k > 1 else 1)
        compiled = trainer._train_chunk.lower(
            state, dev_mega, scale, zero_acc
        ).compile()
        w_state, _, w_acc = compiled(state, dev_mega, scale, zero_acc)
        float(jax.device_get(w_acc["loss"]))
        holder = {"state": w_state}

        def run():
            holder["state"], _, acc = compiled(
                holder["state"], dev_mega, scale, zero_acc
            )
            return acc["loss"]

        if defer_timing:
            # The overlap A/B times its two legs INTERLEAVED (paired
            # executions, best-of): a strict wall-clock compare between
            # runs minutes apart would measure machine drift, not the
            # schedule.
            sec_per_opt_step = None
        else:
            sec_per_opt_step = min(
                _timed(run) for _ in range(3)
            ) / n_steps
        comm_s = _timed_reduction(
            trainer, state.params, max(4, n_steps)
        )
        quantized_wire = leg_compression.lower() in ("int8", "fp8")
        comm_buckets = (
            _per_bucket_comm_ms(
                trainer, state.params, max(4, n_steps)
            )
            if buckets and zero1 and not quantized_wire else None
        )
        return {
            "examples_per_sec_per_chip": (
                k * global_batch / sec_per_opt_step / n_chips
                if sec_per_opt_step else None
            ),
            "sec_per_opt_step": sec_per_opt_step,
            "comm_s": comm_s,
            "comm_buckets": comm_buckets,
            "flops_micro": flops_micro,
            "cost_flops": cost_flops,
            "overlap": trainer._overlap,
            "run_once": run if defer_timing else None,
            "wire_bytes_per_opt_step": wire,
            "opt_state_fleet_bytes": fleet_state_bytes(
                holder["state"].opt_state
            ),
        }

    legs = {
        (1, False): measure(1, False),
        (1, True): measure(1, True),
        (K, False): measure(K, False),
        (K, True): measure(K, True, overlap=True, buckets=True,
                           defer_timing=True),
    }
    serialized = measure(K, True, overlap=False, defer_timing=True)
    lead = legs[(K, True)]
    # Paired interleaved timing of the overlap A/B: alternate the two
    # compiled programs and take each leg's best — drift (thermal, cache,
    # co-tenant load) hits both legs equally.
    pairs = max(3, int(os.environ.get("BENCH_OVERLAP_PAIRS", 5)))
    t_on, t_off = [], []
    for fn in (lead["run_once"], serialized["run_once"]):
        _timed(fn)  # settle both before the paired pass
    for _ in range(pairs):
        t_on.append(_timed(lead["run_once"]))
        t_off.append(_timed(serialized["run_once"]))
    for leg, times in ((lead, t_on), (serialized, t_off)):
        leg["sec_per_opt_step"] = min(times) / n_steps
        leg["examples_per_sec_per_chip"] = (
            K * global_batch / leg["sec_per_opt_step"] / n_chips
        )
    # BENCH_TUNE_AB=1 — the hvt-tune acceptance race (ISSUE 19): the
    # config in the CURRENT env (what the tuner selected) against the
    # registry-default config at the same K/model, decided by the
    # paired-leg discipline. main() exits non-zero when the tuned
    # config does not win.
    tuned_vs_default = None
    if os.environ.get("BENCH_TUNE_AB", "").lower() not in (
            "", "0", "false", "no"):
        from horovod_tpu.tune import probe as tune_probe
        from horovod_tpu.tune import space as tune_space

        tuned_cfg = {
            "HVT_BUCKET_BYTES": bucket_bytes,
            "HVT_BACKWARD_PASSES": K,
            "HVT_COMPRESSION": compression,
            "HVT_COMPRESSION_ICI": compression_ici,
            "HVT_OVERLAP_REDUCTION": _registry.get_flag(
                "HVT_OVERLAP_REDUCTION"),
        }
        default_cfg = dict(tune_space.default_config())
        default_cfg["HVT_BACKWARD_PASSES"] = K  # same model: K pinned
        # The tuned leg already exists: the lead (overlap-on) or the
        # serialized compile, whichever the env picked.
        tuned_leg = (lead if tuned_cfg["HVT_OVERLAP_REDUCTION"]
                     else serialized)
        default_leg = measure(
            K, True, overlap=default_cfg["HVT_OVERLAP_REDUCTION"],
            defer_timing=True,
            cfg={"bucket_bytes": default_cfg["HVT_BUCKET_BYTES"],
                 "compression": default_cfg["HVT_COMPRESSION"],
                 "compression_ici": default_cfg["HVT_COMPRESSION_ICI"]},
        )

        def _honest(leg):
            # Data-dependent fetch: the clock can't stop before the
            # device finished (see _timed's docstring).
            return lambda: float(jax.device_get(leg["run_once"]()))

        _honest(default_leg)()  # settle the fresh leg before pairing
        ab = tune_probe.paired_compare(
            _honest(tuned_leg), _honest(default_leg),
            pairs_min=max(3, int(os.environ.get("BENCH_TUNE_PAIRS", 5))),
            pairs_cap=max(3, int(os.environ.get(
                "BENCH_TUNE_MAX_PAIRS", 9))),
        )
        identical = tuned_cfg == default_cfg
        tuned_vs_default = {
            "tuned_config": tuned_cfg,
            "default_config": default_cfg,
            # median of per-pair (default - tuned) / tuned: positive
            # means the registry-default config is SLOWER.
            "median_pct": round(ab.median_pct, 3),
            "mad_pct": round(ab.mad_pct, 3),
            "pairs": ab.pairs,
            "converged": ab.converged,
            "default_step_ms_total": round(
                tune_probe.median(ab.b_times) / n_steps * 1e3, 3),
            # A race of a config against itself can't gate anything.
            "gate_ok": None if identical else ab.median_pct > 0.0,
        }
    for leg in (lead, serialized, legs[(1, False)], legs[(1, True)],
                legs[(K, False)]):
        leg["comm_s"] = min(leg["comm_s"], leg["sec_per_opt_step"])
        leg.pop("run_once", None)
    # Per-optimizer-step flops of the K leg = K x the k=1 zero1 compile's
    # per-microbatch count (the scan/peel-free program) — guarded below.
    flops_micro = legs[(1, True)]["flops_micro"]
    flops_per_opt_step = flops_micro * K if flops_micro else None
    flops_guard = _flops_guard(
        K, lead["overlap"], flops_micro, lead["cost_flops"]
    )
    mfu = (
        trace.mfu(flops_per_opt_step, lead["sec_per_opt_step"], n_chips)
        if flops_per_opt_step else None
    )
    total_ms = lead["sec_per_opt_step"] * 1e3
    comm_ms = lead["comm_s"] * 1e3
    step_ms = {
        "total": round(total_ms, 3),
        "compute": round(max(0.0, total_ms - comm_ms), 3),
        "comm": round(comm_ms, 3),
        "input": 0.0,
        # Per-bucket attribution of the isolated comm (scatter layout
        # only) — not a phase (non-numeric), outside the overrun guard.
        "comm_buckets": lead["comm_buckets"],
    }
    serialized_total_ms = round(serialized["sec_per_opt_step"] * 1e3, 3)
    # THE wall-clock gate (ISSUE 12): the overlapped SCATTER path beats
    # its own serialized form at the same K. overlap_fraction = how much
    # of the isolated comm the overlap hid. Quantized DCN wires keep the
    # dense bucket layout by design — there is no per-bucket scatter
    # schedule to gate there — so the compare is reported but
    # informational (overlap_gate_ok: null, no exit).
    hidden_s = serialized["sec_per_opt_step"] - lead["sec_per_opt_step"]
    overlap_fraction = (
        max(0.0, min(1.0, hidden_s / lead["comm_s"]))
        if lead["comm_s"] > 0 else 0.0
    )
    quantized = compression.lower() in ("int8", "fp8")
    overlap_gate_ok = (
        lead["sec_per_opt_step"] < serialized["sec_per_opt_step"]
        if not quantized else None
    )
    wire = {
        "replicated": {
            "k1": round(legs[(1, False)]["wire_bytes_per_opt_step"]),
            f"k{K}": round(legs[(K, False)]["wire_bytes_per_opt_step"]),
        },
        "zero1": {
            "k1": round(legs[(1, True)]["wire_bytes_per_opt_step"]),
            f"k{K}": round(legs[(K, True)]["wire_bytes_per_opt_step"]),
        },
    }
    # The PR 10 byte gate: at the same K, the scattered reduction moves
    # strictly fewer bytes than the replicated one. QUANTIZED DCN wires
    # are the deliberate exception — they keep the dense bucket layout
    # (bitwise-identical numerics to the replicated reduction, see
    # collectives._reduce_gradients_scatter) so the two programs are
    # byte-identical; the gate there is equality, never MORE.
    strictly_fewer = (
        wire["zero1"][f"k{K}"] < wire["replicated"][f"k{K}"]
        and wire["zero1"]["k1"] < wire["replicated"]["k1"]
    )
    not_more = (
        wire["zero1"][f"k{K}"] <= wire["replicated"][f"k{K}"]
        and wire["zero1"]["k1"] <= wire["replicated"]["k1"]
    )
    wire_ok = not_more if quantized else strictly_fewer
    sampler_every, sampler_overhead_pct, sampler_gate_ok = (
        _sampler_overhead(
            hvt, Mlp(), x, y, K, compression, compression_ici,
            bucket_bytes, global_batch,
        )
    )
    return {
        "mfu": round(mfu, 4) if mfu is not None else None,
        "metric": "zero1_train_examples_per_sec_per_chip",
        "value": round(lead["examples_per_sec_per_chip"], 1),
        "unit": "examples/sec/chip",
        "k": K,
        "step_ms": step_ms,
        "overlap_fraction": round(overlap_fraction, 4),
        "overlap_gate_ok": overlap_gate_ok,
        "serialized_step_ms_total": serialized_total_ms,
        "serialized_examples_per_sec_per_chip": round(
            serialized["examples_per_sec_per_chip"], 1
        ),
        "wire_bytes_per_opt_step": wire,
        "wire_strictly_fewer": strictly_fewer,
        "wire_gate_ok": wire_ok,
        "replicated_examples_per_sec_per_chip": round(
            legs[(K, False)]["examples_per_sec_per_chip"], 1
        ),
        "opt_state_fleet_bytes": {
            "replicated": legs[(K, False)]["opt_state_fleet_bytes"],
            "zero1": legs[(K, True)]["opt_state_fleet_bytes"],
        },
        "flops_per_opt_step": flops_per_opt_step,
        "flops_guard": flops_guard,
        "sampler_every": sampler_every,
        "sampler_overhead_pct": sampler_overhead_pct,
        "sampler_gate_ok": sampler_gate_ok,
        "compression": compression,
        "compression_ici": compression_ici,
        "peak_flops_per_chip": peak_flops,
        "peak_flops_source": peak_src,
        "per_chip_batch": per_chip_batch,
        "hidden": hidden,
        "bucket_bytes": bucket_bytes,
        "n_chips": n_chips,
        # Self-describing tuner input (ISSUE 19): the fully-resolved
        # tunable-knob values the HEADLINE leg (overlapped zero1) ran
        # under — hvt-tune reads this instead of re-inferring.
        "config": {
            "HVT_BUCKET_BYTES": bucket_bytes,
            "HVT_BACKWARD_PASSES": K,
            "HVT_COMPRESSION": compression,
            "HVT_COMPRESSION_ICI": compression_ici,
            "HVT_OVERLAP_REDUCTION": True,
        },
        "tuned_vs_default": tuned_vs_default,
    }


def bench_decode() -> dict:
    """Autoregressive generation: tokens/sec through ONE compiled program
    (prompt prefill + the whole `lax.scan` decode loop — a per-token host
    dispatch would be pure tunnel round-trip at this op size).

    Decode is bandwidth-bound (every generated token streams all params +
    the KV cache through the MXU as matvecs), so the companion number is
    the model-bandwidth utilisation implied by params x tokens/sec."""
    os.environ.setdefault("HVT_FAST_RNG", "1")

    import jax
    import jax.numpy as jnp
    import numpy as np

    import horovod_tpu as hvt
    from horovod_tpu.models.decoding import make_generate_fn

    hvt.init()
    n_chips = jax.device_count()
    batch = int(os.environ.get("BENCH_DECODE_BATCH", 8))
    prompt_len = int(os.environ.get("BENCH_PROMPT_LEN", 128))
    new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", 512))
    model = _lm_from_env()
    rng = np.random.RandomState(0)
    prompt = jnp.asarray(
        rng.randint(0, 8192, size=(batch, prompt_len)), jnp.int32
    )
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    # BENCH_WEIGHTS=int8: weight-only quantized decode (models/quant.py) —
    # the bandwidth-bound step streams int8 weights instead of bf16.
    quantized = os.environ.get("BENCH_WEIGHTS", "") == "int8"
    if quantized:
        from horovod_tpu.models.quant import quantize_params

        params = quantize_params(params)
    # BENCH_KV_INT8=1: int8 K/V cache (per-(position, head) scales) — the
    # cache stream halves; stacks with BENCH_WEIGHTS/BENCH_KV_HEADS.
    from horovod_tpu import runtime as _rt

    kv_int8 = _rt.env_flag("BENCH_KV_INT8")
    fn = make_generate_fn(
        model, max_new_tokens=new_tokens, include_prompt=False,
        temperature=float(os.environ.get("BENCH_TEMPERATURE", 0.0)),
        quantized=quantized, quantized_cache=kv_int8,
    )
    key = jax.random.PRNGKey(7)

    def run():
        return fn(params, prompt, key).sum()

    float(jax.device_get(run()))  # compile + settle
    reps = max(1, int(os.environ.get("BENCH_DECODE_REPS", 4)))

    def run_reps():
        total = jnp.int32(0)
        for _ in range(reps):
            total = total + run()
        return total

    elapsed = _timed(run_reps) / reps
    n_params = sum(
        int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params)
    )
    if quantized:
        from horovod_tpu.models.quant import quantized_bytes

        model_bytes = quantized_bytes(params)
    else:
        model_bytes = 2 * n_params  # bf16 compute copies
    tok_per_sec = batch * new_tokens / elapsed
    return {
        "metric": "transformer_lm_decode_tokens_per_sec_per_chip",
        "value": round(tok_per_sec / n_chips, 1),
        "unit": "tokens/sec/chip",
        "batch": batch,
        "weights": "int8" if quantized else "bf16",
        "kv_cache": "int8" if kv_int8 else "bf16",
        "n_kv_heads": model.n_kv_heads or model.n_heads,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "ms_per_token": round(elapsed / new_tokens * 1e3, 4),
        "n_params": n_params,
        # Each decode step reads every weight once: the implied HBM traffic
        # floor (as-stored bytes — 2 B/param bf16, ~1 B/param for int8
        # weights — ignoring the KV cache) vs v5e's ~819 GB/s.
        "model_bandwidth_gbps": round(
            model_bytes * (tok_per_sec / batch) / 1e9, 1
        ),
        "n_chips": n_chips,
    }


def bench_int8_compute() -> dict:
    """int8 COMPUTE A/B (models/quant.int8_dot_general): prefill and
    large-batch decode, bf16 MXU vs int8 MXU (dynamic activation scales,
    per-channel weight scales, int32 accumulation).

    Prefill is the compute-bound phase (a full causal forward over the
    prompt); large-batch decode amortizes the weight stream until the
    matmuls, not the bytes, dominate — exactly where v5e's 2x int8 MXU
    rate can pay. Reported: prefill ms and decode tokens/sec for both
    paths at the d1024-class shape (BENCH_DMODEL et al. to vary).
    """
    os.environ.setdefault("HVT_FAST_RNG", "1")
    os.environ.setdefault("BENCH_DMODEL", "1024")
    os.environ.setdefault("BENCH_NLAYERS", "16")

    import jax
    import jax.numpy as jnp
    import numpy as np

    import horovod_tpu as hvt
    from horovod_tpu.models.decoding import make_generate_fn

    hvt.init()
    n_chips = jax.device_count()
    batch = int(os.environ.get("BENCH_DECODE_BATCH", 32))
    prompt_len = int(os.environ.get("BENCH_PROMPT_LEN", 512))
    new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", 128))
    model = _lm_from_env()
    rng = np.random.RandomState(0)
    prompt = jnp.asarray(
        rng.randint(0, 8192, size=(batch, prompt_len)), jnp.int32
    )
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    reps = max(1, int(os.environ.get("BENCH_DECODE_REPS", 4)))

    def measure_prefill(int8: bool) -> float:
        m = model.clone(int8_compute=int8) if int8 else model
        fwd = jax.jit(lambda p, x: m.apply({"params": p}, x).sum())
        float(jax.device_get(fwd(params, prompt)))

        def run_reps():
            total = jnp.float32(0)
            for _ in range(reps):
                total = total + fwd(params, prompt)
            return total

        return min(_timed(run_reps) for _ in range(3)) / reps

    def measure_decode(int8: bool) -> float:
        fn = make_generate_fn(
            model, max_new_tokens=new_tokens, include_prompt=False,
            int8_compute=int8,
        )
        key = jax.random.PRNGKey(7)

        def run():
            return fn(params, prompt, key).sum()

        float(jax.device_get(run()))

        def run_reps():
            total = jnp.int32(0)
            for _ in range(reps):
                total = total + run()
            return total

        return min(_timed(run_reps) for _ in range(3)) / reps

    pre_bf16 = measure_prefill(False)
    pre_int8 = measure_prefill(True)
    dec_bf16 = measure_decode(False)
    dec_int8 = measure_decode(True)
    toks = batch * new_tokens
    return {
        "metric": "int8_compute_prefill_speedup",
        "value": round(pre_bf16 / pre_int8, 2),
        "unit": "x vs bf16",
        "prefill_ms_bf16": round(pre_bf16 * 1e3, 2),
        "prefill_ms_int8": round(pre_int8 * 1e3, 2),
        "prefill_tokens_per_sec_int8": round(
            batch * prompt_len / pre_int8 / n_chips, 1
        ),
        "decode_tokens_per_sec_bf16": round(toks / dec_bf16 / n_chips, 1),
        "decode_tokens_per_sec_int8": round(toks / dec_int8 / n_chips, 1),
        "decode_speedup": round(dec_bf16 / dec_int8, 2),
        "batch": batch,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "d_model": model.d_model,
        "n_layers": model.n_layers,
        "n_chips": n_chips,
    }


def bench_spec() -> dict:
    """Speculative-decoding A/B: exact-greedy speedup on a model that has
    actually learned its task.

    An untrained model's greedy continuation is arbitrary, so NO draft can
    be accepted and a speculative bench on random weights would honestly
    measure nothing. Instead this trains a small LM on the copy task
    on-chip (seconds, device-cached), then decodes copy-structured prompts
    — where the prompt-lookup draft proposes the true continuation — with
    plain greedy vs speculative. Outputs are verified identical; the
    speedup is the accepted-tokens-per-target-pass ratio made wall-clock.
    """
    os.environ.setdefault("HVT_FAST_RNG", "1")

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import horovod_tpu as hvt
    from horovod_tpu.data import datasets
    from horovod_tpu.models.decoding import make_generate_fn
    from horovod_tpu.models.speculative import make_speculative_fn
    from horovod_tpu.models.transformer import TransformerLM

    hvt.init()
    vocab = 64
    seq = int(os.environ.get("BENCH_SPEC_SEQ", 512))
    batch = int(os.environ.get("BENCH_SPEC_BATCH", 1))
    gamma = int(os.environ.get("BENCH_SPEC_GAMMA", 8))
    model = TransformerLM(
        vocab_size=vocab,
        d_model=int(os.environ.get("BENCH_SPEC_DMODEL", 512)),
        n_heads=8,
        n_layers=int(os.environ.get("BENCH_SPEC_LAYERS", 8)),
        dropout=0.0,
        compute_dtype=jnp.bfloat16,
    )
    trainer = hvt.Trainer(
        model,
        hvt.DistributedOptimizer(optax.adam(1e-3)),
        loss="sparse_categorical_crossentropy",
    )
    x, y = datasets.copy_task(4096, seq, vocab_size=vocab, seed=3)
    trainer.fit(
        x=x, y=y, batch_size=64,
        epochs=int(os.environ.get("BENCH_SPEC_EPOCHS", 8)),
        steps_per_epoch=64, verbose=0, cache="device",
    )
    params = trainer.state.params

    xt, _ = datasets.copy_task(batch, seq, vocab_size=vocab, seed=777)
    prompt = jnp.asarray(xt[:, : seq // 2])  # continuation = the copy
    n_new = seq // 2 - 1

    plain = make_generate_fn(
        model, max_new_tokens=n_new, include_prompt=False
    )
    spec = make_speculative_fn(
        model, max_new_tokens=n_new, gamma=gamma, include_prompt=False,
        return_stats=True,
    )
    key = jax.random.PRNGKey(0)
    out_plain = jax.device_get(plain(params, prompt, key))
    out_spec, stats = spec(params, prompt)
    out_spec = jax.device_get(out_spec)
    assert np.array_equal(out_plain, out_spec), (
        "speculative output diverged from plain greedy — exactness bug"
    )
    rounds = int(jax.device_get(stats["rounds"]))
    accepted = int(jax.device_get(stats["tokens"]))

    reps = max(1, int(os.environ.get("BENCH_DECODE_REPS", 8)))

    def chain(fn):
        def run():
            total = jnp.int32(0)
            for _ in range(reps):
                total = total + fn()
            return total

        return run

    # The tunnel's settle period can outlast one warmup execution (the
    # decode benches amortize it over 512-token generations; these are
    # 127-token ones) — warm each fn twice more and take the best of 3
    # chains. Honesty is unchanged: every chain ends in a device fetch.
    plain_chain = chain(lambda: plain(params, prompt, key).sum())
    spec_chain = chain(lambda: spec(params, prompt)[0].sum())
    for c in (plain_chain, spec_chain):
        float(jax.device_get(c()))
    t_plain = min(_timed(plain_chain) for _ in range(3)) / reps
    t_spec = min(_timed(spec_chain) for _ in range(3)) / reps
    n_chips = jax.device_count()
    tok_plain = batch * n_new / t_plain / n_chips
    tok_spec = batch * n_new / t_spec / n_chips
    return {
        "metric": "speculative_decode_tokens_per_sec_per_chip",
        "value": round(tok_spec, 1),
        "unit": "tokens/sec/chip",
        "plain_tokens_per_sec": round(tok_plain, 1),
        "speedup": round(tok_spec / tok_plain, 2),
        "gamma": gamma,
        # stats['tokens'] is the batch-wide committed total; per-row mean
        # acceptance divides by the batch too (speculative.py docstring).
        "accept_per_round": round(accepted / max(rounds, 1) / batch, 2),
        "rounds": rounds,
        "batch": batch,
        "new_tokens": n_new,
        "exact": True,
        "n_chips": n_chips,
    }


def bench_serve() -> dict:
    """Serving-tier tail-latency A/B: continuous batching vs the legacy
    coalescing path, at EQUAL offered load.

    Spins up the real server (launch/serve.py) over a tiny streaming
    generation bundle and drives the SAME precomputed open-loop arrival
    schedule through both modes — open-loop (each request fires at its
    scheduled wall time regardless of completions), because a closed
    loop lets a slow server throttle its own offered load and hide its
    queueing tail. Per request, the client measures TTFT (first NDJSON
    line) and TPOT (per-token decode tail past the first chunk); the
    report is p50/p95/p99 of both, per mode.

    The offered rate is set to ~2x the legacy path's measured solo
    throughput: the legacy streaming path serializes every chunk
    dispatch of every concurrent request through one device lock (K
    single-row streams = K near-empty dispatches per chunk), so its
    queue grows and its tail TTFT blows up — while the continuous engine
    shares each dispatch across up to batch_size live rows and sustains
    the rate. The gate (`serve_gate_ok`, enforced by main): continuous
    p95 TTFT must not exceed the coalescing baseline's.
    """
    import tempfile
    import threading
    import urllib.request

    import jax
    import numpy as np

    from horovod_tpu import serving
    from horovod_tpu.launch.serve import make_server
    from horovod_tpu.models.transformer import TransformerLM

    n_requests = int(os.environ.get("BENCH_SERVE_REQUESTS", 48))
    batch, t0_len, n_new, chunk = 4, 8, 8, 2
    model = TransformerLM(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, dropout=0.0
    )
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((batch, t0_len), np.int32)
    )["params"]
    tmp = tempfile.mkdtemp(prefix="hvt-bench-serve-")
    bundle = serving.export_generate(
        tmp, model, params, batch_size=batch, prompt_len=t0_len,
        max_new_tokens=n_new, streaming_chunk=chunk, timestamp="bench",
    )

    rs = np.random.RandomState(0)
    prompts = [
        [int(t) for t in rs.randint(1, 60, size=1 + i % 6)]
        for i in range(n_requests)
    ]

    def one_stream(url: str, prompt: list) -> tuple:
        req = urllib.request.Request(
            url,
            data=json.dumps({"prompt": [prompt], "stream": True}).encode(),
            headers={"Content-Type": "application/json"},
        )
        t_start = time.perf_counter()
        ttft, n_tok = None, 0
        with urllib.request.urlopen(req, timeout=300) as r:
            for line in r:
                now = time.perf_counter()
                obj = json.loads(line)
                if obj.get("error"):
                    raise RuntimeError(obj["error"])
                if ttft is None:
                    ttft = now - t_start
                if "tokens" in obj and not obj.get("done"):
                    n_tok += sum(len(x) for x in obj["tokens"])
        total = time.perf_counter() - t_start
        # Decode tail per token, past the first chunk (the TTFT edge).
        tpot = (total - ttft) / max(1, n_tok - chunk)
        return ttft, tpot

    def pct(values: list, q: float) -> float:
        return float(np.percentile(np.asarray(values), q))

    def measure(continuous: bool, gap: float) -> dict:
        srv = make_server(bundle, port=0, continuous=continuous)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        url = f"http://127.0.0.1:{srv.server_address[1]}/v1/generate"
        for p in prompts[:2]:
            one_stream(url, p)  # warm the compiled programs
        results: list = [None] * n_requests
        t_begin = time.perf_counter() + 0.05

        def client(i: int) -> None:
            # Open loop: fire at the SCHEDULED time, late or not.
            delay = t_begin + i * gap - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            results[i] = one_stream(url, prompts[i])

        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(n_requests)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0
        engine = getattr(srv.app, "engine", None)
        calls = (
            engine.stats()["device_calls_total"]
            if engine is not None else srv.app.stats["device_calls"]
        )
        if engine is not None:
            engine.stop()
        srv.shutdown()
        ttfts = [r[0] for r in results]
        tpots = [r[1] for r in results]
        return {
            "p50_ttft_ms": round(pct(ttfts, 50) * 1e3, 2),
            "p95_ttft_ms": round(pct(ttfts, 95) * 1e3, 2),
            "p99_ttft_ms": round(pct(ttfts, 99) * 1e3, 2),
            "p50_tpot_ms": round(pct(tpots, 50) * 1e3, 3),
            "p95_tpot_ms": round(pct(tpots, 95) * 1e3, 3),
            "device_calls": calls,
            "elapsed_s": round(elapsed, 2),
        }

    # Calibrate the offered rate off the LEGACY path's solo service time
    # so the schedule oversubscribes it ~2x on any host.
    srv = make_server(bundle, port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_address[1]}/v1/generate"
    one_stream(url, prompts[0])  # compile
    t0 = time.perf_counter()
    for p in prompts[:4]:
        one_stream(url, p)
    solo = (time.perf_counter() - t0) / 4
    srv.shutdown()
    gap = solo / 2.0

    coalesce = measure(continuous=False, gap=gap)
    continuous = measure(continuous=True, gap=gap)
    gate_ok = continuous["p95_ttft_ms"] <= coalesce["p95_ttft_ms"]
    return {
        "metric": "serve_p95_ttft_ms",
        "value": continuous["p95_ttft_ms"],
        "unit": "ms",
        "continuous": continuous,
        "coalescing": coalesce,
        "offered_rps": round(1.0 / gap, 1),
        "requests": n_requests,
        "batch": batch,
        "new_tokens": n_new,
        "serve_gate_ok": gate_ok,
    }


def bench_input() -> dict:
    """Host input-pipeline A/B: native C++ batch assembly vs pure Python.

    Times `training_pipeline` (shuffle + gather + stage) alone — the part the
    native engine (native/hvt_data.cc) owns; no device work."""
    import numpy as np

    from horovod_tpu.data import datasets, native_loader
    from horovod_tpu.data.loader import training_pipeline

    (x_train, y_train), _ = datasets.mnist()
    x = (x_train.astype(np.float32) / 255.0)[..., None]
    arrays = (x, y_train.astype(np.int64))
    steps = 400

    # Decide native availability BEFORE touching HVT_NO_NATIVE: probing under
    # the env var would permanently latch the loader's load-failed flag and
    # the native leg could never run.
    native = native_loader.available()

    # The native engine's value is OVERLAP: its producer thread assembles
    # batch k+1 while the consumer (a training loop dispatching device work)
    # is busy with batch k. Measure both regimes: a tight next() loop (raw
    # assembly speed — numpy's fancy-index gather is already memcpy-bound,
    # so parity is expected) and a consumer that does `busy_s` of work per
    # batch (the realistic loop, where background assembly hides under it).
    busy_s = float(os.environ.get("BENCH_INPUT_BUSY_MS", 1.0)) / 1e3

    def run(no_native: bool, busy: float) -> float:
        if no_native:
            os.environ["HVT_NO_NATIVE"] = "1"
        else:
            os.environ.pop("HVT_NO_NATIVE", None)
        it, close = training_pipeline(arrays, BATCH, seed=0)
        try:
            for _ in range(50):  # warm the producer
                next(it)
            t0 = time.perf_counter()
            for _ in range(steps):
                next(it)
                if busy:
                    end = time.perf_counter() + busy
                    while time.perf_counter() < end:  # simulated step work
                        pass
            return steps * BATCH / (time.perf_counter() - t0)
        finally:
            close()

    python_raw = run(no_native=True, busy=0.0)
    python_busy = run(no_native=True, busy=busy_s)
    # Without the native engine (no toolchain to build it), the "native" legs
    # would silently rerun Python and publish "no speedup" — label it.
    native_raw = run(no_native=False, busy=0.0) if native else python_raw
    native_busy = run(no_native=False, busy=busy_s) if native else python_busy
    return {
        "metric": "input_pipeline_images_per_sec_overlapped",
        "value": round(native_busy, 1),
        "unit": "images/sec",
        "native": native,
        "busy_ms_per_batch": busy_s * 1e3,
        "python_overlapped_images_per_sec": round(python_busy, 1),
        "raw_images_per_sec": {
            "native": round(native_raw, 1),
            "python": round(python_raw, 1),
        },
        "vs_baseline": round(native_busy / python_busy, 2) if native else None,
    }


def _phase_overruns(step_ms: dict) -> list:
    """Phases reported larger than `total` (impossible under the one
    consistent accounting bench_train uses — any hit means the measurement
    or clamping regressed, the r04 `compute: 0.281 > total: 0.256` bug).
    Also flags the phases summing past total. Small float-printing slack
    only (phases are rounded to µs independently of total)."""
    total = step_ms.get("total")
    if total is None:
        return []
    slack = 2e-3  # rounded-to-3-decimals ms values
    phases = {
        k: v for k, v in step_ms.items()
        if k != "total" and isinstance(v, (int, float))
    }
    bad = [k for k, v in phases.items() if v > total + slack]
    if sum(phases.values()) > total + slack * max(1, len(phases)):
        bad.append("sum(phases)")
    return bad


def main() -> None:
    # An unparseable HVT_PEAK_FLOPS override is a usage error — exit 2
    # before any leg runs (the hvt-lint/hvt-audit exit-code contract).
    try:
        from horovod_tpu.analysis import registry as _registry

        _registry.get_float("HVT_PEAK_FLOPS")
    except ValueError as e:
        import sys

        print(f"bench: unparseable HVT_PEAK_FLOPS override: {e}",
              file=sys.stderr)
        sys.exit(2)
    which = os.environ.get("BENCH_MODEL", "mnist")
    if which == "input":
        result = bench_input()
    elif which == "serve":
        result = bench_serve()
    elif which == "int8":
        result = bench_int8_compute()
    elif which == "accum":
        result = bench_accum()
    elif which == "zero1":
        result = bench_zero1()
    elif which == "decode":
        result = bench_decode()
    elif which == "spec":
        result = bench_spec()
    else:
        result = bench_train(which)
        vs = None
        if which == "mnist":
            baseline_path = os.path.join(
                REPO, "benchmarks", "baseline_measured.json"
            )
            if os.path.exists(baseline_path):
                with open(baseline_path) as f:
                    vs = round(result["value"] / json.load(f)["images_per_sec"], 2)
        result["vs_baseline"] = vs
    if "config" not in result:
        # Every row is a self-describing tuner input: stamp the
        # fully-resolved tunable-knob values it ran under. Modes that
        # pick their own values (zero1) stamp explicitly above; the
        # rest resolve from the registry, overridden by whatever the
        # row itself reports it used.
        from horovod_tpu.tune import space as _tune_space

        cfg = _tune_space.resolved_config()
        for knob_name, row_key in (
            ("HVT_BUCKET_BYTES", "bucket_bytes"),
            ("HVT_BACKWARD_PASSES", "k"),
            ("HVT_COMPRESSION", "compression"),
            ("HVT_COMPRESSION_ICI", "compression_ici"),
        ):
            if result.get(row_key) is not None:
                cfg[knob_name] = result[row_key]
        result["config"] = cfg
    print(json.dumps(result))
    overruns = _phase_overruns(result.get("step_ms", {}))
    if overruns:
        import sys

        print(
            f"bench: phase(s) {overruns} exceed step_ms.total — "
            "inconsistent phase accounting",
            file=sys.stderr,
        )
        sys.exit(1)
    if result.get("wire_gate_ok") is False:
        import sys

        print(
            "bench: the ZeRO-1 scattered boundary reduction regressed — "
            "it must move strictly fewer bytes than the replicated one "
            "at the same K (byte-EQUAL for quantized wires, whose dense "
            "layout is deliberate) "
            f"({result.get('wire_bytes_per_opt_step')})",
            file=sys.stderr,
        )
        sys.exit(1)
    if result.get("serve_gate_ok") is False:
        import sys

        print(
            "bench: continuous batching LOST to the coalescing baseline "
            "on tail TTFT at equal offered load "
            f"(continuous p95 {result.get('continuous', {}).get('p95_ttft_ms')} ms "
            f"vs coalescing p95 {result.get('coalescing', {}).get('p95_ttft_ms')} ms) "
            "— per-step admission is not cashing in",
            file=sys.stderr,
        )
        sys.exit(1)
    if result.get("overlap_gate_ok") is False:
        import sys

        print(
            "bench: the overlapped zero1 step did NOT beat its own "
            "serialized form on wall-clock step_ms.total at the same K "
            f"(overlapped {result.get('step_ms', {}).get('total')} ms vs "
            f"serialized {result.get('serialized_step_ms_total')} ms) — "
            "the per-bucket scatter overlap is not cashing in",
            file=sys.stderr,
        )
        sys.exit(1)
    if (result.get("tuned_vs_default") or {}).get("gate_ok") is False:
        import sys

        tvd = result["tuned_vs_default"]
        print(
            "bench: the hvt-tune-selected config did NOT beat the "
            "registry-default config on step_ms.total at the same K "
            f"(tuned {result.get('step_ms', {}).get('total')} ms vs "
            f"default {tvd.get('default_step_ms_total')} ms, paired "
            f"median {tvd.get('median_pct')}% over {tvd.get('pairs')} "
            "pairs) — the tuner crowned a loser",
            file=sys.stderr,
        )
        sys.exit(1)
    if result.get("flops_guard", {}).get("ok") is False:
        import sys

        print(
            "bench: flops_per_opt_step guard failed — the K>1 program's "
            "cost-model FLOP count left the band implied by the peel "
            f"structure ({result.get('flops_guard')}); the MFU "
            "denominator (K x the K=1 compile) no longer matches the "
            "compiled step",
            file=sys.stderr,
        )
        sys.exit(1)
    if result.get("sampler_gate_ok") is False:
        import sys

        print(
            "bench: live StepPhaseSampler overhead "
            f"{result.get('sampler_overhead_pct')}% exceeds the "
            f"{os.environ.get('BENCH_SAMPLER_MAX_OVERHEAD_PCT', 2.0)}% "
            "budget on step_ms.total at "
            f"every={result.get('sampler_every')} — the trainer-side "
            "metrics exporter is too expensive to leave on",
            file=sys.stderr,
        )
        sys.exit(1)


if __name__ == "__main__":
    main()
