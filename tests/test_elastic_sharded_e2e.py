"""Sharded-world fault tolerance end-to-end (the ISSUE 3 acceptance runs):

1. A 3-process elastic fleet with ZeRO-1 cross-process-sharded optimizer
   state (``Trainer(shard_update=True)``) shrinks 3→2 on a clean ``leave``
   and CONTINUES from committed progress with zero survivor process
   restarts: commits snapshot per-process optimizer shards, the
   membership boundary reassembles them across the departing generation
   (the leaver's third included), and the survivors re-place the dense
   snapshot onto the 2-rank ZeRO-1 layout. The loss trajectory is
   compared epoch-by-epoch against the identical run with dense
   (replicated) commits — the per-shard commit path must not change the
   training math.

2. A supervised run whose newest checkpoint is corrupted by the
   ``corrupt`` fault kind (``HVT_FAULT=0:3:corrupt`` — damage the newest
   checkpoint file, then SIGKILL) restarts and resumes from the PREVIOUS
   complete checkpoint: discovery verifies sha256 digests, skips the
   corrupt epoch, and `_discard_future_checkpoints` removes it.

All chaos is injected through env vars (`horovod_tpu.testing.faults`);
the training scripts are the plain `elastic.run` / resume idioms."""

import json
import os
import re
import sys
import textwrap

import pytest

from horovod_tpu.launch import ci_gate, supervisor
from horovod_tpu.launch.supervisor import ElasticPolicy, RestartPolicy

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

EPOCHS = 6

# Tiny synthetic elastic trainer, the test_elastic_e2e.py shape with the
# ZeRO-1 knob: leaf dims divisible by both 3 and 2 so the optimizer state
# shards at either world size. STATUS lines carry per-epoch loss (the
# trajectory the dense-vs-sharded comparison reads) and SHARDED= proves
# the committed state really was cross-process sharded.
TRAIN_SCRIPT = """
import os, sys
sys.path.insert(0, __REPO__)
import numpy as np
import optax
import flax.linen as nn
import horovod_tpu as hvt
from horovod_tpu import checkpoint, elastic

print(f"BOOT member={os.environ['HVT_ELASTIC_MEMBER']}", flush=True)


class Tiny(nn.Module):
    @nn.compact
    def __call__(self, x, train=False):
        return nn.Dense(4)(x)


def train(state, world):
    model_dir = os.path.join(os.environ["PS_MODEL_PATH"], "run")
    rng = np.random.RandomState(0)
    x = rng.rand(96, 12).astype("float32")
    y = (np.arange(96) % 4).astype("int64")
    # HVT_BACKWARD_PASSES=K: the composed ZeRO-1 x accumulation path —
    # K microbatches per optimizer step with the boundary reduction
    # scattered into the sharded update layout (ISSUE 10).
    from horovod_tpu.analysis import registry
    backward_passes = registry.get_int("HVT_BACKWARD_PASSES") or 1
    trainer = hvt.Trainer(
        Tiny(), hvt.DistributedOptimizer(
            optax.adam(1e-2), backward_passes_per_step=backward_passes,
            average_aggregated_gradients=True,
        ),
        shard_update=hvt.runtime.env_flag("ELASTIC_ZERO1"),
    )
    trainer.build(x[:1], y[:1])
    print(
        f"GEN rank={world.rank} size={world.size} gen={world.generation} "
        f"SHARDED={checkpoint.is_cross_process_sharded(trainer.state)}",
        flush=True,
    )
    if state.state is not None:
        trainer.install_state(state.state)
    else:
        trainer.state, done = checkpoint.restore_latest_and_broadcast(
            model_dir, trainer.state, mesh=trainer.mesh, reshard=True)
        state.epoch = max(state.epoch, done)
    # EVERY rank: single-file saves self-gate to the primary; the sharded
    # (ZeRO-1) format needs every process's shard file.
    cbs = [hvt.callbacks.ModelCheckpoint(
        os.path.join(model_dir, "checkpoint-{epoch}.msgpack"))]

    class Status(hvt.callbacks.Callback):
        def on_epoch_end(self, epoch, logs=None):
            import jax
            step = int(jax.device_get(self.trainer.state.step))
            loss = float(logs["loss"]) if logs and "loss" in logs else -1.0
            print(
                f"STATUS epoch={epoch + 1} step={step} rank={world.rank} "
                f"size={world.size} loss={loss:.8f}", flush=True,
            )

    cbs.append(Status())
    cbs.append(elastic.ElasticStateCallback(state, state.client))
    trainer.fit(
        x=x, y=y, batch_size=8, epochs=__EPOCHS__,
        initial_epoch=state.epoch, steps_per_epoch=2, callbacks=cbs,
        verbose=0,
    )


elastic.run(train)
print("TRAINING COMPLETE", flush=True)
"""


def _write_script(tmp_path):
    path = tmp_path / "elastic_train.py"
    path.write_text(
        textwrap.dedent(TRAIN_SCRIPT)
        .replace("__REPO__", repr(REPO))
        .replace("__EPOCHS__", str(EPOCHS))
    )
    return [sys.executable, str(path)]


def _journal(log):
    with open(log) as f:
        return [json.loads(line) for line in f if line.strip()]


def _run_elastic(tmp_path, capfd, tag, zero1, extra_env=None):
    argv = _write_script(tmp_path)
    model_dir = tmp_path / f"models-{tag}"
    log = tmp_path / f"restarts-{tag}.jsonl"
    env = {
        "HVT_PLATFORM": "cpu",
        "HVT_NUM_CPU_DEVICES": "1",
        "PS_MODEL_PATH": str(model_dir),
        "ELASTIC_ZERO1": "1" if zero1 else "0",
        "HVT_FAULT": "2:1:leave",
        "HVT_FAULT_STAMP": str(tmp_path / f"leave-stamp-{tag}"),
        # Chaos children stay out of the suite's shared persistent XLA
        # cache (see test_supervisor_e2e for the torn-entry SEGFAULT).
        "JAX_ENABLE_COMPILATION_CACHE": "0",
        "JAX_COMPILATION_CACHE_DIR": "",
    }
    env.update(extra_env or {})
    code = supervisor.supervise_elastic(
        3, argv, env=env,
        # max_restarts=0: the leaver is NOT replaced, so both runs see the
        # identical deterministic world trajectory (3,3 then 2,2,2,2) and
        # their loss series are comparable epoch by epoch.
        policy=RestartPolicy(max_restarts=0, backoff=0.5,
                             grace_seconds=10.0),
        elastic=ElasticPolicy(min_ranks=2, max_ranks=3,
                              rendezvous_timeout=180.0),
        model_dir=str(model_dir), log_path=str(log),
    )
    out = capfd.readouterr().out
    assert code == 0, out[-4000:]
    return out, log, model_dir


@pytest.mark.slow
def test_zero1_shrink_continues_and_matches_dense(tmp_path, capfd):
    out_sharded, log, model_dir = _run_elastic(
        tmp_path, capfd, "zero1", zero1=True
    )

    # The committed state really was cross-process sharded at size 3.
    gens = re.findall(r"GEN rank=0 size=(\d) gen=\d+ SHARDED=(\w+)",
                      out_sharded)
    assert ("3", "True") in gens, gens
    assert ("2", "True") in gens, gens  # still ZeRO-1 after the shrink

    # Clean leave → shrink journaled; nobody gave up on the SHRINK path
    # (max_restarts=0 forfeits only the replacement).
    records = _journal(log)
    names = [r["name"] for r in records]
    assert "leave" in names and "shrink" in names
    settles = [(r["name"], r["size"]) for r in records
               if r["name"] in ("start", "shrink", "grow", "steady")]
    assert settles[0] == ("start", 3)
    assert ("shrink", 2) in settles
    ok, _ = ci_gate.check_metrics(str(log), "shrink", (1.0, 9.0),
                                  how="count")
    assert ok

    # Zero survivor reboots: exactly the 3 initial boots, no replacement.
    boots = re.findall(r"BOOT member=(\S+)", out_sharded)
    assert len(boots) == 3 and len(set(boots)) == 3, boots

    # Continue-through-failure from committed progress: the step counter
    # is an exact function of the epoch on rank 0 — nothing recomputed,
    # nothing skipped — and training ran to completion.
    statuses = [
        (int(m.group(1)), int(m.group(2)), float(m.group(3)))
        for m in re.finditer(
            r"STATUS epoch=(\d+) step=(\d+) rank=0 size=\d+ "
            r"loss=([0-9.]+)", out_sharded)
    ]
    assert statuses, out_sharded[-2000:]
    assert all(step == 2 * epoch for epoch, step, _ in statuses), statuses
    assert max(e for e, _, _ in statuses) == EPOCHS
    assert "TRAINING COMPLETE" in out_sharded
    # The world actually shrank mid-run: some epoch trained at size 2.
    epoch_sizes = re.findall(
        r"STATUS epoch=\d+ step=\d+ rank=0 size=(\d+)", out_sharded
    )
    assert "3" in epoch_sizes and "2" in epoch_sizes, epoch_sizes

    # Sharded checkpoints landed in the sharded directory format with
    # per-shard digests (ModelCheckpoint on every rank).
    run_dir = model_dir / "run"
    shards = sorted(
        d for d in os.listdir(run_dir) if d.endswith(".shards")
    )
    assert shards, os.listdir(run_dir)
    newest = run_dir / shards[-1]
    assert (newest / "index.json").exists()
    assert any(n.endswith(".sha256") for n in os.listdir(newest))

    # The dense-commit control: identical run, shard_update off. The
    # per-shard commit path must not change the training math — loss
    # trajectories match epoch for epoch.
    out_dense, _, _ = _run_elastic(tmp_path, capfd, "dense", zero1=False)
    assert ("3", "False") in re.findall(
        r"GEN rank=0 size=(\d) gen=\d+ SHARDED=(\w+)", out_dense
    )
    dense = {
        int(m.group(1)): float(m.group(2))
        for m in re.finditer(
            r"STATUS epoch=(\d+) step=\d+ rank=0 size=\d+ loss=([0-9.]+)",
            out_dense)
    }
    sharded_losses = {e: l for e, _, l in statuses}
    assert set(dense) == set(sharded_losses)
    for epoch in sorted(dense):
        assert dense[epoch] == pytest.approx(
            sharded_losses[epoch], rel=1e-4, abs=1e-6
        ), (epoch, dense[epoch], sharded_losses[epoch])


@pytest.mark.slow
def test_zero1_k4_composed_shrink_matches_dense(tmp_path, capfd):
    """ISSUE 10 acceptance leg: the COMPOSED path — ZeRO-1 sharded
    commits x backward_passes_per_step=4 (the scattered boundary
    reduction) — through the same 3→2 clean-leave shrink: sharded at
    both sizes, zero survivor reboots, training completes, and the loss
    trajectory equals the dense (replicated-update) K=4 control epoch
    for epoch at rel 1e-4 — elasticity and the scatter lowering change
    the layout, never the math."""
    k4 = {"HVT_BACKWARD_PASSES": "4"}
    out_sharded, log, _ = _run_elastic(
        tmp_path, capfd, "zero1-k4", zero1=True, extra_env=k4
    )

    gens = re.findall(r"GEN rank=0 size=(\d) gen=\d+ SHARDED=(\w+)",
                      out_sharded)
    assert ("3", "True") in gens and ("2", "True") in gens, gens
    records = _journal(log)
    names = [r["name"] for r in records]
    assert "leave" in names and "shrink" in names
    boots = re.findall(r"BOOT member=(\S+)", out_sharded)
    assert len(boots) == 3 and len(set(boots)) == 3, boots
    statuses = [
        (int(m.group(1)), int(m.group(2)), float(m.group(3)))
        for m in re.finditer(
            r"STATUS epoch=(\d+) step=(\d+) rank=0 size=\d+ "
            r"loss=([0-9.]+)", out_sharded)
    ]
    assert statuses, out_sharded[-2000:]
    # steps_per_epoch=2 OPTIMIZER steps regardless of K — the counter
    # stays exact through the composed shrink.
    assert all(step == 2 * epoch for epoch, step, _ in statuses), statuses
    assert max(e for e, _, _ in statuses) == EPOCHS
    assert "TRAINING COMPLETE" in out_sharded
    sizes = re.findall(
        r"STATUS epoch=\d+ step=\d+ rank=0 size=(\d+)", out_sharded
    )
    assert "3" in sizes and "2" in sizes, sizes

    out_dense, _, _ = _run_elastic(
        tmp_path, capfd, "dense-k4", zero1=False, extra_env=k4
    )
    dense = {
        int(m.group(1)): float(m.group(2))
        for m in re.finditer(
            r"STATUS epoch=(\d+) step=\d+ rank=0 size=\d+ loss=([0-9.]+)",
            out_dense)
    }
    sharded_losses = {e: l for e, _, l in statuses}
    assert set(dense) == set(sharded_losses)
    for epoch in sorted(dense):
        assert dense[epoch] == pytest.approx(
            sharded_losses[epoch], rel=1e-4, abs=1e-6
        ), (epoch, dense[epoch], sharded_losses[epoch])


RESUME_SCRIPT = """
import os, sys
sys.path.insert(0, __REPO__)
import numpy as np
import optax
import flax.linen as nn
import horovod_tpu as hvt
from horovod_tpu import checkpoint

hvt.init()


class Tiny(nn.Module):
    @nn.compact
    def __call__(self, x, train=False):
        return nn.Dense(4)(x)


model_dir = os.environ["PS_MODEL_PATH"]
rng = np.random.RandomState(0)
x = rng.rand(96, 12).astype("float32")
y = (np.arange(96) % 4).astype("int64")
trainer = hvt.Trainer(Tiny(), hvt.DistributedOptimizer(optax.adam(1e-2)))
trainer.build(x[:1], y[:1])
trainer.state, done = checkpoint.restore_latest_and_broadcast(
    model_dir, trainer.state, mesh=trainer.mesh)
print(f"RESUME epoch={done}", flush=True)
trainer.fit(
    x=x, y=y, batch_size=8, epochs=6, initial_epoch=done,
    steps_per_epoch=2, verbose=0,
    callbacks=[hvt.callbacks.ModelCheckpoint(
        os.path.join(model_dir, "checkpoint-{epoch}.msgpack"))],
)
print("TRAINING COMPLETE", flush=True)
"""


@pytest.mark.slow
def test_corrupt_checkpoint_recovers_from_previous(tmp_path, capfd):
    """The acceptance leg for checkpoint integrity: HVT_FAULT=0:3:corrupt
    damages the newest checkpoint (epoch 3) and SIGKILLs; the supervised
    relaunch must resume from epoch 2 — the previous COMPLETE checkpoint
    — re-earn the rest, and finish."""
    script = tmp_path / "resume_train.py"
    script.write_text(
        textwrap.dedent(RESUME_SCRIPT).replace("__REPO__", repr(REPO))
    )
    model_dir = tmp_path / "models"
    model_dir.mkdir()
    log = tmp_path / "restarts.jsonl"
    env = {
        "HVT_PLATFORM": "cpu",
        "HVT_NUM_CPU_DEVICES": "1",
        "PS_MODEL_PATH": str(model_dir),
        "HVT_FAULT": "0:3:corrupt",
        "HVT_FAULT_STAMP": str(tmp_path / "corrupt-stamp"),
        "JAX_ENABLE_COMPILATION_CACHE": "0",
        "JAX_COMPILATION_CACHE_DIR": "",
    }
    code = supervisor.supervise_local(
        1, [sys.executable, str(script)], env=env,
        policy=RestartPolicy(max_restarts=3, backoff=0.2,
                             grace_seconds=10.0),
        model_dir=str(model_dir), log_path=str(log),
    )
    out = capfd.readouterr().out
    assert code == 0, out[-4000:]
    assert "FaultInjection: corrupting" in out
    resumes = re.findall(r"RESUME epoch=(\d+)", out)
    # First launch starts fresh; the relaunch resumes from epoch 2 — the
    # corrupted epoch-3 checkpoint lost discovery to the previous
    # complete one.
    assert resumes == ["0", "2"], out[-3000:]
    assert out.count("TRAINING COMPLETE") == 1
    # Exactly one restart (the corrupt+SIGKILL); the SIGKILL death lands
    # in the oom-kill class (exit 137 — indistinguishable from the host
    # OOM killer by exit status alone).
    restarts = [r for r in _journal(log) if r["name"] == "restarts"]
    assert len(restarts) == 1 and restarts[0]["kind"] == "oom-kill"
    # The final epoch re-earned its checkpoint; the corrupt artifact was
    # discarded on resume and later re-written intact.
    from horovod_tpu import checkpoint as ckpt

    latest = ckpt.latest_checkpoint(str(model_dir))
    assert latest and latest.endswith("checkpoint-6.msgpack")
    assert ckpt.checkpoint_intact(latest)
