"""`analysis.hlo_audit` + `hvt-audit` — the compiled-program auditor
(ISSUE 9 layer 2).

Parser units run over handcrafted fixtures of BOTH text dialects jax
emits (lowered StableHLO, post-optimization HLO), then the integration
tests audit real lowered trainer steps through `analysis.step_probe` —
the same plumbing bench.py and the migrated perf-path tests ride. The
CLI subprocess tests pin the exit-code contract (0 clean / 1 violation
/ 2 usage) and are the tier-1 gate for the canonical K=4 + int8 step:
`hvt-audit step` must fail loudly when the HVT_OVERLAP_REDUCTION or
compression invariants are off.
"""

import os
import subprocess
import sys
import textwrap

import pytest

from horovod_tpu.analysis import hlo_audit

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# --- fixture programs -------------------------------------------------------

STABLEHLO_SAMPLE = textwrap.dedent("""\
    module @jit_train_step {
      func.func public @main(%arg0: tensor<2410xf32>) -> tensor<2410xf32> {
        %0 = stablehlo.while ... {
          %w = stablehlo.add %arg0, %arg0 : tensor<2410xf32>
        }
        %144 = "stablehlo.all_gather"(%143) <{all_gather_dim = 0 : i64,
            channel_handle = #stablehlo.channel_handle<handle = 1, type = 1>
        }> : (tensor<301xi8>) -> tensor<8x301xi8>
        %146 = "stablehlo.all_gather"(%145) <{all_gather_dim = 0 : i64
        }> : (tensor<f32>) -> tensor<8xf32>
        %177 = "stablehlo.all_reduce"(%112) <{channel_handle =
            #stablehlo.channel_handle<handle = 3, type = 1>}> ({
        ^bb0(%a: tensor<f32>, %b: tensor<f32>):
          %s = stablehlo.add %a, %b : tensor<f32>
          stablehlo.return %s : tensor<f32>
        }) : (tensor<f32>) -> tensor<f32>
        %180 = "stablehlo.all_reduce"(%113) <{channel_handle =
            #stablehlo.channel_handle<handle = 4, type = 1>}> ({
        ^bb0(%a: tensor<f32>, %b: tensor<f32>):
          %s = stablehlo.add %a, %b : tensor<f32>
          stablehlo.return %s : tensor<f32>
        }) : (tensor<2410xbf16>) -> tensor<2410xbf16>
      }
    }
""")

HLO_SAMPLE = textwrap.dedent("""\
    HloModule jit_train_step, is_scheduled=true, input_output_alias={ {0}: (0, {}, may-alias), {1}: (2, {}, may-alias) }, entry_computation_layout={...}

    %region_17.445 (x: f32[], y: f32[]) -> f32[] {
      ROOT %add = f32[] add(f32[] %x, f32[] %y)
    }

    ENTRY %main {
      %while.19 = (s32[], f32[2410]{0}) while((s32[], f32[2410]{0}) %tuple.5), condition=%cond, body=%body
      %all-reduce.6 = f32[2410]{0} all-reduce(f32[2410]{0} %convert_fusion), channel_id=1, replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=%region_17.445
      %all-reduce.3 = f32[] all-reduce(f32[] %add_fusion), channel_id=2, to_apply=%region_17.445
      %ag = (s8[8,2410]{1,0}, s8[8,2410]{1,0}) all-gather-start(s8[2410]{0} %q), channel_id=3, dimensions={0}
      %ag-d = s8[8,2410]{1,0} all-gather-done((s8[8,2410]{1,0}, s8[8,2410]{1,0}) %ag)
      %scales = f32[8]{0} all-gather(f32[] %scale), channel_id=4, dimensions={0}
      %use = f32[2410]{0} fusion(f32[2410]{0} %all-reduce.6), kind=kLoop
    }
""")


class TestParsers:
    def test_stablehlo_ops_and_order(self):
        ops = hlo_audit.collective_ops(STABLEHLO_SAMPLE)
        assert [(o.kind, o.dtype, o.shape) for o in ops] == [
            ("all-gather", "i8", (8, 301)),
            ("all-gather", "f32", (8,)),
            ("all-reduce", "f32", ()),
            ("all-reduce", "bf16", (2410,)),
        ]
        assert [o.index for o in ops] == [0, 1, 2, 3]

    def test_hlo_ops_skip_done_and_uses(self):
        """The -done completion and operand USES of a collective's value
        must not double-count; -start counts once; s8 canonicalizes to
        i8; tuple result types count the op once."""
        ops = hlo_audit.collective_ops(HLO_SAMPLE)
        assert [(o.kind, o.dtype, o.shape) for o in ops] == [
            ("all-reduce", "f32", (2410,)),
            ("all-reduce", "f32", ()),
            ("all-gather", "i8", (8, 2410)),
            ("all-gather", "f32", (8,)),
        ]

    def test_gradient_discrimination(self):
        """The shared bench discrimination: scalar all-reduces (metric
        means) and rank-1 gathers (quantized-wire per-bucket scales) are
        NOT gradient traffic; non-scalar all-reduces and rank>=2 payload
        gathers are."""
        for sample in (STABLEHLO_SAMPLE, HLO_SAMPLE):
            grads = hlo_audit.gradient_reductions(sample)
            assert len(grads) == 2
            kinds = {(o.kind, o.rank) for o in grads}
            assert ("all-gather", 2) in kinds
            assert all(
                not (o.kind == "all-gather" and o.rank < 2) for o in grads
            )
            assert all(not o.scalar for o in grads)

    def test_while_count_both_dialects(self):
        assert hlo_audit.while_count(STABLEHLO_SAMPLE) == 1
        assert hlo_audit.while_count(HLO_SAMPLE) == 1

    def test_donated_args_hlo_header(self):
        assert hlo_audit.donated_args(HLO_SAMPLE) == [0, 2]

    def test_donated_args_stablehlo_markers(self):
        text = (
            'func.func public @main(%arg0: tensor<4xf32> '
            '{tf.aliasing_output = 0 : i32}, %arg1: tensor<4xf32>, '
            '%arg2: tensor<4xf32> {jax.buffer_donor = true}) '
            "stablehlo.add"
        )
        assert len(hlo_audit.donated_args(text)) == 2

    def test_wire_dtype_aliases(self):
        assert hlo_audit.wire_dtype("int8") == "i8"
        assert hlo_audit.wire_dtype("fp8") == "f8e4m3"
        assert hlo_audit.wire_dtype("BF16") == "bf16"
        assert hlo_audit.wire_dtype("none") == "f32"
        with pytest.raises(ValueError, match="unknown wire"):
            hlo_audit.wire_dtype("int4")


class TestExpectations:
    def test_parse_grammar(self):
        e = hlo_audit.ProgramExpectation.parse(
            "one-reduction,wire=int8,donates=2"
        )
        assert e.gradient_reductions == 1
        assert e.wire == "int8"
        assert e.min_donated == 2
        e2 = hlo_audit.ProgramExpectation.parse(
            "reductions=3,max-reductions=4,no-collectives"
        )
        assert e2.gradient_reductions == 3
        assert e2.max_gradient_reductions == 4
        assert e2.no_explicit_collectives

    def test_parse_rejects_unknown_token(self):
        with pytest.raises(ValueError, match="unknown expectation"):
            hlo_audit.ProgramExpectation.parse("one-reduction,bogus=1")
        with pytest.raises(ValueError, match="unknown wire"):
            hlo_audit.ProgramExpectation.parse("wire=int4")

    def test_parse_scatter_tokens(self):
        e = hlo_audit.ProgramExpectation.parse("scatter-reduction")
        assert e.scatter_mode and e.scatter_reductions is None
        e2 = hlo_audit.ProgramExpectation.parse("scatters=2,wire=bf16")
        assert e2.scatter_mode and e2.scatter_reductions == 2
        assert e2.wire == "bf16"

    def test_scatter_mode_forbids_full_payload_all_reduce(self):
        """HLO_SAMPLE carries a gradient-shaped f32 all-reduce — in
        scatter mode that is THE violation (the reduction must lower
        into the sharded update's layout), reported alongside the
        missing scatter ops."""
        with pytest.raises(hlo_audit.ProgramAuditError) as e:
            hlo_audit.assert_program(HLO_SAMPLE, "scatter-reduction")
        msg = str(e.value)
        assert "forbids full-payload all-reduce" in msg
        assert "expected scatter-form" in msg

    def test_scatter_reductions_discrimination(self):
        """reduce-scatters and rank >= 2 all-to-alls count; all-gathers
        (param reassembly) and scalar ops never do — both dialects."""
        stablehlo = (
            '%0 = "stablehlo.reduce_scatter"(%a) <{scatter_dimension = 0'
            ' : i64}> : (tensor<2400xf32>) -> tensor<300xf32>\n'
            '%1 = "stablehlo.all_to_all"(%b) <{split_count = 8 : i64}> :'
            " (tensor<8x301xi8>) -> tensor<8x301xi8>\n"
            '%2 = "stablehlo.all_gather"(%c) <{all_gather_dim = 0 : i64'
            "}> : (tensor<301xi8>) -> tensor<8x301xi8>\n"
        )
        ops = hlo_audit.scatter_reductions(stablehlo)
        assert [(o.kind, o.dtype) for o in ops] == [
            ("reduce-scatter", "f32"), ("all-to-all", "i8"),
        ]
        hlo = (
            "ENTRY %main {\n"
            "  %rs = f32[300]{0} reduce-scatter(f32[2400]{0} %g), "
            "channel_id=1, dimensions={0}\n"
            "  %aa = s8[8,301]{1,0} all-to-all(s8[8,301]{1,0} %q), "
            "channel_id=2\n"
            "}\n"
        )
        ops2 = hlo_audit.scatter_reductions(hlo)
        assert [(o.kind, o.dtype) for o in ops2] == [
            ("reduce-scatter", "f32"), ("all-to-all", "i8"),
        ]

    def test_parse_alltoalls_token(self):
        e = hlo_audit.ProgramExpectation.parse("alltoalls=2,wire=f32")
        assert e.alltoalls == 2 and e.wire == "f32"

    def test_payload_alltoalls_discrimination_both_dialects(self):
        """Rank >= 2 all-to-alls count (dispatch/combine payloads, the
        quantized wire's reduce-scatter shot); rank-1 all-to-alls are
        scale/column movement and never do — both dialects."""
        stablehlo = (
            '%0 = "stablehlo.all_to_all"(%a) <{split_count = 8 : i64}> :'
            " (tensor<8x301xi8>) -> tensor<8x301xi8>\n"
            '%1 = "stablehlo.all_to_all"(%s) <{split_count = 8 : i64}> :'
            " (tensor<8xf32>) -> tensor<8xf32>\n"
        )
        ops = hlo_audit.payload_alltoalls(stablehlo)
        assert [(o.kind, o.dtype, o.rank) for o in ops] == [
            ("all-to-all", "i8", 2),
        ]
        hlo = (
            "ENTRY %main {\n"
            "  %aa = f32[8,16]{1,0} all-to-all(f32[8,16]{1,0} %x), "
            "channel_id=1\n"
            "  %sc = f32[8]{0} all-to-all(f32[8]{0} %s), channel_id=2\n"
            "}\n"
        )
        ops2 = hlo_audit.payload_alltoalls(hlo)
        assert [(o.kind, o.rank) for o in ops2] == [("all-to-all", 2)]

    def test_alltoalls_count_violation_names_exclusions(self):
        text = (
            '%0 = "stablehlo.all_to_all"(%a) <{split_count = 8 : i64}> :'
            " (tensor<8x301xi8>) -> tensor<8x301xi8>\n"
            '%1 = "stablehlo.all_to_all"(%s) <{split_count = 8 : i64}> :'
            " (tensor<8xf32>) -> tensor<8xf32>\n"
        )
        violations = hlo_audit.audit(
            text, hlo_audit.ProgramExpectation.parse("alltoalls=2")
        )
        assert violations
        assert "found 1" in violations[0]
        assert "rank-1 scale/column" in violations[0]
        hlo_audit.assert_program(text, "alltoalls=1")  # the true count

    def test_op_bytes_by_kind_in_expectation_diffs(self):
        """A failed count carries the per-kind payload-byte totals —
        where the wire bytes actually went is the first question."""
        with pytest.raises(hlo_audit.ProgramAuditError) as e:
            hlo_audit.assert_program(HLO_SAMPLE, "one-reduction")
        msg = str(e.value)
        assert "payload op_bytes by kind:" in msg
        assert f"all-reduce={2410 * 4}" in msg
        assert f"all-gather={8 * 2410}" in msg
        totals = hlo_audit.op_bytes_by_kind(HLO_SAMPLE)
        # The scalar all-reduce and the rank-1 scale gather contribute 0.
        assert totals == {
            "all-reduce": 2410 * 4, "all-gather": 8 * 2410,
        }

    def test_op_bytes(self):
        op = hlo_audit.CollectiveOp(
            kind="all-to-all", dtype="i8", shape=(8, 301), line=1, index=0
        )
        assert hlo_audit.op_bytes(op) == 8 * 301
        op32 = hlo_audit.CollectiveOp(
            kind="all-reduce", dtype="f32", shape=(2410,), line=1, index=0
        )
        assert hlo_audit.op_bytes(op32) == 2410 * 4

    def test_assert_program_structured_diff(self):
        """The failure message is a structured diff — expected counts,
        every observed op with dtype/shape/line — not a regex mismatch."""
        with pytest.raises(hlo_audit.ProgramAuditError) as e:
            hlo_audit.assert_program(
                HLO_SAMPLE, "one-reduction,wire=int8"
            )
        msg = str(e.value)
        assert "expected exactly 1 gradient reduction(s)" in msg
        assert "found 2" in msg
        assert "all-reduce f32[2410]" in msg
        assert "off-wire traffic" in msg

    def test_wire_on_empty_program_is_a_violation(self):
        with pytest.raises(hlo_audit.ProgramAuditError,
                           match="NO gradient reductions"):
            hlo_audit.assert_program("HloModule empty", "wire=bf16")

    def test_clean_expectations_pass(self):
        hlo_audit.assert_program(HLO_SAMPLE, "reductions=2,donates=2")
        assert hlo_audit.audit(
            "HloModule empty", hlo_audit.ProgramExpectation.parse(
                "no-collectives"
            )
        ) == []


class TestRealPrograms:
    """Integration over real lowered steps via the shared probe."""

    def test_int8_step_audits_one_i8_payload_gather(self):
        import horovod_tpu as hvt
        from horovod_tpu.analysis import step_probe

        hvt.init()
        x, y = step_probe.probe_data()
        text = step_probe.lowered_step_text(
            step_probe.build_trainer(2, "int8"), x, y, 2
        )
        hlo_audit.assert_program(text, "one-reduction,wire=int8")
        grads = hlo_audit.gradient_reductions(text)
        assert [(o.kind, o.dtype) for o in grads] == [("all-gather", "i8")]
        # The two-shot wire (PR 10): one i8 all-to-all (the reduce-
        # scatter shot) + the counted i8 chunk gather, with TWO rank-1
        # f32 scale gathers (one per shot) in the program but not in
        # the count.
        ops = hlo_audit.collective_ops(text)
        assert [
            (o.kind, o.dtype) for o in ops if o.kind == "all-to-all"
        ] == [("all-to-all", "i8")]
        scale_gathers = [
            o for o in ops if o.kind == "all-gather" and o.rank == 1
        ]
        assert len(scale_gathers) == 2
        assert all(o.dtype == "f32" for o in scale_gathers)

    def test_compiled_step_donation_extracted(self):
        """The donated TrainState surfaces as input_output_alias entries
        in the compiled HLO — `donates=1` is auditable."""
        import horovod_tpu as hvt
        from horovod_tpu.analysis import step_probe

        hvt.init()
        x, y = step_probe.probe_data()
        tr = step_probe.build_trainer(1, "none", error_feedback=False)
        # Reuse the probe plumbing up to lowering, then compile.
        import jax.numpy as jnp

        from horovod_tpu.parallel import sharding as sharding_lib

        state = tr.build(x[: tr.dp_size])
        batch = tr._shard((x[:32], y[:32]))
        acc = sharding_lib.replicate(tr.zero_metrics(), tr.mesh)
        ctext = tr._train_step.lower(
            state, batch, jnp.asarray(1.0, jnp.float32), acc
        ).compile().as_text()
        assert len(hlo_audit.donated_args(ctext)) >= 1
        hlo_audit.assert_program(ctext, "donates=1")


def _run_audit(args, env=None):
    full_env = dict(os.environ)
    if env:
        full_env.update(env)
    return subprocess.run(
        [sys.executable, "-m", "horovod_tpu.analysis.audit_cli"] + args,
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env=full_env,
    )


class TestAuditCLI:
    """Exit-code contract + the canonical K=4 + int8 tier-1 gate."""

    def test_canonical_k4_int8_step_gate(self):
        """THE CI gate (ISSUE 9): the canonical accumulating int8 step
        carries exactly one i8 payload reduction AND the overlap peel —
        asserted end to end through the real CLI against a freshly
        lowered program."""
        proc = _run_audit([
            "step", "--platform", "cpu", "--k", "4",
            "--compression", "int8",
            "--expect", "one-reduction,wire=int8,overlap",
        ])
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "ok" in proc.stdout and "overlap peel verified" in proc.stdout

    def test_canonical_k4_zero1_int8_step_gate(self):
        """THE composed-path CI gate (ISSUE 10): K=4 + shard_update +
        int8 compiles to exactly ONE bucketed scatter-form reduction per
        optimizer step (no full-payload all-reduce), wire dtype i8 on
        the lowered StableHLO, and the overlap peel still holds —
        end to end through the real CLI."""
        proc = _run_audit([
            "step", "--platform", "cpu", "--k", "4", "--zero1",
            "--compression", "int8",
            "--expect", "scatters=1,wire=int8,overlap",
        ])
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "ok" in proc.stdout and "overlap peel verified" in proc.stdout

    def test_canonical_k4_zero1_overlap_gate(self):
        """THE ISSUE 12 acceptance gate: the UNCOMPRESSED composed step
        (K=4 + shard_update) packs every leaf — tail family included —
        into ONE leaf-aligned scatter bucket, and the overlap peel
        holds with the scatter count UNCHANGED between the peeled and
        serialized programs (the peel re-schedules the buckets, it must
        not re-bucket the reduction)."""
        proc = _run_audit([
            "step", "--platform", "cpu", "--k", "4", "--zero1",
            "--expect", "scatters=1,overlap",
        ])
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "ok" in proc.stdout and "overlap peel verified" in proc.stdout

    def test_quantized_ici_two_hop_audits_shape(self):
        """--dcn fakes the two-hop factoring and --compression-ici int8
        puts the quantized wire on its ICI hop: the derived expectation
        degrades to the shape-only scatter-reduction (the hop-1 payload
        all-to-all rides next to the hop-2 reduce-scatter, so exact
        counts depend on the factoring) and the program passes it."""
        proc = _run_audit([
            "step", "--platform", "cpu", "--k", "4", "--zero1",
            "--dcn", "2", "--compression-ici", "int8",
        ])
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "derived --expect scatter-reduction" in proc.stdout

    def test_zero1_gate_derives_scatter_expectation(self):
        """`--zero1` without --expect derives the scatter-form
        expectation (scatters=1 for the quantized dense layout)."""
        proc = _run_audit([
            "step", "--platform", "cpu", "--k", "4", "--zero1",
            "--compression", "int8",
        ])
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "derived --expect scatters=1,wire=int8" in proc.stdout

    def test_moe_dispatch_combine_gate(self):
        """THE EP wire gate (ISSUE 14 satellite of ROADMAP item 4): the
        MoE probe's dispatch/combine lowers to exactly TWO payload
        all-to-alls through `collectives.all_to_all` — asserted end to
        end through the real CLI against a freshly lowered program."""
        proc = _run_audit(["moe", "--platform", "cpu"])
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "derived --expect alltoalls=2" in proc.stdout
        assert "2 payload all-to-all(s)" in proc.stdout

    def test_moe_gate_wrong_count_fails(self):
        proc = _run_audit([
            "moe", "--platform", "cpu", "--expect", "alltoalls=3",
        ])
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "payload all-to-all" in proc.stdout

    def test_overlap_knob_off_fails_gate(self):
        """HVT_OVERLAP_REDUCTION=0 must fail the overlap expectation —
        the structural gate catches a fleet de-overlapped by env."""
        proc = _run_audit([
            "step", "--platform", "cpu", "--k", "4",
            "--compression", "int8",
            "--expect", "one-reduction,wire=int8,overlap",
        ], env={"HVT_OVERLAP_REDUCTION": "0"})
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "overlap" in proc.stdout

    def test_wire_violation_fails(self):
        """An uncompressed step audited against wire=int8 exits 1 with
        the off-wire op in the diff (the compression invariant)."""
        proc = _run_audit([
            "step", "--platform", "cpu", "--k", "2",
            "--compression", "none", "--expect", "wire=int8",
        ])
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "off-wire" in proc.stdout

    def test_usage_error_exits_2(self):
        proc = _run_audit(["step", "--expect", "bogus=1"])
        assert proc.returncode == 2
        assert "unknown expectation" in proc.stderr

    def test_file_subcommand(self, tmp_path):
        p = tmp_path / "step.hlo"
        p.write_text(HLO_SAMPLE)
        ok = _run_audit(["file", str(p), "--expect", "reductions=2"])
        assert ok.returncode == 0, ok.stdout + ok.stderr
        bad = _run_audit(["file", str(p), "--expect", "one-reduction"])
        assert bad.returncode == 1
        assert "found 2" in bad.stdout
        missing = _run_audit(
            ["file", str(tmp_path / "nope.hlo"), "--expect", "reductions=1"]
        )
        assert missing.returncode == 2
