"""Overlapped boundary reduction + quantized (int8/fp8) wire compression.

PR 7's proof obligations:

* The overlapped step (last microbatch peeled out of the accumulation
  scan, bucket reductions issued in its straight-line region, reverse
  bucket order) is NUMERICALLY EQUIVALENT to the serialized post-scan
  reduction — same grads to the optimizer across K x bucket_bytes x
  compression.
* int8/fp8 wires really change the emitted collective: the reduction is a
  gather-sum whose payload element type is i8 / f8E4M3, with no
  gradient-shaped f32 all-reduce left.
* Error feedback telescopes: over T steps the accumulated quantization
  error is bounded by ONE step's quantum (|psum(r_T)|), not T of them —
  the bias does not compound.
* The error-feedback residual lives in opt_state (`ErrorFeedbackState`),
  survives a checkpoint save/restore roundtrip, and an elastic reshard
  re-cuts it mass-conserving.
* bench.py's phase guard rejects any phase exceeding step_ms.total.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvt
from horovod_tpu import checkpoint, compat
from horovod_tpu.analysis import hlo_audit, registry
from horovod_tpu.analysis.step_probe import lowered_step_text
from horovod_tpu.parallel import collectives, mesh as mesh_lib
from horovod_tpu.training.optimizer import (
    ErrorFeedbackState,
    compression_error_feedback,
)


class Probe(nn.Module):
    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.reshape((x.shape[0], -1)).astype(jnp.float32)
        return nn.Dense(10)(nn.relu(nn.Dense(32)(x)))


def _data(n=256, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 8, 8, 1).astype(np.float32)
    y = rng.randint(0, 10, n).astype(np.int32)
    return x, y


def _trainer(k=1, compression="none", overlap=None, bucket_bytes=None,
             bucket_order=None, error_feedback=True, seed=3):
    tx = hvt.DistributedOptimizer(
        optax.adam(1e-3), backward_passes_per_step=k,
        average_aggregated_gradients=True, compression=compression,
        error_feedback=error_feedback,
    )
    return hvt.Trainer(
        Probe(), tx, seed=seed, bucket_bytes=bucket_bytes,
        overlap_reduction=overlap, bucket_order=bucket_order,
    )


def _fit_params(tr, x, y, k, steps=4):
    tr.fit(x=x, y=y, batch_size=max(1, 8 // k), epochs=1,
           steps_per_epoch=steps, shuffle_buffer=1, verbose=0)
    return jax.tree.leaves(jax.device_get(tr.state.params))


# Lowered-step plumbing + the gradient-traffic discrimination live in
# `analysis.step_probe` / `analysis.hlo_audit` since PR 9 (one
# implementation, shared with bench.py and `hvt-audit`).


class TestOverlapEquivalence:
    @pytest.mark.parametrize(
        "k,bucket_bytes,compression",
        [
            (1, None, "none"),
            (4, None, "none"),
            (4, 1024, "none"),
            (4, 1024, "bf16"),
            (1, 1024, "int8"),
            (4, 1024, "int8"),
        ],
    )
    def test_same_grads_to_optimizer(self, k, bucket_bytes, compression):
        """THE acceptance property: overlap on vs off changes compiled
        STRUCTURE only — same addition order, same bucket contents — so
        the trained parameters must agree to float-scheduling noise on
        every (K, bucket_bytes, compression) combination."""
        x, y = _data()
        p_on = _fit_params(
            _trainer(k, compression, overlap=True,
                     bucket_bytes=bucket_bytes), x, y, k,
        )
        p_off = _fit_params(
            _trainer(k, compression, overlap=False,
                     bucket_bytes=bucket_bytes), x, y, k,
        )
        for a, b in zip(p_on, p_off):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)

    def test_reverse_vs_forward_bucket_order_identical(self):
        """Reverse issue order re-partitions the leaves into different
        buckets, but a psum is elementwise — the reduced VALUES cannot
        depend on bucket boundaries for non-quantized wires."""
        x, y = _data()
        p_rev = _fit_params(
            _trainer(4, bucket_bytes=1024, bucket_order="reverse"), x, y, 4
        )
        p_fwd = _fit_params(
            _trainer(4, bucket_bytes=1024, bucket_order="forward"), x, y, 4
        )
        for a, b in zip(p_rev, p_fwd):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    def test_overlap_peels_last_microbatch_out_of_scan(self):
        """Structural: at K=2 the overlapped step has NO accumulation scan
        left (microbatch 0 inline, microbatch 1 peeled) while the
        serialized step scans — visible as strictly fewer while ops in
        the lowered text."""
        x, y = _data()
        whiles_on = hlo_audit.while_count(lowered_step_text(
            _trainer(2, "bf16", overlap=True), x, y, 2
        ))
        whiles_off = hlo_audit.while_count(lowered_step_text(
            _trainer(2, "bf16", overlap=False), x, y, 2
        ))
        assert whiles_on < whiles_off

    def test_one_reduction_per_step_still_holds(self):
        """Overlap must not reintroduce per-microbatch communication: the
        K=4 overlapped step still carries exactly the bucket count of
        gradient-shaped collectives (one here — default bucket bytes)."""
        x, y = _data()
        hlo_audit.assert_program(
            lowered_step_text(_trainer(4, "bf16", overlap=True), x, y, 4),
            "one-reduction,wire=bf16",
        )

    def test_knob_defaults(self, monkeypatch):
        assert _trainer()._overlap is True  # HVT_OVERLAP_REDUCTION default
        assert _trainer()._bucket_reverse is True  # HVT_BUCKET_ORDER default
        monkeypatch.setenv("HVT_OVERLAP_REDUCTION", "0")
        assert _trainer()._overlap is False
        monkeypatch.setenv("HVT_BUCKET_ORDER", "forward")
        assert _trainer()._bucket_reverse is False

    def test_bad_bucket_order_is_loud(self):
        with pytest.raises(ValueError, match="bucket_order"):
            _trainer(bucket_order="sideways")


class TestQuantizedWire:
    def test_int8_wire_is_int8_on_the_wire(self):
        """The lowered int8 step's gradient traffic is the per-bucket
        payload gather in i8 (the rank-1 f32 scale gather stays out of
        the count); no gradient-shaped f32 all_reduce remains."""
        x, y = _data()
        hlo_audit.assert_program(
            lowered_step_text(_trainer(2, "int8"), x, y, 2),
            "one-reduction,wire=int8",
        )

    def test_fp8_wire_is_f8_on_the_wire(self):
        x, y = _data()
        hlo_audit.assert_program(
            lowered_step_text(_trainer(2, "fp8"), x, y, 2),
            "one-reduction,wire=fp8",
        )

    def test_quantized_with_axis_name_rejected(self):
        with pytest.raises(ValueError, match="overflow"):
            hvt.DistributedOptimizer(
                optax.adam(1e-3), axis_name="data", compression="int8"
            )

    @pytest.mark.parametrize("wire", [jnp.int8, jnp.float8_e4m3fn])
    def test_error_feedback_telescopes(self, wire):
        """EF's defining property, asserted deterministically at the
        collectives level: feeding the SAME per-shard gradients for T
        rounds while carrying the residual, the summed outputs differ from
        T x the true sum by at most |psum(r_T)| — ONE round's quantization
        quantum, not T of them (the errors telescope)."""
        hvt.init()
        mesh = mesh_lib.data_parallel_mesh()
        P = jax.sharding.PartitionSpec

        def one_round(v, r):
            out, new_r = collectives.reduce_gradients(
                {"g": v}, data_axis="data", extra_axes=("fsdp",),
                wire_dtype=wire, bucket_bytes=1 << 20,
                residual={"g": r},
            )
            return out["g"], new_r["g"]

        f = jax.jit(compat.shard_map(
            one_round, mesh=mesh,
            in_specs=(P(("data", "fsdp")), P(("data", "fsdp"))),
            out_specs=(P(("data", "fsdp")), P(("data", "fsdp"))),
            check_vma=False,
        ))
        rng = np.random.RandomState(0)
        v = jnp.asarray(rng.randn(8, 64).astype(np.float32))
        r = jnp.zeros_like(v)
        T = 6
        acc = np.zeros((8, 64), np.float32)
        for _ in range(T):
            out, r = f(v, r)
            acc += np.asarray(out)
        true = np.broadcast_to(np.asarray(v).sum(0, keepdims=True), v.shape)
        # The telescoping IDENTITY: out_t = psum(Q(g + r_t)) and
        # r_{t+1} = g + r_t - Q(g + r_t), so sum_t out_t = T*true -
        # psum(r_T) exactly — the accumulated error is ONE final
        # residual, not T rounds' worth.
        r_np = np.asarray(r)  # global view: row s = shard s's residual
        np.testing.assert_allclose(
            (T * true - acc)[0], r_np.sum(axis=0), rtol=1e-3, atol=1e-4
        )
        # And that final residual is single-round-sized: per element at
        # most one rounding quantum of the wire format (int8: half-grid
        # amax/127 with slack; e4m3 fp8: relative ulp 2^-3 of the top
        # bin, amax/16 absolute), summed over the 8 shards — a bound T
        # independent no-feedback rounds would exceed T-fold.
        amax = float(np.abs(np.asarray(v)).max())
        quantum = amax / 127.0 if wire == jnp.int8 else amax / 16.0
        bound = 8 * quantum + 1e-5
        np.testing.assert_array_less(np.abs(acc - T * true), bound)

    def test_residual_lives_in_opt_state_and_updates(self):
        x, y = _data()
        tr = _trainer(2, "int8")
        assert tr._ef and compression_error_feedback.__name__  # wired
        tr.fit(x=x, y=y, batch_size=4, epochs=1, steps_per_epoch=2,
               shuffle_buffer=1, verbose=0)
        opt_state = tr.state.opt_state
        assert isinstance(opt_state, ErrorFeedbackState)
        res = jax.device_get(opt_state.ef_residual)
        dp = tr.dp_size
        for leaf, p in zip(
            jax.tree.leaves(res), jax.tree.leaves(tr.state.params)
        ):
            assert leaf.shape == (dp,) + p.shape
            assert leaf.dtype == np.float32
        # After real steps the untransmitted remainder is nonzero.
        assert any(np.abs(l).max() > 0 for l in jax.tree.leaves(res))

    def test_error_feedback_off_keeps_plain_opt_state(self):
        tr = _trainer(2, "int8", error_feedback=False)
        assert not tr._ef
        x, _ = _data(16)
        tr.build(x[:8])
        assert not isinstance(tr.state.opt_state, ErrorFeedbackState)

    def test_loss_tracks_uncompressed(self):
        """int8+EF is lossy in the last bits, not in convergence: after a
        few steps the loss tracks the uncompressed run."""
        x, y = _data()
        l_q = _fit_params  # appease linters; real check below
        t_q = _trainer(1, "int8")
        t_f = _trainer(1, "none")
        h_q = t_q.fit(x=x, y=y, batch_size=8, epochs=1, steps_per_epoch=8,
                      shuffle_buffer=1, verbose=0)
        h_f = t_f.fit(x=x, y=y, batch_size=8, epochs=1, steps_per_epoch=8,
                      shuffle_buffer=1, verbose=0)
        assert abs(h_q[-1]["loss"] - h_f[-1]["loss"]) / max(
            abs(h_f[-1]["loss"]), 1e-6
        ) < 0.1

    def test_device_cached_path_composes(self):
        x, y = _data(512)
        tr = _trainer(2, "int8")
        hist = tr.fit(x=x, y=y, batch_size=2, epochs=3, cache="device",
                      verbose=0)
        assert hist[-1]["loss"] < hist[0]["loss"]


class TestResidualStateSurfaces:
    def _trained(self, steps=2):
        x, y = _data()
        tr = _trainer(2, "int8")
        tr.fit(x=x, y=y, batch_size=4, epochs=1, steps_per_epoch=steps,
               shuffle_buffer=1, verbose=0)
        return tr

    def test_checkpoint_roundtrip_preserves_residual(self, tmp_path):
        tr = self._trained()
        path = str(tmp_path / "state.msgpack")
        checkpoint.save(path, tr.state)
        tr2 = _trainer(2, "int8")
        x, y = _data()
        tr2.build(x[:8], y[:8])
        restored = checkpoint.restore(path, tr2.state)
        a = jax.device_get(tr.state.opt_state.ef_residual)
        b = jax.device_get(restored.opt_state.ef_residual)
        jax.tree.map(
            lambda u, v: np.testing.assert_array_equal(u, v), a, b
        )

    def test_elastic_reshard_conserves_residual_mass(self):
        """install_state with a snapshot from a DIFFERENT world size: the
        residual's leading (shard) axis is re-cut mass-conserving — the
        old shards' remainders sum-redistribute over the new axis (there
        is no per-shard ground truth after a reshard; EF correctness
        only needs the total eventually added back)."""
        tr = self._trained()
        snap = jax.device_get(tr.state)
        # Fake an old 2-shard world's residual with known mass.
        old = jax.tree.map(
            lambda p: np.stack([
                np.full(p.shape, 1.0, np.float32),
                np.full(p.shape, 3.0, np.float32),
            ]),
            jax.device_get(tr.state.params),
        )
        snap = snap.replace(
            opt_state=snap.opt_state.replace(ef_residual=old)
        )
        installed = tr.install_state(snap)
        res = jax.device_get(installed.opt_state.ef_residual)
        dp = tr.dp_size
        for leaf in jax.tree.leaves(res):
            # total mass 4.0 per element, spread evenly over dp shards
            np.testing.assert_allclose(leaf.sum(axis=0), 4.0, rtol=1e-6)
            np.testing.assert_allclose(leaf, 4.0 / dp, rtol=1e-6)

    def test_same_world_snapshot_installs_verbatim(self):
        tr = self._trained()
        snap = jax.device_get(tr.state)
        want = jax.tree.map(np.asarray, snap.opt_state.ef_residual)
        installed = tr.install_state(snap)
        got = jax.device_get(installed.opt_state.ef_residual)
        jax.tree.map(
            lambda u, v: np.testing.assert_array_equal(u, v), want, got
        )


class TestBenchPhaseGuard:
    def _guard(self):
        import bench

        return bench._phase_overruns

    def test_consistent_breakdown_passes(self):
        assert self._guard()(
            {"total": 1.0, "compute": 0.5, "comm": 0.2, "input": 0.3}
        ) == []

    def test_phase_exceeding_total_flagged(self):
        # the r04 regression shape: compute 0.281 > total 0.256
        bad = self._guard()(
            {"total": 0.256, "compute": 0.281, "input": 0.0}
        )
        assert "compute" in bad

    def test_phases_summing_past_total_flagged(self):
        bad = self._guard()(
            {"total": 1.0, "compute": 0.7, "comm": 0.2, "input": 0.3}
        )
        assert "sum(phases)" in bad

    def test_missing_breakdown_is_not_an_error(self):
        assert self._guard()({}) == []


class TestKnobRegistry:
    @pytest.mark.parametrize("name", [
        "HVT_OVERLAP_REDUCTION", "HVT_BUCKET_ORDER", "HVT_PREFETCH_DEPTH",
        "HVT_COMPRESSION", "HVT_COMPRESSION_ICI", "HVT_PEAK_FLOPS",
    ])
    def test_new_knobs_declared(self, name):
        assert registry.is_registered(name)

    def test_prefetch_depth_feeds_streamed_fit(self, monkeypatch):
        monkeypatch.setenv("HVT_PREFETCH_DEPTH", "3")
        x, y = _data(64)
        tr = _trainer()
        hist = tr.fit(x=x, y=y, batch_size=8, epochs=1, steps_per_epoch=4,
                      shuffle_buffer=1, verbose=0)
        assert np.isfinite(hist[-1]["loss"])
