"""Native batch-assembly engine (native/hvt_data.cc via ctypes).

Covers the contract `Trainer.fit` relies on: deterministic seeded shuffles,
a fresh full permutation per epoch with no example repeated within one,
batch lifetime/copy semantics, teardown while a consumer is blocked in
``next``, and the `training_pipeline` routing that decides native vs Python.
"""

import os
import threading
import time

import numpy as np
import pytest

from horovod_tpu.data import loader as loader_lib
from horovod_tpu.data import native_loader

pytestmark = pytest.mark.skipif(
    not native_loader.available(), reason="native library unavailable"
)


def _make(n=64, batch=8, seed=7, **kw):
    x = np.arange(n, dtype=np.int64)
    feats = np.stack([x * 10, x * 100], axis=1).astype(np.float32)
    return native_loader.NativeBatchLoader(
        (x, feats), batch, seed=seed, **kw
    )


class TestSemantics:
    def test_rows_stay_aligned(self):
        """Both arrays are gathered with the SAME permutation."""
        loader = _make()
        try:
            for _ in range(20):
                idx, feats = next(loader)
                np.testing.assert_array_equal(feats[:, 0], idx * 10)
                np.testing.assert_array_equal(feats[:, 1], idx * 100)
        finally:
            loader.close()

    def test_epoch_is_full_permutation(self):
        """One epoch (n/batch batches) sees every example exactly once."""
        n, batch = 64, 8
        loader = _make(n=n, batch=batch)
        try:
            for _ in range(3):  # three consecutive epochs
                seen = np.concatenate(
                    [next(loader)[0] for _ in range(n // batch)]
                )
                assert sorted(seen.tolist()) == list(range(n))
        finally:
            loader.close()

    def test_epoch_remainder_dropped(self):
        """batch ∤ n: the per-epoch remainder is dropped, never straddled."""
        n, batch = 30, 8
        loader = _make(n=n, batch=batch)
        try:
            seen = np.concatenate([next(loader)[0] for _ in range(n // batch)])
            # 3 batches × 8 = 24 distinct examples from one permutation.
            assert len(set(seen.tolist())) == 24
        finally:
            loader.close()

    def test_deterministic_across_instances(self):
        a, b = _make(seed=123), _make(seed=123)
        c = _make(seed=124)
        try:
            batches_a = [next(a)[0] for _ in range(10)]
            batches_b = [next(b)[0] for _ in range(10)]
            batches_c = [next(c)[0] for _ in range(10)]
            for xa, xb in zip(batches_a, batches_b):
                np.testing.assert_array_equal(xa, xb)
            assert any(
                not np.array_equal(xa, xc)
                for xa, xc in zip(batches_a, batches_c)
            )
        finally:
            a.close(), b.close(), c.close()

    def test_no_shuffle_is_sequential(self):
        loader = _make(n=32, batch=8, shuffle=False)
        try:
            idx, _ = next(loader)
            np.testing.assert_array_equal(idx, np.arange(8))
            idx, _ = next(loader)
            np.testing.assert_array_equal(idx, np.arange(8, 16))
        finally:
            loader.close()


class TestLifetime:
    def test_copy_batches_survive_iteration(self):
        """copy=True (default): earlier batches stay valid as iteration
        recycles slots — the lifetime `Trainer.fit`'s pending-batch and JAX's
        async device_put require."""
        loader = _make(n=64, batch=8, n_slots=2)
        try:
            held = [next(loader) for _ in range(12)]  # > n_slots recycles
            for idx, feats in held:
                np.testing.assert_array_equal(feats[:, 0], idx * 10)
        finally:
            loader.close()

    def test_view_batches_are_zero_copy_and_recycled(self):
        """copy=False: arrays alias slot storage; valid until the next
        __next__ (documented contract)."""
        loader = _make(n=64, batch=8, seed=5, copy=False)
        try:
            idx1, feats1 = next(loader)
            snap = idx1.copy()
            np.testing.assert_array_equal(feats1[:, 0], snap * 10)
            assert not idx1.flags.owndata  # a view into the slot ring
        finally:
            loader.close()

    def test_close_idempotent_and_stops_iteration(self):
        loader = _make()
        next(loader)
        loader.close()
        loader.close()
        with pytest.raises(StopIteration):
            next(loader)


class TestDestroyWhileBlocked:
    def test_destroy_unblocks_consumer(self):
        """A consumer parked in hvt_loader_next while destroy() runs must be
        woken and drain cleanly — no deadlock, no crash (the C++ side waits
        for consumers to leave next() before freeing)."""
        loader = _make(n=64, batch=8, n_slots=2)
        # Drain all ready slots WITHOUT releasing them: the producer stalls
        # with nothing free, so the next next() call truly blocks.
        raw = loader._lib
        h = loader._handle
        s1 = raw.hvt_loader_next(h)
        s2 = raw.hvt_loader_next(h)
        assert s1 >= 0 and s2 >= 0

        results = []

        def consumer():
            results.append(raw.hvt_loader_next(h))

        t = threading.Thread(target=consumer)
        t.start()
        time.sleep(0.2)  # let it reach the blocking wait
        assert t.is_alive()
        raw.hvt_loader_destroy(h)
        loader._handle = None  # already destroyed; don't double-free in __del__
        t.join(timeout=5)
        assert not t.is_alive()
        assert results == [-1]


class TestPipelineRouting:
    def test_full_shuffle_routes_native(self):
        x = np.arange(40, dtype=np.float32)
        y = np.arange(40, dtype=np.int32)
        it, close = loader_lib.training_pipeline((x, y), 8, seed=3)
        try:
            xb, yb = next(it)
            assert xb.shape == (8,) and yb.shape == (8,)
            np.testing.assert_array_equal(xb.astype(np.int32), yb)
        finally:
            close()

    def test_hvt_no_native_routes_python(self, monkeypatch):
        monkeypatch.setenv("HVT_NO_NATIVE", "1")
        x = np.arange(40, dtype=np.float32)
        it, close = loader_lib.training_pipeline((x, x), 8, seed=3)
        assert close() is None  # python pipeline: close is a no-op lambda
        xb, _ = next(it)
        assert xb.shape == (8,)

    def test_partial_shuffle_routes_python(self):
        """A bounded shuffle buffer has reservoir (not full-permutation)
        semantics — must use the Python pipeline that implements them."""
        x = np.arange(40, dtype=np.float32)
        it, close = loader_lib.training_pipeline(
            (x, x), 8, seed=3, shuffle_buffer=4
        )
        assert close() is None
        next(it)

    def test_python_fallback_matches_native_contract(self):
        """Both routes yield an infinite stream of aligned (x, y) batches."""
        x = np.arange(24, dtype=np.float32)
        y = (x * 2).astype(np.float32)
        for env in ({}, {"HVT_NO_NATIVE": "1"}):
            old = dict(os.environ)
            os.environ.update(env)
            try:
                it, close = loader_lib.training_pipeline((x, y), 6, seed=9)
                try:
                    for _ in range(10):
                        xb, yb = next(it)
                        np.testing.assert_array_equal(xb * 2, yb)
                finally:
                    close()
            finally:
                os.environ.clear()
                os.environ.update(old)
