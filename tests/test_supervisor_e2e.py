"""Supervised fail-restart, end-to-end on CPU (the ISSUE acceptance runs):

* a rank SIGKILLed mid-epoch under `supervise_local` is relaunched
  automatically, resumes from the newest checkpoint, completes all epochs,
  and the journal records exactly one ``crash`` restart;
* a rank that *hangs* (``HVT_FAULT=...:hang``) is caught by stale
  heartbeats — the supervisor kills the fleet, restarts it, and the rerun
  completes;
* a deterministic crash loop (fault fires every launch, no stamp, no
  progress) exhausts ``max_restarts`` and exits with the original code.

All faults are injected with the `horovod_tpu.testing.faults` harness
through env vars only — the training script is the examples' plain resume
idiom and knows nothing about the chaos."""

import json
import os
import sys

import pytest

from horovod_tpu.launch import supervisor
from horovod_tpu.launch.supervisor import RestartPolicy
from tests.test_supervisor import write_train_script

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

EPOCHS = 3


def _env(tmp_path, model_dir, fault, stamp=True):
    env = {
        "HVT_PLATFORM": "cpu",
        "HVT_NUM_CPU_DEVICES": "2",
        "PS_MODEL_PATH": str(model_dir),
        "DRIVE_EPOCHS": str(EPOCHS),
        "HVT_FAULT": fault,
        # The suite's shared persistent XLA cache (conftest) is unsafe for
        # chaos runs: a SIGKILLed rank can tear a cache write and two ranks
        # compiling the same program race the same entry — both observed to
        # SEGFAULT later deserializations on jax 0.4.x. Fault-injected
        # children always compile fresh.
        "JAX_ENABLE_COMPILATION_CACHE": "0",
        "JAX_COMPILATION_CACHE_DIR": "",
    }
    if stamp:
        env["HVT_FAULT_STAMP"] = str(tmp_path / "fault-stamp")
    return env


def _records(log):
    with open(log) as f:
        return [json.loads(line) for line in f if line.strip()]


@pytest.mark.slow
def test_sigkill_mid_epoch_restart_resume_complete(tmp_path, capfd):
    """Rank 1 of a 2-process fleet is SIGKILLed mid-epoch-1; the supervisor
    classifies the crash, relaunches the fleet, and the rerun resumes from
    checkpoint-1 and completes every epoch."""
    argv = write_train_script(tmp_path)
    model_dir = tmp_path / "models"
    log = tmp_path / "restarts.jsonl"
    code = supervisor.supervise_local(
        2, argv,
        env=_env(tmp_path, model_dir, "1:1:kill"),
        # max_restarts=4: headroom for the transient coordination-service
        # aborts a loaded CPU host injects around the real fault (the
        # supervisor absorbing those is its job, not a test failure).
        policy=RestartPolicy(max_restarts=4, backoff=0.0, grace_seconds=5.0),
        model_dir=str(model_dir), log_path=str(log),
        sleep=lambda s: None,
    )
    assert code == 0
    restarts = [r for r in _records(log) if r["name"] == "restarts"]
    # The injected SIGKILL is the first recorded restart. (On a loaded CPU
    # the relaunch can additionally hit a transient coordination-service
    # abort that the supervisor absorbs with a further restart — that is
    # the supervisor doing its job, so only the injected fault is asserted
    # exactly.)
    assert len(restarts) >= 1
    assert any(
        r["kind"] == "oom-kill" and r["exit_code"] == -9  # the SIGKILL death
        for r in restarts
    )
    # The rerun resumed (epoch-1 checkpoint survived the crash) and ran to
    # completion — every epoch checkpoint exists.
    run_dir = model_dir / "run"
    for e in range(1, EPOCHS + 1):
        assert (run_dir / f"checkpoint-{e}.msgpack").exists()
    out = capfd.readouterr().out
    # The relaunch resumed from SOME checkpoint (epoch number can shift by
    # one if an absorbed flake-restart trained further before the fault).
    assert "Resuming from checkpoint epoch" in out
    assert "TRAINING COMPLETE" in out


@pytest.mark.slow
def test_hang_detected_fleet_killed_and_restarted(tmp_path, capfd):
    """Rank 0 wedges mid-epoch-1 (the silent no-exit-code failure mode);
    its peer blocks in the next collective, so EVERY heartbeat goes stale —
    the supervisor kills the fleet, journals a ``hang``, relaunches, and
    the rerun completes."""
    argv = write_train_script(tmp_path)
    model_dir = tmp_path / "models"
    log = tmp_path / "restarts.jsonl"
    code = supervisor.supervise_local(
        2, argv,
        env=_env(tmp_path, model_dir, "0:1:hang"),
        policy=RestartPolicy(
            max_restarts=4, backoff=0.0, grace_seconds=5.0,
            # Above worst-case compile+step gap on CPU, far below test
            # timeout; beats land from train begin onward.
            heartbeat_timeout=20.0,
        ),
        model_dir=str(model_dir), log_path=str(log),
        sleep=lambda s: None,
    )
    assert code == 0
    restarts = [r for r in _records(log) if r["name"] == "restarts"]
    # At least one restart was the stale-heartbeat kill; transient
    # coordination flakes may add absorbed crash restarts around it.
    assert any(r["kind"] == "hang" for r in restarts)
    out = capfd.readouterr().out
    assert "TRAINING COMPLETE" in out


@pytest.mark.slow
def test_deterministic_crash_loop_exhausts_budget(tmp_path):
    """No stamp: the fault fires mid-epoch-0 on EVERY launch, before any
    checkpoint exists — zero progress, so the budget decrements each time
    and the supervisor exits nonzero with the fault's original exit code."""
    argv = write_train_script(tmp_path)
    model_dir = tmp_path / "models"
    log = tmp_path / "restarts.jsonl"
    code = supervisor.supervise_local(
        1, argv,
        env=_env(tmp_path, model_dir, "0:0:exit7", stamp=False),
        policy=RestartPolicy(max_restarts=2, backoff=0.0, grace_seconds=5.0),
        model_dir=str(model_dir), log_path=str(log),
        sleep=lambda s: None,
    )
    assert code == 7  # the original exit code, not a supervisor rewrite
    records = _records(log)
    restarts = [r for r in records if r["name"] == "restarts"]
    assert len(restarts) == 2  # max_restarts, then give up
    assert all(r["kind"] == "crash" and r["exit_code"] == 7
               and not r["progressed"] for r in restarts)
    assert records[-1]["name"] == "supervisor_gave_up"
    # Nothing ever trained past the fault: no checkpoints at all.
    assert not list((model_dir / "run").glob("checkpoint-*")) \
        if (model_dir / "run").exists() else True
