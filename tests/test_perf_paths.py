"""Tests for the performance-path machinery: steps_per_execution (scan-fused
multi-step executions), the device-resident dataset path
(`fit(cache='device')`), the background device prefetcher, and the
trace/FLOPs/MFU accounting."""

import os
import time

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvt
from horovod_tpu import trace
from horovod_tpu.data.prefetch import DevicePrefetcher


class Probe(nn.Module):
    """Deterministic (dropout-free) classifier so execution strategies can be
    compared exactly."""

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.reshape((x.shape[0], -1)).astype(jnp.float32)
        x = nn.relu(nn.Dense(32)(x))
        return nn.Dense(10)(x)


def _digest(params):
    return float(sum(np.abs(l).sum() for l in jax.tree.leaves(jax.device_get(params))))


def _data(n=256, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 8, 8, 1).astype(np.float32)
    y = rng.randint(0, 10, size=n).astype(np.int32)
    return x, y


class TestStepsPerExecution:
    def _fit(self, spe, steps=8, epochs=2):
        x, y = _data()
        trainer = hvt.Trainer(
            Probe(),
            hvt.DistributedOptimizer(optax.sgd(0.05)),
            steps_per_execution=spe,
        )
        trainer.fit(
            x=x, y=y, batch_size=4, epochs=epochs, steps_per_epoch=steps,
            shuffle_buffer=1, verbose=0,
        )
        return trainer

    def test_fused_matches_per_step_math(self):
        """K steps fused in one scan must produce the same parameters as K
        separate step dispatches — fusion is an execution detail."""
        d1 = _digest(self._fit(1).state.params)
        d4 = _digest(self._fit(4).state.params)
        assert d1 == pytest.approx(d4, rel=1e-6)

    def test_remainder_chunk(self):
        """steps_per_epoch not divisible by K: a remainder chunk runs (and
        the epoch metric divisor stays the true step count)."""
        trainer = self._fit(4, steps=7, epochs=1)
        assert len(trainer.history) == 1
        d = _digest(trainer.state.params)
        assert d == pytest.approx(_digest(self._fit(1, steps=7, epochs=1).state.params), rel=1e-6)

    def test_callbacks_fire_once_per_execution(self):
        calls = []

        class Spy(hvt.callbacks.Callback):
            def on_batch_end(self, batch, logs=None):
                calls.append(batch)

        x, y = _data()
        trainer = hvt.Trainer(
            Probe(), hvt.DistributedOptimizer(optax.sgd(0.01)),
            steps_per_execution=4,
        )
        trainer.fit(
            x=x, y=y, batch_size=4, epochs=1, steps_per_epoch=8,
            callbacks=[Spy()], verbose=0,
        )
        assert calls == [3, 7]  # last step index of each execution


class TestDeviceCachedFit:
    def test_trains_and_caps_steps(self):
        x, y = _data(n=512)
        trainer = hvt.Trainer(Probe(), hvt.DistributedOptimizer(optax.adam(5e-3)))
        hist = trainer.fit(
            x=x, y=y, batch_size=4, epochs=3, cache="device", verbose=0,
        )
        assert len(hist) == 3
        # 512 examples / 8 shards / 4 per chip = 16 steps; loss must fall.
        assert hist[-1]["loss"] < hist[0]["loss"]

    def test_deterministic_for_seed(self):
        x, y = _data(n=256)

        def run():
            t = hvt.Trainer(
                Probe(), hvt.DistributedOptimizer(optax.sgd(0.05)), seed=3
            )
            t.fit(x=x, y=y, batch_size=4, epochs=2, cache="device", verbose=0)
            return _digest(t.state.params)

        assert run() == run()

    def test_epoch_visits_every_example_once(self):
        """The on-device permutation must be a true per-shard permutation:
        training on one epoch of one-hot rows with an SGD sum-style probe
        would be hard to observe, so instead check the gather directly — a
        'model' whose loss sums a per-example tag lets the epoch metric count
        every tag exactly once."""
        n = 128

        class TagSum(nn.Module):
            @nn.compact
            def __call__(self, x, *, train: bool = False):
                # Logits independent of params aren't differentiable; add a
                # zero-scaled param so grads exist.
                w = self.param("w", nn.initializers.zeros, (1,))
                return jnp.zeros((x.shape[0], 2)) + w * 0.0

        x = np.arange(n, dtype=np.float32).reshape(n, 1)  # tag = index
        y = np.zeros(n, dtype=np.int32)

        seen = []

        def tag_loss(logits, labels):
            return logits.sum(-1) * 0.0  # keep loss 0; accuracy unused

        trainer = hvt.Trainer(
            TagSum(), hvt.DistributedOptimizer(optax.sgd(0.0)), loss=tag_loss
        )
        # Instead of instrumenting the jit, verify via the staged layout +
        # permutation invariant: run the internal epoch fn and check each
        # shard's gathered indices form a permutation.
        data, per_shard = trainer._stage_device_dataset(x, y)
        assert per_shard == n // trainer.dp_size
        xs = np.asarray(jax.device_get(data[0]))
        # Staged rows partition the (truncated) dataset exactly once.
        assert sorted(xs.reshape(-1).tolist()) == list(range(n))


class TestDeviceCachedEvaluate:
    def _trainer(self, x, y):
        trainer = hvt.Trainer(Probe(), hvt.DistributedOptimizer(optax.adam(5e-3)))
        trainer.fit(x=x, y=y, batch_size=4, epochs=1, steps_per_epoch=4, verbose=0)
        return trainer

    def test_matches_streamed_evaluate(self):
        """Device-cached eval must reproduce the streamed path exactly,
        including the padded (non-divisible) tail."""
        x, y = _data(n=200)  # 200 is not a multiple of 8 shards x 4 batch
        trainer = self._trainer(x, y)
        streamed = trainer.evaluate(x, y, batch_size=4)
        cached = trainer.evaluate(x, y, batch_size=4, cache="device")
        assert cached["loss"] == pytest.approx(streamed["loss"], rel=1e-5)
        assert cached["accuracy"] == pytest.approx(streamed["accuracy"], rel=1e-6)
        # Second call reuses the staged set (same ids → one cache entry).
        trainer.evaluate(x, y, batch_size=4, cache="device")
        assert len(trainer._eval_cache) == 1

    def test_different_dataset_restages(self):
        x, y = _data(n=64)
        trainer = self._trainer(x, y)
        a = trainer.evaluate(x, y, batch_size=4, cache="device")
        x2, y2 = _data(n=64, seed=9)
        b = trainer.evaluate(x2, y2, batch_size=4, cache="device")
        assert len(trainer._eval_cache) == 2
        assert a != b  # different data, different result

    def test_validation_in_device_cached_fit(self):
        x, y = _data(n=256)
        xv, yv = _data(n=100, seed=5)
        trainer = hvt.Trainer(Probe(), hvt.DistributedOptimizer(optax.adam(5e-3)))
        hist = trainer.fit(
            x=x, y=y, batch_size=4, epochs=2, cache="device",
            validation_data=(xv, yv), verbose=0,
        )
        assert "val_loss" in hist[-1]
        ref = trainer.evaluate(xv, yv, batch_size=4)
        assert hist[-1]["val_loss"] == pytest.approx(ref["loss"], rel=1e-5)


class TestDevicePrefetcher:
    def test_order_and_values(self):
        out = list(DevicePrefetcher(iter(range(10)), lambda v: v * 2))
        assert out == [v * 2 for v in range(10)]

    def test_exception_propagates(self):
        def bad():
            yield 1
            raise RuntimeError("boom")

        pf = DevicePrefetcher(bad(), lambda v: v)
        assert next(pf) == 1
        with pytest.raises(RuntimeError, match="boom"):
            next(pf)

    def test_next_after_exception_stops_not_hangs(self):
        def bad():
            raise RuntimeError("dead")
            yield  # pragma: no cover

        pf = DevicePrefetcher(bad(), lambda v: v)
        with pytest.raises(RuntimeError):
            next(pf)
        with pytest.raises(StopIteration):  # not a deadlock
            next(pf)

    def test_close_unblocks_producer(self):
        def infinite():
            i = 0
            while True:
                yield i
                i += 1

        pf = DevicePrefetcher(infinite(), lambda v: v, depth=1)
        assert next(pf) == 0
        t0 = time.perf_counter()
        pf.close()
        assert time.perf_counter() - t0 < 5
        assert not pf._thread.is_alive()


class TestTraceAccounting:
    def test_peak_flops_none_on_cpu(self):
        assert trace.device_peak_flops(jax.devices()[0]) is None

    def test_mfu_math(self):
        class FakeDev:
            device_kind = "TPU v5 lite"

        # 197e12 peak: 1.97e12 flops in 0.01 s on 1 chip = 100% of peak.
        assert trace.mfu(1.97e12, 0.01, 1, device=FakeDev()) == pytest.approx(1.0)
        assert trace.mfu(None, 0.01) is None

    def test_compiled_flops_positive_or_none(self):
        f = jax.jit(lambda a, b: a @ b)
        a = jnp.ones((64, 64), jnp.float32)
        flops = trace.compiled_flops(f, a, a)
        if flops is not None:  # CPU backends may not report
            assert flops >= 2 * 64**3 * 0.9

    def test_profile_env_wiring(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HVT_PROFILE", str(tmp_path / "prof"))
        assert trace.profile_dir() == str(tmp_path / "prof")
        x, y = _data(n=64)
        trainer = hvt.Trainer(Probe(), hvt.DistributedOptimizer(optax.sgd(0.01)))
        trainer.fit(x=x, y=y, batch_size=4, epochs=1, steps_per_epoch=2, verbose=0)
        # jax.profiler wrote a trace tree under the requested directory.
        assert (tmp_path / "prof").exists()
        assert any((tmp_path / "prof").rglob("*"))

    def test_maybe_trace_noop_without_dir(self):
        with trace.maybe_trace(None):
            pass


class TestEpochShuffleMaterialization:
    """The round-3 input-leg fix: the epoch permutation is applied ONCE as a
    prefix gather and steps read contiguous slices — semantics must be
    unchanged and the gather must cover only the consumed prefix."""

    def test_capped_steps_consume_prefix_only(self):
        """steps_per_epoch below the full epoch must still train (the
        shuffled copy is sized to steps * batch, the review-found waste) and
        produce finite falling loss."""
        x, y = _data(n=512)
        trainer = hvt.Trainer(
            Probe(), hvt.DistributedOptimizer(optax.adam(5e-3))
        )
        hist = trainer.fit(
            x=x, y=y, batch_size=4, epochs=2, steps_per_epoch=3,
            cache="device", verbose=0,
        )
        assert len(hist) == 2
        assert np.isfinite(hist[-1]["loss"])
        assert hist[-1]["loss"] < hist[0]["loss"]

    def test_device_cached_epoch_covers_each_example_once(self):
        """One epoch of the device-cached path must see each example exactly
        once (permutation through the materialized copy) — train a sum-probe
        whose gradient accumulates the example tags; after one epoch the
        param equals the sum over ALL tags regardless of order."""

        class SumProbe(nn.Module):
            @nn.compact
            def __call__(self, x, *, train: bool = False):
                w = self.param("w", nn.initializers.zeros, (1,))
                # loss gradient d/dw = -mean(x) per batch; with SGD lr 1 and
                # steps covering the epoch, w accumulates batch means.
                return jnp.broadcast_to(
                    (w * x.sum(-1, keepdims=True)), (x.shape[0], 2)
                )

        n = 64
        x = np.arange(1, n + 1, dtype=np.float32).reshape(n, 1)
        y = np.zeros(n, dtype=np.int32)

        def loss(logits, labels):
            return logits[:, 0]  # d/dw = x per example

        tr = hvt.Trainer(
            SumProbe(), hvt.DistributedOptimizer(optax.sgd(1.0)), loss=loss
        )
        tr.fit(
            x=x, y=y, batch_size=2, epochs=1, cache="device", verbose=0,
        )
        # 4 steps x global batch 16 = the full epoch; each step's update is
        # -lr * mean(batch tags); summed over a permutation of ALL tags the
        # total is -sum(tags)/global_batch regardless of shuffle order.
        expected = -np.sum(np.arange(1, n + 1)) / 16.0
        got = float(np.asarray(jax.device_get(tr.state.params["w"]))[0])
        np.testing.assert_allclose(got, expected, rtol=1e-5)
