"""hvt-trace, the fleet timeline (ISSUE 15): cross-rank span merge with
host-aware clock alignment, Chrome trace-event export, skew/straggler
analytics offline (`hvt-trace skew`) and live (`SkewProbe`), the
supervisor's ``GET /fleet`` rollup, the ``slow:MS`` straggler fault, and
the span writer's drop counter."""

import json
import os
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

from horovod_tpu.analysis import trace_cli
from horovod_tpu.obs import core, fleet, prom, timeline
from horovod_tpu.obs import server as obs_server
from horovod_tpu.testing import faults

BASE_TS = 1700000000.0  # arbitrary wall-clock epoch for synthetic spans


def write_span_file(trace_dir, rank, spans, pid=None):
    os.makedirs(trace_dir, exist_ok=True)
    pid = pid if pid is not None else 100 + rank
    path = os.path.join(trace_dir, f"spans-rank{rank}-pid{pid}.jsonl")
    with open(path, "a") as f:
        for s in spans:
            f.write(json.dumps(s) + "\n")
    return path


def step_spans(rank, host, *, n=20, period=0.1, clock_offset=0.0,
               late=0.0, dur=0.004, epoch=0, jitter=None, start=BASE_TS):
    """Synthetic per-step spans: true step k starts at
    ``start + k*period + late``, stamped on a clock shifted by
    ``clock_offset``; ``jitter(k)`` adds per-step noise (seconds)."""
    out = []
    for k in range(n):
        ts = start + k * period + late
        if jitter is not None:
            ts += jitter(k)
        out.append({
            "name": "step", "ts": ts + clock_offset, "dur_s": dur,
            "rank": rank, "pid": 100 + rank, "host": host, "id": k + 1,
            "parent": None, "depth": 0, "epoch": epoch, "step": k,
        })
    return out


class TestClockAlignment:
    def test_cross_host_offset_recovered_under_1ms(self, tmp_path):
        # rank 1 lives on a host whose clock is 3.7 s ahead, with
        # +-0.3 ms of per-anchor noise: the recovered offset round-trips
        # to < 1 ms and the residual reports the noise honestly.
        d = str(tmp_path)
        noise = lambda k: ((k * 7919) % 13 - 6) * 5e-5  # +-0.3 ms
        write_span_file(d, 0, step_spans(0, "hostA"))
        write_span_file(
            d, 1,
            step_spans(1, "hostB", clock_offset=3.7, jitter=noise),
        )
        by = timeline.load_spans(d)
        al = timeline.align(by)
        assert al.offsets[0] == 0.0
        assert abs(al.offsets[1] - (-3.7)) < 1e-3
        assert 0.0 < al.residual_ms["hostB"] < 1.0
        assert al.anchor_counts["hostB"] == 20

    def test_same_host_ranks_share_the_clock_exactly(self, tmp_path):
        # Same host = same clock: offset 0 BY CONSTRUCTION, so a
        # consistently-late rank stays visibly late (the alignment must
        # not absorb its lateness the way a cross-host fit would).
        d = str(tmp_path)
        write_span_file(d, 0, step_spans(0, "h"))
        write_span_file(d, 1, step_spans(1, "h", late=0.05))
        al = timeline.align(timeline.load_spans(d))
        assert al.offsets == {0: 0.0, 1: 0.0}
        assert al.residual_ms == {"h": 0.0}

    def test_refuses_unanchored_host(self, tmp_path):
        # rank 1 on another host trained DIFFERENT steps: no common
        # anchors, no clock correlation — alignment must refuse.
        d = str(tmp_path)
        write_span_file(d, 0, step_spans(0, "hostA", epoch=0))
        write_span_file(d, 1, step_spans(1, "hostB", epoch=7))
        with pytest.raises(timeline.TimelineError, match="no step spans"):
            timeline.align(timeline.load_spans(d))

    def test_empty_dir_refused(self, tmp_path):
        with pytest.raises(timeline.TimelineError, match="no spans-"):
            timeline.load_spans(str(tmp_path))

    def test_torn_tail_lines_skipped(self, tmp_path):
        d = str(tmp_path)
        path = write_span_file(d, 0, step_spans(0, "h", n=3))
        with open(path, "a") as f:
            f.write('{"name": "step", "ts": 17')  # killed mid-write
        by = timeline.load_spans(d)
        assert len(by[0]) == 3

    def test_pre_host_span_files_get_per_rank_clocks(self, tmp_path):
        # PR 13 span files carry no "host": each rank must be aligned
        # independently (conservative), which still works when they
        # share step anchors.
        d = str(tmp_path)
        old = [
            {k: v for k, v in s.items() if k != "host"}
            for s in step_spans(0, "x")
        ]
        write_span_file(d, 0, old)
        write_span_file(d, 1, step_spans(1, "hostB", clock_offset=1.0))
        al = timeline.align(timeline.load_spans(d))
        assert al.hosts[0] == "rank0"
        assert abs(al.offsets[1] - (-1.0)) < 1e-6


class TestChromeTrace:
    def _trace(self, tmp_path, with_flight=False):
        d = str(tmp_path)
        write_span_file(d, 0, step_spans(0, "h"))
        write_span_file(d, 1, step_spans(1, "h", late=0.02))
        if with_flight:
            with open(os.path.join(d, "flight-rank1.jsonl"), "w") as f:
                for seq in range(3):
                    f.write(json.dumps({
                        "kind": "psum_scatter", "seq": seq,
                        "t": BASE_TS + 0.05 + seq * 0.1, "bytes": 4096,
                        "bucket": 0,
                    }) + "\n")
        by = timeline.load_spans(d)
        return timeline.chrome_trace(
            by, timeline.align(by), timeline.load_flight(d)
        )

    def test_every_complete_event_carries_the_schema(self, tmp_path):
        doc = self._trace(tmp_path)
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert len(xs) == 40
        for e in xs:
            assert {"pid", "tid", "ts", "dur", "ph", "name"} <= set(e)
            assert e["ts"] >= 0 and e["dur"] >= 0
        # pid = rank; tid = span depth.
        assert {e["pid"] for e in xs} == {0, 1}
        assert {e["tid"] for e in xs} == {0}

    def test_loads_as_strict_json_with_metadata(self, tmp_path):
        doc = self._trace(tmp_path)
        rt = json.loads(json.dumps(doc))
        assert rt["displayTimeUnit"] == "ms"
        names = [
            e for e in rt["traceEvents"]
            if e["ph"] == "M" and e["name"] == "process_name"
        ]
        assert {n["args"]["name"] for n in names} == {
            "rank 0 (h)", "rank 1 (h)"
        }
        assert rt["otherData"]["clock_offsets_s"] == {"0": 0.0, "1": 0.0}

    def test_flight_records_become_instant_events(self, tmp_path):
        doc = self._trace(tmp_path, with_flight=True)
        inst = [e for e in doc["traceEvents"] if e["ph"] == "i"]
        assert len(inst) == 3
        for e in inst:
            assert e["pid"] == 1 and e["tid"] == timeline.FLIGHT_TID
            assert e["s"] == "t" and "seq" in e["args"]
        assert inst[0]["name"] == "psum_scatter#0"
        # The instant sits inside its enclosing step span's interval.
        step0 = next(
            e for e in doc["traceEvents"]
            if e["ph"] == "X" and e["pid"] == 0 and e["args"]["step"] == 0
        )
        assert inst[0]["ts"] >= step0["ts"]

    def test_nested_spans_land_on_depth_tids(self, tmp_path):
        d = str(tmp_path)
        spans = step_spans(0, "h", n=2)
        spans.append({
            "name": "decode", "ts": BASE_TS + 0.01, "dur_s": 0.002,
            "rank": 0, "pid": 100, "host": "h", "id": 99, "parent": 1,
            "depth": 1,
        })
        write_span_file(d, 0, spans)
        by = timeline.load_spans(d)
        doc = timeline.chrome_trace(by, timeline.align(by))
        decode = next(
            e for e in doc["traceEvents"] if e["name"] == "decode"
        )
        assert decode["tid"] == 1
        assert decode["args"]["parent_id"] == 1


class TestSkewMath:
    def test_straggler_named_with_barrier_wait_evidence(self, tmp_path):
        d = str(tmp_path)
        write_span_file(d, 0, step_spans(0, "h"))
        write_span_file(d, 1, step_spans(1, "h", late=0.05))
        write_span_file(d, 2, step_spans(2, "h"))
        by = timeline.load_spans(d)
        rep = timeline.skew(by, timeline.align(by))
        assert rep["straggler"] == 1
        assert rep["per_rank"][1]["straggler_score"] == 1.0
        assert rep["per_rank"][0]["straggler_score"] == 0.0
        # Barrier-wait attribution: the straggler waits ~0, the others
        # pay its lateness at every step boundary.
        assert rep["per_rank"][1]["barrier_wait_ms_mean"] < 1.0
        assert rep["per_rank"][0]["barrier_wait_ms_mean"] == pytest.approx(
            50.0, abs=1.0
        )
        assert "rank 1" in rep["evidence"]
        assert "waited" in rep["evidence"]

    def test_noise_below_threshold_names_no_straggler(self, tmp_path):
        d = str(tmp_path)
        # +-1 ms of alternating noise on a 100 ms period: under the 5%
        # threshold, nobody should be blamed.
        for r in range(2):
            write_span_file(
                d, r,
                step_spans(
                    r, "h",
                    jitter=lambda k, r=r: 1e-3 * ((k + r) % 2),
                ),
            )
        by = timeline.load_spans(d)
        rep = timeline.skew(by, timeline.align(by))
        assert rep["straggler"] is None
        assert "no consistent straggler" in rep["evidence"]

    def test_duration_spread_reported_for_sync_bound_runs(self, tmp_path):
        d = str(tmp_path)
        write_span_file(d, 0, step_spans(0, "h", dur=0.010))
        write_span_file(d, 1, step_spans(1, "h", dur=0.090))
        by = timeline.load_spans(d)
        rep = timeline.skew(by, timeline.align(by))
        assert rep["dur_spread_ms"]["step"] == pytest.approx(40.0, abs=1.0)

    def test_too_few_common_steps_never_name_a_culprit(self, tmp_path):
        # n < 3 common steps: the period (and threshold) is meaningless;
        # even a huge consistent start offset must not produce a verdict
        # (review fix — "one noisy step must not name a culprit").
        d = str(tmp_path)
        write_span_file(d, 0, step_spans(0, "h", n=2))
        write_span_file(d, 1, step_spans(1, "h", n=2, late=0.05))
        by = timeline.load_spans(d)
        rep = timeline.skew(by, timeline.align(by))
        assert rep["straggler"] is None
        assert "too few" in rep["evidence"]

    def test_refuses_without_common_steps(self, tmp_path):
        d = str(tmp_path)
        write_span_file(d, 0, step_spans(0, "h", epoch=0))
        write_span_file(d, 1, step_spans(1, "h", epoch=5))
        by = timeline.load_spans(d)
        with pytest.raises(timeline.TimelineError, match="common"):
            timeline.skew(by, timeline.align(by))

    def test_render_skew_prints_table_and_verdict(self, tmp_path):
        d = str(tmp_path)
        write_span_file(d, 0, step_spans(0, "h"))
        write_span_file(d, 1, step_spans(1, "h", late=0.05))
        by = timeline.load_spans(d)
        text = timeline.render_skew(timeline.skew(by, timeline.align(by)))
        assert "STRAGGLER: rank 1" in text
        assert "barrier-wait" in text

    def test_phase_report_covers_all_ranks_and_names(self, tmp_path):
        d = str(tmp_path)
        spans0 = step_spans(0, "h", n=4)
        spans0.append({
            "name": "checkpoint_save", "ts": BASE_TS + 1, "dur_s": 0.5,
            "rank": 0, "pid": 100, "host": "h", "id": 50, "parent": None,
            "depth": 0,
        })
        write_span_file(d, 0, spans0)
        write_span_file(d, 1, step_spans(1, "h", n=4))
        by = timeline.load_spans(d)
        table = timeline.phase_table(by)
        assert table["step"][0]["count"] == 4
        assert table["step"][1]["count"] == 4
        assert table["checkpoint_save"][0]["mean_ms"] == pytest.approx(500)
        text = timeline.render_report(by)
        assert "checkpoint_save" in text and "step" in text


class TestTraceCLI:
    def _dir(self, tmp_path):
        d = str(tmp_path / "spans")
        write_span_file(d, 0, step_spans(0, "h"))
        write_span_file(d, 1, step_spans(1, "h", late=0.05))
        return d

    def test_timeline_writes_valid_json(self, tmp_path, capsys):
        d = self._dir(tmp_path)
        out = str(tmp_path / "trace.json")
        assert trace_cli.main(["timeline", d, "-o", out]) == 0
        doc = json.load(open(out))
        assert any(e["ph"] == "X" for e in doc["traceEvents"])
        assert "residual" in capsys.readouterr().out

    def test_report_exits_zero(self, tmp_path, capsys):
        assert trace_cli.main(["report", self._dir(tmp_path)]) == 0
        assert "step" in capsys.readouterr().out

    def test_skew_expect_straggler_gate(self, tmp_path, capsys):
        d = self._dir(tmp_path)
        assert trace_cli.main(["skew", d]) == 0
        assert trace_cli.main(["skew", d, "--expect-straggler", "1"]) == 0
        assert trace_cli.main(["skew", d, "--expect-straggler", "0"]) == 1
        out = capsys.readouterr()
        assert "straggler gate passed" in out.out
        assert "expected straggler rank 0" in out.err

    def test_refusals_exit_2(self, tmp_path, capsys):
        empty = str(tmp_path / "empty")
        os.makedirs(empty)
        assert trace_cli.main(["timeline", empty]) == 2
        # Unanchored cross-host dir: refuse, never fabricate a merge.
        d = str(tmp_path / "unanchored")
        write_span_file(d, 0, step_spans(0, "hostA", epoch=0))
        write_span_file(d, 1, step_spans(1, "hostB", epoch=3))
        assert trace_cli.main(["skew", d]) == 2
        assert "hvt-trace:" in capsys.readouterr().err
        # Per-rank duration tables need no merged ordering: report still
        # serves the unanchored dir (review fix).
        assert trace_cli.main(["report", d]) == 0
        assert "step" in capsys.readouterr().out


class TestSlowFault:
    def test_parse_plan_slow_kinds(self):
        plan = faults.parse_plan("1:0:slow:50")
        assert plan.kind == "slow:50" and plan.slow_ms == 50.0
        assert plan.rank == 1 and plan.epoch == 0 and plan.step is None
        plan = faults.parse_plan("0:2.3:slow:12.5")
        assert plan.step == 3 and plan.slow_ms == 12.5
        # Non-slow kinds keep their exact prior contract.
        assert faults.parse_plan("1:1:kill").slow_ms is None

    @pytest.mark.parametrize("bad", [
        "1:0:slow:", "1:0:slow:abc", "1:0:slow:-5", "1:0:slow:0",
        "1:0:bogus", "1:0:kill:extra",
    ])
    def test_bad_specs_still_refused(self, bad):
        with pytest.raises(ValueError):
            faults.parse_plan(bad)

    def test_slow_fires_every_batch_from_target_epoch(self, monkeypatch):
        sleeps = []
        monkeypatch.setattr(faults.time, "sleep", sleeps.append)
        monkeypatch.setattr(faults.runtime, "rank", lambda: 1)
        cb = faults.FaultInjectionCallback(faults.parse_plan("1:1:slow:50"))
        cb.on_epoch_begin(0)
        cb.on_batch_end(0)
        assert sleeps == []  # before the target epoch
        cb.on_epoch_begin(1)
        for b in range(3):
            cb.on_batch_end(b)
        cb.on_epoch_begin(2)  # RECURRING: later epochs stay slow
        cb.on_batch_end(0)
        assert sleeps == [0.05] * 4

    def test_slow_inert_on_other_ranks(self, monkeypatch):
        sleeps = []
        monkeypatch.setattr(faults.time, "sleep", sleeps.append)
        monkeypatch.setattr(faults.runtime, "rank", lambda: 0)
        cb = faults.FaultInjectionCallback(faults.parse_plan("1:0:slow:50"))
        cb.on_epoch_begin(0)
        cb.on_batch_end(0)
        assert sleeps == []


class TestSpanDropCounter:
    @pytest.fixture(autouse=True)
    def _fresh(self, monkeypatch):
        from horovod_tpu import trace

        core.reset()
        monkeypatch.setattr(trace, "_span_writer", trace._SpanWriter())
        yield
        core.reset()

    def test_drops_counted_and_exported(self, tmp_path, monkeypatch):
        from horovod_tpu import trace

        # HVT_TRACE_DIR points at a FILE: the writer dies on open and
        # every span from then on is a counted drop.
        blocker = tmp_path / "not-a-dir"
        blocker.write_text("x")
        monkeypatch.setenv("HVT_TRACE_DIR", str(blocker))
        for _ in range(3):
            with trace.span("step", epoch=0, step=0):
                pass
        assert trace._span_writer.drops == 3
        values = prom.parse_text(prom.render())
        assert values["hvt_trace_spans_dropped_total"] == 3

    def test_healthy_writer_reports_zero(self, tmp_path, monkeypatch):
        from horovod_tpu import trace

        monkeypatch.setenv("HVT_TRACE_DIR", str(tmp_path / "spans"))
        with trace.span("step", epoch=0, step=0):
            pass
        assert trace._span_writer.drops == 0
        values = prom.parse_text(prom.render())
        assert values["hvt_trace_spans_dropped_total"] == 0

    def test_span_records_carry_host(self, tmp_path, monkeypatch):
        from horovod_tpu import trace

        monkeypatch.setenv("HVT_TRACE_DIR", str(tmp_path / "spans"))
        with trace.span("step", epoch=0, step=0):
            pass
        trace.emit_span("queue_wait", time.time(), 0.001)
        files = os.listdir(tmp_path / "spans")
        recs = [
            json.loads(l)
            for l in open(os.path.join(tmp_path / "spans", files[0]))
        ]
        assert len(recs) == 2
        assert all(r["host"] for r in recs)
        assert recs[1]["name"] == "queue_wait"
        assert recs[1]["dur_s"] == 0.001

    def test_attrs_cannot_clobber_the_span_schema(self, tmp_path,
                                                  monkeypatch):
        # A caller attr named like a core field must lose: the timeline
        # merge keys parent linkage on `id` (a serving `id=` attr
        # silently broke it — regression).
        from horovod_tpu import trace

        monkeypatch.setenv("HVT_TRACE_DIR", str(tmp_path / "spans"))
        with trace.span("request", id=999, depth=77):
            trace.emit_span("child", time.time(), 0.001, id=888)
        files = os.listdir(tmp_path / "spans")
        recs = [
            json.loads(l)
            for l in open(os.path.join(tmp_path / "spans", files[0]))
        ]
        child = next(r for r in recs if r["name"] == "child")
        parent = next(r for r in recs if r["name"] == "request")
        assert parent["id"] not in (999, 888)
        assert child["parent"] == parent["id"]


class TestSkewProbe:
    @pytest.fixture(autouse=True)
    def _fresh(self):
        core.reset()
        yield
        core.reset()

    def test_off_single_process_and_off_by_knob(self, monkeypatch):
        from horovod_tpu.training.trainer import SkewProbe

        monkeypatch.delenv("HVT_SKEW_PROBE", raising=False)
        assert SkewProbe.maybe() is None  # single-process CI
        monkeypatch.setenv("HVT_SKEW_PROBE", "0")
        assert SkewProbe.maybe() is None

    def test_publish_names_the_minimal_drain_rank(self, monkeypatch):
        from horovod_tpu.parallel import collectives
        from horovod_tpu.training.trainer import SkewProbe

        # Fake a 3-rank fleet where rank 2 is the straggler: its drain
        # wait is ~0 while the others block for its contribution.
        rows = [(0, 0.050, BASE_TS), (1, 0.048, BASE_TS), (2, 0.001, BASE_TS)]
        monkeypatch.setattr(
            collectives, "allgather_object", lambda obj: rows
        )
        probe = SkewProbe.__new__(SkewProbe)
        probe.rank = 0
        probe.world = 3
        probe.publish(0.050)
        values = prom.parse_text(prom.render())
        assert values["hvt_straggler_rank"] == 2
        assert values["hvt_step_skew_ms"] == pytest.approx(
            (0.050 - 0.048) * 1e3
        )
        # Blocked time beyond the fleet minimum: 50 ms - 1 ms.
        assert values["hvt_barrier_wait_ms"] == pytest.approx(49.0)

    def test_sampler_carries_probe_handle(self, monkeypatch):
        # Single-process: the sampler wires the probe slot but it stays
        # None (nothing to skew against) — the zero-cost default.
        import flax.linen as nn
        import optax

        import horovod_tpu as hvt
        from horovod_tpu.training.trainer import StepPhaseSampler

        class M(nn.Module):
            @nn.compact
            def __call__(self, x, *, train: bool = False):
                return nn.Dense(2)(x)

        t = hvt.Trainer(M(), hvt.DistributedOptimizer(optax.sgd(1e-2)))
        sampler = StepPhaseSampler(t, 8, every=4)
        assert sampler.skew_probe is None


class TestFleetRollup:
    def _member_registry(self, total_ms, skew_ms=None):
        reg = core.Registry()
        reg.gauge("hvt_step_phase_ms", total_ms, phase="total")
        reg.gauge("hvt_step_phase_ms", total_ms * 0.8, phase="compute")
        reg.gauge("hvt_mfu", 0.12)
        if skew_ms is not None:
            reg.gauge("hvt_step_skew_ms", skew_ms)
        return reg

    def test_merge_fleet_injects_rank_labels_and_summary(self):
        members = {
            0: prom.render(self._member_registry(12.0, 3.0)),
            1: prom.render(self._member_registry(61.5, 3.0)),
        }
        sup = core.Registry()
        sup.counter_set("hvt_restarts_total", 1)
        merged = fleet.merge_fleet(prom.render(sup), members)
        values = prom.parse_text(merged)
        assert values["hvt_restarts_total"] == 1
        assert values['hvt_step_phase_ms{phase="total",rank="0"}'] == 12.0
        assert values['hvt_step_phase_ms{phase="total",rank="1"}'] == 61.5
        assert values['hvt_step_skew_ms{rank="1"}'] == 3.0
        assert values['hvt_fleet_step_ms{stat="slowest"}'] == 61.5
        assert values['hvt_fleet_step_ms{stat="fastest"}'] == 12.0
        # One HELP/TYPE block per family (a valid single exposition).
        assert merged.count("# TYPE hvt_step_phase_ms gauge") == 1

    def test_merge_without_members_is_the_supervisor_exposition(self):
        sup = core.Registry()
        sup.gauge("hvt_fleet_size", 2)
        text = prom.render(sup)
        assert fleet.merge_fleet(text, {}) == text

    def test_torn_member_scrape_skipped_not_fatal(self):
        members = {0: "%%% not an exposition %%%"}
        sup = core.Registry()
        sup.gauge("hvt_fleet_size", 1)
        merged = fleet.merge_fleet(prom.render(sup), members)
        assert prom.parse_text(merged)["hvt_fleet_size"] == 1

    def test_fleet_endpoint_over_fake_member_exporters(self, tmp_path):
        from horovod_tpu.launch import supervisor

        m0 = obs_server.start_metrics_server(
            0, registry=self._member_registry(10.0, 1.0)
        )
        m1 = obs_server.start_metrics_server(
            0, registry=self._member_registry(55.0, 1.0)
        )
        log = tmp_path / "restarts.jsonl"
        log.write_text(json.dumps(
            {"name": "restarts", "value": 0, "wall_time": 0}
        ) + "\n")
        ports = {
            0: m0.server_address[1],
            1: m1.server_address[1],
        }
        srv = supervisor.start_status_server(
            0, str(log), fleet_ports=ports
        )
        try:
            url = (
                f"http://127.0.0.1:{srv.server_address[1]}/fleet"
            )
            with urllib.request.urlopen(url, timeout=5) as r:
                assert r.headers["Content-Type"] == prom.CONTENT_TYPE
                text = r.read().decode()
            values = prom.parse_text(text)
            # Per-rank member series, supervisor series, and computed
            # fleet stats in ONE scrape body.
            assert values['hvt_step_phase_ms{phase="total",rank="0"}'] == 10.0
            assert values['hvt_step_phase_ms{phase="total",rank="1"}'] == 55.0
            assert values['hvt_step_skew_ms{rank="0"}'] == 1.0
            assert values['hvt_fleet_step_ms{stat="slowest"}'] == 55.0
            assert values["hvt_restarts_total"] == 0
            # The rollup cached the member scrapes for the final dump.
            assert set(srv.fleet_cache["members"]) == {0, 1}
            dump = tmp_path / "metrics.prom"
            supervisor.dump_metrics(
                str(log), path=str(dump),
                members=srv.fleet_cache["members"],
            )
            dumped = prom.parse_text(dump.read_text())
            assert dumped['hvt_mfu{rank="1"}'] == 0.12
        finally:
            srv.shutdown()
            m0.shutdown()
            m1.shutdown()

    def test_fleet_endpoint_skips_dead_members(self, tmp_path):
        from horovod_tpu.launch import supervisor

        m0 = obs_server.start_metrics_server(
            0, registry=self._member_registry(10.0)
        )
        with socket.socket() as s:  # a port nobody answers
            s.bind(("127.0.0.1", 0))
            dead_port = s.getsockname()[1]
        srv = supervisor.start_status_server(
            0, None, fleet_ports={0: m0.server_address[1], 1: dead_port}
        )
        try:
            url = f"http://127.0.0.1:{srv.server_address[1]}/fleet"
            with urllib.request.urlopen(url, timeout=10) as r:
                values = prom.parse_text(r.read().decode())
            assert 'hvt_step_phase_ms{phase="total",rank="0"}' in values
            assert not any('rank="1"' in k for k in values)
        finally:
            srv.shutdown()
            m0.shutdown()

    def test_fleet_404_without_known_ports(self, tmp_path):
        from horovod_tpu.launch import supervisor

        srv = supervisor.start_status_server(0, None)
        try:
            url = f"http://127.0.0.1:{srv.server_address[1]}/fleet"
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(url, timeout=5)
            assert e.value.code == 404
            assert "metrics ports" in json.loads(e.value.read())["error"]
        finally:
            srv.shutdown()

    def test_member_metrics_ports_resolution(self, monkeypatch):
        from horovod_tpu.launch import supervisor

        monkeypatch.delenv("HVT_METRICS_PORT", raising=False)
        assert supervisor.member_metrics_ports({}, 2) is None
        assert supervisor.member_metrics_ports(
            {"HVT_METRICS_PORT": "0"}, 2
        ) is None  # ephemeral ports are unknowable
        assert supervisor.member_metrics_ports(
            {"HVT_METRICS_PORT": "9000"}, 3
        ) == {0: 9000, 1: 9001, 2: 9002}
        assert supervisor.member_metrics_ports(
            {"HVT_METRICS_PORT": "junk"}, 2
        ) is None


class TestServeRequestSpans:
    """The serving tier leaves spans too (ISSUE 15 satellite): one
    `request` span per POST with `queue_wait` and `decode` children, so
    `hvt-trace timeline` shows TTFT as span structure."""

    @pytest.fixture(autouse=True)
    def _spans_on(self, tmp_path, monkeypatch):
        from horovod_tpu import trace

        self.span_dir = tmp_path / "spans"
        monkeypatch.setenv("HVT_TRACE_DIR", str(self.span_dir))
        monkeypatch.setattr(trace, "_span_writer", trace._SpanWriter())
        yield

    def _spans(self):
        recs = []
        for name in os.listdir(self.span_dir):
            if name.startswith("spans-"):
                with open(self.span_dir / name) as f:
                    recs.extend(json.loads(l) for l in f if l.strip())
        return recs

    def test_batcher_emits_queue_wait_and_decode(self):
        from horovod_tpu.launch.serve import _Batcher

        done = threading.Event()

        def run_rows(items):
            time.sleep(0.02)
            return [i * 2 for i in items]

        b = _Batcher(run_rows, batch=4, stats={"device_calls": 0,
                                               "rows": 0})
        assert b.submit([1, 2]) == [2, 4]
        done.set()
        names = [r["name"] for r in self._spans()]
        assert names.count("queue_wait") == 1
        assert names.count("decode") == 1
        decode = next(r for r in self._spans() if r["name"] == "decode")
        assert decode["dur_s"] >= 0.02
        assert decode["rows"] == 2

    def test_generate_lock_path_emits_children_under_request(self):
        # The sampled-generate path (no batcher): lock wait becomes
        # queue_wait, the device call a decode child — exercised on a
        # stub bundle so no export is paid here.
        from horovod_tpu import trace
        from horovod_tpu.launch.serve import _GenerateApp

        class StubBundle:
            batch_size = 4
            tokenizer = None
            meta = {"temperature": 0.7}

            def validate_prompts(self, prompts):
                return prompts

            def generate_tokens(self, prompts, seed=0):
                return [[1, 2] for _ in prompts]

        app = _GenerateApp.__new__(_GenerateApp)
        app.bundle = StubBundle()
        app.stats = {"device_calls": 0, "rows": 0}
        app._lock = threading.Lock()
        app._batcher = None
        with trace.span("request", req=1, route="/v1/generate"):
            out = app.generate({"prompt": [[3, 1]]})
        assert out["tokens"] == [[1, 2]]
        recs = {r["name"]: r for r in self._spans()}
        assert {"request", "queue_wait", "decode"} <= set(recs)
        req = recs["request"]
        assert req["route"] == "/v1/generate"
        assert req["req"] == 1  # the request-correlation attr
        # Children nest under the request span.
        assert recs["queue_wait"]["parent"] == req["id"]
        assert recs["decode"]["parent"] == req["id"]
        assert recs["decode"]["depth"] == 1

    def test_predict_http_request_carries_span_tree(self):
        # Over real HTTP with the coalescing batcher (the cheap predict
        # bundle): request span on the handler thread, queue_wait +
        # decode measured on the worker.
        import flax.linen as nn
        import jax
        import numpy as np

        from horovod_tpu import checkpoint
        from horovod_tpu.launch.serve import make_server

        class Tiny(nn.Module):
            @nn.compact
            def __call__(self, x, train: bool = False):
                return nn.Dense(3)(x)

        model = Tiny()
        x0 = np.zeros((2, 4), np.float32)
        params = model.init(jax.random.PRNGKey(0), x0)["params"]
        out = checkpoint.export_serving(
            str(self.span_dir.parent / "bundle"),
            lambda p, x: model.apply({"params": p}, x),
            params, input_shape=(2, 4), timestamp="19700101-000000",
        )
        srv = make_server(out, port=0)
        t = threading.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.server_address[1]}/v1/predict",
                data=json.dumps(
                    {"input": np.zeros((2, 4)).tolist()}
                ).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=10) as r:
                assert r.status == 200
        finally:
            srv.shutdown()
        recs = self._spans()
        by_name = {r["name"]: r for r in recs}
        assert {"request", "queue_wait", "decode"} <= set(by_name)
        assert by_name["request"]["route"] == "/v1/predict"
        assert by_name["queue_wait"]["parent"] == by_name["request"]["id"]


# --- the slow e2e: injected straggler -> named straggler --------------------


def _free_port_base(n=2):
    """A base port with n consecutive free ports (best-effort)."""
    for base in range(29611, 29911, 10):
        try:
            socks = []
            for i in range(n):
                s = socket.socket()
                s.bind(("127.0.0.1", base + i))
                socks.append(s)
            for s in socks:
                s.close()
            return base
        except OSError:
            for s in socks:
                s.close()
    raise RuntimeError("no free port window")


SLOW_TRAIN_SCRIPT = """
import os, sys
sys.path.insert(0, __REPO__)
import numpy as np
import optax
import flax.linen as nn
import horovod_tpu as hvt


class Tiny(nn.Module):
    @nn.compact
    def __call__(self, x, train=False):
        return nn.Dense(4)(x)


def main():
    hvt.init()
    rng = np.random.RandomState(0)
    x = rng.rand(96, 8).astype("float32")
    y = (np.arange(96) % 4).astype("int64")
    trainer = hvt.Trainer(
        Tiny(), hvt.DistributedOptimizer(optax.adam(1e-2))
    )
    cbs = [hvt.callbacks.BroadcastGlobalVariablesCallback(0)]
    trainer.fit(
        x=x, y=y, batch_size=8, epochs=2, steps_per_epoch=6,
        callbacks=cbs, verbose=0,
    )
    if hvt.rank() == 0:
        print("TRAINING COMPLETE", flush=True)


main()
"""


@pytest.mark.slow
def test_slow_fault_e2e_straggler_named_and_fleet_scraped(tmp_path, capfd):
    """The ISSUE 15 acceptance run: a real 2-process supervised run with
    an injected ``slow:50`` on rank 1 yields (a) a valid merged Chrome
    trace with both ranks' step spans on one clock, (b) ``hvt-trace
    skew`` naming rank 1 with barrier-wait evidence, and (c) one
    ``GET /fleet`` scrape carrying per-rank step-phase series plus the
    live SkewProbe's ``hvt_step_skew_ms`` — which also survives into the
    final metrics.prom dump via the fleet poller."""
    from horovod_tpu.launch import supervisor
    from horovod_tpu.launch.supervisor import RestartPolicy

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "train.py"
    script.write_text(SLOW_TRAIN_SCRIPT.replace("__REPO__", repr(repo)))
    trace_dir = tmp_path / "trace"
    model_dir = tmp_path / "models"
    log = tmp_path / "restarts.jsonl"
    base = _free_port_base(2)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        status_port = s.getsockname()[1]
    env = {
        "HVT_PLATFORM": "cpu",
        "HVT_NUM_CPU_DEVICES": "1",
        "PS_MODEL_PATH": str(model_dir),
        "HVT_FAULT": "1:0:slow:50",
        "HVT_TRACE_DIR": str(trace_dir),
        "HVT_METRICS_PORT": str(base),
        "HVT_METRICS_EVERY": "1",   # drain every step: max skew signal
        "HVT_FLEET_POLL_S": "0.5",  # cache member scrapes fast
        "HVT_PEAK_FLOPS": "1e12",   # skip the matmul calibration
        "JAX_ENABLE_COMPILATION_CACHE": "0",
        "JAX_COMPILATION_CACHE_DIR": "",
    }
    fleet_text = {}

    def scrape_fleet():
        deadline = time.monotonic() + 120
        url = f"http://127.0.0.1:{status_port}/fleet"
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(url, timeout=2) as r:
                    candidate = r.read().decode()
                values = prom.parse_text(candidate)
                if (
                    'hvt_step_phase_ms{phase="total",rank="0"}' in values
                    and 'hvt_step_phase_ms{phase="total",rank="1"}' in values
                    and any(
                        k.startswith("hvt_step_skew_ms") for k in values
                    )
                ):
                    fleet_text["text"] = candidate
                    return
            except (urllib.error.URLError, OSError, ConnectionError,
                    ValueError):
                pass
            time.sleep(0.3)

    scraper = threading.Thread(target=scrape_fleet, daemon=True)
    scraper.start()
    code = supervisor.supervise_local(
        2, [os.sys.executable, str(script)],
        env=env,
        policy=RestartPolicy(max_restarts=2, backoff=0.0,
                             grace_seconds=5.0),
        model_dir=str(model_dir), log_path=str(log),
        status_port=status_port, tag_output=False,
        sleep=lambda s: None,
    )
    assert code == 0
    out = capfd.readouterr().out
    assert "TRAINING COMPLETE" in out
    scraper.join(timeout=5)

    # (a) merged Chrome trace: both ranks, one clock, strict JSON.
    trace_json = tmp_path / "trace.json"
    assert trace_cli.main(
        ["timeline", str(trace_dir), "-o", str(trace_json)]
    ) == 0
    doc = json.load(open(trace_json))
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["pid"] for e in xs} >= {0, 1}
    assert all({"pid", "tid", "ts", "dur", "ph"} <= set(e) for e in xs)
    steps = [e for e in xs if e["name"] == "step"]
    assert {e["pid"] for e in steps} == {0, 1}

    # (b) skew names the injected straggler with barrier-wait evidence.
    assert trace_cli.main(
        ["skew", str(trace_dir), "--expect-straggler", "1"]
    ) == 0
    by = timeline.load_spans(str(trace_dir))
    rep = timeline.skew(by, timeline.align(by))
    assert rep["straggler"] == 1
    assert rep["per_rank"][0]["barrier_wait_ms_mean"] > 10.0
    assert (
        rep["per_rank"][1]["barrier_wait_ms_mean"]
        < rep["per_rank"][0]["barrier_wait_ms_mean"]
    )

    # (c) the live /fleet scrape carried per-rank series + skew, and
    # the per-rank series survived into the final dump.
    assert "text" in fleet_text, "never scraped a full fleet rollup"
    values = prom.parse_text(fleet_text["text"])
    skew_keys = [k for k in values if k.startswith("hvt_step_skew_ms")]
    assert skew_keys
    assert values['hvt_fleet_step_ms{stat="slowest"}'] >= values[
        'hvt_fleet_step_ms{stat="fastest"}'
    ]
    dump = model_dir / "metrics.prom"
    assert dump.exists()
    dumped = prom.parse_text(dump.read_text())
    assert any(k.startswith("hvt_step_phase_ms") and 'rank="1"' in k
               for k in dumped)
