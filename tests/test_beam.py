"""Beam search (models/beam.py): width-W maximum-likelihood decode over
the KV cache. The load-bearing check is score consistency — the
incrementally-accumulated beam scores must equal a teacher-forced
recompute of the returned sequence, which transitively proves the
per-step cache reordering (a wrong gather would score later steps against
the wrong prefix)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.models.beam import make_beam_search_fn
from horovod_tpu.models.decoding import generate
from horovod_tpu.models.transformer import TransformerLM

VOCAB = 32
N = 10


def _model(**kw):
    kw.setdefault("vocab_size", VOCAB)
    kw.setdefault("d_model", 32)
    kw.setdefault("n_heads", 4)
    kw.setdefault("n_layers", 2)
    kw.setdefault("dropout", 0.0)
    return TransformerLM(**kw)


def _setup(seed=0, **kw):
    model = _model(**kw)
    toks = jnp.asarray(
        np.random.RandomState(seed).randint(1, VOCAB, size=(2, 8)), jnp.int32
    )
    params = model.init(jax.random.PRNGKey(0), toks)["params"]
    return model, params, toks[:, :6]


def _seq_logprob(model, params, full, n):
    logits = model.apply({"params": params}, full[:, :-1])
    lp = jax.nn.log_softmax(logits.astype(jnp.float32))
    pick = jnp.take_along_axis(lp, full[:, 1:, None], -1)[..., 0]
    return pick[:, -n:].sum(-1)


class TestBeam:
    def test_beam_one_is_greedy(self):
        model, params, prompt = _setup()
        g = generate(model, params, prompt, N)
        b1 = make_beam_search_fn(model, max_new_tokens=N, beam_size=1)(
            params, prompt
        )
        np.testing.assert_array_equal(np.asarray(b1), np.asarray(g))

    def test_scores_match_teacher_forced_recompute(self):
        model, params, prompt = _setup(1)
        toks, scores = make_beam_search_fn(
            model, max_new_tokens=N, beam_size=4, return_scores=True
        )(params, prompt)
        want = _seq_logprob(model, params, toks, N)
        np.testing.assert_allclose(
            np.asarray(scores), np.asarray(want), rtol=1e-4, atol=1e-4
        )

    def test_beats_or_matches_greedy_likelihood(self):
        model, params, prompt = _setup(2)
        g = generate(model, params, prompt, N)
        toks = make_beam_search_fn(model, max_new_tokens=N, beam_size=4)(
            params, prompt
        )
        lp_beam = _seq_logprob(model, params, toks, N)
        lp_greedy = _seq_logprob(model, params, g, N)
        assert (np.asarray(lp_beam) >= np.asarray(lp_greedy) - 1e-4).all()

    def test_gqa_model(self):
        model, params, prompt = _setup(3, n_kv_heads=2)
        toks, scores = make_beam_search_fn(
            model, max_new_tokens=N, beam_size=3, return_scores=True
        )(params, prompt)
        want = _seq_logprob(model, params, toks, N)
        np.testing.assert_allclose(
            np.asarray(scores), np.asarray(want), rtol=1e-4, atol=1e-4
        )

    def test_quantized_matches_quantized_greedy_at_beam_one(self):
        from horovod_tpu.models.decoding import make_generate_fn
        from horovod_tpu.models.quant import quantize_params

        model, params, prompt = _setup(4)
        q = quantize_params(params, min_size=64)
        g = make_generate_fn(model, max_new_tokens=N, quantized=True)(
            q, prompt, jax.random.PRNGKey(0)
        )
        b1 = make_beam_search_fn(
            model, max_new_tokens=N, beam_size=1, quantized=True
        )(q, prompt)
        np.testing.assert_array_equal(np.asarray(b1), np.asarray(g))

    def test_eos_freezes_and_pads(self):
        """After a beam emits eos it expands only to eos at zero score
        cost, and the returned row is eos-padded past the first eos."""
        model, params, prompt = _setup(5)
        eos = 7
        toks = make_beam_search_fn(
            model, max_new_tokens=N, beam_size=3, eos_id=eos,
            include_prompt=False,
        )(params, prompt)
        arr = np.asarray(toks)
        for row in arr:
            hits = np.where(row == eos)[0]
            if hits.size:
                assert (row[hits[0]:] == eos).all()

    def test_include_prompt_and_validation(self):
        model, params, prompt = _setup(6)
        full = make_beam_search_fn(model, max_new_tokens=4, beam_size=2)(
            params, prompt
        )
        tail = make_beam_search_fn(
            model, max_new_tokens=4, beam_size=2, include_prompt=False
        )(params, prompt)
        np.testing.assert_array_equal(
            np.asarray(full[:, 6:]), np.asarray(tail)
        )
        with pytest.raises(ValueError, match="beam_size"):
            make_beam_search_fn(model, max_new_tokens=4, beam_size=0)
        with pytest.raises(ValueError, match="max_new_tokens"):
            make_beam_search_fn(model, max_new_tokens=0, beam_size=2)

    def test_length_penalty_prefers_longer(self):
        """With eos in play, a positive length penalty divides scores by
        ((5+len)/6)^alpha — the returned score must equal the penalized
        recompute (bookkeeping check, not a behavioral claim)."""
        model, params, prompt = _setup(7)
        eos = 3
        toks, scores = make_beam_search_fn(
            model, max_new_tokens=N, beam_size=3, eos_id=eos,
            length_penalty=0.8, return_scores=True, include_prompt=False,
        )(params, prompt)
        arr = np.asarray(toks)
        # recompute: raw logprob of the kept tokens / penalty(len)
        full = jnp.concatenate([prompt, toks], axis=1)
        lp = np.asarray(_seq_logprob_masked(model, params, full, arr, eos))
        lens = []
        for row in arr:
            hits = np.where(row == eos)[0]
            lens.append(hits[0] + 1 if hits.size else N)
        norm = ((5.0 + np.asarray(lens)) / 6.0) ** 0.8
        np.testing.assert_allclose(
            np.asarray(scores), lp / norm, rtol=1e-3, atol=1e-3
        )


def _seq_logprob_masked(model, params, full, gen_arr, eos):
    """Raw log-prob of generated tokens up to and including the first eos
    (positions after it were force-padded and contributed zero score)."""
    n = gen_arr.shape[1]
    logits = model.apply({"params": params}, full[:, :-1])
    lp = jax.nn.log_softmax(logits.astype(jnp.float32))
    pick = np.asarray(
        jnp.take_along_axis(lp, full[:, 1:, None], -1)[..., 0]
    )[:, -n:]
    out = []
    for row_lp, row in zip(pick, gen_arr):
        hits = np.where(row == eos)[0]
        ln = hits[0] + 1 if hits.size else n
        out.append(row_lp[:ln].sum())
    return np.asarray(out)
