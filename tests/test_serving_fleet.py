"""Slow-lane e2e for the elastic serving fleet: 2 continuous-batching
replicas behind the router, SIGTERM lands on one MID-TRAFFIC, and the
contract under test is the operator story — the dying replica announces
a clean `leave` to the coordinator, the router drains it (in-flight
requests finish, nothing new lands), every driven request succeeds, and
the journal records the departure as a leave, not a crash."""

import json
import os
import signal
import threading
import time

import pytest

from horovod_tpu.serving import fleet as fleet_mod

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def demo_bundle(tmp_path_factory):
    return fleet_mod._export_demo_bundle(
        str(tmp_path_factory.mktemp("serve-fleet-bundle"))
    )


def _journal_events(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_sigterm_mid_traffic_drains_cleanly(demo_bundle, tmp_path):
    journal = str(tmp_path / "restarts.jsonl")
    fleet = fleet_mod.ServeFleet(
        demo_bundle, replicas=2, log_path=journal,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    ).start()
    try:
        out = {}

        def load():
            out["result"] = fleet_mod._drive_load(
                fleet.router_url, 30, n_threads=4
            )

        t = threading.Thread(target=load)
        t.start()
        # Let traffic establish, then kill one replica under it.
        time.sleep(1.0)
        victim = fleet.replicas["serve-0"]
        victim.proc.send_signal(signal.SIGTERM)
        t.join(timeout=180)
        assert not t.is_alive(), "load generator wedged"
        ok, failed, failures = out["result"]
        assert failed == 0, f"requests failed through the drain: {failures}"
        assert ok == 30

        # The replica exited on its own terms (rc 0, not a kill).
        assert victim.proc.wait(timeout=30) == 0
        # ... and the watchdog removed it from rotation.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if fleet.router.replicas.get("serve-0") is None:
                break
            time.sleep(0.1)
        assert fleet.router.replicas.get("serve-0") is None
        assert fleet.router.replicas.live_count() == 1

        # Traffic still flows on the survivor.
        ok2, failed2, failures2 = fleet_mod._drive_load(
            fleet.router_url, 6, n_threads=2
        )
        assert (ok2, failed2) == (6, 0), failures2
    finally:
        fleet.stop()

    events = _journal_events(journal)
    names = [e["name"] for e in events]
    assert names.count("serve_replica_up") == 2
    # The SIGTERM'd replica LEFT — a journaled clean leave, and the
    # watchdog's removal cites the leave, not a crash/exit.
    leaves = [e for e in events if e["name"] == "leave"
              and e.get("member") == "serve-0"]
    assert leaves, f"no clean leave in journal: {names}"
    downs = [e for e in events if e["name"] == "serve_replica_down"
             and e.get("member") == "serve-0"]
    assert downs and downs[0]["reason"] == "leave", downs
    # The survivor's own stop is also a leave (fleet.stop SIGTERMs it).
    assert "serve_stop" in names
