"""Tier-1 gate: the shipped tree is `hvt-lint`-clean (ISSUE 6).

Three drift directions are closed here:

* code drift — any non-baselined finding in ``horovod_tpu/`` fails CI
  (the prose invariants of PRs 1-5 are now machine-checked);
* baseline drift — a baseline entry whose flagged line was since fixed or
  edited no longer matches anything and must be deleted;
* doc drift — ``docs/ENVVARS.md`` must be byte-identical to what
  `registry.generate_doc()` renders, and every registered knob must still
  be referenced somewhere in the tree (a knob documented but no longer
  read is drift too, just in the other direction).
"""

import os
import re
import subprocess
import sys

from horovod_tpu.analysis import core, registry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "horovod_tpu")


def _lint_package():
    return core.lint_paths([PACKAGE], root=REPO)


class TestLintClean:
    def test_package_is_lint_clean(self):
        result = _lint_package()
        assert result.files > 50  # the walk actually covered the package
        assert not result.findings, (
            "hvt-lint found non-baselined issues — fix them, or baseline "
            "with a one-line justification "
            "(horovod_tpu/analysis/baseline.json):\n"
            + "\n".join(f.format() for f in result.findings)
        )

    def test_no_stale_baseline_entries(self):
        """Every committed baseline entry still matches a live finding —
        a fixed site must take its grandfather clause with it."""
        entries = core.load_baseline(core.DEFAULT_BASELINE)
        result = _lint_package()
        matched = {(f.rule, f.path, f.snippet) for f in result.baselined}
        stale = [
            e for e in entries
            if (e["rule"], e["path"], e["snippet"]) not in matched
        ]
        assert not stale, (
            "baseline entries no longer match any finding — delete them:\n"
            + "\n".join(f"{e['rule']} {e['path']}: {e['snippet']}"
                        for e in stale)
        )

    def test_cli_exit_code_contract(self):
        """`hvt-lint horovod_tpu/` exits 0 on the shipped tree — the
        pre-commit-hook surface, end to end through the real CLI."""
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.analysis", "horovod_tpu"],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "0 finding(s)" in proc.stdout


class TestSchedCheckClean:
    """Tier-1 gate (ISSUE 14): the shipped tree passes whole-program
    schedule verification — every unit's rank-feasible paths submit one
    collective sequence per uniform configuration, and the real entry
    paths (Trainer loops, elastic commit/sync, rescale boundary,
    checkpoint save/broadcast) each verify."""

    def test_package_schedule_verifies(self):
        result = core.lint_paths(
            [PACKAGE], root=REPO, select=["HVT010"]
        )
        assert result.files > 50
        assert not result.findings, (
            "hvt-sched found schedule divergences — fix them, or "
            "baseline with a one-line justification:\n"
            + "\n".join(f.format() for f in result.findings)
        )

    def test_entry_paths_all_agree(self):
        """Every declared entry automaton verifies AND exists — a
        renamed entry unit must update schedule.ENTRY_PATHS, not
        silently drop out of the report."""
        from horovod_tpu.analysis import schedule

        modules = []
        for path in core.iter_python_files([PACKAGE]):
            with open(path, encoding="utf-8") as f:
                modules.append(core.ModuleSource(
                    path, os.path.relpath(path, REPO), f.read()
                ))
        graph = core.Project(modules).callgraph()
        rows = schedule.entry_report(graph)
        assert len(rows) == len(schedule.ENTRY_PATHS), (
            "entry units missing from the module set — update "
            "schedule.ENTRY_PATHS for the rename: "
            f"{[r['unit'] for r in rows]}"
        )
        diverging = [r["unit"] for r in rows if not r["agree"]]
        assert not diverging, f"entry automata diverge: {diverging}"
        # The elastic sync boundary is the load-bearing one: its
        # automaton must actually carry the snapshot transport.
        sync = next(r for r in rows if r["unit"].endswith("ElasticState.sync"))
        assert "allgather_object" in sync["sequence"]

    def test_sched_cli_exit_code_contract(self):
        """`hvt-sched check horovod_tpu/` exits 0 on the shipped tree —
        the pre-commit surface, end to end through the real CLI."""
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.analysis.sched_cli",
             "check", "horovod_tpu"],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "0 schedule finding(s)" in proc.stdout
        assert "entry horovod_tpu.elastic.state:ElasticState.sync" in (
            proc.stdout
        )
        assert "DIVERGE" not in proc.stdout


class TestEnvvarsDoc:
    DOC = os.path.join(REPO, "docs", "ENVVARS.md")

    def test_regeneration_produces_no_diff(self):
        with open(self.DOC) as f:
            on_disk = f.read()
        assert on_disk == registry.generate_doc(), (
            "docs/ENVVARS.md is stale — regenerate: "
            "python -m horovod_tpu.analysis.registry > docs/ENVVARS.md"
        )

    def test_every_registered_knob_is_read_somewhere(self):
        """Reverse drift: a registered knob nothing references anymore
        should be deleted from the registry (and thus from the doc)."""
        referenced = set()
        roots = [PACKAGE, os.path.join(REPO, "examples"),
                 os.path.join(REPO, "benchmarks"),
                 os.path.join(REPO, "bench.py")]
        for path in core.iter_python_files(p for p in roots
                                           if os.path.exists(p)):
            if os.path.abspath(path).startswith(
                os.path.join(PACKAGE, "analysis") + os.sep
            ):
                continue  # the registry declaring a name is not a use
            with open(path, encoding="utf-8") as f:
                referenced.update(re.findall(r"HVT_[A-Z0-9_]+", f.read()))
        unused = sorted(set(registry.KNOBS) - referenced)
        assert not unused, (
            f"registered knobs referenced nowhere: {unused} — remove the "
            "Knob rows and regenerate docs/ENVVARS.md"
        )

    def test_readme_links_envvars_doc(self):
        with open(os.path.join(REPO, "README.md")) as f:
            assert "docs/ENVVARS.md" in f.read()


class TestLintRulesDoc:
    """The ENVVARS.md contract, applied to the rule registry: the
    committed docs/LINT_RULES.md must be byte-identical to what the rule
    metadata renders (ISSUE 9 satellite)."""

    DOC = os.path.join(REPO, "docs", "LINT_RULES.md")

    def test_regeneration_produces_no_diff(self):
        with open(self.DOC) as f:
            on_disk = f.read()
        assert on_disk == core.generate_rules_doc(), (
            "docs/LINT_RULES.md is stale — regenerate: "
            "python -m horovod_tpu.analysis.rules > docs/LINT_RULES.md"
        )

    def test_every_rule_carries_metadata(self):
        """A rule without rationale/provenance renders an empty doc
        section — refuse at the gate, not in review."""
        for cls in core.iter_rules():
            assert cls.rationale, f"{cls.rule_id} has no rationale"
            assert cls.provenance, f"{cls.rule_id} has no provenance"
            assert cls.example, f"{cls.rule_id} has no example"

    def test_readme_links_rules_doc(self):
        with open(os.path.join(REPO, "README.md")) as f:
            assert "docs/LINT_RULES.md" in f.read()
