"""HTTP model server over a StableHLO serving bundle: the TF-Serving role
(mnist_keras.py:126-140's 'so it can be served') with the input→prob
contract over real HTTP — health, predict, server-side batch pad/split,
and input validation."""

import json
import threading
import urllib.request

import jax
import numpy as np
import pytest

from horovod_tpu import checkpoint
from horovod_tpu.launch.serve import make_server

BATCH, DIM, CLASSES = 4, 6, 3


@pytest.fixture(scope="module")
def bundle(tmp_path_factory):
    import flax.linen as nn

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = False):
            return nn.Dense(CLASSES)(x)

    model = Tiny()
    x0 = np.zeros((BATCH, DIM), np.float32)
    params = model.init(jax.random.PRNGKey(0), x0)["params"]
    d = tmp_path_factory.mktemp("export")
    out = checkpoint.export_serving(
        str(d),
        lambda p, x: model.apply({"params": p}, x),
        params,
        input_shape=(BATCH, DIM),
        timestamp="19700101-000000",
    )
    return out, model, params


@pytest.fixture(scope="module")
def server(bundle):
    out, _, _ = bundle
    srv = make_server(out, port=0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()


def _url(server, path):
    return f"http://127.0.0.1:{server.server_address[1]}{path}"


def _post(server, path, payload):
    req = urllib.request.Request(
        _url(server, path), data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_healthz(server):
    with urllib.request.urlopen(_url(server, "/healthz")) as r:
        body = json.loads(r.read())
    assert body["status"] == "ok"
    assert body["signature"]["inputs"]["input"]["shape"] == [BATCH, DIM]


def test_predict_matches_local(server, bundle):
    _, model, params = bundle
    rng = np.random.RandomState(0)
    x = rng.randn(BATCH, DIM).astype(np.float32)
    status, body = _post(server, "/v1/predict", {"input": x.tolist()})
    assert status == 200
    want = jax.nn.softmax(model.apply({"params": params}, x), axis=-1)
    np.testing.assert_allclose(
        np.asarray(body["prob"]), np.asarray(want), atol=1e-5
    )


def test_pad_and_split_arbitrary_row_counts(server, bundle):
    """Clients never see the compiled batch shape: 1 row pads up, 11 rows
    split into compiled-batch chunks."""
    _, model, params = bundle
    rng = np.random.RandomState(1)
    for n in (1, BATCH - 1, BATCH, 2 * BATCH + 3):
        x = rng.randn(n, DIM).astype(np.float32)
        status, body = _post(server, "/v1/predict", {"input": x.tolist()})
        assert status == 200
        prob = np.asarray(body["prob"])
        assert prob.shape == (n, CLASSES)
        want = jax.nn.softmax(model.apply({"params": params}, x), axis=-1)
        np.testing.assert_allclose(prob, np.asarray(want), atol=1e-5)


def test_bad_input_is_400_not_crash(server):
    status, body = _post(server, "/v1/predict", {"input": [[1.0, 2.0]]})
    assert status == 400 and "error" in body
    status, body = _post(server, "/v1/predict", {"wrong_key": []})
    assert status == 400
    status, body = _post(server, "/nope", {"input": []})
    assert status == 404


def test_unknown_get_404(server):
    try:
        urllib.request.urlopen(_url(server, "/nope"))
        raise AssertionError("expected 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404


def test_runtime_failure_is_500_json(server):
    """An unexpected error inside the model call must surface as a 5xx
    JSON body, not a dropped socket."""
    app = server.app
    orig = app.fn
    app.fn = lambda x: (_ for _ in ()).throw(RuntimeError("device fell over"))
    try:
        x = np.zeros((BATCH, DIM), np.float32)
        status, body = _post(server, "/v1/predict", {"input": x.tolist()})
        assert status == 500
        assert "device fell over" in body["error"]
    finally:
        app.fn = orig


class TestCoalescing:
    """Concurrent single-row requests must share device dispatches (the
    coalescing queue), not serialize one call each."""

    def test_concurrent_requests_coalesce_and_match(self, bundle):
        import threading as th
        import time

        out, model, params = bundle
        srv = make_server(out, port=0)
        t = th.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            app = srv.app
            real_fn = app.fn

            def slow_fn(x):  # hold the device so the queue builds up
                time.sleep(0.15)
                return real_fn(x)

            app.fn = slow_fn
            rng = np.random.RandomState(7)
            xs = [rng.randn(1, DIM).astype(np.float32) for _ in range(8)]
            results = [None] * 8
            errors = []

            def client(i):
                try:
                    status, body = _post(
                        srv, "/v1/predict", {"input": xs[i].tolist()}
                    )
                    assert status == 200, body
                    results[i] = np.asarray(body["prob"])
                except Exception as e:  # surface in the main thread
                    errors.append(e)

            threads = [th.Thread(target=client, args=(i,)) for i in range(8)]
            for c in threads:
                c.start()
            for c in threads:
                c.join(timeout=30)
            assert not errors, errors
            # Correctness per client, whatever the packing was.
            for i in range(8):
                want = jax.nn.softmax(
                    model.apply({"params": params}, xs[i]), axis=-1
                )
                np.testing.assert_allclose(
                    results[i], np.asarray(want), atol=1e-5
                )
            # Coalescing: 8 rows at batch 4 with a held device must pack —
            # strictly fewer dispatches than requests.
            assert app.stats["rows"] == 8
            assert app.stats["device_calls"] < 8, app.stats
        finally:
            srv.shutdown()

    def test_coalesce_false_keeps_serialized_baseline(self, bundle):
        out, model, params = bundle
        srv = make_server(out, port=0, coalesce=False)
        import threading as th

        t = th.Thread(target=srv.serve_forever, daemon=True)
        t.start()
        try:
            x = np.random.RandomState(3).randn(2, DIM).astype(np.float32)
            status, body = _post(srv, "/v1/predict", {"input": x.tolist()})
            assert status == 200
            want = jax.nn.softmax(model.apply({"params": params}, x), axis=-1)
            np.testing.assert_allclose(
                np.asarray(body["prob"]), np.asarray(want), atol=1e-5
            )
            assert srv.app.stats["device_calls"] == 1
        finally:
            srv.shutdown()


def test_healthz_fleet_section_from_journal(bundle, tmp_path):
    """`--fleet-journal` surfaces the supervisor's restart/rescale journal
    in serving health: generation/size from the last settle, counts, and
    the trailing events (the ROADMAP follow-up the elastic PR closes)."""
    out, _, _ = bundle
    journal = tmp_path / "restarts.jsonl"
    with open(journal, "w") as f:
        for rec in (
            {"name": "start", "value": 3.0, "generation": 3, "size": 3},
            {"name": "leave", "value": 1.0, "member": "m1", "generation": 4},
            {"name": "shrink", "value": 2.0, "generation": 4, "size": 2},
            {"name": "restarts", "value": 1.0, "member": "m1",
             "kind": "leave"},
            {"name": "grow", "value": 3.0, "generation": 5, "size": 3},
        ):
            f.write(json.dumps(rec) + "\n")
    srv = make_server(out, port=0, fleet_journal=str(journal))
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        with urllib.request.urlopen(_url(srv, "/healthz")) as r:
            body = json.loads(r.read())
        fleet = body["fleet"]
        assert fleet["generation"] == 5 and fleet["size"] == 3
        assert fleet["shrinks"] == 1 and fleet["grows"] == 1
        assert fleet["restarts"] == 1
        assert [e["name"] for e in fleet["events"]][-1] == "grow"
        # Journal is read per request: a new event shows up live.
        with open(journal, "a") as f:
            f.write(json.dumps(
                {"name": "shrink", "value": 2.0, "generation": 6, "size": 2}
            ) + "\n")
        with urllib.request.urlopen(_url(srv, "/healthz")) as r:
            body = json.loads(r.read())
        assert body["fleet"]["size"] == 2
        assert body["fleet"]["shrinks"] == 2
    finally:
        srv.shutdown()


def test_healthz_without_journal_has_no_fleet_section(server):
    with urllib.request.urlopen(_url(server, "/healthz")) as r:
        body = json.loads(r.read())
    assert "fleet" not in body


class TestServeMetrics:
    """GET /metrics on the serving server (ISSUE 13): request counters by
    route/code, device-call/row totals mirrored from app.stats, queue
    depth, and the latency/TTFT histograms — valid text exposition."""

    def test_metrics_route_serves_valid_exposition(self, server, bundle):
        from horovod_tpu.obs import prom

        rows = np.random.rand(3, DIM).astype(np.float32)
        status, _ = _post(server, "/v1/predict", {"input": rows.tolist()})
        assert status == 200
        with urllib.request.urlopen(_url(server, "/metrics")) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
        values = prom.parse_text(text)
        assert values["hvt_serve_rows_total"] >= 3
        assert values["hvt_serve_device_calls_total"] >= 1
        assert values["hvt_serve_queue_depth"] == 0
        assert (
            values['hvt_serve_requests_total{route="/v1/predict",code="200"}']
            >= 1
        )
        # Histogram invariants on the request-latency family.
        route = 'route="/v1/predict"'
        count = values[f"hvt_serve_request_seconds_count{{{route}}}"]
        inf = values[f'hvt_serve_request_seconds_bucket{{{route},le="+Inf"}}']
        assert count >= 1 and inf == count
        assert f"hvt_serve_request_seconds_sum{{{route}}}" in values
        # HELP/TYPE present for every exposed family.
        families = {
            line.split()[2] for line in text.splitlines()
            if line.startswith("# TYPE")
        }
        helps = {
            line.split()[2] for line in text.splitlines()
            if line.startswith("# HELP")
        }
        assert families and families == helps

    def test_error_requests_counted_by_code(self, server):
        status, _ = _post(server, "/v1/predict", {"wrong": 1})
        assert status == 400
        with urllib.request.urlopen(_url(server, "/metrics")) as r:
            text = r.read().decode()
        from horovod_tpu.obs import prom

        values = prom.parse_text(text)
        assert (
            values['hvt_serve_requests_total{route="/v1/predict",code="400"}']
            >= 1
        )

    def test_per_server_registries_are_private(self, bundle):
        # Two servers over the same bundle: each carries its own
        # instrument store (no cross-talk between fleets in one process).
        out, _, _ = bundle
        a = make_server(out, port=0)
        b = make_server(out, port=0)
        assert a.metrics_registry is not b.metrics_registry
        a.server_close()
        b.server_close()
