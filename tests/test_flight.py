"""Flight recorder + `hvt-sched replay` (ISSUE 14 runtime side).

Units pin the recorder contracts (bounded ring, write-through JSONL,
the off-by-default zero-cost gate — asserted structurally against
collectives.py's AST), the replay cross-check (mismatch / missing /
extra, context windows, the 0/1/2 exit contract), the `reorder` fault's
seeded divergence, the POST /flightrecord surface, and the supervisor's
hang-path collection + `hvt_flight_dumps_total`. The slow e2e is the
ISSUE acceptance run: a 2-proc supervised fleet with
``HVT_FAULT=0:1:reorder`` hangs, the supervisor auto-collects every
member's record, and `hvt-sched replay` exits nonzero naming the exact
rank/seq/op.
"""

import ast
import json
import os
import sys
import urllib.request

import pytest

from horovod_tpu import flight
from horovod_tpu.analysis import sched_cli
from horovod_tpu.testing import faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def recorder(tmp_path):
    flight.disable()
    rec = flight.enable(str(tmp_path / "flight"), size=8)
    yield rec
    flight.disable()


class TestRecorder:
    def test_write_through_and_fields(self, recorder):
        recorder.record("broadcast_object", tag="sync")
        recorder.record("reduce_gradients", dtype="float32", shape=(64,),
                        nbytes=256, bucket=0, tag="step")
        lines = [json.loads(l) for l in open(recorder.path)]
        assert [r["seq"] for r in lines] == [0, 1]
        assert lines[1] == {
            "seq": 1, "kind": "reduce_gradients", "dtype": "float32",
            "shape": [64], "bytes": 256, "bucket": 0, "tag": "step",
            "t": lines[1]["t"],
        }

    def test_ring_bound_and_dump_rewrite(self, recorder):
        for i in range(20):
            recorder.record("allreduce", bucket=i)
        assert recorder.count == 8  # the HVT_FLIGHT_RECORD_SIZE bound
        recorder.dump()
        lines = [json.loads(l) for l in open(recorder.path)]
        assert len(lines) == 8
        assert [r["seq"] for r in lines] == list(range(12, 20))

    def test_swap_last_two_seeds_divergence(self, recorder):
        recorder.record("broadcast_pytree", tag="a")
        recorder.record("broadcast_object", tag="b")
        assert recorder.swap_last_two()
        lines = [json.loads(l) for l in open(recorder.path)]
        # seqs keep their order; the op payloads traded places.
        assert [r["seq"] for r in lines] == [0, 1]
        assert [r["kind"] for r in lines] == [
            "broadcast_object", "broadcast_pytree",
        ]
        assert [r["tag"] for r in lines] == ["b", "a"]

    def test_swap_needs_two_records(self, recorder):
        recorder.record("allreduce")
        assert not recorder.swap_last_two()

    def test_collect_quarantines_copies(self, recorder, tmp_path):
        recorder.record("allreduce")
        recorder.dump()
        src_dir = os.path.dirname(recorder.path)
        dest = str(tmp_path / "hang-1")
        copied = flight.collect(src_dir, dest)
        assert len(copied) == 1
        assert flight.read_records(copied[0])[0]["kind"] == "allreduce"


class TestZeroCostOff:
    def test_recorder_off_by_default(self, monkeypatch):
        flight.disable()
        monkeypatch.delenv("HVT_FLIGHT_RECORD", raising=False)
        assert flight.enable() is None
        assert flight.RECORDER is None

    def test_collectives_gate_is_structural(self):
        """The zero-cost contract, asserted against the AST: every
        submission site in collectives.py routes through the ONE
        `_maybe_record` gate, whose off-path is exactly a
        ``flight.RECORDER`` load + ``is None`` return — no other code
        in the module touches the flight module."""
        path = os.path.join(
            REPO, "horovod_tpu", "parallel", "collectives.py"
        )
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
        gate = next(
            n for n in ast.walk(tree)
            if isinstance(n, ast.FunctionDef) and n.name == "_maybe_record"
        )
        body = [s for s in gate.body
                if not isinstance(s, ast.Expr)
                or not isinstance(s.value, ast.Constant)]  # skip docstring
        first, second = body[0], body[1]
        assert isinstance(first, ast.Assign)
        assert ast.unparse(first.value) == "flight.RECORDER"
        assert isinstance(second, ast.If)
        assert ast.unparse(second.test).endswith("is None")
        assert isinstance(second.body[0], ast.Return)
        # Every flight-module touch outside the gate is the import.
        sites = 0
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Name
            ) and node.func.id == "_maybe_record":
                sites += 1
        assert sites >= 10  # every submission site feeds the recorder
        touches = [
            n for n in ast.walk(tree)
            if isinstance(n, ast.Attribute)
            and isinstance(n.value, ast.Name) and n.value.id == "flight"
        ]
        assert all(
            gate.lineno <= t.lineno <= gate.end_lineno for t in touches
        )


def _write_records(directory, label, ops):
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"flight-{label}.jsonl")
    # Test fixture, not a crash-consistency artifact.
    with open(path, "w") as f:  # hvt: noqa[HVT005]
        for i, op in enumerate(ops):
            rec = {"seq": i, "t": float(i)}
            rec.update(op)
            f.write(json.dumps(rec) + "\n")
    return path


class TestReplayCrossCheck:
    OPS = [
        {"kind": "broadcast_pytree", "tag": "train_begin"},
        {"kind": "broadcast_object", "tag": "train_begin"},
        {"kind": "allgather_object", "tag": "epoch_end"},
    ]

    def test_agreement(self, tmp_path):
        d = str(tmp_path)
        _write_records(d, "rank0", self.OPS)
        _write_records(d, "rank1", self.OPS)
        by = {lb: flight.read_records(p) for lb, p in [
            ("rank0", os.path.join(d, "flight-rank0.jsonl")),
            ("rank1", os.path.join(d, "flight-rank1.jsonl")),
        ]}
        assert flight.first_divergence(by) is None
        assert sched_cli.main(["replay", d]) == 0

    def test_mismatch_names_rank_seq_op(self, tmp_path, capsys):
        d = str(tmp_path)
        swapped = [self.OPS[1], self.OPS[0], self.OPS[2]]
        _write_records(d, "rank0", swapped)
        _write_records(d, "rank1", self.OPS)
        assert sched_cli.main(["replay", d]) == 1
        out = capsys.readouterr().out
        assert "first divergent submission at seq 0 (mismatch)" in out
        assert "member rank0: broadcast_object" in out
        assert "member rank1: broadcast_pytree" in out
        assert ">> [0]" in out  # the context-window marker

    def test_missing_submission(self, tmp_path, capsys):
        d = str(tmp_path)
        _write_records(d, "rank0", self.OPS)
        _write_records(d, "rank1", self.OPS[:2])  # wedged before op 2
        assert sched_cli.main(["replay", d]) == 1
        out = capsys.readouterr().out
        assert "seq 2 (missing)" in out
        assert "(no submission)" in out

    def test_ring_truncation_is_not_divergence(self, tmp_path):
        """Coverage asymmetry — one member's ring dropped early history
        while a natively-wedged peer's write-through file kept it all —
        must NOT read as divergence: only the commonly-covered seq
        window is compared."""
        ops = [{"kind": k} for k in
               ("broadcast_pytree", "allreduce", "allgather_object",
                "broadcast_object")]
        full = [dict(seq=i, t=float(i), **op) for i, op in enumerate(ops)]
        truncated = full[2:]  # the ring kept only seqs 2..3
        assert flight.first_divergence(
            {"rank0": full, "rank1": truncated}
        ) is None
        # A genuinely silent member is still the verdict, not a window
        # artifact.
        div = flight.first_divergence({"rank0": full, "rank1": []})
        assert div is not None and div["seq"] == 0
        # And one empty member must not re-expose ANOTHER member's
        # ring-truncated head as a false missing: the window still
        # clips, and the empty member is the named divergence.
        div3 = flight.first_divergence(
            {"rank0": full, "rank1": truncated, "rank2": []}
        )
        assert div3 is not None
        assert div3["seq"] == 2  # the window start, not seq 0
        assert div3["member_b"] == "rank2"

    def test_usage_errors_exit_2(self, tmp_path, capsys):
        assert sched_cli.main(
            ["replay", str(tmp_path / "nope")]
        ) == 2
        assert "no flight-" in capsys.readouterr().err
        d = str(tmp_path)
        _write_records(d, "rank0", self.OPS)
        assert sched_cli.main(["replay", d]) == 2  # one rank can't cross-check
        assert "at least two ranks" in capsys.readouterr().err


class TestReorderFault:
    def test_parse_plan_accepts_reorder(self):
        plan = faults.parse_plan("0:1:reorder")
        assert plan.kind == "reorder" and plan.rank == 0 and plan.epoch == 1

    def test_fire_swaps_then_wedges(self, recorder, monkeypatch):
        recorder.record("broadcast_pytree", tag="a")
        recorder.record("broadcast_object", tag="b")
        wedged = []
        monkeypatch.setattr(
            faults.FaultInjectionCallback, "_wedge",
            staticmethod(lambda: wedged.append(True)),
        )
        cb = faults.FaultInjectionCallback(faults.parse_plan("0:0:reorder"))
        cb._fire()
        assert wedged == [True]
        assert [r["kind"] for r in recorder.records] == [
            "broadcast_object", "broadcast_pytree",
        ]

    def test_recorded_submission_sites_feed_recorder(self, recorder):
        """The collectives gate actually reaches the recorder: a
        host-level object collective in a single-process world records
        its submission (kind + caller tag) before degrading to the
        identity."""
        from horovod_tpu.parallel import collectives

        def my_caller():
            return collectives.broadcast_object({"cfg": 1})

        my_caller()
        assert recorder.count == 1
        rec = recorder.records[-1]
        assert rec["kind"] == "broadcast_object"
        assert "my_caller" in rec["tag"]


class TestPostFlightrecord:
    def test_post_dumps_and_reports(self, recorder):
        from horovod_tpu.obs import server as obs_server

        recorder.record("allreduce")
        srv = obs_server.start_metrics_server(0)
        try:
            port = srv.server_address[1]
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/flightrecord", method="POST"
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                payload = json.loads(resp.read())
            assert payload["records"] == 1
            assert payload["path"] == recorder.path
            assert os.path.exists(payload["path"])
        finally:
            srv.shutdown()

    def test_post_without_recorder_is_409(self):
        from horovod_tpu.obs import server as obs_server

        flight.disable()
        srv = obs_server.start_metrics_server(0)
        try:
            port = srv.server_address[1]
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/flightrecord", method="POST"
            )
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=10)
            assert e.value.code == 409
        finally:
            srv.shutdown()


class TestSupervisorCollection:
    def test_hang_collection_journals_and_counts(self, tmp_path):
        from horovod_tpu.launch import supervisor

        flight_dir = str(tmp_path / "flight")
        _write_records(flight_dir, "rank0", TestReplayCrossCheck.OPS)
        _write_records(flight_dir, "rank1", TestReplayCrossCheck.OPS)
        log_path = str(tmp_path / "restarts.jsonl")
        log = supervisor.RestartLog(log_path)
        files = supervisor.collect_flight_records(
            flight_dir, log, attempt=2, kind="hang"
        )
        assert len(files) == 2
        assert all(os.path.dirname(f).endswith("hang-2") for f in files)
        records = supervisor.journal_records(log_path)
        dump = next(r for r in records if r["name"] == "flight_dump")
        assert dump["files"] == [
            "flight-rank0.jsonl", "flight-rank1.jsonl",
        ]
        # The journal record is what the /metrics scrape counts.
        reg = supervisor.supervisor_metrics(log_path)
        series = {
            spec.name: values for spec, values in reg.collect()
        }
        assert series["hvt_flight_dumps_total"] == [((), 1.0)]

    def test_no_flight_dir_is_a_noop(self, tmp_path):
        from horovod_tpu.launch import supervisor

        log = supervisor.RestartLog(str(tmp_path / "restarts.jsonl"))
        assert supervisor.collect_flight_records(None, log, 1) == []
        assert supervisor.journal_records(log.path) == []


@pytest.mark.slow
def test_reorder_hang_collect_replay_e2e(tmp_path, capfd):
    """THE ISSUE 14 acceptance run: a 2-proc supervised fleet with
    ``HVT_FAULT=0:1:reorder`` — rank 0 swaps its last two recorded
    submissions and wedges, its peer blocks in the next step's
    collective, the supervisor classifies the hang, auto-collects every
    member's flight record into a quarantine dir (journaling
    ``flight_dump``), relaunches (stamp: the fault is spent), and the
    rerun completes. `hvt-sched replay` over the collected dir then
    exits nonzero naming rank 0, the swapped seq, and the ops."""
    from horovod_tpu.launch import supervisor
    from horovod_tpu.launch.supervisor import RestartPolicy
    from tests.test_supervisor import write_train_script

    argv = write_train_script(tmp_path)
    model_dir = tmp_path / "models"
    flight_dir = tmp_path / "flight"
    log = tmp_path / "restarts.jsonl"
    env = {
        "HVT_PLATFORM": "cpu",
        "HVT_NUM_CPU_DEVICES": "2",
        "PS_MODEL_PATH": str(model_dir),
        "DRIVE_EPOCHS": "2",
        "HVT_FAULT": "0:1:reorder",
        "HVT_FAULT_STAMP": str(tmp_path / "fault-stamp"),
        "HVT_FLIGHT_RECORD": str(flight_dir),
        # Chaos children stay out of the shared XLA cache (see
        # test_supervisor_e2e._env).
        "JAX_ENABLE_COMPILATION_CACHE": "0",
        "JAX_COMPILATION_CACHE_DIR": "",
    }
    code = supervisor.supervise_local(
        2, argv, env=env,
        policy=RestartPolicy(
            max_restarts=4, backoff=0.0, grace_seconds=5.0,
            heartbeat_timeout=20.0,
        ),
        model_dir=str(model_dir), log_path=str(log),
        sleep=lambda s: None,
    )
    assert code == 0
    records = [json.loads(l) for l in open(log) if l.strip()]
    assert any(
        r["name"] == "restarts" and r["kind"] == "hang" for r in records
    )
    dumps = [r for r in records if r["name"] == "flight_dump"]
    assert dumps, "the hang classification must collect flight records"
    collected = dumps[0]["dir"]
    assert len(flight.record_files(collected)) == 2
    # The replay names the seeded divergence: rank 0, the swapped seq,
    # and the mismatched ops.
    rc = sched_cli.main(["replay", collected])
    out = capfd.readouterr().out
    assert rc == 1, out
    assert "replay FAILED" in out
    assert "mismatch" in out
    assert "rank0" in out and "rank1" in out
