"""Data layer: dataset contract parity + the sharding the reference lacks."""

import numpy as np
import pytest

from horovod_tpu.data import datasets
from horovod_tpu.data.loader import ArrayDataset


@pytest.mark.slow
def test_mnist_contract(tmp_cache):
    (x_train, y_train), (x_test, y_test) = datasets.mnist(path="mnist-0.npz")
    # Exact keras-layout contract (tensorflow2_keras_mnist.py:34-35)
    assert x_train.shape == (60_000, 28, 28) and x_train.dtype == np.uint8
    assert x_test.shape == (10_000, 28, 28)
    assert y_train.shape == (60_000,) and y_train.dtype == np.int64
    assert set(np.unique(y_train)) == set(range(10))
    # Deterministic + cached: second load identical
    (x2, y2), _ = datasets.mnist(path="mnist-0.npz")
    np.testing.assert_array_equal(x_train, x2)


@pytest.mark.slow
def test_mnist_per_rank_paths_differ_but_content_consistent(tmp_cache):
    # per-rank cache filename convention (race avoidance, §5.2)
    a = datasets.mnist(path="mnist-0.npz")
    b = datasets.mnist(path="mnist-1.npz")
    np.testing.assert_array_equal(a[0][0], b[0][0])


@pytest.mark.slow
def test_cifar_contract(tmp_cache):
    (x_train, y_train), (x_test, y_test) = datasets.cifar10()
    assert x_train.shape == (50_000, 32, 32, 3) and x_train.dtype == np.uint8
    assert x_test.shape == (10_000, 32, 32, 3)


def test_loader_chain_repeat_shuffle_batch():
    x = np.arange(100)
    y = np.arange(100) * 2
    ds = ArrayDataset((x, y)).repeat().shuffle(10, seed=3).batch(8)
    batches = ds.take(30)  # 240 examples -> crosses epoch boundary: repeat works
    assert all(b[0].shape == (8,) for b in batches)
    for xb, yb in batches:
        np.testing.assert_array_equal(yb, xb * 2)  # rows stay aligned
    # shuffle actually permutes
    flat = np.concatenate([b[0] for b in batches[:12]])
    assert not np.array_equal(flat[:96], np.arange(96))


def test_loader_shard_partitions_disjointly():
    x = np.arange(64)
    shards = [
        set(ArrayDataset((x,)).shard(i, 4)._arrays[0].tolist()) for i in range(4)
    ]
    assert set().union(*shards) == set(range(64))
    for i in range(4):
        for j in range(i + 1, 4):
            assert not shards[i] & shards[j]


def test_loader_no_repeat_stops():
    ds = ArrayDataset((np.arange(10),)).batch(4, drop_remainder=False)
    batches = list(ds)
    assert [len(b[0]) for b in batches] == [4, 4, 2]
    ds2 = ArrayDataset((np.arange(10),)).batch(4)
    assert [len(b[0]) for b in ds2] == [4, 4]


def test_loader_pytree_batches():
    """Dict (multi-input) datasets: batches keep the pytree structure, rows
    stay aligned across every leaf, and the flat-leaves + structure pair
    round-trips through training_pipeline."""
    x = {"src": np.arange(40), "tgt": np.arange(40) * 3}
    y = np.arange(40) * 7
    ds = ArrayDataset((x, y)).repeat().shuffle(40, seed=1).batch(5)
    for xb, yb in ds.take(16):
        assert set(xb) == {"src", "tgt"}
        np.testing.assert_array_equal(xb["tgt"], xb["src"] * 3)
        np.testing.assert_array_equal(yb, xb["src"] * 7)

    from horovod_tpu.data.loader import training_pipeline

    it, close = training_pipeline(
        ds.arrays, 5, seed=2, structure=ds.structure
    )
    try:
        xb, yb = next(it)
        assert set(xb) == {"src", "tgt"}
        np.testing.assert_array_equal(yb, xb["src"] * 7)
    finally:
        close()


def test_loader_pytree_shard_keeps_alignment():
    x = {"a": np.arange(16)}
    ds = ArrayDataset((x, np.arange(16) * 2)).shard(1, 4).batch(2)
    for xb, yb in ds:
        np.testing.assert_array_equal(yb, xb["a"] * 2)
        assert all(v % 4 == 1 for v in xb["a"])


def test_genuine_npz_preempts_synthesis(tmp_cache):
    """A keras-layout npz already at the cache path is LOADED, not
    regenerated — the real-data hook (SURVEY.md §2.1 data pipeline row;
    the synthetic path is a fallback, not a fork of the API)."""
    import os

    rng = np.random.RandomState(3)
    real = {
        "x_train": rng.randint(0, 255, size=(64, 28, 28), dtype=np.uint8),
        "y_train": rng.randint(0, 10, size=(64,)).astype(np.int64),
        "x_test": rng.randint(0, 255, size=(16, 28, 28), dtype=np.uint8),
        "y_test": rng.randint(0, 10, size=(16,)).astype(np.int64),
    }
    cache = os.environ["HVT_DATA_DIR"]
    np.savez_compressed(os.path.join(cache, "mnist-7.npz"), **real)
    (xtr, ytr), (xte, yte) = datasets.mnist(path="mnist-7.npz")
    np.testing.assert_array_equal(xtr, real["x_train"])
    np.testing.assert_array_equal(ytr, real["y_train"])
    np.testing.assert_array_equal(xte, real["x_test"])
    np.testing.assert_array_equal(yte, real["y_test"])


def test_loader_reshard_recuts_from_full_data():
    """Elastic rescale hook: resharding N→N-1 re-derives the split from the
    ORIGINAL arrays (not shard-of-shard), so the new world's shards again
    partition every example — each example is seen at least once per epoch
    after the shrink."""
    x = np.arange(60)
    shards3 = [ArrayDataset((x,)).shard(i, 3) for i in range(3)]
    # Mid-stream: rank 2 left; ranks 0..1 recut to a 2-way split.
    shards2 = [shards3[i].reshard(i, 2) for i in range(2)]
    seen = set()
    for ds in shards2:
        seen.update(ds._arrays[0].tolist())
    assert seen == set(range(60))  # full coverage at the new size
    # Disjoint partition, not shard-of-shard (which could only ever see
    # rank i's third of the data).
    assert not set(shards2[0]._arrays[0]) & set(shards2[1]._arrays[0])
    assert shards2[0].shard_spec == (0, 2)


def test_loader_reshard_keeps_batch_geometry_static():
    """Per-rank batch shapes stay static across a reshard: batch size and
    drop_remainder carry over, so every batch is full-shape (the tail that
    doesn't fill a batch is dropped, exactly as pre-shrink)."""
    x = np.arange(61)  # deliberately indivisible
    y = np.arange(61) * 2
    ds = ArrayDataset((x, y)).shard(0, 3).batch(4)
    pre = [b[0].shape for b in ds]
    assert set(pre) == {(4,)}  # drop_remainder: full batches only
    re = ds.reshard(0, 2)
    post = list(re)
    assert {b[0].shape for b in post} == {(4,)}
    # 31 examples in shard 0-of-2 → 7 full batches, tail of 3 dropped.
    assert len(post) == 31 // 4
    for xb, yb in post:
        np.testing.assert_array_equal(yb, xb * 2)  # rows stay aligned


def test_loader_reshard_preserves_chain_config():
    x = np.arange(40)
    ds = ArrayDataset((x,)).shard(1, 4).repeat().shuffle(40, seed=5).batch(3)
    re = ds.reshard(1, 2)
    assert re._repeat and re._shuffle_buffer == 40
    assert re._batch_size == 3
    batches = re.take(8)  # crosses the shard-epoch boundary: repeat works
    vals = set(np.concatenate([b[0] for b in batches]).tolist())
    assert vals <= set(range(1, 40, 2))  # shard 1 of 2 — odd indices


def test_loader_reshard_unsharded_and_bad_index():
    import pytest

    x = np.arange(8)
    ds = ArrayDataset((x,))
    # reshard on a never-sharded dataset behaves like shard().
    np.testing.assert_array_equal(
        ds.reshard(0, 2)._arrays[0], ds.shard(0, 2)._arrays[0]
    )
    with pytest.raises(ValueError, match="out of range"):
        ds.reshard(2, 2)
