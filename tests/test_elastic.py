"""Elastic subsystem units (tier-1, no jax worlds): rendezvous coordinator
protocol (join/sync/beat/leave/generation/settle/timeout), ElasticState
commit/restore/progress, the ``leave`` fault kind, the supervisor's elastic
loop driven by jax-free fake workers speaking the real TCP protocol, the
journal summary behind /healthz, and the CLI/YAML wiring."""

import json
import os
import sys
import textwrap
import threading
import time

import pytest

from horovod_tpu.elastic.coordinator import (
    SYNC_PORT_WINDOW,
    Coordinator,
    ElasticClient,
    ElasticError,
    WorldInfo,
)
from horovod_tpu.elastic.state import ElasticState, progress_marker
from horovod_tpu.launch import ci_gate, launcher, supervisor
from horovod_tpu.launch.supervisor import ElasticPolicy, RestartPolicy

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))



def _journal(log_path):
    with open(log_path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _sync_all(address, member_ids, progress=None, timeout=20.0):
    """Drive one rendezvous round from N client threads; returns
    {member_id: WorldInfo}."""
    out, errs = {}, {}

    def worker(mid):
        try:
            out[mid] = ElasticClient(address, mid).sync(
                progress=(progress or {}).get(mid, -1)
            )
        except Exception as e:  # surfaced by the caller's assert
            errs[mid] = e

    threads = [
        threading.Thread(target=worker, args=(m,)) for m in member_ids
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
    assert not errs, errs
    assert len(out) == len(member_ids)
    return out


class TestCoordinator:
    def test_first_round_settles_expected_members(self):
        coord = Coordinator(expected=3, rendezvous_timeout=10.0).start()
        try:
            worlds = _sync_all(coord.address, ["a", "b", "c"])
            assert sorted(w.rank for w in worlds.values()) == [0, 1, 2]
            gens = {w.generation for w in worlds.values()}
            ports = {w.jax_coordinator for w in worlds.values()}
            assert len(gens) == 1 and len(ports) == 1
            assert all(w.size == 3 for w in worlds.values())
        finally:
            coord.stop()

    def test_leave_bumps_generation_and_next_round_shrinks(self, tmp_path):
        log = supervisor.RestartLog(str(tmp_path / "j.jsonl"))
        coord = Coordinator(
            expected=2, rendezvous_timeout=10.0, journal=log.write
        ).start()
        try:
            worlds = _sync_all(coord.address, ["a", "b"])
            gen0 = worlds["a"].generation
            ElasticClient(coord.address, "b").leave("test")
            # Beats tell the survivor the world moved on.
            assert ElasticClient(coord.address, "a").beat() > gen0
            again = _sync_all(coord.address, ["a"])
            assert again["a"].size == 1 and again["a"].rank == 0
            # Size 1 = bare local mode: no jax coordinator to dial.
            assert again["a"].jax_coordinator is None
            names = [r["name"] for r in _journal(log.path)]
            assert "start" in names and "leave" in names
            assert "shrink" in names  # the settle after the leave
        finally:
            coord.stop()

    def test_join_midflight_grows_next_round(self):
        coord = Coordinator(expected=2, rendezvous_timeout=10.0).start()
        try:
            worlds = _sync_all(coord.address, ["a", "b"])
            gen0 = worlds["a"].generation
            # A third member starts syncing: blocks (a/b not waiting), but
            # its JOIN bumps the generation immediately.
            result = {}
            t = threading.Thread(
                target=lambda: result.update(
                    c=ElasticClient(coord.address, "c").sync()
                )
            )
            t.start()
            deadline = time.monotonic() + 5
            while (
                ElasticClient(coord.address, "a").beat() == gen0
                and time.monotonic() < deadline
            ):
                time.sleep(0.02)
            assert ElasticClient(coord.address, "a").beat() > gen0
            worlds2 = _sync_all(coord.address, ["a", "b"])
            t.join(10)
            assert result["c"].size == 3
            assert worlds2["a"].generation == result["c"].generation
            ranks = sorted(
                [worlds2["a"].rank, worlds2["b"].rank, result["c"].rank]
            )
            assert ranks == [0, 1, 2]
            # Survivors keep their relative order; the joiner is last.
            assert result["c"].rank == 2
        finally:
            coord.stop()

    def test_root_election_prefers_most_progress(self):
        coord = Coordinator(expected=2, rendezvous_timeout=10.0).start()
        try:
            worlds = _sync_all(
                coord.address, ["a", "b"],
                progress={"a": progress_marker(1), "b": progress_marker(5)},
            )
            # Root is b (most committed progress), whatever rank it got.
            assert worlds["a"].root_rank == worlds["b"].rank
            assert worlds["a"].max_progress == progress_marker(5)
        finally:
            coord.stop()

    def test_rendezvous_timeout_drops_laggard(self, tmp_path):
        log = supervisor.RestartLog(str(tmp_path / "j.jsonl"))
        coord = Coordinator(
            expected=2, min_ranks=1, rendezvous_timeout=0.5,
            journal=log.write,
        ).start()
        try:
            # 'b' joins (known live) but never syncs again after round 1;
            # 'a' re-rendezvous must not hang forever on it.
            _sync_all(coord.address, ["a", "b"])
            ElasticClient(coord.address, "a").beat()
            world = ElasticClient(coord.address, "a").sync(timeout=30.0)
            assert world.size == 1
            dead = [r for r in _journal(log.path) if r["name"] == "dead"]
            assert dead and dead[0]["member"] == "b"
            assert dead[0]["reason"] == "rendezvous-timeout"
        finally:
            coord.stop()

    def test_fresh_beats_exempt_busy_member_from_expiry(self, tmp_path):
        """A joiner out-waiting the rendezvous window must NOT get a
        survivor declared dead while that survivor's TCP beats are fresh:
        it is mid-epoch (slower than rendezvous_timeout), not crashed. The
        round settles only once the busy member reaches its boundary —
        with everyone still in."""
        log = supervisor.RestartLog(str(tmp_path / "j.jsonl"))
        coord = Coordinator(
            expected=1, min_ranks=1, rendezvous_timeout=0.3,
            heartbeat_window=10.0, journal=log.write,
        ).start()
        try:
            _sync_all(coord.address, ["a"])
            stop = threading.Event()

            def beat_a():
                while not stop.is_set():
                    ElasticClient(coord.address, "a").beat()
                    time.sleep(0.05)

            beater = threading.Thread(target=beat_a)
            beater.start()
            try:
                # 'b' joins and waits; 'a' is busy training (absent from
                # the round, beating).
                worlds = {}
                joiner = threading.Thread(
                    target=lambda: worlds.update(
                        b=ElasticClient(coord.address, "b").sync(timeout=30.0)
                    )
                )
                joiner.start()
                time.sleep(1.0)  # several rendezvous windows of waiting
                assert coord.member_status("a")[0] == "live"
            finally:
                stop.set()
                beater.join(5)
            # 'a' reaches its commit boundary: the round settles at 2.
            world_a = ElasticClient(coord.address, "a").sync(timeout=30.0)
            joiner.join(10)
            assert world_a.size == 2
            assert worlds["b"].size == 2
            assert not [
                r for r in _journal(log.path) if r["name"] == "dead"
            ]
        finally:
            coord.stop()

    def test_sync_outlives_client_socket_timeout(self, tmp_path):
        """An unbounded sync() must survive epochs longer than the client
        socket timeout: each expired attempt re-enters the rendezvous (the
        server superseding the stale waiter slot) until the busy member
        arrives and the round settles with everyone in."""
        log = supervisor.RestartLog(str(tmp_path / "j.jsonl"))
        coord = Coordinator(
            expected=1, min_ranks=1, rendezvous_timeout=0.2,
            heartbeat_window=10.0, journal=log.write,
        ).start()
        try:
            _sync_all(coord.address, ["a"])
            stop = threading.Event()

            def beat_a():
                while not stop.is_set():
                    ElasticClient(coord.address, "a").beat()
                    time.sleep(0.05)

            beater = threading.Thread(target=beat_a)
            beater.start()
            try:
                worlds = {}
                joiner = threading.Thread(
                    target=lambda: worlds.update(
                        # Per-attempt socket timeout far below the wait:
                        # forces several timeout→re-sync cycles.
                        b=ElasticClient(coord.address, "b", timeout=0.3)
                        .sync()
                    )
                )
                joiner.start()
                time.sleep(1.2)  # ≥3 client attempts while 'a' is busy
                assert coord.member_status("b")[0] == "live"
            finally:
                stop.set()
                beater.join(5)
            world_a = ElasticClient(coord.address, "a").sync(timeout=30.0)
            joiner.join(10)
            assert world_a.size == 2
            assert worlds["b"].size == 2
            assert not [
                r for r in _journal(log.path) if r["name"] == "dead"
            ]
        finally:
            coord.stop()

    def test_sync_port_rotation_stays_bounded(self):
        coord = Coordinator(expected=1, sync_port_base=9100)
        coord.generation = 7 + 5 * SYNC_PORT_WINDOW  # a long-churned fleet
        assert coord._pick_sync_port() == 9100 + 7

    def test_below_min_ranks_fails_loudly(self):
        coord = Coordinator(
            expected=1, min_ranks=2, rendezvous_timeout=0.4
        ).start()
        try:
            with pytest.raises(ElasticError, match="below min_ranks"):
                ElasticClient(coord.address, "a").sync(timeout=30.0)
        finally:
            coord.stop()

    def test_world_full_rejected(self):
        coord = Coordinator(
            expected=1, max_ranks=1, rendezvous_timeout=5.0
        ).start()
        try:
            _sync_all(coord.address, ["a"])
            with pytest.raises(ElasticError, match="full"):
                ElasticClient(coord.address, "b").sync(timeout=10.0)
        finally:
            coord.stop()

    def test_stale_members_exempts_pending_sync(self):
        coord = Coordinator(expected=1, rendezvous_timeout=10.0).start()
        try:
            _sync_all(coord.address, ["a"])
            # Beat recorded at sync; ancient clock → stale.
            assert coord.stale_members(
                0.0, now=time.monotonic() + 100
            ) == ["a"]
            # A member parked in sync is alive by construction.
            t = threading.Thread(
                target=lambda: ElasticClient(coord.address, "b").sync()
            )
            t.start()
            deadline = time.monotonic() + 5
            while (
                coord.member_status("b")[0] == "unknown"
                and time.monotonic() < deadline
            ):
                time.sleep(0.02)
            assert "b" not in coord.stale_members(
                0.0, now=time.monotonic() + 100
            )
            ElasticClient(coord.address, "a").sync()  # settle, release b
            t.join(10)
        finally:
            coord.stop()

    def test_snapshot_state_command(self):
        coord = Coordinator(expected=1, rendezvous_timeout=5.0).start()
        try:
            _sync_all(coord.address, ["a"])
            snap = ElasticClient(coord.address, "x").state()
            assert snap["last_settle"]["size"] == 1
            assert snap["members"]["a"]["status"] == "live"
        finally:
            coord.stop()


class TestElasticState:
    def test_commit_restore_roundtrip(self):
        import numpy as np

        s = ElasticState(state={"w": np.arange(4)}, epoch=0)
        s.commit()
        s.state = {"w": np.zeros(4)}
        s.epoch = 7
        s.restore()
        np.testing.assert_array_equal(s.state["w"], np.arange(4))
        assert s.epoch == 0

    def test_restore_before_commit_keeps_initials(self):
        s = ElasticState(epoch=3)
        s.restore()
        assert s.epoch == 3 and s.state is None

    def test_progress_tracks_committed_not_live(self):
        s = ElasticState(epoch=0)
        assert s.progress == -1  # nothing committed yet
        s.epoch = 4
        s.commit()
        s.epoch = 9  # live value moves on; progress stays committed
        assert s.progress == progress_marker(4)

    def test_extra_attrs_tracked(self):
        s = ElasticState(epoch=0, lr=0.1)
        s.commit()
        s.lr = 99.0
        s.restore()
        assert s.lr == 0.1

    def test_sync_single_process_is_restore(self):
        s = ElasticState(epoch=2)
        s.commit()
        s.epoch = 5
        s.sync(root_rank=0)
        assert s.epoch == 2

    def test_sync_skips_transport_when_members_match(self, monkeypatch):
        """The common shrink: every survivor committed the same boundary
        of the same SPMD program — matching (structure, progress) votes
        mean the model-sized broadcast would move nothing, so it must not
        run at all."""
        import jax
        import numpy as np

        from horovod_tpu.elastic import state as state_mod

        s = ElasticState(state={"w": np.arange(4)}, epoch=2)
        s.commit()
        s.state = {"w": np.zeros(4)}  # live value drifts past the commit
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(
            state_mod.collectives, "allgather_object", lambda v: [v, v]
        )

        def no_transport(*a, **k):
            raise AssertionError("transport must be skipped on a shrink")

        monkeypatch.setattr(
            state_mod.collectives, "broadcast_pytree", no_transport
        )
        monkeypatch.setattr(
            state_mod.collectives, "broadcast_object", no_transport
        )
        s.sync(root_rank=0)
        np.testing.assert_array_equal(s.state["w"], np.arange(4))
        assert s.epoch == 2

    def test_sync_transports_when_content_diverged(self, monkeypatch):
        """Same structure and progress but divergent bytes (low-bit
        replica drift, rank-dependent tracked extras): the digest vote
        differs, so the root's content IS broadcast — the skip never
        trades correctness for the saved transport."""
        import jax
        import numpy as np

        from horovod_tpu.elastic import state as state_mod

        s = ElasticState(state={"w": np.arange(4)}, epoch=2)
        s.commit()
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(
            state_mod.collectives, "allgather_object",
            lambda v: [v, (v[0], v[1], "diverged-digest", False)],
        )
        sent = []
        monkeypatch.setattr(
            state_mod.collectives, "broadcast_pytree",
            lambda tree, root=0: sent.append(tree) or tree,
        )
        s.sync(root_rank=0)
        assert len(sent) == 1

    def test_sync_transports_to_empty_handed_joiner(self, monkeypatch):
        """A fresh joiner votes (None, -1): structures differ, so the full
        snapshot travels as one broadcast_object."""
        import jax
        import numpy as np

        from horovod_tpu.elastic import state as state_mod

        s = ElasticState(state={"w": np.arange(4)}, epoch=2)
        s.commit()
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(
            state_mod.collectives, "allgather_object",
            lambda v: [v, (None, -1, None, False)],
        )
        sent = []

        def fake_broadcast_object(obj, root=0):
            sent.append(obj)
            return obj

        monkeypatch.setattr(
            state_mod.collectives, "broadcast_object", fake_broadcast_object
        )
        s.sync(root_rank=0)
        assert len(sent) == 1 and sent[0]["epoch"] == 2


class TestShardedCommit:
    """Per-shard elastic commit for cross-process-sharded (ZeRO-1/TP/FSDP)
    state. Real cross-process arrays cannot exist in a single test process,
    so these units drive the classification through a patched
    `_is_cross_process` with duck-typed fake arrays; the real 3-proc
    ZeRO-1 shrink is proven end-to-end in test_elastic_sharded_e2e.py."""

    @staticmethod
    def _fake_sharded(full: "np.ndarray", lo: int, hi: int):
        """A fake jax.Array holding rows [lo:hi) of ``full`` as its only
        owned (replica-0) shard."""
        from types import SimpleNamespace

        import numpy as np

        return SimpleNamespace(
            shape=full.shape,
            dtype=full.dtype,
            addressable_shards=[SimpleNamespace(
                index=(slice(lo, hi),) + tuple(
                    slice(0, d) for d in full.shape[1:]
                ),
                replica_id=0,
                data=np.ascontiguousarray(full[lo:hi]),
            )],
        )

    def _patch(self, monkeypatch):
        from types import SimpleNamespace

        from horovod_tpu.elastic import state as state_mod

        monkeypatch.setattr(
            state_mod, "_is_cross_process",
            lambda l: isinstance(l, SimpleNamespace),
        )
        return state_mod

    def test_commit_snapshots_owned_pieces_with_digests(self, monkeypatch):
        import hashlib

        import numpy as np

        from horovod_tpu.elastic.state import ShardedLeaf

        self._patch(monkeypatch)
        full = np.arange(24, dtype=np.float32).reshape(6, 4)
        s = ElasticState(
            state={"w": self._fake_sharded(full, 0, 2), "b": np.ones(3)},
            epoch=1,
        )
        s.commit()
        leaf = s._committed["state"]["w"]
        assert isinstance(leaf, ShardedLeaf)
        assert leaf.shape == (6, 4) and leaf.dtype == "float32"
        np.testing.assert_array_equal(leaf.pieces["0:2,0:4"], full[0:2])
        assert leaf.digests["0:2,0:4"] == hashlib.sha256(
            full[0:2].tobytes()
        ).hexdigest()
        # Dense leaves commit dense, untouched by the sharded path.
        np.testing.assert_array_equal(s._committed["state"]["b"], np.ones(3))
        assert s.has_sharded_commit
        man = s.manifest()
        sharded = [e for e in man["leaves"] if e["sharded"]]
        assert len(sharded) == 1
        assert sharded[0]["shape"] == [6, 4]
        assert sharded[0]["pieces"] == ["0:2,0:4"]
        assert man["progress"] == s.progress

    @staticmethod
    def _contribution(m):
        """What one member sends into the gather — the wire contract:
        ``{leaf_index|index_spec: piece}`` plus the matching digests."""
        import jax

        from horovod_tpu.elastic.state import ShardedLeaf

        leaves, _ = jax.tree_util.tree_flatten(m._committed)
        payload, digests = {}, {}
        for i, leaf in enumerate(leaves):
            if isinstance(leaf, ShardedLeaf):
                for spec, piece in leaf.pieces.items():
                    payload[f"{i}|{spec}"] = piece
                    digests[f"{i}|{spec}"] = leaf.digests[spec]
        return payload, digests

    def test_gather_reassembles_across_members(self, monkeypatch):
        """Three members each commit one third; after the gather every
        member holds the dense global array — the 3→2 shrink keeps the
        leaver's third."""
        import numpy as np

        state_mod = self._patch(monkeypatch)
        full = np.arange(24, dtype=np.float32).reshape(6, 4)
        members = [
            ElasticState(state={"w": self._fake_sharded(full, lo, hi)},
                         epoch=2)
            for lo, hi in ((0, 2), (2, 4), (4, 6))
        ]
        for m in members:
            m.commit()
        everyone = [self._contribution(m) for m in members]
        monkeypatch.setattr(
            state_mod.collectives, "allgather_object",
            lambda obj: list(everyone),
        )
        for m in members:
            m.gather_committed()
            np.testing.assert_array_equal(m._committed["state"]["w"], full)
            assert not m.has_sharded_commit
            assert m.progress == progress_marker(2)  # progress untouched

    def test_gather_missing_coverage_is_loud(self, monkeypatch):
        """Pieces that no longer tile the array (a hard death took them)
        must raise the actionable fallback error, not return garbage."""
        import numpy as np

        state_mod = self._patch(monkeypatch)
        full = np.arange(12, dtype=np.float32).reshape(6, 2)
        m = ElasticState(state={"w": self._fake_sharded(full, 0, 2)})
        m.commit()
        monkeypatch.setattr(
            state_mod.collectives, "allgather_object", lambda obj: [obj]
        )
        with pytest.raises(RuntimeError, match="checkpoint"):
            m.gather_committed()

    def test_gather_detects_corrupt_piece(self, monkeypatch):
        import numpy as np

        state_mod = self._patch(monkeypatch)
        full = np.arange(8, dtype=np.float32).reshape(2, 4)
        m = ElasticState(state={"w": self._fake_sharded(full, 0, 2)})
        m.commit()

        def corrupting_allgather(obj):
            payload, digests = obj
            bad = {k: v.copy() for k, v in payload.items()}
            next(iter(bad.values()))[0] += 1.0  # transport flipped a value
            return [(bad, digests)]

        monkeypatch.setattr(
            state_mod.collectives, "allgather_object", corrupting_allgather
        )
        with pytest.raises(RuntimeError, match="sha256"):
            m.gather_committed()

    def test_sync_gathers_sharded_votes_then_skips(self, monkeypatch):
        """A residual sharded commit at sync time is reassembled across
        the current membership first; with every member then holding the
        same dense bytes, the model-sized transport is still skipped."""
        import jax
        import numpy as np

        state_mod = self._patch(monkeypatch)
        full = np.arange(12, dtype=np.float32).reshape(6, 2)
        m = ElasticState(state={"w": self._fake_sharded(full, 0, 6)},
                         epoch=3)
        m.commit()
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(
            state_mod.collectives, "allgather_object", lambda obj: [obj, obj]
        )

        def no_transport(*a, **k):
            raise AssertionError("transport must be skipped")

        monkeypatch.setattr(
            state_mod.collectives, "broadcast_pytree", no_transport
        )
        monkeypatch.setattr(
            state_mod.collectives, "broadcast_object", no_transport
        )
        m.sync(root_rank=0)
        np.testing.assert_array_equal(m.state["w"], full)
        assert m.epoch == 3

    def test_gather_force_participates_without_sharded_commit(
        self, monkeypatch
    ):
        """Lockstep discipline: when sync sees ANY sharded vote, every
        member — including one with no sharded commit, or no commit at
        all — must enter the gather's allgather (with an empty
        contribution), or the collective wedges."""
        import numpy as np

        from horovod_tpu.elastic import state as state_mod

        calls = []
        monkeypatch.setattr(
            state_mod.collectives, "allgather_object",
            lambda obj: calls.append(obj) or [obj],
        )
        empty = ElasticState()
        empty.gather_committed(force=True)
        dense = ElasticState(state={"w": np.ones(3)})
        dense.commit()
        dense.gather_committed(force=True)
        assert calls == [({}, {})] * 2   # both participated, empty-handed
        np.testing.assert_array_equal(
            dense._committed["state"]["w"], np.ones(3)
        )
        # Without force, no sharded commit = communication-free no-op.
        calls.clear()
        dense.gather_committed()
        empty.gather_committed()
        assert calls == []

    def test_validate_committable_strided_is_loud(self, monkeypatch):
        from types import SimpleNamespace

        import numpy as np

        state_mod = self._patch(monkeypatch)
        bad = SimpleNamespace(
            shape=(8,),
            dtype=np.float32,
            addressable_shards=[SimpleNamespace(
                index=(slice(0, 8, 2),), replica_id=0,
                data=np.zeros(4, np.float32),
            )],
        )
        with pytest.raises(RuntimeError, match="--max-restarts"):
            state_mod.validate_committable({"w": bad}, where="elastic.run")

    def test_validate_committable_accepts_dense(self):
        import numpy as np

        from horovod_tpu.elastic.state import validate_committable

        validate_committable({"w": np.zeros(4)})  # no raise


class TestLeaveFault:
    def test_parse_leave(self):
        from horovod_tpu.testing import faults

        assert faults.parse_plan("2:1:leave").kind == "leave"

    def test_leave_sets_flag_under_elastic_env(self, monkeypatch):
        from horovod_tpu import runtime
        from horovod_tpu.testing import faults

        faults.reset_leave()
        monkeypatch.setenv(runtime.ENV_ELASTIC_COORDINATOR, "127.0.0.1:1")
        killed = []
        monkeypatch.setattr(os, "kill", lambda *a: killed.append(a))
        cb = faults.FaultInjectionCallback(faults.parse_plan("0:0:leave"))
        cb.on_epoch_begin(0)
        cb.on_batch_end(0)
        assert faults.leave_requested()
        assert not killed  # elastic mode: intent only, no signal
        faults.reset_leave()

    def test_leave_degrades_to_sigterm_without_elastic(self, monkeypatch):
        import signal

        from horovod_tpu import runtime
        from horovod_tpu.testing import faults

        faults.reset_leave()
        monkeypatch.delenv(runtime.ENV_ELASTIC_COORDINATOR, raising=False)
        killed = []
        monkeypatch.setattr(
            os, "kill", lambda pid, sig: killed.append((pid, sig))
        )
        cb = faults.FaultInjectionCallback(faults.parse_plan("0:0:leave"))
        cb.on_epoch_begin(0)
        cb.on_batch_end(0)
        assert killed == [(os.getpid(), signal.SIGTERM)]
        assert not faults.leave_requested()


# Jax-free fake worker: speaks the real rendezvous WIRE protocol (sync →
# paced "epochs" with beats → membership-change re-sync → done-leave), so
# the supervisor's elastic loop is testable in seconds. The client is
# inlined (same JSON-lines protocol ElasticClient speaks — which the
# coordinator tests above drive through the real class) because importing
# horovod_tpu pulls jax, and ~3s of import per spawned fake would dominate
# tier-1 time. Behavior knobs via env: FAKE_EPOCHS/FAKE_PACE, FAKE_LEAVER
# (member id that leaves after one epoch; one-shot via FAKE_STAMP; "ALL"
# matches every member), FAKE_CRASHER (exits 7 instead), FAKE_WEDGER
# (joins, then stops beating forever), FAKE_DEAF (swallows the first
# SIGTERM, leaves cleanly on the second — stamps via FAKE_DEAF_STAMP).
FAKE_WORKER = """
import json, os, socket, sys, time
from types import SimpleNamespace

member = os.environ["HVT_ELASTIC_MEMBER"]
host, port = os.environ["HVT_ELASTIC_COORDINATOR"].rsplit(":", 1)


class MiniClient:  # ElasticClient's wire protocol, import-free
    def _call(self, **msg):
        with socket.create_connection((host, int(port)), timeout=60) as s:
            s.sendall(json.dumps(msg).encode() + b"\\n")
            buf = b""
            while not buf.endswith(b"\\n"):
                chunk = s.recv(65536)
                if not chunk:
                    raise OSError("closed")
                buf += chunk
        reply = json.loads(buf)
        if "error" in reply:
            raise SystemExit(f"coordinator error: {reply['error']}")
        return reply

    def sync(self, progress=-1):
        r = self._call(cmd="sync", member=member, host="127.0.0.1",
                       progress=progress)
        return SimpleNamespace(
            generation=r["generation"],
            max_progress=r.get("max_progress", -1),
        )

    def beat(self, progress=None):
        return self._call(cmd="beat", member=member,
                          progress=progress)["generation"]

    def leave(self, reason):
        self._call(cmd="leave", member=member, reason=reason)


client = MiniClient()
epochs = int(os.environ.get("FAKE_EPOCHS", "4"))
pace = float(os.environ.get("FAKE_PACE", "0.1"))
stamp = os.environ.get("FAKE_STAMP")

if os.environ.get("FAKE_DEAF") == member:
    # Impersonates XLA's preemption notifier swallowing the FIRST
    # SIGTERM (as jax.distributed.initialize does mid-startup): the
    # first TERM only re-arms the handler; a SECOND one is honored as
    # a clean leave. Exercises the supervisor's in-grace TERM re-send.
    import signal

    def _honor(signum, frame):
        open(os.environ["FAKE_DEAF_STAMP"] + ".left", "w").close()
        try:
            client.leave(reason="preempted")
        except Exception:
            pass
        sys.exit(143)

    def _swallow(signum, frame):
        signal.signal(signal.SIGTERM, _honor)

    signal.signal(signal.SIGTERM, _swallow)
    open(os.environ["FAKE_DEAF_STAMP"], "w").close()  # armed marker

def fire_once(kind_env):
    target = os.environ.get(kind_env)
    if target not in (member, "ALL") or (stamp and os.path.exists(stamp)):
        return False
    if stamp:
        open(stamp, "w").close()
    return True

epoch = 0
while epoch < epochs:
    world = client.sync(progress=epoch)
    epoch = max(epoch, world.max_progress if world.max_progress > 0 else 0)
    while epoch < epochs:
        time.sleep(pace)
        epoch += 1
        if fire_once("FAKE_LEAVER"):
            client.leave(reason="fake-leave")
            sys.exit(143)
        if fire_once("FAKE_CRASHER"):
            sys.exit(7)
        if fire_once("FAKE_WEDGER"):
            # A real wedged rank traps SIGTERM (the elastic callback's
            # flag-only handler) and never acts on it — only the
            # supervisor's SIGKILL escalation can reap it.
            import signal
            signal.signal(signal.SIGTERM, signal.SIG_IGN)
            time.sleep(3600)
        if client.beat(progress=epoch) != world.generation:
            break  # membership changed: re-rendezvous
client.leave(reason="done")
print(f"FAKE-DONE {member}", flush=True)
"""


def write_fake_worker(tmp_path):
    path = tmp_path / "fake_worker.py"
    path.write_text(textwrap.dedent(FAKE_WORKER))
    return [sys.executable, str(path)]


def _shrink_gated_spawn(argv, log, nprocs, timeout=60.0):
    """Deterministic shrink-then-grow for the leave tests.

    The supervisor races the survivors' re-rendezvous (which journals the
    ``shrink`` settle) against the replacement's join: with a short
    backoff the replacement can join the round FIRST, the world settles
    back at full size, and no shrink record ever lands — the historic
    flake in ``test_leave_shrinks_then_replacement_grows`` /
    ``test_journal_gateable_with_count``. Instead of tuning sleeps,
    condition-poll coordinator state: hold every REPLACEMENT spawn
    (member seq >= the launch size) until the journal carries the
    settled shrink, bounded generously — on timeout the member spawns
    anyway and the assertions explain. Survivor/initial spawns pass
    through untouched. (Blocking inside ``spawn`` is safe: the
    rendezvous settles on the coordinator's own threads.)"""
    def spawn(member_id, slot, env):
        if int(member_id[1:]) >= nprocs:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if os.path.exists(log) and any(
                    r.get("name") == "shrink" for r in _journal(log)
                ):
                    break
                time.sleep(0.05)
        return supervisor._spawn_member_local(argv, env, member_id, slot)

    return spawn


class TestSuperviseElastic:
    def test_clean_completion_no_restarts(self, tmp_path, capfd):
        argv = write_fake_worker(tmp_path)
        log = tmp_path / "restarts.jsonl"
        code = supervisor.supervise_elastic(
            2, argv, env={"FAKE_EPOCHS": "2"},
            policy=RestartPolicy(max_restarts=2, backoff=0.0,
                                 grace_seconds=5.0),
            elastic=ElasticPolicy(min_ranks=1, rendezvous_timeout=20.0),
            log_path=str(log),
        )
        assert code == 0
        names = [r["name"] for r in _journal(log)]
        assert "start" in names
        assert "restarts" not in names
        assert capfd.readouterr().out.count("FAKE-DONE") == 2

    def test_leave_shrinks_then_replacement_grows(self, tmp_path, capfd):
        argv = write_fake_worker(tmp_path)
        log = tmp_path / "restarts.jsonl"
        code = supervisor.supervise_elastic(
            3, argv,
            env={
                "FAKE_EPOCHS": "10", "FAKE_PACE": "0.2",
                "FAKE_LEAVER": "m1", "FAKE_STAMP": str(tmp_path / "stamp"),
            },
            policy=RestartPolicy(max_restarts=3, backoff=0.1,
                                 grace_seconds=5.0),
            elastic=ElasticPolicy(min_ranks=2, max_ranks=3,
                                  rendezvous_timeout=20.0),
            log_path=str(log),
            spawn=_shrink_gated_spawn(argv, str(log), 3),
        )
        assert code == 0
        records = _journal(log)
        names = [r["name"] for r in records]
        assert names.count("shrink") >= 1
        assert names.count("grow") >= 1
        # Order: start at 3 → shrink to 2 → grow back to 3.
        sizes = [r["size"] for r in records
                 if r["name"] in ("start", "shrink", "grow", "steady")]
        assert sizes[0] == 3
        assert 2 in sizes and sizes.index(2) < len(sizes) - 1 \
            and 3 in sizes[sizes.index(2):]
        # The replacement (m3) was spawned; the survivors were NOT
        # respawned — exactly one restart journaled, for the leaver.
        restarts = [r for r in records if r["name"] == "restarts"]
        assert len(restarts) == 1
        assert restarts[0]["kind"] == "leave"
        assert restarts[0]["member"] == "m1"

    def test_crash_respawned_with_budget(self, tmp_path):
        argv = write_fake_worker(tmp_path)
        log = tmp_path / "restarts.jsonl"
        code = supervisor.supervise_elastic(
            2, argv,
            env={
                "FAKE_EPOCHS": "8", "FAKE_PACE": "0.2",
                "FAKE_CRASHER": "m0", "FAKE_STAMP": str(tmp_path / "stamp"),
            },
            policy=RestartPolicy(max_restarts=3, backoff=0.1,
                                 grace_seconds=5.0),
            elastic=ElasticPolicy(min_ranks=1, max_ranks=2,
                                  rendezvous_timeout=20.0),
            log_path=str(log),
        )
        assert code == 0
        records = _journal(log)
        restarts = [r for r in records if r["name"] == "restarts"]
        assert len(restarts) == 1
        assert restarts[0]["kind"] == "crash"
        assert restarts[0]["exit_code"] == 7
        dead = [r for r in records if r["name"] == "dead"]
        assert any(r["member"] == "m0" for r in dead)

    def test_deterministic_crash_loop_gives_up_below_min(self, tmp_path):
        """No stamp: every incarnation crashes before joining a settled
        world twice... the budget spends and the supervisor exits with the
        fault's code once the fleet cannot reach min_ranks."""
        argv = write_fake_worker(tmp_path)
        log = tmp_path / "restarts.jsonl"
        code = supervisor.supervise_elastic(
            1, argv,
            env={"FAKE_EPOCHS": "10", "FAKE_PACE": "0.05",
                 "FAKE_CRASHER": "ALL"},
            policy=RestartPolicy(max_restarts=2, backoff=0.0,
                                 grace_seconds=5.0),
            elastic=ElasticPolicy(min_ranks=1, rendezvous_timeout=5.0),
            log_path=str(log),
        )
        assert code == 7
        records = _journal(log)
        assert any(r["name"] == "supervisor_gave_up" for r in records)

    def test_hosts_members_receive_coordinator_env(
        self, tmp_path, monkeypatch
    ):
        """The ssh path end-to-end with a PATH-shimmed ssh that execs
        locally. The spawn closure only learns HVT_ELASTIC_COORDINATOR via
        the resolved env supervise_elastic hands it (the address exists
        only after the coordinator starts), so a clean completion — the
        fake worker dials that address at startup — proves propagation;
        the captured remote commands pin it, and pin that the
        supervisor-assigned member identity beats a stale one leaked into
        the user env."""
        bin_dir = tmp_path / "fakebin"
        bin_dir.mkdir()
        capture = tmp_path / "ssh-cmds.log"
        ssh = bin_dir / "ssh"
        ssh.write_text(
            "#!/bin/bash\n"
            'while [[ "$1" == -* ]]; do\n'
            '  if [[ "$1" == "-o" ]]; then shift 2; else shift; fi\n'
            "done\n"
            'host="$1"; shift\n'
            f'printf \'%s\\n\' "$*" >> {capture}\n'
            'exec sh -c "$*"\n'
        )
        ssh.chmod(0o755)
        monkeypatch.setenv("PATH", f"{bin_dir}:{os.environ['PATH']}")
        argv = write_fake_worker(tmp_path)
        log = tmp_path / "restarts.jsonl"
        code = supervisor.supervise_elastic_hosts(
            ["hostA", "hostB"], argv,
            env={"FAKE_EPOCHS": "2", "HVT_ELASTIC_MEMBER": "stale-zombie"},
            policy=RestartPolicy(max_restarts=1, backoff=0.0,
                                 grace_seconds=5.0),
            elastic=ElasticPolicy(min_ranks=1, rendezvous_timeout=20.0),
            log_path=str(log),
        )
        assert code == 0
        cmds = capture.read_text()
        assert "HVT_ELASTIC_COORDINATOR=" in cmds
        assert "HVT_ELASTIC_MEMBER=m0" in cmds
        assert "HVT_ELASTIC_MEMBER=m1" in cmds
        assert "stale-zombie" not in cmds
        names = [r["name"] for r in _journal(log)]
        assert "start" in names

    def test_tcp_beat_hang_detection_kills_and_replaces(self, tmp_path):
        argv = write_fake_worker(tmp_path)
        log = tmp_path / "restarts.jsonl"
        code = supervisor.supervise_elastic(
            2, argv,
            env={
                # Long enough that the healthy member is still training
                # when the wedge is detected (1.5s), SIGTERM is ignored,
                # and the SIGKILL escalation (grace 1.0s) reaps it.
                "FAKE_EPOCHS": "30", "FAKE_PACE": "0.25",
                "FAKE_WEDGER": "m1", "FAKE_STAMP": str(tmp_path / "stamp"),
            },
            policy=RestartPolicy(max_restarts=3, backoff=0.1,
                                 grace_seconds=1.0, heartbeat_timeout=1.5),
            elastic=ElasticPolicy(min_ranks=1, max_ranks=2,
                                  rendezvous_timeout=20.0),
            log_path=str(log),
        )
        assert code == 0
        restarts = [
            r for r in _journal(log) if r["name"] == "restarts"
        ]
        assert len(restarts) == 1
        assert restarts[0]["kind"] == "hang"
        assert restarts[0]["member"] == "m1"

    def test_journal_gateable_with_count(self, tmp_path):
        argv = write_fake_worker(tmp_path)
        log = tmp_path / "restarts.jsonl"
        code = supervisor.supervise_elastic(
            3, argv,
            env={
                "FAKE_EPOCHS": "10", "FAKE_PACE": "0.2",
                "FAKE_LEAVER": "m2", "FAKE_STAMP": str(tmp_path / "stamp"),
            },
            policy=RestartPolicy(max_restarts=3, backoff=0.1,
                                 grace_seconds=5.0),
            elastic=ElasticPolicy(min_ranks=2, max_ranks=3,
                                  rendezvous_timeout=20.0),
            log_path=str(log),
            spawn=_shrink_gated_spawn(argv, str(log), 3),
        )
        assert code == 0
        # The CI-gate contract from the job spec: a shrink occurred.
        ok, value = ci_gate.check_metrics(
            str(log), "shrink", (1.0, 9.0), how="count"
        )
        assert ok and value >= 1.0


class TestFleetStatus:
    def test_summarizes_journal(self, tmp_path):
        log = supervisor.RestartLog(str(tmp_path / "j.jsonl"))
        log.write("start", 3.0, generation=3, size=3)
        log.write("restarts", 1.0, member="m1", kind="leave", exit_code=143)
        log.write("shrink", 2.0, generation=4, size=2)
        log.write("grow", 3.0, generation=5, size=3)
        status = supervisor.fleet_status(log.path)
        assert status["generation"] == 5 and status["size"] == 3
        assert status["restarts"] == 1
        assert status["shrinks"] == 1 and status["grows"] == 1
        assert [e["name"] for e in status["events"]] == [
            "start", "restarts", "shrink", "grow"
        ]

    def test_missing_journal_is_soft(self, tmp_path):
        status = supervisor.fleet_status(str(tmp_path / "nope.jsonl"))
        assert status["error"] == "journal not found"
        assert status["generation"] is None

    def test_torn_line_tolerated(self, tmp_path):
        p = tmp_path / "j.jsonl"
        p.write_text(
            json.dumps({"name": "start", "value": 2.0, "size": 2,
                        "generation": 1}) + "\n" + '{"name": "sh'
        )
        assert supervisor.fleet_status(str(p))["size"] == 2


class TestWiring:
    def test_cli_elastic_flags_route_to_supervise_elastic(self, monkeypatch):
        calls = {}

        def fake(nprocs, command, env=None, policy=None, elastic=None,
                 log_path=None, status_port=None, policy_config=None,
                 spares=0):
            calls.update(nprocs=nprocs, command=command, policy=policy,
                         elastic=elastic, status_port=status_port,
                         policy_config=policy_config)
            return 0

        monkeypatch.setattr(supervisor, "supervise_elastic", fake)
        code = launcher.main([
            "run", "--nprocs", "3", "--elastic", "--min-ranks", "2",
            "--max-ranks", "3", "--max-restarts", "5",
            "--", "python", "train.py",
        ])
        assert code == 0
        assert calls["nprocs"] == 3
        assert calls["elastic"].min_ranks == 2
        assert calls["elastic"].max_ranks == 3
        assert calls["policy"].max_restarts == 5

    def test_cli_min_ranks_alone_opts_in(self, monkeypatch):
        seen = {}
        monkeypatch.setattr(
            supervisor, "supervise_elastic",
            lambda *a, **k: seen.update(k) or 0,
        )
        assert launcher.main(
            ["run", "--nprocs", "2", "--min-ranks", "1", "--", "x"]
        ) == 0
        assert seen["elastic"].min_ranks == 1

    def test_pod_heartbeat_without_shared_fs_fails_fast(self, monkeypatch,
                                                        capsys):
        monkeypatch.delenv("PS_MODEL_PATH", raising=False)
        with pytest.raises(SystemExit) as e:
            launcher.main([
                "pod", "--hosts", "h1,h2", "--heartbeat-timeout", "60",
                "--", "python", "train.py",
            ])
        assert e.value.code == 2  # argparse error
        err = capsys.readouterr().err
        assert "--elastic" in err and "shared" in err

    def test_pod_heartbeat_with_model_path_accepted(self, monkeypatch):
        monkeypatch.setenv("PS_MODEL_PATH", "/tmp/shared")
        seen = {}
        monkeypatch.setattr(
            supervisor, "supervise_hosts",
            lambda *a, **k: seen.update(k) or 0,
        )
        assert launcher.main([
            "pod", "--hosts", "h1,h2", "--heartbeat-timeout", "60",
            "--", "python", "train.py",
        ]) == 0
        assert seen["policy"].heartbeat_timeout == 60.0

    def test_supervise_hosts_raises_same_contract(self, monkeypatch):
        monkeypatch.delenv("PS_MODEL_PATH", raising=False)
        with pytest.raises(ValueError, match="--elastic"):
            supervisor.supervise_hosts(
                ["h1"], ["x"], env={},
                policy=RestartPolicy(heartbeat_timeout=30.0),
            )

    def test_elastic_policy_mapping_rejects_unknown(self):
        with pytest.raises(ValueError, match="unknown elastic"):
            ElasticPolicy.from_mapping({"min_rank": 2})
        p = ElasticPolicy.from_mapping(
            {"min_ranks": "2", "rendezvous_timeout": 30}
        )
        assert p.min_ranks == 2 and p.rendezvous_timeout == 30.0

    def test_job_spec_elastic_block(self, tmp_path, monkeypatch):
        from horovod_tpu.launch import job as job_lib

        seen = {}
        monkeypatch.setattr(
            supervisor, "supervise_elastic",
            lambda nprocs, argv, **k: seen.update(nprocs=nprocs, **k) or 0,
        )
        spec = tmp_path / "job.yaml"
        spec.write_text(textwrap.dedent("""
            name: elastic-test
            job:
              command: python train.py
              nprocs: 3
              elastic:
                min_ranks: 2
                max_ranks: 3
              restart:
                max_restarts: 4
        """))
        assert job_lib.run_job(str(spec)) == 0
        assert seen["nprocs"] == 3
        assert seen["elastic"].min_ranks == 2
        assert seen["policy"].max_restarts == 4

    def test_shipped_elastic_job_spec_parses(self):
        import yaml

        spec_path = os.path.join(
            REPO, "horovod_tpu", "launch", "jobs",
            "mnist-elastic-2proc.yaml",
        )
        with open(spec_path) as f:
            spec = yaml.safe_load(f)
        ElasticPolicy.from_mapping(spec["job"]["elastic"])
        RestartPolicy.from_mapping(
            {k: v for k, v in spec["job"]["restart"].items() if k != "log"}
        )
        from horovod_tpu.testing import faults

        plan = faults.parse_plan(spec["job"]["env"]["HVT_FAULT"])
        assert plan.kind == "leave"
        assert spec["checks"]["loss"]["target"] == "0.0..0.3"
        assert spec["journal_checks"]["shrink"]["aggregate"] == "count"

    def test_shipped_sharded_elastic_job_spec_parses(self):
        """The ZeRO-1 sibling job: same elastic/restart grammar, the
        ELASTIC_ZERO1 knob on, the same unchanged loss gate, plus the
        in-spec journal gates."""
        import yaml

        spec_path = os.path.join(
            REPO, "horovod_tpu", "launch", "jobs",
            "mnist-elastic-sharded-2proc.yaml",
        )
        with open(spec_path) as f:
            spec = yaml.safe_load(f)
        elastic = ElasticPolicy.from_mapping(spec["job"]["elastic"])
        assert elastic.min_ranks == 2 and elastic.max_ranks == 3
        RestartPolicy.from_mapping(
            {k: v for k, v in spec["job"]["restart"].items() if k != "log"}
        )
        from horovod_tpu.testing import faults

        assert spec["job"]["env"]["ELASTIC_ZERO1"] == "1"
        plan = faults.parse_plan(spec["job"]["env"]["HVT_FAULT"])
        assert plan.kind == "leave" and plan.rank == 2
        # Elasticity + sharding must not move the convergence bar.
        assert spec["checks"]["loss"]["target"] == "0.0..0.3"
        assert spec["journal_checks"]["shrink"]["aggregate"] == "count"
        assert (
            spec["journal_checks"]["supervisor_gave_up"]["target"] == "0..0"
        )

    def test_job_journal_checks_gate(self, tmp_path, monkeypatch):
        """journal_checks: evaluated against the restart journal — passes
        when the journaled lifecycle matches, fails the job when it
        doesn't, and fails loudly without a supervised launch."""
        import textwrap as tw

        from horovod_tpu.launch import job as job_lib

        def fake_supervise(nprocs, argv, env=None, policy=None,
                           elastic=None, log_path=None, status_port=None,
                           policy_config=None, spares=0):
            log = supervisor.RestartLog(log_path)
            log.touch()
            if env.get("DO_SHRINK") == "1":
                log.write("shrink", 2.0, generation=2, size=2)
            return 0

        monkeypatch.setattr(supervisor, "supervise_elastic", fake_supervise)

        def write_spec(name, do_shrink):
            spec = tmp_path / name
            spec.write_text(tw.dedent(f"""
                name: jc-test
                job:
                  command: python train.py
                  nprocs: 2
                  elastic:
                    min_ranks: 1
                  env:
                    PS_MODEL_PATH: {tmp_path / name}.models
                    DO_SHRINK: "{do_shrink}"
                journal_checks:
                  shrink:
                    target: "1..9"
                    aggregate: count
            """))
            return str(spec)

        assert job_lib.run_job(write_spec("pass.yaml", 1)) == 0
        assert job_lib.run_job(write_spec("fail.yaml", 0)) == 1

    def test_job_journal_checks_require_supervised_launch(
        self, tmp_path, monkeypatch
    ):
        import textwrap as tw

        from horovod_tpu.launch import job as job_lib

        monkeypatch.setattr(launcher, "run_local", lambda *a, **k: 0)
        spec = tmp_path / "job.yaml"
        spec.write_text(tw.dedent("""
            name: jc-unsupervised
            job:
              command: python train.py
              nprocs: 1
            journal_checks:
              shrink: {target: "1..9", aggregate: count}
        """))
        assert job_lib.run_job(str(spec)) == 1


class TestWorldInfo:
    def test_from_wire_defaults(self):
        w = WorldInfo.from_wire({"rank": 0, "size": 1, "generation": 2})
        assert w.jax_coordinator is None
        assert w.root_rank == 0 and w.max_progress == -1


class TestCommitCadence:
    """Sub-epoch commit cadence (commit_every_steps) + the job-spec env
    surface. on_batch_end fires once per OPTIMIZER step, so step commits
    are accumulation-boundary-aligned by construction."""

    class _Client:
        synced_generation = 3

        def beat(self, progress=None):
            return 3

    class _Trainer:
        state = {"w": 1}

    def _callback(self, **kw):
        from horovod_tpu.elastic.state import ElasticStateCallback

        cb = ElasticStateCallback(ElasticState(), self._Client(), **kw)
        cb.trainer = self._Trainer()
        return cb

    def test_commits_every_n_steps(self):
        cb = self._callback(commit_every_steps=2)
        cb.on_epoch_begin(4)
        cb.on_batch_end(0)
        assert cb.state.commits == 0
        cb.on_batch_end(1)
        assert cb.state.commits == 1
        assert cb.state.progress == progress_marker(4, 2)
        cb.on_batch_end(2)
        assert cb.state.commits == 1
        cb.on_batch_end(3)
        assert cb.state.commits == 2
        assert cb.state.progress == progress_marker(4, 4)
        # committed snapshot carries the trainer's live state
        assert cb.state._committed["state"] == {"w": 1}

    def test_step_commit_orders_under_epoch_commit(self):
        """progress_marker total order: a mid-epoch commit of epoch E must
        rank above E's start and below the epoch-end commit (E+1, 0)."""
        assert (
            progress_marker(4, 0)
            < progress_marker(4, 7)
            < progress_marker(5, 0)
        )

    def test_marker_step_clamped_into_radix(self):
        """A beyond-radix step count degrades to an in-epoch tie — it can
        never make a mid-epoch commit outrank the NEXT epoch's start
        (which represents strictly more training)."""
        from horovod_tpu.elastic.coordinator import PROGRESS_STEP_RADIX

        huge = PROGRESS_STEP_RADIX + 12345
        assert progress_marker(0, huge) < progress_marker(1, 0)
        assert progress_marker(0, huge) == progress_marker(
            0, PROGRESS_STEP_RADIX - 1
        )

    def test_chunked_executions_commit_at_next_boundary(self):
        """steps_per_execution strides: batch indices jump by the chunk
        size; cadence uses >= since-last-commit, so a chunk striding past
        the target still commits at its end."""
        cb = self._callback(commit_every_steps=3)
        cb.on_epoch_begin(0)
        cb.on_batch_end(1)   # 2 steps done — below cadence
        assert cb.state.commits == 0
        cb.on_batch_end(3)   # 4 steps done — past cadence: commit
        assert cb.state.commits == 1
        assert cb.state.progress == progress_marker(0, 4)

    def test_epoch_begin_resets_cadence(self):
        cb = self._callback(commit_every_steps=2)
        cb.on_epoch_begin(0)
        cb.on_batch_end(1)
        assert cb.state.commits == 1
        cb.on_epoch_begin(1)
        cb.on_batch_end(0)  # 1 step into the new epoch — no commit yet
        assert cb.state.commits == 1

    def test_zero_means_epoch_cadence_only(self):
        cb = self._callback()
        cb.on_epoch_begin(0)
        for b in range(10):
            cb.on_batch_end(b)
        assert cb.state.commits == 0

    def test_env_defaults_from_job_spec_surface(self, monkeypatch):
        monkeypatch.setenv("HVT_COMMIT_EVERY", "2")
        monkeypatch.setenv("HVT_COMMIT_EVERY_STEPS", "50")
        cb = self._callback()
        assert cb.commit_every == 2
        assert cb.commit_every_steps == 50
        # explicit args beat the env
        cb2 = self._callback(commit_every=1, commit_every_steps=0)
        assert cb2.commit_every == 1 and cb2.commit_every_steps == 0

    def test_policy_parses_and_exports_commit_env(self):
        p = ElasticPolicy.from_mapping(
            {"min_ranks": 2, "commit_every": 3, "commit_every_steps": 25}
        )
        assert p.commit_every == 3 and p.commit_every_steps == 25
        assert p.commit_env() == {
            "HVT_COMMIT_EVERY": "3", "HVT_COMMIT_EVERY_STEPS": "25"
        }
        # defaults export NOTHING — user-code callback args must win
        assert ElasticPolicy().commit_env() == {}

    def test_policy_rejects_unknown_keys(self):
        with pytest.raises(ValueError, match="unknown elastic policy"):
            ElasticPolicy.from_mapping({"commit_cadence": 1})


class TestGrowOnlyFastPath:
    """A membership change that only ADDS ranks must skip the boundary
    piece-allgather: no piece's owner is departing, so survivors keep
    their compact sharded commits and sync's reassembly on the new world
    covers the joiners (ROADMAP follow-up from PR 3)."""

    class _Client:
        def __init__(self):
            self.synced_generation = 3
            self.left = []

        def beat(self, progress=None):
            return 4  # a NEW generation: membership changed

        def leave(self, reason=""):
            self.left.append(reason)

    class _Trainer:
        state = {"w": 1}

    def _boundary(self, monkeypatch, leaving_votes):
        """Run one epoch-end agreement with fake votes; returns
        (callback, gather_calls, interrupt type raised)."""
        import jax

        from horovod_tpu import runtime
        from horovod_tpu.elastic import state as state_mod
        from horovod_tpu.elastic.state import (
            ElasticStateCallback,
            HostsUpdatedInterrupt,
            LeaveInterrupt,
        )

        state = ElasticState()
        cb = ElasticStateCallback(state, self._Client())
        cb.trainer = self._Trainer()
        monkeypatch.setattr(jax, "process_count", lambda: len(leaving_votes))
        monkeypatch.setattr(
            state_mod.collectives, "allgather_object",
            lambda v: [(4, l) for l in leaving_votes],
        )
        monkeypatch.setattr(runtime, "shutdown", lambda: None)
        # A sharded commit: state.commit() is patched to mark one
        # (real cross-process arrays cannot exist in one test process).
        from horovod_tpu.elastic.state import ShardedLeaf

        def fake_commit():
            state._committed = {
                "state": ShardedLeaf(
                    shape=(2,), dtype="float32", pieces={}, digests={}
                ),
                "epoch": state.epoch, "step": state.step,
            }
            state.commits += 1

        monkeypatch.setattr(state, "commit", fake_commit)
        gathered = []
        monkeypatch.setattr(
            state, "gather_committed",
            lambda force=False: gathered.append(force),
        )
        raised = None
        try:
            cb.on_epoch_end(5)
        except (HostsUpdatedInterrupt, LeaveInterrupt) as e:
            raised = type(e).__name__
        return cb, gathered, raised

    def test_grow_only_skips_piece_allgather(self, monkeypatch):
        cb, gathered, raised = self._boundary(
            monkeypatch, leaving_votes=[False, False]
        )
        assert raised == "HostsUpdatedInterrupt"
        assert cb.state.commits == 1      # the boundary still commits
        assert gathered == []             # ...but nothing is reassembled

    def test_departure_still_gathers(self, monkeypatch):
        cb, gathered, raised = self._boundary(
            monkeypatch, leaving_votes=[False, True]
        )
        assert raised == "HostsUpdatedInterrupt"
        assert gathered == [False]        # boundary reassembly ran


class TestStepGranularElastic:
    """The sub-epoch rescale cadence (`rescale_every_steps`) + the
    (epoch, step) resume contract: steady-state rounds are one cheap
    boolean agreement; a pending membership change or leave intent
    executes the full boundary — commit at the CURRENT optimizer step,
    lockstep teardown, interrupt — and restore() hands the step back."""

    class _Client:
        def __init__(self, gen=3, pending=False):
            self.synced_generation = 3
            self._gen = gen
            self.last_beat_pending = pending
            self.left = []

        def beat(self, progress=None):
            return self._gen

        def leave(self, reason=""):
            self.left.append(reason)

    class _Trainer:
        state = {"w": 1}
        _resume_epoch = 0
        _resume_step = 0

    def _callback(self, client=None, **kw):
        from horovod_tpu.elastic.state import ElasticStateCallback

        cb = ElasticStateCallback(
            ElasticState(), client or self._Client(), **kw
        )
        cb.trainer = self._Trainer()
        return cb

    def test_restore_hands_back_epoch_and_step(self):
        s = ElasticState(state={"w": 2}, epoch=0, step=0)
        s.epoch, s.step = 4, 7
        s.commit()
        s.epoch, s.step = 5, 0  # live values drift past the commit
        assert s.restore() == (4, 7)
        assert (s.epoch, s.step) == (4, 7)

    def test_restore_before_commit_returns_current(self):
        s = ElasticState(epoch=2, step=5)
        assert s.restore() == (2, 5)

    def test_steady_state_no_interrupt_single_cheap_round(self, monkeypatch):
        """Same generation, no pending flag, nobody leaving: the cadence
        round must end at the boolean agreement — no votes, no commit,
        no interrupt."""
        import jax

        from horovod_tpu.elastic import state as state_mod

        cb = self._callback(rescale_every_steps=2)
        calls = []
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(
            state_mod.collectives, "allgather_object",
            lambda v: calls.append(v) or [v, v],
        )
        cb.on_epoch_begin(0)
        cb.on_batch_end(0)  # below cadence: nothing at all
        assert calls == []
        cb.on_batch_end(1)  # cadence boundary: ONE boolean agreement
        assert calls == [False]
        assert cb.state.commits == 0

    def test_pending_generation_executes_step_boundary(self, monkeypatch):
        """A generation drift (joiner waiting) rescales at the STEP
        boundary: commit at (epoch, done), teardown, interrupt — and the
        committed snapshot resumes at that exact step."""
        import jax

        from horovod_tpu import runtime
        from horovod_tpu.elastic import state as state_mod
        from horovod_tpu.elastic.state import HostsUpdatedInterrupt

        cb = self._callback(client=self._Client(gen=4),
                            rescale_every_steps=2)
        monkeypatch.setattr(jax, "process_count", lambda: 2)

        def fake_allgather(v):
            if isinstance(v, bool):
                return [v, v]          # the cheap agreement
            return [v, v]              # the (gen, leaving) votes

        monkeypatch.setattr(
            state_mod.collectives, "allgather_object", fake_allgather
        )
        shutdowns = []
        monkeypatch.setattr(runtime, "shutdown",
                            lambda: shutdowns.append(1))
        cb.on_epoch_begin(5)
        cb.on_batch_end(0)
        with pytest.raises(HostsUpdatedInterrupt):
            cb.on_batch_end(1)
        assert shutdowns == [1]
        assert cb.state.commits == 1
        assert cb.state.progress == progress_marker(5, 2)
        assert cb.state.restore() == (5, 2)

    def test_leave_intent_executes_step_boundary(self, monkeypatch):
        import jax

        from horovod_tpu import runtime
        from horovod_tpu.elastic import state as state_mod
        from horovod_tpu.elastic.state import LeaveInterrupt

        client = self._Client(gen=3)
        cb = self._callback(client=client, rescale_every_steps=1)
        cb._leave_requested = True
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(
            state_mod.collectives, "allgather_object", lambda v: [v, v]
        )
        monkeypatch.setattr(runtime, "shutdown", lambda: None)
        cb.on_epoch_begin(2)
        with pytest.raises(LeaveInterrupt):
            cb.on_batch_end(2)
        assert client.left == ["sigterm"]
        assert cb.state.progress == progress_marker(2, 3)

    def test_beat_pending_flag_triggers_vote(self, monkeypatch):
        """The coordinator's piggybacked pending flag alone (same
        generation number visible to THIS member) escalates to the vote
        — and a vote revealing a real drift interrupts."""
        import jax

        from horovod_tpu import runtime
        from horovod_tpu.elastic import state as state_mod
        from horovod_tpu.elastic.state import HostsUpdatedInterrupt

        cb = self._callback(client=self._Client(gen=3, pending=True),
                            rescale_every_steps=1)
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(
            state_mod.collectives, "allgather_object",
            lambda v: [v, v] if isinstance(v, bool) else [(3, False),
                                                          (4, False)],
        )
        monkeypatch.setattr(runtime, "shutdown", lambda: None)
        cb.on_epoch_begin(0)
        with pytest.raises(HostsUpdatedInterrupt):
            cb.on_batch_end(0)

    def test_pending_race_with_settle_is_soft(self, monkeypatch):
        """agree_any fires but the votes reveal no actual change (the
        pending flag raced a settle this member already adopted): keep
        training — the next cadence re-checks."""
        import jax

        from horovod_tpu.elastic import state as state_mod

        cb = self._callback(client=self._Client(gen=3, pending=True),
                            rescale_every_steps=1)
        monkeypatch.setattr(jax, "process_count", lambda: 2)
        monkeypatch.setattr(
            state_mod.collectives, "allgather_object",
            lambda v: [v, v] if isinstance(v, bool) else [(3, False),
                                                          (3, False)],
        )
        cb.on_epoch_begin(0)
        cb.on_batch_end(0)  # no raise
        assert cb.state.commits == 0

    def test_cadence_measures_from_resume_step(self):
        """A fit resumed at (epoch, S) must not insta-fire its cadences:
        baselines start at S for the resume epoch, 0 afterwards."""
        class _T:
            state = {"w": 1}
            _resume_epoch = 3
            _resume_step = 5

        cb = self._callback(commit_every_steps=4)
        cb.trainer = _T()
        cb.on_epoch_begin(3)
        assert cb._last_commit_step == 5
        cb.on_batch_end(6)  # 7 steps done, 2 since resume: below cadence
        assert cb.state.commits == 0
        cb.on_batch_end(8)  # 9 done, 4 since resume: commit
        assert cb.state.commits == 1
        assert cb.state.progress == progress_marker(3, 9)
        cb.on_epoch_begin(4)  # past the resume epoch: baseline back to 0
        assert cb._last_commit_step == 0

    def test_env_default_and_policy_export(self, monkeypatch):
        monkeypatch.setenv("HVT_RESCALE_EVERY_STEPS", "25")
        cb = self._callback()
        assert cb.rescale_every_steps == 25
        cb2 = self._callback(rescale_every_steps=0)
        assert cb2.rescale_every_steps == 0
        p = ElasticPolicy.from_mapping(
            {"rescale_every_steps": 7, "commit_every_steps": 3}
        )
        assert p.commit_env() == {
            "HVT_COMMIT_EVERY_STEPS": "3",
            "HVT_RESCALE_EVERY_STEPS": "7",
        }
        assert ElasticPolicy().commit_env() == {}


class TestCoordinatorStepProgress:
    """Beat replies piggyback the pending-membership flag, and settle
    journal records carry the root's (epoch, step) — shrink/grow
    additionally journal a step-valued record job specs can gate
    (`shrink_step: 1..N` = the shrink happened MID-epoch)."""

    def test_beat_pending_flag(self):
        coord = Coordinator(min_ranks=1, expected=1,
                            rendezvous_timeout=10.0).start()
        try:
            c = ElasticClient(coord.address, "m0")
            c.sync()
            c.beat()
            assert c.last_beat_pending is False
            # A join bumps the generation: m0's next beat says pending.
            threading.Thread(
                target=lambda: ElasticClient(coord.address, "m1").sync(),
                daemon=True,
            ).start()
            deadline = time.monotonic() + 5.0
            while not c.last_beat_pending:
                assert time.monotonic() < deadline
                time.sleep(0.05)
                c.beat()
            # m0 re-rendezvouses; the settled world clears the flag.
            c.sync()
            c.beat()
            assert c.last_beat_pending is False
        finally:
            coord.stop()

    def test_shrink_journal_carries_step(self, tmp_path):
        log = supervisor.RestartLog(str(tmp_path / "j.jsonl"))
        coord = Coordinator(min_ranks=1, expected=3,
                            rendezvous_timeout=10.0,
                            journal=log.write).start()
        try:
            _sync_all(coord.address, ["m0", "m1", "m2"])
            # m2 leaves with the fleet's freshest committed progress at
            # (epoch 1, step 3) — a MID-epoch boundary.
            ElasticClient(coord.address, "m2").leave()
            _sync_all(
                coord.address, ["m0", "m1"],
                progress={"m0": progress_marker(1, 3),
                          "m1": progress_marker(1, 3)},
            )
        finally:
            coord.stop()
        records = _journal(str(tmp_path / "j.jsonl"))
        shrink = next(r for r in records if r["name"] == "shrink")
        assert shrink["epoch"] == 1 and shrink["step"] == 3
        assert shrink["progress"] == progress_marker(1, 3)
        steps = [r for r in records if r["name"] == "shrink_step"]
        assert steps and steps[-1]["value"] == 3.0
        # the CI-gate contract of mnist-elastic-midstep-2proc.yaml
        ok, value = ci_gate.check_metrics(
            str(tmp_path / "j.jsonl"), "shrink_step", (1.0, 999999.0),
            how="max",
        )
        assert ok and value == 3.0
