"""Bootstrap/topology parity tests (hvd.init()/rank/size surface, SURVEY.md §2.4)."""

import jax

import horovod_tpu as hvt


def test_init_idempotent_single_process():
    w1 = hvt.init()
    w2 = hvt.init()
    assert w1 == w2
    assert hvt.is_initialized()


def test_topology_queries():
    hvt.init()
    # 8 fake devices, one process: size() is chip count (what LR scaling
    # reacts to), rank() is the single-writer gate.
    assert hvt.size() == 8
    assert hvt.rank() == 0
    assert hvt.local_rank() == 0
    assert hvt.local_size() == 8
    assert hvt.process_count() == 1
    assert hvt.is_primary()


def test_world_snapshot():
    w = hvt.runtime.world()
    assert w.device_count == jax.device_count() == 8
    assert not w.is_distributed
