"""Mesh construction and world-size-reactive scaling helpers (SURVEY.md §5.6)."""

import pytest

import horovod_tpu as hvt
from horovod_tpu.parallel.mesh import AXES, MeshSpec, build_mesh, dp_size


def test_default_mesh_is_pure_dp():
    mesh = hvt.data_parallel_mesh()
    assert mesh.axis_names == AXES
    assert mesh.shape["data"] == 8
    assert all(mesh.shape[ax] == 1 for ax in AXES if ax != "data")
    assert dp_size(mesh) == 8


def test_mesh_spec_resolution():
    assert MeshSpec(model=2).resolve(8) == {
        "data": 4, "fsdp": 1, "pipe": 1, "seq": 1, "model": 2, "expert": 1,
    }
    assert MeshSpec(data=2, seq=2, model=2).resolve(8)["fsdp"] == 1
    with pytest.raises(ValueError):
        MeshSpec(data=3).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec(data=-1, model=-1).resolve(8)


def test_mixed_mesh_builds():
    mesh = build_mesh(MeshSpec(data=2, model=2, seq=2))
    assert mesh.shape["data"] == 2
    assert mesh.shape["model"] == 2
    assert mesh.shape["seq"] == 2
    assert mesh.devices.size == 8


def test_scaling_helpers_match_reference_idioms():
    # lr × size (tensorflow2_keras_mnist.py:55)
    assert hvt.scale_lr(0.001, 8) == pytest.approx(0.008)
    # steps // size (tensorflow2_keras_mnist.py:96)
    assert hvt.shard_steps(500, 8) == 62
    assert hvt.shard_steps(500, 1) == 500
    # ceil(epochs / size) (mnist_keras.py:42)
    assert hvt.shard_epochs(12, 8) == 2
    assert hvt.shard_epochs(12, 1) == 12
    # defaults react to the ambient world (8 fake chips)
    assert hvt.scale_lr(1.0) == 8.0
