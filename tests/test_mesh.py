"""Mesh construction and world-size-reactive scaling helpers (SURVEY.md §5.6)."""

import jax
import numpy as np
import pytest

import horovod_tpu as hvt
from horovod_tpu.parallel.mesh import AXES, MeshSpec, build_mesh, dp_size


def test_default_mesh_is_pure_dp():
    mesh = hvt.data_parallel_mesh()
    assert mesh.axis_names == AXES
    assert mesh.shape["data"] == 8
    assert all(mesh.shape[ax] == 1 for ax in AXES if ax != "data")
    assert dp_size(mesh) == 8


def test_mesh_spec_resolution():
    assert MeshSpec(model=2).resolve(8) == {
        "data": 4, "fsdp": 1, "pipe": 1, "seq": 1, "model": 2, "expert": 1,
    }
    assert MeshSpec(data=2, seq=2, model=2).resolve(8)["fsdp"] == 1
    with pytest.raises(ValueError):
        MeshSpec(data=3).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec(data=-1, model=-1).resolve(8)


def test_mixed_mesh_builds():
    mesh = build_mesh(MeshSpec(data=2, model=2, seq=2))
    assert mesh.shape["data"] == 2
    assert mesh.shape["model"] == 2
    assert mesh.shape["seq"] == 2
    assert mesh.devices.size == 8


def test_scaling_helpers_match_reference_idioms():
    # lr × size (tensorflow2_keras_mnist.py:55)
    assert hvt.scale_lr(0.001, 8) == pytest.approx(0.008)
    # steps // size (tensorflow2_keras_mnist.py:96)
    assert hvt.shard_steps(500, 8) == 62
    assert hvt.shard_steps(500, 1) == 500
    # ceil(epochs / size) (mnist_keras.py:42)
    assert hvt.shard_epochs(12, 8) == 2
    assert hvt.shard_epochs(12, 1) == 12
    # defaults react to the ambient world (8 fake chips)
    assert hvt.scale_lr(1.0) == 8.0


class TestDeviceLayout:
    """ICI-topology-aware device layout (mesh._device_array): multi-chip
    TPU delegates to mesh_utils.create_device_mesh so mesh-axis rings ride
    physical links; CPU/virtual devices and HVT_MESH_ORDER=flat keep the
    deterministic enumeration-order reshape the tests (and multi-process
    bit-parity) rely on."""

    class _FakeTpu:
        platform = "tpu"

        def __init__(self, i):
            self.id = i

    def test_cpu_devices_use_flat_reshape(self):
        from horovod_tpu.parallel.mesh import _device_array

        devs = np.asarray(jax.devices())
        shape = (2, 1, 2, 1, 2, 1)
        out = _device_array(devs, shape)
        assert [d.id for d in out.flat] == [d.id for d in devs.flat]

    def test_tpu_devices_route_through_mesh_utils(self, monkeypatch):
        from jax.experimental import mesh_utils

        from horovod_tpu.parallel.mesh import _device_array

        calls = {}

        def fake_create(shape, devices=None, **kw):
            calls["shape"] = tuple(shape)
            calls["n"] = len(devices)
            return np.asarray(devices).reshape(shape)[::-1]  # any permutation

        monkeypatch.setattr(mesh_utils, "create_device_mesh", fake_create)
        devs = np.asarray([self._FakeTpu(i) for i in range(8)])
        out = _device_array(devs, (8,))
        assert calls == {"shape": (8,), "n": 8}
        assert [d.id for d in out.flat] == list(reversed(range(8)))

    def test_flat_override_skips_mesh_utils(self, monkeypatch):
        from jax.experimental import mesh_utils

        from horovod_tpu.parallel.mesh import _device_array

        def boom(*a, **kw):
            raise AssertionError("must not be called with order='flat'")

        monkeypatch.setattr(mesh_utils, "create_device_mesh", boom)
        devs = np.asarray([self._FakeTpu(i) for i in range(8)])
        out = _device_array(devs, (2, 4), order="flat")
        assert [d.id for d in out.flat] == list(range(8))

    def test_solver_rejection_falls_back_to_flat(self, monkeypatch):
        from jax.experimental import mesh_utils

        from horovod_tpu.parallel.mesh import _device_array

        def reject(*a, **kw):
            raise ValueError("no assignment for this topology")

        monkeypatch.setattr(mesh_utils, "create_device_mesh", reject)
        devs = np.asarray([self._FakeTpu(i) for i in range(6)])
        out = _device_array(devs, (6,))
        assert [d.id for d in out.flat] == list(range(6))

    def test_bad_order_rejected(self):
        from horovod_tpu.parallel.mesh import _device_array

        with pytest.raises(ValueError, match="HVT_MESH_ORDER"):
            _device_array(np.asarray(jax.devices()), (8,), order="torus")


class TestHybridLayout:
    """Multi-slice (DCN-connected) device sets: the slice count is factored
    out of the outermost (low-traffic) axes and routed through
    create_hybrid_device_mesh so model/seq/expert collectives stay on ICI."""

    class _SliceDev:
        platform = "tpu"

        def __init__(self, i, slice_index):
            self.id = i
            self.slice_index = slice_index

    def _devs(self, n=8, slices=2):
        per = n // slices
        return np.asarray(
            [self._SliceDev(i, i // per) for i in range(n)]
        )

    def test_hybrid_shapes_factor_outermost(self):
        from horovod_tpu.parallel.mesh import _hybrid_shapes

        # data=4 absorbs 2 slices -> dcn (2,..), ici (2,..)
        assert _hybrid_shapes((4, 1, 1, 2, 1, 1), 2) == (
            (2, 1, 1, 1, 1, 1), (2, 1, 1, 2, 1, 1)
        )
        # data=1: slices fall through to pipe
        assert _hybrid_shapes((1, 1, 2, 1, 2, 2), 2) == (
            (1, 1, 2, 1, 1, 1), (1, 1, 1, 1, 2, 2)
        )
        # split across data AND fsdp (6 slices = 2 x 3)
        assert _hybrid_shapes((2, 3, 1, 1, 4, 1), 6) == (
            (2, 3, 1, 1, 1, 1), (1, 1, 1, 1, 4, 1)
        )
        # unfactorable
        assert _hybrid_shapes((1, 1, 1, 1, 8, 1), 3) is None

    def test_multi_slice_routes_through_hybrid(self, monkeypatch):
        from jax.experimental import mesh_utils

        from horovod_tpu.parallel.mesh import _device_array

        calls = {}

        def fake_hybrid(ici_shape, dcn_shape, devices=None, **kw):
            calls["ici"] = tuple(ici_shape)
            calls["dcn"] = tuple(dcn_shape)
            full = tuple(i * d for i, d in zip(ici_shape, dcn_shape))
            return np.asarray(devices).reshape(full)

        monkeypatch.setattr(
            mesh_utils, "create_hybrid_device_mesh", fake_hybrid
        )
        shape = (4, 1, 1, 1, 2, 1)  # data=4, model=2 over 2 slices
        out = _device_array(self._devs(8, 2), shape)
        assert out.shape == shape
        assert calls == {
            "dcn": (2, 1, 1, 1, 1, 1), "ici": (2, 1, 1, 1, 2, 1)
        }

    def test_single_slice_uses_plain_mesh(self, monkeypatch):
        from jax.experimental import mesh_utils

        from horovod_tpu.parallel.mesh import _device_array

        def boom(*a, **kw):
            raise AssertionError("hybrid must not be called for one slice")

        monkeypatch.setattr(mesh_utils, "create_hybrid_device_mesh", boom)
        monkeypatch.setattr(
            mesh_utils, "create_device_mesh",
            lambda shape, devices=None, **kw: np.asarray(devices).reshape(shape),
        )
        out = _device_array(self._devs(8, 1), (8, 1, 1, 1, 1, 1))
        assert out.shape == (8, 1, 1, 1, 1, 1)

    def test_unfactorable_slices_warn_and_flatten(self, monkeypatch):
        import warnings

        from horovod_tpu.parallel.mesh import _device_array

        devs = np.asarray(
            [self._SliceDev(i, i // 2) for i in range(6)]  # 3 slices
        )
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            out = _device_array(devs, (1, 1, 1, 1, 6, 1))
        assert out.shape == (1, 1, 1, 1, 6, 1)
        assert any("falling back" in str(x.message) for x in w)
