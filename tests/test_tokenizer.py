"""Byte-level BPE: lossless round-trip, merge learning, specials,
persistence, and the text → packing → model bridge."""

import time

import numpy as np
import pytest

from horovod_tpu.data import tokenizer as tokenizer_mod
from horovod_tpu.data.tokenizer import ByteBPETokenizer, _pretokenize

CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "the quick brown fox was quick and the dog was lazy",
    "pack my box with five dozen liquor jugs",
    "the the the quick quick brown brown",
]


class TestPretokenize:
    def test_space_attaches_forward(self):
        assert _pretokenize("hello world") == [b"hello", b" world"]
        assert _pretokenize("  hi") == [b"  hi"]
        assert _pretokenize("a\nb") == [b"a", b"\nb"]
        assert _pretokenize("") == []

    def test_reassembles(self):
        for t in CORPUS + ["  x  y  ", "tab\tsep"]:
            assert b"".join(_pretokenize(t)).decode() == t


class TestRoundTrip:
    def test_lossless_any_unicode(self):
        tok = ByteBPETokenizer.train(CORPUS, vocab_size=300)
        for t in CORPUS + [
            "unseen wörds — ünïcode ✓ 中文 🙂",
            "\n\n  leading and trailing  \n",
            "",
        ]:
            assert tok.decode(tok.encode(t)) == t

    def test_untrained_is_raw_bytes(self):
        tok = ByteBPETokenizer()
        ids = tok.encode("hi é")
        assert ids == list("hi é".encode("utf-8"))
        assert tok.decode(ids) == "hi é"


class TestTraining:
    def test_merges_compress(self):
        tok = ByteBPETokenizer.train(CORPUS, vocab_size=400)
        raw = sum(len(t.encode()) for t in CORPUS)
        enc = sum(len(tok.encode(t)) for t in CORPUS)
        assert enc < raw * 0.7  # repeated words collapse
        # " the" (the most frequent unit) became few tokens.
        assert len(tok.encode(" the")) <= 2

    def test_vocab_accounting(self):
        tok = ByteBPETokenizer.train(CORPUS, vocab_size=300, specials=("<eos>",))
        assert tok.vocab_size <= 300
        assert all(i < tok.vocab_size for i in tok.encode(CORPUS[0]))

    def test_stops_when_nothing_repeats(self):
        tok = ByteBPETokenizer.train(["ab"], vocab_size=10_000)
        assert tok.vocab_size < 300  # no runaway merges on a tiny corpus

    def test_deterministic(self):
        a = ByteBPETokenizer.train(CORPUS, vocab_size=350)
        b = ByteBPETokenizer.train(CORPUS, vocab_size=350)
        assert a.merges == b.merges


class TestSpecials:
    def test_whole_literal_match(self):
        tok = ByteBPETokenizer.train(CORPUS, vocab_size=300, specials=("<eos>",))
        ids = tok.encode("the dog<eos>the fox")
        assert ids.count(tok.special_id("<eos>")) == 1
        assert tok.decode(ids) == "the dog<eos>the fox"

    def test_longest_special_wins_at_same_position(self):
        tok = ByteBPETokenizer(specials=("<e>", "<eos>"))
        ids = tok.encode("x<eos>y")
        assert tok.special_id("<eos>") in ids
        assert tok.special_id("<e>") not in ids
        assert tok.decode(ids) == "x<eos>y"


class TestPersistence:
    def test_save_load_identical(self, tmp_path):
        tok = ByteBPETokenizer.train(CORPUS, vocab_size=320, specials=("<eos>",))
        p = tok.save(str(tmp_path / "tok.json"))
        tok2 = ByteBPETokenizer.load(p)
        for t in CORPUS:
            assert tok.encode(t) == tok2.encode(t)
        assert tok2.vocab_size == tok.vocab_size

    def test_load_rejects_garbage(self, tmp_path):
        p = tmp_path / "x.json"
        p.write_text("{}")
        with pytest.raises(ValueError, match="not a tokenizer"):
            ByteBPETokenizer.load(str(p))


class TestPackingBridge:
    def test_corpus_to_packed_rows(self):
        from horovod_tpu.data.packing import pack_documents

        tok = ByteBPETokenizer.train(CORPUS, vocab_size=300)
        docs = tok.encode_corpus(CORPUS)
        assert all(d.dtype == np.int32 for d in docs)
        tokens, seg, _ = pack_documents(docs, seq_len=32)
        assert tokens.shape == seg.shape
        assert tokens.shape[1] == 32
        # Every document survives packing intact: docs here are shorter
        # than seq_len, so each is exactly one segment of one row.
        chunks = {
            tuple(tokens[r][seg[r] == s])
            for r in range(len(tokens))
            for s in set(seg[r][seg[r] > 0])
        }
        for d in docs:
            assert tuple(d) in chunks


class TestIncrementalTrainer:
    def test_matches_full_rescan_trainer(self):
        # The incremental merge-queue trainer must learn EXACTLY the merges
        # of the O(merges x corpus) full-rescan reference it replaced
        # (same count ordering, ties to the smallest (a, b) pair).
        def rescan_train(texts, n_merges):
            import collections

            word_freq = collections.Counter()
            for t in texts:
                word_freq.update(tokenizer_mod._pretokenize(t))
            words = [(list(w), f) for w, f in word_freq.items()]
            merges = []
            for _ in range(n_merges):
                pairs = collections.Counter()
                for sym, f in words:
                    for a, b in zip(sym, sym[1:]):
                        pairs[(a, b)] += f
                if not pairs:
                    break
                (a, b), count = max(
                    pairs.items(),
                    key=lambda kv: (kv[1], -kv[0][0], -kv[0][1]),
                )
                if count < 2:
                    break
                new_id = 256 + len(merges)
                merges.append((a, b))
                for sym, _ in words:
                    i = 0
                    while i < len(sym) - 1:
                        if sym[i] == a and sym[i + 1] == b:
                            sym[i : i + 2] = [new_id]
                        else:
                            i += 1
            return merges

        corpus = [
            "the quick brown fox jumps over the lazy dog",
            "pack my box with five dozen liquor jugs",
            "the the the quick quick fox fox fox dog",
            "sphinx of black quartz judge my vow " * 3,
        ] * 4
        expected = rescan_train(corpus, 120)
        got = ByteBPETokenizer.train(corpus, vocab_size=256 + 120).merges
        assert got == expected

    def test_mb_scale_corpus_trains_fast(self):
        # ~2 MB synthetic corpus with natural-ish word repetition: the
        # incremental trainer must finish in seconds (the rescan trainer
        # took minutes here). Generous bound - the test box is 1 CPU and
        # may be running a sibling suite.
        rng = np.random.RandomState(0)
        lexicon = [
            "".join(
                rng.choice(list("abcdefghijklmnopqrstuvwxyz"))
                for _ in range(int(rng.randint(2, 12)))
            )
            for _ in range(2000)
        ]
        zipf = rng.zipf(1.3, size=400_000) % len(lexicon)
        text = " ".join(lexicon[i] for i in zipf)
        assert len(text) > 2_000_000
        t0 = time.time()
        tok = ByteBPETokenizer.train([text], vocab_size=1024)
        elapsed = time.time() - t0
        assert len(tok.merges) == 1024 - 256
        assert elapsed < 90, f"BPE training took {elapsed:.1f}s"
        # Round-trip still exact on a sample.
        sample = text[:2000]
        assert tok.decode(tok.encode(sample)) == sample
