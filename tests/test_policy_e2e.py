"""The policy engine's acceptance runs, end-to-end on CPU (ISSUE 16):

* a 2-proc elastic fleet with ``HVT_FAULT=1:0:slow:200`` (the recurring
  straggler shape — rank 1 pays +200 ms per step, forever): the fleet
  poller's member scrapes carry the SkewProbe verdict, the policy engine
  confirms it across consecutive fresh windows, SIGTERMs the member, the
  elastic leave→shrink path re-slices the work, training completes at
  size 1 with the loss gate green and the restart budget UNSPENT;
* the dry-run variant journals the identical decision and touches
  nothing — both ranks finish;
* a ``reorder``-wedged supervised fleet journals the `hvt-sched replay`
  first-divergence verdict (``policy_triage``) BEFORE the relaunch
  decision;
* the spare-promotion run (``spares=1``): the evicted straggler's slot
  is refilled by the parked warm standby, so world size is preserved.

All chaos is injected through env vars (`horovod_tpu.testing.faults`);
the training script is the plain `elastic.run` idiom plus the metrics
exporter the observe half of the loop reads."""

import json
import os
import re
import socket
import sys
import textwrap

import pytest

from horovod_tpu.launch import ci_gate, supervisor
from horovod_tpu.launch.policy import PolicyConfig
from horovod_tpu.launch.supervisor import ElasticPolicy, RestartPolicy

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

EPOCHS = 14

# The synthetic elastic trainer from test_elastic_e2e, with the policy
# loop's sensing tier wired on: metrics exporter (HVT_METRICS_PORT +
# local rank), the step-phase sampler feeding SkewProbe every
# HVT_METRICS_EVERY steps, and per-epoch loss pushed to the CI-gate
# metrics stream (the mnist-policy-2proc.yaml `checks:` shape).
TRAIN_SCRIPT = """
import os, sys, time
sys.path.insert(0, __REPO__)
import numpy as np
import optax
import flax.linen as nn
import horovod_tpu as hvt
from horovod_tpu import checkpoint, elastic, metrics

metrics.init()
print(f"BOOT member={os.environ['HVT_ELASTIC_MEMBER']}", flush=True)


class Tiny(nn.Module):
    @nn.compact
    def __call__(self, x, train=False):
        return nn.Dense(4)(x)


def train(state, world):
    print(
        f"GEN member={os.environ['HVT_ELASTIC_MEMBER']} rank={world.rank} "
        f"size={world.size} gen={world.generation}", flush=True,
    )
    model_dir = os.path.join(os.environ["PS_MODEL_PATH"], "run")
    rng = np.random.RandomState(0)
    # Separable on purpose: the loss gate asserts the eviction did not
    # cost convergence, so the task must actually converge.
    y = (np.arange(96) % 4).astype("int64")
    x = (np.eye(8, dtype="float32")[y] + 0.1 * rng.rand(96, 8)).astype(
        "float32")
    trainer = hvt.Trainer(Tiny(), hvt.DistributedOptimizer(optax.adam(0.1)))
    trainer.build(x[:1], y[:1])
    if state.state is not None:
        trainer.install_state(state.state)
    else:
        trainer.state, done = checkpoint.restore_latest_and_broadcast(
            model_dir, trainer.state, mesh=trainer.mesh)
        state.epoch = max(state.epoch, done)
    cbs = []
    if world.rank == 0:
        cbs.append(hvt.callbacks.ModelCheckpoint(
            os.path.join(model_dir, "checkpoint-{epoch}.msgpack")))

    class Status(hvt.callbacks.Callback):
        def on_epoch_end(self, epoch, logs=None):
            import jax
            step = int(jax.device_get(self.trainer.state.step))
            print(
                f"STATUS epoch={epoch + 1} step={step} rank={world.rank} "
                f"size={world.size} gen={world.generation}", flush=True,
            )
            if logs and "loss" in logs and world.rank == 0:
                metrics.push("loss", float(logs["loss"]))

    cbs.append(Status())
    cbs.append(elastic.ElasticStateCallback(state, state.client))
    trainer.fit(
        x=x, y=y, batch_size=8, epochs=__EPOCHS__,
        initial_epoch=state.epoch, steps_per_epoch=2, callbacks=cbs,
        verbose=0,
    )


elastic.run(train)
print("TRAINING COMPLETE", flush=True)
"""


def _write_script(tmp_path, epochs=EPOCHS):
    path = tmp_path / "elastic_train.py"
    path.write_text(
        textwrap.dedent(TRAIN_SCRIPT)
        .replace("__REPO__", repr(REPO))
        .replace("__EPOCHS__", str(epochs))
    )
    return [sys.executable, str(path)]


def _journal(log):
    with open(log) as f:
        return [json.loads(line) for line in f if line.strip()]


def _port_base(n):
    """A window of n consecutive free loopback ports (member exporters
    bind HVT_METRICS_PORT + local rank)."""
    for base in range(30850, 60000, 43):
        socks = []
        try:
            for i in range(n):
                s = socket.socket()
                s.bind(("127.0.0.1", base + i))
                socks.append(s)
            return base
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError("no free port window")


def _env(tmp_path, model_dir, base):
    return {
        "HVT_PLATFORM": "cpu",
        "HVT_NUM_CPU_DEVICES": "1",
        "PS_MODEL_PATH": str(model_dir),
        # The recurring straggler: rank 1, epoch 0 onward, +200 ms per
        # step — no stamp, the fault never "spends".
        "HVT_FAULT": "1:0:slow:200",
        # The sensing tier: member exporters at base + local rank, the
        # step-phase sampler (and so the SkewProbe allgather) every 2
        # optimizer steps = every epoch here, fleet poller at 0.2 s.
        "HVT_METRICS_PORT": str(base),
        "HVT_METRICS_EVERY": "2",
        "HVT_FLEET_POLL_S": "0.2",
        # Chaos children stay out of the suite's shared persistent XLA
        # cache (see test_supervisor_e2e._env for the torn-entry
        # SEGFAULT).
        "JAX_ENABLE_COMPILATION_CACHE": "0",
        "JAX_COMPILATION_CACHE_DIR": "",
    }


def _policy_config(mode, **over):
    return PolicyConfig.from_mapping({
        "mode": mode, "straggler_windows": 2, "straggler_wait_ms": 50,
        "evict_budget": 1, "cooldown_s": 5, **over,
    })


@pytest.mark.slow
def test_slow_straggler_evicted_shrinks_and_completes(tmp_path, capfd):
    """THE acceptance run: the observe→act loop closed over a real
    fleet. The eviction must spend ZERO restart budget — that is the
    entire point of acting below the restart rung."""
    argv = _write_script(tmp_path)
    model_dir = tmp_path / "models"
    log = tmp_path / "restarts.jsonl"
    base = _port_base(3)
    code = supervisor.supervise_elastic(
        2, argv, env=_env(tmp_path, model_dir, base),
        policy=RestartPolicy(max_restarts=4, backoff=0.5,
                             grace_seconds=10.0),
        elastic=ElasticPolicy(min_ranks=1, max_ranks=2,
                              rendezvous_timeout=180.0),
        model_dir=str(model_dir), log_path=str(log),
        status_port=base + 2,
        policy_config=_policy_config("on"),
    )
    out = capfd.readouterr().out
    assert code == 0, out[-4000:]

    records = _journal(log)
    evicts = [r for r in records if r["name"] == "policy_evict"]
    assert evicts, out[-4000:]
    assert evicts[0]["outcome"] == "sigterm"
    assert evicts[0]["rank"] == 1  # the fault's target, named by vote
    assert evicts[0]["voters"] >= 2
    assert any(r["name"] == "policy_warn" and r["rank"] == 1
               for r in records)
    # The evictee left CLEANLY and the world shrank in place.
    assert any(r["name"] == "shrink" and r["size"] == 1 for r in records)
    # Restart budget unspent: the rescue was an eviction, not a restart.
    assert not [r for r in records if r["name"] == "restarts"]
    assert not [r for r in records if r["name"] == "supervisor_gave_up"]

    # Training completed (the survivor ran every epoch) with the loss
    # gate green — the mnist-policy-2proc.yaml `checks:` contract.
    assert "TRAINING COMPLETE" in out
    statuses = [
        int(m.group(1))
        for m in re.finditer(r"STATUS epoch=(\d+)", out)
    ]
    assert statuses and max(statuses) == EPOCHS
    ok, value = ci_gate.check_metrics(
        os.path.join(str(model_dir), "metrics.jsonl"),
        "loss", (0.0, 0.3), how="last",
    )
    assert ok, f"final loss {value} outside the gate"
    # Some epoch actually trained at the shrunken size.
    assert re.search(r"STATUS epoch=\d+ step=\d+ rank=0 size=1", out)


@pytest.mark.slow
def test_dry_run_journals_decision_without_evicting(tmp_path, capfd):
    """HVT_POLICY=dry-run: the identical decision lands in the journal
    (budget charged, rank named) but the fleet is untouched — both
    ranks run every epoch at size 2."""
    epochs = 10
    argv = _write_script(tmp_path, epochs=epochs)
    model_dir = tmp_path / "models"
    log = tmp_path / "restarts.jsonl"
    base = _port_base(3)
    code = supervisor.supervise_elastic(
        2, argv, env=_env(tmp_path, model_dir, base),
        policy=RestartPolicy(max_restarts=4, backoff=0.5,
                             grace_seconds=10.0),
        elastic=ElasticPolicy(min_ranks=1, max_ranks=2,
                              rendezvous_timeout=180.0),
        model_dir=str(model_dir), log_path=str(log),
        status_port=base + 2,
        policy_config=_policy_config("dry-run"),
    )
    out = capfd.readouterr().out
    assert code == 0, out[-4000:]
    records = _journal(log)
    evicts = [r for r in records if r["name"] == "policy_evict"]
    assert evicts, out[-4000:]
    assert evicts[0]["outcome"] == "dry-run"
    assert evicts[0]["rank"] == 1
    assert evicts[0]["mode"] == "dry-run"
    # Nothing acted: no shrink, no restarts, the straggler ran to the
    # end at full size.
    assert not [r for r in records if r["name"] == "shrink"]
    assert not [r for r in records if r["name"] == "restarts"]
    statuses = [
        (int(m.group(1)), int(m.group(2)))
        for m in re.finditer(r"STATUS epoch=(\d+) .*size=(\d+)", out)
    ]
    assert statuses and max(e for e, _ in statuses) == epochs
    assert all(s == 2 for _, s in statuses), statuses
    assert out.count("TRAINING COMPLETE") == 2


@pytest.mark.slow
def test_spare_promotion_preserves_world_size(tmp_path, capfd):
    """``spares=1``: three processes launch, one parks at the full
    world's door; the straggler eviction frees its slot and the spare
    joins — world size is PRESERVED instead of shrunk, still without a
    restart-budget spend."""
    argv = _write_script(tmp_path)
    model_dir = tmp_path / "models"
    log = tmp_path / "restarts.jsonl"
    base = _port_base(4)
    code = supervisor.supervise_elastic(
        2, argv, env=_env(tmp_path, model_dir, base),
        policy=RestartPolicy(max_restarts=4, backoff=0.5,
                             grace_seconds=10.0),
        elastic=ElasticPolicy(min_ranks=1, max_ranks=2,
                              rendezvous_timeout=180.0),
        model_dir=str(model_dir), log_path=str(log),
        status_port=base + 3,
        policy_config=_policy_config("on", spares=1),
    )
    out = capfd.readouterr().out
    assert code == 0, out[-4000:]
    records = _journal(log)
    evicts = [r for r in records if r["name"] == "policy_evict"]
    assert evicts and evicts[0]["outcome"] == "sigterm", out[-4000:]
    promotes = [r for r in records if r["name"] == "policy_promote"]
    assert promotes and promotes[0]["outcome"] == "released"
    assert promotes[0]["spares"] >= 1
    # The freed slot was refilled: a settle at FULL size after the
    # eviction decision.
    evict_at = records.index(evicts[0])
    assert any(
        r["name"] in ("grow", "steady") and r.get("size") == 2
        for r in records[evict_at:]
    ), [r["name"] for r in records]
    # Still zero restart-budget spend: the spare was a warm standby,
    # not a respawn.
    assert not [r for r in records if r["name"] == "restarts"]
    # Three boots exactly: 2 members + 1 spare; nobody was respawned.
    boots = re.findall(r"BOOT member=(\S+)", out)
    assert len(set(boots)) == 3, boots
    assert "TRAINING COMPLETE" in out


@pytest.mark.slow
def test_reorder_hang_triage_journaled_before_relaunch(tmp_path, capfd):
    """The hang auto-triage leg: rank 0 reorders its collective
    submissions and wedges; the supervisor collects the flight records,
    and the engine journals the `hvt-sched replay` first-divergence
    verdict (``policy_triage``) BEFORE the relaunch decision — a
    ``reorder`` hang is diagnosed, not just restarted."""
    from tests.test_supervisor import write_train_script

    argv = write_train_script(tmp_path)
    model_dir = tmp_path / "models"
    flight_dir = tmp_path / "flight"
    log = tmp_path / "restarts.jsonl"
    env = {
        "HVT_PLATFORM": "cpu",
        "HVT_NUM_CPU_DEVICES": "2",
        "PS_MODEL_PATH": str(model_dir),
        "DRIVE_EPOCHS": "2",
        "HVT_FAULT": "0:1:reorder",
        "HVT_FAULT_STAMP": str(tmp_path / "fault-stamp"),
        "HVT_FLIGHT_RECORD": str(flight_dir),
        # The engine rides the supervise loop via the env knob — the
        # whole-fleet mode has no actuator, so dry-run IS the mode.
        "HVT_POLICY": "dry-run",
        "JAX_ENABLE_COMPILATION_CACHE": "0",
        "JAX_COMPILATION_CACHE_DIR": "",
    }
    code = supervisor.supervise_local(
        2, argv, env=env,
        policy=RestartPolicy(
            max_restarts=4, backoff=0.0, grace_seconds=5.0,
            heartbeat_timeout=20.0,
        ),
        model_dir=str(model_dir), log_path=str(log),
        sleep=lambda s: None,
    )
    assert code == 0, capfd.readouterr().out[-4000:]
    records = _journal(log)
    names = [r["name"] for r in records]
    triage = [r for r in records if r["name"] == "policy_triage"]
    assert triage, names
    # The verdict names the seeded divergence...
    assert triage[0]["outcome"] == "diverged"
    assert triage[0]["kind"] == "mismatch"
    assert {triage[0]["member_a"], triage[0]["member_b"]} == {
        "rank0", "rank1"
    }
    assert triage[0]["op_a"] != triage[0]["op_b"]
    # ... and lands BEFORE the relaunch decision for that hang.
    hang_restart_at = next(
        i for i, r in enumerate(records)
        if r["name"] == "restarts" and r["kind"] == "hang"
    )
    assert records.index(triage[0]) < hang_restart_at
    # The collection the verdict was computed over is the journaled one.
    dumps = [r for r in records if r["name"] == "flight_dump"]
    assert dumps and triage[0]["dir"] == dumps[0]["dir"]


class TestShippedPolicyJobSpec:
    """mnist-policy-2proc.yaml parses through the same validators the
    launch path uses (tier-1 — the slow run above proves the scenario
    itself against the synthetic trainer)."""

    def _spec(self):
        import yaml

        path = os.path.join(
            REPO, "horovod_tpu", "launch", "jobs",
            "mnist-policy-2proc.yaml",
        )
        with open(path) as f:
            return yaml.safe_load(f)

    def test_spec_validates_clean(self):
        from horovod_tpu.launch import job as job_mod

        assert job_mod.validate_spec(self._spec()) == []

    def test_blocks_carry_the_scenario(self):
        from horovod_tpu.testing import faults

        spec = self._spec()
        pcfg = PolicyConfig.from_mapping(spec["job"]["policy"])
        assert pcfg.mode == "on" and pcfg.active
        assert pcfg.evict_budget == 1
        plan = faults.parse_plan(spec["job"]["env"]["HVT_FAULT"])
        assert plan.rank == 1 and plan.slow_ms == 200.0
        # The gates encode the acceptance: an eviction happened, the
        # world shrank, the restart budget was untouched, loss landed.
        assert spec["journal_checks"]["policy_evict"]["target"] == "1..9"
        assert spec["journal_checks"]["shrink"]["target"] == "1..9"
        assert spec["metrics_checks"]["hvt_restarts_total"][
            "target"] == "0..0"
        assert "loss" in spec["checks"]
