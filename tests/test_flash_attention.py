"""Pallas flash-attention kernel vs the dense reference (interpret mode —
the same kernel code the TPU compiles, run through the pallas interpreter)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops.attention import dense_attention
from horovod_tpu.ops.flash_attention import (
    flash_attention,
    flash_attention_with_lse,
    pick_blocks,
    supported,
)

B, T, H, D = 2, 128, 4, 64
BLOCKS = dict(block_q=32, block_k=32)


def _qkv(seed=0):
    rng = np.random.RandomState(seed)
    return tuple(
        jnp.asarray(rng.randn(B, T, H, D).astype(np.float32)) for _ in range(3)
    )


class TestForward:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        q, k, v = _qkv()
        out = flash_attention(q, k, v, causal=causal, **BLOCKS)
        expected = dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), rtol=2e-5, atol=2e-5
        )

    def test_uneven_blocks(self):
        """bq != bk exercises the off-diagonal causal masking."""
        q, k, v = _qkv(1)
        out = flash_attention(q, k, v, causal=True, block_q=64, block_k=32)
        expected = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), rtol=2e-5, atol=2e-5
        )

    def test_fallback_when_unsupported(self):
        """Tiling that doesn't divide T falls back to dense, not an error."""
        rng = np.random.RandomState(2)
        q = jnp.asarray(rng.randn(1, 100, 2, 16).astype(np.float32))
        assert not supported(q.shape, 64, 64)
        out = flash_attention(q, q, q, causal=True, block_q=64, block_k=64)
        expected = dense_attention(q, q, q, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), rtol=1e-5, atol=1e-5
        )

    def test_fallback_unaligned_sublane(self):
        """T < block clamps blocks to T; a non-sublane-aligned T (e.g. 100)
        must fall back rather than hit the kernel with unaligned tiles."""
        rng = np.random.RandomState(5)
        q = jnp.asarray(rng.randn(1, 100, 2, 64).astype(np.float32))
        # After clamping, block_q = block_k = 100, which divides T but is
        # not a multiple of the f32 sublane granule (8).
        assert not supported(q.shape, 100, 100)
        out = flash_attention(q, q, q, causal=True)
        expected = dense_attention(q, q, q, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), rtol=1e-5, atol=1e-5
        )
        # bf16 needs 16-sublane tiles: an 8-aligned block is f32-only.
        assert supported((1, 104, 2, 64), 8, 8, dtype=jnp.float32)
        assert not supported((1, 104, 2, 64), 8, 8, dtype=jnp.bfloat16)

    def test_cross_attention_runs_kernel(self):
        """Tk != Tq runs the kernel on a rectangular nq×nk grid (round-3:
        previously this was a dense fallback) and matches dense exactly."""
        rng = np.random.RandomState(6)
        q = jnp.asarray(rng.randn(1, 64, 2, 64).astype(np.float32))
        k = jnp.asarray(rng.randn(1, 128, 2, 64).astype(np.float32))
        v = jnp.asarray(rng.randn(1, 128, 2, 64).astype(np.float32))
        assert supported(q.shape, 32, 32, k_shape=k.shape)
        out = flash_attention(q, k, v, causal=False, block_q=32, block_k=32)
        expected = dense_attention(q, k, v, causal=False)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), rtol=1e-5, atol=1e-5
        )


@pytest.mark.slow
class TestWithLse:
    """The (out, lse) kernel entry that cross-chip merges build on."""

    def _dense_ref(self, q, k, v, causal=True):
        scale = q.shape[-1] ** -0.5
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        if causal:
            tq, tk = s.shape[-2:]
            mask = (
                jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :]
            )
            s = jnp.where(mask, s, -1e30)
        lse = jax.nn.logsumexp(s, axis=-1)  # [B,H,T]
        return jnp.transpose(lse, (0, 2, 1))  # [B,T,H]

    @pytest.mark.parametrize("causal", [True, False])
    def test_out_and_lse_match_dense(self, causal):
        q, k, v = _qkv(5)
        out, lse = flash_attention_with_lse(q, k, v, causal=causal, **BLOCKS)
        np.testing.assert_allclose(
            np.asarray(out),
            np.asarray(dense_attention(q, k, v, causal=causal)),
            rtol=2e-5, atol=2e-5,
        )
        np.testing.assert_allclose(
            np.asarray(lse),
            np.asarray(self._dense_ref(q, k, v, causal)),
            rtol=1e-5, atol=1e-5,
        )

    def test_lse_cotangent_flows(self):
        """Gradients of a loss that CONSUMES lse must match the natively
        differentiable dense computation — this is the δ-adjustment path in
        the kernel's custom VJP."""
        q, k, v = _qkv(6)

        def loss_flash(q, k, v):
            out, lse = flash_attention_with_lse(q, k, v, causal=True, **BLOCKS)
            return (out ** 2).sum() + (lse ** 2).sum() * 0.1

        def loss_dense(q, k, v):
            out = dense_attention(q, k, v, causal=True)
            lse = self._dense_ref(q, k, v, True)
            return (out ** 2).sum() + (lse ** 2).sum() * 0.1

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gd):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            )

    def test_fallback_returns_lse_too(self):
        """Shapes the kernel can't tile still honor the (out, lse) contract
        through the dense fallback."""
        rng = np.random.RandomState(11)
        q = jnp.asarray(rng.randn(1, 100, 2, 16).astype(np.float32))
        out, lse = flash_attention_with_lse(q, q, q, causal=True)
        assert out.shape == (1, 100, 2, 16)
        assert lse.shape == (1, 100, 2)
        np.testing.assert_allclose(
            np.asarray(lse),
            np.asarray(self._dense_ref(q, q, q, True)),
            rtol=1e-5, atol=1e-5,
        )


class TestPickBlocks:
    """Block selection: the kernel must degrade block size, not fall back to
    dense, for sequence lengths the default 1024² tiles don't divide."""

    def test_divisor_fallthrough(self):
        # 1536 % 1024 != 0 → halve to 512 (1536 % 512 == 0), both axes.
        assert pick_blocks(1536, 64, jnp.bfloat16) == (512, 512)
        bq, bk = pick_blocks(1536, 64, jnp.bfloat16)
        assert supported((1, 1536, 2, 64), bq, bk, dtype=jnp.bfloat16)

    def test_full_blocks_at_long_seq(self):
        assert pick_blocks(8192, 64, jnp.bfloat16) == (1024, 1024)

    def test_clamped_to_t(self):
        assert pick_blocks(512, 64, jnp.bfloat16) == (512, 512)
        assert pick_blocks(128, 64, jnp.float32) == (128, 128)

    def test_wide_head_clamp(self):
        # D > 128 keeps the f32 score tile + wide blocks inside VMEM.
        assert pick_blocks(4096, 256, jnp.bfloat16) == (512, 512)

    def test_degradation_floor(self):
        """Awkward T (1040 = 16·65) must NOT degrade below 128 into tiny
        MXU-underfilling tiles; the non-dividing 128 makes supported()
        reject → dense fallback, which is faster there."""
        bq, bk = pick_blocks(1040, 64, jnp.bfloat16)
        assert (bq, bk) == (128, 128)
        assert not supported((1, 1040, 2, 64), bq, bk, dtype=jnp.bfloat16)
        # Explicit small blocks are honored, not degraded-to.
        assert pick_blocks(128, 64, jnp.float32, 32, 32) == (32, 32)
        # Non-power-of-two explicit blocks stop AT the floor boundary
        # instead of halving through it (384 → 192, not → 96).
        assert pick_blocks(1056, 64, jnp.float32, 384, 384) == (192, 192)

    def test_odd_t_runs_kernel_via_smaller_blocks(self):
        """T=1536 must run the pallas kernel (via 512² tiles), matching
        dense numerics — previously this shape regressed to dense."""
        # The kernel-actually-runs guard: the picked blocks must tile T
        # (dense-vs-dense would trivially pass the parity check below).
        bq, bk = pick_blocks(1536, 16, jnp.float32)
        assert supported((1, 1536, 2, 16), bq, bk, dtype=jnp.float32)
        rng = np.random.RandomState(7)
        q, k, v = (
            jnp.asarray(rng.randn(1, 1536, 2, 16).astype(np.float32))
            for _ in range(3)
        )
        out = flash_attention(q, k, v, causal=True)
        expected = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), rtol=2e-5, atol=2e-5
        )


class TestBackward:
    def test_grads_match_dense(self):
        q, k, v = _qkv(3)

        def loss_flash(q, k, v):
            return (flash_attention(q, k, v, causal=True, **BLOCKS) ** 2).sum()

        def loss_dense(q, k, v):
            return (dense_attention(q, k, v, causal=True) ** 2).sum()

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gd):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            )

    def test_noncausal_grads(self):
        q, k, v = _qkv(4)
        gf = jax.grad(
            lambda q: (flash_attention(q, k, v, causal=False, **BLOCKS) ** 2).sum()
        )(q)
        gd = jax.grad(
            lambda q: (dense_attention(q, k, v, causal=False) ** 2).sum()
        )(q)
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gd), rtol=1e-4, atol=1e-4
        )


def _dense_masked(q, k, v, keep):
    """Independent dense reference: explicit [B,Tq,Tk] boolean mask, exact
    zero rows where nothing is kept (the kernel's empty-row convention)."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    s = jnp.where(keep[:, None, :, :], s, -1e30)
    m = s.max(axis=-1, keepdims=True)
    p = jnp.where(keep[:, None, :, :], jnp.exp(s - m), 0.0)
    l = p.sum(axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bkhd->bqhd", p / jnp.where(l == 0, 1.0, l), v)
    return out


def _packed_segments(rng, b, t, max_docs=4):
    """[B, T] contiguous-run segment ids, like sequence packing produces."""
    ids = np.zeros((b, t), np.int32)
    for i in range(b):
        cuts = np.sort(rng.choice(np.arange(1, t), size=max_docs - 1, replace=False))
        ids[i] = np.searchsorted(cuts, np.arange(t), side="right")
    return jnp.asarray(ids)


class TestSegments:
    """Packed-sequence (segment-id) masking — round-3 feature. bk must be a
    multiple of 128 (lane tiling of the q-id block), so blocks are 32×128."""

    SEG_BLOCKS = dict(block_q=32, block_k=128)

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_masked_dense(self, causal):
        rng = np.random.RandomState(11)
        q, k, v = _qkv(11)
        seg = _packed_segments(rng, B, T)
        out = flash_attention(
            q, k, v, causal=causal,
            q_segment_ids=seg, kv_segment_ids=seg, **self.SEG_BLOCKS,
        )
        keep = seg[:, :, None] == seg[:, None, :]
        if causal:
            tri = jnp.tril(jnp.ones((T, T), bool))
            keep = keep & tri[None]
        expected = _dense_masked(q, k, v, keep)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), rtol=1e-5, atol=1e-5
        )

    def test_grads_match_masked_dense(self):
        rng = np.random.RandomState(12)
        q, k, v = _qkv(12)
        seg = _packed_segments(rng, B, T)
        keep = (seg[:, :, None] == seg[:, None, :]) & jnp.tril(
            jnp.ones((T, T), bool)
        )[None]

        gf = jax.grad(
            lambda q, k, v: (
                flash_attention(
                    q, k, v, causal=True,
                    q_segment_ids=seg, kv_segment_ids=seg, **self.SEG_BLOCKS,
                ) ** 2
            ).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        gd = jax.grad(
            lambda q, k, v: (_dense_masked(q, k, v, keep) ** 2).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b in zip(gf, gd):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            )

    def test_with_lse_segments(self):
        """The lse entry (ring building block) honors segments too."""
        rng = np.random.RandomState(13)
        q, k, v = _qkv(13)
        seg = _packed_segments(rng, B, T)
        out, lse = flash_attention_with_lse(
            q, k, v, causal=False,
            q_segment_ids=seg, kv_segment_ids=seg, **self.SEG_BLOCKS,
        )
        keep = seg[:, :, None] == seg[:, None, :]
        expected = _dense_masked(q, k, v, keep)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), rtol=1e-5, atol=1e-5
        )
        assert lse.shape == (B, T, H)
        # lse really is log-sum-exp of the kept scores.
        scale = D ** -0.5
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        s = jnp.where(keep[:, None, :, :], s, -jnp.inf)
        ref = jax.nn.logsumexp(s, axis=-1)  # [B,H,T]
        np.testing.assert_allclose(
            np.asarray(jnp.transpose(ref, (0, 2, 1))), np.asarray(lse),
            rtol=1e-5, atol=1e-4,
        )

    def test_empty_rows_zero_not_nan(self):
        """A q row whose segment has no kv tokens (cross-attention against a
        filtered memory): zero output, finite lse, zero grads — never NaN."""
        rng = np.random.RandomState(14)
        q, k, v = _qkv(14)
        q_seg = jnp.asarray(rng.randint(0, 2, (B, T)).astype(np.int32))
        kv_seg = jnp.zeros((B, T), jnp.int32)  # only segment 0 has keys

        def f(q, k, v):
            out = flash_attention(
                q, k, v, causal=False,
                q_segment_ids=q_seg, kv_segment_ids=kv_seg, **self.SEG_BLOCKS,
            )
            return out, (out ** 2).sum()

        out, _ = f(q, k, v)
        rows_empty = np.asarray(q_seg) == 1
        np.testing.assert_array_equal(
            np.asarray(out)[rows_empty], 0.0
        )
        grads = jax.grad(lambda *a: f(*a)[1], argnums=(0, 1, 2))(q, k, v)
        for g in grads:
            assert np.isfinite(np.asarray(g)).all()

    def test_mismatched_segment_args_rejected(self):
        q, k, v = _qkv(15)
        seg = jnp.zeros((B, T), jnp.int32)
        with pytest.raises(ValueError, match="together"):
            flash_attention(q, k, v, q_segment_ids=seg)
        with pytest.raises(ValueError, match="Tq"):
            flash_attention(
                q, k, v, q_segment_ids=seg[:, :64], kv_segment_ids=seg
            )

    def test_unaligned_block_falls_back_dense(self):
        """Segmented with bk not lane-aligned must fall back (still correct)."""
        rng = np.random.RandomState(16)
        q, k, v = _qkv(16)
        seg = _packed_segments(rng, B, T)
        assert not supported(
            q.shape, 32, 32, k_shape=q.shape, segmented=True
        )
        out = flash_attention(
            q, k, v, causal=False, block_q=32, block_k=32,
            q_segment_ids=seg, kv_segment_ids=seg,
        )
        keep = seg[:, :, None] == seg[:, None, :]
        expected = _dense_masked(q, k, v, keep)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), rtol=1e-5, atol=1e-5
        )


@pytest.mark.slow
class TestCrossAttention:
    """Tk != Tq on the kernel's rectangular grid — round-3 feature."""

    def test_causal_offset_matches_dense(self):
        """Causal cross-attention aligns sequence ENDS: query i sees keys
        j <= i + Tk - Tq (the decode/suffix convention)."""
        rng = np.random.RandomState(21)
        tq, tk = 64, 192
        q = jnp.asarray(rng.randn(B, tq, H, D).astype(np.float32))
        k = jnp.asarray(rng.randn(B, tk, H, D).astype(np.float32))
        v = jnp.asarray(rng.randn(B, tk, H, D).astype(np.float32))
        out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
        rows = np.arange(tq)[:, None] + (tk - tq)
        keep = jnp.asarray(
            np.broadcast_to(rows >= np.arange(tk)[None, :], (B, tq, tk))
        )
        expected = _dense_masked(q, k, v, keep)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), rtol=1e-5, atol=1e-5
        )

    def test_cross_grads_match_dense(self):
        rng = np.random.RandomState(22)
        tq, tk = 96, 32
        q = jnp.asarray(rng.randn(B, tq, H, D).astype(np.float32))
        k = jnp.asarray(rng.randn(B, tk, H, D).astype(np.float32))
        v = jnp.asarray(rng.randn(B, tk, H, D).astype(np.float32))
        keep = jnp.ones((B, tq, tk), bool)

        gf = jax.grad(
            lambda q, k, v: (
                flash_attention(
                    q, k, v, causal=False, block_q=32, block_k=32
                ) ** 2
            ).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        gd = jax.grad(
            lambda q, k, v: (_dense_masked(q, k, v, keep) ** 2).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b in zip(gf, gd):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            )

    def test_cross_with_segments(self):
        """Cross-attention + segment filtering compose (retrieval pattern:
        each query row attends only its document's memory slice)."""
        rng = np.random.RandomState(23)
        tq, tk = 64, 128
        q = jnp.asarray(rng.randn(B, tq, H, D).astype(np.float32))
        k = jnp.asarray(rng.randn(B, tk, H, D).astype(np.float32))
        v = jnp.asarray(rng.randn(B, tk, H, D).astype(np.float32))
        q_seg = jnp.asarray(rng.randint(0, 3, (B, tq)).astype(np.int32))
        kv_seg = jnp.asarray(rng.randint(0, 3, (B, tk)).astype(np.int32))
        out = flash_attention(
            q, k, v, causal=False, block_q=32, block_k=128,
            q_segment_ids=q_seg, kv_segment_ids=kv_seg,
        )
        keep = q_seg[:, :, None] == kv_seg[:, None, :]
        expected = _dense_masked(q, k, v, keep)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), rtol=1e-5, atol=1e-5
        )

    def test_causal_tk_smaller_empty_head_rows(self):
        """Tk < Tq causal: the first Tq-Tk rows see no keys at all — they
        must come out zero with finite grads (empty-row convention)."""
        rng = np.random.RandomState(24)
        tq, tk = 96, 32
        q = jnp.asarray(rng.randn(B, tq, H, D).astype(np.float32))
        k = jnp.asarray(rng.randn(B, tk, H, D).astype(np.float32))
        v = jnp.asarray(rng.randn(B, tk, H, D).astype(np.float32))
        out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
        assert np.isfinite(np.asarray(out)).all()
        np.testing.assert_array_equal(np.asarray(out)[:, : tq - tk], 0.0)
        g = jax.grad(
            lambda q: (
                flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
                ** 2
            ).sum()
        )(q)
        assert np.isfinite(np.asarray(g)).all()


@pytest.mark.slow
class TestWindow:
    """Sliding-window (local) attention: the band mask row − col < window
    plus block-level skip of out-of-band tiles. Reference = dense_attention
    with the same window."""

    @pytest.mark.parametrize("window", [1, 17, 32, 100, T, 3 * T])
    def test_matches_dense(self, window):
        q, k, v = _qkv(11)
        out = flash_attention(q, k, v, causal=True, window=window, **BLOCKS)
        expected = dense_attention(q, k, v, causal=True, window=window)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), rtol=2e-5, atol=2e-5
        )

    def test_grads_match_dense(self):
        q, k, v = _qkv(12)
        window = 40  # not a block multiple: exercises partial band tiles

        def loss_flash(q, k, v):
            return (
                flash_attention(q, k, v, causal=True, window=window, **BLOCKS)
                ** 2
            ).sum()

        def loss_dense(q, k, v):
            return (
                dense_attention(q, k, v, causal=True, window=window) ** 2
            ).sum()

        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            )

    def test_with_lse_and_q_offset(self):
        """The ring building block: a q block at global offset attends a
        past K/V block under the window band; (out, lse) must match the
        dense fallback's same-offset math, gradients included (the offset
        path is what window-aware ring hops run)."""
        from horovod_tpu.ops.flash_attention import _dense_with_lse

        rng = np.random.RandomState(13)
        tq = tk = 64
        q, k, v = (
            jnp.asarray(rng.randn(B, t, H, D).astype(np.float32))
            for t in (tq, tk, tk)
        )
        window, offset = 80, 64  # band straddles the block boundary
        out, lse = flash_attention_with_lse(
            q, k, v, causal=True, window=window, q_offset=offset, **BLOCKS
        )
        ref_out, ref_lse = _dense_with_lse(
            q, k, v, causal=True, window=window, q_offset=offset
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref_out), rtol=2e-5, atol=2e-5
        )
        np.testing.assert_allclose(
            np.asarray(lse), np.asarray(ref_lse), rtol=1e-5, atol=1e-5
        )

        def loss_k(fn):
            def f(q, k, v):
                o, s = fn(q, k, v)
                return (o.astype(jnp.float32) ** 2).sum() + (
                    jnp.where(s > -1e29, s, 0.0) ** 2
                ).sum()

            return jax.grad(f, argnums=(0, 1, 2))

        g1 = loss_k(
            lambda q, k, v: flash_attention_with_lse(
                q, k, v, causal=True, window=window, q_offset=offset, **BLOCKS
            )
        )(q, k, v)
        g2 = loss_k(
            lambda q, k, v: _dense_with_lse(
                q, k, v, causal=True, window=window, q_offset=offset
            )
        )(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            )

    def test_composes_with_segments(self):
        """Packed documents AND a window: attention restricted to the
        intersection (same doc, within the band)."""
        rng = np.random.RandomState(14)
        q, k, v = _qkv(14)
        ids = jnp.asarray(
            np.sort(rng.randint(0, 3, size=(B, T)), axis=1), jnp.int32
        )
        out = flash_attention(
            q, k, v, causal=True, window=24,
            q_segment_ids=ids, kv_segment_ids=ids, **BLOCKS
        )
        expected = dense_attention(
            q, k, v, causal=True, window=24,
            q_segment_ids=ids, kv_segment_ids=ids,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), rtol=2e-5, atol=2e-5
        )

    def test_fallback_path_applies_window(self):
        """Tiling that can't run the kernel must still honor the window in
        the dense fallback."""
        rng = np.random.RandomState(15)
        q = jnp.asarray(rng.randn(1, 100, 2, 16).astype(np.float32))
        assert not supported(q.shape, 64, 64)
        out = flash_attention(
            q, q, q, causal=True, window=30, block_q=64, block_k=64
        )
        expected = dense_attention(q, q, q, causal=True, window=30)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), rtol=1e-5, atol=1e-5
        )

    def test_window_requires_causal(self):
        q, k, v = _qkv()
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, k, v, causal=False, window=8)
        with pytest.raises(ValueError, match="positive"):
            flash_attention(q, k, v, causal=True, window=0)
        with pytest.raises(ValueError, match="causal"):
            dense_attention(q, k, v, causal=False, window=8)


class TestSinks:
    """Global+local (window + pinned sinks) through the banded grid: one
    extra sink tile per q block, disjoint masks, sink-only dK/dV pass."""

    @pytest.mark.parametrize("window,sinks", [(32, 8), (24, 24), (100, 17)])
    def test_matches_dense(self, window, sinks):
        q, k, v = _qkv(31)
        out = flash_attention(
            q, k, v, causal=True, window=window, sinks=sinks, **BLOCKS
        )
        expected = dense_attention(
            q, k, v, causal=True, window=window, sinks=sinks
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), rtol=2e-5, atol=2e-5
        )

    def test_grads_match_dense(self):
        q, k, v = _qkv(32)
        window, sinks = 40, 12

        def loss(fn):
            return jax.grad(
                lambda q, k, v: (fn(q, k, v) ** 2).sum(), argnums=(0, 1, 2)
            )

        g1 = loss(lambda q, k, v: flash_attention(
            q, k, v, causal=True, window=window, sinks=sinks, **BLOCKS
        ))(q, k, v)
        g2 = loss(lambda q, k, v: dense_attention(
            q, k, v, causal=True, window=window, sinks=sinks
        ))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            )

    def test_composes_with_segments(self):
        rng = np.random.RandomState(33)
        q, k, v = _qkv(33)
        ids = jnp.asarray(
            np.sort(rng.randint(0, 3, size=(B, T)), axis=1), jnp.int32
        )
        out = flash_attention(
            q, k, v, causal=True, window=24, sinks=8,
            q_segment_ids=ids, kv_segment_ids=ids, **BLOCKS
        )
        expected = dense_attention(
            q, k, v, causal=True, window=24, sinks=8,
            q_segment_ids=ids, kv_segment_ids=ids,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), rtol=2e-5, atol=2e-5
        )

    def test_sinks_without_window_is_plain_causal(self):
        q, k, v = _qkv(34)
        out = flash_attention(q, k, v, causal=True, sinks=16, **BLOCKS)
        expected = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), rtol=2e-5, atol=2e-5
        )

    def test_oversized_sinks_fall_back_dense(self):
        """sinks > block_k can't ride the single pinned tile — must still
        produce the right answer via the dense fallback."""
        q, k, v = _qkv(35)
        out = flash_attention(
            q, k, v, causal=True, window=32, sinks=100,
            block_q=32, block_k=32,
        )
        expected = dense_attention(
            q, k, v, causal=True, window=32, sinks=100
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), rtol=2e-5, atol=2e-5
        )
