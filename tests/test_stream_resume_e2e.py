"""Durable stream cursors, end to end (the ISSUE 8 acceptance runs).

Tier-1 lane (bounded, single-process):

* the transient-read chaos smoke — a real 2-epoch file-backed fit with
  ``HVT_DATA_FAULT_READS`` injecting transient OSErrors into the shard
  mmap path: the bounded retry (`HVT_DATA_RETRIES` ×
  `HVT_DATA_BACKOFF_S`) absorbs them and training completes; an
  exhausted budget fails FAST with the actionable checkpoint-fallback
  message.

Slow lane (subprocess chaos):

* streamed ``x=/y=`` fit SIGKILLed MID-epoch 2 by a step-filtered fault
  and relaunched with the identical command — python AND native loader
  engines: the relaunch resumes from the step-carrying manifest
  (`restore_latest_and_broadcast(with_step=True)`) and the FINAL
  checkpoint is byte-identical to an uninterrupted control's. Bitwise
  final state is strictly stronger than batch equality: any replayed,
  skipped, or re-anchored batch — including in the epochs that PREDATE
  the resume call, the PR 5 gap — changes a gradient and breaks it.
* the packed-LM long-horizon soak: `examples/packed_lm_pretrain.py`
  (file-backed corpus, `FileDataset.reshard` striping) killed mid-epoch
  and relaunched, with the ``DIGEST_LOG`` audit stream asserting
  PER-BATCH byte identity against an uninterrupted control across
  multiple epoch boundaries; plus the elastic soak job
  (`launch/jobs/packed-lm-soak-2proc.yaml`) — 3 procs, a mid-run clean
  leave (shrink) with a replacement growing back, injected transient
  read faults, journal + loss gates.
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def fresh_fault_injection():
    """Re-arm the stream-layer fault injector around a test and disarm
    after (the budget is module-global, armed lazily from the env)."""
    from horovod_tpu.data import stream as stream_lib

    stream_lib.reset_fault_injection()
    yield stream_lib
    stream_lib.reset_fault_injection()


class TestTransientReadRetrySmoke:
    """Tier-1: the injected-transient-fault retry path under a REAL
    file-backed fit (single process, 2 epochs, bounded)."""

    def _store(self, tmp_path):
        from horovod_tpu.data.filedataset import write_shards

        rng = np.random.RandomState(0)
        x = rng.rand(128, 8).astype(np.float32)
        y = (np.arange(128) % 4).astype(np.int64)
        return write_shards(
            {"x": x, "y": y}, str(tmp_path / "ds"), shard_size=32
        )

    def test_fit_survives_transient_read_faults(
        self, tmp_path, monkeypatch, fresh_fault_injection
    ):
        import flax.linen as nn
        import optax

        import horovod_tpu as hvt
        from horovod_tpu.data.filedataset import FileDataset

        d = self._store(tmp_path)
        monkeypatch.setenv("HVT_NO_NATIVE", "1")
        monkeypatch.setenv("HVT_DATA_RETRIES", "3")
        monkeypatch.setenv("HVT_DATA_BACKOFF_S", "0.001")
        monkeypatch.setenv("HVT_DATA_FAULT_READS", "2")
        fresh_fault_injection.reset_fault_injection()
        before = fresh_fault_injection.RETRY_STATS["retried"]

        class Tiny(nn.Module):
            @nn.compact
            def __call__(self, x, train=False):
                return nn.Dense(4)(x)

        trainer = hvt.Trainer(
            Tiny(), hvt.DistributedOptimizer(optax.adam(1e-2)), seed=1
        )
        stream = FileDataset(d).pairs_stream("x", "y", 8, seed=9)
        hist = trainer.fit(
            stream, steps_per_epoch=3, epochs=2, verbose=0
        )
        assert len(hist) == 2
        # Both injected faults were absorbed by retries, not surfaced.
        assert (
            fresh_fault_injection.RETRY_STATS["retried"] - before >= 2
        )

    def test_exhausted_budget_fails_with_checkpoint_escalation(
        self, tmp_path, monkeypatch, fresh_fault_injection
    ):
        from horovod_tpu.data.filedataset import FileDataset

        d = self._store(tmp_path)
        monkeypatch.setenv("HVT_DATA_RETRIES", "1")
        monkeypatch.setenv("HVT_DATA_BACKOFF_S", "0.001")
        monkeypatch.setenv("HVT_DATA_FAULT_READS", "10")
        fresh_fault_injection.reset_fault_injection()
        with pytest.raises(RuntimeError) as e:
            FileDataset(d)
        # Actionable: names the knob and the checkpoint-restart fallback.
        assert "HVT_DATA_RETRIES" in str(e.value)
        assert "checkpoint" in str(e.value)

    def test_non_retriable_errors_propagate_immediately(
        self, tmp_path, monkeypatch, fresh_fault_injection
    ):
        from horovod_tpu.data import stream as stream_lib

        monkeypatch.setenv("HVT_DATA_RETRIES", "5")
        calls = {"n": 0}

        def bad():
            calls["n"] += 1
            raise ValueError("corrupt index")

        with pytest.raises(ValueError, match="corrupt index"):
            stream_lib.read_with_retries(bad, "x")
        assert calls["n"] == 1  # no retry spent on a non-transient error


# --- slow: SIGKILL mid-epoch + relaunch, streamed x=/y= engines ------------

STEPS, EPOCHS = 4, 5

CHILD = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    import numpy as np
    import optax
    import flax.linen as nn
    import horovod_tpu as hvt
    from horovod_tpu import checkpoint

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(4)(x)

    hvt.init()
    model_dir = os.environ["MODEL_DIR"]
    rng = np.random.RandomState(0)
    x = rng.rand(256, 8).astype("float32")
    y = (np.arange(256) % 4).astype("int64")
    trainer = hvt.Trainer(
        Tiny(), hvt.DistributedOptimizer(optax.adam(1e-2)), seed=7
    )
    trainer.build(x[:8], y[:8])
    trainer.state, e0, s0 = checkpoint.restore_latest_and_broadcast(
        model_dir, trainer.state, mesh=trainer.mesh, with_step=True
    )
    print(f"RESUME epoch={{e0}} step={{s0}}", flush=True)
    trainer.fit(
        x=x, y=y, batch_size=4, epochs={epochs}, initial_epoch=e0,
        initial_step=s0, steps_per_epoch={steps},
        callbacks=[hvt.callbacks.ModelCheckpoint(
            os.path.join(model_dir, "checkpoint-{{epoch}}.msgpack"),
            save_every_steps=1,
        )],
        verbose=0,
    )
    print("CHILD DONE", flush=True)
""").format(repo=REPO, steps=STEPS, epochs=EPOCHS)


def _child_env(model_dir, *, native: bool, fault: str | None = None):
    env = {
        **os.environ,
        "HVT_PLATFORM": "cpu",
        "HVT_NUM_CPU_DEVICES": "2",
        "MODEL_DIR": str(model_dir),
        "HVT_NO_NATIVE": "" if native else "1",
        # SIGKILLed children must not share the suite's persistent XLA
        # cache (torn writes poison later runs — conftest caveat).
        "JAX_ENABLE_COMPILATION_CACHE": "0",
        "JAX_COMPILATION_CACHE_DIR": "",
    }
    env.pop("HVT_FAULT", None)
    if fault:
        env["HVT_FAULT"] = fault
    return env


def _run_child(tmp, name, *, native, fault=None, timeout=420):
    script = tmp / "child.py"
    script.write_text(CHILD)
    return subprocess.run(
        [sys.executable, str(script)],
        env=_child_env(tmp / name, native=native, fault=fault),
        capture_output=True, text=True, timeout=timeout,
    )


@pytest.mark.slow
@pytest.mark.parametrize("native", [False, True],
                         ids=["python-engine", "native-engine"])
def test_streamed_sigkill_midepoch_resume_bitwise(tmp_path, native):
    """The acceptance run: a streamed fit killed MID-epoch 2 (epochs 0-1
    already consumed — the re-anchoring case) and relaunched with the
    identical command ends bitwise equal to the uninterrupted control,
    on both feeding engines."""
    if native:
        from horovod_tpu.data import native_loader

        if not native_loader.available():
            pytest.skip("native loader unavailable")
    (tmp_path / "ctrl").mkdir()
    (tmp_path / "fault").mkdir()

    ctrl = _run_child(tmp_path, "ctrl", native=native)
    assert ctrl.returncode == 0, ctrl.stdout + ctrl.stderr
    assert "CHILD DONE" in ctrl.stdout

    # Step-filtered kill at optimizer step 2 of epoch 2 (0-based): the
    # PR 5 fault plan is one-shot for step plans — a run resumed at/past
    # the step does not re-fire.
    first = _run_child(tmp_path, "fault", native=native,
                       fault="0:2.2:kill")
    assert first.returncode != 0  # SIGKILL mid-run
    relaunches = 0
    while True:
        res = _run_child(tmp_path, "fault", native=native,
                         fault="0:2.2:kill")
        relaunches += 1
        if res.returncode == 0:
            break
        assert relaunches < 4, res.stdout + res.stderr
    assert "CHILD DONE" in res.stdout
    # It genuinely resumed (not restarted from scratch)...
    m = [ln for ln in res.stdout.splitlines() if ln.startswith("RESUME")]
    assert m and "epoch=0 step=0" not in m[0], res.stdout
    # ...and the final checkpoints are byte-identical: any skew in the
    # resumed stream — a replayed batch, a re-anchored earlier epoch —
    # would change a gradient and the serialized state with it.
    final = f"checkpoint-{EPOCHS}.msgpack"
    a = (tmp_path / "ctrl" / final).read_bytes()
    b = (tmp_path / "fault" / final).read_bytes()
    assert a == b


@pytest.mark.slow
@pytest.mark.parametrize("fault", ["0:2.2:kill", "0:3:corrupt"],
                         ids=["sigkill-midepoch", "corrupt-checkpoint"])
def test_packed_lm_kill_resume_digest_identity(tmp_path, fault):
    """The file-backed packed-LM soak, single-process form: the example
    is SIGKILLed mid-epoch 2 (or has its newest checkpoint CORRUPTED
    then killed at epoch 3 — the resume then falls back to the previous
    complete checkpoint and legitimately REPLAYS batches) and
    relaunched; the DIGEST_LOG audit stream must show PER-BATCH byte
    identity with the uninterrupted control on every (epoch, step) —
    across multiple epoch boundaries, with any replayed batch carrying
    the SAME bytes."""
    argv = [sys.executable,
            os.path.join(REPO, "examples", "packed_lm_pretrain.py")]

    def env(root, fault=None):
        e = {
            **os.environ,
            "HVT_PLATFORM": "cpu",
            "HVT_NUM_CPU_DEVICES": "1",
            "PS_MODEL_PATH": str(root),
            "DRIVE_STEPS": "4", "DRIVE_EPOCHS": "5", "DOCS": "150",
            "HVT_SAVE_EVERY_STEPS": "1",
            "DIGEST_LOG": str(root / "digests"),
            "JAX_ENABLE_COMPILATION_CACHE": "0",
            "JAX_COMPILATION_CACHE_DIR": "",
        }
        e.pop("HVT_FAULT", None)
        e.pop("HVT_FAULT_STAMP", None)
        if fault:
            e["HVT_FAULT"] = fault
            if ":corrupt" in fault:
                # Epoch-filtered plans need the one-shot stamp (step
                # plans are stamp-free — the PR 5 contract).
                e["HVT_FAULT_STAMP"] = str(root / "fault-stamp")
        return e

    (tmp_path / "ctrl").mkdir()
    (tmp_path / "fault").mkdir()
    ctrl = subprocess.run(argv, env=env(tmp_path / "ctrl"),
                          capture_output=True, text=True, timeout=420)
    assert ctrl.returncode == 0, ctrl.stdout + ctrl.stderr

    first = subprocess.run(argv, env=env(tmp_path / "fault", fault),
                           capture_output=True, text=True, timeout=420)
    assert first.returncode != 0
    for attempt in range(4):
        res = subprocess.run(argv, env=env(tmp_path / "fault", fault),
                             capture_output=True, text=True, timeout=420)
        if res.returncode == 0:
            break
    assert res.returncode == 0, res.stdout + res.stderr

    def digests(root):
        out = {}
        with open(root / "digests.rank0") as f:
            for line in f:
                rec = json.loads(line)
                key = (rec["epoch"], rec["step"])
                # A key logged twice (a consumed-but-unsaved batch
                # replayed after the kill) must carry the SAME bytes.
                if key in out:
                    assert out[key] == rec["sha256"], (
                        f"replayed batch {key} differs"
                    )
                out[key] = rec["sha256"]
        return out

    want = digests(tmp_path / "ctrl")
    got = digests(tmp_path / "fault")
    assert set(want) == set(got)
    diff = [k for k in want if want[k] != got[k]]
    assert not diff, f"byte-divergent batches at {sorted(diff)[:5]}"


@pytest.mark.slow
def test_packed_lm_soak_job():
    """The elastic chaos soak, in-spec: 3 procs, a clean mid-run leave
    (3→2 shrink, replacement grows back), injected transient read
    faults, journal + loss gates — the packed-lm-soak-2proc.yaml
    contract, asserted by the job runner's own gate evaluation."""
    import shutil

    shutil.rmtree("/tmp/hvt-packed-lm-soak", ignore_errors=True)
    spec = os.path.join(
        REPO, "horovod_tpu", "launch", "jobs", "packed-lm-soak-2proc.yaml"
    )
    res = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.launch", "job", spec],
        env={**os.environ,
             "JAX_ENABLE_COMPILATION_CACHE": "0",
             "JAX_COMPILATION_CACHE_DIR": ""},
        capture_output=True, text=True, timeout=900,
    )
    assert res.returncode == 0, res.stdout[-4000:] + res.stderr[-4000:]
