"""Restart supervisor (launch/supervisor.py): classification, progress-aware
budget, hang detection, restart journal — plus the HVT_FAULT harness units
and the tier-1 supervised-trainer smoke test (one injected exit1 → exactly
one recorded restart)."""

import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

from horovod_tpu.launch import ci_gate, launcher, supervisor
from horovod_tpu.launch.supervisor import RestartPolicy

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

NO_SLEEP = lambda s: None  # noqa: E731 — backoff without wall-clock


def _script(tmp_path, body, name="child.py"):
    path = tmp_path / name
    path.write_text(textwrap.dedent(body))
    return [sys.executable, str(path)]


def _start(argv, env=None):
    return lambda: launcher.start_local(1, argv, env=env, tag_output=False)


def _records(log_path):
    with open(log_path) as f:
        return [json.loads(line) for line in f if line.strip()]


class TestClassification:
    def test_exit_codes(self):
        assert supervisor.classify(1) == "crash"
        assert supervisor.classify(7) == "crash"
        assert supervisor.classify(-9) == "oom-kill"     # SIGKILL death
        assert supervisor.classify(137) == "oom-kill"    # 128+SIGKILL
        assert supervisor.classify(143) == "preemption"  # 128+SIGTERM
        assert supervisor.classify(-15) == "preemption"  # raw SIGTERM
        assert supervisor.classify(0, hang=True) == "hang"
        assert supervisor.classify(1, hang=True) == "hang"

    def test_shell_code_normalization(self):
        assert supervisor.shell_code(7) == 7      # original code preserved
        assert supervisor.shell_code(143) == 143
        assert supervisor.shell_code(-9) == 137   # 128+SIGKILL
        assert supervisor.shell_code(-15) == 143
        assert supervisor.shell_code(0) == 0


class TestSupervise:
    def test_success_needs_no_restart(self, tmp_path):
        log = tmp_path / "restarts.jsonl"
        code = supervisor.supervise(
            _start(_script(tmp_path, "raise SystemExit(0)")),
            RestartPolicy(max_restarts=3),
            log_path=str(log), sleep=NO_SLEEP,
        )
        assert code == 0
        # The journal EXISTS (so a count gate can tell 'ran clean' from
        # 'never ran') but holds no restart records.
        assert log.exists()
        assert _records(log) == []

    def test_crash_loop_exhausts_budget_with_original_code(self, tmp_path):
        """Acceptance: a deterministic crash loop (failure every launch, no
        progress) burns max_restarts and exits with the ORIGINAL code."""
        log = tmp_path / "restarts.jsonl"
        code = supervisor.supervise(
            _start(_script(tmp_path, "raise SystemExit(7)")),
            RestartPolicy(max_restarts=2, backoff=0.0),
            model_dir=str(tmp_path / "models"),
            log_path=str(log), sleep=NO_SLEEP,
        )
        assert code == 7
        records = _records(log)
        restarts = [r for r in records if r["name"] == "restarts"]
        assert len(restarts) == 2  # budget fully used, then give up
        assert [r["value"] for r in restarts] == [1, 2]
        assert all(r["kind"] == "crash" and r["exit_code"] == 7
                   for r in restarts)
        assert records[-1]["name"] == "supervisor_gave_up"
        assert records[-1]["exit_code"] == 7

    def test_progress_spares_the_budget(self, tmp_path):
        """A launch that wrote a NEW checkpoint does not decrement the
        budget: transient faults restart past max_restarts, as long as
        each incarnation gets further."""
        model_dir = tmp_path / "models"
        model_dir.mkdir()
        log = tmp_path / "restarts.jsonl"
        # Each launch writes checkpoint-<n> then dies, until n == 4.
        argv = _script(tmp_path, f"""
            import os, sys, time
            md = {str(model_dir)!r}
            n = len([f for f in os.listdir(md) if f.startswith('checkpoint')])
            if n >= 4:
                sys.exit(0)
            open(os.path.join(md, f'checkpoint-{{n + 1}}.msgpack'), 'w').close()
            sys.exit(1)
        """)
        code = supervisor.supervise(
            _start(argv), RestartPolicy(max_restarts=1, backoff=0.0),
            model_dir=str(model_dir), log_path=str(log), sleep=NO_SLEEP,
        )
        # 4 failing launches survived a budget of 1 because each progressed.
        assert code == 0
        restarts = [r for r in _records(log) if r["name"] == "restarts"]
        assert len(restarts) == 4
        assert all(r["progressed"] for r in restarts)

    def test_checkpoint_mtime_counts_as_progress(self, tmp_path):
        """Overwriting the same checkpoint path (deeper epoch after resume
        overwrote nothing new by name) still reads as progress via mtime."""
        model_dir = tmp_path / "models"
        model_dir.mkdir()
        ckpt = model_dir / "checkpoint-1.msgpack"
        ckpt.write_bytes(b"a")
        before = supervisor.newest_checkpoint_marker(str(model_dir))
        os.utime(ckpt, (time.time() + 5, time.time() + 5))
        after = supervisor.newest_checkpoint_marker(str(model_dir))
        assert before != after

    def test_preemption_classified(self, tmp_path):
        stamp = tmp_path / "fired"
        log = tmp_path / "restarts.jsonl"
        argv = _script(tmp_path, f"""
            import os, sys
            if os.path.exists({str(stamp)!r}):
                sys.exit(0)
            open({str(stamp)!r}, 'w').close()
            sys.exit(143)  # the PreemptionCheckpointCallback convention
        """)
        code = supervisor.supervise(
            _start(argv), RestartPolicy(max_restarts=2, backoff=0.0),
            log_path=str(log), sleep=NO_SLEEP,
        )
        assert code == 0
        restarts = [r for r in _records(log) if r["name"] == "restarts"]
        assert len(restarts) == 1
        assert restarts[0]["kind"] == "preemption"

    def test_backoff_grows_and_resets_on_progress(self, tmp_path):
        sleeps = []
        model_dir = tmp_path / "models"
        model_dir.mkdir()
        # Fail 3x with no progress, then write a checkpoint + fail, then ok.
        argv = _script(tmp_path, f"""
            import os, sys
            md = {str(model_dir)!r}
            c = os.path.join(md, 'count')
            n = int(open(c).read()) if os.path.exists(c) else 0
            open(c, 'w').write(str(n + 1))
            if n < 3:
                sys.exit(1)
            if n == 3:
                open(os.path.join(md, 'checkpoint-1.msgpack'), 'w').close()
                sys.exit(1)
            sys.exit(0)
        """)
        code = supervisor.supervise(
            _start(argv),
            RestartPolicy(max_restarts=5, backoff=1.0, backoff_factor=2.0),
            model_dir=str(model_dir), sleep=sleeps.append,
        )
        assert code == 0
        # Exponential while stuck (1, 2, 4), back to base after progress (1).
        assert sleeps == [1.0, 2.0, 4.0, 1.0]

    def test_hang_detected_killed_and_restarted(self, tmp_path):
        """A fleet that beats once then wedges: the supervisor must see the
        stale heartbeat, kill the fleet, journal a 'hang', and relaunch."""
        stamp = tmp_path / "fired"
        hb_dir = tmp_path / "hb"
        log = tmp_path / "restarts.jsonl"
        argv = _script(tmp_path, f"""
            import os, sys, time
            if os.path.exists({str(stamp)!r}):
                sys.exit(0)
            open({str(stamp)!r}, 'w').close()
            hb = os.environ['HVT_HEARTBEAT_DIR']
            os.makedirs(hb, exist_ok=True)
            open(os.path.join(hb, 'rank-0'), 'w').close()
            time.sleep(300)  # wedged: alive, no exit code, no beats
        """)
        env = {"HVT_HEARTBEAT_DIR": str(hb_dir)}
        code = supervisor.supervise(
            _start(argv, env=env),
            RestartPolicy(max_restarts=2, backoff=0.0,
                          heartbeat_timeout=0.5, grace_seconds=2.0),
            heartbeat_dir=str(hb_dir), log_path=str(log), sleep=NO_SLEEP,
        )
        assert code == 0
        restarts = [r for r in _records(log) if r["name"] == "restarts"]
        assert len(restarts) == 1
        assert restarts[0]["kind"] == "hang"

    def test_never_beating_fleet_killed_after_startup_timeout(self, tmp_path):
        """A fleet wedged BEFORE its first beat (stuck distributed init)
        writes no exit code and no rank files — the startup timeout must
        bound it, or supervise() polls forever."""
        stamp = tmp_path / "fired"
        hb_dir = tmp_path / "hb"
        log = tmp_path / "restarts.jsonl"
        argv = _script(tmp_path, f"""
            import os, sys, time
            if os.path.exists({str(stamp)!r}):
                sys.exit(0)
            open({str(stamp)!r}, 'w').close()
            time.sleep(300)  # wedged pre-fit: never beats
        """)
        code = supervisor.supervise(
            _start(argv),
            RestartPolicy(max_restarts=2, backoff=0.0,
                          heartbeat_timeout=5.0, startup_timeout=0.6,
                          grace_seconds=2.0),
            heartbeat_dir=str(hb_dir), log_path=str(log), sleep=NO_SLEEP,
        )
        assert code == 0
        restarts = [r for r in _records(log) if r["name"] == "restarts"]
        assert len(restarts) == 1
        assert restarts[0]["kind"] == "hang"

    def test_stale_beats_cleared_between_launches(self, tmp_path):
        """Leftover rank files from the killed attempt must not instantly
        re-kill the next one: a launch that writes NO beats (files cleared)
        and exits 0 must succeed even with an aggressive timeout."""
        hb_dir = tmp_path / "hb"
        hb_dir.mkdir()
        old = hb_dir / "rank-0"
        old.write_text("")
        os.utime(old, (1, 1))  # ancient — stale by any timeout
        code = supervisor.supervise(
            _start(_script(tmp_path, "import time; time.sleep(1)")),
            RestartPolicy(max_restarts=0, heartbeat_timeout=0.3),
            heartbeat_dir=str(hb_dir), sleep=NO_SLEEP,
        )
        assert code == 0

    def test_staleness_is_clock_skew_immune(self, tmp_path):
        """The abort hook judges liveness by mtime CHANGE over the
        supervisor's monotonic clock — a rank host whose clock trails the
        launcher's by more than the timeout (beats land with 'ancient'
        mtimes) must not read as hung while it keeps beating."""
        hb = tmp_path / "hb"
        hb.mkdir()
        beat = hb / "rank-0"
        beat.write_text("")
        os.utime(beat, (1, 1))  # skewed far into the past
        # Wall-clock comparison misjudges this beat as ancient...
        assert supervisor.heartbeats_stale(str(hb), 5.0)
        # ...but the abort hook sees a CHANGING mtime and stays calm,
        # even as monotonic time advances past the 1s timeout.
        abort = supervisor._throttled_staleness_check(
            str(hb), timeout=1.0, startup_timeout=60.0)
        t_end = time.monotonic() + 1.6
        tick = 2
        while time.monotonic() < t_end:
            assert not abort(), "skewed-but-live beats judged hung"
            os.utime(beat, (tick, tick))  # keep beating, still 'ancient'
            tick += 1
            time.sleep(0.3)

    def test_abort_hook_detects_stopped_beats(self, tmp_path):
        hb = tmp_path / "hb"
        hb.mkdir()
        (hb / "rank-0").write_text("")
        abort = supervisor._throttled_staleness_check(
            str(hb), timeout=0.5, startup_timeout=60.0)
        assert not abort()  # observed once — fresh
        deadline = time.monotonic() + 10
        while not abort():
            assert time.monotonic() < deadline, "never detected the stop"
            time.sleep(0.1)

    def test_heartbeats_stale_semantics(self, tmp_path):
        hb = tmp_path / "hb"
        # No dir / no files: never stale (fleet may still be compiling).
        assert not supervisor.heartbeats_stale(str(hb), 0.1)
        hb.mkdir()
        assert not supervisor.heartbeats_stale(str(hb), 0.1)
        beat = hb / "rank-0"
        beat.write_text("")
        assert not supervisor.heartbeats_stale(str(hb), 60.0)
        # Newest beat rules: one fresh rank keeps the fleet alive.
        old = hb / "rank-1"
        old.write_text("")
        os.utime(old, (1, 1))
        assert not supervisor.heartbeats_stale(str(hb), 60.0)
        os.utime(beat, (1, 1))
        assert supervisor.heartbeats_stale(str(hb), 60.0)


class TestRestartPolicyMapping:
    def test_partial_mapping_and_none_skip(self):
        p = RestartPolicy.from_mapping(
            {"max_restarts": "5", "backoff": None, "heartbeat_timeout": 30}
        )
        assert p.max_restarts == 5
        assert p.backoff == RestartPolicy().backoff  # None = keep default
        assert p.heartbeat_timeout == 30.0

    def test_unknown_key_rejected(self):
        with pytest.raises(ValueError, match="unknown restart policy"):
            RestartPolicy.from_mapping({"max_restart": 3})  # typo'd key


class TestGateOnJournal:
    def test_missing_journal_fails_count_gate(self, tmp_path):
        """A journal that was never created (supervisor never ran) must
        fail even restarts=0..0 — only an EXISTING empty journal passes."""
        missing = tmp_path / "nope" / "restarts.jsonl"
        ok, _ = ci_gate.check_metrics(
            str(missing), "restarts", (0.0, 0.0), how="count")
        assert not ok
        existing = tmp_path / "restarts.jsonl"
        existing.write_text("")
        ok, value = ci_gate.check_metrics(
            str(existing), "restarts", (0.0, 0.0), how="count")
        assert ok and value == 0.0


class TestRestartLogRotation:
    """Journal rotation for long-lived fleets: past the size/line bound the
    live file rotates to ``<path>.1`` (one predecessor kept), and every
    reader — fleet_status, the CI gate's count aggregate — reads across
    the rotation boundary."""

    def test_rotates_at_max_lines_keeping_one_predecessor(self, tmp_path):
        log = supervisor.RestartLog(str(tmp_path / "j.jsonl"), max_lines=3)
        for i in range(8):
            log.write("restarts", float(i + 1), attempt=i + 1)
        live = _records(log.path)
        prev = _records(log.path + ".1")
        # Exactly two windows on disk, nothing lost in the newest two.
        assert len(prev) == 3
        assert [r["value"] for r in prev] + [r["value"] for r in live] == [
            4.0, 5.0, 6.0, 7.0, 8.0
        ]

    def test_rotates_at_max_bytes(self, tmp_path):
        log = supervisor.RestartLog(
            str(tmp_path / "j.jsonl"), max_lines=0, max_bytes=1
        )
        log.write("restarts", 1.0)
        log.write("restarts", 2.0)
        assert os.path.exists(log.path + ".1")

    def test_env_zero_disables_rotation(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HVT_RESTART_LOG_MAX_LINES", "0")
        monkeypatch.setenv("HVT_RESTART_LOG_MAX_MB", "0")
        log = supervisor.RestartLog(str(tmp_path / "j.jsonl"))
        for i in range(50):
            log.write("restarts", float(i))
        assert not os.path.exists(log.path + ".1")
        assert len(_records(log.path)) == 50

    def test_ci_gate_counts_across_rotation(self, tmp_path):
        log = supervisor.RestartLog(str(tmp_path / "j.jsonl"), max_lines=2)
        for i in range(5):
            log.write("shrink", float(i + 1), generation=i)
        # Two windows survive: the .1 predecessor (writes 3-4) + the live
        # file (write 5); the oldest window (writes 1-2) rotated away.
        ok, value = ci_gate.check_metrics(
            str(log.path), "shrink", (3.0, 3.0), how="count")
        assert ok and value == 3.0

    def test_ci_gate_accepts_rotated_away_live_file(self, tmp_path):
        """Right after a rotation the live file may not exist yet; the
        stream still counts as present via its .1 predecessor."""
        p = tmp_path / "j.jsonl"
        (tmp_path / "j.jsonl.1").write_text(
            json.dumps({"name": "restarts", "value": 1.0}) + "\n"
        )
        ok, value = ci_gate.check_metrics(
            str(p), "restarts", (1.0, 1.0), how="count")
        assert ok and value == 1.0

    def test_fleet_status_reads_across_rotation(self, tmp_path):
        log = supervisor.RestartLog(str(tmp_path / "j.jsonl"), max_lines=2)
        log.write("start", 3.0, generation=1, size=3)
        log.write("restarts", 1.0, member="m1", kind="leave")
        # rotation happens here (2 lines reached)
        log.write("shrink", 2.0, generation=2, size=2)
        assert os.path.exists(log.path + ".1")
        status = supervisor.fleet_status(log.path)
        assert status["generation"] == 2 and status["size"] == 2
        assert status["restarts"] == 1 and status["shrinks"] == 1
        assert [e["name"] for e in status["events"]] == [
            "start", "restarts", "shrink"
        ]

    def test_status_server_routes_and_loopback_default(self, tmp_path):
        """--status-port serves /status /journal /healthz from the
        supervisor itself — and binds LOOPBACK by default (the routes are
        unauthenticated; off-host exposure is the HVT_STATUS_HOST /
        host= opt-in)."""
        import urllib.request

        log = supervisor.RestartLog(str(tmp_path / "j.jsonl"))
        log.write("start", 2.0, generation=1, size=2)
        log.write("shrink", 1.0, generation=2, size=1)
        server = supervisor.start_status_server(0, log.path)
        try:
            bound_host, port = server.server_address[:2]
            assert bound_host == "127.0.0.1"

            def get(route):
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{route}", timeout=5
                ) as r:
                    return json.loads(r.read())

            status = get("/status")
            assert status["fleet"]["shrinks"] == 1
            assert status["coordinator"] is None  # no elastic coord here
            records = get("/journal")["records"]
            assert [r["name"] for r in records] == ["start", "shrink"]
            assert get("/healthz")["status"] == "ok"
        finally:
            server.shutdown()


class TestFleet:
    def test_abort_terminates_and_marks(self):
        proc = subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(60)"]
        )
        fleet = launcher.Fleet([proc])
        t0 = time.monotonic()
        code = fleet.wait(grace_seconds=30.0, abort=lambda: True)
        assert time.monotonic() - t0 < 15
        assert fleet.aborted
        assert proc.returncode is not None and proc.returncode != 0
        assert code != 0

    def test_abort_not_consulted_after_failure(self, tmp_path):
        """Once a rank failed, the grace window owns teardown — the abort
        hook (stale heartbeats are *expected* while peers wind down) must
        not override the fail-stop path."""
        dead = subprocess.Popen([sys.executable, "-c", "raise SystemExit(3)"])
        dead.wait()
        slow = subprocess.Popen(
            [sys.executable, "-c", "import time; time.sleep(0.5)"]
        )
        calls = []

        def abort():
            calls.append(1)
            return True

        fleet = launcher.Fleet([dead, slow])
        code = fleet.wait(grace_seconds=30.0, abort=abort)
        assert code == 3
        assert not fleet.aborted
        assert slow.returncode == 0  # finished inside grace, untouched


class TestFaultPlan:
    def test_parse_kinds(self):
        from horovod_tpu.testing import faults

        plan = faults.parse_plan("1:3:kill")
        assert (plan.rank, plan.epoch, plan.kind) == (1, 3, "kill")
        assert plan.exit_code is None
        assert faults.parse_plan("0:0:hang").kind == "hang"
        exit_plan = faults.parse_plan("0:2:exit143")
        assert exit_plan.kind == "exit143"
        assert exit_plan.exit_code == 143

    def test_parse_step_filter(self):
        from horovod_tpu.testing import faults

        plan = faults.parse_plan("2:1.5:leave")
        assert (plan.rank, plan.epoch, plan.step, plan.kind) == (
            2, 1, 5, "leave")
        assert faults.parse_plan("0:3:kill").step is None

    @pytest.mark.parametrize("bad", [
        "0:1", "a:1:kill", "0:b:kill", "0:1:explode", "0:1:exitX", "",
        "0:1.x:kill", "0:1.0:kill", "0:1.-2:kill",
    ])
    def test_parse_rejects(self, bad):
        from horovod_tpu.testing import faults

        with pytest.raises(ValueError):
            faults.parse_plan(bad)

    def test_step_filter_fires_at_or_past_target(self, monkeypatch):
        from horovod_tpu.testing import faults

        fired = []
        cb = faults.FaultInjectionCallback(faults.parse_plan("0:1.3:kill"))
        monkeypatch.setattr(cb, "_fire", lambda: fired.append(1))
        cb.on_epoch_begin(1)
        cb.on_batch_end(0)
        cb.on_batch_end(1)
        assert not fired  # steps 1, 2 done — before the target
        # A steps_per_execution chunk striding past step 3 (>= semantics).
        cb.on_batch_end(4)
        assert len(fired) == 1

    def test_step_filter_does_not_refire_on_resumed_run(self, monkeypatch):
        from types import SimpleNamespace

        from horovod_tpu.testing import faults

        fired = []
        cb = faults.FaultInjectionCallback(faults.parse_plan("0:1.3:kill"))
        monkeypatch.setattr(cb, "_fire", lambda: fired.append(1))
        # The relaunch resumed fit(initial_epoch=1, initial_step=3): the
        # fault fired in the run being resumed, so it must stay quiet —
        # no stamp file needed for step-filtered plans.
        cb.set_trainer(SimpleNamespace(_resume_epoch=1, _resume_step=3))
        cb.on_epoch_begin(1)
        cb.on_batch_end(3)  # first batch end after the resume point
        cb.on_batch_end(4)
        assert not fired
        # A resume BEFORE the target (crash from another cause) still
        # fires once the target step completes.
        cb.set_trainer(SimpleNamespace(_resume_epoch=1, _resume_step=1))
        cb.on_batch_end(2)
        assert len(fired) == 1

    def test_callback_gates_on_rank_epoch_and_stamp(self, tmp_path,
                                                    monkeypatch):
        from horovod_tpu.testing import faults

        fired = []
        cb = faults.FaultInjectionCallback(
            faults.parse_plan("0:1:exit1"), stamp=str(tmp_path / "stamp")
        )
        monkeypatch.setattr(cb, "_fire", lambda: fired.append(1))
        cb.on_epoch_begin(0)
        cb.on_batch_end(0)
        assert not fired  # wrong epoch
        cb.on_epoch_begin(1)
        cb.on_batch_end(0)
        assert len(fired) == 1  # fired, stamp written
        assert (tmp_path / "stamp").exists()
        cb.on_batch_end(1)
        assert len(fired) == 1  # one-shot: stamp suppresses re-fire

    def test_wrong_rank_does_not_fire(self, monkeypatch):
        from horovod_tpu.testing import faults

        fired = []
        cb = faults.FaultInjectionCallback(faults.parse_plan("5:0:kill"))
        monkeypatch.setattr(cb, "_fire", lambda: fired.append(1))
        cb.on_epoch_begin(0)
        cb.on_batch_end(0)
        assert not fired  # this process is rank 0, plan targets rank 5


class TestEnvWiring:
    def test_env_callbacks_off_by_default(self, monkeypatch):
        from horovod_tpu.training import callbacks as cb_lib

        monkeypatch.delenv("HVT_HEARTBEAT_DIR", raising=False)
        monkeypatch.delenv("HVT_FAULT", raising=False)
        assert cb_lib.env_callbacks() == []

    def test_env_callbacks_install_heartbeat_and_fault(self, tmp_path,
                                                       monkeypatch):
        from horovod_tpu.testing import faults
        from horovod_tpu.training import callbacks as cb_lib

        monkeypatch.setenv("HVT_HEARTBEAT_DIR", str(tmp_path / "hb"))
        monkeypatch.setenv("HVT_FAULT", "0:2:hang")
        cbs = cb_lib.env_callbacks()
        assert [type(c).__name__ for c in cbs] == [
            "HeartbeatCallback", "FaultInjectionCallback"]
        assert isinstance(cbs[1], faults.FaultInjectionCallback)
        assert cbs[1].plan.epoch == 2

    def test_heartbeat_callback_touches_rank_file(self, tmp_path):
        from horovod_tpu.training.callbacks import HeartbeatCallback

        cb = HeartbeatCallback(str(tmp_path / "hb"), interval=0.0)
        cb.on_train_begin()
        beat = tmp_path / "hb" / "rank-0"
        assert beat.exists()
        first = beat.stat().st_mtime_ns
        time.sleep(0.05)
        cb.on_batch_end(0)
        assert beat.stat().st_mtime_ns > first

    def test_heartbeat_throttles_batch_beats(self, tmp_path):
        from horovod_tpu.training.callbacks import HeartbeatCallback

        cb = HeartbeatCallback(str(tmp_path / "hb"), interval=3600.0)
        cb.on_train_begin()
        beat = tmp_path / "hb" / "rank-0"
        first = beat.stat().st_mtime_ns
        time.sleep(0.05)
        cb.on_batch_end(0)  # inside the throttle window — no touch
        assert beat.stat().st_mtime_ns == first
        cb.on_epoch_end(0)  # boundaries always beat
        assert beat.stat().st_mtime_ns > first


# Tiny self-contained trainer (synthetic data — no downloads) driven as a
# subprocess by the smoke/e2e tests; mirrors the examples' resume idiom.
TRAIN_SCRIPT = """
import os, sys
sys.path.insert(0, __REPO__)
import numpy as np
import optax
import flax.linen as nn
import horovod_tpu as hvt
from horovod_tpu import checkpoint


class Tiny(nn.Module):
    @nn.compact
    def __call__(self, x, train=False):
        return nn.Dense(4)(x)


def main():
    model_dir = os.path.join(os.environ["PS_MODEL_PATH"], "run")
    hvt.init()
    rng = np.random.RandomState(0)
    x = rng.rand(64, 8).astype("float32")
    y = (np.arange(64) % 4).astype("int64")
    trainer = hvt.Trainer(
        Tiny(), hvt.DistributedOptimizer(optax.adam(1e-2))
    )
    trainer.build(x[:1], y[:1])
    trainer.state, done = checkpoint.restore_latest_and_broadcast(
        model_dir, trainer.state, mesh=trainer.mesh
    )
    if done and hvt.rank() == 0:
        print(f"Resuming from checkpoint epoch {done}", flush=True)
    cbs = [hvt.callbacks.BroadcastGlobalVariablesCallback(0)]
    if hvt.rank() == 0:
        cbs.append(hvt.callbacks.ModelCheckpoint(
            os.path.join(model_dir, "checkpoint-{epoch}.msgpack")))
    epochs = int(os.environ.get("DRIVE_EPOCHS", "3"))
    trainer.fit(
        x=x, y=y, batch_size=8, epochs=epochs, initial_epoch=done,
        steps_per_epoch=2, callbacks=cbs,
        verbose=1 if hvt.rank() == 0 else 0,
    )
    if hvt.rank() == 0:
        print("TRAINING COMPLETE", flush=True)


main()
"""


def write_train_script(tmp_path):
    path = tmp_path / "train.py"
    path.write_text(TRAIN_SCRIPT.replace("__REPO__", repr(REPO)))
    return [sys.executable, str(path)]


def test_supervised_smoke_one_exit1_one_restart(tmp_path):
    """Tier-1 smoke (ISSUE satellite): a real (tiny) training run with one
    injected ``exit1`` under `supervise_local` — the supervisor restarts
    exactly once, the rerun completes, and the JSONL journal records exactly
    one crash restart (checked through the CI gate's count aggregate)."""
    argv = write_train_script(tmp_path)
    model_dir = tmp_path / "models"
    log = tmp_path / "restarts.jsonl"
    env = {
        "HVT_PLATFORM": "cpu",
        "PS_MODEL_PATH": str(model_dir),
        "DRIVE_EPOCHS": "1",
        "HVT_FAULT": "0:0:exit1",
        "HVT_FAULT_STAMP": str(tmp_path / "fault-stamp"),
        # Keep chaos children out of the suite's shared persistent XLA
        # cache: an os._exit mid-write tears the entry (see
        # test_supervisor_e2e._env).
        "JAX_ENABLE_COMPILATION_CACHE": "0",
        "JAX_COMPILATION_CACHE_DIR": "",
    }
    code = supervisor.supervise_local(
        1, argv, env=env,
        policy=RestartPolicy(max_restarts=2, backoff=0.0, grace_seconds=5.0),
        model_dir=str(model_dir), log_path=str(log), tag_output=False,
        sleep=NO_SLEEP,
    )
    assert code == 0
    restarts = [r for r in _records(log) if r["name"] == "restarts"]
    assert len(restarts) == 1
    assert restarts[0]["kind"] == "crash"
    assert restarts[0]["exit_code"] == 1
    # The journal is CI-gateable as-is: exactly one restart.
    ok, value = ci_gate.check_metrics(
        str(log), "restarts", (1.0, 1.0), how="count")
    assert ok and value == 1.0
    ok_zero, _ = ci_gate.check_metrics(
        str(log), "restarts", (0.0, 0.0), how="count")
    assert not ok_zero
