"""The fleet control plane's acceptance run (slow lane), end-to-end on
CPU over the SHIPPED two-job scenario (launch/jobs/fleet-two-jobs.yaml):

* high-priority MNIST (world exactly 2, with a recurring ``slow:MS``
  straggler) arrives mid-run and PREEMPTS the low-priority packed-LM
  soak — clean elastic shrink, journaled ``preempt``, ZERO restart
  budget spent on the victims;
* the ``hostdown`` fault then takes the soak's whole surviving host in
  one stroke — classified as ONE ``host_lost`` (charged once, sibling
  free), the host quarantined for the spec's cooldown;
* when units free up (cooldown expiry, then MNIST finishing) fleetd
  REGROWS the soak back to its FULL world size and it completes;
* mid-run, fleetd itself is SIGKILLed and relaunched: the restarted
  daemon replays ``fleet-journal.jsonl``, probes the recorded pids +
  control ports, and ADOPTS both still-running jobs instead of
  relaunching them (place count stays exactly 2);
* per-job budgets stay isolated: every journal record carries its own
  job's name, asserted by `budget_isolation_violations` and re-checked
  here;
* ``GET /fleetd`` serves the rollup while the recovered daemon runs.

Everything below drives the real `hvt-launch fleet` CLI in
subprocesses — no scheduler internals are touched."""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

from horovod_tpu.launch import fleetd

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SPEC = os.path.join(REPO, "horovod_tpu", "launch", "jobs",
                    "fleet-two-jobs.yaml")


def _journal(path):
    try:
        with open(path) as f:
            return [json.loads(line) for line in f if line.strip()]
    except OSError:
        return []


def _named(records, name, **fields):
    return [r for r in records if r.get("name") == name
            and all(r.get(k) == v for k, v in fields.items())]


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _env():
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        # Chaos children stay out of the suite's shared persistent XLA
        # cache (see test_supervisor_e2e._env for the torn-entry
        # SEGFAULT).
        "JAX_ENABLE_COMPILATION_CACHE": "0",
        "JAX_COMPILATION_CACHE_DIR": "",
    })
    return env


def _wait_for(predicate, timeout, what, poll=0.5):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(poll)
    raise AssertionError(f"timed out after {timeout}s waiting for {what}")


def _reap_fleet(journal_path):
    """Best-effort teardown of every job process group the journal ever
    named — the cleanup net under the SIGKILL choreography."""
    for rec in _journal(journal_path):
        pid = rec.get("pid")
        if rec.get("name") in ("place", "adopt") and pid:
            for sig in (signal.SIGTERM, signal.SIGKILL):
                try:
                    os.killpg(int(pid), sig)
                except (ProcessLookupError, PermissionError, OSError):
                    break
                time.sleep(0.2)


@pytest.mark.slow
def test_fleet_two_jobs_preempt_hostdown_recovery(tmp_path, capfd):
    """THE fleet acceptance run — shipped spec, real CLI, one mid-run
    fleetd SIGKILL, all gates green."""
    with open(SPEC) as f:
        text = f.read()
    assert "/tmp/hvt-fleet-ci" in text  # the paths this test relocates
    root = str(tmp_path / "fleet-ci")
    spec_path = str(tmp_path / "fleet-two-jobs.yaml")
    with open(spec_path, "w") as f:  # hvt: noqa[HVT005] — test fixture
        f.write(text.replace("/tmp/hvt-fleet-ci", root))
    journal = os.path.join(root, "fleet-state", fleetd.JOURNAL_NAME)
    status_port = _free_port()
    argv = [sys.executable, "-m", "horovod_tpu.launch", "fleet",
            spec_path, "--status-port", str(status_port)]

    first = subprocess.Popen(argv, cwd=REPO, env=_env())
    second = None
    try:
        # Phase A: let the story start — soak admitted at full size,
        # MNIST arrives, preemption lands, MNIST placed. Then kill the
        # daemon the hard way, mid-flight.
        _wait_for(
            lambda: (first.poll() is None
                     and _named(_journal(journal), "place",
                                job="mnist-hi")),
            timeout=180, what="both jobs placed",
        )
        assert first.poll() is None, "fleetd died before the kill point"
        pids = {r["job"]: r["pid"]
                for r in _named(_journal(journal), "place")}
        first.send_signal(signal.SIGKILL)
        first.wait(timeout=30)
        # The job children live in their OWN sessions: a dead fleetd
        # must not have taken them down.
        time.sleep(1.0)
        for job, pid in pids.items():
            assert fleetd._pid_alive(pid), \
                f"{job} (pid {pid}) died with fleetd"

        # Phase B: same command again — recovery, not a fresh fleet.
        second = subprocess.Popen(argv, cwd=REPO, env=_env())
        _wait_for(
            lambda: _named(_journal(journal), "adopt"),
            timeout=60, what="journal adoption records",
        )
        # The recovered daemon serves the rollup for the adopted fleet.
        snap = _wait_for(
            lambda: _fleetd_snapshot(status_port),
            timeout=30, what="GET /fleetd",
        )
        assert set(snap["jobs"]) == {"lm-soak", "mnist-hi"}
        rc = second.wait(timeout=540)
        assert rc == 0, capfd.readouterr().out[-6000:]
    finally:
        for proc in (first, second):
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait()
        _reap_fleet(journal)

    records = _journal(journal)
    # One fleet, told once: a single start, a green finish.
    assert len(_named(records, "fleet_start")) == 1
    done = _named(records, "fleet_done")
    assert len(done) == 1 and done[0]["ok"] is True

    # Adoption, not relaunch: both jobs were running at the kill point,
    # both were adopted, and NO job was ever placed twice.
    adopted = {r["job"] for r in _named(records, "adopt")}
    assert adopted == {"lm-soak", "mnist-hi"}
    places = _named(records, "place")
    assert len(places) == 2
    assert {r["job"] for r in places} == {"lm-soak", "mnist-hi"}

    # Preemption-as-elastic-shrink: the scheduler reclaimed soak units
    # for the high-priority arrival — and never touched mnist-hi.
    assert _named(records, "preempt", job="lm-soak")
    assert not _named(records, "preempt", job="mnist-hi")
    assert _named(records, "release", job="lm-soak", source="ctl")

    # Host failure is ONE event: a single host_lost, quarantine stamped.
    lost = _named(records, "host_lost", job="lm-soak")
    assert len(lost) == 1
    assert lost[0]["until"] > lost[0]["wall_time"]

    # ... and the victim was regrown once capacity freed.
    assert _named(records, "regrow", job="lm-soak")

    # Per-job journals: the budget story, strictly isolated.
    lm_log = os.path.join(root, "lm", "restarts.jsonl")
    mnist_log = os.path.join(root, "mnist", "restarts.jsonl")
    lm = _journal(lm_log)
    mnist = _journal(mnist_log)
    assert lm and mnist
    assert fleetd.budget_isolation_violations("lm-soak", lm_log) == []
    assert fleetd.budget_isolation_violations("mnist-hi", mnist_log) == []

    # The soak's clean-leave preemption spent NOTHING; the host loss
    # charged exactly ONCE (the sibling's death rode free).
    assert _named(lm, "preempt")
    charges = _named(lm, "restarts")
    assert len(charges) == 1, charges
    assert charges[0]["kind"] == "host_lost"
    assert len(_named(lm, "host_lost")) == 1  # the free sibling
    assert not _named(lm, "supervisor_gave_up")
    # Full-size regrow: the coordinator settled back at world size 4.
    assert any(r["name"] == "grow" and r.get("size") == 4 for r in lm)

    # The high-priority job never restarted, never shrank, finished
    # with its whole budget: total isolation from the soak's chaos.
    assert not _named(mnist, "restarts")
    assert not _named(mnist, "preempt")
    assert not _named(mnist, "supervisor_gave_up")


def _fleetd_snapshot(port):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/fleetd", timeout=2.0
        ) as r:
            return json.loads(r.read().decode())
    except Exception:
        return None
