"""Weight-only int8 quantization (models/quant.py) and quantized decode."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvt
from horovod_tpu.data import datasets
from horovod_tpu.models.decoding import make_generate_fn
from horovod_tpu.models.quant import (
    dequantize_params,
    quantize_params,
    quantized_bytes,
)
from horovod_tpu.models.transformer import TransformerLM

VOCAB = 32


def _model(**kw):
    kw.setdefault("vocab_size", VOCAB)
    kw.setdefault("d_model", 64)
    kw.setdefault("n_heads", 4)
    kw.setdefault("n_layers", 2)
    kw.setdefault("dropout", 0.0)
    return TransformerLM(**kw)


class TestQuantize:
    def test_roundtrip_error_bounded_by_half_scale(self):
        """Symmetric round-to-nearest: |deq - p| <= scale/2 per element —
        the tightest guarantee the format makes."""
        rng = np.random.RandomState(0)
        p = {"k": jnp.asarray(rng.randn(64, 128).astype(np.float32))}
        q = quantize_params(p, min_size=1)
        deq = dequantize_params(q, dtype=jnp.float32)
        scale = np.asarray(q["k"]["scale"])  # [1, 128]
        err = np.abs(np.asarray(deq["k"]) - np.asarray(p["k"]))
        assert (err <= scale / 2 + 1e-7).all()

    def test_small_and_1d_leaves_pass_through(self):
        p = {
            "ln": jnp.ones((64,), jnp.float32),
            "tiny": jnp.ones((4, 4), jnp.float32),
            "big": jnp.ones((128, 128), jnp.float32),
        }
        q = quantize_params(p, min_size=4096)
        assert q["ln"] is p["ln"] and q["tiny"] is p["tiny"]
        assert q["big"]["int8_q"].dtype == jnp.int8

    def test_bytes_roughly_quartered(self):
        """f32 kernels -> int8 + f32 per-channel scales: ~4x smaller."""
        p = {"k": jnp.ones((256, 256), jnp.float32)}
        q = quantize_params(p, min_size=1)
        assert quantized_bytes(q) < p["k"].size * 4 / 3.5

    def test_model_params_structure(self):
        model = _model()
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32)
        )["params"]
        q = quantize_params(params, min_size=64)
        flat = jax.tree_util.tree_leaves(q)
        assert any(leaf.dtype == jnp.int8 for leaf in flat)
        deq = dequantize_params(q, dtype=jnp.float32)
        assert jax.tree_util.tree_structure(
            deq
        ) == jax.tree_util.tree_structure(params)


class TestQuantizedDecode:
    def test_generates_valid_tokens(self):
        model = _model()
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32)
        )["params"]
        fn = make_generate_fn(
            model, max_new_tokens=12, include_prompt=False, quantized=True
        )
        out = np.asarray(
            fn(quantize_params(params), jnp.asarray([[1, 2, 3, 4]], jnp.int32),
               jax.random.PRNGKey(0))
        )
        assert out.shape == (1, 12)
        assert out.min() >= 0 and out.max() < VOCAB

    def test_trained_model_quality_preserved(self):
        """Weight-only int8 on a model that learned the copy task: the
        quantized greedy decode must still recall the copied half almost
        perfectly, and agree with the bf16 decode on nearly every token —
        the quality gate that makes the bandwidth saving usable."""
        from horovod_tpu.parallel import mesh as mesh_lib

        model = _model()
        trainer = hvt.Trainer(
            model,
            hvt.DistributedOptimizer(optax.adam(3e-3)),
            loss="sparse_categorical_crossentropy",
            mesh=mesh_lib.build_mesh(
                mesh_lib.MeshSpec(data=1), devices=jax.devices()[:1]
            ),
        )
        x, y = datasets.copy_task(512, 32, vocab_size=VOCAB, seed=9)
        trainer.fit(
            x=x, y=y, batch_size=32, epochs=4, steps_per_epoch=16, verbose=0
        )
        params = trainer.state.params
        xt, _ = datasets.copy_task(4, 32, vocab_size=VOCAB, seed=21)
        prompt = jnp.asarray(xt[:, :16])
        n_new = 15

        bf16 = make_generate_fn(
            model, max_new_tokens=n_new, include_prompt=False
        )(params, prompt, jax.random.PRNGKey(0))
        int8 = make_generate_fn(
            model, max_new_tokens=n_new, include_prompt=False, quantized=True
        )(quantize_params(params), prompt, jax.random.PRNGKey(0))

        agree = float(
            (np.asarray(bf16) == np.asarray(int8)).mean()
        )
        recall = float(
            (np.asarray(int8) == np.asarray(xt[:, 16:31])).mean()
        )
        assert agree >= 0.9, f"top-1 agreement with bf16 only {agree:.2f}"
        assert recall >= 0.85, f"quantized recall dropped to {recall:.2f}"
