"""Weight-only int8 quantization (models/quant.py) and quantized decode."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvt
from horovod_tpu.data import datasets
from horovod_tpu.models.decoding import make_generate_fn
from horovod_tpu.models.quant import (
    dequantize_params,
    quantize_params,
    quantized_bytes,
)
from horovod_tpu.models.transformer import TransformerLM

VOCAB = 32


def _model(**kw):
    kw.setdefault("vocab_size", VOCAB)
    kw.setdefault("d_model", 64)
    kw.setdefault("n_heads", 4)
    kw.setdefault("n_layers", 2)
    kw.setdefault("dropout", 0.0)
    return TransformerLM(**kw)


class TestQuantize:
    def test_roundtrip_error_bounded_by_half_scale(self):
        """Symmetric round-to-nearest: |deq - p| <= scale/2 per element —
        the tightest guarantee the format makes."""
        rng = np.random.RandomState(0)
        p = {"k": jnp.asarray(rng.randn(64, 128).astype(np.float32))}
        q = quantize_params(p, min_size=1)
        deq = dequantize_params(q, dtype=jnp.float32)
        scale = np.asarray(q["k"]["scale"])  # [1, 128]
        err = np.abs(np.asarray(deq["k"]) - np.asarray(p["k"]))
        assert (err <= scale / 2 + 1e-7).all()

    def test_small_and_1d_leaves_pass_through(self):
        p = {
            "ln": jnp.ones((64,), jnp.float32),
            "tiny": jnp.ones((4, 4), jnp.float32),
            "big": jnp.ones((128, 128), jnp.float32),
        }
        q = quantize_params(p, min_size=4096)
        assert q["ln"] is p["ln"] and q["tiny"] is p["tiny"]
        assert q["big"]["int8_q"].dtype == jnp.int8

    def test_bytes_roughly_quartered(self):
        """f32 kernels -> int8 + f32 per-channel scales: ~4x smaller."""
        p = {"k": jnp.ones((256, 256), jnp.float32)}
        q = quantize_params(p, min_size=1)
        assert quantized_bytes(q) < p["k"].size * 4 / 3.5

    @pytest.mark.slow
    def test_model_params_structure(self):
        model = _model()
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32)
        )["params"]
        q = quantize_params(params, min_size=64)
        flat = jax.tree_util.tree_leaves(q)
        assert any(leaf.dtype == jnp.int8 for leaf in flat)
        deq = dequantize_params(q, dtype=jnp.float32)
        assert jax.tree_util.tree_structure(
            deq
        ) == jax.tree_util.tree_structure(params)


@pytest.mark.slow
class TestQuantizedDecode:
    def test_generates_valid_tokens(self):
        model = _model()
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32)
        )["params"]
        fn = make_generate_fn(
            model, max_new_tokens=12, include_prompt=False, quantized=True
        )
        out = np.asarray(
            fn(quantize_params(params), jnp.asarray([[1, 2, 3, 4]], jnp.int32),
               jax.random.PRNGKey(0))
        )
        assert out.shape == (1, 12)
        assert out.min() >= 0 and out.max() < VOCAB

    def test_trained_model_quality_preserved(self):
        """Weight-only int8 on a model that learned the copy task: the
        quantized greedy decode must still recall the copied half almost
        perfectly, and agree with the bf16 decode on nearly every token —
        the quality gate that makes the bandwidth saving usable."""
        from horovod_tpu.parallel import mesh as mesh_lib

        model = _model()
        trainer = hvt.Trainer(
            model,
            hvt.DistributedOptimizer(optax.adam(3e-3)),
            loss="sparse_categorical_crossentropy",
            mesh=mesh_lib.build_mesh(
                mesh_lib.MeshSpec(data=1), devices=jax.devices()[:1]
            ),
        )
        x, y = datasets.copy_task(512, 32, vocab_size=VOCAB, seed=9)
        trainer.fit(
            x=x, y=y, batch_size=32, epochs=4, steps_per_epoch=16, verbose=0
        )
        params = trainer.state.params
        xt, _ = datasets.copy_task(4, 32, vocab_size=VOCAB, seed=21)
        prompt = jnp.asarray(xt[:, :16])
        n_new = 15

        bf16 = make_generate_fn(
            model, max_new_tokens=n_new, include_prompt=False
        )(params, prompt, jax.random.PRNGKey(0))
        int8 = make_generate_fn(
            model, max_new_tokens=n_new, include_prompt=False, quantized=True
        )(quantize_params(params), prompt, jax.random.PRNGKey(0))

        agree = float(
            (np.asarray(bf16) == np.asarray(int8)).mean()
        )
        recall = float(
            (np.asarray(int8) == np.asarray(xt[:, 16:31])).mean()
        )
        assert agree >= 0.9, f"top-1 agreement with bf16 only {agree:.2f}"
        assert recall >= 0.85, f"quantized recall dropped to {recall:.2f}"


class TestInt8DotGeneral:
    def test_matches_f32_dot_within_quant_error(self):
        from horovod_tpu.models.quant import int8_dot_general

        rng = np.random.RandomState(0)
        x = rng.randn(16, 64).astype(np.float32)
        w = rng.randn(64, 32).astype(np.float32)
        dims = (((1,), (0,)), ((), ()))
        got = np.asarray(
            int8_dot_general(jnp.asarray(x), jnp.asarray(w), dims,
                             preferred_element_type=jnp.float32)
        )
        want = x @ w
        # Two symmetric roundings at 127 levels each: relative error on
        # the order of a few percent of the row/channel magnitudes.
        denom = np.maximum(np.abs(want), np.abs(want).mean())
        assert (np.abs(got - want) / denom).max() < 0.08

    def test_exact_on_int8_lattice(self):
        """Operands already on their int8 lattices quantize losslessly, so
        the int32 MXU accumulation makes the whole product EXACT."""
        from horovod_tpu.models.quant import int8_dot_general

        rng = np.random.RandomState(1)
        xi = rng.randint(-127, 128, size=(8, 32)).astype(np.float32)
        wi = rng.randint(-127, 128, size=(32, 16)).astype(np.float32)
        # Pin each row's / channel's amax to exactly 127 so the dynamic
        # scale is the lattice unit and quantization round-trips.
        xi[:, 0] = 127.0
        wi[0, :] = 127.0
        x = xi * 0.013
        w = wi * 0.07
        dims = (((1,), (0,)), ((), ()))
        got = np.asarray(
            int8_dot_general(jnp.asarray(x), jnp.asarray(w), dims,
                             preferred_element_type=jnp.float32)
        )
        # Ground truth in exact integer arithmetic (a f32 x @ w reference
        # would itself carry accumulation error near zero entries).
        want = (xi.astype(np.int64) @ wi.astype(np.int64)) * (0.013 * 0.07)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)

    def test_multi_axis_contraction(self):
        # DenseGeneral's axis=(-2,-1) pattern (attn_out: [B,T,H,D]x[H,D,dm]).
        from horovod_tpu.models.quant import int8_dot_general

        rng = np.random.RandomState(2)
        x = rng.randn(4, 6, 4, 8).astype(np.float32)
        w = rng.randn(4, 8, 16).astype(np.float32)
        dims = (((2, 3), (0, 1)), ((), ()))
        got = np.asarray(
            int8_dot_general(jnp.asarray(x), jnp.asarray(w), dims,
                             preferred_element_type=jnp.float32)
        )
        want = np.einsum("bthd,hdm->btm", x, w)
        denom = np.maximum(np.abs(want), np.abs(want).mean())
        assert (np.abs(got - want) / denom).max() < 0.08

    def test_batch_dims_rejected(self):
        from horovod_tpu.models.quant import int8_dot_general

        with pytest.raises(NotImplementedError, match="batch"):
            int8_dot_general(
                jnp.ones((2, 3, 4)), jnp.ones((2, 4, 5)),
                (((2,), (1,)), ((0,), (0,))),
            )


@pytest.mark.slow
class TestInt8Compute:
    def test_forward_close_to_bf16_and_train_rejected(self):
        model = _model()
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32)
        )["params"]
        x = jnp.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], jnp.int32)
        base = np.asarray(model.apply({"params": params}, x), np.float32)
        q = np.asarray(
            model.clone(int8_compute=True).apply({"params": params}, x),
            np.float32,
        )
        # Same argmax token at nearly every position on an untrained net.
        agree = (base.argmax(-1) == q.argmax(-1)).mean()
        assert agree >= 0.8, agree
        with pytest.raises(ValueError, match="inference-only"):
            model.clone(int8_compute=True).apply(
                {"params": params}, x, train=True,
                rngs={"dropout": jax.random.PRNGKey(0)},
            )

    def test_trained_model_quality_preserved(self):
        """int8 COMPUTE on the trained copy-task model: greedy decode with
        dynamic activation quant + int8 MXU matmuls still recalls the
        copy and agrees with bf16 — the existing quality gate applied to
        the compute path (VERDICT Weak #7)."""
        from horovod_tpu.parallel import mesh as mesh_lib

        model = _model()
        trainer = hvt.Trainer(
            model,
            hvt.DistributedOptimizer(optax.adam(3e-3)),
            loss="sparse_categorical_crossentropy",
            mesh=mesh_lib.build_mesh(
                mesh_lib.MeshSpec(data=1), devices=jax.devices()[:1]
            ),
        )
        x, y = datasets.copy_task(512, 32, vocab_size=VOCAB, seed=9)
        trainer.fit(
            x=x, y=y, batch_size=32, epochs=4, steps_per_epoch=16, verbose=0
        )
        params = trainer.state.params
        xt, _ = datasets.copy_task(4, 32, vocab_size=VOCAB, seed=23)
        prompt = jnp.asarray(xt[:, :16])
        n_new = 15

        bf16 = make_generate_fn(
            model, max_new_tokens=n_new, include_prompt=False
        )(params, prompt, jax.random.PRNGKey(0))
        int8c = make_generate_fn(
            model, max_new_tokens=n_new, include_prompt=False,
            int8_compute=True,
        )(params, prompt, jax.random.PRNGKey(0))

        agree = float((np.asarray(bf16) == np.asarray(int8c)).mean())
        recall = float(
            (np.asarray(int8c) == np.asarray(xt[:, 16:31])).mean()
        )
        assert agree >= 0.9, f"top-1 agreement with bf16 only {agree:.2f}"
        assert recall >= 0.85, f"int8-compute recall dropped to {recall:.2f}"

    def test_stacks_with_weight_only_storage(self):
        # quantized=True (int8 HBM stream) + int8_compute=True (int8 MXU):
        # requantization round-trips the lattice, generation stays valid.
        model = _model()
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32)
        )["params"]
        fn = make_generate_fn(
            model, max_new_tokens=8, include_prompt=False,
            quantized=True, int8_compute=True,
        )
        out = np.asarray(
            fn(quantize_params(params),
               jnp.asarray([[1, 2, 3, 4]], jnp.int32),
               jax.random.PRNGKey(0))
        )
        assert out.shape == (1, 8)
        assert out.min() >= 0 and out.max() < VOCAB


def test_int8_compute_moe_rejected():
    model = _model(moe_every=2, n_experts=4, int8_compute=True)
    params_model = _model(moe_every=2, n_experts=4)
    params = params_model.init(
        jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32)
    )["params"]
    with pytest.raises(ValueError, match="MoE"):
        model.apply({"params": params}, jnp.zeros((2, 8), jnp.int32))


class TestQuantizedKVCache:
    def test_cache_is_int8_with_scales(self):
        model = _model(quantized_cache=True)
        dmodel = model.clone(decode=True, max_decode_len=12)
        prompt = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
        params = _model().init(jax.random.PRNGKey(0), prompt)["params"]
        _, vars_ = dmodel.apply({"params": params}, prompt, mutable=["cache"])
        blk = vars_["cache"]["Block_0"]
        assert blk["k"].dtype == jnp.int8 and blk["v"].dtype == jnp.int8
        assert blk["k_scale"].shape == blk["k"].shape[:3]
        # bytes: int8 values + f32 per-(pos,head) scales ≈ (1 + 4/D)·B·L·H·D
        full = blk["k"].size * 4  # f32-equivalent full-width cache
        stored = blk["k"].size + blk["k_scale"].size * 4
        assert stored < full / 3, (stored, full)

    def test_first_token_exact_rest_valid(self):
        # The prefill attention uses the fresh full-precision K/V (only the
        # cache WRITES are quantized), so the FIRST sampled token is exact
        # vs the full-width cache; later tokens read the quantized cache
        # and may legitimately differ near ties on an untrained net.
        model = _model()
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32)
        )["params"]
        prompt = jnp.asarray([[3, 1, 4, 1, 5], [9, 2, 6, 5, 3]], jnp.int32)
        full = make_generate_fn(model, max_new_tokens=10, include_prompt=False)(
            params, prompt, jax.random.PRNGKey(0)
        )
        q = make_generate_fn(
            model, max_new_tokens=10, include_prompt=False,
            quantized_cache=True,
        )(params, prompt, jax.random.PRNGKey(0))
        np.testing.assert_array_equal(
            np.asarray(q[:, 0]), np.asarray(full[:, 0])
        )
        assert (np.asarray(q) >= 0).all() and (np.asarray(q) < VOCAB).all()

    @pytest.mark.slow
    def test_trained_model_quality_preserved(self):
        """int8 KV cache on the trained copy-task model — the same quality
        gate as the weight paths: top-1 agreement with the full-width
        cache and near-perfect task recall."""
        from horovod_tpu.parallel import mesh as mesh_lib

        model = _model(n_kv_heads=2)  # GQA composition too
        trainer = hvt.Trainer(
            model,
            hvt.DistributedOptimizer(optax.adam(3e-3)),
            loss="sparse_categorical_crossentropy",
            mesh=mesh_lib.build_mesh(
                mesh_lib.MeshSpec(data=1), devices=jax.devices()[:1]
            ),
        )
        x, y = datasets.copy_task(512, 32, vocab_size=VOCAB, seed=9)
        trainer.fit(
            x=x, y=y, batch_size=32, epochs=4, steps_per_epoch=16, verbose=0
        )
        params = trainer.state.params
        xt, _ = datasets.copy_task(4, 32, vocab_size=VOCAB, seed=27)
        prompt = jnp.asarray(xt[:, :16])
        n_new = 15
        full = make_generate_fn(
            model, max_new_tokens=n_new, include_prompt=False
        )(params, prompt, jax.random.PRNGKey(0))
        q = make_generate_fn(
            model, max_new_tokens=n_new, include_prompt=False,
            quantized_cache=True,
        )(params, prompt, jax.random.PRNGKey(0))
        agree = float((np.asarray(full) == np.asarray(q)).mean())
        recall = float((np.asarray(q) == np.asarray(xt[:, 16:31])).mean())
        assert agree >= 0.9, f"top-1 agreement only {agree:.2f}"
        assert recall >= 0.85, f"quantized-cache recall {recall:.2f}"

    def test_ragged_composition(self):
        # Per-row cache indices write int8 values AND scales per row.
        model = _model(quantized_cache=True)
        params = _model().init(
            jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32)
        )["params"]
        lens = jnp.array([3, 6], jnp.int32)
        prompt = jnp.asarray(
            [[5, 3, 7, 0, 0, 0], [1, 9, 8, 4, 2, 6]], jnp.int32
        )
        fn = make_generate_fn(model, max_new_tokens=5, include_prompt=False)
        got = np.asarray(fn(params, prompt, jax.random.PRNGKey(0), lens))
        # Each row equals its solo generation under the SAME quantized
        # cache (per-position quantization is row-independent).
        for i, L in enumerate([3, 6]):
            solo = np.asarray(
                fn(params, prompt[i : i + 1, :L], jax.random.PRNGKey(0))
            )
            np.testing.assert_array_equal(got[i], solo[0], err_msg=f"row {i}")

    def test_sliding_cache_rejected(self):
        model = _model(window=4, sliding_cache=True, quantized_cache=True)
        params = _model(window=4).init(
            jax.random.PRNGKey(0), jnp.zeros((1, 6), jnp.int32)
        )["params"]
        fn = make_generate_fn(model, max_new_tokens=4)
        with pytest.raises(ValueError, match="quantized_cache"):
            fn(params, jnp.zeros((1, 6), jnp.int32), jax.random.PRNGKey(0))

    def test_speculative_exact_vs_plain_quantized_cache(self):
        # Exactness contract survives: speculative-with-qcache must equal
        # plain-greedy-with-qcache bit for bit (both consult the same
        # quantized cache values at every position).
        from horovod_tpu.models.speculative import make_speculative_fn

        model = _model(quantized_cache=True)
        params = _model().init(
            jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32)
        )["params"]
        prompt = jnp.asarray(
            np.random.RandomState(5).randint(1, VOCAB, size=(2, 10)),
            jnp.int32,
        )
        want = make_generate_fn(model, max_new_tokens=16)(
            params, prompt, jax.random.PRNGKey(0)
        )
        got = make_speculative_fn(model, max_new_tokens=16, gamma=4)(
            params, prompt
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_tp_mesh_matches_single_device(self):
        # The scale state carries the same heads-over-model constraint as
        # the int8 K/V it describes — sharded decode must bit-match.
        from horovod_tpu.models.transformer import ShardingConfig
        from horovod_tpu.parallel import mesh as mesh_lib

        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=4, model=2))
        plain = _model()
        sharded = _model(sharding=ShardingConfig(mesh=mesh))
        prompt = jnp.asarray(
            np.random.RandomState(0).randint(1, VOCAB, (4, 8)), jnp.int32
        )
        params = plain.init(jax.random.PRNGKey(0), prompt)["params"]
        a = make_generate_fn(plain, max_new_tokens=8, quantized_cache=True)(
            params, prompt, jax.random.PRNGKey(0)
        )
        b = make_generate_fn(sharded, max_new_tokens=8, quantized_cache=True)(
            params, prompt, jax.random.PRNGKey(0)
        )
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
