"""Gradient wire compression on the COMPILED data-parallel path.

Horovod 0.18.1's ``DistributedOptimizer(compression=Compression.fp16)``
(SURVEY.md §2.4 row 3) compresses the gradient bytes that cross the
interconnect. In SPMD-jit mode the gradient all-reduce is placed by XLA, so
the knob is honoured by `Trainer` switching to an explicit-collective
shard_map gradient step whose psum runs on the 16-bit dtype
(trainer.py `compressed_grads`). These tests prove, at the HLO level, that
the emitted collective really changed element type — the round-2 verdict's
"API theater" fix — plus numerics and composition coverage.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvt
from horovod_tpu.analysis import hlo_audit
from horovod_tpu.analysis.step_probe import lowered_step_text
from horovod_tpu.parallel import sharding as sharding_lib
from horovod_tpu.training.optimizer import compression_dtype
from horovod_tpu.training.trainer import Trainer


class _MLP(nn.Module):
    @nn.compact
    def __call__(self, x, train=False):
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(32)(x)
        x = nn.relu(x)
        x = nn.Dropout(0.25, deterministic=not train)(x)
        return nn.Dense(10)(x)


class _BNNet(nn.Module):
    """Tiny BatchNorm model: exercises the compressed path's cross-shard
    pmean of updated batch statistics (the SPMD path computes them over the
    global batch by construction; the shard_map path must reduce)."""

    @nn.compact
    def __call__(self, x, train=False):
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(16)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.0)(x)
        return nn.Dense(10)(x)


def _data(n=64, d=8, seed=0):
    rng = np.random.RandomState(seed)
    return (
        rng.rand(n, d).astype(np.float32),
        rng.randint(0, 10, n).astype(np.int64),
    )


def _trainer(compression, module=None, **kw):
    tx = hvt.DistributedOptimizer(optax.adam(1e-2), compression=compression, **kw)
    return Trainer(module or _MLP(), tx)


def _step_args(tr, x, y):
    state = tr.build(x[: tr.dp_size])
    batch = tr._shard((x, y))
    acc = sharding_lib.replicate(
        {"loss": jnp.zeros(()), "accuracy": jnp.zeros(())}, tr.mesh
    )
    return state, batch, jnp.asarray(1.0, jnp.float32), acc


def _run_steps(tr, x, y, n=5):
    state, batch, scale, acc = _step_args(tr, x, y)
    for _ in range(n):
        state, metrics, acc = tr._train_step(state, batch, scale, acc)
    tr.state = state
    return float(jax.device_get(metrics["loss"]))


class TestWireDtype:
    def test_emitted_allreduce_is_bf16(self):
        """The lowered step of a compression='bf16' trainer must carry
        its gradient traffic in bf16 — the proof the wire bytes (ICI/DCN)
        actually halve, not just an API flag. Scalar loss/acc metric
        means may legitimately reduce in f32 (`hlo_audit` excludes them
        from gradient traffic); no gradient-shaped f32 reduction may
        remain."""
        x, y = _data()
        hlo_audit.assert_program(
            lowered_step_text(_trainer("bf16"), x, y, 1, n=len(x)),
            "wire=bf16",
        )

    def test_uncompressed_step_emits_no_manual_allreduce(self):
        """Control: the default SPMD step carries no explicit collective in
        its lowered form (XLA inserts the f32 reduction at partitioning) —
        so the bf16 assertion above isn't vacuously matching shared code."""
        x, y = _data()
        hlo_audit.assert_program(
            lowered_step_text(_trainer("none"), x, y, 1, n=len(x)),
            "no-collectives",
        )


class TestNumerics:
    def test_loss_tracks_f32_path(self):
        """bf16 wire gradients + per-shard dropout draw a slightly different
        trajectory; after a few steps the losses must still agree to ~bf16
        tolerance (the reference's compression contract: lossy in the last
        bits, not in convergence)."""
        x, y = _data()
        l_bf16 = _run_steps(_trainer("bf16"), x, y)
        l_f32 = _run_steps(_trainer("none"), x, y)
        # 3%: the exact divergence depends on the jax version's dropout-rng
        # partitioning and psum lowering (measured 2.4% on jax 0.4.37,
        # <2% on current) — the contract under test is "tracks, does not
        # diverge", not a bit-level bound.
        assert abs(l_bf16 - l_f32) / max(abs(l_f32), 1e-6) < 0.03

    def test_eval_unaffected(self):
        """Compression touches gradient traffic only: evaluate() runs the
        unmodified forward path on both trainers and must agree exactly on
        identical weights. (Train each first so state exists.)"""
        x, y = _data()
        tr = _trainer("bf16")
        _run_steps(tr, x, y, n=1)
        m = tr.evaluate(x, y, batch_size=8)
        assert np.isfinite(m["loss"]) and 0.0 <= m["accuracy"] <= 1.0

    def test_batchnorm_stats_are_global(self):
        """Updated batch statistics must reflect the GLOBAL batch (pmean of
        equal-sized shard stats == global mean), matching the SPMD path's
        global-batch BN semantics."""
        x, y = _data(n=64, d=8, seed=3)
        tr_c = _trainer("bf16", module=_BNNet())
        _run_steps(tr_c, x, y, n=1)
        tr_f = _trainer("none", module=_BNNet())
        _run_steps(tr_f, x, y, n=1)
        stats_c = jax.device_get(tr_c.state.model_state["batch_stats"])
        stats_f = jax.device_get(tr_f.state.model_state["batch_stats"])
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-2, atol=2e-2),
            stats_c,
            stats_f,
        )


class TestComposition:
    def test_sharded_params_rejected_loudly(self):
        """compression + param_specs must fail at construction — never
        silently fall back to an uncompressed (or wrong-layout) reduction."""
        tx = hvt.DistributedOptimizer(optax.adam(1e-3), compression="bf16")
        with pytest.raises(ValueError, match="compression"):
            Trainer(_MLP(), tx, param_specs={})

    def test_tag_survives_multisteps(self):
        """backward_passes_per_step composes with compression: the tag
        survives, the Trainer runs the K-microbatch accumulating step, and
        only the boundary reduction is compressed (one reduction per
        optimizer step, fed a [K, G, ...] microbatch stack)."""
        tx = hvt.DistributedOptimizer(
            optax.adam(1e-2), compression="bf16", backward_passes_per_step=2
        )
        assert compression_dtype(tx) == jnp.bfloat16
        x, y = _data(n=128)
        tr = Trainer(_MLP(), tx)
        loss = tr.fit(
            x=x, y=y, batch_size=4, epochs=1, steps_per_epoch=2,
            shuffle_buffer=1, verbose=0,
        )[-1]["loss"]
        assert np.isfinite(loss)

    def test_axis_name_mode_not_tagged(self):
        """With an explicit axis_name the update_fn itself compresses (unit-
        tested in test_collectives); tagging too would double-compress."""
        tx = hvt.DistributedOptimizer(
            optax.adam(1e-2), axis_name="data", compression="bf16"
        )
        assert compression_dtype(tx) is None

    def test_none_not_tagged(self):
        tx = hvt.DistributedOptimizer(optax.adam(1e-2))
        assert compression_dtype(tx) is None
