"""Supervisor policy engine (launch/policy.py): config plumbing, the
windowed straggler detector, every ladder rung (warn → evict/promote →
budget), hang auto-triage, the oom-kill classification budget, job-spec
validation (satellite: typo'd specs fail before any process spawns),
RestartPolicy backoff edges, the warm-standby park path, and the
policy_* journal → hvt_policy_actions_total metric rendering."""

import json
import os
import sys
import threading
import time

import pytest
import yaml

from horovod_tpu.launch import job as job_mod
from horovod_tpu.launch import launcher, supervisor
from horovod_tpu.launch.policy import (
    PolicyConfig, PolicyEngine, StragglerDetector,
)
from horovod_tpu.launch.supervisor import RestartPolicy
from horovod_tpu.obs import prom as obs_prom

NO_SLEEP = lambda s: None  # noqa: E731


def _expo(samples, straggler=None, wait=None):
    """A synthetic member exposition carrying the SkewProbe gauges."""
    lines = [f"hvt_step_samples_total {samples}"]
    if straggler is not None:
        lines.append(f"hvt_straggler_rank {straggler}")
    if wait is not None:
        lines.append(f"hvt_barrier_wait_ms {wait}")
    return "\n".join(lines) + "\n"


def _fleet(samples, straggler, wait, n=2):
    """n members unanimously naming ``straggler`` at ``wait`` ms."""
    return {slot: _expo(samples, straggler, wait) for slot in range(n)}


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def tick(self, dt=2.0):
        self.t += dt


def _engine(records, config, **kwargs):
    journal = lambda name, value, **f: records.append(  # noqa: E731
        {"name": name, "value": value, **f}
    )
    clock = kwargs.pop("clock", _Clock())
    return PolicyEngine(config, journal, clock=clock, **kwargs), clock


def _by_name(records, name):
    return [r for r in records if r["name"] == name]


class TestPolicyConfig:
    def test_defaults(self):
        cfg = PolicyConfig()
        assert cfg.mode == "off" and not cfg.active and not cfg.dry_run
        assert cfg.straggler_windows == 3
        assert cfg.evict_budget == 1 and cfg.spares == 0

    def test_from_mapping_partial_and_none_keeps_default(self):
        cfg = PolicyConfig.from_mapping(
            {"mode": "dry-run", "straggler_wait_ms": "50",
             "cooldown_s": None}
        )
        assert cfg.mode == "dry-run" and cfg.dry_run and cfg.active
        assert cfg.straggler_wait_ms == 50.0
        assert cfg.cooldown_s == 60.0  # None = keep default

    def test_from_mapping_rejects_unknown_keys_loudly(self):
        with pytest.raises(ValueError) as e:
            PolicyConfig.from_mapping({"straggler_window": 2})
        # The error names the bad key AND the valid set.
        assert "straggler_window" in str(e.value)
        assert "straggler_windows" in str(e.value)

    def test_bad_mode_rejected(self):
        with pytest.raises(ValueError, match="dry-run"):
            PolicyConfig.from_mapping({"mode": "auto"})
        with pytest.raises(ValueError, match="unknown policy mode"):
            PolicyConfig.from_env({"HVT_POLICY": "bogus"})

    def test_from_env_overlay_wins(self):
        cfg = PolicyConfig.from_env({
            "HVT_POLICY": "on",
            "HVT_POLICY_STRAGGLER_WINDOWS": "5",
            "HVT_POLICY_STRAGGLER_WAIT_MS": "25.5",
            "HVT_POLICY_EVICT_BUDGET": "2",
            "HVT_POLICY_COOLDOWN_S": "7",
            "HVT_POLICY_SPARES": "1",
        })
        assert cfg.mode == "on" and cfg.active and not cfg.dry_run
        assert cfg.straggler_windows == 5
        assert cfg.straggler_wait_ms == 25.5
        assert cfg.evict_budget == 2
        assert cfg.cooldown_s == 7.0
        assert cfg.spares == 1

    def test_from_env_defaults_off(self, monkeypatch):
        monkeypatch.delenv("HVT_POLICY", raising=False)
        cfg = PolicyConfig.from_env({})
        assert cfg.mode == "off" and not cfg.active


class TestStragglerDetector:
    def test_no_fresh_window_returns_none(self):
        det = StragglerDetector(windows=2, wait_ms=100.0)
        fleet = _fleet(samples=4, straggler=1, wait=150.0)
        assert det.observe(fleet)["confirmed"]
        # Same cached scrapes again: no sample advance, no window — the
        # wall-clock poll must not inflate the streak.
        assert det.observe(fleet) is None
        assert det.streak == 1

    def test_streak_counts_fresh_windows(self):
        det = StragglerDetector(windows=2, wait_ms=100.0)
        for n, samples in enumerate((4, 8, 12), start=1):
            w = det.observe(_fleet(samples, straggler=1, wait=150.0))
            assert w["confirmed"] and w["rank"] == 1 and w["streak"] == n

    def test_single_voter_never_confirms(self):
        # One member's self-report is not cross-rank evidence — and the
        # stale-gauge survivor after a shrink-to-1 looks exactly like
        # this.
        det = StragglerDetector(windows=1, wait_ms=10.0)
        w = det.observe({0: _expo(4, straggler=1, wait=500.0)})
        assert not w["confirmed"] and w["rank"] is None

    def test_wait_threshold_gates_confirmation(self):
        det = StragglerDetector(windows=1, wait_ms=100.0)
        w = det.observe(_fleet(4, straggler=1, wait=99.0))
        assert not w["confirmed"]
        w = det.observe(_fleet(8, straggler=1, wait=100.0))
        assert w["confirmed"]

    def test_candidate_change_resets_streak(self):
        det = StragglerDetector(windows=3, wait_ms=10.0)
        assert det.observe(_fleet(4, straggler=1, wait=50.0))["streak"] == 1
        assert det.observe(_fleet(8, straggler=1, wait=50.0))["streak"] == 2
        w = det.observe(_fleet(12, straggler=0, wait=50.0))
        assert w["rank"] == 0 and w["streak"] == 1

    def test_unconfirmed_window_resets_streak(self):
        det = StragglerDetector(windows=3, wait_ms=100.0)
        assert det.observe(_fleet(4, straggler=1, wait=150.0))["streak"] == 1
        # A calm window (wait below threshold) clears the evidence.
        assert not det.observe(_fleet(8, straggler=1, wait=5.0))["confirmed"]
        assert det.observe(_fleet(12, straggler=1, wait=150.0))["streak"] == 1

    def test_majority_not_plurality(self):
        det = StragglerDetector(windows=1, wait_ms=10.0)
        members = {
            0: _expo(4, straggler=1, wait=50.0),
            1: _expo(4, straggler=1, wait=50.0),
            2: _expo(4, straggler=2, wait=50.0),
            3: _expo(4, straggler=2, wait=50.0),
        }
        w = det.observe(members)  # 2-2 split: no majority
        assert not w["confirmed"]
        members = {
            0: _expo(8, straggler=1, wait=50.0),
            1: _expo(8, straggler=1, wait=50.0),
            2: _expo(8, straggler=2, wait=50.0),
        }
        w = det.observe(members)  # 2 of 3
        assert w["confirmed"] and w["rank"] == 1 and w["voters"] == 3

    def test_torn_scrape_skipped_not_fatal(self):
        det = StragglerDetector(windows=1, wait_ms=10.0)
        members = _fleet(4, straggler=1, wait=50.0, n=2)
        members[2] = "hvt_step_samples_total not-a-float\n"
        w = det.observe(members)
        assert w["confirmed"] and w["rank"] == 1

    def test_negative_straggler_rank_is_no_vote(self):
        # SkewProbe publishes -1 when no rank stands out.
        det = StragglerDetector(windows=1, wait_ms=10.0)
        w = det.observe(_fleet(4, straggler=-1, wait=50.0))
        assert not w["confirmed"] and w["voters"] == 0


class TestPolicyEngineLadder:
    def test_warn_rung_journals_once_per_rank(self):
        records = []
        engine, clock = _engine(records, PolicyConfig.from_mapping(
            {"mode": "on", "straggler_windows": 5,
             "straggler_wait_ms": 10}
        ))
        for samples in (4, 8, 12):
            clock.tick()
            engine.poll(_fleet(samples, straggler=1, wait=50.0))
        warns = _by_name(records, "policy_warn")
        assert len(warns) == 1
        assert warns[0]["rank"] == 1 and warns[0]["outcome"] == "journaled"
        assert not _by_name(records, "policy_evict")  # streak < 5

    def test_dry_run_journals_decision_without_acting(self):
        records = []
        evicted = []
        engine, clock = _engine(
            records,
            PolicyConfig.from_mapping(
                {"mode": "dry-run", "straggler_windows": 2,
                 "straggler_wait_ms": 10}
            ),
            evict=lambda rank: evicted.append(rank) or "sigterm",
            spare_count=lambda: 1,
        )
        for samples in (4, 8, 12):
            clock.tick()
            engine.poll(_fleet(samples, straggler=1, wait=50.0))
        evicts = _by_name(records, "policy_evict")
        assert len(evicts) == 1  # decided once, not re-decided per window
        assert evicts[0]["outcome"] == "dry-run" and evicts[0]["rank"] == 1
        promotes = _by_name(records, "policy_promote")
        assert len(promotes) == 1 and promotes[0]["outcome"] == "dry-run"
        assert evicted == []           # the actuator was never touched
        assert engine.evicts_used == 1  # ... but the budget was charged

    def test_evict_rung_calls_actuator_and_promotes(self):
        records = []
        evicted = []
        engine, clock = _engine(
            records,
            PolicyConfig.from_mapping(
                {"mode": "on", "straggler_windows": 2,
                 "straggler_wait_ms": 10}
            ),
            evict=lambda rank: evicted.append(rank) or "sigterm",
            spare_count=lambda: 2,
        )
        for samples in (4, 8):
            clock.tick()
            engine.poll(_fleet(samples, straggler=1, wait=50.0))
        assert evicted == [1]
        evicts = _by_name(records, "policy_evict")
        assert len(evicts) == 1 and evicts[0]["outcome"] == "sigterm"
        assert evicts[0]["spares"] == 2
        promotes = _by_name(records, "policy_promote")
        assert len(promotes) == 1 and promotes[0]["outcome"] == "released"

    def test_no_actuator_journals_unsupported(self):
        records = []
        engine, clock = _engine(records, PolicyConfig.from_mapping(
            {"mode": "on", "straggler_windows": 1,
             "straggler_wait_ms": 10}
        ))
        clock.tick()
        engine.poll(_fleet(4, straggler=0, wait=50.0))
        evicts = _by_name(records, "policy_evict")
        assert len(evicts) == 1 and evicts[0]["outcome"] == "unsupported"

    def test_budget_exhausted_defers_to_restart_machinery(self):
        records = []
        evicted = []
        engine, clock = _engine(
            records,
            PolicyConfig.from_mapping(
                {"mode": "on", "straggler_windows": 1,
                 "straggler_wait_ms": 10, "evict_budget": 1,
                 "cooldown_s": 0}
            ),
            evict=lambda rank: evicted.append(rank) or "sigterm",
        )
        clock.tick()
        engine.poll(_fleet(4, straggler=1, wait=50.0))
        # A SECOND straggler emerges with the budget spent.
        clock.tick()
        engine.poll(_fleet(8, straggler=0, wait=50.0))
        clock.tick()
        engine.poll(_fleet(12, straggler=0, wait=50.0))
        assert evicted == [1]
        evicts = _by_name(records, "policy_evict")
        outcomes = [r["outcome"] for r in evicts]
        assert outcomes == ["sigterm", "budget-exhausted"]

    def test_cooldown_delays_second_action(self):
        records = []
        evicted = []
        engine, clock = _engine(
            records,
            PolicyConfig.from_mapping(
                {"mode": "on", "straggler_windows": 1,
                 "straggler_wait_ms": 10, "evict_budget": 2,
                 "cooldown_s": 60}
            ),
            evict=lambda rank: evicted.append(rank) or "sigterm",
        )
        clock.tick()
        engine.poll(_fleet(4, straggler=1, wait=50.0))
        assert evicted == [1]
        # Rank 0 confirmed inside the cooldown: no action yet.
        clock.tick(5.0)
        engine.poll(_fleet(8, straggler=0, wait=50.0))
        assert evicted == [1]
        # Past the cooldown (streak kept the evidence warm).
        clock.tick(60.0)
        engine.poll(_fleet(12, straggler=0, wait=50.0))
        assert evicted == [1, 0]

    def test_min_poll_throttle(self):
        records = []
        engine, clock = _engine(records, PolicyConfig.from_mapping(
            {"mode": "on", "straggler_windows": 1,
             "straggler_wait_ms": 10}
        ))
        clock.t = 10.0
        engine.poll(_fleet(4, straggler=0, wait=50.0))
        # Same instant (a 10 Hz supervise loop): the second poll is a
        # no-op even with fresh evidence queued.
        engine.poll(_fleet(8, straggler=0, wait=50.0))
        assert len(_by_name(records, "policy_evict")) == 1


class TestHangTriage:
    def _write(self, directory, label, records):
        path = os.path.join(directory, f"flight-{label}.jsonl")
        with open(path, "w") as f:
            for rec in records:
                f.write(json.dumps(rec) + "\n")

    def _ops(self, kinds):
        return [
            {"seq": i, "kind": k, "dtype": "float32", "shape": [4]}
            for i, k in enumerate(kinds)
        ]

    def test_divergence_verdict_journaled(self, tmp_path):
        self._write(tmp_path, "m0",
                    self._ops(["all_reduce", "all_reduce"]))
        self._write(tmp_path, "m1",
                    self._ops(["all_reduce", "all_gather"]))
        records = []
        engine, _ = _engine(records, PolicyConfig.from_mapping(
            {"mode": "on"}
        ))
        verdict = engine.on_hang(str(tmp_path))
        assert verdict["status"] == "diverged" and verdict["seq"] == 1
        triage = _by_name(records, "policy_triage")
        assert len(triage) == 1
        assert triage[0]["outcome"] == "diverged"
        assert triage[0]["seq"] == 1 and triage[0]["kind"] == "mismatch"
        assert "all_gather" in triage[0]["op_b"]

    def test_agreeing_records_journal_agree(self, tmp_path):
        ops = self._ops(["all_reduce", "broadcast"])
        self._write(tmp_path, "m0", ops)
        self._write(tmp_path, "m1", ops)
        records = []
        engine, _ = _engine(records, PolicyConfig.from_mapping(
            {"mode": "on"}
        ))
        assert engine.on_hang(str(tmp_path))["status"] == "agree"
        assert _by_name(records, "policy_triage")[0]["outcome"] == "agree"

    def test_single_member_is_no_verdict(self, tmp_path):
        self._write(tmp_path, "m0", self._ops(["all_reduce"]))
        records = []
        engine, _ = _engine(records, PolicyConfig.from_mapping(
            {"mode": "on"}
        ))
        assert engine.on_hang(str(tmp_path)) is None
        assert engine.on_hang(None) is None
        assert not records


class TestSpecValidation:
    def _spec(self, **job):
        return {"job": {"command": "python train.py", **job}}

    def test_valid_spec_passes(self):
        assert job_mod.validate_spec(self._spec(
            restart={"max_restarts": 2},
            elastic={"min_ranks": 1},
            policy={"mode": "dry-run"},
        )) == []

    def test_typoed_policy_key_names_key_and_valid_set(self):
        errors = job_mod.validate_spec(self._spec(
            restart={}, policy={"evict_budgte": 1}
        ))
        assert len(errors) == 1
        assert "evict_budgte" in errors[0] and "evict_budget" in errors[0]
        assert errors[0].startswith("job policy:")

    def test_typoed_restart_key_fails(self):
        errors = job_mod.validate_spec(self._spec(
            restart={"max_restart": 3}
        ))
        assert len(errors) == 1
        assert "max_restart" in errors[0] and "max_restarts" in errors[0]

    def test_non_mapping_blocks_fail(self):
        errors = job_mod.validate_spec(self._spec(restart=True))
        assert errors and "must be a mapping" in errors[0]

    def test_policy_without_supervision_fails(self):
        errors = job_mod.validate_spec(self._spec(policy={"mode": "on"}))
        assert errors and "restart: or" in errors[0]

    def test_missing_command_fails(self):
        assert job_mod.validate_spec({"job": {"nprocs": 2}}) == [
            "job command: is required"
        ]
        assert job_mod.validate_spec({"job": None}) != []
        assert job_mod.validate_spec([]) != []

    def test_run_job_rejects_before_side_effects(self, tmp_path, capsys):
        # `fresh: true` + an invalid block: the model dir must SURVIVE —
        # validation runs before the wipe (or any spawn).
        model_dir = tmp_path / "models"
        model_dir.mkdir()
        sentinel = model_dir / "precious.ckpt"
        sentinel.write_text("do not wipe")
        spec = {
            "job": {
                "command": "python train.py",
                "fresh": True,
                "restart": {},
                "policy": {"mode": "on", "bogus_knob": 1},
                "env": {"PS_MODEL_PATH": str(model_dir)},
            },
        }
        spec_path = tmp_path / "bad.yaml"
        spec_path.write_text(yaml.safe_dump(spec))
        assert job_mod.run_job(str(spec_path)) == 1
        assert sentinel.exists()
        out = capsys.readouterr().out
        assert "bogus_knob" in out and str(spec_path) in out


class TestRestartPolicyEdges:
    def test_oom_kill_budget_key_accepted(self):
        p = RestartPolicy.from_mapping({"oom_kill_budget": "2"})
        assert p.oom_kill_budget == 2
        assert RestartPolicy().oom_kill_budget is None
        with pytest.raises(ValueError, match="oom_budget"):
            RestartPolicy.from_mapping({"oom_budget": 2})

    def test_backoff_max_clamps_growth(self, tmp_path):
        # A deterministic crash loop: backoff doubles per restart but
        # must clamp at backoff_max. Sleeps observed: [10, 15, 15].
        log = tmp_path / "restarts.jsonl"
        sleeps = []
        code = supervisor.supervise(
            lambda: launcher.start_local(
                1, [sys.executable, "-c", "import sys; sys.exit(3)"],
                tag_output=False,
            ),
            policy=RestartPolicy(
                max_restarts=3, backoff=10.0, backoff_factor=1.5,
                backoff_max=15.0, grace_seconds=5.0,
            ),
            log_path=str(log), sleep=sleeps.append, verbose=False,
        )
        assert code == 3
        assert sleeps == [10.0, 15.0, 15.0]
        backoffs = [
            r["backoff_s"] for r in _journal(log) if r["name"] == "restarts"
        ]
        assert backoffs == [10.0, 15.0, 15.0]

    def test_budget_resets_on_progress(self, tmp_path):
        # Each attempt writes a FRESH checkpoint then crashes; with
        # max_restarts=1 the run still reaches attempt 3's success —
        # progress must refill the budget (and reset the backoff).
        model_dir = tmp_path / "models"
        model_dir.mkdir()
        script = tmp_path / "child.py"
        script.write_text(
            "import os, sys, time\n"
            "d = os.environ['PS_MODEL_PATH']\n"
            "n = len([f for f in os.listdir(d) if 'checkpoint' in f])\n"
            "open(os.path.join(d, f'checkpoint-{n + 1}.msgpack'), 'w')"
            ".write('x')\n"
            "sys.exit(0 if n + 1 >= 3 else 1)\n"
        )
        log = tmp_path / "restarts.jsonl"
        sleeps = []
        code = supervisor.supervise(
            lambda: launcher.start_local(
                1, [sys.executable, str(script)],
                env={"PS_MODEL_PATH": str(model_dir)}, tag_output=False,
            ),
            policy=RestartPolicy(max_restarts=1, backoff=2.0,
                                 backoff_factor=2.0, grace_seconds=5.0),
            model_dir=str(model_dir), log_path=str(log),
            sleep=sleeps.append, verbose=False,
        )
        assert code == 0
        restarts = [r for r in _journal(log) if r["name"] == "restarts"]
        assert len(restarts) == 2
        assert all(r["progressed"] for r in restarts)
        # Backoff reset with the budget: both sleeps at the base value.
        assert sleeps == [2.0, 2.0]
        assert not [
            r for r in _journal(log) if r["name"] == "supervisor_gave_up"
        ]

    def test_startup_timeout_defaults_to_10x_heartbeat(
        self, tmp_path, monkeypatch
    ):
        captured = {}

        def fake_check(heartbeat_dir, timeout, startup_timeout):
            captured["timeout"] = timeout
            captured["startup"] = startup_timeout
            return lambda: False

        monkeypatch.setattr(
            supervisor, "_throttled_staleness_check", fake_check
        )
        code = supervisor.supervise(
            lambda: launcher.start_local(
                1, [sys.executable, "-c", "pass"], tag_output=False
            ),
            policy=RestartPolicy(heartbeat_timeout=2.0, grace_seconds=5.0),
            heartbeat_dir=str(tmp_path / "hb"),
            log_path=str(tmp_path / "r.jsonl"),
            sleep=NO_SLEEP, verbose=False,
        )
        assert code == 0
        assert captured["timeout"] == 2.0
        assert captured["startup"] == 20.0  # the documented 10x default
        # An explicit startup_timeout wins over the derived default.
        supervisor.supervise(
            lambda: launcher.start_local(
                1, [sys.executable, "-c", "pass"], tag_output=False
            ),
            policy=RestartPolicy(heartbeat_timeout=2.0,
                                 startup_timeout=7.0, grace_seconds=5.0),
            heartbeat_dir=str(tmp_path / "hb2"),
            log_path=str(tmp_path / "r2.jsonl"),
            sleep=NO_SLEEP, verbose=False,
        )
        assert captured["startup"] == 7.0

    def test_oom_budget_gives_up_before_restart_budget(self, tmp_path):
        # SIGKILL-self loop: oom_kill_budget=1 must stop it after ONE
        # oom restart even with max_restarts=5 left.
        log = tmp_path / "restarts.jsonl"
        code = supervisor.supervise(
            lambda: launcher.start_local(
                1, [sys.executable, "-c",
                    "import os, signal; os.kill(os.getpid(), "
                    "signal.SIGKILL)"],
                tag_output=False,
            ),
            policy=RestartPolicy(max_restarts=5, backoff=0.0,
                                 oom_kill_budget=1, grace_seconds=5.0),
            log_path=str(log), sleep=NO_SLEEP, verbose=False,
        )
        assert code == 137
        records = _journal(log)
        restarts = [r for r in records if r["name"] == "restarts"]
        assert len(restarts) == 1
        assert restarts[0]["kind"] == "oom-kill"
        gave_up = [r for r in records if r["name"] == "supervisor_gave_up"]
        assert len(gave_up) == 1
        assert gave_up[0]["budget"] == "oom-kill"
        assert gave_up[0]["kind"] == "oom-kill"


class TestPolicyMetrics:
    def test_journal_renders_action_outcome_counters(self, tmp_path):
        log = supervisor.RestartLog(str(tmp_path / "restarts.jsonl"))
        log.write("policy_warn", 1.0, mode="on", outcome="journaled",
                  rank=1)
        log.write("policy_evict", 1.0, mode="on", outcome="sigterm",
                  rank=1)
        log.write("policy_evict", 1.0, mode="on",
                  outcome="budget-exhausted", rank=0)
        log.write("policy_triage", 1.0, mode="on", outcome="diverged",
                  seq=7)
        text = obs_prom.render(
            supervisor.supervisor_metrics(log.path, None, None, None)
        )
        assert ('hvt_policy_actions_total{action="warn",'
                'outcome="journaled"} 1') in text
        assert ('hvt_policy_actions_total{action="evict",'
                'outcome="sigterm"} 1') in text
        assert ('hvt_policy_actions_total{action="evict",'
                'outcome="budget-exhausted"} 1') in text
        assert ('hvt_policy_actions_total{action="triage",'
                'outcome="diverged"} 1') in text


class TestSparePark:
    def test_world_full_parks_then_joins(self, monkeypatch):
        from horovod_tpu.elastic.coordinator import (
            Coordinator, ElasticClient, ElasticError,
        )

        coord = Coordinator(
            expected=1, max_ranks=1, rendezvous_timeout=10.0
        ).start()
        try:
            first = ElasticClient(coord.address, "a")
            assert first.sync().size == 1
            # Without the spare flag, a full world is a hard error.
            with pytest.raises(ElasticError, match="world is full"):
                ElasticClient(coord.address, "b").sync()
            # With it, the spare parks — then joins once a slot frees.
            monkeypatch.setenv("HVT_ELASTIC_SPARE", "1")
            result = {}
            t = threading.Thread(
                target=lambda: result.update(
                    world=ElasticClient(coord.address, "b").sync(
                        timeout=30.0
                    )
                )
            )
            t.start()
            time.sleep(1.2)  # at least one rejected knock while parked
            assert "world" not in result  # still parked, still alive
            first.leave("evicted")
            t.join(30.0)
            assert result["world"].size == 1
            assert result["world"].rank == 0
        finally:
            coord.stop()

    def test_park_respects_deadline(self, monkeypatch):
        from horovod_tpu.elastic.coordinator import (
            Coordinator, ElasticClient, ElasticError,
        )

        coord = Coordinator(
            expected=1, max_ranks=1, rendezvous_timeout=10.0
        ).start()
        try:
            ElasticClient(coord.address, "a").sync()
            monkeypatch.setenv("HVT_ELASTIC_SPARE", "1")
            t0 = time.monotonic()
            with pytest.raises((ElasticError, OSError)):
                ElasticClient(coord.address, "b").sync(timeout=1.5)
            assert time.monotonic() - t0 < 10.0
        finally:
            coord.stop()


def _journal(log_path):
    with open(log_path) as f:
        return [json.loads(line) for line in f if line.strip()]


# The full actuator loop needs members that speak the coordinator wire
# protocol AND serve a trainer-shaped /metrics exposition (the fleet
# poller feeds the engine from those scrapes) — import-free, like
# test_elastic.py's FAKE_WORKER. The exporter starts only after first
# admission, so straggler votes never precede a rank the actuator can
# find; the sample counter advances per scrape, so every engine poll
# sees a fresh window. Runs until FAKE_DONE_FILE appears (the TEST
# decides when the scenario is over), a SIGTERM turns into the elastic
# callback's clean leave(sigterm)/exit-143, and a parked spare retries a
# full world exactly like `ElasticClient.sync`.
POLICY_WORKER = """
import json, os, signal, socket, sys, threading, time
from types import SimpleNamespace

member = os.environ["HVT_ELASTIC_MEMBER"]
slot = int(os.environ["HVT_LOCAL_RANK"])
host, port = os.environ["HVT_ELASTIC_COORDINATOR"].rsplit(":", 1)
spare_park = bool(os.environ.get("HVT_ELASTIC_SPARE"))


class MiniClient:
    def _call(self, **msg):
        with socket.create_connection((host, int(port)), timeout=60) as s:
            s.sendall(json.dumps(msg).encode() + b"\\n")
            buf = b""
            while not buf.endswith(b"\\n"):
                chunk = s.recv(65536)
                if not chunk:
                    raise OSError("closed")
                buf += chunk
        reply = json.loads(buf)
        if "error" in reply:
            raise RuntimeError(f"coordinator error: {reply['error']}")
        return reply

    def sync(self, progress=-1):
        while True:
            try:
                r = self._call(cmd="sync", member=member,
                               host="127.0.0.1", progress=progress)
            except RuntimeError as e:
                if spare_park and "world is full" in str(e):
                    time.sleep(0.5)
                    continue
                raise
            return SimpleNamespace(generation=r["generation"])

    def beat(self, progress=None):
        return self._call(cmd="beat", member=member,
                          progress=progress)["generation"]

    def leave(self, reason):
        self._call(cmd="leave", member=member, reason=reason)


flag = {"term": False}
signal.signal(
    signal.SIGTERM, lambda *a: flag.__setitem__("term", True)
)

client = MiniClient()
world = client.sync()

import http.server
count = [0]


class H(http.server.BaseHTTPRequestHandler):
    def do_GET(self):
        count[0] += 1
        body = (
            "hvt_step_samples_total %d\\n" % count[0]
            + "hvt_straggler_rank %s\\n"
            % os.environ.get("FAKE_STRAGGLER_RANK", "-1")
            + "hvt_barrier_wait_ms %s\\n"
            % os.environ.get("FAKE_WAIT_MS", "0")
        ).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass


srv = http.server.HTTPServer(
    ("127.0.0.1", int(os.environ["HVT_METRICS_PORT"]) + slot), H
)
threading.Thread(target=srv.serve_forever, daemon=True).start()

done_file = os.environ["FAKE_DONE_FILE"]
deadline = time.monotonic() + 120  # leak guard; the test drives done
progress = 0
while time.monotonic() < deadline:
    if flag["term"]:
        client.leave("sigterm")
        sys.exit(143)
    if os.path.exists(done_file):
        client.leave("done")
        print("POLICY-WORKER-DONE " + member, flush=True)
        sys.exit(0)
    progress += 1
    if client.beat(progress=progress) != world.generation:
        world = client.sync(progress=progress)
    time.sleep(0.1)
sys.exit(3)
"""


def _write_policy_worker(tmp_path):
    import textwrap

    path = tmp_path / "policy_worker.py"
    path.write_text(textwrap.dedent(POLICY_WORKER))
    return [sys.executable, str(path)]


def _port_base(n):
    """A window of n consecutive free loopback ports (member exporters
    bind HVT_METRICS_PORT + slot, so the window must be contiguous)."""
    import socket as socket_mod

    for base in range(29850, 60000, 41):
        socks = []
        try:
            for i in range(n):
                s = socket_mod.socket()
                s.bind(("127.0.0.1", base + i))
                socks.append(s)
            return base
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError("no free port window")


def _run_elastic_until(tmp_path, done_file, journal_path, trigger,
                       timeout=60.0, **kwargs):
    """Drive supervise_elastic in a thread until ``trigger(records)``
    holds on the journal (then release the workers via ``done_file``);
    returns (exit code, journal records)."""
    result = {}
    t = threading.Thread(
        target=lambda: result.update(
            code=supervisor.supervise_elastic(**kwargs)
        )
    )
    t.start()
    fired = False
    deadline = time.monotonic() + timeout
    try:
        while time.monotonic() < deadline and t.is_alive():
            if not fired and os.path.exists(journal_path) and trigger(
                _journal(journal_path)
            ):
                fired = True
                open(done_file, "w").close()
            time.sleep(0.1)
    finally:
        # Always release the workers — a failed trigger must not leave
        # the fleet (and the test) wedged for the worker's leak guard.
        open(done_file, "w").close()
        t.join(60.0)
    assert fired, (
        f"trigger never held on the journal within {timeout}s: "
        f"{_journal(journal_path) if os.path.exists(journal_path) else []}"
    )
    assert not t.is_alive(), "supervise_elastic did not finish"
    return result["code"], _journal(journal_path)


class TestSuperviseElasticPolicy:
    """The closed loop against real member processes: fleet poller →
    detector → SIGTERM actuator → clean leave → shrink (or spare
    promotion), with zero restart-budget spend."""

    def _env(self, tmp_path, base, done_file, straggler="1"):
        return {
            "HVT_METRICS_PORT": str(base),
            "HVT_FLEET_POLL_S": "0.2",
            "FAKE_DONE_FILE": str(done_file),
            "FAKE_STRAGGLER_RANK": straggler,
            "FAKE_WAIT_MS": "150",
        }

    def _pcfg(self, mode, **over):
        return PolicyConfig.from_mapping({
            "mode": mode, "straggler_windows": 2,
            "straggler_wait_ms": 50, "evict_budget": 1,
            "cooldown_s": 1, **over,
        })

    def test_straggler_evicted_and_shrunk_without_restart_spend(
        self, tmp_path
    ):
        argv = _write_policy_worker(tmp_path)
        base = _port_base(3)
        done_file = tmp_path / "done"
        log = tmp_path / "restarts.jsonl"
        code, records = _run_elastic_until(
            tmp_path, done_file, str(log),
            trigger=lambda rs: any(
                r["name"] == "policy_evict" for r in rs
            ) and any(r["name"] == "shrink" for r in rs),
            nprocs=2, argv=argv,
            env=self._env(tmp_path, base, done_file),
            policy=RestartPolicy(max_restarts=3, backoff=0.1,
                                 grace_seconds=5.0),
            elastic=supervisor.ElasticPolicy(min_ranks=1, max_ranks=2,
                                             rendezvous_timeout=20.0),
            log_path=str(log), status_port=base + 2,
            policy_config=self._pcfg("on"),
            tag_output=False,
        )
        assert code == 0
        evicts = [r for r in records if r["name"] == "policy_evict"]
        assert evicts and evicts[0]["outcome"] == "sigterm"
        assert evicts[0]["rank"] == 1
        assert any(r["name"] == "policy_warn" for r in records)
        assert any(r["name"] == "shrink" for r in records)
        # The whole point: the rescue spent NO restart budget.
        assert not [r for r in records if r["name"] == "restarts"]
        assert not [
            r for r in records if r["name"] == "supervisor_gave_up"
        ]

    def test_dry_run_journals_the_decision_but_keeps_the_fleet(
        self, tmp_path
    ):
        argv = _write_policy_worker(tmp_path)
        base = _port_base(3)
        done_file = tmp_path / "done"
        log = tmp_path / "restarts.jsonl"
        code, records = _run_elastic_until(
            tmp_path, done_file, str(log),
            trigger=lambda rs: any(
                r["name"] == "policy_evict" for r in rs
            ),
            nprocs=2, argv=argv,
            env=self._env(tmp_path, base, done_file),
            policy=RestartPolicy(max_restarts=3, backoff=0.1,
                                 grace_seconds=5.0),
            elastic=supervisor.ElasticPolicy(min_ranks=1, max_ranks=2,
                                             rendezvous_timeout=20.0),
            log_path=str(log), status_port=base + 2,
            policy_config=self._pcfg("dry-run"),
            tag_output=False,
        )
        assert code == 0
        evicts = [r for r in records if r["name"] == "policy_evict"]
        assert evicts and evicts[0]["outcome"] == "dry-run"
        assert evicts[0]["rank"] == 1
        # Nothing acted: no leave-shrink, no restarts — both members ran
        # to the release signal.
        assert not [r for r in records if r["name"] == "shrink"]
        assert not [r for r in records if r["name"] == "restarts"]

    def test_spare_promotion_preserves_world_size(self, tmp_path):
        argv = _write_policy_worker(tmp_path)
        base = _port_base(4)
        done_file = tmp_path / "done"
        log = tmp_path / "restarts.jsonl"

        def trigger(rs):
            promoted = any(r["name"] == "policy_promote" for r in rs)
            # Wait for the freed slot to be refilled (a settle at full
            # size AFTER the eviction) before releasing the workers.
            if not promoted:
                return False
            evict_at = next(
                i for i, r in enumerate(rs)
                if r["name"] == "policy_evict"
            )
            return any(
                r["name"] in ("grow", "steady") and r.get("size") == 2
                for r in rs[evict_at:]
            )

        code, records = _run_elastic_until(
            tmp_path, done_file, str(log), trigger,
            nprocs=2, argv=argv,
            env=self._env(tmp_path, base, done_file),
            policy=RestartPolicy(max_restarts=3, backoff=0.1,
                                 grace_seconds=5.0),
            elastic=supervisor.ElasticPolicy(min_ranks=1, max_ranks=2,
                                             rendezvous_timeout=20.0),
            log_path=str(log), status_port=base + 3,
            policy_config=self._pcfg("on", spares=1),
            tag_output=False,
        )
        assert code == 0
        evicts = [r for r in records if r["name"] == "policy_evict"]
        assert evicts and evicts[0]["outcome"] == "sigterm"
        promotes = [r for r in records if r["name"] == "policy_promote"]
        assert promotes and promotes[0]["outcome"] == "released"
        assert promotes[0]["spares"] >= 1
        # World size was PRESERVED (the spare filled the freed slot) and
        # no restart budget was spent doing it.
        evict_at = records.index(evicts[0])
        assert any(
            r["name"] in ("grow", "steady") and r.get("size") == 2
            for r in records[evict_at:]
        )
        assert not [r for r in records if r["name"] == "restarts"]
