"""Checkpoint/resume + serving export parity (SURVEY.md §5.4)."""

import os

import jax
import numpy as np
import optax
import pytest

import horovod_tpu as hvt
from horovod_tpu import checkpoint
from horovod_tpu.models import MnistCNN


def _rewrite_index(idx_path, mutate):
    """Hand-edit a sharded checkpoint's index.json (topology-faking tests)
    AND refresh its digest sidecar — the index is integrity-verified like
    every payload file, so a bare rewrite would read as corruption."""
    import hashlib
    import json

    with open(idx_path) as f:
        idx = json.load(f)
    mutate(idx)
    data = json.dumps(idx).encode()
    with open(idx_path, "wb") as f:
        f.write(data)
    with open(idx_path + checkpoint.DIGEST_SUFFIX, "w") as f:
        f.write(hashlib.sha256(data).hexdigest() + "\n")


@pytest.fixture()
def trainer_and_data():
    hvt.init()
    rng = np.random.RandomState(0)
    x = rng.rand(64, 28, 28, 1).astype(np.float32)
    y = rng.randint(0, 10, 64).astype(np.int64)
    trainer = hvt.Trainer(MnistCNN(), optax.adam(1e-3), seed=0)
    trainer.fit(x=x, y=y, batch_size=4, epochs=1)
    return trainer, x, y


def test_save_restore_roundtrip(trainer_and_data, tmp_path):
    trainer, x, y = trainer_and_data
    path = checkpoint.save(str(tmp_path / "state.msgpack"), trainer.state)
    fresh = hvt.Trainer(MnistCNN(), optax.adam(1e-3), seed=123)
    fresh.build(x)
    restored = checkpoint.restore(path, fresh.state)
    for a, b in zip(jax.tree.leaves(jax.device_get(trainer.state.params)),
                    jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(a, b)
    # optimizer slots restored too (the 'global variables' include them, §7.3)
    assert int(restored.step) == int(trainer.state.step)


def test_resume_produces_identical_eval(trainer_and_data, tmp_path):
    trainer, x, y = trainer_and_data
    path = checkpoint.save(str(tmp_path / "s.msgpack"), trainer.state)
    fresh = hvt.Trainer(MnistCNN(), optax.adam(1e-3), seed=9)
    fresh.build(x)
    fresh.state = checkpoint.broadcast_parameters(
        checkpoint.restore(path, fresh.state), mesh=fresh.mesh
    )
    a = trainer.evaluate(x, y, batch_size=4)
    b = fresh.evaluate(x, y, batch_size=4)
    assert a["loss"] == pytest.approx(b["loss"], rel=1e-5)


def test_latest_checkpoint_selection(tmp_path, trainer_and_data):
    trainer, _, _ = trainer_and_data
    for epoch in (1, 2, 10):
        checkpoint.save_checkpoint(str(tmp_path), trainer.state, epoch)
    assert checkpoint.latest_checkpoint(str(tmp_path)).endswith("checkpoint-10.msgpack")
    state, epoch = checkpoint.restore_latest_and_broadcast(
        str(tmp_path), trainer.state, mesh=trainer.mesh
    )
    assert epoch == 10


def test_restore_latest_empty_dir(tmp_path, trainer_and_data):
    trainer, _, _ = trainer_and_data
    state, epoch = checkpoint.restore_latest_and_broadcast(
        str(tmp_path / "nope"), trainer.state
    )
    assert epoch == 0


def test_serving_export_roundtrip(trainer_and_data, tmp_path):
    """Timestamped dir + input->prob signature + reloadable compiled fn
    (mnist_keras.py:126-140 parity, TF-free)."""
    trainer, x, _ = trainer_and_data
    params = jax.device_get(trainer.state.params)

    def apply_fn(p, inp):
        return trainer.module.apply({"params": p}, inp, train=False)

    out_dir = checkpoint.export_serving(
        str(tmp_path), apply_fn, params,
        input_shape=(1, 28, 28, 1), timestamp="20260729-000000",
    )
    assert out_dir.endswith("20260729-000000")
    assert os.path.exists(os.path.join(out_dir, "model.stablehlo"))
    assert os.path.exists(os.path.join(out_dir, "signature.json"))
    serve = checkpoint.load_serving(out_dir)
    probs = np.asarray(serve(x[:1]))
    expected = trainer.predict(x[:1], batch_size=1)
    np.testing.assert_allclose(probs, expected[:1], rtol=1e-5, atol=1e-6)


def test_savedmodel_export_loads_in_tf(trainer_and_data, tmp_path):
    """format='savedmodel' (round 3): the exported artifact must load with
    TF's OWN loader, expose the reference's serving signature (input→prob,
    mnist_keras.py:126-140), accept a different batch size (polymorphic
    batch dim), and agree with trainer.predict."""
    tf = pytest.importorskip("tensorflow")
    trainer, x, _ = trainer_and_data
    params = jax.device_get(trainer.state.params)

    def apply_fn(p, inp):
        return trainer.module.apply({"params": p}, inp, train=False)

    out_dir = checkpoint.export_serving(
        str(tmp_path), apply_fn, params,
        input_shape=(1, 28, 28, 1), timestamp="20260730-000000",
        format="savedmodel",
    )
    assert os.path.exists(os.path.join(out_dir, "saved_model.pb"))
    loaded = tf.saved_model.load(out_dir)
    sig = loaded.signatures["serving_default"]
    out = sig(input=tf.constant(x[:4]))
    assert set(out.keys()) == {"prob"}
    probs = out["prob"].numpy()
    np.testing.assert_allclose(probs.sum(-1), 1.0, rtol=1e-5)
    expected = trainer.predict(x[:4], batch_size=4)
    np.testing.assert_allclose(probs, expected, rtol=1e-4, atol=1e-5)


def test_export_unknown_format_rejected(trainer_and_data, tmp_path):
    trainer, x, _ = trainer_and_data
    with pytest.raises(ValueError, match="format"):
        checkpoint.export_serving(
            str(tmp_path),
            lambda p, inp: trainer.module.apply({"params": p}, inp),
            trainer.state.params, input_shape=(1, 28, 28, 1),
            format="onnx",
        )


def test_save_async_matches_sync_and_survives_donation(trainer_and_data, tmp_path):
    """Async save must write byte-identical content to sync save, from a
    device snapshot that outlives the live state (whose buffers the next
    train step donates away)."""
    trainer, _, _ = trainer_and_data
    sync_path = str(tmp_path / "sync.msgpack")
    async_path = str(tmp_path / "async.msgpack")
    checkpoint.save(sync_path, trainer.state)
    t = checkpoint.save_async(async_path, trainer.state)
    # Simulate the donation hazard: delete the live buffers immediately.
    for leaf in jax.tree.leaves(trainer.state):
        leaf.delete()
    t.join(timeout=30)
    assert not t.is_alive()
    assert open(async_path, "rb").read() == open(sync_path, "rb").read()


def test_model_checkpoint_async_orders_writes(tmp_path):
    """async_save=True: per-epoch files land in order and are all complete
    at train end."""
    import flax.linen as nn

    class Probe(nn.Module):
        @nn.compact
        def __call__(self, x, *, train=False):
            import jax.numpy as jnp

            return nn.Dense(10)(x.reshape((x.shape[0], -1)).astype(jnp.float32))

    rng = np.random.RandomState(0)
    x = rng.rand(64, 8, 8, 1).astype(np.float32)
    y = rng.randint(0, 10, 64).astype(np.int32)
    trainer = hvt.Trainer(Probe(), hvt.DistributedOptimizer(optax.sgd(0.01)))
    cb = hvt.callbacks.ModelCheckpoint(
        str(tmp_path / "checkpoint-{epoch}.msgpack"), async_save=True
    )
    trainer.fit(
        x=x, y=y, batch_size=4, epochs=3, steps_per_epoch=2,
        callbacks=[cb], verbose=0,
    )
    for e in (1, 2, 3):
        p = tmp_path / f"checkpoint-{e}.msgpack"
        assert p.exists() and p.stat().st_size > 0
    # Epoch-3 checkpoint restores into the final state's structure.
    restored = checkpoint.restore(
        str(tmp_path / "checkpoint-3.msgpack"), trainer.state
    )
    assert int(restored.step) == 6


class TestShardedCheckpoint:
    """The distributed checkpoint format (VERDICT r2 #1): per-process shard
    files + index, restore re-placing by the template's shardings. Exercised
    here single-process on the 8-device mesh (format + placement mechanics);
    the cross-process save/kill/resume proof lives in
    test_multiprocess.py::TestModelParallelCheckpointResume."""

    def _mesh(self):
        from horovod_tpu.parallel import mesh as mesh_lib

        return mesh_lib.build_mesh(mesh_lib.MeshSpec(data=4, model=2))

    def _state(self, mesh, fill):
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        def put(val, *axes):
            return jax.device_put(val, NamedSharding(mesh, P(*axes)))

        rng = np.random.RandomState(3 if fill else 7)

        def arr(*shape):
            a = rng.rand(*shape).astype(np.float32)
            return a if fill else np.zeros_like(a)

        return {
            "w_row": put(arr(8, 16), "data", None),
            "w_col": put(arr(16, 8), None, "model"),
            "w_2d": put(arr(8, 8), "data", "model"),
            "bias": put(arr(16)),  # replicated
            "step": put(jnp.asarray(123 if fill else 0)),  # 0-d
            "host": np.int64(5 if fill else 0),  # non-jax leaf
        }

    def test_roundtrip_preserves_values_and_shardings(self, tmp_path):
        mesh = self._mesh()
        state = self._state(mesh, fill=True)
        path = checkpoint.save_sharded(str(tmp_path / "c.shards"), state)
        assert checkpoint._sharded_complete(path)
        restored = checkpoint.restore_sharded(path, self._state(mesh, fill=False))
        for k in state:
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(restored[k])),
                np.asarray(jax.device_get(state[k])),
            )
        for k in ("w_row", "w_col", "w_2d", "bias", "step"):
            assert restored[k].sharding == state[k].sharding

    def test_each_global_piece_stored_once(self, tmp_path):
        """replica_id==0 dedup: total stored bytes for a replicated leaf are
        ONE copy, and for sharded leaves exactly the global array."""
        from flax import serialization

        mesh = self._mesh()
        state = self._state(mesh, fill=True)
        path = checkpoint.save_sharded(str(tmp_path / "c.shards"), state)
        with open(os.path.join(path, "shard-0.msgpack"), "rb") as f:
            store = serialization.msgpack_restore(f.read())
        leaves, _ = jax.tree_util.tree_flatten(state)
        by_leaf = {}
        for key, val in store.items():
            idx = int(key.split("|")[0])
            by_leaf[idx] = by_leaf.get(idx, 0) + np.asarray(val).size
        for i, leaf in enumerate(leaves):
            assert by_leaf[i] == np.asarray(leaf).size  # once, exactly

    def test_incomplete_sharded_dir_is_skipped(self, tmp_path):
        mesh = self._mesh()
        state = self._state(mesh, fill=True)
        checkpoint.save_checkpoint(str(tmp_path), state, 1)  # single-proc -> file
        sh = checkpoint.save_sharded(str(tmp_path / "checkpoint-2.shards"), state)
        assert checkpoint.latest_checkpoint(str(tmp_path)).endswith(
            "checkpoint-2.shards"
        )
        os.remove(os.path.join(sh, "shard-0.msgpack"))  # tear it
        assert checkpoint.latest_checkpoint(str(tmp_path)).endswith(
            "checkpoint-1.msgpack"
        )

    def test_restore_routes_directories(self, tmp_path):
        mesh = self._mesh()
        state = self._state(mesh, fill=True)
        path = checkpoint.save_sharded(str(tmp_path / "c.shards"), state)
        restored = checkpoint.restore(path, self._state(mesh, fill=False))
        np.testing.assert_array_equal(
            jax.device_get(restored["w_2d"]), jax.device_get(state["w_2d"])
        )

    def test_layout_mismatch_is_loud(self, tmp_path):
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self._mesh()
        state = self._state(mesh, fill=True)
        path = checkpoint.save_sharded(str(tmp_path / "c.shards"), state)
        template = self._state(mesh, fill=False)
        # Resume under a DIFFERENT layout for w_row: model-sharded columns.
        template["w_row"] = jax.device_put(
            np.zeros((8, 16), np.float32), NamedSharding(mesh, P(None, "model"))
        )
        with pytest.raises(ValueError, match="different mesh or sharding"):
            checkpoint.restore_sharded(path, template)

    def test_structure_mismatch_is_loud(self, tmp_path):
        mesh = self._mesh()
        state = self._state(mesh, fill=True)
        path = checkpoint.save_sharded(str(tmp_path / "c.shards"), state)
        template = self._state(mesh, fill=False)
        del template["bias"]
        with pytest.raises(ValueError, match="structure changed"):
            checkpoint.restore_sharded(path, template)

    def test_renamed_leaf_is_loud(self, tmp_path):
        """Same leaf count, same shapes, different NAME: positional shard
        keys would silently restore the wrong weights without the
        leaf-name validation."""
        mesh = self._mesh()
        state = self._state(mesh, fill=True)
        path = checkpoint.save_sharded(str(tmp_path / "c.shards"), state)
        template = self._state(mesh, fill=False)
        template["aaa_renamed"] = template.pop("bias")  # same shape/sharding
        with pytest.raises(ValueError, match="leaf names differ"):
            checkpoint.restore_sharded(path, template)

    def test_save_async_refuses_cross_process_sharded_loudly(self):
        """The guard must fire on the CALLER thread, before jnp.copy touches
        a non-fully-addressable array (single-process states are always
        host-syncable, so fake the predicate)."""
        import unittest.mock as mock

        with mock.patch.object(
            checkpoint, "is_cross_process_sharded", return_value=True
        ):
            with pytest.raises(ValueError, match="save_sharded_async"):
                checkpoint.save_async("/tmp/nope.msgpack", {"w": np.ones(2)})
            with pytest.raises(ValueError, match="save_sharded"):
                checkpoint.save("/tmp/nope.msgpack", {"w": np.ones(2)})

    def test_resume_discards_future_checkpoints(self, tmp_path):
        """Resume at epoch N deletes artifacts for epochs > N: a torn sharded
        dir from the crash must not survive to mix shard generations with the
        retrained epoch's re-save (the silent-corruption scenario)."""
        mesh = self._mesh()
        state = self._state(mesh, fill=True)
        checkpoint.save_sharded(str(tmp_path / "checkpoint-2.shards"), state)
        torn = checkpoint.save_sharded(
            str(tmp_path / "checkpoint-3.shards"), state
        )
        os.remove(os.path.join(torn, "shard-0.msgpack"))
        restored, epoch = checkpoint.restore_latest_and_broadcast(
            str(tmp_path), self._state(mesh, fill=False)
        )
        assert epoch == 2
        np.testing.assert_array_equal(
            jax.device_get(restored["w_2d"]), jax.device_get(state["w_2d"])
        )
        assert not (tmp_path / "checkpoint-3.shards").exists()

    def test_torn_only_directory_is_loud_on_resume(self, tmp_path):
        """A directory holding ONLY incomplete sharded checkpoints (the
        signature of a rank-gated saver on a model-parallel run — or a crash
        during the very first save) must raise, never silently restart from
        epoch 0 discarding all progress."""
        mesh = self._mesh()
        state = self._state(mesh, fill=True)
        torn = checkpoint.save_sharded(
            str(tmp_path / "checkpoint-1.shards"), state
        )
        os.remove(os.path.join(torn, checkpoint.INDEX_FILE))
        with pytest.raises(RuntimeError, match="EVERY process"):
            checkpoint.restore_latest_and_broadcast(
                str(tmp_path), self._state(mesh, fill=False)
            )

    def test_process_count_mismatch_is_loud(self, tmp_path):
        """Resuming a sharded checkpoint under a different process topology
        must raise the designed ValueError on every rank — not leak a
        FileNotFoundError from a missing shard file on some ranks only."""
        mesh = self._mesh()
        state = self._state(mesh, fill=True)
        path = checkpoint.save_sharded(str(tmp_path / "c.shards"), state)
        idx_path = os.path.join(path, checkpoint.INDEX_FILE)
        # pretend it was saved by a 2-process run
        _rewrite_index(idx_path, lambda idx: idx.update(n_processes=2))
        # _sharded_complete now wants shard-1 too; satisfy it so the check
        # under test (restore_sharded's topology guard) is what fires.
        import shutil

        shutil.copy(
            os.path.join(path, "shard-0.msgpack"),
            os.path.join(path, "shard-1.msgpack"),
        )
        with pytest.raises(ValueError, match="process topology"):
            checkpoint.restore_sharded(path, self._state(mesh, fill=False))

    def test_async_sharded_save_matches_sync(self, tmp_path):
        mesh = self._mesh()
        state = self._state(mesh, fill=True)
        sync = checkpoint.save_sharded(str(tmp_path / "sync.shards"), state)
        t = checkpoint.save_sharded_async(str(tmp_path / "async.shards"), state)
        t.join(timeout=30)
        assert not t.is_alive()
        a = open(os.path.join(sync, "shard-0.msgpack"), "rb").read()
        b = open(str(tmp_path / "async.shards" / "shard-0.msgpack"), "rb").read()
        assert a == b


class TestCheckpointIntegrity:
    """End-to-end checkpoint integrity (the robustness-PR tentpole's third
    leg): every save records a sha256; discovery and restore verify it; a
    corrupted newest checkpoint loses to the previous complete one instead
    of crashing the resume or loading garbage."""

    def _save(self, tmp_path, epoch, value=1.0):
        state = {"w": np.full(8, value, np.float32), "step": np.int64(epoch)}
        return checkpoint.save_checkpoint(str(tmp_path), state, epoch)

    def test_save_writes_digest_sidecar(self, tmp_path):
        path = self._save(tmp_path, 1)
        sidecar = path + checkpoint.DIGEST_SUFFIX
        assert os.path.exists(sidecar)
        assert checkpoint.file_intact(path)
        import hashlib

        with open(path, "rb") as f:
            assert open(sidecar).read().strip() == hashlib.sha256(
                f.read()
            ).hexdigest()

    def test_corrupt_file_detected_and_restore_refuses(self, tmp_path):
        from horovod_tpu.testing import faults

        path = self._save(tmp_path, 1)
        template = {"w": np.zeros(8, np.float32), "step": np.int64(0)}
        faults.corrupt_file(path)
        assert not checkpoint.file_intact(path)
        with pytest.raises(checkpoint.CheckpointCorruptError, match="sha256"):
            checkpoint.restore(path, template)

    def test_legacy_file_without_sidecar_accepted(self, tmp_path):
        path = self._save(tmp_path, 1)
        os.remove(path + checkpoint.DIGEST_SUFFIX)
        assert checkpoint.file_intact(path)
        restored = checkpoint.restore(
            path, {"w": np.zeros(8, np.float32), "step": np.int64(0)}
        )
        np.testing.assert_array_equal(restored["w"], np.full(8, 1.0))

    def test_latest_checkpoint_fallback_ordering(self, tmp_path):
        """Table-driven: for each way the NEWEST checkpoint can be bad —
        torn sharded dir, digest-mismatched sharded shard, corrupted
        single file — discovery falls back to the previous complete epoch,
        and resume discards the bad artifact
        (`_discard_future_checkpoints`)."""
        from horovod_tpu.parallel import mesh as mesh_lib
        from horovod_tpu.testing import faults

        hvt.init()
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=4, model=2))

        def sharded_state(fill):
            from jax.sharding import NamedSharding, PartitionSpec as P

            val = np.full((8, 8), fill, np.float32)
            return {
                "w": jax.device_put(val, NamedSharding(mesh, P("data", None)))
            }

        def tear(path):
            os.remove(os.path.join(path, "shard-0.msgpack"))

        def corrupt_shard(path):
            faults.corrupt_file(os.path.join(path, "shard-0.msgpack"))

        cases = [
            ("torn-sharded", True, tear),
            ("digest-mismatched-shard", True, corrupt_shard),
            ("corrupted-file", False, faults.corrupt_file),
        ]
        for name, sharded, damage in cases:
            d = tmp_path / name
            d.mkdir()
            # Epoch 1: always a good single-file checkpoint.
            checkpoint.save(
                str(d / "checkpoint-1.msgpack"), {"w": np.ones((8, 8))}
            )
            # Epoch 2: the newest, damaged per the case.
            if sharded:
                newest = checkpoint.save_sharded(
                    str(d / ("checkpoint-2" + checkpoint.SHARDED_SUFFIX)),
                    sharded_state(2.0),
                )
            else:
                newest = checkpoint.save(
                    str(d / "checkpoint-2.msgpack"), {"w": np.ones((8, 8))}
                )
            assert checkpoint.latest_checkpoint(str(d)).endswith(
                os.path.basename(newest)
            ), name
            damage(newest)
            got = checkpoint.latest_checkpoint(str(d))
            assert got and got.endswith("checkpoint-1.msgpack"), name
            # The full resume path agrees AND removes the bad artifact so
            # the retrained epoch can never mix generations with it.
            restored, epoch = checkpoint.restore_latest_and_broadcast(
                str(d), {"w": np.zeros((8, 8), np.float32)}
            )
            assert epoch == 1, name
            np.testing.assert_array_equal(restored["w"], np.ones((8, 8)))
            assert not os.path.exists(newest), name
            if not sharded:
                assert not os.path.exists(
                    newest + checkpoint.DIGEST_SUFFIX
                ), name

    def test_corrupt_fault_targets_newest_payload(self, tmp_path, monkeypatch):
        """`HVT_FAULT=...:corrupt` unit: the fault finds the newest payload
        (never a .sha256 sidecar), damages it so integrity fails, and
        SIGKILLs itself."""
        import signal as signal_mod

        from horovod_tpu.testing import faults

        p1 = self._save(tmp_path, 1)
        import time as time_mod

        os.utime(p1 + checkpoint.DIGEST_SUFFIX, None)  # sidecar newest
        time_mod.sleep(0.01)
        p2 = self._save(tmp_path, 2)
        os.utime(p2 + checkpoint.DIGEST_SUFFIX, None)
        target = faults.newest_checkpoint_file(str(tmp_path))
        assert target == p2  # payload, not its newer sidecar
        monkeypatch.setenv("PS_MODEL_PATH", str(tmp_path))
        killed = []
        monkeypatch.setattr(
            os, "kill", lambda pid, sig: killed.append((pid, sig))
        )
        cb = faults.FaultInjectionCallback(faults.parse_plan("0:0:corrupt"))
        cb.on_epoch_begin(0)
        cb.on_batch_end(0)
        assert killed == [(os.getpid(), signal_mod.SIGKILL)]
        assert not checkpoint.file_intact(p2)
        assert checkpoint.file_intact(p1)
        assert checkpoint.latest_checkpoint(str(tmp_path)).endswith(
            "checkpoint-1.msgpack"
        )

    # --- index.json integrity sidecar (ROADMAP follow-up) -------------------

    def _sharded(self, d, epoch, mesh):
        from jax.sharding import NamedSharding, PartitionSpec as P

        state = {
            "w": jax.device_put(
                np.full((8, 8), float(epoch), np.float32),
                NamedSharding(mesh, P("data", None)),
            )
        }
        return checkpoint.save_sharded(
            str(d / f"checkpoint-{epoch}{checkpoint.SHARDED_SUFFIX}"), state
        )

    def test_index_gets_digest_sidecar(self, tmp_path):
        from horovod_tpu.parallel import mesh as mesh_lib

        hvt.init()
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=4, model=2))
        path = self._sharded(tmp_path, 1, mesh)
        ipath = os.path.join(path, checkpoint.INDEX_FILE)
        assert os.path.exists(ipath + checkpoint.DIGEST_SUFFIX)
        assert checkpoint.file_intact(ipath)
        assert checkpoint._sharded_complete(path)

    def test_corrupt_index_loses_discovery_and_restore_refuses(
        self, tmp_path
    ):
        """A bit-rotted index.json (payloads all clean) must lose discovery
        to the previous complete epoch, and a direct restore must raise
        CheckpointCorruptError — never steer the restore with garbage."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from horovod_tpu.parallel import mesh as mesh_lib
        from horovod_tpu.testing import faults

        hvt.init()
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=4, model=2))
        self._sharded(tmp_path, 1, mesh)
        newest = self._sharded(tmp_path, 2, mesh)
        ipath = os.path.join(newest, checkpoint.INDEX_FILE)
        faults.corrupt_file(ipath)
        assert not checkpoint._sharded_complete(newest)
        got = checkpoint.latest_checkpoint(str(tmp_path))
        assert got and got.endswith(
            f"checkpoint-1{checkpoint.SHARDED_SUFFIX}"
        )
        template = {
            "w": jax.device_put(
                np.zeros((8, 8), np.float32),
                NamedSharding(mesh, P("data", None)),
            )
        }
        with pytest.raises(checkpoint.CheckpointCorruptError, match="sha256"):
            checkpoint.restore_sharded(newest, template)

    def test_legacy_index_without_sidecar_accepted(self, tmp_path):
        from horovod_tpu.parallel import mesh as mesh_lib

        hvt.init()
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=4, model=2))
        path = self._sharded(tmp_path, 1, mesh)
        os.remove(
            os.path.join(path, checkpoint.INDEX_FILE)
            + checkpoint.DIGEST_SUFFIX
        )
        assert checkpoint._sharded_complete(path)

    # --- corrupt@target (ROADMAP follow-up) ---------------------------------

    def test_corrupt_target_parsing(self):
        from horovod_tpu.testing import faults

        assert faults.corrupt_target("corrupt") == (None, None)
        assert faults.corrupt_target("corrupt@epoch3") == (3, None)
        assert faults.corrupt_target("corrupt@shard1") == (None, 1)
        assert faults.corrupt_target("corrupt@epoch3/shard1") == (3, 1)
        assert faults.parse_plan("0:1:corrupt@epoch3").kind == "corrupt@epoch3"
        with pytest.raises(ValueError, match="corrupt target"):
            faults.parse_plan("0:1:corrupt@newest")

    def test_corrupt_fault_hits_targeted_epoch(self, tmp_path, monkeypatch):
        """corrupt@epoch1 must damage epoch 1's payload even when epoch 2
        is newer — the fallback-across-history scenario (newest stays
        intact, so discovery keeps epoch 2 and the PREVIOUS epoch is the
        corrupted one)."""
        import time as time_mod

        from horovod_tpu.testing import faults

        p1 = self._save(tmp_path, 1)
        time_mod.sleep(0.01)
        p2 = self._save(tmp_path, 2)
        assert faults.newest_checkpoint_file(str(tmp_path), epoch=1) == p1
        monkeypatch.setenv("PS_MODEL_PATH", str(tmp_path))
        killed = []
        monkeypatch.setattr(
            os, "kill", lambda pid, sig: killed.append(sig)
        )
        cb = faults.FaultInjectionCallback(
            faults.parse_plan("0:0:corrupt@epoch1")
        )
        cb.on_epoch_begin(0)
        cb.on_batch_end(0)
        assert killed  # still SIGKILLs after corrupting
        assert not checkpoint.file_intact(p1)
        assert checkpoint.file_intact(p2)
        assert checkpoint.latest_checkpoint(str(tmp_path)).endswith(
            "checkpoint-2.msgpack"
        )

    def test_corrupt_fault_hits_targeted_shard(self, tmp_path):
        """corrupt@shard1 damages exactly shard file 1 of the newest
        sharded checkpoint; single-file checkpoints never match a shard
        target."""
        import time as time_mod

        from horovod_tpu.parallel import mesh as mesh_lib
        from horovod_tpu.testing import faults

        hvt.init()
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=4, model=2))
        self._save(tmp_path, 1)
        time_mod.sleep(0.01)
        shards = self._sharded(tmp_path, 2, mesh)
        # single-process save writes shard-0 only; fake a shard-1
        import shutil as shutil_mod

        shutil_mod.copy(
            os.path.join(shards, "shard-0.msgpack"),
            os.path.join(shards, "shard-1.msgpack"),
        )
        shutil_mod.copy(
            os.path.join(
                shards, "shard-0.msgpack" + checkpoint.DIGEST_SUFFIX
            ),
            os.path.join(
                shards, "shard-1.msgpack" + checkpoint.DIGEST_SUFFIX
            ),
        )
        target = faults.newest_checkpoint_file(str(tmp_path), shard=1)
        assert target == os.path.join(shards, "shard-1.msgpack")
        faults.corrupt_file(target)
        assert not checkpoint.file_intact(target)
        assert checkpoint.file_intact(
            os.path.join(shards, "shard-0.msgpack")
        )
        # combined epoch+shard addressing
        assert faults.newest_checkpoint_file(
            str(tmp_path), epoch=2, shard=0
        ) == os.path.join(shards, "shard-0.msgpack")
        assert faults.newest_checkpoint_file(
            str(tmp_path), epoch=1, shard=0
        ) is None


class TestAsyncSaveErrorSurfacing:
    """A save thread that raised must surface at every consumption point —
    join(), is_alive(), and the next ModelCheckpoint epoch — never vanish
    (a checkpoint that silently failed to write looks successful)."""

    def _failing_async_save(self, tmp_path):
        # The payload path IS a directory: the atomic os.replace inside
        # save() fails on the worker thread, after the snapshot succeeded
        # on the caller thread.
        target = tmp_path / "checkpoint-1.msgpack"
        target.mkdir()
        return checkpoint.save_async(
            str(target), {"w": np.ones(4, np.float32)}
        )

    def _wait_done(self, t):
        t._t.join(timeout=30)
        assert not t._t.is_alive()

    def test_join_reraises(self, tmp_path):
        t = self._failing_async_save(tmp_path)
        self._wait_done(t)
        with pytest.raises(OSError):
            t.join()

    def test_is_alive_reraises_after_death(self, tmp_path):
        t = self._failing_async_save(tmp_path)
        self._wait_done(t)
        with pytest.raises(OSError):
            t.is_alive()
        # The failure is kept, not consumed: a later join raises again.
        with pytest.raises(OSError):
            t.join()

    def test_model_checkpoint_next_epoch_reraises(self, tmp_path):
        """async_save=True: epoch N's failed write surfaces at epoch N+1's
        on_epoch_end (which joins the pending write before starting the
        next), and again at train end."""
        from types import SimpleNamespace

        (tmp_path / "checkpoint-1.msgpack").mkdir()  # epoch-1 write fails
        cb = hvt.callbacks.ModelCheckpoint(
            str(tmp_path / "checkpoint-{epoch}.msgpack"), async_save=True
        )
        cb.set_trainer(SimpleNamespace(state={"w": np.ones(4, np.float32)}))
        cb.on_epoch_end(0)  # starts the doomed async write
        self._wait_done(cb._pending)
        with pytest.raises(OSError):
            cb.on_epoch_end(1)
        with pytest.raises(OSError):
            cb.on_train_end()


def test_backward_passes_per_step_accumulates():
    """Horovod's gradient-accumulation argument: N passes of batch B must
    equal 1 pass of batch N*B (mean semantics) for a linear model + SGD.
    steps_per_epoch counts OPTIMIZER steps — each consumes N microbatches
    inside one compiled step (trainer-native accumulation)."""
    import flax.linen as nn
    import jax.numpy as jnp

    class Linear(nn.Module):
        @nn.compact
        def __call__(self, x, *, train=False):
            return nn.Dense(10, use_bias=False)(
                x.reshape((x.shape[0], -1)).astype(jnp.float32)
            )

    rng = np.random.RandomState(1)
    x = rng.rand(64, 8, 8, 1).astype(np.float32)
    y = rng.randint(0, 10, 64).astype(np.int32)

    def digest(trainer):
        return float(
            sum(np.abs(l).sum() for l in jax.tree.leaves(jax.device_get(trainer.state.params)))
        )

    # 4 AVERAGED accumulated passes of per-chip batch 1 (global 8)...
    acc = hvt.Trainer(
        Linear(),
        hvt.DistributedOptimizer(
            optax.sgd(0.1), backward_passes_per_step=4,
            average_aggregated_gradients=True,
        ),
        seed=3,
    )
    acc.fit(x=x, y=y, batch_size=1, epochs=1, steps_per_epoch=2,
            shuffle_buffer=1, verbose=0)
    # ...equal 2 plain steps of per-chip batch 4 (global 32) over the same
    # 64 examples in the same order.
    plain = hvt.Trainer(
        Linear(), hvt.DistributedOptimizer(optax.sgd(0.1)), seed=3
    )
    plain.fit(x=x, y=y, batch_size=4, epochs=1, steps_per_epoch=2,
              shuffle_buffer=1, verbose=0)
    assert digest(acc) == pytest.approx(digest(plain), rel=1e-6)

    # Horovod's DEFAULT is SUM (average_aggregated_gradients=False): after
    # ONE accumulation cycle (4 passes → 1 update; weights diverge between
    # the two runs after that) the SGD update is exactly 4x the averaged one.
    def one_cycle(**kw):
        t = hvt.Trainer(
            Linear(),
            hvt.DistributedOptimizer(
                optax.sgd(0.1), backward_passes_per_step=4, **kw
            ),
            seed=3,
        )
        t.fit(x=x, y=y, batch_size=1, epochs=1, steps_per_epoch=1,
              shuffle_buffer=1, verbose=0)
        return jax.device_get(jax.tree.leaves(t.state.params)[0])

    init = hvt.Trainer(
        Linear(), hvt.DistributedOptimizer(optax.sgd(0.1)), seed=3
    )
    init.build(x[:1])
    w0 = jax.device_get(jax.tree.leaves(init.state.params)[0])
    w_sum = one_cycle()
    w_mean1 = one_cycle(average_aggregated_gradients=True)
    np.testing.assert_allclose(
        w_sum - w0, 4.0 * (w_mean1 - w0), rtol=1e-5, atol=1e-7
    )


class TestReshardRestore:
    """`restore_sharded(..., reshard=True)`: a sharded checkpoint restores
    onto a DIFFERENT mesh/layout/process count — mismatched leaves are
    reassembled from all shard pieces and re-sliced for the template's
    shardings (train on one topology, resume on another)."""

    def _mesh(self, data, model):
        from jax.sharding import Mesh

        return Mesh(
            np.array(jax.devices()[: data * model]).reshape(data, model),
            ("data", "model"),
        )

    def _state(self, mesh, specs, fill=True):
        from jax.sharding import NamedSharding, PartitionSpec as P

        rng = np.random.RandomState(3 if fill else 7)

        def put(val, spec):
            return jax.device_put(val, NamedSharding(mesh, spec))

        def arr(*shape):
            a = rng.rand(*shape).astype(np.float32)
            return a if fill else np.zeros_like(a)

        return {
            "w_row": put(arr(8, 16), specs[0]),
            "w_col": put(arr(16, 8), specs[1]),
            "bias": put(arr(16), P()),
            "step": put(np.asarray(123 if fill else 0), P()),
        }

    def test_reshard_across_layouts(self, tmp_path):
        from jax.sharding import PartitionSpec as P

        save_mesh = self._mesh(2, 4)
        state = self._state(save_mesh, [P("data", None), P(None, "model")])
        path = checkpoint.save_sharded(str(tmp_path / "c.shards"), state)
        # Different device factorization AND transposed layouts.
        new_mesh = self._mesh(4, 2)
        template = self._state(
            new_mesh, [P(None, "model"), P("data", None)], fill=False
        )
        with pytest.raises(ValueError, match="different mesh or sharding"):
            checkpoint.restore_sharded(path, template)
        restored = checkpoint.restore_sharded(path, template, reshard=True)
        for k in state:
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(restored[k])),
                np.asarray(jax.device_get(state[k])),
            )
            assert restored[k].sharding == template[k].sharding

    def test_reshard_to_single_device(self, tmp_path):
        """Model-parallel checkpoint → an unsharded (1-device) run: the
        'load my pod checkpoint on a workstation' case."""
        from jax.sharding import PartitionSpec as P

        state = self._state(
            self._mesh(2, 4), [P("data", "model"), P("model", "data")]
        )
        path = checkpoint.save_sharded(str(tmp_path / "c.shards"), state)
        template = jax.tree.map(
            lambda a: jax.device_put(np.zeros_like(a), jax.devices()[0]),
            jax.device_get(state),
        )
        restored = checkpoint.restore_sharded(path, template, reshard=True)
        for k in state:
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(restored[k])),
                np.asarray(jax.device_get(state[k])),
            )

    def test_reshard_accepts_process_count_mismatch(self, tmp_path):
        from flax import serialization as ser
        from jax.sharding import PartitionSpec as P

        mesh = self._mesh(2, 4)
        state = self._state(mesh, [P("data", None), P(None, "model")])
        path = checkpoint.save_sharded(str(tmp_path / "c.shards"), state)
        idx_path = os.path.join(path, checkpoint.INDEX_FILE)
        # as if saved by a 2-process fleet
        _rewrite_index(idx_path, lambda idx: idx.update(n_processes=2))
        with open(os.path.join(path, "shard-1.msgpack"), "wb") as f:
            f.write(ser.msgpack_serialize({}))  # rank 1 owned nothing
        template = self._state(mesh, [P("data", None), P(None, "model")],
                               fill=False)
        with pytest.raises(ValueError, match="process topology"):
            checkpoint.restore_sharded(path, template)
        restored = checkpoint.restore_sharded(path, template, reshard=True)
        np.testing.assert_array_equal(
            jax.device_get(restored["w_row"]), jax.device_get(state["w_row"])
        )

    def test_torn_coverage_is_loud(self, tmp_path):
        """Resharding reassembles from ALL pieces — missing coverage (a torn
        save that still passed the file-count check) must raise, not return
        uninitialized memory."""
        from flax import serialization as ser
        from jax.sharding import PartitionSpec as P

        mesh = self._mesh(2, 4)
        state = self._state(mesh, [P("data", None), P(None, "model")])
        path = checkpoint.save_sharded(str(tmp_path / "c.shards"), state)
        fn = os.path.join(path, "shard-0.msgpack")
        with open(fn, "rb") as f:
            store = ser.msgpack_restore(f.read())
        # Drop one piece of leaf 0 ('w_row' — sharded over data=2).
        victim = next(k for k in store if k.startswith("0|") and ":" in k)
        del store[victim]
        with open(fn, "wb") as f:
            f.write(ser.msgpack_serialize(store))
        template = self._state(
            mesh, [P(None, "model"), P("data", None)], fill=False
        )
        with pytest.raises(ValueError, match="cover"):
            checkpoint.restore_sharded(path, template, reshard=True)


@pytest.mark.slow
class TestExportFromShardedState:
    """export_serving over model-parallel params (VERDICT Missing #2):
    single-process TP/FSDP shardings must export transparently and the
    bundle must match single-device predict."""

    def _model_and_sharded_params(self):
        import jax.numpy as jnp

        from horovod_tpu.models.transformer import (
            TransformerLM, param_specs,
        )
        from horovod_tpu.parallel import mesh as mesh_lib

        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshSpec(data=2, fsdp=2, model=2)
        )
        model = TransformerLM(
            vocab_size=32, d_model=32, n_heads=4, n_layers=2, dropout=0.0
        )
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32)
        )["params"]
        specs = param_specs(params, mesh)
        sharded = jax.device_put(
            params,
            jax.tree.map(
                lambda s: jax.sharding.NamedSharding(mesh, s), specs,
                is_leaf=lambda s: isinstance(
                    s, jax.sharding.PartitionSpec
                ),
            ),
        )
        return model, params, sharded

    def test_tp_fsdp_sharded_export_matches_plain(self, tmp_path):
        model, params, sharded = self._model_and_sharded_params()

        def apply_fn(p, x):
            return model.apply({"params": p}, x)

        out = checkpoint.export_serving(
            str(tmp_path), apply_fn, sharded,
            input_shape=(2, 8), input_dtype=np.int32,
            timestamp="19700101-000000",
        )
        fn = checkpoint.load_serving(out)
        x = np.arange(16, dtype=np.int32).reshape(2, 8) % 32
        got = np.asarray(fn(x))
        want = np.asarray(
            jax.nn.softmax(apply_fn(jax.device_get(params), x), axis=-1)
        )
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_gather_to_host_assembles_sharded_tree(self):
        _, params, sharded = self._model_and_sharded_params()
        gathered = checkpoint.gather_to_host(sharded)
        for a, b in zip(
            jax.tree.leaves(jax.device_get(params)),
            jax.tree.leaves(gathered),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestProgressManifest:
    """Checkpoints carry their (epoch, step) resume point: the .meta.json
    manifest (single-file) / index.json "progress" (sharded), read back by
    `checkpoint_progress` and `restore_latest_and_broadcast(with_step=
    True)` — step-granular restart resume."""

    def test_save_checkpoint_records_step(self, tmp_path, trainer_and_data):
        trainer, _, _ = trainer_and_data
        path = checkpoint.save_checkpoint(
            str(tmp_path), trainer.state, 3, step=7
        )
        assert os.path.exists(path + checkpoint.META_SUFFIX)
        assert checkpoint.checkpoint_progress(path) == (3, 7)

    def test_manifestless_checkpoint_reads_step_zero(
        self, tmp_path, trainer_and_data
    ):
        trainer, _, _ = trainer_and_data
        path = checkpoint.save(str(tmp_path / "checkpoint-4.msgpack"),
                               trainer.state)  # no progress= → no manifest
        assert checkpoint.checkpoint_progress(path) == (4, 0)

    def test_stale_manifest_degrades_to_epoch_start(
        self, tmp_path, trainer_and_data
    ):
        """A manifest whose recorded payload sha256 no longer matches the
        payload (crash between the payload's replace and the manifest's)
        must NOT pair the fresh weights with the stale step — fall back
        to (filename epoch, 0), a safe full-epoch replay."""
        trainer, _, _ = trainer_and_data
        path = checkpoint.save(
            str(tmp_path / "checkpoint-2.msgpack"), trainer.state,
            progress=(2, 5),
        )
        assert checkpoint.checkpoint_progress(path) == (2, 5)
        # Re-save the payload (newer bytes) WITHOUT refreshing the meta:
        # device_get(state) serializes identically, so tweak the step
        # counter to change the payload bytes.
        newer = trainer.state.replace(step=trainer.state.step + 1)
        checkpoint.save(path, newer)  # overwrites payload + digest only
        assert checkpoint.checkpoint_progress(path) == (2, 0)

    def test_restore_latest_with_step(self, tmp_path, trainer_and_data):
        trainer, _, _ = trainer_and_data
        checkpoint.save_checkpoint(str(tmp_path), trainer.state, 1)
        checkpoint.save_checkpoint(str(tmp_path), trainer.state, 2, step=9)
        state, epoch, step = checkpoint.restore_latest_and_broadcast(
            str(tmp_path), trainer.state, mesh=trainer.mesh, with_step=True
        )
        assert (epoch, step) == (2, 9)

    def test_step_unaware_restore_skips_midepoch_artifacts(
        self, tmp_path, trainer_and_data
    ):
        """A 2-tuple (step-unaware) caller must NEVER be handed mid-epoch
        weights: it resumes fit(initial_epoch=) alone, which would
        re-apply the epoch prefix's data to weights that already trained
        it. The resolution falls back to the newest COMPLETE-epoch
        checkpoint — mid-epoch artifacts are consumable only by
        with_step=True callers."""
        trainer, _, _ = trainer_and_data
        checkpoint.save_checkpoint(str(tmp_path), trainer.state, 1)
        path2 = checkpoint.save_checkpoint(
            str(tmp_path), trainer.state, 2, step=9
        )
        assert checkpoint.latest_checkpoint(str(tmp_path)) == path2
        complete = checkpoint.latest_checkpoint(
            str(tmp_path), complete_only=True
        )
        assert complete is not None and "checkpoint-1" in complete
        state, epoch = checkpoint.restore_latest_and_broadcast(
            str(tmp_path), trainer.state, mesh=trainer.mesh
        )
        assert epoch == 1
        # The abandoned mid-epoch epoch-2 artifact was discarded (the
        # resumed trajectory will rewrite it from the epoch-1 point).
        assert checkpoint.latest_checkpoint(str(tmp_path)) is not None
        assert "checkpoint-1" in checkpoint.latest_checkpoint(str(tmp_path))

    def test_epoch0_midepoch_checkpoint_restores(
        self, tmp_path, trainer_and_data
    ):
        """A mid-epoch save DURING epoch 0 is checkpoint-0 with step > 0:
        real progress, not the 'nothing to resume' sentinel."""
        trainer, _, _ = trainer_and_data
        checkpoint.save_checkpoint(str(tmp_path), trainer.state, 0, step=3)
        state, epoch, step = checkpoint.restore_latest_and_broadcast(
            str(tmp_path), trainer.state, mesh=trainer.mesh, with_step=True
        )
        assert (epoch, step) == (0, 3)
        assert int(state.step) == int(trainer.state.step)

    def test_discard_future_removes_manifest(
        self, tmp_path, trainer_and_data
    ):
        trainer, _, _ = trainer_and_data
        p2 = checkpoint.save_checkpoint(str(tmp_path), trainer.state, 2)
        p5 = checkpoint.save_checkpoint(str(tmp_path), trainer.state, 5)
        checkpoint._discard_future_checkpoints(str(tmp_path), 2)
        assert os.path.exists(p2 + checkpoint.META_SUFFIX)
        assert not os.path.exists(p5)
        assert not os.path.exists(p5 + checkpoint.META_SUFFIX)

    def test_sharded_index_carries_progress(self, tmp_path, trainer_and_data):
        trainer, _, _ = trainer_and_data
        path = checkpoint.save_sharded(
            str(tmp_path / "checkpoint-3.shards"), trainer.state,
            progress=(3, 11),
        )
        assert checkpoint.checkpoint_progress(path) == (3, 11)
