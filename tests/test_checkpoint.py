"""Checkpoint/resume + serving export parity (SURVEY.md §5.4)."""

import os

import jax
import numpy as np
import optax
import pytest

import horovod_tpu as hvt
from horovod_tpu import checkpoint
from horovod_tpu.models import MnistCNN


@pytest.fixture()
def trainer_and_data():
    hvt.init()
    rng = np.random.RandomState(0)
    x = rng.rand(64, 28, 28, 1).astype(np.float32)
    y = rng.randint(0, 10, 64).astype(np.int64)
    trainer = hvt.Trainer(MnistCNN(), optax.adam(1e-3), seed=0)
    trainer.fit(x=x, y=y, batch_size=4, epochs=1)
    return trainer, x, y


def test_save_restore_roundtrip(trainer_and_data, tmp_path):
    trainer, x, y = trainer_and_data
    path = checkpoint.save(str(tmp_path / "state.msgpack"), trainer.state)
    fresh = hvt.Trainer(MnistCNN(), optax.adam(1e-3), seed=123)
    fresh.build(x)
    restored = checkpoint.restore(path, fresh.state)
    for a, b in zip(jax.tree.leaves(jax.device_get(trainer.state.params)),
                    jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(a, b)
    # optimizer slots restored too (the 'global variables' include them, §7.3)
    assert int(restored.step) == int(trainer.state.step)


def test_resume_produces_identical_eval(trainer_and_data, tmp_path):
    trainer, x, y = trainer_and_data
    path = checkpoint.save(str(tmp_path / "s.msgpack"), trainer.state)
    fresh = hvt.Trainer(MnistCNN(), optax.adam(1e-3), seed=9)
    fresh.build(x)
    fresh.state = checkpoint.broadcast_parameters(
        checkpoint.restore(path, fresh.state), mesh=fresh.mesh
    )
    a = trainer.evaluate(x, y, batch_size=4)
    b = fresh.evaluate(x, y, batch_size=4)
    assert a["loss"] == pytest.approx(b["loss"], rel=1e-5)


def test_latest_checkpoint_selection(tmp_path, trainer_and_data):
    trainer, _, _ = trainer_and_data
    for epoch in (1, 2, 10):
        checkpoint.save_checkpoint(str(tmp_path), trainer.state, epoch)
    assert checkpoint.latest_checkpoint(str(tmp_path)).endswith("checkpoint-10.msgpack")
    state, epoch = checkpoint.restore_latest_and_broadcast(
        str(tmp_path), trainer.state, mesh=trainer.mesh
    )
    assert epoch == 10


def test_restore_latest_empty_dir(tmp_path, trainer_and_data):
    trainer, _, _ = trainer_and_data
    state, epoch = checkpoint.restore_latest_and_broadcast(
        str(tmp_path / "nope"), trainer.state
    )
    assert epoch == 0


def test_serving_export_roundtrip(trainer_and_data, tmp_path):
    """Timestamped dir + input->prob signature + reloadable compiled fn
    (mnist_keras.py:126-140 parity, TF-free)."""
    trainer, x, _ = trainer_and_data
    params = jax.device_get(trainer.state.params)

    def apply_fn(p, inp):
        return trainer.module.apply({"params": p}, inp, train=False)

    out_dir = checkpoint.export_serving(
        str(tmp_path), apply_fn, params,
        input_shape=(1, 28, 28, 1), timestamp="20260729-000000",
    )
    assert out_dir.endswith("20260729-000000")
    assert os.path.exists(os.path.join(out_dir, "model.stablehlo"))
    assert os.path.exists(os.path.join(out_dir, "signature.json"))
    serve = checkpoint.load_serving(out_dir)
    probs = np.asarray(serve(x[:1]))
    expected = trainer.predict(x[:1], batch_size=1)
    np.testing.assert_allclose(probs, expected[:1], rtol=1e-5, atol=1e-6)
