"""Step-granular resume: `fit(initial_epoch=, initial_step=)` must
deterministically fast-forward every feeding path to optimizer step S —
the data a resumed run consumes is BYTE-IDENTICAL to what the
uninterrupted run consumed from step S on, accumulation-aligned (exactly
K·S microbatches skipped), without materializing the skipped batches, and
stable across an `ArrayDataset.reshard` at resume.

Two layers of proof:

* `TestLoaderFastForward` — the data layer: `ArrayDataset.batches(skip)`
  and `training_pipeline(skip_batches=)` yield the uninterrupted stream's
  tail, byte for byte, python and native engines alike.
* `TestResumeBitwise` — the trainer: for {streamed, steps_per_execution,
  device-cached} × K ∈ {1, 4} (× reshard at resume), training epoch E in
  two fits — steps [0, S) then a resumed fit(initial_step=S) — ends with
  params AND optimizer state bitwise equal to the uninterrupted single
  fit. Bitwise state equality is strictly stronger than batch equality:
  any skew in the fast-forward (off-by-one batch, wrong microbatch
  alignment, a differently-seeded shuffle) changes some gradient and
  breaks it.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import flax.linen as nn  # noqa: E402
import optax  # noqa: E402

import horovod_tpu as hvt  # noqa: E402
from horovod_tpu.data.loader import ArrayDataset, training_pipeline  # noqa: E402


class Tiny(nn.Module):
    @nn.compact
    def __call__(self, x, train=False):
        return nn.Dense(4)(x)


def _batches_equal(a, b):
    for xa, xb in zip(a, b):
        la, lb = jax.tree.leaves(xa), jax.tree.leaves(xb)
        assert len(la) == len(lb)
        for ua, ub in zip(la, lb):
            np.testing.assert_array_equal(np.asarray(ua), np.asarray(ub))


class TestLoaderFastForward:
    def _ds(self):
        x = np.arange(80, dtype=np.float32).reshape(40, 2)
        y = np.arange(40)
        return (
            ArrayDataset((x, y)).repeat().shuffle(40, seed=3).batch(4)
        )

    def test_skip_yields_uninterrupted_tail(self):
        ds = self._ds()
        full = [b for _, b in zip(range(10), iter(ds))]
        tail = [b for _, b in zip(range(7), ds.batches(skip=3))]
        _batches_equal(full[3:], tail)

    def test_skip_materializes_nothing(self, monkeypatch):
        """The skipped stretch must never gather rows: poison __getitem__
        on the arrays and unpoison only after the skip is spent."""
        ds = self._ds()
        it = ds.batches(skip=5)
        reads = {"n": 0}

        class Poison:
            def __init__(self, arr):
                self.arr = arr
                self.shape = arr.shape

            def __getitem__(self, sel):
                reads["n"] += 1
                return self.arr[sel]

        ds._arrays = tuple(Poison(a) for a in ds._arrays)
        first = next(it)
        # Exactly ONE gather per array part — for the first YIELDED batch.
        assert reads["n"] == len(ds._arrays)
        assert jax.tree.leaves(first)[0].shape[0] == 4

    def test_reshard_at_resume_same_cut(self):
        """reshard() at the same world size reproduces the identical
        stream, so a resumed generation's skip lands on the same cut."""
        ds = self._ds().shard(0, 1).batch(4)
        full = [b for _, b in zip(range(8), iter(ds))]
        resharded = ds.reshard(0, 1).batch(4)
        tail = [b for _, b in zip(range(4), resharded.batches(skip=4))]
        _batches_equal(full[4:], tail)

    def test_skip_count_is_world_size_independent(self):
        """The fast-forward cut is defined in BATCHES (optimizer steps ×
        K), not bytes: at a different world size each process skips the
        same batch count of its own resharded stream."""
        ds = self._ds().shard(0, 2).batch(4)
        full = [b for _, b in zip(range(4), iter(ds))]
        tail = [b for _, b in zip(range(2), ds.batches(skip=2))]
        _batches_equal(full[2:], tail)

    @pytest.mark.parametrize("native", [False, True])
    def test_training_pipeline_skip(self, native, monkeypatch):
        if native:
            from horovod_tpu.data import native_loader

            if not native_loader.available():
                pytest.skip("native loader unavailable")
        else:
            monkeypatch.setenv("HVT_NO_NATIVE", "1")
        x = np.arange(60, dtype=np.float32).reshape(30, 2)
        y = np.arange(30, dtype=np.int64)
        it_a, close_a = training_pipeline((x, y), 5, seed=11)
        full = [b for _, b in zip(range(9), it_a)]
        close_a()
        it_b, close_b = training_pipeline((x, y), 5, seed=11, skip_batches=4)
        tail = [b for _, b in zip(range(5), it_b)]
        close_b()
        _batches_equal(full[4:], tail)


def _params_bytes(trainer):
    state = jax.device_get(trainer.state)
    return [
        np.asarray(l).tobytes()
        for l in jax.tree.leaves((state.params, state.opt_state))
    ]


T, S = 4, 2  # steps per epoch, resume step
EPOCHS = 3   # train epochs [1, 3)


def _data(n=256):
    rng = np.random.RandomState(0)
    x = rng.rand(n, 8).astype(np.float32)
    y = (np.arange(n) % 4).astype(np.int64)
    return x, y


def _trainer(K=1, spe=1):
    return hvt.Trainer(
        Tiny(),
        hvt.DistributedOptimizer(
            optax.adam(1e-2), backward_passes_per_step=K
        ),
        seed=7,
        steps_per_execution=spe,
    )


class TestResumeBitwise:
    """Uninterrupted control vs [partial epoch + fit(initial_step=S)]:
    final params + optimizer state must be BITWISE equal (CPU determinism
    — any fast-forward skew breaks it). The control starts the same fit
    call shape (fresh stream at initial_epoch), matching the elastic
    contract where every generation rebuilds its input pipeline."""

    @pytest.mark.parametrize("K", [1, 4])
    @pytest.mark.parametrize("spe", [1, 3])
    def test_streamed(self, K, spe, monkeypatch):
        monkeypatch.setenv("HVT_NO_NATIVE", "1")
        x, y = _data()
        tA = _trainer(K, spe)
        tA.fit(x=x, y=y, batch_size=4, epochs=EPOCHS, initial_epoch=1,
               steps_per_epoch=T, verbose=0)
        tB = _trainer(K, spe)
        # The interruption: epoch 1 trained only S steps (the stream,
        # fresh per fit, consumed exactly the control's first S·K
        # microbatches — steps_per_epoch only caps consumption).
        tB.fit(x=x, y=y, batch_size=4, epochs=2, initial_epoch=1,
               steps_per_epoch=S, verbose=0)
        # The resume: fast-forward S·K microbatches, continue to the end.
        tB.fit(x=x, y=y, batch_size=4, epochs=EPOCHS, initial_epoch=1,
               initial_step=S, steps_per_epoch=T, verbose=0)
        assert _params_bytes(tA) == _params_bytes(tB)

    @pytest.mark.parametrize("K", [1, 4])
    def test_device_cached(self, K):
        # 256 rows over the suite's 8-device mesh: per-shard 32 examples
        # = T·K·batch at K=4 — the epoch exactly covers the shard.
        x, y = _data(256)
        tA = _trainer(K)
        tA.fit(x=x, y=y, batch_size=2, cache="device", epochs=EPOCHS,
               initial_epoch=1, steps_per_epoch=T, verbose=0)
        tB = _trainer(K)
        # The epoch permutation is a pure function of (seed, epoch), so
        # a partial epoch consumes the uninterrupted epoch's prefix...
        tB.fit(x=x, y=y, batch_size=2, cache="device", epochs=2,
               initial_epoch=1, steps_per_epoch=S, verbose=0)
        # ...and the resume gathers/scans from step S of the SAME order.
        tB.fit(x=x, y=y, batch_size=2, cache="device", epochs=EPOCHS,
               initial_epoch=1, initial_step=S, steps_per_epoch=T,
               verbose=0)
        assert _params_bytes(tA) == _params_bytes(tB)

    @pytest.mark.parametrize("K", [1, 4])
    def test_streamed_reshard_at_resume(self, K, monkeypatch):
        """The dataset= path across a reshard: the resumed fit feeds a
        RESHARDED (same-size) recut of the dataset — the elastic
        generation-change shape — and still lands bitwise."""
        monkeypatch.setenv("HVT_NO_NATIVE", "1")
        x, y = _data()

        def chain(ds):
            # Batch divisible by the suite's 8-device data axis.
            return ds.repeat().shuffle(len(x), seed=5).batch(8 * K)

        tA = _trainer(K)
        tA.fit(chain(ArrayDataset((x, y)).shard(0, 1)), epochs=EPOCHS,
               initial_epoch=1, steps_per_epoch=T, verbose=0)
        tB = _trainer(K)
        base = ArrayDataset((x, y)).shard(0, 1)
        tB.fit(chain(base), epochs=2, initial_epoch=1, steps_per_epoch=S,
               verbose=0)
        tB.fit(chain(base.reshard(0, 1)), epochs=EPOCHS, initial_epoch=1,
               initial_step=S, steps_per_epoch=T, verbose=0)
        assert _params_bytes(tA) == _params_bytes(tB)

    def test_batch_indices_resume_at_step(self, monkeypatch):
        """on_batch_end fires with TRUE within-epoch step indices after a
        resume — step-keyed cadences (elastic commits, step faults) stay
        aligned — and the epoch's logged mean covers only the steps the
        resumed fit actually ran."""
        monkeypatch.setenv("HVT_NO_NATIVE", "1")
        x, y = _data()
        seen = []

        class Spy(hvt.callbacks.Callback):
            def on_batch_end(self, batch, logs=None):
                seen.append(batch)

        t = _trainer()
        t.fit(x=x, y=y, batch_size=4, epochs=2, initial_epoch=1,
              initial_step=S, steps_per_epoch=T, callbacks=[Spy()],
              verbose=0)
        assert seen == list(range(S, T))
        assert t._resume_epoch == 1 and t._resume_step == S

    def test_step_rolls_into_next_epoch(self, monkeypatch):
        """A resume point at the epoch's end (a commit taken at the last
        step boundary) normalizes to the NEXT epoch's start."""
        monkeypatch.setenv("HVT_NO_NATIVE", "1")
        x, y = _data()
        t = _trainer()
        hist = t.fit(x=x, y=y, batch_size=4, epochs=3, initial_epoch=1,
                     initial_step=T, steps_per_epoch=T, verbose=0)
        # (1, T) ≡ (2, 0): exactly one epoch (epoch 2) runs.
        assert len(hist) == 1
        assert t._resume_epoch == 2 and t._resume_step == 0

    def test_negative_step_rejected(self):
        x, y = _data()
        t = _trainer()
        with pytest.raises(ValueError, match="initial_step"):
            t.fit(x=x, y=y, batch_size=4, epochs=2, initial_step=-1,
                  steps_per_epoch=T, verbose=0)


# =========================================================================
# ISSUE 8 — durable stream cursors: byte-exact CROSS-EPOCH resume.
#
# The PR 5 gap: the streamed paths re-anchored epochs that PREDATE the
# resume call (a resumed fit's fresh stream called its first pass "the
# resume epoch", while the uninterrupted run's resume epoch was a later
# pass of an evolving RNG). With every engine's per-epoch order now a
# pure function of (seed, epoch, pass), a run interrupted in epoch N ≥ 2
# and resumed at (N, S) must land BITWISE equal to the uninterrupted
# control — the previously-impossible case.
# =========================================================================


class TestCrossEpochStreamAnchoring:
    """Data layer: the stream from (start_epoch=E, skip=S) equals the
    uninterrupted stream's tail — python and native engines."""

    @pytest.mark.parametrize("native", [False, True])
    def test_pipeline_cross_epoch_tail(self, native, monkeypatch):
        if native:
            from horovod_tpu.data import native_loader

            if not native_loader.available():
                pytest.skip("native loader unavailable")
        else:
            monkeypatch.setenv("HVT_NO_NATIVE", "1")
        from horovod_tpu.data.loader import training_pipeline

        x = np.arange(120, dtype=np.float32).reshape(60, 2)
        y = np.arange(60, dtype=np.int64)
        B = 7  # batches per (trainer) epoch; pass = 12 batches
        it_a, close_a = training_pipeline(
            (x, y), 5, seed=11, batches_per_epoch=B
        )
        full = [b for _, b in zip(range(5 * B), it_a)]
        close_a()
        # Resume at (epoch 3, step 2): epochs 0-2 were consumed by a
        # process that no longer exists — the re-anchoring case.
        it_b, close_b = training_pipeline(
            (x, y), 5, seed=11, start_epoch=3, skip_batches=2,
            batches_per_epoch=B,
        )
        tail = [b for _, b in zip(range(2 * B - 2), it_b)]
        close_b()
        _batches_equal(full[3 * B + 2:], tail)

    def test_epoch_longer_than_one_pass_rolls_anchored(self, monkeypatch):
        """batches_per_epoch > one permutation pass: intra-epoch passes
        are themselves anchored ((seed, epoch, pass)), so the resume
        still lands byte-exactly mid-rollover."""
        monkeypatch.setenv("HVT_NO_NATIVE", "1")
        from horovod_tpu.data.loader import training_pipeline

        x = np.arange(40, dtype=np.float32).reshape(20, 2)
        y = np.arange(20, dtype=np.int64)
        B = 7  # pass = 4 batches -> ~2 rollovers per epoch
        it_a, close_a = training_pipeline(
            (x, y), 5, seed=3, batches_per_epoch=B
        )
        full = [b for _, b in zip(range(3 * B), it_a)]
        close_a()
        it_b, close_b = training_pipeline(
            (x, y), 5, seed=3, start_epoch=1, skip_batches=5,
            batches_per_epoch=B,
        )
        tail = [b for _, b in zip(range(2 * B - 5), it_b)]
        close_b()
        _batches_equal(full[B + 5:], tail)


class TestStreamCursorContract:
    """The serializable cursor surface: round trips, loud refusals."""

    def _ds(self):
        x = np.arange(80, dtype=np.float32).reshape(40, 2)
        return ArrayDataset((x, np.arange(40))).repeat().shuffle(
            40, seed=3
        ).batch(4)

    def test_cursor_round_trip_byte_exact(self):
        import json

        ds = self._ds()
        full = [b for _, b in zip(range(21),
                                  ds.batches(batches_per_epoch=7))]
        cur = json.loads(json.dumps(
            ds.stream_cursor(2, 3, batches_per_epoch=7).to_dict()
        ))
        tail = [b for _, b in zip(range(4), ds.batches_from(cur))]
        _batches_equal(full[17:], tail)

    def test_older_format_refused_loudly(self):
        from horovod_tpu.data import stream as stream_lib

        ds = self._ds()
        cur = ds.stream_cursor(1, 0).to_dict()
        cur["format"] = 0
        with pytest.raises(stream_lib.StreamCursorError,
                           match="format 0"):
            ds.batches_from(cur)
        with pytest.raises(stream_lib.StreamCursorError,
                           match="missing 'format'"):
            ds.batches_from({"kind": "array", "epoch": 1})

    def test_wrong_kind_and_geometry_refused(self):
        from horovod_tpu.data import stream as stream_lib

        ds = self._ds()
        cur = ds.stream_cursor(1, 0)
        cur.kind = "file"
        with pytest.raises(stream_lib.StreamCursorError,
                           match="cannot resume"):
            ds.batches_from(cur)
        cur2 = ds.stream_cursor(1, 0)
        cur2.position["n_examples"] = 39
        with pytest.raises(stream_lib.StreamCursorError,
                           match="n_examples"):
            ds.batches_from(cur2)

    def test_file_cursor_preserves_shuffle_mode(self, tmp_path):
        """shuffle=False is stream GEOMETRY: the cursor records it and
        reconstruction honours it (a shuffled reconstruction of an
        ordered stream is silently different bytes — the review-found
        bug class)."""
        from horovod_tpu.data.filedataset import FileDataset, write_shards

        d = write_shards({"a": np.arange(40)}, str(tmp_path / "ds"),
                         shard_size=16)
        ds = FileDataset(d)
        full = [b["a"] for _, b in zip(range(10), ds.batches(
            4, shuffle=False, batches_per_epoch=5))]
        cur = ds.stream_cursor(
            0, 2, batch_size=4, shuffle=False, batches_per_epoch=5
        ).to_dict()
        got = [b["a"] for _, b in zip(range(8), ds.batches_from(cur))]
        for p, q in zip(full[2:], got):
            np.testing.assert_array_equal(p, q)

    def test_file_cursor_from_repeat_stream_stays_infinite(self, tmp_path):
        """A cursor cut from a repeating stream reconstructs as a
        REPEATING stream — never silently truncated at the resume
        epoch's boundary (review-found trap)."""
        from horovod_tpu.data.filedataset import FileDataset, write_shards

        d = write_shards({"a": np.arange(40)}, str(tmp_path / "ds"),
                         shard_size=16)
        ds = FileDataset(d)
        full = [b["a"] for _, b in zip(
            range(30), ds.batches(4, seed=2, repeat=True))]
        cur = ds.stream_cursor(1, 2, batch_size=4, seed=2).to_dict()
        # 18 batches spans well past the resume epoch's remainder (8).
        got = [b["a"] for _, b in zip(range(18), ds.batches_from(cur))]
        assert len(got) == 18
        for p, q in zip(full[12:], got):
            np.testing.assert_array_equal(p, q)

    def test_preemption_checkpoint_carries_cursor(self, tmp_path,
                                                  monkeypatch):
        """The preemption grace-window save stamps the cursor like every
        other checkpoint writer — the restart path is exactly where the
        format/geometry refusal matters."""
        import signal as _signal

        from horovod_tpu import checkpoint

        monkeypatch.setenv("HVT_NO_NATIVE", "1")
        x, y = _data()
        t = _trainer()
        cb = hvt.callbacks.PreemptionCheckpointCallback(
            str(tmp_path / "checkpoint-{epoch}.msgpack")
        )

        class Fire(hvt.callbacks.Callback):
            def on_epoch_begin(self, epoch, logs=None):
                os.kill(os.getpid(), _signal.SIGTERM)

        import os

        t.fit(x=x, y=y, batch_size=4, epochs=2, steps_per_epoch=T,
              callbacks=[cb, Fire()], verbose=0)
        path = checkpoint.latest_checkpoint(str(tmp_path))
        assert path is not None
        cur = checkpoint.checkpoint_cursor(path)
        assert cur is not None and cur.kind == "fit"

    def test_file_pairs_refuses_mismatched_stripe(self, tmp_path):
        """FilePairs validates the FULL geometry: a cursor cut on a
        different per-process stripe is refused, not silently resumed
        on the new stripe's permutations."""
        from horovod_tpu.data import stream as stream_lib
        from horovod_tpu.data.filedataset import FileDataset, write_shards

        d = write_shards({"x": np.arange(40), "y": np.arange(40)},
                         str(tmp_path / "ds"), shard_size=16)
        ds = FileDataset(d)
        cur = ds.shard(0, 2).pairs_stream("x", "y", 4).stream_cursor(1, 1)
        with pytest.raises(stream_lib.StreamCursorError, match="shard"):
            ds.shard(0, 4).pairs_stream("x", "y", 4).batches_from(cur)

    def test_native_cursor_missing_batch_size_refused(self):
        from horovod_tpu.data import native_loader, stream as stream_lib

        if not native_loader.available():
            pytest.skip("native loader unavailable")
        cur = stream_lib.StreamCursor(
            kind="native", seed=1, epoch=0, step=0,
            position={"n_examples": 16},
        ).to_dict()
        with pytest.raises(stream_lib.StreamCursorError,
                           match="batch_size"):
            native_loader.NativeBatchLoader.from_cursor(
                [np.arange(16)], cur
            )

    def test_packed_lm_stream_cursor(self):
        from horovod_tpu.data.packing import PackedLMStream

        rng = np.random.RandomState(0)
        docs = [rng.randint(1, 30, size=rng.randint(4, 10))
                for _ in range(60)]
        s = PackedLMStream(docs, seq_len=16, batch_size=4, seed=5)
        full = [b for _, b in zip(range(12),
                                  s.batches(batches_per_epoch=4))]
        cur = s.stream_cursor(1, 2, batches_per_epoch=4).to_dict()
        tail = [b for _, b in zip(range(6), s.batches_from(cur))]
        _batches_equal(full[6:], tail)

    def test_native_cursor_reconstruction(self):
        from horovod_tpu.data import native_loader

        if not native_loader.available():
            pytest.skip("native loader unavailable")
        x = np.arange(48, dtype=np.int64)
        a = native_loader.NativeBatchLoader(
            [x], 6, seed=4, batches_per_epoch=5
        )
        consumed = [next(a)[0] for _ in range(8)]
        cur = a.cursor().to_dict()
        rest = [next(a)[0] for _ in range(7)]
        a.close()
        b = native_loader.NativeBatchLoader.from_cursor([x], cur)
        got = [next(b)[0] for _ in range(7)]
        b.close()
        for p, q in zip(rest, got):
            np.testing.assert_array_equal(p, q)

    def test_checkpoint_manifest_carries_cursor(self, tmp_path):
        """The cursor rides .meta.json; an incompatible format version is
        refused loudly at read time, never silently re-anchored."""
        from horovod_tpu import checkpoint
        from horovod_tpu.data import stream as stream_lib

        path = str(tmp_path / "checkpoint-3.msgpack")
        cur = stream_lib.StreamCursor(
            kind="fit", seed=7, epoch=3, step=2,
            position={"steps_per_epoch": T, "accum": 1},
        ).to_dict()
        checkpoint.save(path, {"w": np.zeros(2)}, progress=(3, 2),
                        cursor=cur)
        got = checkpoint.checkpoint_cursor(path)
        assert (got.epoch, got.step, got.kind) == (3, 2, "fit")
        assert checkpoint.checkpoint_progress(path) == (3, 2)
        # Corrupt the recorded format version in place.
        import json

        meta = json.loads(
            open(path + checkpoint.META_SUFFIX).read()
        )
        meta["cursor"]["format"] = 99
        with open(path + checkpoint.META_SUFFIX, "w") as f:
            f.write(json.dumps(meta))
        with pytest.raises(stream_lib.StreamCursorError, match="99"):
            checkpoint.checkpoint_cursor(path)


def _interrupt_and_resume(make_trainer, fit, S_kill):
    """The matrix cell driver: control = one uninterrupted fit over
    EPOCHS epochs; interrupted = epochs [0, 2) in one fit, a partial
    epoch 2 of S_kill steps (mid-epoch kill; skipped when S_kill == 0 —
    the epoch-boundary kill), then a resumed fit from (2, S_kill).
    Returns (control trainer, resumed trainer) for bitwise comparison."""
    tA = make_trainer()
    fit(tA, initial_epoch=0, initial_step=0, epochs=EPOCHS + 1)
    tB = make_trainer()
    fit(tB, initial_epoch=0, initial_step=0, epochs=2)
    if S_kill:
        fit(tB, initial_epoch=2, initial_step=0, epochs=3,
            steps_override=S_kill)
    fit(tB, initial_epoch=2, initial_step=S_kill, epochs=EPOCHS + 1)
    return tA, tB


class TestCrossEpochResumeMatrix:
    """Trainer level: {streamed, file-backed, packed-LM, native,
    device-cached} × kill-point {mid-epoch, epoch boundary} × {same
    world, resharded} — interrupted in epoch 2 (consumed epochs 0-1
    PREDATE the resume call), final params + opt state bitwise equal to
    the uninterrupted control."""

    @pytest.mark.parametrize("S_kill", [S, 0],
                             ids=["mid-epoch", "boundary"])
    @pytest.mark.parametrize("K", [1, 4])
    def test_streamed_python(self, K, S_kill, monkeypatch):
        monkeypatch.setenv("HVT_NO_NATIVE", "1")
        x, y = _data()

        def fit(t, *, initial_epoch, initial_step, epochs,
                steps_override=None):
            t.fit(x=x, y=y, batch_size=4, epochs=epochs,
                  initial_epoch=initial_epoch, initial_step=initial_step,
                  steps_per_epoch=steps_override or T, verbose=0)

        tA, tB = _interrupt_and_resume(lambda: _trainer(K), fit, S_kill)
        assert _params_bytes(tA) == _params_bytes(tB)

    @pytest.mark.parametrize("S_kill", [S, 0],
                             ids=["mid-epoch", "boundary"])
    def test_streamed_native(self, S_kill, monkeypatch):
        from horovod_tpu.data import native_loader

        if not native_loader.available():
            pytest.skip("native loader unavailable")
        monkeypatch.delenv("HVT_NO_NATIVE", raising=False)
        x, y = _data()

        def fit(t, *, initial_epoch, initial_step, epochs,
                steps_override=None):
            t.fit(x=x, y=y, batch_size=4, epochs=epochs,
                  initial_epoch=initial_epoch, initial_step=initial_step,
                  steps_per_epoch=steps_override or T, verbose=0)

        tA, tB = _interrupt_and_resume(_trainer, fit, S_kill)
        assert _params_bytes(tA) == _params_bytes(tB)

    @pytest.mark.parametrize("reshard", [False, True])
    @pytest.mark.parametrize("S_kill", [S, 0],
                             ids=["mid-epoch", "boundary"])
    def test_file_backed(self, S_kill, reshard, tmp_path, monkeypatch):
        from horovod_tpu.data.filedataset import FileDataset, write_shards

        monkeypatch.setenv("HVT_NO_NATIVE", "1")
        x, y = _data()
        d = write_shards({"x": x, "y": y}, str(tmp_path / "ds"),
                         shard_size=32)
        base = FileDataset(d).shard(0, 2)

        def fit(t, *, initial_epoch, initial_step, epochs,
                steps_override=None, view=base):
            t.fit(view.pairs_stream("x", "y", 8, seed=13),
                  epochs=epochs, initial_epoch=initial_epoch,
                  initial_step=initial_step,
                  steps_per_epoch=steps_override or T, verbose=0)

        tA = _trainer()
        fit(tA, initial_epoch=0, initial_step=0, epochs=EPOCHS + 1)
        tB = _trainer()
        fit(tB, initial_epoch=0, initial_step=0, epochs=2)
        if S_kill:
            fit(tB, initial_epoch=2, initial_step=0, epochs=3,
                steps_override=S_kill)
        # The resumed generation recuts its stripe from the full row
        # space (the elastic rescale hook) when `reshard` — same-size
        # recut must reproduce the identical stream.
        resumed_view = base.reshard(0, 2) if reshard else base
        fit(tB, initial_epoch=2, initial_step=S_kill,
            epochs=EPOCHS + 1, view=resumed_view)
        assert _params_bytes(tA) == _params_bytes(tB)

    @pytest.mark.parametrize("S_kill", [S, 0],
                             ids=["mid-epoch", "boundary"])
    def test_packed_lm(self, S_kill, monkeypatch):
        import flax.linen as nn2
        import optax as optax2

        from horovod_tpu.data.packing import PackedLMStream

        monkeypatch.setenv("HVT_NO_NATIVE", "1")

        class TinyLM(nn2.Module):
            @nn2.compact
            def __call__(self, x, train=False):
                emb = nn2.Embed(32, 8)(x[..., 0])
                return nn2.Dense(32)(emb)

        def masked_ce(logits, y2):
            import jax.numpy as jnp
            import optax as _o

            per = _o.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), y2[..., 0]
            )
            w = y2[..., 1].astype(jnp.float32)
            return (per * w).sum(-1) / jnp.maximum(w.sum(-1), 1.0)

        rng = np.random.RandomState(1)
        docs = [rng.randint(1, 30, size=rng.randint(4, 10))
                for _ in range(160)]
        stream = PackedLMStream(docs, seq_len=12, batch_size=8, seed=21)

        def make():
            return hvt.Trainer(
                TinyLM(),
                hvt.DistributedOptimizer(optax2.adam(1e-2)),
                loss=masked_ce, seed=3,
            )

        def fit(t, *, initial_epoch, initial_step, epochs,
                steps_override=None):
            t.fit(stream, epochs=epochs, initial_epoch=initial_epoch,
                  initial_step=initial_step,
                  steps_per_epoch=steps_override or T, verbose=0)

        tA, tB = _interrupt_and_resume(make, fit, S_kill)
        assert _params_bytes(tA) == _params_bytes(tB)

    @pytest.mark.parametrize("S_kill", [S, 0],
                             ids=["mid-epoch", "boundary"])
    def test_device_cached(self, S_kill):
        x, y = _data(256)

        def fit(t, *, initial_epoch, initial_step, epochs,
                steps_override=None):
            t.fit(x=x, y=y, batch_size=2, cache="device", epochs=epochs,
                  initial_epoch=initial_epoch, initial_step=initial_step,
                  steps_per_epoch=steps_override or T, verbose=0)

        tA, tB = _interrupt_and_resume(_trainer, fit, S_kill)
        assert _params_bytes(tA) == _params_bytes(tB)


class TestDeviceCachedChunking:
    """HVT_EPOCH_CHUNK_STEPS: step-chunked epoch executables on the
    device-cached path — identical arithmetic, per-chunk on_batch_end
    (so sub-epoch commit/rescale/save cadences work there too)."""

    def test_chunked_bitwise_equal_and_callbacks_fire(self, monkeypatch):
        x, y = _data(256)
        tA = _trainer()
        tA.fit(x=x, y=y, batch_size=2, cache="device", epochs=2,
               steps_per_epoch=T, verbose=0)
        seen = []

        class Spy(hvt.callbacks.Callback):
            def on_batch_end(self, batch, logs=None):
                seen.append(batch)

        monkeypatch.setenv("HVT_EPOCH_CHUNK_STEPS", "2")
        tB = _trainer()
        tB.fit(x=x, y=y, batch_size=2, cache="device", epochs=2,
               steps_per_epoch=T, callbacks=[Spy()], verbose=0)
        assert _params_bytes(tA) == _params_bytes(tB)
        # T=4 steps, chunk=2 -> on_batch_end at steps 2 and 4 (1-based
        # minus one), twice (2 epochs).
        assert seen == [1, 3, 1, 3]

    def test_chunked_mid_epoch_resume(self, monkeypatch):
        """Chunking composes with the resume contract: a chunked fit
        resumed at (epoch, S) still lands bitwise."""
        monkeypatch.setenv("HVT_EPOCH_CHUNK_STEPS", "2")
        x, y = _data(256)

        def fit(t, *, initial_epoch, initial_step, epochs,
                steps_override=None):
            t.fit(x=x, y=y, batch_size=2, cache="device", epochs=epochs,
                  initial_epoch=initial_epoch, initial_step=initial_step,
                  steps_per_epoch=steps_override or T, verbose=0)

        tA, tB = _interrupt_and_resume(_trainer, fit, S)
        assert _params_bytes(tA) == _params_bytes(tB)
