"""Step-granular resume: `fit(initial_epoch=, initial_step=)` must
deterministically fast-forward every feeding path to optimizer step S —
the data a resumed run consumes is BYTE-IDENTICAL to what the
uninterrupted run consumed from step S on, accumulation-aligned (exactly
K·S microbatches skipped), without materializing the skipped batches, and
stable across an `ArrayDataset.reshard` at resume.

Two layers of proof:

* `TestLoaderFastForward` — the data layer: `ArrayDataset.batches(skip)`
  and `training_pipeline(skip_batches=)` yield the uninterrupted stream's
  tail, byte for byte, python and native engines alike.
* `TestResumeBitwise` — the trainer: for {streamed, steps_per_execution,
  device-cached} × K ∈ {1, 4} (× reshard at resume), training epoch E in
  two fits — steps [0, S) then a resumed fit(initial_step=S) — ends with
  params AND optimizer state bitwise equal to the uninterrupted single
  fit. Bitwise state equality is strictly stronger than batch equality:
  any skew in the fast-forward (off-by-one batch, wrong microbatch
  alignment, a differently-seeded shuffle) changes some gradient and
  breaks it.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import flax.linen as nn  # noqa: E402
import optax  # noqa: E402

import horovod_tpu as hvt  # noqa: E402
from horovod_tpu.data.loader import ArrayDataset, training_pipeline  # noqa: E402


class Tiny(nn.Module):
    @nn.compact
    def __call__(self, x, train=False):
        return nn.Dense(4)(x)


def _batches_equal(a, b):
    for xa, xb in zip(a, b):
        la, lb = jax.tree.leaves(xa), jax.tree.leaves(xb)
        assert len(la) == len(lb)
        for ua, ub in zip(la, lb):
            np.testing.assert_array_equal(np.asarray(ua), np.asarray(ub))


class TestLoaderFastForward:
    def _ds(self):
        x = np.arange(80, dtype=np.float32).reshape(40, 2)
        y = np.arange(40)
        return (
            ArrayDataset((x, y)).repeat().shuffle(40, seed=3).batch(4)
        )

    def test_skip_yields_uninterrupted_tail(self):
        ds = self._ds()
        full = [b for _, b in zip(range(10), iter(ds))]
        tail = [b for _, b in zip(range(7), ds.batches(skip=3))]
        _batches_equal(full[3:], tail)

    def test_skip_materializes_nothing(self, monkeypatch):
        """The skipped stretch must never gather rows: poison __getitem__
        on the arrays and unpoison only after the skip is spent."""
        ds = self._ds()
        it = ds.batches(skip=5)
        reads = {"n": 0}

        class Poison:
            def __init__(self, arr):
                self.arr = arr
                self.shape = arr.shape

            def __getitem__(self, sel):
                reads["n"] += 1
                return self.arr[sel]

        ds._arrays = tuple(Poison(a) for a in ds._arrays)
        first = next(it)
        # Exactly ONE gather per array part — for the first YIELDED batch.
        assert reads["n"] == len(ds._arrays)
        assert jax.tree.leaves(first)[0].shape[0] == 4

    def test_reshard_at_resume_same_cut(self):
        """reshard() at the same world size reproduces the identical
        stream, so a resumed generation's skip lands on the same cut."""
        ds = self._ds().shard(0, 1).batch(4)
        full = [b for _, b in zip(range(8), iter(ds))]
        resharded = ds.reshard(0, 1).batch(4)
        tail = [b for _, b in zip(range(4), resharded.batches(skip=4))]
        _batches_equal(full[4:], tail)

    def test_skip_count_is_world_size_independent(self):
        """The fast-forward cut is defined in BATCHES (optimizer steps ×
        K), not bytes: at a different world size each process skips the
        same batch count of its own resharded stream."""
        ds = self._ds().shard(0, 2).batch(4)
        full = [b for _, b in zip(range(4), iter(ds))]
        tail = [b for _, b in zip(range(2), ds.batches(skip=2))]
        _batches_equal(full[2:], tail)

    @pytest.mark.parametrize("native", [False, True])
    def test_training_pipeline_skip(self, native, monkeypatch):
        if native:
            from horovod_tpu.data import native_loader

            if not native_loader.available():
                pytest.skip("native loader unavailable")
        else:
            monkeypatch.setenv("HVT_NO_NATIVE", "1")
        x = np.arange(60, dtype=np.float32).reshape(30, 2)
        y = np.arange(30, dtype=np.int64)
        it_a, close_a = training_pipeline((x, y), 5, seed=11)
        full = [b for _, b in zip(range(9), it_a)]
        close_a()
        it_b, close_b = training_pipeline((x, y), 5, seed=11, skip_batches=4)
        tail = [b for _, b in zip(range(5), it_b)]
        close_b()
        _batches_equal(full[4:], tail)


def _params_bytes(trainer):
    state = jax.device_get(trainer.state)
    return [
        np.asarray(l).tobytes()
        for l in jax.tree.leaves((state.params, state.opt_state))
    ]


T, S = 4, 2  # steps per epoch, resume step
EPOCHS = 3   # train epochs [1, 3)


def _data(n=256):
    rng = np.random.RandomState(0)
    x = rng.rand(n, 8).astype(np.float32)
    y = (np.arange(n) % 4).astype(np.int64)
    return x, y


def _trainer(K=1, spe=1):
    return hvt.Trainer(
        Tiny(),
        hvt.DistributedOptimizer(
            optax.adam(1e-2), backward_passes_per_step=K
        ),
        seed=7,
        steps_per_execution=spe,
    )


class TestResumeBitwise:
    """Uninterrupted control vs [partial epoch + fit(initial_step=S)]:
    final params + optimizer state must be BITWISE equal (CPU determinism
    — any fast-forward skew breaks it). The control starts the same fit
    call shape (fresh stream at initial_epoch), matching the elastic
    contract where every generation rebuilds its input pipeline."""

    @pytest.mark.parametrize("K", [1, 4])
    @pytest.mark.parametrize("spe", [1, 3])
    def test_streamed(self, K, spe, monkeypatch):
        monkeypatch.setenv("HVT_NO_NATIVE", "1")
        x, y = _data()
        tA = _trainer(K, spe)
        tA.fit(x=x, y=y, batch_size=4, epochs=EPOCHS, initial_epoch=1,
               steps_per_epoch=T, verbose=0)
        tB = _trainer(K, spe)
        # The interruption: epoch 1 trained only S steps (the stream,
        # fresh per fit, consumed exactly the control's first S·K
        # microbatches — steps_per_epoch only caps consumption).
        tB.fit(x=x, y=y, batch_size=4, epochs=2, initial_epoch=1,
               steps_per_epoch=S, verbose=0)
        # The resume: fast-forward S·K microbatches, continue to the end.
        tB.fit(x=x, y=y, batch_size=4, epochs=EPOCHS, initial_epoch=1,
               initial_step=S, steps_per_epoch=T, verbose=0)
        assert _params_bytes(tA) == _params_bytes(tB)

    @pytest.mark.parametrize("K", [1, 4])
    def test_device_cached(self, K):
        # 256 rows over the suite's 8-device mesh: per-shard 32 examples
        # = T·K·batch at K=4 — the epoch exactly covers the shard.
        x, y = _data(256)
        tA = _trainer(K)
        tA.fit(x=x, y=y, batch_size=2, cache="device", epochs=EPOCHS,
               initial_epoch=1, steps_per_epoch=T, verbose=0)
        tB = _trainer(K)
        # The epoch permutation is a pure function of (seed, epoch), so
        # a partial epoch consumes the uninterrupted epoch's prefix...
        tB.fit(x=x, y=y, batch_size=2, cache="device", epochs=2,
               initial_epoch=1, steps_per_epoch=S, verbose=0)
        # ...and the resume gathers/scans from step S of the SAME order.
        tB.fit(x=x, y=y, batch_size=2, cache="device", epochs=EPOCHS,
               initial_epoch=1, initial_step=S, steps_per_epoch=T,
               verbose=0)
        assert _params_bytes(tA) == _params_bytes(tB)

    @pytest.mark.parametrize("K", [1, 4])
    def test_streamed_reshard_at_resume(self, K, monkeypatch):
        """The dataset= path across a reshard: the resumed fit feeds a
        RESHARDED (same-size) recut of the dataset — the elastic
        generation-change shape — and still lands bitwise."""
        monkeypatch.setenv("HVT_NO_NATIVE", "1")
        x, y = _data()

        def chain(ds):
            # Batch divisible by the suite's 8-device data axis.
            return ds.repeat().shuffle(len(x), seed=5).batch(8 * K)

        tA = _trainer(K)
        tA.fit(chain(ArrayDataset((x, y)).shard(0, 1)), epochs=EPOCHS,
               initial_epoch=1, steps_per_epoch=T, verbose=0)
        tB = _trainer(K)
        base = ArrayDataset((x, y)).shard(0, 1)
        tB.fit(chain(base), epochs=2, initial_epoch=1, steps_per_epoch=S,
               verbose=0)
        tB.fit(chain(base.reshard(0, 1)), epochs=EPOCHS, initial_epoch=1,
               initial_step=S, steps_per_epoch=T, verbose=0)
        assert _params_bytes(tA) == _params_bytes(tB)

    def test_batch_indices_resume_at_step(self, monkeypatch):
        """on_batch_end fires with TRUE within-epoch step indices after a
        resume — step-keyed cadences (elastic commits, step faults) stay
        aligned — and the epoch's logged mean covers only the steps the
        resumed fit actually ran."""
        monkeypatch.setenv("HVT_NO_NATIVE", "1")
        x, y = _data()
        seen = []

        class Spy(hvt.callbacks.Callback):
            def on_batch_end(self, batch, logs=None):
                seen.append(batch)

        t = _trainer()
        t.fit(x=x, y=y, batch_size=4, epochs=2, initial_epoch=1,
              initial_step=S, steps_per_epoch=T, callbacks=[Spy()],
              verbose=0)
        assert seen == list(range(S, T))
        assert t._resume_epoch == 1 and t._resume_step == S

    def test_step_rolls_into_next_epoch(self, monkeypatch):
        """A resume point at the epoch's end (a commit taken at the last
        step boundary) normalizes to the NEXT epoch's start."""
        monkeypatch.setenv("HVT_NO_NATIVE", "1")
        x, y = _data()
        t = _trainer()
        hist = t.fit(x=x, y=y, batch_size=4, epochs=3, initial_epoch=1,
                     initial_step=T, steps_per_epoch=T, verbose=0)
        # (1, T) ≡ (2, 0): exactly one epoch (epoch 2) runs.
        assert len(hist) == 1
        assert t._resume_epoch == 2 and t._resume_step == 0

    def test_negative_step_rejected(self):
        x, y = _data()
        t = _trainer()
        with pytest.raises(ValueError, match="initial_step"):
            t.fit(x=x, y=y, batch_size=4, epochs=2, initial_step=-1,
                  steps_per_epoch=T, verbose=0)
