"""hvt-tune — the trace-replay autotuner (ISSUE 19).

Covers the pieces in isolation and the seams between them:

* the paired-leg A/B discipline over a FAKE clock (alternating order,
  median-of-pair-diffs statistic, MAD-adaptive stop vs pair cap);
* candidate-space enumeration from registry ``tunable=`` metadata, and
  the no-drift tie to `collectives.DEFAULT_BUCKET_BYTES`;
* evidence loading (wrapper rows, bare rows, legacy rows without a
  stamped ``config:`` block, garbage files);
* the analytic model against SYNTHETIC evidence built so the optimum
  is known in closed form (n* = sqrt(hide_rate / alpha) buckets), with
  an independent brute-force argmin cross-check;
* `run_probe_plan` over a fake builder + fake clock;
* in-situ `resolve`: selection, the persisted store, restart REUSE
  (the prober must not run twice), journal event shapes;
* the `tune:` job-spec surface (validate_spec, the shipped YAML);
* the `hvt-tune offline --check` tier-1 gate over the repo's own
  recorded evidence, end to end through the real CLI;
* slow: predicted ranking matches the MEASURED A/B ranking on three
  real candidate configs (the offline acceptance gate).
"""

import json
import os
import subprocess
import sys

import pytest
import yaml

from horovod_tpu.analysis import registry
from horovod_tpu.tune import evidence, insitu, model, offline, probe, space

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MB = 1 << 20


# --- the paired-leg discipline over a fake clock ----------------------------


class FakeClock:
    """Legs advance `t` by their scripted duration; paired_compare times
    them by calling clock() around each leg."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def leg(self, durations, calls=None, name=None):
        """A zero-arg leg taking durations[i] seconds on its i-th call
        (the last duration repeats)."""
        state = {"i": 0}

        def run():
            d = durations[min(state["i"], len(durations) - 1)]
            state["i"] += 1
            self.t += d
            if calls is not None:
                calls.append(name)

        return run


class TestPairedCompare:
    def test_alternating_order_cancels_drift(self):
        clock = FakeClock()
        calls = []
        res = probe.paired_compare(
            clock.leg([1.0], calls, "a"), clock.leg([2.0], calls, "b"),
            pairs_min=3, clock=clock,
        )
        # pair 0: a,b — pair 1: b,a — pair 2: a,b
        assert calls == ["a", "b", "b", "a", "a", "b"]
        assert res.pairs == 3
        assert res.median_pct == pytest.approx(100.0)  # b is 2x slower
        assert not res.b_wins

    def test_faster_b_wins(self):
        clock = FakeClock()
        res = probe.paired_compare(
            clock.leg([2.0]), clock.leg([1.0]), pairs_min=3, clock=clock)
        assert res.median_pct == pytest.approx(-50.0)
        assert res.b_wins

    def test_mad_stop_converges_at_pairs_min_on_quiet_host(self):
        clock = FakeClock()
        res = probe.paired_compare(
            clock.leg([1.0]), clock.leg([1.01]), pairs_min=3, pairs_cap=9,
            clock=clock)
        assert res.converged and res.pairs == 3
        assert res.mad_pct == pytest.approx(0.0)

    def test_noisy_host_buys_pairs_until_cap(self):
        clock = FakeClock()
        # leg A drifts monotonically: every pair diff lands somewhere
        # new, the MAD never stabilizes, and the race must run to the
        # cap, unconverged.
        res = probe.paired_compare(
            clock.leg([1.0, 1.2, 1.5, 1.9, 2.4, 3.0, 3.7, 4.5]),
            clock.leg([1.5]),
            pairs_min=3, pairs_cap=7, mad_stop_pct=0.75, clock=clock)
        assert res.pairs == 7
        assert not res.converged

    def test_median_is_outlier_immune(self):
        clock = FakeClock()
        # one catastrophic leg-B outlier in pair 1 (10x) cannot move the
        # median verdict: B is genuinely ~equal elsewhere.
        res = probe.paired_compare(
            clock.leg([1.0]), clock.leg([1.0, 10.0, 1.0, 1.0, 1.0]),
            pairs_min=5, pairs_cap=5, mad_stop_pct=0.0, clock=clock)
        assert res.median_pct == pytest.approx(0.0)

    def test_upper_median(self):
        assert probe.median([3.0, 1.0, 2.0, 4.0]) == 3.0
        with pytest.raises(ValueError):
            probe.median([])


# --- candidate space from registry metadata ---------------------------------


class TestSpace:
    def test_default_bucket_bytes_does_not_drift_from_collectives(self):
        from horovod_tpu.parallel import collectives

        assert space.DEFAULT_BUCKET_BYTES == collectives.DEFAULT_BUCKET_BYTES

    def test_domains_are_the_five_tuned_knobs(self):
        doms = space.domains()
        assert sorted(doms) == [
            "HVT_BACKWARD_PASSES", "HVT_BUCKET_BYTES", "HVT_COMPRESSION",
            "HVT_COMPRESSION_ICI", "HVT_OVERLAP_REDUCTION",
        ]
        assert doms["HVT_OVERLAP_REDUCTION"] == (False, True)
        assert doms["HVT_BACKWARD_PASSES"] == (1, 2, 4, 8)
        assert "none" in doms["HVT_COMPRESSION"]
        assert "bf16" in doms["HVT_COMPRESSION"]
        # log domain: powers of two, 256 KB .. 256 MB inclusive
        bb = doms["HVT_BUCKET_BYTES"]
        assert bb[0] == 1 << 18 and bb[-1] == 1 << 28
        assert all(b & (b - 1) == 0 for b in bb)

    def test_default_config_matches_registry_defaults(self):
        cfg = space.default_config()
        assert cfg["HVT_BUCKET_BYTES"] == space.DEFAULT_BUCKET_BYTES
        assert cfg["HVT_BACKWARD_PASSES"] == 1
        assert cfg["HVT_COMPRESSION"] == "none"
        assert cfg["HVT_OVERLAP_REDUCTION"] is True

    def test_enumerate_restricts_to_named_knobs(self):
        configs = space.enumerate_configs(
            knobs=["HVT_OVERLAP_REDUCTION"], environ={})
        assert len(configs) == 2
        base = space.default_config()
        for c in configs:
            for name in base:
                if name != "HVT_OVERLAP_REDUCTION":
                    assert c[name] == base[name]

    def test_enumerate_pin_and_cross_product(self):
        configs = space.enumerate_configs(
            knobs=["HVT_BUCKET_BYTES", "HVT_OVERLAP_REDUCTION"],
            pin={"HVT_BACKWARD_PASSES": 4}, environ={})
        assert len(configs) == 11 * 2
        assert all(c["HVT_BACKWARD_PASSES"] == 4 for c in configs)

    def test_non_tunable_knob_is_an_error(self):
        with pytest.raises(ValueError, match="not a tunable knob"):
            space.enumerate_configs(knobs=["HVT_FAULT"], environ={})

    def test_env_of_renders_launcher_strings(self):
        env = space.env_of({"HVT_BUCKET_BYTES": 4 * MB,
                            "HVT_OVERLAP_REDUCTION": False})
        assert env == {"HVT_BUCKET_BYTES": "4194304",
                       "HVT_OVERLAP_REDUCTION": "0"}

    def test_deviations_counts_non_default_knobs(self):
        cfg = dict(space.default_config())
        assert space.deviations(cfg) == 0
        cfg["HVT_BUCKET_BYTES"] = 4 * MB
        cfg["HVT_COMPRESSION"] = "bf16"
        assert space.deviations(cfg) == 2


# --- evidence loading -------------------------------------------------------


def _write_row(dirpath, name, row, wrapper=True):
    path = os.path.join(str(dirpath), name)
    payload = ({"n": name, "cmd": "BENCH_MODEL=zero1 python bench.py",
                "rc": 0, "tail": json.dumps(row)}
               if wrapper else row)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f)
    return path


class TestEvidence:
    def test_load_rows_wrapper_bare_and_garbage(self, tmp_path):
        _write_row(tmp_path, "BENCH_r01.json", {"k": 1})
        _write_row(tmp_path, "BENCH_r02.json", {"k": 2}, wrapper=False)
        (tmp_path / "BENCH_r03.json").write_text("{not json")
        (tmp_path / "NOTES.json").write_text("{}")  # not a BENCH row
        rows = evidence.load_rows(str(tmp_path))
        assert [r["k"] for r in rows] == [1, 2]
        assert rows[0]["_source"] == "BENCH_r01.json"
        assert "zero1" in rows[0]["_cmd"]
        assert rows[1]["_cmd"] == ""

    def test_config_of_legacy_row_inferred(self):
        cfg = evidence.config_of({
            "bucket_bytes": 4 * MB, "k": 4, "compression": "none",
            "compression_ici": "none", "overlap_fraction": 0.5,
        })
        assert cfg["HVT_BUCKET_BYTES"] == 4 * MB
        assert cfg["HVT_BACKWARD_PASSES"] == 4
        assert cfg["HVT_OVERLAP_REDUCTION"] is True

    def test_config_of_stamped_block_wins_over_inference(self):
        cfg = evidence.config_of({
            "bucket_bytes": 4 * MB,
            "config": {"HVT_BUCKET_BYTES": 8 * MB,
                       "HVT_OVERLAP_REDUCTION": False},
        })
        assert cfg["HVT_BUCKET_BYTES"] == 8 * MB
        assert cfg["HVT_OVERLAP_REDUCTION"] is False

    def test_anchor_is_newest_row_with_bucket_attribution(self, tmp_path):
        _write_row(tmp_path, "BENCH_r01.json", {
            "step_ms": {"total": 10.0,
                        "comm_buckets": [{"bytes": MB, "ms": 1.0}]}})
        _write_row(tmp_path, "BENCH_r02.json", {
            "step_ms": {"total": 20.0}})  # newer but too thin
        rows = evidence.load_rows(str(tmp_path))
        assert evidence.anchor_row(rows)["_source"] == "BENCH_r01.json"
        assert evidence.anchor_row([]) is None

    def test_comm_points_exclude_quantized_wire_rows(self):
        rows = [
            {"step_ms": {"comm_buckets": [{"bytes": MB, "ms": 2.0}]}},
            {"compression": "int8",
             "step_ms": {"comm_buckets": [{"bytes": MB, "ms": 0.5}]}},
        ]
        assert evidence.comm_points(rows) == [(float(MB), 2.0)]

    def test_wire_ratio(self):
        assert evidence.wire_ratio("none") == 1.0
        assert evidence.wire_ratio("bf16") == 0.5
        assert evidence.wire_ratio("int8") == 0.25
        assert evidence.wire_ratio(None) == 1.0


# --- the analytic model against a known closed-form optimum -----------------

# Synthetic world: alpha = 1 ms/bucket, beta = 1 ms/MB, payload S = 40 MB,
# compute = 500 ms, input = 0, hiding capacity H = 55 ms (kept BELOW the
# anchor's comm so the physical hidden <= comm clamp never rewrites the
# tradeoff under test).
#
#   total(b) = compute + n*alpha + S*beta - min(H*(n-1)/n, comm, compute)
#   with n = ceil(S/b); d/dn [n*alpha - H*(n-1)/n] = 0  =>  n* = sqrt(H/alpha)
#
# Continuous optimum n* = sqrt(55) ~ 7.4; over the discrete bucket domain
# the argmin is n = 10 => bucket_bytes = 4 MB, total = 500.5 ms (n = 5,
# the 8 MB anchor, predicts 501.0 — the discrete neighbors bracket n*).

ALPHA, BETA_PER_MB, S_MB, COMPUTE, HIDE = 1.0, 1.0, 40, 500.0, 55.0


def _synthetic_evidence(tmp_path):
    # Older row at 4 MB buckets: the second distinct size that gives the
    # least-squares fit its slope (all points sit exactly on the line).
    _write_row(tmp_path, "BENCH_r01.json", {
        "k": 1, "bucket_bytes": 4 * MB, "compression": "none",
        "compression_ici": "none", "overlap_fraction": 0.5,
        "step_ms": {
            "total": 400.0,
            "comm_buckets": [{"bytes": 4 * MB,
                              "ms": ALPHA + 4 * BETA_PER_MB}] * 10,
        },
    })
    # Anchor (newest): 5 buckets of 8 MB => comm = 5*1 + 40*1 = 45 ms;
    # serialized = 500 + 45 = 545; hidden at n=5 is H*(4/5) = 44 ms.
    _write_row(tmp_path, "BENCH_r02.json", {
        "k": 1, "bucket_bytes": 8 * MB, "compression": "none",
        "compression_ici": "none", "overlap_fraction": 0.9,
        "serialized_step_ms_total": COMPUTE + 45.0,
        "step_ms": {
            "total": COMPUTE + 45.0 - HIDE * 4 / 5,
            "compute": COMPUTE, "comm": 45.0, "input": 0.0,
            "comm_buckets": [{"bytes": 8 * MB,
                              "ms": ALPHA + 8 * BETA_PER_MB}] * 5,
        },
    })
    return str(tmp_path)


def _closed_form_total(bucket_bytes):
    import math

    n = max(1, math.ceil(S_MB * MB / bucket_bytes))
    comm = n * ALPHA + S_MB * BETA_PER_MB
    hidden = min(HIDE * (n - 1) / n, comm, COMPUTE) if n > 1 else 0.0
    return COMPUTE + comm - hidden


class TestModelClosedForm:
    def test_fit_recovers_the_synthetic_terms(self, tmp_path):
        m = model.fit(evidence.load_rows(_synthetic_evidence(tmp_path)))
        assert m.alpha_ms == pytest.approx(ALPHA, rel=1e-6)
        assert m.beta_ms_per_byte * MB == pytest.approx(BETA_PER_MB,
                                                        rel=1e-6)
        assert m.payload_bytes == S_MB * MB
        assert m.compute_ms == pytest.approx(COMPUTE)
        assert m.hide_rate_ms == pytest.approx(HIDE)
        assert m.n_points == 15
        # every term can say where it came from
        for term in ("alpha/beta", "payload", "compute", "hide_rate",
                     "anchor"):
            assert "BENCH_r" in m.provenance[term] or \
                "comm samples" in m.provenance[term]

    def test_anchor_row_is_reproduced_exactly(self, tmp_path):
        m = model.fit(evidence.load_rows(_synthetic_evidence(tmp_path)))
        pred = m.predict(m.anchor_config)
        assert pred.total_ms == pytest.approx(m.anchor_total_ms, rel=1e-9)

    def test_search_finds_the_closed_form_optimum(self, tmp_path):
        m = model.fit(evidence.load_rows(_synthetic_evidence(tmp_path)))
        scored = offline.rank(m, space.enumerate_configs(
            knobs=["HVT_BUCKET_BYTES", "HVT_OVERLAP_REDUCTION"],
            environ={}))
        win = offline.best(scored)
        # discrete argmin of n*alpha - H*(n-1)/n over the bucket domain:
        # n = 10 buckets over 40 MB => 4 MB cap
        assert win.config["HVT_BUCKET_BYTES"] == 4 * MB
        assert win.config["HVT_OVERLAP_REDUCTION"] is True
        assert win.prediction.total_ms == pytest.approx(500.5)

    def test_model_matches_independent_brute_force(self, tmp_path):
        """The fitted model's argmin over the bucket domain equals a
        from-scratch brute force of the closed-form cost."""
        m = model.fit(evidence.load_rows(_synthetic_evidence(tmp_path)))
        doms = space.domains()["HVT_BUCKET_BYTES"]
        base = space.default_config()
        for b in doms:
            cfg = dict(base, HVT_BUCKET_BYTES=b)
            assert m.predict(cfg).total_ms == pytest.approx(
                _closed_form_total(b), rel=1e-6), f"bucket={b}"
        best_brute = min(doms, key=_closed_form_total)
        best_model = min(
            doms, key=lambda b: m.predict(
                dict(base, HVT_BUCKET_BYTES=b)).total_ms)
        assert best_brute == best_model == 4 * MB

    def test_quantized_wire_is_ranked_but_unevidenced(self, tmp_path):
        m = model.fit(evidence.load_rows(_synthetic_evidence(tmp_path)))
        pred = m.predict(dict(space.default_config(),
                              HVT_COMPRESSION="int8"))
        assert pred.unevidenced == ("HVT_COMPRESSION",)
        scored = offline.rank(m, space.enumerate_configs(environ={}))
        win = offline.best(scored)
        assert win.prediction.evidenced
        # int8 halves-and-halves the wire, so SOME quantized config
        # out-predicts the winner — and is exactly why require_evidence
        # exists: the model invented the quantize cost.
        free = offline.best(scored, require_evidence=False)
        assert free.score <= win.score

    def test_fit_error_without_anchor(self, tmp_path):
        with pytest.raises(model.FitError):
            model.fit([])
        _write_row(tmp_path, "BENCH_r01.json", {"step_ms": {"total": 1.0}})
        with pytest.raises(model.FitError):
            model.fit(evidence.load_rows(str(tmp_path)))

    def test_check_passes_on_synthetic_evidence(self, tmp_path):
        code, msg = offline.check(_synthetic_evidence(tmp_path))
        assert code == 0, msg
        assert "anchor reproduced within" in msg

    def test_check_exit_2_without_evidence(self, tmp_path):
        code, msg = offline.check(str(tmp_path))
        assert code == 2
        assert "no usable evidence" in msg

    def test_report_names_winner_and_provenance(self, tmp_path):
        m = model.fit(evidence.load_rows(_synthetic_evidence(tmp_path)))
        scored = offline.rank(m, space.enumerate_configs(
            knobs=["HVT_BUCKET_BYTES"], environ={}))
        text = offline.render_report(m, scored, top=3)
        assert "winner: bucket=4MB" in text
        assert "BENCH_r02.json" in text          # provenance is visible
        assert "anchor" in text


# --- probe-plan racing over a fake builder ----------------------------------


class TestRunProbePlan:
    def _plan(self):
        base = space.default_config()
        fast = dict(base, HVT_BUCKET_BYTES=4 * MB)
        slow = dict(base, HVT_BUCKET_BYTES=1 << 18)
        return base, fast, slow

    def test_fastest_candidate_wins(self):
        base, fast, slow = self._plan()
        clock = FakeClock()
        speed = {json.dumps(base, sort_keys=True, default=str): 1.0,
                 json.dumps(fast, sort_keys=True, default=str): 0.5,
                 json.dumps(slow, sort_keys=True, default=str): 2.0}

        def builder(cfg, steps=3):
            return clock.leg([speed[json.dumps(cfg, sort_keys=True,
                                               default=str)]])

        out = insitu.run_probe_plan(
            {"default": base, "candidates": [slow, fast], "steps": 3},
            builder=builder, clock=clock)
        assert out["winner"] == fast
        assert out["improvement_pct"] == pytest.approx(50.0)
        assert len(out["results"]) == 2
        assert out["results"][0]["median_pct"] > 0    # slow lost
        assert out["results"][1]["median_pct"] < 0    # fast won

    def test_all_candidates_slower_keeps_the_default(self):
        base, _, slow = self._plan()
        clock = FakeClock()

        def builder(cfg, steps=3):
            return clock.leg([2.0 if cfg == slow else 1.0])

        out = insitu.run_probe_plan(
            {"default": base, "candidates": [slow]},
            builder=builder, clock=clock)
        assert out["winner"] == base
        assert out["improvement_pct"] == 0.0

    def test_candidate_equal_to_default_is_not_raced(self):
        base, fast, _ = self._plan()
        clock = FakeClock()
        built = []

        def builder(cfg, steps=3):
            built.append(cfg)
            return clock.leg([1.0])

        out = insitu.run_probe_plan(
            {"default": base, "candidates": [dict(base), fast]},
            builder=builder, clock=clock)
        assert out["results"][0]["note"] == "is the default"
        # built once for the default leg, once for the real candidate
        assert built == [base, fast]


# --- in-situ resolve: selection, store, restart reuse -----------------------


class TestInsituResolve:
    def _block(self, tmp_path, **over):
        block = {"mode": "offline",
                 "evidence": _synthetic_evidence(tmp_path),
                 "store": str(tmp_path / "models" / "tune.json")}
        block.update(over)
        return block

    def test_mode_off_is_a_no_op(self):
        tuned, event = insitu.resolve({"mode": "off"}, {})
        assert tuned == {}
        assert event == {"event": "tune_off"}

    def test_offline_selects_and_persists(self, tmp_path):
        block = self._block(tmp_path)
        tuned, event = insitu.resolve(block, {})
        assert tuned["HVT_BUCKET_BYTES"] == str(4 * MB)
        assert tuned["HVT_OVERLAP_REDUCTION"] == "1"
        assert event["event"] == "tune_selected"
        assert event["predicted_total_ms"] == pytest.approx(500.5)
        with open(block["store"], encoding="utf-8") as f:
            rec = json.load(f)
        assert rec["env"] == tuned
        assert rec["mode"] == "offline"

    def test_restart_reuses_the_stored_winner(self, tmp_path):
        """The restart contract: same block, same domains -> the stored
        selection is reused verbatim, nothing is re-fit or re-probed."""
        block = self._block(tmp_path)
        first, ev1 = insitu.resolve(block, {})
        os.remove(os.path.join(block["evidence"], "BENCH_r01.json"))
        os.remove(os.path.join(block["evidence"], "BENCH_r02.json"))
        # evidence is GONE — only the store can answer now
        second, ev2 = insitu.resolve(block, {})
        assert second == first
        assert ev1["event"] == "tune_selected"
        assert ev2["event"] == "tune_reused"
        assert ev2["config"] == ev1["config"]

    def test_changed_block_invalidates_the_store(self, tmp_path):
        block = self._block(tmp_path)
        insitu.resolve(block, {})
        changed = dict(block, knobs=["HVT_OVERLAP_REDUCTION"])
        tuned, event = insitu.resolve(changed, {})
        assert event["event"] == "tune_selected"   # re-searched, not reused
        assert "HVT_BUCKET_BYTES" in tuned         # still exported, unvaried

    def test_probe_mode_uses_the_prober_once_then_reuses(self, tmp_path):
        calls = []

        def prober(plan, env):
            calls.append(plan)
            return {"winner": plan["candidates"][0],
                    "improvement_pct": 5.0, "results": []}

        block = self._block(tmp_path, mode="probe", candidates=2, steps=4)
        tuned, event = insitu.resolve(block, {}, prober=prober)
        assert len(calls) == 1
        plan = calls[0]
        assert plan["steps"] == 4
        assert len(plan["candidates"]) == 2
        assert plan["default"] == space.resolved_config(
            environ=dict(os.environ))
        assert event["event"] == "tune_selected"
        assert event["mode"] == "probe"
        # second resolve: the store answers; the prober must NOT run
        insitu.resolve(block, {}, prober=prober)
        assert len(calls) == 1

    def test_job_env_feeds_the_resolution(self, tmp_path):
        """Spec env participates in resolution context (HVT_TUNE_* and
        the baseline the candidates vary from come from the job's
        resolved env, not just the process env)."""
        calls = []

        def prober(plan, env):
            calls.append((plan, env))
            return {"winner": None, "results": []}

        block = self._block(tmp_path, mode="probe")
        insitu.resolve(block, {"HVT_TUNE_STEPS": 7,
                               "HVT_BACKWARD_PASSES": "4"},
                       prober=prober)
        plan, env = calls[0]
        assert plan["steps"] == 7
        assert plan["default"]["HVT_BACKWARD_PASSES"] == 4
        assert env["HVT_TUNE_STEPS"] == "7"

    def test_missing_evidence_is_a_tune_error(self, tmp_path):
        block = {"mode": "offline", "evidence": str(tmp_path),
                 "store": str(tmp_path / "tune.json")}
        with pytest.raises(insitu.TuneError, match="no usable evidence"):
            insitu.resolve(block, {})

    def test_validate_block_rejects_malformed_blocks(self):
        for bad, why in [
            (["probe"], "mapping"),
            ({"mode": "magic"}, "mode"),
            ({"knobs": []}, "non-empty"),
            ({"knobs": ["HVT_FAULT"]}, "not a tunable knob"),
            ({"steps": 0}, "positive int"),
            ({"candidates": "three"}, "positive int"),
            ({"budget": 5}, "unknown keys"),
        ]:
            with pytest.raises(insitu.TuneError, match=why):
                insitu.validate_block(bad)
        insitu.validate_block({})  # empty block = all defaults: valid


# --- the job-spec surface ---------------------------------------------------


class TestJobSpecTune:
    def test_validate_spec_catches_bad_tune_block(self):
        from horovod_tpu.launch.job import validate_spec

        errors = validate_spec({
            "name": "t", "job": {"command": "python x.py", "nprocs": 1,
                                 "tune": {"mode": "magic"}}})
        assert any("job tune:" in e and "mode" in e for e in errors)

    def test_validate_spec_rejects_tune_on_serve_jobs(self):
        from horovod_tpu.launch.job import validate_spec

        errors = validate_spec({
            "name": "t",
            "job": {"serve": {"replicas": 1}, "command": "python x.py",
                    "nprocs": 1, "tune": {"mode": "off"}}})
        assert any("serve" in e and "tune" in e for e in errors)

    def test_shipped_ci_job_carries_a_valid_tune_block(self):
        from horovod_tpu.launch.job import validate_spec

        path = os.path.join(REPO, "horovod_tpu", "launch", "jobs",
                            "mnist-ci-2proc.yaml")
        with open(path, encoding="utf-8") as f:
            spec = yaml.safe_load(f)
        tune = spec["job"]["tune"]
        assert tune["mode"] == "offline"
        assert "HVT_BUCKET_BYTES" in tune["knobs"]
        assert validate_spec(spec) == []


# --- tier-1 gate: the tuner is trustworthy on the repo's own evidence -------


class TestOfflineCheckClean:
    """`hvt-tune offline --check` over the committed BENCH_* rows — the
    recorded evidence loads, the model reproduces the measured anchor,
    and the search beats its own anchor (ISSUE 19's --check gate)."""

    def test_check_exits_zero_on_repo_evidence(self):
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.tune", "offline",
             "--check", "--evidence", REPO],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "hvt-tune check: ok" in proc.stdout

    def test_offline_report_runs_end_to_end(self):
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.tune", "offline",
             "--evidence", REPO, "--top", "5"],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "winner:" in proc.stdout
        assert "calibrated to BENCH_" in proc.stdout

    def test_missing_evidence_exits_two(self, tmp_path):
        proc = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.tune", "offline",
             "--check", "--evidence", str(tmp_path)],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 2, proc.stdout + proc.stderr


# --- slow: predicted ranking vs measured ranking ----------------------------


@pytest.mark.slow
class TestPredictedRankingMatchesMeasured:
    """The offline acceptance gate: on three well-separated candidate
    configs, the analytic model's predicted ORDER matches a real
    paired-leg measurement on this host (the evidence rows were recorded
    on the same container, so the fitted terms transfer)."""

    def test_three_config_ranking(self):
        # The fitted terms only transfer to the workload the evidence
        # describes: bench_zero1's MLP (hidden 2048, ~21 MB of f32
        # gradients, 32/chip over 8 CPU devices). Probing a smaller
        # model would measure a different bucket economy.
        os.environ.setdefault("HVT_PLATFORM", "cpu")
        os.environ.setdefault("HVT_NUM_CPU_DEVICES", "8")
        os.environ.setdefault("HVT_FAST_RNG", "1")
        rows = evidence.load_rows(REPO)
        m = model.fit(rows)
        base = dict(space.default_config(),
                    HVT_BACKWARD_PASSES=m.anchor_k)
        # Three configs along the overlap-starvation axis, where the
        # model's fitted terms and the host's physics agree: the fitted
        # optimum region (4 MB: 6 buckets, comm mostly hidden), a
        # half-starved middle (16 MB: 2 buckets, half the comm exposed)
        # and the monolithic default (64 MB: one bucket, nothing to
        # overlap).  Sub-MB fragmentation is deliberately NOT a
        # candidate: the serialized per-bucket alpha the model
        # extrapolates from does not transfer to overlapped execution,
        # where launch costs hide under compute.
        configs = [dict(base, HVT_BUCKET_BYTES=b)
                   for b in (4 * MB, 16 * MB, 64 * MB)]
        predicted = [m.predict(c).total_ms for c in configs]

        legs = []
        for c in configs:
            leg = insitu.build_probe_step(c, hidden=2048,
                                          per_chip_batch=32, steps=2)
            leg()  # settle
            legs.append(leg)
        # measure each leg against the first with the paired discipline;
        # the sign/magnitude of the medians orders the configs.
        rel = [0.0]
        for leg in legs[1:]:
            res = probe.paired_compare(legs[0], leg, pairs_min=3,
                                       pairs_cap=9)
            rel.append(res.median_pct)
        pred_order = sorted(range(3), key=lambda i: predicted[i])
        meas_order = sorted(range(3), key=lambda i: rel[i])
        assert pred_order == meas_order, (
            f"predicted {predicted} (order {pred_order}) vs measured "
            f"relative {rel} (order {meas_order})")
