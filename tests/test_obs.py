"""One-pane-of-glass telemetry (ISSUE 13): the typed metric registry
(declaration discipline, thread safety), the Prometheus text exposition
(golden output, escaping, histogram invariants — the promtool lint rules
as assertions), the metrics HTTP server (+ the on-demand /profile
trigger), structured trace spans, the supervisor /metrics aggregation
(unit + a LIVE supervised elastic scrape over real subprocess workers),
the live trainer-side step-phase sampler, and the `metrics_checks:` CI
gate over exposition dumps."""

import json
import os
import sys
import textwrap
import threading
import time
import urllib.error
import urllib.request

import pytest

from horovod_tpu.obs import core, prom
from horovod_tpu.obs import server as obs_server

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint_exposition(text: str):
    """The promtool-style checks the acceptance criteria name, as one
    reusable assertion walk: HELP/TYPE present (and TYPE valid) for every
    family with samples, histogram buckets cumulative-monotone, the
    ``+Inf`` bucket equal to ``_count``, ``_sum``/``_count`` present."""
    helps, types, samples = set(), {}, {}
    for line in text.splitlines():
        if line.startswith("# HELP "):
            helps.add(line.split()[2])
        elif line.startswith("# TYPE "):
            _, _, name, kind = line.split()
            assert kind in ("counter", "gauge", "histogram")
            types[name] = kind
        elif line.strip():
            name, _, value = line.rpartition(" ")
            samples[name] = float(value)
    for name, kind in types.items():
        assert name in helps, f"{name}: TYPE without HELP"
    # Every sample belongs to a declared family (histogram suffixes fold).
    for sample in samples:
        base = sample.split("{")[0]
        family = base
        for suffix in ("_bucket", "_sum", "_count"):
            if base.endswith(suffix) and base[: -len(suffix)] in types:
                family = base[: -len(suffix)]
        assert family in types, f"sample {sample} has no TYPE line"
    # Histogram invariants per labeled series.
    for name, kind in types.items():
        if kind != "histogram":
            continue
        series = {}
        for sample, value in samples.items():
            if sample.startswith(name + "_bucket"):
                labels = sample[len(name + "_bucket"):]
                pairs = [
                    p for p in labels.strip("{}").split(",")
                    if not p.startswith("le=")
                ]
                key = ",".join(pairs)
                le = [
                    p for p in labels.strip("{}").split(",")
                    if p.startswith("le=")
                ][0][4:].strip('"')
                series.setdefault(key, []).append(
                    (float("inf") if le == "+Inf" else float(le), value)
                )
        for key, buckets in series.items():
            buckets.sort()
            counts = [c for _, c in buckets]
            assert counts == sorted(counts), f"{name}: non-monotone buckets"
            assert buckets[-1][0] == float("inf")
            suffix = "{" + key + "}" if key else ""
            assert samples[name + "_count" + suffix] == buckets[-1][1]
            assert name + "_sum" + suffix in samples


class TestRegistryDiscipline:
    def test_undeclared_names_refused_on_every_verb(self):
        reg = core.Registry()
        for verb in (reg.counter, reg.gauge, reg.histogram,
                     reg.counter_set):
            with pytest.raises(core.UnknownMetricError) as e:
                verb("hvt_not_a_thing", 1.0)
            assert "MetricSpec" in str(e.value)

    def test_kind_mismatch_refused(self):
        reg = core.Registry()
        with pytest.raises(ValueError, match="gauge, not a counter"):
            reg.counter("hvt_mfu")
        with pytest.raises(ValueError, match="counter, not a gauge"):
            reg.gauge("hvt_restarts_total", 1.0)
        with pytest.raises(ValueError, match="not a histogram"):
            reg.histogram("hvt_mfu", 0.5)

    def test_label_set_must_match_declaration(self):
        reg = core.Registry()
        with pytest.raises(ValueError, match="label"):
            reg.gauge("hvt_member_heartbeat_age_seconds", 1.0)  # missing
        with pytest.raises(ValueError, match="label"):
            reg.gauge("hvt_mfu", 1.0, member="m0")  # extra
        reg.gauge("hvt_member_heartbeat_age_seconds", 1.0, member="m0")

    def test_counters_only_go_up(self):
        reg = core.Registry()
        with pytest.raises(ValueError, match="only go up"):
            reg.counter("hvt_restarts_total", -1.0)

    def test_declaration_validation(self):
        # The _decl guards: the catalog cannot ship malformed specs.
        with pytest.raises(ValueError, match="_total"):
            core._decl([core.MetricSpec("hvt_bad", "counter", "x", "obs")])
        with pytest.raises(ValueError, match="bucket edges"):
            core._decl([core.MetricSpec(
                "hvt_bad", "histogram", "x", "obs", buckets=(2.0, 1.0),
            )])
        with pytest.raises(ValueError, match="need bucket"):
            core._decl([core.MetricSpec("hvt_bad", "histogram", "x", "obs")])
        with pytest.raises(ValueError, match="duplicate"):
            core._decl([
                core.MetricSpec("hvt_x", "gauge", "x", "obs"),
                core.MetricSpec("hvt_x", "gauge", "y", "obs"),
            ])

    def test_every_declared_metric_is_well_formed(self):
        # The shipped catalog re-validates through its own guards (METRICS
        # was built by _decl) — spot the conventions tests rely on.
        for s in core.METRICS.values():
            assert s.help and s.subsystem
            if s.kind == "counter":
                assert s.name.endswith("_total")
            if s.kind == "histogram":
                assert s.buckets and list(s.buckets) == sorted(s.buckets)

    def test_thread_safety_no_lost_updates(self):
        reg = core.Registry()
        n, threads = 500, 8

        def work():
            for _ in range(n):
                reg.counter("hvt_scrapes_total")
                reg.histogram("hvt_step_seconds", 0.01)

        ts = [threading.Thread(target=work) for _ in range(threads)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        collected = dict(
            (s.name, series) for s, series in reg.collect()
        )
        assert collected["hvt_scrapes_total"][0][1] == n * threads
        assert collected["hvt_step_seconds"][0][1].count == n * threads

    def test_broken_collector_never_breaks_collect(self):
        reg = core.Registry()
        reg.register_collector(lambda r: 1 / 0)
        reg.register_collector(
            lambda r: r.gauge("hvt_serve_queue_depth", 3)
        )
        names = [s.name for s, _ in reg.collect()]
        assert "hvt_serve_queue_depth" in names


class TestExposition:
    def test_golden_output(self):
        """Byte-exact golden rendering: HELP/TYPE lines, label rendering,
        integer formatting, cumulative histogram with +Inf/_sum/_count."""
        reg = core.Registry()
        reg.counter_set("hvt_restarts_total", 3)
        reg.gauge("hvt_member_heartbeat_age_seconds", 1.5, member="m0")
        reg.histogram(
            "hvt_serve_tpot_seconds", 0.002
        )
        reg.histogram(
            "hvt_serve_tpot_seconds", 0.03
        )
        golden = textwrap.dedent("""\
            # HELP hvt_restarts_total Lifetime restarts the supervisor journaled (fleet relaunches, or per-member replacements in elastic mode).
            # TYPE hvt_restarts_total counter
            hvt_restarts_total 3
            # HELP hvt_member_heartbeat_age_seconds Seconds since each live member's last TCP beat (coordinator clock).
            # TYPE hvt_member_heartbeat_age_seconds gauge
            hvt_member_heartbeat_age_seconds{member="m0"} 1.5
            # HELP hvt_serve_tpot_seconds Time per output token per generate request (decode tail / generated tokens).
            # TYPE hvt_serve_tpot_seconds histogram
            hvt_serve_tpot_seconds_bucket{le="0.0005"} 0
            hvt_serve_tpot_seconds_bucket{le="0.001"} 0
            hvt_serve_tpot_seconds_bucket{le="0.0025"} 1
            hvt_serve_tpot_seconds_bucket{le="0.005"} 1
            hvt_serve_tpot_seconds_bucket{le="0.01"} 1
            hvt_serve_tpot_seconds_bucket{le="0.025"} 1
            hvt_serve_tpot_seconds_bucket{le="0.05"} 2
            hvt_serve_tpot_seconds_bucket{le="0.1"} 2
            hvt_serve_tpot_seconds_bucket{le="0.25"} 2
            hvt_serve_tpot_seconds_bucket{le="0.5"} 2
            hvt_serve_tpot_seconds_bucket{le="1"} 2
            hvt_serve_tpot_seconds_bucket{le="+Inf"} 2
            hvt_serve_tpot_seconds_sum 0.032
            hvt_serve_tpot_seconds_count 2
        """)
        assert prom.render(reg) == golden
        _lint_exposition(prom.render(reg))

    def test_label_value_escaping(self):
        reg = core.Registry()
        tricky = 'a"b\\c\nd'
        reg.gauge(
            "hvt_member_heartbeat_age_seconds", 2.0, member=tricky
        )
        text = prom.render(reg)
        assert 'member="a\\"b\\\\c\\nd"' in text
        assert "\n" not in text.split("member=")[1].split("}")[0].replace(
            "\\n", ""
        )

    def test_declaration_order_is_render_order(self):
        reg = core.Registry()
        reg.gauge("hvt_mfu", 0.2)                 # training
        reg.counter("hvt_restarts_total")         # supervisor (earlier)
        text = prom.render(reg)
        assert text.index("hvt_restarts_total") < text.index("hvt_mfu")

    def test_empty_registry_renders_empty(self):
        assert prom.render(core.Registry()) == ""

    def test_histogram_monotonicity_property(self):
        """Property test: any observation set yields cumulative-monotone
        buckets with +Inf == count and sum == the exact total."""
        import random

        rng = random.Random(13)
        reg = core.Registry()
        values = [
            rng.choice([rng.uniform(0, 0.002), rng.uniform(0, 1.0),
                        rng.uniform(0, 500.0)])
            for _ in range(300)
        ]
        for v in values:
            reg.histogram("hvt_step_seconds", v)
        _lint_exposition(prom.render(reg))
        parsed = prom.parse_text(prom.render(reg))
        assert parsed["hvt_step_seconds_count"] == len(values)
        assert parsed["hvt_step_seconds_sum"] == pytest.approx(sum(values))
        # Bucket counts == exact manual bucketing against the spec edges.
        edges = core.spec("hvt_step_seconds").buckets
        for edge in edges:
            expected = sum(1 for v in values if v <= edge)
            key = f'hvt_step_seconds_bucket{{le="{prom._fmt(edge)}"}}'
            assert parsed[key] == expected

    def test_parse_text_round_trip_and_malformed(self):
        reg = core.Registry()
        reg.counter_set("hvt_restarts_total", 2)
        reg.gauge("hvt_committed_step", 17)
        parsed = prom.parse_text(prom.render(reg))
        assert parsed == {"hvt_restarts_total": 2.0,
                          "hvt_committed_step": 17.0}
        with pytest.raises(ValueError):
            prom.parse_text("hvt_x 1\nnot-a-number-line x y z q\n")


class TestMetricsServer:
    def test_scrape_healthz_and_404(self):
        reg = core.Registry()
        reg.gauge("hvt_mfu", 0.4)
        srv = obs_server.start_metrics_server(0, registry=reg)
        try:
            port = srv.server_address[1]
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics"
            ) as r:
                assert r.status == 200
                assert r.headers["Content-Type"].startswith("text/plain")
                text = r.read().decode()
            assert "hvt_mfu 0.4" in text
            assert "hvt_scrapes_total 1" in text
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz"
            ) as r:
                assert json.loads(r.read())["status"] == "ok"
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(f"http://127.0.0.1:{port}/nope")
            assert e.value.code == 404
        finally:
            srv.shutdown()

    def test_profile_trigger(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HVT_TRACE_DIR", str(tmp_path))
        srv = obs_server.start_metrics_server(0, profile=True)
        try:
            port = srv.server_address[1]
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/profile?seconds=0.3",
                method="POST",
            )
            with urllib.request.urlopen(req) as r:
                body = json.loads(r.read())
            assert body["profiling"].startswith(str(tmp_path))
            # Concurrent capture refused while the first runs.
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{port}/profile?seconds=0.3",
                    method="POST",
                ))
            assert e.value.code == 409
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if os.path.isdir(body["profiling"]) and any(
                    os.scandir(body["profiling"])
                ):
                    break
                time.sleep(0.1)
            assert os.path.isdir(body["profiling"])
        finally:
            srv.shutdown()

    def test_profile_without_dir_is_400(self, monkeypatch):
        monkeypatch.delenv("HVT_TRACE_DIR", raising=False)
        monkeypatch.delenv("HVT_PROFILE", raising=False)
        srv = obs_server.start_metrics_server(0, profile=True)
        try:
            port = srv.server_address[1]
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{port}/profile?seconds=1",
                    method="POST",
                ))
            assert e.value.code == 400
        finally:
            srv.shutdown()


class TestSpans:
    def test_nested_spans_record_parent_depth_rank(self, tmp_path,
                                                   monkeypatch):
        from horovod_tpu import trace

        monkeypatch.setenv("HVT_TRACE_DIR", str(tmp_path))
        monkeypatch.setattr(trace, "_span_writer", trace._SpanWriter())
        with trace.span("outer", epoch=1):
            with trace.span("inner", step=2):
                pass
        files = [f for f in os.listdir(tmp_path) if f.startswith("spans-")]
        assert len(files) == 1 and f"pid{os.getpid()}" in files[0]
        recs = [
            json.loads(l)
            for l in open(os.path.join(tmp_path, files[0]))
        ]
        by_name = {r["name"]: r for r in recs}
        inner, outer = by_name["inner"], by_name["outer"]
        assert inner["parent"] == outer["id"] and outer["parent"] is None
        assert inner["depth"] == 1 and outer["depth"] == 0
        assert inner["step"] == 2 and outer["epoch"] == 1
        assert all(r["rank"] == 0 and r["dur_s"] >= 0 for r in recs)

    def test_spans_off_without_dir(self, tmp_path, monkeypatch):
        from horovod_tpu import trace

        monkeypatch.delenv("HVT_TRACE_DIR", raising=False)
        monkeypatch.setattr(trace, "_span_writer", trace._SpanWriter())
        with trace.span("noop"):
            pass
        assert not any(
            f.startswith("spans-") for f in os.listdir(tmp_path)
        )

    def test_span_write_failure_never_raises(self, tmp_path, monkeypatch):
        from horovod_tpu import trace

        monkeypatch.setenv(
            "HVT_TRACE_DIR", str(tmp_path / "file-not-dir")
        )
        (tmp_path / "file-not-dir").write_text("occupied")
        monkeypatch.setattr(trace, "_span_writer", trace._SpanWriter())
        with trace.span("survives"):  # makedirs fails; span must not
            pass


class _FakeCoord:
    """Duck-typed Coordinator.snapshot for the aggregation unit."""

    def __init__(self, snap):
        self._snap = snap

    def snapshot(self):
        return self._snap


class TestSupervisorMetrics:
    def _journal(self, tmp_path, records):
        p = tmp_path / "restarts.jsonl"
        with open(p, "w") as f:
            for r in records:
                f.write(json.dumps(r) + "\n")
        return str(p)

    def test_aggregates_journal_coord_budget(self, tmp_path):
        from horovod_tpu.elastic.coordinator import PROGRESS_STEP_RADIX
        from horovod_tpu.launch import supervisor

        log = self._journal(tmp_path, [
            {"name": "start", "value": 3.0, "generation": 1, "size": 3},
            {"name": "restarts", "value": 1.0},
            {"name": "shrink", "value": 2.0, "generation": 2, "size": 2},
            {"name": "restarts", "value": 2.0},
            {"name": "grow", "value": 3.0, "generation": 3, "size": 3},
            {"name": "supervisor_gave_up", "value": 1.0},
        ])
        coord = _FakeCoord({
            "generation": 4,
            "last_settle": {"size": 3},
            "members": {
                "m0": {"status": "live", "beat_age_s": 0.5,
                       "progress": 2 * PROGRESS_STEP_RADIX + 7},
                "m1": {"status": "live", "beat_age_s": 1.25,
                       "progress": 2 * PROGRESS_STEP_RADIX + 5},
                "m2": {"status": "left", "beat_age_s": None,
                       "progress": -1},
            },
        })
        reg = supervisor.supervisor_metrics(
            log, coord, {"max": 3, "used": 2}
        )
        values = prom.parse_text(prom.render(reg))
        assert values["hvt_restarts_total"] == 2
        assert values["hvt_fleet_shrinks_total"] == 1
        assert values["hvt_fleet_grows_total"] == 1
        assert values["hvt_supervisor_gave_up_total"] == 1
        assert values["hvt_elastic_generation"] == 4
        assert values["hvt_fleet_size"] == 3
        assert values["hvt_fleet_live_members"] == 2
        assert values['hvt_member_heartbeat_age_seconds{member="m0"}'] == 0.5
        assert values['hvt_member_heartbeat_age_seconds{member="m1"}'] == 1.25
        assert 'member="m2"' not in prom.render(reg)
        assert values["hvt_committed_epoch"] == 2
        assert values["hvt_committed_step"] == 7
        assert values["hvt_restart_budget_remaining"] == 1
        _lint_exposition(prom.render(reg))

    def test_manifest_progress_single_and_sharded(self, tmp_path):
        from horovod_tpu.launch import supervisor

        d = tmp_path / "models"
        d.mkdir()
        (d / "checkpoint-2.msgpack.meta.json").write_text(json.dumps({
            "epoch": 2, "step": 0, "payload_sha256": "x",
            "cursor": {"position": {"steps_per_epoch": 40}},
        }))
        (d / "checkpoint-3.sharded").mkdir()
        (d / "checkpoint-3.sharded" / "index.json").write_text(json.dumps({
            "format": 1, "progress": {"epoch": 3, "step": 5},
        }))
        epoch, step, total, spe = supervisor.manifest_progress(str(d))
        # Sharded manifest is newest by (epoch, step); no cursor there,
        # so cumulative degrades to the within-epoch step.
        assert (epoch, step, spe) == (3, 5, None)
        # Single-file manifest alone: cumulative = 2 x 40 + 0, and the
        # epoch geometry is surfaced for marker conversion.
        os.remove(d / "checkpoint-3.sharded" / "index.json")
        assert supervisor.manifest_progress(str(d)) == (2, 0, 80, 40)
        # Torn manifest skipped, not fatal.
        (d / "checkpoint-9.msgpack.meta.json").write_text("{torn")
        assert supervisor.manifest_progress(str(d))[0] == 2
        assert supervisor.manifest_progress(None) == (-1, -1, -1, None)

    def test_fresher_marker_keeps_cumulative_scale(self, tmp_path):
        """A sub-epoch elastic commit marker fresher than the manifest
        must convert onto the manifest's cumulative scale, not clobber
        the total with a within-epoch step (review fix)."""
        from horovod_tpu.elastic.coordinator import PROGRESS_STEP_RADIX
        from horovod_tpu.launch import supervisor

        d = tmp_path / "models"
        d.mkdir()
        (d / "checkpoint-0.msgpack.meta.json").write_text(json.dumps({
            "epoch": 0, "step": 99,
            "cursor": {"position": {"steps_per_epoch": 100}},
        }))
        coord = _FakeCoord({
            "generation": 2, "last_settle": {"size": 1},
            "members": {"m0": {
                "status": "live", "beat_age_s": 0.1,
                "progress": 1 * PROGRESS_STEP_RADIX + 10,
            }},
        })
        reg = supervisor.supervisor_metrics(None, coord, None, str(d))
        values = prom.parse_text(prom.render(reg))
        assert values["hvt_committed_epoch"] == 1
        assert values["hvt_committed_step"] == 110  # 1x100 + 10, not 99

    def test_dump_and_gate(self, tmp_path, capsys):
        from horovod_tpu.launch import ci_gate, supervisor

        log = self._journal(tmp_path, [
            {"name": "start", "value": 2.0, "generation": 1, "size": 2},
        ])
        d = tmp_path / "models"
        d.mkdir()
        (d / "checkpoint-1.msgpack.meta.json").write_text(json.dumps({
            "epoch": 1, "step": 0,
            "cursor": {"position": {"steps_per_epoch": 40}},
        }))
        path = supervisor.dump_metrics(log, None, {"max": 2, "used": 0},
                                       str(d))
        assert path == str(d / "metrics.prom")
        assert ci_gate.run_prom_checks(path, {
            "hvt_committed_step": {"target": "1..1000000"},
            "hvt_restarts_total": {"target": "0..0"},
        })
        assert not ci_gate.run_prom_checks(path, {
            "hvt_restarts_total": {"target": "1..9"},
        })
        # Absent series and missing dump both fail loudly.
        assert not ci_gate.run_prom_checks(path, {
            "hvt_mfu": {"target": "0..1"},
        })
        assert not ci_gate.run_prom_checks(
            str(tmp_path / "nope.prom"), {"hvt_mfu": {"target": "0..1"}}
        )
        out = capsys.readouterr().out
        assert "PASS" in out and "FAIL" in out

    def test_job_metrics_checks_require_supervision(self, tmp_path):
        from horovod_tpu.launch import job

        spec = tmp_path / "job.yaml"
        spec.write_text(textwrap.dedent(f"""\
            name: t
            job:
              command: {sys.executable} -c "pass"
              nprocs: 1
            metrics_checks:
              hvt_restarts_total: {{target: "0..0"}}
        """))
        assert job.run_job(str(spec)) == 1

    def test_shipped_ci_job_spec_parses_with_metrics_checks(self):
        import yaml

        with open(os.path.join(
            REPO, "horovod_tpu", "launch", "jobs", "mnist-ci-2proc.yaml"
        )) as f:
            spec = yaml.safe_load(f)
        checks = spec["metrics_checks"]
        assert "hvt_committed_step" in checks
        assert checks["hvt_restarts_total"]["target"] == "0..0"
        # ISSUE 15: the skew-series presence gate over the /fleet-merged
        # dump (rank-labeled — parse_text keys carry rendered labels).
        assert 'hvt_step_skew_ms{rank="0"}' in checks
        for name in checks:
            assert core.is_declared(name.split("{", 1)[0])


FAKE_DIR = os.path.join(REPO, "tests")


class TestLiveSupervisorScrape:
    """The acceptance shape: GET /metrics against a LIVE supervised
    elastic run (real subprocess fake workers speaking the rendezvous
    wire protocol) returns valid exposition carrying restart-journal
    counts, elastic generation and committed progress."""

    def test_scrape_live_supervised_elastic_run(self, tmp_path):
        import socket

        from test_elastic import write_fake_worker

        from horovod_tpu.launch.supervisor import (
            ElasticPolicy,
            RestartPolicy,
            supervise_elastic,
        )

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        argv = write_fake_worker(tmp_path)
        log = tmp_path / "restarts.jsonl"
        result = {}

        def run():
            result["code"] = supervise_elastic(
                2, argv, env={"FAKE_EPOCHS": "14", "FAKE_PACE": "0.25"},
                policy=RestartPolicy(max_restarts=2, backoff=0.0,
                                     grace_seconds=5.0),
                elastic=ElasticPolicy(min_ranks=1,
                                      rendezvous_timeout=20.0),
                log_path=str(log), status_port=port,
            )

        t = threading.Thread(target=run, daemon=True)
        t.start()
        text = None
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=2
                ) as r:
                    candidate = r.read().decode()
                values = prom.parse_text(candidate)
                if (
                    "hvt_elastic_generation" in values
                    and values.get("hvt_fleet_live_members") == 2
                    and "hvt_committed_step" in values
                ):
                    text = candidate
                    break
            except (urllib.error.URLError, OSError, ConnectionError):
                pass
            time.sleep(0.2)
        assert text is not None, "never scraped a settled fleet"
        _lint_exposition(text)
        values = prom.parse_text(text)
        assert values["hvt_restarts_total"] == 0
        assert values["hvt_fleet_size"] == 2
        assert values["hvt_restart_budget_remaining"] == 2
        assert values['hvt_member_heartbeat_age_seconds{member="m0"}'] >= 0
        assert values["hvt_committed_epoch"] >= 0
        t.join(timeout=60)
        assert result.get("code") == 0
        # The final dump landed beside the journal for post-run gating.
        dump = tmp_path / "metrics.prom"
        assert dump.exists()
        prom.parse_text(dump.read_text())


class TestTrainerExporter:
    @pytest.fixture(autouse=True)
    def _fresh_exporter(self, monkeypatch):
        # The exporter is a process singleton by design; tests get a
        # fresh one and the default registry is cleared.
        monkeypatch.setattr(obs_server, "_trainer_exporter", None)
        core.reset()
        yield
        srv = obs_server.trainer_exporter()
        if srv is not None:
            srv.shutdown()
        monkeypatch.setattr(obs_server, "_trainer_exporter", None)
        core.reset()

    def test_exporter_off_without_knob(self, monkeypatch):
        monkeypatch.delenv("HVT_METRICS_PORT", raising=False)
        assert obs_server.ensure_trainer_exporter() is None

    def test_live_fit_publishes_step_phase_gauges(self, tmp_path,
                                                  monkeypatch):
        import flax.linen as nn
        import numpy as np
        import optax

        import horovod_tpu as hvt

        monkeypatch.setenv("HVT_METRICS_PORT", "0")
        monkeypatch.setenv("HVT_METRICS_EVERY", "2")
        monkeypatch.setenv("HVT_PEAK_FLOPS", "1e12")  # skip calibration
        monkeypatch.setenv("HVT_TRACE_DIR", str(tmp_path / "spans"))
        from horovod_tpu import trace

        monkeypatch.setattr(trace, "_span_writer", trace._SpanWriter())

        class M(nn.Module):
            @nn.compact
            def __call__(self, x, *, train: bool = False):
                return nn.Dense(4)(x.astype("float32"))

        t = hvt.Trainer(M(), hvt.DistributedOptimizer(optax.adam(1e-3)))
        rng = np.random.RandomState(0)
        x = rng.rand(64, 8).astype(np.float32)
        y = rng.randint(0, 4, 64).astype(np.int32)
        t.fit(x=x, y=y, batch_size=8, epochs=3, verbose=0)
        srv = obs_server.trainer_exporter()
        assert srv is not None
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.server_address[1]}/metrics"
        ) as r:
            text = r.read().decode()
        _lint_exposition(text)
        values = prom.parse_text(text)
        # Non-null step-phase and MFU gauges — the acceptance criterion.
        for phase in ("total", "compute", "comm", "input"):
            key = f'hvt_step_phase_ms{{phase="{phase}"}}'
            assert key in values and values[key] >= 0
        total = values['hvt_step_phase_ms{phase="total"}']
        phases = sum(
            values[f'hvt_step_phase_ms{{phase="{p}"}}']
            for p in ("compute", "comm", "input")
        )
        assert phases <= total * 1.001  # the bench clamp discipline
        assert values["hvt_mfu"] > 0
        assert values["hvt_peak_flops_per_chip"] == 1e12
        assert values["hvt_examples_per_sec"] > 0
        assert values["hvt_accum_k"] == 1
        import jax

        steps_per_epoch = len(x) // (8 * jax.device_count())
        assert values["hvt_optimizer_steps_total"] == 3 * steps_per_epoch
        assert values["hvt_step_samples_total"] >= 1
        assert values["hvt_step_seconds_count"] >= 1
        assert values['hvt_data_retries_total{outcome="retried"}'] == 0
        assert values['hvt_data_retries_total{outcome="exhausted"}'] == 0
        # The step/reduction spans landed in HVT_TRACE_DIR.
        span_dir = tmp_path / "spans"
        files = [
            f for f in os.listdir(span_dir) if f.startswith("spans-")
        ]
        assert files
        names = {
            json.loads(l)["name"]
            for l in open(os.path.join(span_dir, files[0]))
        }
        assert {"step", "reduction"} <= names


class TestCheckpointSpan:
    def test_save_emits_checkpoint_span(self, tmp_path, monkeypatch):
        import numpy as np

        from horovod_tpu import checkpoint, trace

        monkeypatch.setenv("HVT_TRACE_DIR", str(tmp_path / "spans"))
        monkeypatch.setattr(trace, "_span_writer", trace._SpanWriter())
        checkpoint.save(
            str(tmp_path / "checkpoint-1.msgpack"),
            {"w": np.zeros(3, np.float32)}, progress=(1, 0),
        )
        files = os.listdir(tmp_path / "spans")
        recs = [
            json.loads(l)
            for l in open(os.path.join(tmp_path / "spans", files[0]))
        ]
        assert any(
            r["name"] == "checkpoint_save"
            and r["path"] == "checkpoint-1.msgpack"
            for r in recs
        )

    def test_commit_emits_span(self, tmp_path, monkeypatch):
        from horovod_tpu import trace
        from horovod_tpu.elastic.state import ElasticState

        monkeypatch.setenv("HVT_TRACE_DIR", str(tmp_path / "spans"))
        monkeypatch.setattr(trace, "_span_writer", trace._SpanWriter())
        st = ElasticState(epoch=2)
        st.step = 3
        st.commit()
        files = os.listdir(tmp_path / "spans")
        recs = [
            json.loads(l)
            for l in open(os.path.join(tmp_path / "spans", files[0]))
        ]
        assert any(
            r["name"] == "commit" and r["epoch"] == 2 and r["step"] == 3
            for r in recs
        )
