"""Collective semantics: the Horovod C++-core parity surface (SURVEY.md §3.5).

Critical details under test: AVERAGE (not sum) reduction, root-selective
broadcast, allgather concatenation — exercised through shard_map over the
8-fake-device mesh, the traced context real training uses.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvt
from horovod_tpu.parallel import collectives

try:
    from jax import shard_map

    def smap(f, mesh, in_specs, out_specs):
        return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
except ImportError:  # older spelling
    from jax.experimental.shard_map import shard_map

    def smap(f, mesh, in_specs, out_specs):
        return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


@pytest.fixture(scope="module")
def mesh():
    return hvt.data_parallel_mesh()


def per_worker_values(mesh):
    # worker i holds value i: [0..7], one element per data shard
    return jnp.arange(8, dtype=jnp.float32)


def test_allreduce_average_semantics(mesh):
    x = per_worker_values(mesh)
    out = smap(
        lambda v: collectives.allreduce(v, average=True, axis_name="data"),
        mesh, P("data"), P("data"),
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 3.5))


def test_allreduce_sum(mesh):
    x = per_worker_values(mesh)
    out = smap(
        lambda v: collectives.allreduce(v, average=False, axis_name="data"),
        mesh, P("data"), P("data"),
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))


def test_broadcast_from_root(mesh):
    x = per_worker_values(mesh)
    for root in (0, 3):
        out = smap(
            lambda v: collectives.broadcast(v, root=root, axis_name="data"),
            mesh, P("data"), P("data"),
        )(x)
        np.testing.assert_allclose(np.asarray(out), np.full(8, float(root)))


def test_allgather_concatenates(mesh):
    x = jnp.arange(16, dtype=jnp.float32)  # 2 per worker
    out = smap(
        lambda v: collectives.allgather(v, axis_name="data"),
        mesh, P("data"), P("data"),
    )(x)
    # every worker gets the full 16-vector; stacked along data -> (8*16,)
    assert out.shape == (8 * 16,)
    np.testing.assert_allclose(np.asarray(out)[:16], np.arange(16))


def test_pmean_pytree(mesh):
    tree = {"a": jnp.arange(8.0), "b": {"c": jnp.arange(8.0) * 2}}
    out = smap(
        lambda t: collectives.pmean_pytree(t, axis_name="data"),
        mesh, P("data"), P("data"),
    )(tree)
    np.testing.assert_allclose(np.asarray(out["a"]), np.full(8, 3.5))
    np.testing.assert_allclose(np.asarray(out["b"]["c"]), np.full(8, 7.0))


def test_eager_single_process_degradation():
    # README.md:49-52 no-launcher mode: collectives are identity at size 1.
    x = jnp.arange(4.0)
    np.testing.assert_allclose(collectives.allreduce(x), x)
    np.testing.assert_allclose(collectives.broadcast(x, root=0), x)
    np.testing.assert_allclose(collectives.allgather(x), x)
    m = collectives.metric_mean({"loss": 0.5, "acc": 0.9})
    assert m == {"loss": 0.5, "acc": pytest.approx(0.9)}


def test_distributed_optimizer_averages_grads(mesh):
    """hvd.DistributedOptimizer parity: per-worker grads are averaged before
    the update (tensorflow2_keras_mnist.py:58; average-not-sum §3.5)."""
    import optax

    tx = hvt.DistributedOptimizer(optax.sgd(1.0), axis_name="data")
    params = jnp.zeros(8)

    def step(p, g):
        state = tx.init(p)
        updates, _ = tx.update(g, state, p)
        return optax.apply_updates(p, updates)

    grads = jnp.arange(8, dtype=jnp.float32)  # worker i grad = i
    new_params = smap(step, mesh, (P("data"), P("data")), P("data"))(params, grads)
    # sgd(1.0): p - mean(grads) = -3.5 on every worker
    np.testing.assert_allclose(np.asarray(new_params), np.full(8, -3.5))


class TestCompression:
    """`compression=` on DistributedOptimizer — Horovod's Compression.fp16
    role: gradients cross the interconnect in 16 bits, arrive back in f32."""

    def _step_fn(self, compression):
        import optax

        tx = hvt.DistributedOptimizer(
            optax.sgd(1.0), axis_name="data", compression=compression
        )

        def step(p, g):
            state = tx.init(p)
            updates, _ = tx.update(g, state, p)
            return optax.apply_updates(p, updates)

        return step

    @pytest.mark.parametrize("compression", ["bf16", "fp16"])
    def test_compressed_mean_and_dtype(self, mesh, compression):
        params = jnp.zeros(8)
        grads = jnp.arange(8, dtype=jnp.float32) + 0.25  # mean = 3.75
        new_params = smap(
            self._step_fn(compression), mesh, (P("data"), P("data")), P("data")
        )(params, grads)
        assert new_params.dtype == jnp.float32  # decompressed after reduce
        # 16-bit mantissa tolerance (bf16: 8 bits → ~0.4% relative)
        np.testing.assert_allclose(
            np.asarray(new_params), np.full(8, -3.75), rtol=5e-3
        )

    def test_non_f32_grads_pass_through(self, mesh):
        """Only f32 gradients are compressed: an already-16-bit or integer
        leaf must not be up/down-cast behind the caller's back."""
        import optax

        tx = hvt.DistributedOptimizer(
            optax.sgd(1.0), axis_name="data", compression="bf16"
        )

        def step(g):
            updates, _ = tx.update(g, tx.init(g * 0))
            return updates

        g16 = jnp.arange(8, dtype=jnp.bfloat16)
        out = smap(step, mesh, (P("data"),), P("data"))(g16)
        assert out.dtype == jnp.bfloat16

    def test_unknown_compression_rejected(self):
        import optax

        with pytest.raises(ValueError, match="compression"):
            hvt.DistributedOptimizer(optax.sgd(1.0), compression="int4")

    def test_spmd_mode_accepts_and_is_inert(self, mesh):
        """Without axis_name (SPMD-jit mode) the argument validates but the
        update path is untouched — XLA owns the reduction there."""
        import optax

        tx = hvt.DistributedOptimizer(optax.sgd(1.0), compression="bf16")
        p = jnp.ones(4)
        g = jnp.full(4, 2.0)
        updates, _ = tx.update(g, tx.init(p), p)
        np.testing.assert_allclose(np.asarray(updates), np.full(4, -2.0))
        assert updates.dtype == jnp.float32
