"""Fused chunked linear-CE (ops/fused_ce.py) and the Trainer loss='module'
contract: math parity with the dense logits path, gradient parity through
the custom VJP, and the memory claim (no full [B·T, vocab] logits array)
verified against XLA's own memory analysis."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvt
from horovod_tpu.models.transformer import TransformerLM
from horovod_tpu.ops.fused_ce import fused_linear_cross_entropy


def _dense_loss(h, w, labels):
    logits = (h.astype(jnp.float32) @ w.astype(jnp.float32))
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels)


class TestFusedLinearCrossEntropy:
    def _data(self, b=2, t=24, d=16, v=37, dtype=jnp.float32, seed=0):
        rng = np.random.RandomState(seed)
        h = jnp.asarray(rng.randn(b, t, d), dtype)
        w = jnp.asarray(rng.randn(d, v) / np.sqrt(d), jnp.float32)
        labels = jnp.asarray(rng.randint(0, v, size=(b, t)), jnp.int32)
        return h, w, labels

    @pytest.mark.parametrize("n_chunks", [1, 3, 8])
    def test_loss_matches_dense(self, n_chunks):
        # 3 chunks: 48 rows pad to 3×16 — the non-divisible path.
        h, w, labels = self._data()
        loss, correct = fused_linear_cross_entropy(h, w, labels, n_chunks)
        assert loss.shape == labels.shape and correct.shape == labels.shape
        ref = _dense_loss(h, w, labels)
        np.testing.assert_allclose(loss, ref, rtol=1e-5, atol=1e-5)

    def test_correct_indicator_matches_argmax(self):
        h, w, labels = self._data()
        _, correct = fused_linear_cross_entropy(h, w, labels, 4)
        pred = jnp.argmax(h @ w, axis=-1)
        np.testing.assert_array_equal(
            np.asarray(correct, bool), np.asarray(pred == labels)
        )

    @pytest.mark.parametrize("n_chunks", [1, 5])
    def test_gradients_match_dense(self, n_chunks):
        h, w, labels = self._data()

        def fused(h, w):
            loss, _ = fused_linear_cross_entropy(h, w, labels, n_chunks)
            return loss.mean()

        def dense(h, w):
            return _dense_loss(h, w, labels).mean()

        (dh_f, dw_f) = jax.grad(fused, argnums=(0, 1))(h, w)
        (dh_d, dw_d) = jax.grad(dense, argnums=(0, 1))(h, w)
        np.testing.assert_allclose(dh_f, dh_d, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(dw_f, dw_d, rtol=1e-5, atol=1e-6)

    def test_bf16_hidden_states(self):
        h, w, labels = self._data(dtype=jnp.bfloat16)
        loss, _ = fused_linear_cross_entropy(h, w, labels, 4)
        ref = _dense_loss(
            h.astype(jnp.float32), w, labels
        )
        # bf16 inputs with f32 MXU accumulation: 8-bit-mantissa input error.
        np.testing.assert_allclose(loss, ref, rtol=3e-2, atol=3e-2)
        dh = jax.grad(
            lambda h: fused_linear_cross_entropy(h, w, labels, 4)[0].mean()
        )(h)
        assert dh.dtype == jnp.bfloat16

    def test_correct_cotangent_is_discarded(self):
        # Differentiating THROUGH the correctness indicator must not
        # contribute (argmax is piecewise constant, like the dense path).
        h, w, labels = self._data()

        def f(h):
            loss, correct = fused_linear_cross_entropy(h, w, labels, 2)
            return loss.mean() + 7.0 * correct.sum()

        dh = jax.grad(f)(h)
        dh_ref = jax.grad(
            lambda h: fused_linear_cross_entropy(h, w, labels, 2)[0].mean()
        )(h)
        np.testing.assert_allclose(dh, dh_ref, rtol=1e-6)

    def test_peak_memory_scales_down_with_chunks(self):
        # The op's reason to exist: XLA's own accounting shows the compiled
        # backward never holds the full [N, V] logits when chunked. Sized so
        # logits (256·rows × 4096·vocab × 4 B ≈ 4 MB/copy) dominate.
        b, t, d, v = 2, 128, 32, 4096
        rng = np.random.RandomState(0)
        h = jnp.asarray(rng.randn(b, t, d), jnp.float32)
        w = jnp.asarray(rng.randn(d, v) / 6.0, jnp.float32)
        labels = jnp.asarray(rng.randint(0, v, size=(b, t)), jnp.int32)

        def temp_bytes(n_chunks):
            def f(h, w):
                loss, _ = fused_linear_cross_entropy(h, w, labels, n_chunks)
                return loss.mean()

            compiled = jax.jit(jax.grad(f, argnums=(0, 1))).lower(h, w).compile()
            return int(compiled.memory_analysis().temp_size_in_bytes)

        one = temp_bytes(1)   # dense-equivalent: full logits tile
        many = temp_bytes(16)
        assert many < one / 4, (one, many)


@pytest.mark.slow
class TestModuleLossTrainer:
    """TransformerLM(fused_head_chunks=...) + Trainer(loss='module')."""

    def _fit(self, loss, fused_chunks, steps=6, **model_kw):
        model = TransformerLM(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, dropout=0.0,
            fused_head_chunks=fused_chunks, **model_kw,
        )
        trainer = hvt.Trainer(
            model, hvt.DistributedOptimizer(optax.adam(1e-2)), loss=loss
        )
        rng = np.random.RandomState(0)
        x = rng.randint(1, 64, size=(16, 12)).astype(np.int32)
        y = np.roll(x, -1, axis=1).astype(np.int32)
        state = trainer.build(x)
        zero = trainer.zero_metrics()
        losses = []
        for _ in range(steps):
            state, metrics, _ = trainer._train_step(
                state, trainer._shard((x, y)), np.float32(1.0), zero
            )
            losses.append(float(metrics["loss"]))
        trainer.state = state  # the originally-built state was donated
        return trainer, state, losses, (x, y), float(metrics["accuracy"])

    def test_training_matches_logits_path(self):
        _, state_m, losses_m, _, acc_m = self._fit("module", 4)
        _, state_d, losses_d, _, acc_d = self._fit(
            "sparse_categorical_crossentropy", 0
        )
        # Same math, different matmul chunking → fp-accumulation-order-level
        # differences only.
        np.testing.assert_allclose(losses_m, losses_d, rtol=1e-4)
        np.testing.assert_allclose(acc_m, acc_d, rtol=1e-4)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-5),
            state_m.params, state_d.params,
        )

    def test_evaluate_matches_logits_path(self):
        trainer_m, state_m, _, (x, y), _ = self._fit("module", 4, steps=2)
        trainer_d, _, _, _, _ = self._fit(
            "sparse_categorical_crossentropy", 0, steps=2
        )
        # Same trained params through both eval paths — including the padded
        # tail batch (20 examples over batch 8 → mask exercises the
        # per-token broadcast).
        trainer_d.state = trainer_d.state.replace(params=state_m.params)
        xs = np.concatenate([x, x[:4]])
        ys = np.concatenate([y, y[:4]])
        em = trainer_m.evaluate(xs, ys, batch_size=8)
        ed = trainer_d.evaluate(xs, ys, batch_size=8)
        np.testing.assert_allclose(em["loss"], ed["loss"], rtol=1e-4)
        np.testing.assert_allclose(em["accuracy"], ed["accuracy"], rtol=1e-4)

    def test_predict_still_returns_probs(self):
        trainer, _, _, (x, _), _ = self._fit("module", 4, steps=1)
        probs = trainer.predict(x[:4])
        assert probs.shape == (4, 12, 64)
        np.testing.assert_allclose(probs.sum(-1), 1.0, rtol=1e-5)

    def test_composes_with_remat_and_bf16(self):
        # The long-context stack: remat blocks + bf16 compute + fused head.
        _, _, losses, _, _ = self._fit(
            "module", 4, steps=3, remat=True,
            compute_dtype=jnp.bfloat16,
        )
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]

    def test_checkpoint_param_path_unchanged(self):
        # The explicit LMHead keeps the DenseGeneral-era param tree:
        # lm_head/kernel [d_model, vocab] — old checkpoints stay loadable.
        model = TransformerLM(vocab_size=64, d_model=32, n_heads=4, n_layers=1)
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        assert params["lm_head"]["kernel"].shape == (32, 64)


class TestBuildTracesFusedPath:
    def test_init_receives_labels_under_module_loss(self):
        # build() must init with dummy labels so the module traces the
        # fused-CE branch — the dense [B, T, vocab] branch at init is the
        # OOM point at long-context scale (ADVICE r3, trainer.py build).
        seen = []

        class Rec(nn.Module):
            @nn.compact
            def __call__(self, tokens, train: bool = False, labels=None):
                seen.append(labels is not None)
                emb = self.param(
                    "emb", nn.initializers.normal(0.02), (64, 8)
                )
                h = emb[tokens].mean(axis=1) @ emb.T  # [B, 64]
                if labels is None:
                    return h
                loss = optax.softmax_cross_entropy_with_integer_labels(
                    h, labels[:, 0]
                )
                correct = (jnp.argmax(h, -1) == labels[:, 0]).astype(
                    jnp.float32
                )
                return loss, correct

        trainer = hvt.Trainer(
            Rec(), hvt.DistributedOptimizer(optax.adam(1e-2)), loss="module"
        )
        x = np.random.RandomState(0).randint(1, 64, size=(8, 4)).astype(
            np.int32
        )
        trainer.build(x)
        assert seen and all(seen), seen

    def test_build_with_sample_y_for_non_token_labels(self):
        # labels that differ from x in dtype/shape (float inputs, int class
        # labels): build must use the provided sample_y, not zeros_like(x).
        class Clf(nn.Module):
            @nn.compact
            def __call__(self, x, train: bool = False, labels=None):
                w = self.param("w", nn.initializers.normal(0.02), (4, 8))
                h = x @ w  # [B, 8] logits
                if labels is None:
                    return h
                loss = optax.softmax_cross_entropy_with_integer_labels(
                    h, labels
                )
                correct = (jnp.argmax(h, -1) == labels).astype(jnp.float32)
                return loss, correct

        trainer = hvt.Trainer(
            Clf(), hvt.DistributedOptimizer(optax.adam(1e-2)), loss="module"
        )
        x = np.random.RandomState(0).randn(16, 4).astype(np.float32)
        y = (np.arange(16) % 8).astype(np.int32)
        # fit threads the real labels through to init.
        history = trainer.fit(x=x, y=y, batch_size=2, epochs=1, verbose=0)
        assert np.isfinite(history[-1]["loss"])

    def test_build_without_sample_y_raises_with_hint(self):
        # Same classifier, but build(x) alone: zeros_like(float x) is a
        # wrong-typed label for the integer-CE branch. The failure must
        # carry a hint naming sample_y instead of an opaque trace error.
        class Clf(nn.Module):
            @nn.compact
            def __call__(self, x, train: bool = False, labels=None):
                w = self.param("w", nn.initializers.normal(0.02), (4, 8))
                h = x @ w
                if labels is None:
                    return h
                loss = optax.softmax_cross_entropy_with_integer_labels(
                    h, labels
                )
                correct = (jnp.argmax(h, -1) == labels).astype(jnp.float32)
                return loss, correct

        trainer = hvt.Trainer(
            Clf(), hvt.DistributedOptimizer(optax.adam(1e-2)), loss="module"
        )
        x = np.random.RandomState(0).randn(16, 4).astype(np.float32)
        with pytest.raises(Exception, match="sample_y"):
            trainer.build(x)
