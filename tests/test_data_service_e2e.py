"""hvt-data chaos acceptance (slow lane) — the ISSUE 20 e2e runs.

* **The dispatcher-kill chaos run**: a REAL 2-process service-fed fit
  (`examples/service_fed_fit.py`) against an external `hvt-data serve`
  dispatcher subprocess. Mid-run (once epoch 2 is underway) the
  dispatcher is SIGKILLed and restarted on the SAME ``--dir`` + port
  (journal recovery); separately, ``HVT_FAULT=1:1:netdrop:MS`` drops
  rank 1's connection on every fetch of epoch 1 → that rank degrades to
  rank-local feeding from the same cursor and re-attaches at the next
  epoch boundary. The FINAL checkpoint must be byte-identical to an
  uninterrupted, locally-fed control run's, and the per-batch DIGEST_LOG
  sha256 maps must match exactly — the strongest possible statement that
  served, degraded-local, and recovered-dispatcher batches are ONE byte
  stream. The dispatcher also carries ``dataslow`` (its own HVT_FAULT),
  so the per-batch delay path runs under the same roof.

* **The shared-data fleet scenario**: the shipped
  `launch/jobs/fleet-shared-data-2job.yaml` through the real
  `hvt-launch fleet` CLI — fleetd owns one dispatcher, injects
  HVT_DATA_SERVICE into both jobs, and the fleet-level metrics gates
  (per-job ``hvt_data_batches_served_total`` ≥ 1, zero cursor refusals)
  must come back green against the dispatcher's final scrape.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from horovod_tpu.launch import launcher

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLE = os.path.join(REPO, "examples", "service_fed_fit.py")

STEPS, EPOCHS = 25, 6


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_tcp(port, timeout=30):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port), timeout=1).close()
            return
        except OSError:
            time.sleep(0.05)
    raise AssertionError(f"dispatcher never listened on :{port}")


def _fit_env(root, **extra):
    env = {
        **os.environ,
        "HVT_PLATFORM": "cpu",
        "HVT_NUM_CPU_DEVICES": "1",
        "PS_MODEL_PATH": str(root),
        "DIGEST_LOG": str(root / "digests"),
        "DRIVE_STEPS": str(STEPS),
        "DRIVE_EPOCHS": str(EPOCHS),
        "N_ROWS": "400",
        # SIGKILL choreography must not share the suite's persistent XLA
        # cache (torn writes poison later runs — conftest caveat).
        "JAX_ENABLE_COMPILATION_CACHE": "0",
        "JAX_COMPILATION_CACHE_DIR": "",
        **{k: str(v) for k, v in extra.items()},
    }
    for k in ("HVT_FAULT", "HVT_FAULT_STAMP", "HVT_DATA_SERVICE"):
        env.pop(k, None)
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _digests(path):
    out = {}
    with open(path) as f:
        for line in f:
            rec = json.loads(line)
            key = (rec["epoch"], rec["step"])
            # A key logged twice (consumed again around a failover) must
            # carry the SAME bytes.
            if key in out:
                assert out[key] == rec["sha256"], (
                    f"replayed batch {key} differs"
                )
            out[key] = rec["sha256"]
    return out


def _spawn_dispatcher(dirpath, port):
    env = {**os.environ,
           # The dispatcher-side per-batch delay fault rides along: every
           # shard-0 'next' from epoch 0 on is delayed — which also paces
           # the tiny fit enough to SIGKILL it mid-flight reliably.
           "HVT_FAULT": "0:0:dataslow:20"}
    env.pop("HVT_FAULT_STAMP", None)
    return subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.data.service", "serve",
         "--dir", str(dirpath), "--port", str(port)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


@pytest.mark.slow
def test_dispatcher_sigkill_and_netdrop_end_byte_identical(tmp_path):
    """THE chaos acceptance run: dispatcher SIGKILL + journal-recovered
    restart, a netdrop brownout degrading one rank to local feeding, and
    a FINAL checkpoint byte-identical to the locally-fed control."""
    # Control: same script, no HVT_DATA_SERVICE — pure local feeding.
    ctrl = tmp_path / "ctrl"
    code = launcher.run_local(
        2, [sys.executable, EXAMPLE], env=_fit_env(ctrl), tag_output=False
    )
    assert code == 0

    # Chaos: external dispatcher, netdrop on rank 1 during epoch 1.
    chaos = tmp_path / "chaos"
    dsdir = tmp_path / "dispatch"
    port = _free_port()
    disp = _spawn_dispatcher(dsdir, port)
    killed = restarted = None
    fit = None
    try:
        _wait_tcp(port)
        fit = subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.launch", "run",
             "--nprocs", "2", "--", sys.executable, EXAMPLE],
            env=_fit_env(
                chaos,
                HVT_DATA_SERVICE=f"127.0.0.1:{port}",
                HVT_DATA_RETRIES="2",
                HVT_DATA_BACKOFF_S="0.05",
                HVT_FAULT="1:1:netdrop:5",
            ),
            cwd=REPO,
        )
        # SIGKILL the dispatcher once epoch 2 is underway (the digest
        # audit stream is the ground truth for "underway").
        digest0 = chaos / "digests.rank0"
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if fit.poll() is not None:
                break
            try:
                with open(digest0) as f:
                    if any(json.loads(l)["epoch"] >= 2
                           for l in f if l.strip()):
                        break
            except OSError:
                pass
            time.sleep(0.02)
        assert fit.poll() is None, "fit finished before the kill window"
        disp.kill()
        disp.wait()
        killed = True
        time.sleep(0.5)  # a real outage: retries drain, ranks degrade
        disp = _spawn_dispatcher(dsdir, port)  # SAME dir + port: recovery
        _wait_tcp(port)
        restarted = True
        assert fit.wait(timeout=600) == 0
        # The restarted dispatcher ADOPTED the journaled admissions: a
        # SPEC-LESS hello (the re-attach form) succeeds, and the batch it
        # serves is byte-identical to the local derivation — journal
        # recovery, proven at the byte level.
        from horovod_tpu.data import service as service_lib
        from horovod_tpu.data.client import build_source

        spec = {
            "source": "npz", "path": str(chaos / "corpus.npz"),
            "keys": ["x", "y"], "batch_size": 8, "seed": 11,
            "shuffle_buffer": 0, "shard": [0, 2],
        }
        sock = socket.create_connection(("127.0.0.1", port), timeout=10)
        try:
            service_lib.send_frame(sock, {
                "op": "hello", "job": "default", "shard": [0, 2],
            })
            resp, _ = service_lib.recv_frame(sock)
            assert resp["ok"] and resp["adopted"], resp
            cursor = build_source(spec).stream_cursor(
                0, 0, batches_per_epoch=STEPS
            ).to_dict()
            service_lib.send_frame(sock, {
                "op": "next", "job": "default", "shard": [0, 2],
                "cursor": cursor,
            })
            resp, payload = service_lib.recv_frame(sock)
            assert resp["ok"], resp
            import numpy as np

            x, y = next(build_source(spec).batches(batches_per_epoch=STEPS))
            want = (np.ascontiguousarray(x).tobytes()
                    + np.ascontiguousarray(y).tobytes())
            assert payload == want
        finally:
            sock.close()
    finally:
        if fit is not None and fit.poll() is None:
            fit.kill()
        disp.kill()
        disp.wait()
    assert killed and restarted

    # Byte-identity, the strongest form first: the FINAL checkpoint.
    final = f"checkpoint-{EPOCHS}.msgpack"
    a = (ctrl / "service-fed" / final).read_bytes()
    b = (chaos / "service-fed" / final).read_bytes()
    assert a == b

    # Per-batch digest identity on BOTH ranks, across served, degraded-
    # local, and recovered-dispatcher stretches.
    for rank in (0, 1):
        want = _digests(ctrl / f"digests.rank{rank}")
        got = _digests(chaos / f"digests.rank{rank}")
        assert set(want) == set(got)
        diff = [k for k in want if want[k] != got[k]]
        assert not diff, f"byte-divergent batches at {sorted(diff)[:5]}"

    # The failover arcs really happened: rank 1 degraded (netdrop epoch
    # 1) and re-attached at an epoch boundary.
    with open(chaos / "client-events.rank1.jsonl") as f:
        events = [json.loads(l) for l in f if l.strip()]
    kinds = [e["event"] for e in events]
    assert "degrade" in kinds and "reattach" in kinds
    first_degrade = next(e for e in events if e["event"] == "degrade")
    assert first_degrade["epoch"] == 1  # the netdrop window

    # And the restarted dispatcher genuinely recovered from its journal.
    with open(dsdir / "data-journal.jsonl") as f:
        names = [json.loads(l)["name"] for l in f if l.strip()]
    assert "recover" in names
    assert names.count("serve_start") == 2


@pytest.mark.slow
def test_fleet_shared_data_two_jobs_gates_green(tmp_path):
    """The shipped shared-data fleet spec through the real CLI: one
    fleetd-owned dispatcher feeds both jobs; the fleet-level metrics
    gates against its final scrape come back green (exit 0)."""
    spec_src = os.path.join(REPO, "horovod_tpu", "launch", "jobs",
                            "fleet-shared-data-2job.yaml")
    with open(spec_src) as f:
        text = f.read()
    assert "/tmp/hvt-fleet-data" in text  # the paths this test relocates
    root = str(tmp_path / "fleet-data")
    spec_path = str(tmp_path / "fleet-shared-data-2job.yaml")
    with open(spec_path, "w") as f:  # hvt: noqa[HVT005] — test fixture
        f.write(text.replace("/tmp/hvt-fleet-data", root))

    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "JAX_ENABLE_COMPILATION_CACHE": "0",
        "JAX_COMPILATION_CACHE_DIR": "",
    })
    for k in ("HVT_FAULT", "HVT_FAULT_STAMP", "HVT_DATA_SERVICE"):
        env.pop(k, None)
    res = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.launch", "fleet", spec_path],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=540,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    # The gate verdicts are in the output — and the scrape dump exists
    # for post-mortem.
    assert "metrics check hvt_data_cursor_refusals_total" in res.stdout
    assert os.path.exists(
        os.path.join(root, "fleet-state", "data-metrics.prom")
    )
    journal = os.path.join(root, "fleet-state", "fleet-journal.jsonl")
    with open(journal) as f:
        names = [json.loads(l)["name"] for l in f if l.strip()]
    assert "data_service" in names
    assert "fleet_done" in names
