"""The tfevents writer: byte-level format checks plus the gold-standard
proof — TensorBoard's OWN event-file loader (CRC-verifying) reads our files
and recovers the scalars."""

import json
import struct

import numpy as np
import optax
import pytest

import horovod_tpu as hvt
from horovod_tpu import metrics, tbevents


class TestWireFormat:
    def test_crc32c_known_vectors(self):
        # Standard CRC-32C test vectors.
        assert tbevents._crc32c(b"") == 0x0
        assert tbevents._crc32c(b"123456789") == 0xE3069283

    def test_record_framing_golden(self):
        payload = b"hello"
        rec = tbevents.encode_record(payload)
        (length,) = struct.unpack("<Q", rec[:8])
        assert length == 5
        assert rec[12:17] == payload
        # CRCs verify through the reader.
        assert tbevents.read_records is not None

    def test_roundtrip_with_own_reader(self, tmp_path):
        w = tbevents.TBEventWriter(str(tmp_path))
        w.scalar("loss", 0.25, step=1)
        w.scalars({"loss": 0.125, "accuracy": 0.9}, step=2)
        w.close()
        payloads = tbevents.read_records(w.path)
        assert len(payloads) == 3  # version sentinel + 2 events
        assert b"brain.Event:2" in payloads[0]
        assert b"loss" in payloads[1]

    def test_corruption_detected(self, tmp_path):
        w = tbevents.TBEventWriter(str(tmp_path))
        w.scalar("loss", 0.5, step=1)
        w.close()
        blob = bytearray(open(w.path, "rb").read())
        blob[-6] ^= 0xFF  # flip a payload byte
        open(w.path, "wb").write(bytes(blob))
        with pytest.raises(ValueError, match="crc"):
            tbevents.read_records(w.path)


class TestTensorBoardCompat:
    def test_tensorboard_loader_reads_our_files(self, tmp_path):
        """TensorBoard's EventFileLoader verifies CRCs and parses the proto;
        if it recovers our tags/values/steps, `tensorboard --logdir` works."""
        pytest.importorskip("tensorboard")
        from tensorboard.backend.event_processing import event_file_loader

        w = tbevents.TBEventWriter(str(tmp_path))
        w.scalars({"epoch/loss": 0.75, "epoch/accuracy": 0.5}, step=1,
                  wall_time=123.25)
        w.scalar("epoch/loss", 0.25, step=2)
        w.close()

        events = list(
            event_file_loader.EventFileLoader(w.path).Load()
        )
        assert events[0].file_version == "brain.Event:2"
        scalars = {}
        for ev in events[1:]:
            for val in ev.summary.value:
                # Modern loaders migrate simple_value → tensor form.
                v = (
                    val.tensor.float_val[0]
                    if val.HasField("tensor")
                    else val.simple_value
                )
                scalars.setdefault(val.tag, []).append(
                    (ev.step, round(float(v), 6))
                )
        assert scalars["epoch/loss"] == [(1, 0.75), (2, 0.25)]
        assert scalars["epoch/accuracy"] == [(1, 0.5)]
        assert events[1].wall_time == 123.25


class TestScalarLoggerIntegration:
    def _fit(self, log_dir, sync: bool, tmp_path):
        import flax.linen as nn
        import jax.numpy as jnp

        class Probe(nn.Module):
            @nn.compact
            def __call__(self, x, *, train=False):
                return nn.Dense(10)(x.reshape((x.shape[0], -1)).astype(jnp.float32))

        metrics.set_sink(metrics.NullSink())  # reset module state
        metrics.init(
            sync_tensorboard=sync, path=str(tmp_path / "metrics.jsonl")
        )
        rng = np.random.RandomState(0)
        trainer = hvt.Trainer(Probe(), hvt.DistributedOptimizer(optax.sgd(0.01)))
        trainer.fit(
            x=rng.rand(64, 8, 8, 1).astype(np.float32),
            y=rng.randint(0, 10, 64).astype(np.int32),
            batch_size=4, epochs=2, steps_per_epoch=2, verbose=0,
            callbacks=[
                hvt.callbacks.ScalarLogger(str(log_dir), update_freq="batch")
            ],
        )

    def test_logger_writes_both_formats_and_syncs(self, tmp_path):
        log_dir = tmp_path / "tb"
        self._fit(log_dir, sync=True, tmp_path=tmp_path)
        # JSONL stream
        events = [
            json.loads(l)
            for l in (log_dir / "events.jsonl").read_text().splitlines()
        ]
        assert any("epoch/loss" in e for e in events)
        # Real tfevents file, loadable by tensorboard
        pytest.importorskip("tensorboard")
        from tensorboard.backend.event_processing import event_file_loader

        tb_files = list(log_dir.glob("events.out.tfevents.*"))
        assert len(tb_files) == 1
        loaded = list(event_file_loader.EventFileLoader(str(tb_files[0])).Load())
        tags = {v.tag for ev in loaded for v in ev.summary.value}
        assert "epoch/loss" in tags
        assert any(t.startswith("batch/") for t in tags)
        # sync_tensorboard: epoch scalars reached the platform sink under
        # their plain names.
        pushed = [
            json.loads(l)
            for l in (tmp_path / "metrics.jsonl").read_text().splitlines()
        ]
        assert any(p["name"] == "loss" for p in pushed)

    def test_no_sync_no_pushes(self, tmp_path):
        log_dir = tmp_path / "tb2"
        self._fit(log_dir, sync=False, tmp_path=tmp_path)
        assert not (tmp_path / "metrics.jsonl").exists()
