"""LoRA adapter fine-tuning (models/lora.py): zero-init identity, frozen
base under training, merged-weights equivalence, and composition with the
Trainer / DistributedOptimizer / fused-CE stack."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvt
from horovod_tpu.models import lora
from horovod_tpu.models.lora import LoRAModel
from horovod_tpu.models.transformer import TransformerLM, param_specs
from horovod_tpu.parallel import mesh as mesh_lib


def _lm(**kw):
    return TransformerLM(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, dropout=0.0, **kw
    )


def _data(seed=0, n=16, t=12):
    rng = np.random.RandomState(seed)
    x = rng.randint(1, 64, size=(n, t)).astype(np.int32)
    return x, np.roll(x, -1, axis=1).astype(np.int32)


class TestAdapters:
    def test_zero_init_is_identity(self):
        inner = _lm()
        model = LoRAModel(inner=inner, rank=4)
        x, _ = _data()
        variables = model.init(jax.random.PRNGKey(0), x)
        out_wrapped = model.apply(variables, x)
        out_inner = inner.apply({"params": variables["params"]["base"]}, x)
        np.testing.assert_allclose(out_wrapped, out_inner, rtol=1e-6)

    def test_adapter_param_count_is_small(self):
        # rank 2 on d_model 32 — at real widths the ratio shrinks as r/d.
        model = LoRAModel(inner=_lm(), rank=2)
        x, _ = _data()
        params = model.init(jax.random.PRNGKey(0), x)["params"]
        n_base = sum(p.size for p in jax.tree.leaves(params["base"]))
        n_lora = sum(p.size for p in jax.tree.leaves(params["lora"]))
        assert n_lora < n_base / 5, (n_lora, n_base)

    def test_merge_params_matches_wrapped_forward(self):
        model = LoRAModel(inner=_lm(), rank=4, alpha=16.0)
        x, _ = _data()
        variables = model.init(jax.random.PRNGKey(0), x)
        params = variables["params"]
        # Give the adapters nonzero B so the delta actually matters.
        params = jax.tree.map(lambda p: p + 0.01, params)
        wrapped = model.apply({"params": params}, x)
        merged = lora.merge_params(params, alpha=16.0)
        plain = _lm().apply({"params": merged}, x)
        np.testing.assert_allclose(wrapped, plain, rtol=2e-5, atol=1e-5)


class TestLoRATraining:
    def _fit(self, steps=5, **inner_kw):
        model = LoRAModel(inner=_lm(**inner_kw), rank=4, alpha=8.0)
        loss = "module" if inner_kw.get("fused_head_chunks") else (
            "sparse_categorical_crossentropy"
        )
        trainer = hvt.Trainer(
            model,
            hvt.DistributedOptimizer(lora.freeze_base(optax.adamw(1e-2))),
            loss=loss,
        )
        x, y = _data()
        state = trainer.build(x)
        base0 = jax.device_get(state.params["base"])
        zero = trainer.zero_metrics()
        losses = []
        for _ in range(steps):
            state, metrics, _ = trainer._train_step(
                state, trainer._shard((x, y)), np.float32(1.0), zero
            )
            losses.append(float(metrics["loss"]))
        return state, base0, losses

    def test_base_frozen_adapters_move_loss_drops(self):
        state, base0, losses = self._fit()
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(a, jax.device_get(b)),
            base0, state.params["base"],
        )
        b_leaves = [
            ab["b"]
            for ab in jax.tree.leaves(
                state.params["lora"], is_leaf=lora._is_adapter_node
            )
            if isinstance(ab, dict)
        ]
        assert any(float(jnp.abs(b).max()) > 0 for b in b_leaves)
        assert losses[-1] < losses[0]

    def test_optimizer_state_only_covers_adapters(self):
        # The point of freezing: Adam mirrors exist for adapters only.
        state, _, _ = self._fit(steps=1)

        def adam_leaves(opt_state):
            return [
                l
                for l in jax.tree.leaves(opt_state)
                if hasattr(l, "size") and l.size > 1
            ]

        sized = sum(l.size for l in adam_leaves(state.opt_state))
        n_lora = sum(p.size for p in jax.tree.leaves(state.params["lora"]))
        n_total = sum(p.size for p in jax.tree.leaves(state.params))
        # mu + nu for adapters = 2·n_lora exactly — no base-sized mirrors
        # (base mirrors alone would be 2·n_total ≈ 5-6× this at toy scale,
        # and r/d × that at real widths).
        assert sized <= 2 * n_lora + 16, (sized, n_lora)
        assert sized < 2 * (n_total - n_lora), (sized, n_total)

    def test_composes_with_fused_ce_head(self):
        state, base0, losses = self._fit(steps=3, fused_head_chunks=4)
        assert np.isfinite(losses).all() and losses[-1] < losses[0]
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(a, jax.device_get(b)),
            base0, state.params["base"],
        )

    def test_stateful_inner_state_carries_through_wrapper(self):
        # Inner mutable collections beyond sows must survive the wrap: the
        # wrapper carries them as its 'inner_state' variable, so the
        # Trainer's model_state path threads them step to step.
        class StatefulNet(nn.Module):
            @nn.compact
            def __call__(self, x, train: bool = False):
                k = self.param(
                    "mlp_up", nn.initializers.normal(0.02), (4, 8)
                )
                count = self.variable(
                    "counter", "steps", lambda: jnp.zeros((), jnp.int32)
                )
                if train and self.is_mutable_collection("counter"):
                    count.value = count.value + 1
                return (x @ k) @ k.T

        model = LoRAModel(inner=StatefulNet(), rank=2)
        trainer = hvt.Trainer(
            model,
            hvt.DistributedOptimizer(lora.freeze_base(optax.adamw(1e-2))),
            loss="sparse_categorical_crossentropy",
        )
        x = np.random.RandomState(0).randn(16, 4).astype(np.float32)
        y = (np.arange(16) % 4).astype(np.int32)
        state = trainer.build(x)
        assert state.model_state and "inner_state" in state.model_state
        for _ in range(3):
            state, _, _ = trainer._train_step(
                state, trainer._shard((x, y)), np.float32(1.0),
                trainer.zero_metrics(),
            )
        steps = jax.device_get(
            state.model_state["inner_state"]["collections"]["counter"]["steps"]
        )
        assert int(steps) == 3

    def test_intermediates_not_carried_as_state(self):
        # Sows into 'intermediates' have append semantics: if the wrapper
        # seeded them into the inner_state carry, every mutable apply would
        # append again, growing the tuple and changing the model_state
        # pytree structure (breaking the jitted step / scan carry).
        class SowingNet(nn.Module):
            @nn.compact
            def __call__(self, x, train: bool = False):
                k = self.param(
                    "mlp_up", nn.initializers.normal(0.02), (4, 8)
                )
                h = x @ k
                self.sow("intermediates", "hidden", h)
                return h @ k.T

        model = LoRAModel(inner=SowingNet(), rank=2)
        x = np.ones((4, 4), np.float32)
        variables = model.init(jax.random.PRNGKey(0), x)
        carried = variables.get("inner_state", {}).get("collections", {})
        assert "intermediates" not in carried
        # Two mutable applies: carry structure must be a fixed point.
        _, upd1 = model.apply(variables, x, mutable=["inner_state"])
        _, upd2 = model.apply(
            {**variables, **upd1}, x, mutable=["inner_state"]
        )
        assert jax.tree_util.tree_structure(
            upd1
        ) == jax.tree_util.tree_structure(upd2)

    def test_moe_aux_channels_pass_through(self):
        # The wrapper re-sows the inner module's 'losses'/'metrics': the MoE
        # load-balance objective and drop-rate observability must survive.
        model = LoRAModel(
            inner=_lm(moe_every=2, n_experts=4), rank=4, alpha=8.0
        )
        trainer = hvt.Trainer(
            model,
            hvt.DistributedOptimizer(lora.freeze_base(optax.adamw(1e-2))),
        )
        x, y = _data()
        state = trainer.build(x)
        assert "moe_drop_rate" in trainer.metric_names
        _, metrics, _ = trainer._train_step(
            state, trainer._shard((x, y)), np.float32(1.0),
            trainer.zero_metrics(),
        )
        assert np.isfinite(float(metrics["moe_drop_rate"]))


class TestLoRAWithTP:
    def test_param_specs_replicate_adapters(self):
        # rank 3 is NOT divisible by the model axis (2): pre-fix the TP rule
        # matched adapter leaves through their layer names and raised / left
        # degenerate shardings. Adapters must skip the TP rule entirely.
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=4, model=2))
        model = LoRAModel(inner=_lm(), rank=3)
        x, _ = _data()
        params = model.init(jax.random.PRNGKey(0), x)["params"]
        specs = param_specs(params, mesh)

        def axes(spec):
            out = []
            for ax in spec:
                out.extend(ax if isinstance(ax, tuple) else (ax,))
            return [a for a in out if a is not None]

        flat = jax.tree_util.tree_flatten_with_path(
            specs, is_leaf=lambda s: isinstance(s, P)
        )[0]
        lora_specs = [
            (path, s) for path, s in flat
            if "lora" in [
                p.key for p in path if isinstance(p, jax.tree_util.DictKey)
            ]
        ]
        base_specs = [(p, s) for p, s in flat if (p, s) not in lora_specs]
        assert lora_specs, "adapter leaves missing from the spec tree"
        for path, s in lora_specs:
            assert "model" not in axes(s), (path, s)
        # The base kernels must still carry TP shardings.
        assert any("model" in axes(s) for _, s in base_specs)

    def test_nested_lora_model_keeps_adapter_exemption(self):
        # A LoRAModel nested under a wrapper module: adapter paths start
        # with the wrapper's name, not 'lora' — the exemption must key on
        # the 'lora' subtree + 'a'/'b' leaves, not on path position.
        class Wrap(nn.Module):
            inner: nn.Module

            @nn.compact
            def __call__(self, x, train: bool = False):
                return self.inner(x, train=train)

        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=4, model=2))
        model = Wrap(inner=LoRAModel(inner=_lm(), rank=3, name="peft"))
        x, _ = _data()
        params = model.init(jax.random.PRNGKey(0), x)["params"]
        specs = param_specs(params, mesh)  # rank 3 % model 2 != 0: must
        # not raise, and no adapter leaf may carry the model axis.
        flat = jax.tree_util.tree_flatten_with_path(
            specs, is_leaf=lambda s: isinstance(s, P)
        )[0]
        for path, s in flat:
            names = [
                p.key for p in path if isinstance(p, jax.tree_util.DictKey)
            ]
            if names[-1] in ("a", "b"):
                assert "model" not in [ax for ax in s if ax is not None], (
                    names, s
                )

    def test_submodule_named_lora_still_tp_sharded(self):
        # A user model that merely CONTAINS a submodule named 'lora' is not
        # the LoRAModel layout — its kernels must still get TP shardings.
        class Sub(nn.Module):
            @nn.compact
            def __call__(self, x):
                k = self.param(
                    "mlp_up", nn.initializers.normal(0.02), (32, 128)
                )
                return x @ k

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                return Sub(name="lora")(x)

        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=4, model=2))
        params = Net().init(
            jax.random.PRNGKey(0), np.ones((4, 32), np.float32)
        )["params"]
        specs = param_specs(params, mesh)
        spec = specs["lora"]["mlp_up"]
        assert "model" in [ax for ax in spec if ax is not None], spec

    def test_moe_targets_do_not_hit_expert_rule(self):
        # Custom targets adapting expert weights: the 2-D [E, r] adapter
        # must not be pushed through the 3-D moe sharding rule.
        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshSpec(data=2, expert=2, model=2)
        )
        model = LoRAModel(
            inner=_lm(moe_every=2, n_experts=4), rank=2,
            targets=("moe_up", "moe_down", "qkv"),
        )
        x, _ = _data()
        params = model.init(jax.random.PRNGKey(0), x)["params"]
        specs = param_specs(params, mesh)  # must not raise / index OOB
        assert specs is not None

    def test_init_does_not_advance_unconditional_inner_state(self):
        # An inner module that advances state on EVERY forward (the decode-
        # cache pattern): the wrapper's init must seed inner.init's fresh
        # values, not state contaminated by the init-time forward; and a
        # read-only eval apply must not advance it either.
        class Ticker(nn.Module):
            @nn.compact
            def __call__(self, x, train: bool = False):
                k = self.param("mlp_up", nn.initializers.normal(0.02), (4, 8))
                idx = self.variable(
                    "cache", "index", lambda: jnp.zeros((), jnp.int32)
                )
                if self.is_mutable_collection("cache"):
                    idx.value = idx.value + 1
                return (x @ k) @ k.T

        model = LoRAModel(inner=Ticker(), rank=2)
        x = np.ones((4, 4), np.float32)
        # Parity target: whatever plain inner.init leaves in the state
        # (its init forward ticks once, like the unwrapped module).
        plain = int(
            Ticker().init(jax.random.PRNGKey(0), x)["cache"]["index"]
        )
        variables = model.init(jax.random.PRNGKey(0), x)
        carried = variables["inner_state"]["collections"]["cache"]["index"]
        assert int(carried) == plain, (
            "wrapper init forward advanced the seeded state past "
            "inner.init's"
        )
        # Read-only apply: no mutable collections -> inner must not tick.
        out = model.apply(variables, x)
        assert out.shape == (4, 4)
        # Mutable apply: ticks exactly once past the seed.
        _, upd = model.apply(variables, x, mutable=["inner_state"])
        assert int(
            upd["inner_state"]["collections"]["cache"]["index"]
        ) == plain + 1
