"""LoRA adapter fine-tuning (models/lora.py): zero-init identity, frozen
base under training, merged-weights equivalence, and composition with the
Trainer / DistributedOptimizer / fused-CE stack."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvt
from horovod_tpu.models import lora
from horovod_tpu.models.lora import LoRAModel
from horovod_tpu.models.transformer import TransformerLM


def _lm(**kw):
    return TransformerLM(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, dropout=0.0, **kw
    )


def _data(seed=0, n=16, t=12):
    rng = np.random.RandomState(seed)
    x = rng.randint(1, 64, size=(n, t)).astype(np.int32)
    return x, np.roll(x, -1, axis=1).astype(np.int32)


class TestAdapters:
    def test_zero_init_is_identity(self):
        inner = _lm()
        model = LoRAModel(inner=inner, rank=4)
        x, _ = _data()
        variables = model.init(jax.random.PRNGKey(0), x)
        out_wrapped = model.apply(variables, x)
        out_inner = inner.apply({"params": variables["params"]["base"]}, x)
        np.testing.assert_allclose(out_wrapped, out_inner, rtol=1e-6)

    def test_adapter_param_count_is_small(self):
        # rank 2 on d_model 32 — at real widths the ratio shrinks as r/d.
        model = LoRAModel(inner=_lm(), rank=2)
        x, _ = _data()
        params = model.init(jax.random.PRNGKey(0), x)["params"]
        n_base = sum(p.size for p in jax.tree.leaves(params["base"]))
        n_lora = sum(p.size for p in jax.tree.leaves(params["lora"]))
        assert n_lora < n_base / 5, (n_lora, n_base)

    def test_merge_params_matches_wrapped_forward(self):
        model = LoRAModel(inner=_lm(), rank=4, alpha=16.0)
        x, _ = _data()
        variables = model.init(jax.random.PRNGKey(0), x)
        params = variables["params"]
        # Give the adapters nonzero B so the delta actually matters.
        params = jax.tree.map(lambda p: p + 0.01, params)
        wrapped = model.apply({"params": params}, x)
        merged = lora.merge_params(params, alpha=16.0)
        plain = _lm().apply({"params": merged}, x)
        np.testing.assert_allclose(wrapped, plain, rtol=2e-5, atol=1e-5)


class TestLoRATraining:
    def _fit(self, steps=5, **inner_kw):
        model = LoRAModel(inner=_lm(**inner_kw), rank=4, alpha=8.0)
        loss = "module" if inner_kw.get("fused_head_chunks") else (
            "sparse_categorical_crossentropy"
        )
        trainer = hvt.Trainer(
            model,
            hvt.DistributedOptimizer(lora.freeze_base(optax.adamw(1e-2))),
            loss=loss,
        )
        x, y = _data()
        state = trainer.build(x)
        base0 = jax.device_get(state.params["base"])
        zero = trainer.zero_metrics()
        losses = []
        for _ in range(steps):
            state, metrics, _ = trainer._train_step(
                state, trainer._shard((x, y)), np.float32(1.0), zero
            )
            losses.append(float(metrics["loss"]))
        return state, base0, losses

    def test_base_frozen_adapters_move_loss_drops(self):
        state, base0, losses = self._fit()
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(a, jax.device_get(b)),
            base0, state.params["base"],
        )
        b_leaves = [
            ab["b"]
            for ab in jax.tree.leaves(
                state.params["lora"], is_leaf=lora._is_adapter_node
            )
            if isinstance(ab, dict)
        ]
        assert any(float(jnp.abs(b).max()) > 0 for b in b_leaves)
        assert losses[-1] < losses[0]

    def test_optimizer_state_only_covers_adapters(self):
        # The point of freezing: Adam mirrors exist for adapters only.
        state, _, _ = self._fit(steps=1)

        def adam_leaves(opt_state):
            return [
                l
                for l in jax.tree.leaves(opt_state)
                if hasattr(l, "size") and l.size > 1
            ]

        sized = sum(l.size for l in adam_leaves(state.opt_state))
        n_lora = sum(p.size for p in jax.tree.leaves(state.params["lora"]))
        n_total = sum(p.size for p in jax.tree.leaves(state.params))
        # mu + nu for adapters = 2·n_lora exactly — no base-sized mirrors
        # (base mirrors alone would be 2·n_total ≈ 5-6× this at toy scale,
        # and r/d × that at real widths).
        assert sized <= 2 * n_lora + 16, (sized, n_lora)
        assert sized < 2 * (n_total - n_lora), (sized, n_total)

    def test_composes_with_fused_ce_head(self):
        state, base0, losses = self._fit(steps=3, fused_head_chunks=4)
        assert np.isfinite(losses).all() and losses[-1] < losses[0]
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(a, jax.device_get(b)),
            base0, state.params["base"],
        )

    def test_moe_aux_channels_pass_through(self):
        # The wrapper re-sows the inner module's 'losses'/'metrics': the MoE
        # load-balance objective and drop-rate observability must survive.
        model = LoRAModel(
            inner=_lm(moe_every=2, n_experts=4), rank=4, alpha=8.0
        )
        trainer = hvt.Trainer(
            model,
            hvt.DistributedOptimizer(lora.freeze_base(optax.adamw(1e-2))),
        )
        x, y = _data()
        state = trainer.build(x)
        assert "moe_drop_rate" in trainer.metric_names
        _, metrics, _ = trainer._train_step(
            state, trainer._shard((x, y)), np.float32(1.0),
            trainer.zero_metrics(),
        )
        assert np.isfinite(float(metrics["moe_drop_rate"]))
