"""Encoder-decoder family: cross-attention (flash Tk≠Tq grids) correctness,
padding masks, TP shardings, Trainer integration, cached generation."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvt
from horovod_tpu.models.seq2seq import (
    Seq2SeqTransformer,
    make_seq2seq_generate_fn,
    param_specs,
)
from horovod_tpu.models.transformer import ShardingConfig
from horovod_tpu.parallel import mesh as mesh_lib

VOCAB = 32
PAD, BOS, EOS = 0, 1, 2


def _model(mesh=None, attn="ring", **kw):
    kw.setdefault("vocab_size", VOCAB)
    kw.setdefault("d_model", 64)
    kw.setdefault("n_heads", 4)
    kw.setdefault("n_enc_layers", 2)
    kw.setdefault("n_dec_layers", 2)
    kw.setdefault("dropout", 0.0)
    return Seq2SeqTransformer(
        sharding=ShardingConfig(mesh=mesh, attn=attn), **kw
    )


def _batch(rng, b=2, s=12, t=10, pad_tail=0):
    src = rng.randint(3, VOCAB, size=(b, s)).astype(np.int32)
    if pad_tail:
        src[:, -pad_tail:] = PAD
    tgt = rng.randint(3, VOCAB, size=(b, t)).astype(np.int32)
    return {"src": jnp.asarray(src), "tgt": jnp.asarray(tgt)}


class TestForward:
    def test_shapes(self):
        model = _model()
        batch = _batch(np.random.RandomState(0))
        params = model.init(jax.random.PRNGKey(0), batch)["params"]
        logits = model.apply({"params": params}, batch)
        assert logits.shape == (2, 10, VOCAB)

    @pytest.mark.slow
    def test_flash_matches_dense(self):
        """The flash path (encoder non-causal segments, decoder causal,
        cross-attention Tk≠Tq) agrees with the dense reference — values AND
        gradients."""
        batch = _batch(np.random.RandomState(1), pad_tail=4)
        flash = _model()
        densem = _model(attn="dense")
        params = flash.init(jax.random.PRNGKey(0), batch)["params"]

        def loss(m):
            def f(p):
                out = m.apply({"params": p}, batch)
                return (out.astype(jnp.float32) ** 2).mean()
            return f

        lf, gf = jax.value_and_grad(loss(flash))(params)
        ld, gd = jax.value_and_grad(loss(densem))(params)
        assert abs(float(lf) - float(ld)) < 2e-5
        flat_f = jax.tree_util.tree_leaves(gf)
        flat_d = jax.tree_util.tree_leaves(gd)
        for a, b in zip(flat_f, flat_d):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)

    def test_decoder_causality(self):
        """Changing a future target token must not change past logits."""
        model = _model()
        batch = _batch(np.random.RandomState(2))
        params = model.init(jax.random.PRNGKey(0), batch)["params"]
        out1 = model.apply({"params": params}, batch)
        tgt2 = np.asarray(batch["tgt"]).copy()
        tgt2[:, -1] = (tgt2[:, -1] + 5) % VOCAB
        out2 = model.apply(
            {"params": params}, {"src": batch["src"], "tgt": jnp.asarray(tgt2)}
        )
        np.testing.assert_allclose(
            np.asarray(out1)[:, :-1], np.asarray(out2)[:, :-1], atol=1e-6
        )

    def test_padding_invariance(self):
        """Padding must be inert: appending MORE pad columns to the source
        cannot change the logits (the pad embeddings enter the encoder, but
        the self- and cross-attention masks keep them out of every real
        position's receptive field)."""
        model = _model()
        batch = _batch(np.random.RandomState(3), pad_tail=5)
        params = model.init(jax.random.PRNGKey(0), batch)["params"]
        out1 = model.apply({"params": params}, batch)
        src2 = np.asarray(batch["src"]).copy()
        src2 = np.concatenate([src2, np.full((2, 3), PAD, np.int32)], axis=1)
        out2 = model.apply(
            {"params": params}, {"src": jnp.asarray(src2), "tgt": batch["tgt"]}
        )
        np.testing.assert_allclose(
            np.asarray(out1), np.asarray(out2), atol=2e-5
        )


class TestTP:
    def test_tp_matches_unsharded(self):
        """data×model mesh: params actually sharded over `model`, forward
        matches the single-device result."""
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=2, model=4))
        batch = _batch(np.random.RandomState(4), pad_tail=3)
        model = _model()
        params = model.init(jax.random.PRNGKey(0), batch)["params"]
        ref = model.apply({"params": params}, batch)

        smodel = _model(mesh=mesh)
        specs = param_specs(params, mesh)
        sharded = jax.tree.map(
            lambda x, s: jax.device_put(
                x, jax.sharding.NamedSharding(mesh, s)
            ),
            params, specs,
        )
        # The cross-attention projections really shard over `model`.
        ck = sharded["decoder"]["Block_0"]["cross_kv"]["kernel"]
        assert not ck.sharding.is_fully_replicated
        out = jax.jit(
            lambda p, b: smodel.apply({"params": p}, b)
        )(sharded, batch)
        np.testing.assert_allclose(
            np.asarray(ref), np.asarray(out), atol=3e-5
        )


def _copy_task(n, s_len, t_len, rng):
    """tgt = the first t_len source tokens (teacher-forced copy): src row,
    decoder input [BOS, y[:-1]], labels y."""
    src = rng.randint(3, VOCAB, size=(n, s_len)).astype(np.int32)
    y = src[:, :t_len]
    tgt_in = np.concatenate(
        [np.full((n, 1), BOS, np.int32), y[:, :-1]], axis=1
    )
    return {"src": src, "tgt": tgt_in}, y


@pytest.mark.slow
class TestTraining:
    def test_learns_copy_through_trainer(self):
        """End-to-end through Trainer on a data×model mesh: the dict batch
        shards, the loss falls, and generation reproduces the source."""
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=4, model=2))
        model = _model(mesh=mesh, d_model=64)
        trainer = hvt.Trainer(
            model,
            hvt.DistributedOptimizer(optax.adam(3e-3)),
            loss="sparse_categorical_crossentropy",
            mesh=mesh,
            param_specs=param_specs,
        )
        rng = np.random.RandomState(0)
        # Enough distinct rows that the copy RELATION must be learned —
        # with a few hundred rows the model just memorizes the training
        # set (train acc high, eval/generation at chance).
        x, y = _copy_task(4096, 12, 8, rng)
        history = trainer.fit(x=x, y=y, epochs=4, batch_size=8, verbose=0)
        assert history[-1]["loss"] < history[0]["loss"] * 0.2
        xe, ye = _copy_task(64, 12, 8, rng)
        ev = trainer.evaluate(xe, ye, batch_size=8)
        assert ev["accuracy"] > 0.85

        # Greedy generation on the trained params copies the source.
        params = jax.device_get(trainer.state.params)
        gen = make_seq2seq_generate_fn(
            _model(d_model=64), max_new_tokens=8, bos_id=BOS
        )
        src_eval = rng.randint(3, VOCAB, size=(4, 12)).astype(np.int32)
        out = np.asarray(gen(params, jnp.asarray(src_eval), jax.random.PRNGKey(0)))
        assert (out == src_eval[:, :8]).mean() > 0.85


class TestGeneration:
    def test_cached_decode_matches_teacher_forced(self):
        """Greedy cached generation == argmax of a teacher-forced recompute
        over the generated prefix (the cache carries no approximation)."""
        model = _model()
        rng = np.random.RandomState(5)
        batch = _batch(rng, pad_tail=2)
        params = model.init(jax.random.PRNGKey(0), batch)["params"]
        gen = make_seq2seq_generate_fn(model, max_new_tokens=7, bos_id=BOS)
        out = gen(params, batch["src"], jax.random.PRNGKey(1))
        tf_in = jnp.concatenate(
            [jnp.full((2, 1), BOS, jnp.int32), out[:, :-1]], axis=1
        )
        tf_logits = model.apply(
            {"params": params}, {"src": batch["src"], "tgt": tf_in}
        )
        np.testing.assert_array_equal(
            np.asarray(jnp.argmax(tf_logits, axis=-1)), np.asarray(out)
        )

    def test_eos_fill(self):
        """After a row emits eos, its remaining positions are eos."""
        model = _model()
        batch = _batch(np.random.RandomState(6))
        params = model.init(jax.random.PRNGKey(0), batch)["params"]
        gen = make_seq2seq_generate_fn(
            model, max_new_tokens=12, bos_id=BOS, eos_id=EOS
        )
        out = np.asarray(gen(params, batch["src"], jax.random.PRNGKey(2)))
        for row in out:
            hits = np.where(row == EOS)[0]
            if len(hits):
                assert (row[hits[0]:] == EOS).all()

    def test_sampled_generation_runs(self):
        model = _model()
        batch = _batch(np.random.RandomState(7))
        params = model.init(jax.random.PRNGKey(0), batch)["params"]
        gen = make_seq2seq_generate_fn(
            model, max_new_tokens=5, bos_id=BOS, temperature=0.8, top_k=8
        )
        out = np.asarray(gen(params, batch["src"], jax.random.PRNGKey(3)))
        assert out.shape == (2, 5)
        assert (out >= 0).all() and (out < VOCAB).all()


@pytest.mark.slow
class TestSequenceParallel:
    """All three attention families over a live `seq` axis: the encoder's
    segmented bidirectional ring, the decoder's causal ring, and the
    cross-attention ring (memory blocks + padding ids rotating)."""

    def _sp_pair(self, seed=8, s=16, t=16, pad_tail=4):
        rng = np.random.RandomState(seed)
        src = rng.randint(3, VOCAB, size=(2, s)).astype(np.int32)
        if pad_tail:
            src[0, -pad_tail:] = PAD
        tgt = rng.randint(3, VOCAB, size=(2, t)).astype(np.int32)
        return {"src": jnp.asarray(src), "tgt": jnp.asarray(tgt)}

    def test_matches_unsharded_values_and_grads(self):
        batch = self._sp_pair()
        ref_m = _model()
        params = ref_m.init(jax.random.PRNGKey(0), batch)["params"]
        ref = ref_m.apply({"params": params}, batch)

        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=2, seq=4))
        sp_m = _model(mesh=mesh)
        out = jax.jit(lambda p, b: sp_m.apply({"params": p}, b))(params, batch)
        np.testing.assert_allclose(
            np.asarray(ref), np.asarray(out), atol=3e-5
        )

        def loss(m):
            return lambda p: (
                m.apply({"params": p}, batch).astype(jnp.float32) ** 2
            ).mean()

        g_ref = jax.grad(loss(ref_m))(params)
        g_sp = jax.jit(jax.grad(loss(sp_m)))(params)
        for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_sp)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)

    def test_trains_on_dp_sp_mesh(self):
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=2, seq=4))
        model = _model(mesh=mesh)
        trainer = hvt.Trainer(
            model, hvt.DistributedOptimizer(optax.adam(3e-3)),
            loss="sparse_categorical_crossentropy", mesh=mesh,
        )
        rng = np.random.RandomState(0)
        x, y = _copy_task(512, 16, 16, rng)
        hist = trainer.fit(x=x, y=y, epochs=2, batch_size=8, verbose=0)
        assert hist[-1]["loss"] < hist[0]["loss"]

    def test_sp_requires_ring(self):
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=2, seq=4))
        model = _model(mesh=mesh, attn="dense")
        with pytest.raises(ValueError, match="attn='ring'"):
            model.init(jax.random.PRNGKey(0), self._sp_pair())

    def test_decode_refused_on_seq_mesh(self):
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=2, seq=4))
        model = _model(mesh=mesh).clone(decode=True, max_decode_len=8)
        with pytest.raises(ValueError, match="decode mode"):
            model.init(jax.random.PRNGKey(0), self._sp_pair(t=1))


@pytest.mark.slow
def test_predict_with_dict_inputs():
    """Trainer.predict slices/pads/shards pytree inputs leaf-wise —
    teacher-forced next-token probabilities for a dict-batch model,
    including the padded tail batch."""
    model = _model()
    trainer = hvt.Trainer(
        model, hvt.DistributedOptimizer(optax.adam(1e-3)),
        loss="sparse_categorical_crossentropy",
    )
    rng = np.random.RandomState(9)
    x, y = _copy_task(19, 12, 8, rng)  # 19: forces a ragged final batch
    trainer.build(jax.tree.map(lambda a: a[:8], x))
    probs = trainer.predict(x, batch_size=1)
    assert probs.shape == (19, 8, VOCAB)
    np.testing.assert_allclose(probs.sum(-1), 1.0, atol=1e-4)
