"""Sequence-parallel attention correctness: ring and Ulysses must match the
dense reference exactly (same math, different communication schedule), and
must be differentiable — the backward pass replays the ring."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from horovod_tpu.ops.attention import (
    dense_attention,
    ring_attention,
    ring_flash_attention,
    ulysses_attention,
)

B, T, H, D = 2, 32, 4, 8
SEQ_DEVICES = 4


def _qkv(seed=0):
    rng = np.random.RandomState(seed)
    shape = (B, T, H, D)
    return tuple(rng.randn(*shape).astype(np.float32) for _ in range(3))


def _seq_mesh():
    return Mesh(np.array(jax.devices()[:SEQ_DEVICES]), ("seq",))


def _sharded(fn, mesh, **kwargs):
    spec = P(None, "seq", None, None)
    return jax.jit(
        shard_map(
            functools.partial(fn, axis_name="seq", **kwargs),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )
    )


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        q, k, v = _qkv()
        expected = dense_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                                   causal=causal)
        got = _sharded(ring_attention, _seq_mesh(), causal=causal)(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=2e-5, atol=2e-5)

    def test_single_device_degenerates(self):
        q, k, v = _qkv(1)
        mesh = Mesh(np.array(jax.devices()[:1]), ("seq",))
        got = _sharded(ring_attention, mesh, causal=True)(q, k, v)
        expected = dense_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=2e-5, atol=2e-5)

    def test_gradients_match_dense(self):
        q, k, v = _qkv(2)
        mesh = _seq_mesh()

        def loss_ring(q, k, v):
            return (_sharded(ring_attention, mesh, causal=True)(q, k, v) ** 2).sum()

        def loss_dense(q, k, v):
            return (dense_attention(q, k, v, causal=True) ** 2).sum()

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(*map(jnp.asarray, (q, k, v)))
        g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(*map(jnp.asarray, (q, k, v)))
        for a, b in zip(g_ring, g_dense):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)


class TestRingFlashAttention:
    """Ring with flash-kernel block compute: same math as ring_attention,
    blockwise (out, lse) per hop merged by the logsumexp recurrence, with
    above-diagonal hops skipped via lax.cond rather than masked."""

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        q, k, v = _qkv(7)
        expected = dense_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal
        )
        got = _sharded(ring_flash_attention, _seq_mesh(), causal=causal)(q, k, v)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=2e-5, atol=2e-5
        )

    def test_single_device_degenerates(self):
        q, k, v = _qkv(8)
        mesh = Mesh(np.array(jax.devices()[:1]), ("seq",))
        got = _sharded(ring_flash_attention, mesh, causal=True)(q, k, v)
        expected = dense_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=2e-5, atol=2e-5
        )

    def test_gradients_match_dense(self):
        """The lse cotangent path: hop weights exp(lse_j - lse) depend on
        q/k, so ring-flash grads only match dense if d(lse)/d(q,k) flows
        correctly through the kernel's custom VJP."""
        q, k, v = _qkv(9)
        mesh = _seq_mesh()

        def loss_ring(q, k, v):
            return (
                _sharded(ring_flash_attention, mesh, causal=True)(q, k, v) ** 2
            ).sum()

        def loss_dense(q, k, v):
            return (dense_attention(q, k, v, causal=True) ** 2).sum()

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(
            *map(jnp.asarray, (q, k, v))
        )
        g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(
            *map(jnp.asarray, (q, k, v))
        )
        for a, b in zip(g_ring, g_dense):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            )

    def test_matches_dense_ring(self):
        """Flash-block and dense-block rings agree on the same shards."""
        q, k, v = _qkv(10)
        mesh = _seq_mesh()
        a = _sharded(ring_flash_attention, mesh, causal=True)(q, k, v)
        b = _sharded(ring_attention, mesh, causal=True)(q, k, v)
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5
        )


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        q, k, v = _qkv(3)
        expected = dense_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                                   causal=causal)
        got = _sharded(ulysses_attention, _seq_mesh(), causal=causal)(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=2e-5, atol=2e-5)

    def test_rejects_indivisible_heads(self):
        mesh = _seq_mesh()
        rng = np.random.RandomState(0)
        bad = rng.randn(B, T, 6, D).astype(np.float32)  # 6 % 4 != 0
        with pytest.raises(ValueError, match="divisible"):
            _sharded(ulysses_attention, mesh)(bad, bad, bad)


class TestDenseAttention:
    def test_causal_masks_future(self):
        q, k, v = map(jnp.asarray, _qkv(4))
        out = dense_attention(q, k, v, causal=True)
        # Position 0 may only attend to k[0] → its output is exactly v[0].
        np.testing.assert_allclose(
            np.asarray(out[:, 0]), np.asarray(v[:, 0]), rtol=1e-5, atol=1e-5
        )
