"""Sequence-parallel attention correctness: ring and Ulysses must match the
dense reference exactly (same math, different communication schedule), and
must be differentiable — the backward pass replays the ring."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from horovod_tpu.compat import shard_map

from horovod_tpu.ops.attention import (
    dense_attention,
    ring_attention,
    ring_flash_attention,
    ulysses_attention,
)

B, T, H, D = 2, 32, 4, 8
SEQ_DEVICES = 4


def _qkv(seed=0):
    rng = np.random.RandomState(seed)
    shape = (B, T, H, D)
    return tuple(rng.randn(*shape).astype(np.float32) for _ in range(3))


def _seq_mesh():
    return Mesh(np.array(jax.devices()[:SEQ_DEVICES]), ("seq",))


def _sharded(fn, mesh, **kwargs):
    spec = P(None, "seq", None, None)
    return jax.jit(
        shard_map(
            functools.partial(fn, axis_name="seq", **kwargs),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
            check_vma=False,
        )
    )


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        q, k, v = _qkv()
        expected = dense_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                                   causal=causal)
        got = _sharded(ring_attention, _seq_mesh(), causal=causal)(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=2e-5, atol=2e-5)

    def test_single_device_degenerates(self):
        q, k, v = _qkv(1)
        mesh = Mesh(np.array(jax.devices()[:1]), ("seq",))
        got = _sharded(ring_attention, mesh, causal=True)(q, k, v)
        expected = dense_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=2e-5, atol=2e-5)

    def test_gradients_match_dense(self):
        q, k, v = _qkv(2)
        mesh = _seq_mesh()

        def loss_ring(q, k, v):
            return (_sharded(ring_attention, mesh, causal=True)(q, k, v) ** 2).sum()

        def loss_dense(q, k, v):
            return (dense_attention(q, k, v, causal=True) ** 2).sum()

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(*map(jnp.asarray, (q, k, v)))
        g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(*map(jnp.asarray, (q, k, v)))
        for a, b in zip(g_ring, g_dense):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)


class TestRingFlashAttention:
    """Ring with flash-kernel block compute: same math as ring_attention,
    blockwise (out, lse) per hop merged by the logsumexp recurrence, with
    above-diagonal hops skipped via lax.cond rather than masked."""

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        q, k, v = _qkv(7)
        expected = dense_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal
        )
        got = _sharded(ring_flash_attention, _seq_mesh(), causal=causal)(q, k, v)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=2e-5, atol=2e-5
        )

    def test_single_device_degenerates(self):
        q, k, v = _qkv(8)
        mesh = Mesh(np.array(jax.devices()[:1]), ("seq",))
        got = _sharded(ring_flash_attention, mesh, causal=True)(q, k, v)
        expected = dense_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=2e-5, atol=2e-5
        )

    def test_gradients_match_dense(self):
        """The lse cotangent path: hop weights exp(lse_j - lse) depend on
        q/k, so ring-flash grads only match dense if d(lse)/d(q,k) flows
        correctly through the kernel's custom VJP."""
        q, k, v = _qkv(9)
        mesh = _seq_mesh()

        def loss_ring(q, k, v):
            return (
                _sharded(ring_flash_attention, mesh, causal=True)(q, k, v) ** 2
            ).sum()

        def loss_dense(q, k, v):
            return (dense_attention(q, k, v, causal=True) ** 2).sum()

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(
            *map(jnp.asarray, (q, k, v))
        )
        g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(
            *map(jnp.asarray, (q, k, v))
        )
        for a, b in zip(g_ring, g_dense):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            )

    def test_matches_dense_ring(self):
        """Flash-block and dense-block rings agree on the same shards."""
        q, k, v = _qkv(10)
        mesh = _seq_mesh()
        a = _sharded(ring_flash_attention, mesh, causal=True)(q, k, v)
        b = _sharded(ring_attention, mesh, causal=True)(q, k, v)
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5
        )


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        q, k, v = _qkv(3)
        expected = dense_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                                   causal=causal)
        got = _sharded(ulysses_attention, _seq_mesh(), causal=causal)(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=2e-5, atol=2e-5)

    def test_rejects_indivisible_heads(self):
        mesh = _seq_mesh()
        rng = np.random.RandomState(0)
        bad = rng.randn(B, T, 6, D).astype(np.float32)  # 6 % 4 != 0
        with pytest.raises(ValueError, match="divisible"):
            _sharded(ulysses_attention, mesh)(bad, bad, bad)


class TestDenseAttention:
    def test_causal_masks_future(self):
        q, k, v = map(jnp.asarray, _qkv(4))
        out = dense_attention(q, k, v, causal=True)
        # Position 0 may only attend to k[0] → its output is exactly v[0].
        np.testing.assert_allclose(
            np.asarray(out[:, 0]), np.asarray(v[:, 0]), rtol=1e-5, atol=1e-5
        )


class TestSegmentedSequenceParallel:
    """Packed-sequence (segment-id) masking through the SP schemes — the ids
    shard with the tokens; kv ids ride the ring / gather across the swap."""

    def _ids(self, seed=30):
        rng = np.random.RandomState(seed)
        cuts = np.sort(rng.choice(np.arange(1, T), 3, replace=False))
        ids = np.searchsorted(cuts, np.arange(T), side="right")
        return np.broadcast_to(ids, (B, T)).astype(np.int32).copy()

    def _global_ref(self, q, k, v, ids, causal):
        from horovod_tpu.ops.flash_attention import flash_attention

        return flash_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal,
            q_segment_ids=jnp.asarray(ids), kv_segment_ids=jnp.asarray(ids),
        )

    def _sharded_seg(self, fn, mesh, **kwargs):
        spec = P(None, "seq", None, None)
        ispec = P(None, "seq")
        return jax.jit(
            shard_map(
                lambda q, k, v, ids: fn(
                    q, k, v, axis_name="seq", segment_ids=ids, **kwargs
                ),
                mesh=mesh,
                in_specs=(spec, spec, spec, ispec),
                out_specs=spec,
                check_vma=False,
            )
        )

    @pytest.mark.parametrize("causal", [True, False])
    def test_ring_flash_matches_global(self, causal):
        q, k, v = _qkv(31)
        ids = self._ids()
        got = self._sharded_seg(ring_flash_attention, _seq_mesh(), causal=causal)(
            q, k, v, ids
        )
        expected = self._global_ref(q, k, v, ids, causal)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=2e-5, atol=2e-5
        )

    @pytest.mark.parametrize("causal", [True, False])
    def test_ulysses_matches_global(self, causal):
        q, k, v = _qkv(32)
        ids = self._ids(33)
        got = self._sharded_seg(ulysses_attention, _seq_mesh(), causal=causal)(
            q, k, v, ids
        )
        expected = self._global_ref(q, k, v, ids, causal)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=2e-5, atol=2e-5
        )

    def test_ring_flash_segment_grads(self):
        q, k, v = _qkv(34)
        ids = self._ids(35)
        ring = self._sharded_seg(ring_flash_attention, _seq_mesh(), causal=True)

        g_ring = jax.grad(
            lambda q, k, v: (ring(q, k, v, ids) ** 2).sum(), argnums=(0, 1, 2)
        )(*map(jnp.asarray, (q, k, v)))
        g_ref = jax.grad(
            lambda q, k, v: (self._global_ref(q, k, v, ids, True) ** 2).sum(),
            argnums=(0, 1, 2),
        )(*map(jnp.asarray, (q, k, v)))
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            )


def test_dense_attention_empty_segment_rows_zero():
    """A q row whose segment has no kv tokens must output ZERO from
    dense_attention too (not softmax's uniform average of all values — a
    cross-segment leak), matching the flash kernel's empty-row convention."""
    rng = np.random.RandomState(40)
    q = jnp.asarray(rng.randn(1, 8, 2, 4).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 8, 2, 4).astype(np.float32))
    v = jnp.asarray(rng.randn(1, 8, 2, 4).astype(np.float32))
    q_seg = jnp.asarray(np.array([[0, 0, 1, 1, 0, 0, 1, 1]], np.int32))
    kv_seg = jnp.zeros((1, 8), jnp.int32)
    out = dense_attention(
        q, k, v, causal=False, q_segment_ids=q_seg, kv_segment_ids=kv_seg
    )
    empty = np.asarray(q_seg)[0] == 1
    np.testing.assert_array_equal(np.asarray(out)[0, empty], 0.0)
    assert np.isfinite(np.asarray(out)).all()


class TestWindowedSequenceParallel:
    """Sliding-window attention across shard boundaries: the band is over
    GLOBAL positions, so it must be exact through the ring's hop arithmetic
    (static q_offset per hop distance) and Ulysses' head swap."""

    @pytest.mark.parametrize("window", [1, 5, 8, 20, T])
    def test_ring_flash_matches_dense(self, window):
        q, k, v = _qkv(21)
        expected = dense_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            causal=True, window=window,
        )
        got = _sharded(
            ring_flash_attention, _seq_mesh(), causal=True, window=window
        )(q, k, v)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=2e-5, atol=2e-5
        )

    @pytest.mark.parametrize("window", [5, 20])
    def test_ring_dense_matches_dense(self, window):
        q, k, v = _qkv(22)
        expected = dense_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            causal=True, window=window,
        )
        got = _sharded(
            ring_attention, _seq_mesh(), causal=True, window=window
        )(q, k, v)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=2e-5, atol=2e-5
        )

    def test_ulysses_matches_dense(self):
        q, k, v = _qkv(23)
        expected = dense_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            causal=True, window=9,
        )
        got = _sharded(
            ulysses_attention, _seq_mesh(), causal=True, window=9
        )(q, k, v)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=2e-5, atol=2e-5
        )

    def test_ring_flash_gradients(self):
        q, k, v = _qkv(24)
        mesh = _seq_mesh()
        window = 11

        def loss_ring(q, k, v):
            out = _sharded(
                ring_flash_attention, mesh, causal=True, window=window
            )(q, k, v)
            return (out ** 2).sum()

        def loss_dense(q, k, v):
            return (
                dense_attention(q, k, v, causal=True, window=window) ** 2
            ).sum()

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(
            *map(jnp.asarray, (q, k, v))
        )
        g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(
            *map(jnp.asarray, (q, k, v))
        )
        for a, b in zip(g_ring, g_dense):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            )

    def test_ring_flash_segments_and_window(self):
        """Packed docs riding the windowed ring: intersection semantics,
        global-position band."""
        rng = np.random.RandomState(25)
        q, k, v = _qkv(25)
        ids = np.sort(rng.randint(0, 3, size=(B, T)), axis=1).astype(np.int32)
        expected = dense_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True,
            window=13, q_segment_ids=jnp.asarray(ids),
            kv_segment_ids=jnp.asarray(ids),
        )
        mesh = _seq_mesh()
        spec = P(None, "seq", None, None)
        got = jax.jit(
            shard_map(
                lambda q, k, v, ids: ring_flash_attention(
                    q, k, v, axis_name="seq", causal=True, window=13,
                    segment_ids=ids,
                ),
                mesh=mesh,
                in_specs=(spec, spec, spec, P(None, "seq")),
                out_specs=spec,
                check_vma=False,
            )
        )(q, k, v, ids)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=2e-5, atol=2e-5
        )

    def test_window_requires_causal(self):
        q, k, v = _qkv(26)
        with pytest.raises(ValueError, match="causal"):
            _sharded(
                ring_flash_attention, _seq_mesh(), causal=False, window=4
            )(q, k, v)


class TestRingSinks:
    """Global+local through the flash ring: the hop holding global block 0
    contributes the sink columns (dense, disjoint from the band), merged
    by the same lse recurrence."""

    @pytest.mark.parametrize("window,sinks", [(5, 2), (9, 7), (16, 8)])
    def test_matches_dense(self, window, sinks):
        q, k, v = _qkv(41)
        expected = dense_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            causal=True, window=window, sinks=sinks,
        )
        got = _sharded(
            ring_flash_attention, _seq_mesh(), causal=True, window=window,
            sinks=sinks,
        )(q, k, v)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=2e-5, atol=2e-5
        )

    def test_gradients_match_dense(self):
        q, k, v = _qkv(42)
        mesh = _seq_mesh()
        window, sinks = 7, 3

        def loss_ring(q, k, v):
            out = _sharded(
                ring_flash_attention, mesh, causal=True, window=window,
                sinks=sinks,
            )(q, k, v)
            return (out ** 2).sum()

        def loss_dense(q, k, v):
            return (dense_attention(
                q, k, v, causal=True, window=window, sinks=sinks
            ) ** 2).sum()

        g1 = jax.grad(loss_ring, argnums=(0, 1, 2))(
            *map(jnp.asarray, (q, k, v))
        )
        g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(
            *map(jnp.asarray, (q, k, v))
        )
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            )

    def test_segments_compose(self):
        rng = np.random.RandomState(43)
        q, k, v = _qkv(43)
        ids = np.sort(rng.randint(0, 2, size=(B, T)), axis=1).astype(np.int32)
        expected = dense_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True,
            window=9, sinks=4, q_segment_ids=jnp.asarray(ids),
            kv_segment_ids=jnp.asarray(ids),
        )
        mesh = _seq_mesh()
        spec = P(None, "seq", None, None)
        got = jax.jit(
            shard_map(
                lambda q, k, v, ids: ring_flash_attention(
                    q, k, v, axis_name="seq", causal=True, window=9,
                    sinks=4, segment_ids=ids,
                ),
                mesh=mesh,
                in_specs=(spec, spec, spec, P(None, "seq")),
                out_specs=spec,
                check_vma=False,
            )
        )(q, k, v, ids)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=2e-5, atol=2e-5
        )

    def test_ulysses_sinks(self):
        q, k, v = _qkv(44)
        expected = dense_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            causal=True, window=9, sinks=4,
        )
        got = _sharded(
            ulysses_attention, _seq_mesh(), causal=True, window=9, sinks=4
        )(q, k, v)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=2e-5, atol=2e-5
        )

    def test_sinks_need_window_and_fit_shard(self):
        q, k, v = _qkv(45)
        with pytest.raises(ValueError, match="window"):
            _sharded(
                ring_flash_attention, _seq_mesh(), causal=True, sinks=4
            )(q, k, v)
        with pytest.raises(ValueError, match="shard"):
            _sharded(
                ring_flash_attention, _seq_mesh(), causal=True, window=9,
                sinks=T,  # > T/n
            )(q, k, v)


class TestRingCrossAttention:
    """Non-causal cross-attention over the seq ring (seq2seq's cross path):
    queries and memory shard DIFFERENT logical sequences."""

    def _cross(self, tq=32, tk=48, seed=3):
        rng = np.random.RandomState(seed)
        q = rng.randn(B, tq, H, D).astype(np.float32)
        k = rng.randn(B, tk, H, D).astype(np.float32)
        v = rng.randn(B, tk, H, D).astype(np.float32)
        return q, k, v

    def test_matches_dense_unequal_lengths(self):
        from horovod_tpu.ops.attention import ring_cross_attention

        q, k, v = self._cross()
        expected = dense_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=False
        )
        spec = P(None, "seq", None, None)
        got = jax.jit(
            shard_map(
                functools.partial(ring_cross_attention, axis_name="seq"),
                mesh=_seq_mesh(), in_specs=(spec, spec, spec),
                out_specs=spec, check_vma=False,
            )
        )(q, k, v)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=2e-5, atol=2e-5
        )

    def test_padding_mask_and_gradients(self):
        from horovod_tpu.ops.attention import ring_cross_attention

        q, k, v = self._cross(tq=16, tk=32)
        kv_ids = np.ones((B, 32), np.int32)
        kv_ids[:, 20:] = 0  # padded memory tail
        q_ids = np.ones((B, 16), np.int32)
        spec = P(None, "seq", None, None)
        ids_spec = P(None, "seq")

        def ring(q, k, v, qi, ki):
            return ring_cross_attention(
                q, k, v, axis_name="seq", q_segment_ids=qi, kv_segment_ids=ki
            )

        f = jax.jit(
            shard_map(
                ring, mesh=_seq_mesh(),
                in_specs=(spec, spec, spec, ids_spec, ids_spec),
                out_specs=spec, check_vma=False,
            )
        )

        def loss_ring(q, k, v):
            return (f(q, k, v, jnp.asarray(q_ids), jnp.asarray(ki)) ** 2).sum()

        def loss_dense(q, k, v):
            return (
                dense_attention(
                    q, k, v, causal=False,
                    q_segment_ids=jnp.asarray(q_ids),
                    kv_segment_ids=jnp.asarray(ki),
                ) ** 2
            ).sum()

        ki = jnp.asarray(kv_ids)
        args = tuple(jnp.asarray(a) for a in (q, k, v))
        np.testing.assert_allclose(
            float(loss_ring(*args)), float(loss_dense(*args)), rtol=2e-5
        )
        g_r = jax.grad(loss_ring, argnums=(0, 1, 2))(*args)
        g_d = jax.grad(loss_dense, argnums=(0, 1, 2))(*args)
        for a, b in zip(g_r, g_d):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
            )

    def test_all_pad_source_row_gives_zero(self):
        from horovod_tpu.ops.attention import ring_cross_attention

        q, k, v = self._cross(tq=16, tk=32)
        kv_ids = np.ones((B, 32), np.int32)
        kv_ids[1, :] = 0  # row 1: the whole source is padding
        q_ids = np.ones((B, 16), np.int32)
        spec = P(None, "seq", None, None)
        ids_spec = P(None, "seq")
        f = jax.jit(
            shard_map(
                lambda q, k, v, qi, ki: ring_cross_attention(
                    q, k, v, axis_name="seq",
                    q_segment_ids=qi, kv_segment_ids=ki,
                ),
                mesh=_seq_mesh(),
                in_specs=(spec, spec, spec, ids_spec, ids_spec),
                out_specs=spec, check_vma=False,
            )
        )
        out = f(*(jnp.asarray(a) for a in (q, k, v)),
                jnp.asarray(q_ids), jnp.asarray(kv_ids))
        assert float(jnp.abs(out[1]).max()) == 0.0
        assert float(jnp.abs(out[0]).max()) > 0.0

    def test_mismatched_ids_rejected(self):
        from horovod_tpu.ops.attention import ring_cross_attention

        q, k, v = self._cross(tq=16, tk=16)
        with pytest.raises(ValueError, match="pair"):
            # Outside shard_map is fine for the arg check: it raises before
            # any collective is touched.
            ring_cross_attention(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                q_segment_ids=jnp.ones((B, 16), jnp.int32),
            )
