"""End-to-end multi-process training tests.

The reference's CI runs a REAL multi-worker MPI job
(.ps_project/distributed-keras-sample.yaml:5 `workerCount: 3`); its
single-machine analogue is `mpirun -np N` in one container (README.md:53-58).
These tests are that mode, TPU-native: `launcher.run_local(2, ...)` spawns two
coordinated processes, each driving 2 virtual CPU devices, so every
`process_count > 1` branch executes for real — `jax.distributed` bootstrap,
`sharding.shard_batch`/`make_array_from_process_local_data`,
`Trainer._local_slice`, the cross-process BroadcastGlobalVariablesCallback,
and the single-writer checkpoint/metrics discipline.
"""

import json
import os
import sys
import textwrap

import pytest

from horovod_tpu.launch import launcher

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mp_env(tmp_path, devices_per_proc=2, **extra):
    return {
        "HVT_PLATFORM": "cpu",
        "HVT_NUM_CPU_DEVICES": str(devices_per_proc),
        "PS_MODEL_PATH": str(tmp_path),
        **{k: str(v) for k, v in extra.items()},
    }


@pytest.mark.slow
class TestMultiProcessTraining:
    def test_tf2_two_process_fit_checkpoint_events(self, tmp_path):
        """fit under 2 processes x 2 devices: sharded batches cross the
        process boundary, rank 0 alone writes checkpoints + events."""
        code = launcher.run_local(
            2,
            [sys.executable, os.path.join(REPO, "examples", "tf2_style_mnist.py")],
            env=_mp_env(tmp_path, DRIVE_STEPS=6, DRIVE_EPOCHS=2),
            tag_output=False,
        )
        assert code == 0
        model_dir = tmp_path / "horovod-mnist"
        assert (model_dir / "checkpoint-1.msgpack").exists()
        assert (model_dir / "checkpoint-2.msgpack").exists()
        events = [
            json.loads(l)
            for l in (model_dir / "events.jsonl").read_text().splitlines()
        ]
        assert any("batch/loss" in e for e in events)
        assert any("epoch/loss" in e for e in events)
        # Epoch metrics were pushed to the platform sink by the primary only.
        metrics = [
            json.loads(l)
            for l in (tmp_path / "metrics.jsonl").read_text().splitlines()
        ]
        assert any(m["name"] == "loss" for m in metrics)

    def test_tf1_two_process_eval_export_metrics(self, tmp_path):
        """The full tf1-script tail under 2 processes: per-epoch validation
        and final evaluate() (each process feeding its _local_slice), rank-0
        serving export, platform metrics stream."""
        code = launcher.run_local(
            2,
            [sys.executable, os.path.join(REPO, "examples", "tf1_style_mnist.py")],
            env=_mp_env(
                tmp_path, DRIVE_EPOCHS=1, DRIVE_TRAIN_N=2048, DRIVE_EVAL_N=512
            ),
            tag_output=False,
        )
        assert code == 0
        model_dir = tmp_path / "horovod-mnist"
        assert (model_dir / "checkpoint-1.msgpack").exists()
        assert (model_dir / "keras-sample-model.msgpack").exists()
        exports = list((tmp_path / "horovod-mnist-export").iterdir())
        assert len(exports) == 1
        metrics = [
            json.loads(l)
            for l in (tmp_path / "metrics.jsonl").read_text().splitlines()
        ]
        # Final test-set loss reached the sink exactly once (single writer).
        assert sum(1 for m in metrics if m["name"] == "loss" and m["step"] is None) == 1

    @pytest.mark.parametrize("cache", ["stream", "device"])
    def test_multiprocess_matches_single_process(self, tmp_path, cache):
        """Same data, same seed, same global batch: a 2-process x 2-device run
        and a 1-process x 4-device run must produce identical training math —
        the process boundary is a deployment detail, not a semantics change.
        Covered for BOTH input paths: the streamed pipeline and the
        device-resident dataset (each process staging its chips' shards).
        Each worker writes its final params' digest; digests must agree."""
        script = tmp_path / "digest.py"
        script.write_text(textwrap.dedent(f"""
            import sys
            sys.path.insert(0, {REPO!r})
            import os
            import flax.linen as nn
            import numpy as np
            import optax
            import horovod_tpu as hvt

            class Probe(nn.Module):
                # Dropout-free on purpose: dropout masks key off the global
                # batch POSITION, and the example->position mapping is a
                # layout artifact (interleaved across processes vs
                # sequential), so a stochastic model would diverge for a
                # reason that has nothing to do with collective semantics.
                @nn.compact
                def __call__(self, x, train=False):
                    x = x.reshape((x.shape[0], -1))
                    x = nn.relu(nn.Dense(64)(x))
                    return nn.Dense(10)(x)

            hvt.init()
            rng = np.random.RandomState(0)
            x = rng.rand(512, 28, 28, 1).astype(np.float32)
            y = rng.randint(0, 10, size=512).astype(np.int64)
            trainer = hvt.Trainer(
                Probe(),
                hvt.DistributedOptimizer(optax.sgd(0.05)),
                loss="sparse_categorical_crossentropy",
            )
            fit_kw = (
                {{"cache": "device"}}
                if os.environ.get("DIGEST_CACHE") == "device"
                else {{"shuffle_buffer": 1}}  # deterministic order
            )
            trainer.fit(
                x=x, y=y, batch_size=32, epochs=1, steps_per_epoch=4,
                callbacks=[hvt.callbacks.BroadcastGlobalVariablesCallback(0)],
                verbose=0,
                **fit_kw,
            )
            import jax
            leaves = jax.tree.leaves(jax.device_get(trainer.state.params))
            digest = float(sum(np.abs(l).sum() for l in leaves))
            out = os.environ["DIGEST_OUT"]
            with open(f"{{out}}.{{hvt.process_rank()}}", "w") as f:
                f.write(repr(digest))
        """))
        digests = {}
        for nprocs, devs in ((1, 4), (2, 2)):
            out = tmp_path / f"digest-{nprocs}p"
            code = launcher.run_local(
                nprocs,
                [sys.executable, str(script)],
                env=_mp_env(
                    tmp_path, devices_per_proc=devs, DIGEST_OUT=out,
                    DIGEST_CACHE=cache,
                ),
                tag_output=False,
            )
            assert code == 0
            vals = [
                float((tmp_path / f"digest-{nprocs}p.{r}").read_text())
                for r in range(nprocs)
            ]
            assert all(v == vals[0] for v in vals)  # ranks agree
            digests[nprocs] = vals[0]
        assert digests[1] == pytest.approx(digests[2], rel=1e-5)


@pytest.mark.slow
class TestBroadcastActuallySyncs:
    def test_divergent_state_adopts_root(self, tmp_path):
        """The one scenario deterministic init masks: ranks start with
        DIVERGENT parameters (rank r perturbs its replicated state by +r);
        after BroadcastGlobalVariablesCallback every rank must hold rank 0's
        values — a silent no-op broadcast fails this."""
        script = tmp_path / "worker.py"
        script.write_text(textwrap.dedent(f"""
            import sys
            sys.path.insert(0, {REPO!r})
            import flax.linen as nn
            import jax
            import numpy as np
            import optax
            import horovod_tpu as hvt
            from horovod_tpu.parallel import sharding as sl

            class Probe(nn.Module):
                @nn.compact
                def __call__(self, x, train=False):
                    return nn.Dense(4)(x)

            hvt.init()
            r = hvt.process_rank()
            trainer = hvt.Trainer(Probe(), hvt.DistributedOptimizer(optax.sgd(0.0)))
            trainer.build(np.ones((2, 4), np.float32))
            # Diverge: every rank shifts its (replicated) params by +rank.
            trainer.state = trainer.state.replace(
                params=jax.tree.map(lambda p: p + r, trainer.state.params)
            )
            x = np.ones((4, 4), np.float32)
            y = np.zeros((4,), np.int32)
            trainer.fit(
                x=x, y=y, batch_size=2, epochs=1, steps_per_epoch=1,
                callbacks=[hvt.callbacks.BroadcastGlobalVariablesCallback(0)],
                verbose=0,
            )
            leaves = jax.tree.leaves(jax.device_get(trainer.state.params))
            digest = float(sum(np.sum(l) for l in leaves))
            with open({str(tmp_path)!r} + f'/bc-{{r}}', 'w') as f:
                f.write(repr(digest))
        """))
        code = launcher.run_local(
            2,
            [sys.executable, str(script)],
            env=_mp_env(tmp_path, devices_per_proc=1),
            tag_output=False,
        )
        assert code == 0
        d0 = float((tmp_path / "bc-0").read_text())
        d1 = float((tmp_path / "bc-1").read_text())
        # Identical post-training state on both ranks — and in particular
        # rank 1's +1 perturbation was overwritten by rank 0's values
        # BEFORE the (lr=0) training step, not averaged into it.
        assert d0 == d1


@pytest.mark.slow
class TestMultiProcessModelParallel:
    """The non-data axes crossing a PROCESS boundary — what a multi-host pod
    does over DCN: pipeline ppermute handoffs and MoE expert all-to-alls
    between two coordinated processes (1 device each)."""

    def _run(self, tmp_path, body: str) -> None:
        script = tmp_path / "worker.py"
        script.write_text(textwrap.dedent(f"""
            import sys
            sys.path.insert(0, {REPO!r})
            import numpy as np
            import optax
            import horovod_tpu as hvt
            from horovod_tpu.data import datasets
            from horovod_tpu.parallel import mesh as mesh_lib

            hvt.init()
            assert hvt.process_count() == 2
        """) + textwrap.dedent(body))
        code = launcher.run_local(
            2,
            [sys.executable, str(script)],
            env=_mp_env(tmp_path, devices_per_proc=1),
            tag_output=False,
        )
        assert code == 0

    @pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
    def test_pipeline_stages_across_processes(self, tmp_path, schedule):
        self._run(tmp_path, f"""
            from horovod_tpu.models import pipelined_lm
            mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=1, pipe=2))
            model = pipelined_lm.PipelinedLM(
                vocab_size=16, d_model=16, n_heads=2, n_layers=2, n_micro=2,
                mesh=mesh, schedule={schedule!r},
            )
            trainer = hvt.Trainer(
                model, hvt.DistributedOptimizer(optax.adam(1e-3)),
                mesh=mesh, param_specs=pipelined_lm.param_specs,
            )
            x, y = datasets.copy_task(4, 8, vocab_size=16)
            hist = trainer.fit(
                x=x, y=y, batch_size=4, epochs=1, steps_per_epoch=2,
                # Broadcast with process-spanning (pipe-sharded) leaves:
                # replicated leaves sync, sharded ones stay in place.
                callbacks=[hvt.callbacks.BroadcastGlobalVariablesCallback(0)],
                verbose=0,
            )
            assert np.isfinite(hist[-1]['loss'])
            with open({str(tmp_path)!r} + f'/pp-ok-{{hvt.process_rank()}}', 'w') as f:
                f.write(repr(hist[-1]['loss']))
        """)
        losses = [
            float((tmp_path / f"pp-ok-{r}").read_text()) for r in range(2)
        ]
        # SPMD coherence: both processes computed the SAME global program
        # over the SAME (replicated-where-needed) data.
        assert losses[0] == losses[1]

    def test_experts_across_processes(self, tmp_path):
        self._run(tmp_path, f"""
            from jax.sharding import PartitionSpec as P
            from horovod_tpu.models.transformer import (
                ShardingConfig, TransformerLM, param_specs,
            )
            mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=1, expert=2))
            model = TransformerLM(
                vocab_size=16, d_model=16, n_heads=2, n_layers=2, dropout=0.0,
                moe_every=2, n_experts=2,
                sharding=ShardingConfig(mesh=mesh, attn='dense'),
            )
            spec = P(('data', 'fsdp'), 'seq')
            trainer = hvt.Trainer(
                model, hvt.DistributedOptimizer(optax.adam(1e-3)),
                mesh=mesh, param_specs=param_specs, batch_specs=(spec, spec),
            )
            x, y = datasets.copy_task(4, 8, vocab_size=16)
            hist = trainer.fit(x=x, y=y, batch_size=4, epochs=1,
                               steps_per_epoch=2, verbose=0)
            assert np.isfinite(hist[-1]['loss'])
            with open({str(tmp_path)!r} + f'/ep-ok-{{hvt.process_rank()}}', 'w') as f:
                f.write(repr(hist[-1]['loss']))
        """)
        losses = [
            float((tmp_path / f"ep-ok-{r}").read_text()) for r in range(2)
        ]
        assert losses[0] == losses[1]


@pytest.mark.slow
class TestModelParallelCheckpointResume:
    """The durability contract for model-parallel state (VERDICT r2 #1):
    a 2-process run whose weights are sharded ACROSS the processes (pipe=2 /
    fsdp=2) checkpoints every epoch, is SIGKILLed, and a relaunch with the
    identical command must restore the sharded leaves EXACTLY (per-process
    shard digests, bitwise) and continue the epoch numbering — the
    reference's checkpoint+restore-broadcast contract
    (tensorflow2_keras_mnist.py:86-88, :68-71) on meshes the reference never
    had."""

    SETUPS = {
        "pipe2": """
            from horovod_tpu.models import pipelined_lm
            mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=1, pipe=2))
            model = pipelined_lm.PipelinedLM(
                vocab_size=16, d_model=16, n_heads=2, n_layers=2, n_micro=2,
                mesh=mesh,
            )
            trainer = hvt.Trainer(
                model, hvt.DistributedOptimizer(optax.adam(1e-3)),
                mesh=mesh, param_specs=pipelined_lm.param_specs,
            )
            fit_kw = {}
        """,
        "fsdp2": """
            from jax.sharding import PartitionSpec as P
            from horovod_tpu.models.transformer import (
                ShardingConfig, TransformerLM, param_specs,
            )
            mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=1, fsdp=2))
            model = TransformerLM(
                vocab_size=16, d_model=16, n_heads=2, n_layers=2, dropout=0.0,
                sharding=ShardingConfig(mesh=mesh, attn='dense'),
            )
            spec = P(('data', 'fsdp'), 'seq')
            trainer = hvt.Trainer(
                model, hvt.DistributedOptimizer(optax.adam(1e-3)),
                mesh=mesh, param_specs=param_specs, batch_specs=(spec, spec),
            )
            fit_kw = {}
        """,
    }

    @pytest.mark.parametrize("config", ["pipe2", "fsdp2"])
    def test_checkpoint_sigkill_resume(self, tmp_path, config):
        script = tmp_path / "worker.py"
        script.write_text(textwrap.dedent(f"""
            import sys
            sys.path.insert(0, {REPO!r})
            import os
            import signal
            import time
            import jax
            import numpy as np
            import optax
            import horovod_tpu as hvt
            from horovod_tpu import checkpoint
            from horovod_tpu.data import datasets
            from horovod_tpu.parallel import mesh as mesh_lib

            hvt.init()
            r = hvt.process_rank()
            base = {str(tmp_path)!r}
            model_dir = os.path.join(base, "ckpts")
        """) + textwrap.dedent(self.SETUPS[config]) + textwrap.dedent(f"""
            def shard_digest(tree):
                total = 0.0
                for l in jax.tree.leaves(tree):
                    for sh in l.addressable_shards:
                        total += float(np.abs(np.asarray(sh.data, np.float64)).sum())
                return total

            x, y = datasets.copy_task(8, 8, vocab_size=16)
            trainer.build(x[:4])
            assert checkpoint.is_cross_process_sharded(trainer.state)
            trainer.state, done = checkpoint.restore_latest_and_broadcast(
                model_dir, trainer.state
            )

            class DigestCallback(hvt.callbacks.Callback):
                # Record MY addressable shards' digest per epoch, BEFORE the
                # ModelCheckpoint in the list writes that epoch's shard file:
                # a complete checkpoint-N therefore implies digest-N files
                # exist on both ranks, whatever epoch the kill lands on.
                def on_epoch_end(self, epoch, logs=None):
                    with open(os.path.join(base, f"digest-{{epoch + 1}}-{{r}}"), "w") as f:
                        f.write(repr(shard_digest(self.trainer.state.params)))

            cbs = [
                hvt.callbacks.BroadcastGlobalVariablesCallback(0),
                DigestCallback(),
                # EVERY process adds ModelCheckpoint: with sharded state each
                # writes its own shard file (the callback self-gates for the
                # single-file case).
                hvt.callbacks.ModelCheckpoint(
                    os.path.join(model_dir, "checkpoint-{{epoch}}.msgpack")
                ),
            ]
            if done == 0:
                trainer.fit(x=x, y=y, batch_size=4, epochs=2, steps_per_epoch=2,
                            callbacks=cbs, verbose=0, **fit_kw)
                if r == 0:
                    time.sleep(1.0)  # grace for rank 1's epoch-2 writes
                    os.kill(os.getpid(), signal.SIGKILL)
                time.sleep(300)  # rank 1: killed by the launcher's fail-stop
            else:
                # Normally 2; 1 iff the SIGKILL raced rank 1's epoch-2 shard
                # write and the torn checkpoint-2 was (correctly) skipped.
                assert done in (1, 2), f"resume saw epoch {{done}}"
                got = shard_digest(trainer.state.params)
                want = float(open(os.path.join(base, f"digest-{{done}}-{{r}}")).read())
                assert got == want, (got, want)  # bitwise restore of MY shards
                hist = trainer.fit(x=x, y=y, batch_size=4, epochs=3,
                                   initial_epoch=done, steps_per_epoch=2,
                                   callbacks=cbs, verbose=0, **fit_kw)
                assert len(hist) == 3 - done  # only the remaining epochs ran
                assert np.isfinite(hist[-1]["loss"])
                with open(os.path.join(base, f"resumed-{{r}}"), "w") as f:
                    f.write(repr(hist[-1]["loss"]))
        """))
        # SIGKILLed children must stay out of the suite's shared persistent
        # XLA cache: a kill racing a cache write poisons the entry, and on
        # this jax a poisoned entry later deserializes into a silently
        # WRONG executable (observed here as NaN shard digests on the
        # resume leg) — the conftest caveat, applied.
        env = _mp_env(tmp_path, devices_per_proc=1,
                      JAX_ENABLE_COMPILATION_CACHE=0)
        code = launcher.run_local(
            2, [sys.executable, str(script)], env=env, tag_output=False
        )
        assert code != 0  # run 1 dies by SIGKILL
        # Epoch 1's checkpoint is always complete (both ranks passed epoch 2's
        # collectives, which gate on epoch 1's host work being done); epoch
        # 2's may be torn only in the SIGKILL race the resume run tolerates.
        ckpt = tmp_path / "ckpts" / "checkpoint-1.shards"
        assert ckpt.is_dir()
        assert (ckpt / "index.json").exists()
        assert (ckpt / "shard-0.msgpack").exists()
        assert (ckpt / "shard-1.msgpack").exists()
        code = launcher.run_local(
            2, [sys.executable, str(script)], env=env, tag_output=False
        )
        assert code == 0  # run 2 resumed, verified digests, finished epoch 3
        losses = [float((tmp_path / f"resumed-{r}").read_text()) for r in range(2)]
        assert losses[0] == losses[1]
        assert (tmp_path / "ckpts" / "checkpoint-3.shards").is_dir()


class TestMultiProcessJob:
    def test_job_spec_nprocs_2(self, tmp_path):
        """Job machinery with nprocs: 2 — both ranks launch, the gate reads
        the single-writer stream (fast: the command is a stub trainer)."""
        metrics = tmp_path / "metrics.jsonl"
        spec = tmp_path / "job.yaml"
        writer = textwrap.dedent(f"""
            import json, os
            if os.environ["HVT_PROCESS_ID"] == "0":
                with open({str(metrics)!r}, "w") as f:
                    f.write(json.dumps({{"name": "loss", "value": 0.12}}) + "\\n")
        """)
        spec.write_text(textwrap.dedent(f"""
            name: mp-job
            job:
              command: ["{sys.executable}", "-c", {json.dumps(writer)}]
              nprocs: 2
            metrics: {metrics}
            checks:
              loss:
                target: "0.0..0.3"
        """))
        from horovod_tpu.launch.job import run_job

        assert run_job(str(spec)) == 0


@pytest.mark.slow
@pytest.mark.ci_job
class TestMultiProcessCIJob:
    def test_mnist_ci_2proc_job_gates_green(self):
        """The committed 2-process CI job end-to-end: train under nprocs: 2
        and clear the reference's loss gate (config.yaml:8-11). ~6 min."""
        from horovod_tpu.launch.job import run_job

        spec = os.path.join(
            REPO, "horovod_tpu", "launch", "jobs", "mnist-ci-2proc.yaml"
        )
        assert run_job(spec) == 0


@pytest.mark.slow
class TestReshardAcrossTopologies:
    """Topology-change resume (`restore_sharded(reshard=True)`): a sharded
    checkpoint written by a 2-process fsdp=2 fleet restores into THIS
    single-process suite on a different mesh — the 'pod checkpoint on a
    workstation' / changed-fleet-size durability case the same-topology
    guard otherwise refuses."""

    def test_two_process_checkpoint_restores_single_process(self, tmp_path):
        script = tmp_path / "worker.py"
        script.write_text(textwrap.dedent(f"""
            import sys
            sys.path.insert(0, {REPO!r})
            import os
            import jax
            import numpy as np
            import optax
            import horovod_tpu as hvt
            from horovod_tpu import checkpoint
            from horovod_tpu.data import datasets
            from horovod_tpu.parallel import mesh as mesh_lib
            from jax.sharding import PartitionSpec as P
            from horovod_tpu.models.transformer import (
                ShardingConfig, TransformerLM, param_specs,
            )

            hvt.init()
            r = hvt.process_rank()
            base = {str(tmp_path)!r}
            mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=1, fsdp=2))
            model = TransformerLM(
                vocab_size=16, d_model=16, n_heads=2, n_layers=2, dropout=0.0,
                sharding=ShardingConfig(mesh=mesh, attn='dense'),
            )
            spec = P(('data', 'fsdp'), 'seq')
            trainer = hvt.Trainer(
                model, hvt.DistributedOptimizer(optax.adam(1e-3)),
                mesh=mesh, param_specs=param_specs, batch_specs=(spec, spec),
            )
            x, y = datasets.copy_task(8, 8, vocab_size=16)
            trainer.build(x[:4])
            trainer.fit(x=x, y=y, batch_size=4, epochs=1, steps_per_epoch=2,
                        verbose=0)
            assert checkpoint.is_cross_process_sharded(trainer.state)
            checkpoint.save_sharded(
                os.path.join(base, "ckpt.shards"), trainer.state
            )
            # Each rank's replica-0 shard sum: the two ranks' files tile the
            # global state exactly once, so their sum is THE global digest.
            total = 0.0
            for l in jax.tree.leaves(trainer.state):
                if isinstance(l, jax.Array):
                    for sh in l.addressable_shards:
                        if sh.replica_id == 0:
                            total += float(
                                np.abs(np.asarray(sh.data, np.float64)).sum()
                            )
                elif r == 0:
                    total += float(np.abs(np.float64(l)))
            with open(os.path.join(base, f"digest-{{r}}"), "w") as f:
                f.write(repr(total))
        """))
        env = _mp_env(tmp_path, devices_per_proc=1)
        code = launcher.run_local(
            2, [sys.executable, str(script)], env=env, tag_output=False
        )
        assert code == 0

        # Restore HERE: 1 process, different mesh (data=2, model=2), then a
        # plain single-device template — both via reshard.
        import jax
        import numpy as np
        import optax
        from jax.sharding import PartitionSpec as P

        import horovod_tpu as hvt
        from horovod_tpu import checkpoint
        from horovod_tpu.data import datasets
        from horovod_tpu.models.transformer import (
            ShardingConfig,
            TransformerLM,
            param_specs,
        )
        from horovod_tpu.parallel import mesh as mesh_lib

        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshSpec(data=2, model=2), devices=jax.devices()[:4]
        )
        model = TransformerLM(
            vocab_size=16, d_model=16, n_heads=2, n_layers=2, dropout=0.0,
            sharding=ShardingConfig(mesh=mesh, attn="dense"),
        )
        spec = P(("data", "fsdp"), "seq")
        trainer = hvt.Trainer(
            model, hvt.DistributedOptimizer(optax.adam(1e-3)),
            mesh=mesh, param_specs=param_specs, batch_specs=(spec, spec),
        )
        x, _ = datasets.copy_task(8, 8, vocab_size=16)
        trainer.build(x[:4])
        path = str(tmp_path / "ckpt.shards")
        with pytest.raises(ValueError, match="process topology"):
            checkpoint.restore_sharded(path, trainer.state)
        restored = checkpoint.restore_sharded(
            path, trainer.state, reshard=True
        )
        total = 0.0
        for leaf in jax.tree.leaves(restored):
            if isinstance(leaf, jax.Array):
                arr = np.asarray(jax.device_get(leaf), np.float64)
                total += float(np.abs(arr).sum())
            else:
                total += float(np.abs(np.float64(leaf)))
        want = sum(
            float((tmp_path / f"digest-{r}").read_text()) for r in range(2)
        )
        np.testing.assert_allclose(total, want, rtol=1e-9)
        # And training continues from the resharded state.
        trainer.state = restored
        x, y = datasets.copy_task(8, 8, vocab_size=16)
        hist = trainer.fit(x=x, y=y, batch_size=4, epochs=1,
                           steps_per_epoch=2, verbose=0)
        assert np.isfinite(hist[-1]["loss"])


@pytest.mark.slow
class TestExportFromCrossProcessShardedState:
    """VERDICT Missing #2, multi-host half: params sharded ACROSS processes
    (fsdp spanning a 2-process mesh; pipeline stages) export via the
    collective gather path — every process calls export_serving, the
    primary writes a bundle that matches single-device predict."""

    SCRIPT = """
        import sys
        sys.path.insert(0, {repo!r})
        import os
        import numpy as np
        import optax
        import jax
        import horovod_tpu as hvt
        from horovod_tpu import checkpoint
        from horovod_tpu.parallel import mesh as mesh_lib
        from horovod_tpu.models import pipelined_lm, transformer
        from horovod_tpu.models.pipelined_lm import PipelinedLM
        from horovod_tpu.models.transformer import TransformerLM

        hvt.init()
        case = os.environ["EXPORT_CASE"]
        kw = dict(vocab_size=32, d_model=32, n_heads=4, n_layers=2)
        if case == "fsdp":
            mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=2, fsdp=2))
            model = TransformerLM(dropout=0.0, **kw)
            specs = transformer.param_specs
            apply_model = model
        else:
            mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=2, pipe=2))
            model = PipelinedLM(n_micro=2, mesh=mesh, **kw)
            specs = pipelined_lm.param_specs
            apply_model = PipelinedLM(n_micro=2, mesh=None, **kw)
        trainer = hvt.Trainer(
            model,
            hvt.DistributedOptimizer(optax.adam(1e-3)),
            loss="sparse_categorical_crossentropy",
            mesh=mesh,
            param_specs=specs,
        )
        x = (np.arange(4 * 16, dtype=np.int32).reshape(4, 16) % 32)
        state = trainer.build(x)
        assert checkpoint.is_cross_process_sharded(state.params), (
            "test setup expected cross-process sharded params"
        )
        bundle = checkpoint.export_serving(
            os.environ["EXPORT_OUT"],
            lambda p, xx: apply_model.apply({{"params": p}}, xx),
            state.params,
            input_shape=(2, 16),
            input_dtype=np.int32,
            timestamp="19700101-000000",
        )
        # Collective contract: primary writes, others return None.
        assert (bundle is not None) == hvt.is_primary()
    """

    @pytest.mark.parametrize("case", ["fsdp", "pipe"])
    def test_export_matches_single_device_predict(self, tmp_path, case):
        import textwrap as tw

        import jax
        import jax.numpy as jnp
        import numpy as np

        script = tmp_path / f"export_{case}.py"
        script.write_text(tw.dedent(self.SCRIPT.format(repo=REPO)))
        out = tmp_path / f"export-{case}"
        code = launcher.run_local(
            2,
            [sys.executable, str(script)],
            env=_mp_env(
                tmp_path, devices_per_proc=2,
                EXPORT_CASE=case, EXPORT_OUT=out,
            ),
            tag_output=False,
        )
        assert code == 0
        bundle = out / "19700101-000000"
        assert bundle.is_dir()

        # Single-device ground truth: same deterministic init (Trainer
        # seed), mesh-free apply.
        from horovod_tpu import checkpoint
        from horovod_tpu.models.pipelined_lm import PipelinedLM
        from horovod_tpu.models.transformer import TransformerLM

        kw = dict(vocab_size=32, d_model=32, n_heads=4, n_layers=2)
        model = (
            TransformerLM(dropout=0.0, **kw) if case == "fsdp"
            else PipelinedLM(n_micro=2, mesh=None, **kw)
        )
        import optax

        import horovod_tpu as hvt
        from horovod_tpu.parallel import mesh as mesh_lib

        trainer = hvt.Trainer(
            model,
            hvt.DistributedOptimizer(optax.adam(1e-3)),
            loss="sparse_categorical_crossentropy",
            mesh=mesh_lib.build_mesh(
                mesh_lib.MeshSpec(data=1), devices=jax.devices()[:1]
            ),
        )
        x = (np.arange(4 * 16, dtype=np.int32).reshape(4, 16) % 32)
        state = trainer.build(x)
        xq = (np.arange(2 * 16, dtype=np.int32).reshape(2, 16) * 3) % 32
        want = np.asarray(
            jax.nn.softmax(
                model.apply(
                    {"params": jax.device_get(state.params)}, jnp.asarray(xq)
                ),
                axis=-1,
            )
        )
        got = np.asarray(checkpoint.load_serving(str(bundle))(xq))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


@pytest.mark.slow
class TestEMAShardedCheckpointFormat:
    """VERDICT Weak #5: with params sharded ACROSS processes the EMA shadow
    persists via the sharded directory format (every process writes its
    shard; per-epoch dirs; newest-complete discovery) and a relaunch
    resumes the same running average."""

    SCRIPT = """
        import sys
        sys.path.insert(0, {repo!r})
        import os
        import numpy as np
        import optax
        import jax
        import horovod_tpu as hvt
        from horovod_tpu import checkpoint
        from horovod_tpu.parallel import mesh as mesh_lib
        from horovod_tpu.models import transformer
        from horovod_tpu.models.transformer import TransformerLM
        from horovod_tpu.training.callbacks import ExponentialMovingAverage
        from jax.sharding import PartitionSpec as P

        hvt.init()
        mesh = mesh_lib.build_mesh(mesh_lib.MeshSpec(data=2, fsdp=2))
        trainer = hvt.Trainer(
            TransformerLM(
                vocab_size=32, d_model=32, n_heads=4, n_layers=2,
                dropout=0.0,
            ),
            hvt.DistributedOptimizer(optax.adam(1e-2)),
            loss="sparse_categorical_crossentropy",
            mesh=mesh,
            param_specs=transformer.param_specs,
            batch_specs=(P(("data", "fsdp")), P(("data", "fsdp"))),
        )
        rng = np.random.RandomState(0)
        x = rng.randint(1, 32, size=(32, 16)).astype(np.int32)
        y = np.roll(x, -1, axis=1).astype(np.int32)
        d = os.environ["EMA_DIR"]
        ema = ExponentialMovingAverage(decay=0.8, checkpoint_dir=d)
        trainer.fit(
            x=x, y=y, epochs=2, batch_size=8, callbacks=[ema], verbose=0
        )
        assert checkpoint.is_cross_process_sharded(ema._ema), (
            "test setup expected a cross-process sharded shadow"
        )
        if hvt.is_primary():
            with open(os.environ["COUNT_OUT"], "a") as f:
                f.write(f"{{ema._count}}\\n")
    """

    def test_relaunch_resumes_sharded_shadow(self, tmp_path):
        import textwrap as tw

        script = tmp_path / "ema_sharded.py"
        script.write_text(tw.dedent(self.SCRIPT.format(repo=REPO)))
        ema_dir = tmp_path / "ema-ckpt"
        ema_dir.mkdir()
        count_out = tmp_path / "counts.txt"
        env = _mp_env(
            tmp_path, devices_per_proc=2,
            EMA_DIR=ema_dir, COUNT_OUT=count_out,
        )
        for _ in range(2):  # run, then relaunch-resume
            code = launcher.run_local(
                2, [sys.executable, str(script)], env=env, tag_output=False
            )
            assert code == 0
        counts = [int(l) for l in count_out.read_text().split()]
        # The second run RESUMED the average: its final count is the
        # first run's plus its own updates, not a restart from zero.
        assert len(counts) == 2
        assert counts[1] == 2 * counts[0], counts
        # Persisted in the sharded directory format, per-epoch dirs,
        # and never the single-file path.
        shards = [
            p.name for p in ema_dir.iterdir() if p.name.endswith(".shards")
        ]
        assert shards, list(ema_dir.iterdir())
        assert not (ema_dir / "ema.msgpack").exists()
