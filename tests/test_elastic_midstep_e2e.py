"""Step-granular elastic recovery end-to-end (the ISSUE 5 acceptance run):

a 3-process elastic fleet gets a STEP-filtered ``leave`` fault
(``HVT_FAULT=2:1.5:leave``) — rank 2 records leave intent at optimizer
step 5 OF epoch 1, mid-epoch. With ``rescale_every_steps`` the membership
agreement runs at step boundaries, so the departure executes within steps
(not at the epoch end): survivors commit at the current ``(epoch, step)``,
tear down in lockstep, re-rendezvous at size 2, and resume with
``fit(initial_epoch=, initial_step=)`` — the data iterator fast-forwarded
to the committed optimizer step. The supervisor spawns a replacement; its
join is likewise admitted at a step boundary, mid-epoch.

The assertions are the acceptance criteria verbatim:

* **step counter exact, zero replayed optimizer steps** — the rank-0
  per-step trace covers every global optimizer step exactly once, and the
  optimizer's own step counter equals the global step at every point;
* **loss trajectory equal (rel 1e-4) to an uninterrupted control** — the
  feed is a pure function of the global batch index and identical on
  every rank (so the gradient is world-size-invariant), and a 1-process
  uninterrupted control run must produce the same per-step losses;
* **the joiner is admitted mid-epoch** — the coordinator's ``grow_step``
  journal record carries step > 0 (and ``shrink_step`` > 0 proves the
  shrink was mid-epoch), the gate contract of
  `launch/jobs/mnist-elastic-midstep-2proc.yaml`.
"""

import json
import os
import re
import subprocess
import sys
import textwrap

import pytest

from horovod_tpu.launch import ci_gate, supervisor
from horovod_tpu.launch.supervisor import ElasticPolicy, RestartPolicy

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

EPOCHS = 4
STEPS = 40  # optimizer steps per epoch

# One script, two modes. Elastic mode is the plain `elastic.run` idiom
# with the step-granular resume contract (initial_epoch AND initial_step);
# CONTROL=1 runs the identical fit uninterrupted in one process. The feed
# is deterministic AND world-size-invariant: batch i is a pure function of
# the global batch index, and every rank feeds the SAME batch, so the
# allreduced gradient — hence the whole trajectory — does not depend on
# the world size, and the two runs are comparable per step at rel 1e-4.
TRAIN_SCRIPT = """
import os, sys, time
sys.path.insert(0, __REPO__)
import numpy as np
import optax
import flax.linen as nn
import horovod_tpu as hvt
from horovod_tpu import elastic

STEPS = __STEPS__
EPOCHS = __EPOCHS__

print(f"BOOT member={os.environ.get('HVT_ELASTIC_MEMBER', 'control')}",
      flush=True)


class Tiny(nn.Module):
    @nn.compact
    def __call__(self, x, train=False):
        return nn.Dense(4)(x)


def make_batch(i):
    # Pure function of the GLOBAL batch index — the determinism anchor.
    rng = np.random.RandomState(1000 + i)
    x = rng.rand(8, 8).astype("float32")
    y = rng.randint(0, 4, size=(8,)).astype("int64")
    return x, y


class Stream:
    \"""`ArrayDataset.batches`-protocol feed over the global index space.
    ``start`` anchors position 0 at the resume epoch's first batch, so a
    resumed fit(initial_epoch=E, initial_step=S) — which skips S batches —
    lands at global batch E*STEPS+S, exactly where the uninterrupted
    control is at that optimizer step.\"""

    def __init__(self, start=0):
        self.start = start

    def batches(self, skip=0):
        i = self.start + skip
        while True:
            yield make_batch(i)
            i += 1

    def __iter__(self):
        return self.batches()


class Trace(hvt.callbacks.Callback):
    \"""Per-step proof line from rank 0: global step, the optimizer's own
    step counter, and the step's loss.\"""

    def __init__(self, rank, size):
        self.rank, self.size = rank, size
        self._epoch = 0

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch

    def on_batch_end(self, batch, logs=None):
        import jax
        g = self._epoch * STEPS + batch + 1
        if self.rank == 0:
            opt = int(jax.device_get(self.trainer.state.step))
            print(f"TRACE g={g} opt={opt} loss={float(logs['loss']):.8f}",
                  flush=True)
        if self.size < 3 and os.environ.get("CONTROL") != "1":
            # Pace the shrunken generation so the replacement's join
            # (spawn + jax import away) lands MID-epoch deterministically.
            time.sleep(0.25)


def make_trainer():
    trainer = hvt.Trainer(Tiny(), hvt.DistributedOptimizer(optax.adam(1e-2)))
    x0, y0 = make_batch(0)
    trainer.build(x0, y0)
    return trainer


def train(state, world):
    print(f"GEN member={os.environ['HVT_ELASTIC_MEMBER']} rank={world.rank} "
          f"size={world.size} gen={world.generation} epoch={state.epoch} "
          f"step={state.step}", flush=True)
    trainer = make_trainer()
    if state.state is not None:
        trainer.install_state(state.state)
    cbs = [Trace(world.rank, world.size),
           elastic.ElasticStateCallback(state, state.client)]
    trainer.fit(
        dataset=Stream(start=state.epoch * STEPS),
        steps_per_epoch=STEPS, epochs=EPOCHS,
        initial_epoch=state.epoch, initial_step=state.step,
        callbacks=cbs, verbose=0,
    )


if os.environ.get("CONTROL") == "1":
    hvt.init()
    trainer = make_trainer()
    trainer.fit(
        dataset=Stream(0), steps_per_epoch=STEPS, epochs=EPOCHS,
        callbacks=[Trace(0, 3)], verbose=0,
    )
else:
    elastic.run(train)
print("TRAINING COMPLETE", flush=True)
"""

TRACE_RE = re.compile(r"TRACE g=(\d+) opt=(\d+) loss=([0-9.eE+-]+)")


def _write_script(tmp_path):
    path = tmp_path / "midstep_train.py"
    path.write_text(
        textwrap.dedent(TRAIN_SCRIPT)
        .replace("__REPO__", repr(REPO))
        .replace("__STEPS__", str(STEPS))
        .replace("__EPOCHS__", str(EPOCHS))
    )
    return [sys.executable, str(path)]


def _traces(out):
    return {
        int(m.group(1)): (int(m.group(2)), float(m.group(3)))
        for m in TRACE_RE.finditer(out)
    }


def _journal(log):
    with open(log) as f:
        return [json.loads(line) for line in f if line.strip()]


@pytest.mark.slow
def test_midepoch_leave_resumes_at_step_and_matches_control(tmp_path, capfd):
    argv = _write_script(tmp_path)
    base_env = {
        "HVT_PLATFORM": "cpu",
        "HVT_NUM_CPU_DEVICES": "1",
        "JAX_ENABLE_COMPILATION_CACHE": "0",
        "JAX_COMPILATION_CACHE_DIR": "",
    }

    # The uninterrupted control: same fit, one process, no chaos.
    control = subprocess.run(
        argv, capture_output=True, text=True, timeout=300,
        env={**os.environ, **base_env, "CONTROL": "1"},
    )
    assert control.returncode == 0, control.stdout[-3000:] + control.stderr[-3000:]
    control_traces = _traces(control.stdout)
    total = EPOCHS * STEPS
    assert sorted(control_traces) == list(range(1, total + 1))

    # The chaos run: rank 2 leaves at epoch 1 STEP 5 (mid-epoch), the
    # agreement cadence is 2 optimizer steps, and every step is committed
    # so the boundary always resumes fresh (zero replayed steps).
    log = tmp_path / "restarts.jsonl"
    env = {
        **base_env,
        "HVT_FAULT": "2:1.5:leave",
        "HVT_FAULT_STAMP": str(tmp_path / "leave-stamp"),
    }
    code = supervisor.supervise_elastic(
        3, argv, env=env,
        policy=RestartPolicy(max_restarts=4, backoff=0.5, grace_seconds=10.0),
        elastic=ElasticPolicy(min_ranks=2, max_ranks=3,
                              rendezvous_timeout=180.0,
                              commit_every_steps=1, rescale_every_steps=2),
        log_path=str(log),
    )
    out = capfd.readouterr().out
    assert code == 0, out[-4000:]
    assert "TRAINING COMPLETE" in out

    # Survivors were NOT restarted: 3 initial members + 1 replacement.
    boots = re.findall(r"BOOT member=(\S+)", out)
    assert len(boots) == 4 and len(set(boots)) == 4, boots

    # --- step counter exact, zero replayed optimizer steps -----------------
    # Each generation's rank 0 traces the steps it trained; across the whole
    # run every global optimizer step appears EXACTLY once (a replayed step
    # would duplicate a g=, a skipped one would leave a hole), and the
    # optimizer's own step counter agrees with the global step everywhere —
    # the committed (epoch, step) resume is exact.
    lines = re.findall(r"TRACE g=(\d+)", out)
    assert sorted(int(g) for g in lines) == list(range(1, total + 1)), (
        "replayed or skipped optimizer steps",
        sorted(int(g) for g in lines)[:10],
    )
    chaos_traces = _traces(out)
    for g, (opt, _) in sorted(chaos_traces.items()):
        assert opt == g, (g, opt)

    # --- the rescales happened MID-epoch, at step boundaries ---------------
    records = _journal(log)
    shrink = next(r for r in records if r["name"] == "shrink")
    assert shrink["size"] == 2
    assert shrink["epoch"] == 1 and shrink["step"] > 0, shrink
    grow = next(r for r in records if r["name"] == "grow")
    assert grow["size"] == 3 and grow["step"] > 0, grow
    # The departure was the CLEAN path; nobody exhausted the budget.
    names = [r["name"] for r in records]
    assert "leave" in names
    assert "supervisor_gave_up" not in names
    # The CI-gate contract of mnist-elastic-midstep-2proc.yaml, verbatim.
    ok, value = ci_gate.check_metrics(
        str(log), "shrink_step", (1.0, 999999.0), how="max")
    assert ok and value >= 1.0
    ok, _ = ci_gate.check_metrics(str(log), "shrink", (1.0, 9.0), how="count")
    assert ok

    # A resumed generation really did start mid-epoch (initial_step > 0).
    gens = re.findall(r"GEN member=\S+ rank=\d+ size=\d+ gen=\d+ "
                      r"epoch=(\d+) step=(\d+)", out)
    assert any(int(s) > 0 for _, s in gens), gens

    # --- loss trajectory equal (rel 1e-4) to the uninterrupted control -----
    for g in range(1, total + 1):
        c, x = control_traces[g][1], chaos_traces[g][1]
        assert x == pytest.approx(c, rel=1e-4, abs=1e-6), (g, c, x)
