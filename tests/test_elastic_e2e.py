"""Elastic training end-to-end on CPU/gloo (the ISSUE acceptance run):

a 3-process fleet under `supervise_elastic` gets a ``leave`` fault injected
at rank 2 mid-training. The departing rank executes a clean exit at the
epoch boundary (agreement → synchronized teardown → coordinator leave →
exit 143); the survivors re-rendezvous at size 2 and continue from the
last committed state WITHOUT their processes restarting; the supervisor
spawns a replacement that joins and grows the fleet back to 3; training
completes with a monotonic step counter and at most one commit interval
(= one epoch here) of recomputed progress. Every transition lands in the
generation-tagged journal, which the CI gate's ``count`` aggregate then
asserts — the same checks `launch/jobs/mnist-elastic-2proc.yaml` encodes.

All chaos is injected through env vars (`horovod_tpu.testing.faults`);
the training script is the plain `elastic.run` idiom."""

import json
import os
import re
import sys
import textwrap

import pytest

from horovod_tpu.launch import ci_gate, supervisor
from horovod_tpu.launch.supervisor import ElasticPolicy, RestartPolicy

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

EPOCHS = 10

# Tiny synthetic elastic trainer (no downloads): the examples'
# elastic_mnist.py idiom at test scale. STATUS lines carry the
# per-generation observability the assertions parse; the epoch pace keeps
# the shrunken generation alive long enough for the replacement to join
# (spawn + jax import ≈ seconds), so the grow leg is exercised
# deterministically.
TRAIN_SCRIPT = """
import os, sys, time
sys.path.insert(0, __REPO__)
import numpy as np
import optax
import flax.linen as nn
import horovod_tpu as hvt
from horovod_tpu import checkpoint, elastic

print(f"BOOT member={os.environ['HVT_ELASTIC_MEMBER']}", flush=True)


class Tiny(nn.Module):
    @nn.compact
    def __call__(self, x, train=False):
        return nn.Dense(4)(x)


def train(state, world):
    print(
        f"GEN member={os.environ['HVT_ELASTIC_MEMBER']} rank={world.rank} "
        f"size={world.size} gen={world.generation}", flush=True,
    )
    model_dir = os.path.join(os.environ["PS_MODEL_PATH"], "run")
    rng = np.random.RandomState(0)
    x = rng.rand(96, 8).astype("float32")
    y = (np.arange(96) % 4).astype("int64")
    trainer = hvt.Trainer(Tiny(), hvt.DistributedOptimizer(optax.adam(1e-2)))
    trainer.build(x[:1], y[:1])
    if state.state is not None:
        trainer.install_state(state.state)
    else:
        trainer.state, done = checkpoint.restore_latest_and_broadcast(
            model_dir, trainer.state, mesh=trainer.mesh)
        state.epoch = max(state.epoch, done)
    cbs = []
    if world.rank == 0:
        cbs.append(hvt.callbacks.ModelCheckpoint(
            os.path.join(model_dir, "checkpoint-{epoch}.msgpack")))

    class Status(hvt.callbacks.Callback):
        def on_epoch_end(self, epoch, logs=None):
            import jax
            step = int(jax.device_get(self.trainer.state.step))
            print(
                f"STATUS epoch={epoch + 1} step={step} rank={world.rank} "
                f"size={world.size} gen={world.generation}", flush=True,
            )
            if world.size < 3:
                # Pace the shrunken generation so the replacement's join
                # (a process spawn + jax import away) lands mid-training.
                time.sleep(2.0)

    cbs.append(Status())
    cbs.append(elastic.ElasticStateCallback(state, state.client))
    trainer.fit(
        x=x, y=y, batch_size=8, epochs=__EPOCHS__,
        initial_epoch=state.epoch, steps_per_epoch=2, callbacks=cbs,
        verbose=0,
    )


elastic.run(train)
print("TRAINING COMPLETE", flush=True)
"""


def _write_script(tmp_path):
    path = tmp_path / "elastic_train.py"
    path.write_text(
        textwrap.dedent(TRAIN_SCRIPT)
        .replace("__REPO__", repr(REPO))
        .replace("__EPOCHS__", str(EPOCHS))
    )
    return [sys.executable, str(path)]


def _journal(log):
    with open(log) as f:
        return [json.loads(line) for line in f if line.strip()]


@pytest.mark.slow
def test_leave_shrinks_grows_back_and_completes(tmp_path, capfd):
    argv = _write_script(tmp_path)
    model_dir = tmp_path / "models"
    log = tmp_path / "restarts.jsonl"
    env = {
        "HVT_PLATFORM": "cpu",
        "HVT_NUM_CPU_DEVICES": "1",
        "PS_MODEL_PATH": str(model_dir),
        "HVT_FAULT": "2:1:leave",
        "HVT_FAULT_STAMP": str(tmp_path / "leave-stamp"),
        # Chaos children stay out of the suite's shared persistent XLA
        # cache (see test_supervisor_e2e._env for the torn-entry SEGFAULT).
        "JAX_ENABLE_COMPILATION_CACHE": "0",
        "JAX_COMPILATION_CACHE_DIR": "",
    }
    code = supervisor.supervise_elastic(
        3, argv, env=env,
        policy=RestartPolicy(max_restarts=4, backoff=0.5, grace_seconds=10.0),
        elastic=ElasticPolicy(min_ranks=2, max_ranks=3,
                              rendezvous_timeout=180.0),
        model_dir=str(model_dir), log_path=str(log),
    )
    out = capfd.readouterr().out
    assert code == 0, out[-4000:]

    records = _journal(log)
    names = [r["name"] for r in records]
    # Generation-tagged lifecycle: start at 3 → clean leave → shrink to 2 →
    # replacement joins → grow back to 3.
    settles = [
        (r["name"], r["size"], r["generation"]) for r in records
        if r["name"] in ("start", "shrink", "grow", "steady")
    ]
    assert settles[0][0] == "start" and settles[0][1] == 3
    kinds = [s[0] for s in settles]
    assert "shrink" in kinds and "grow" in kinds
    assert kinds.index("shrink") < kinds.index("grow")
    assert settles[kinds.index("shrink")][1] == 2
    assert settles[kinds.index("grow")][1] == 3
    gens = [s[2] for s in settles]
    assert gens == sorted(gens)  # generations only move forward
    assert "leave" in names  # the departure was the CLEAN path
    assert not any(r["name"] == "supervisor_gave_up" for r in records)

    # The CI-gate contract of mnist-elastic-2proc.yaml, verbatim.
    ok, value = ci_gate.check_metrics(
        str(log), "shrink", (1.0, 9.0), how="count")
    assert ok and value >= 1.0
    ok, _ = ci_gate.check_metrics(str(log), "grow", (1.0, 9.0), how="count")
    assert ok

    # Survivors were NOT restarted: exactly 4 process boots — the initial
    # 3 members plus the one replacement.
    boots = re.findall(r"BOOT member=(\S+)", out)
    assert len(boots) == 4, boots
    assert len(set(boots)) == 4

    # Continue-through-failure: training resumed from committed state, so
    # the step counter is an exact function of the epoch — monotonic, with
    # no recomputed or skipped epochs (≤ one commit interval of loss; the
    # clean boundary makes it exactly zero here).
    statuses = [
        (int(m.group(1)), int(m.group(2)))
        for m in re.finditer(r"STATUS epoch=(\d+) step=(\d+)", out)
    ]
    assert statuses, out[-2000:]
    assert all(step == 2 * epoch for epoch, step in statuses), statuses
    assert max(e for e, _ in statuses) == EPOCHS
    assert "TRAINING COMPLETE" in out

    # The world actually shrank and grew mid-run: some epoch trained at
    # size 2 and a LATER one at size 3 again.
    sizes = [
        (int(m.group(1)), int(m.group(2)))
        for m in re.finditer(r"STATUS epoch=(\d+) .* size=(\d+)", out)
    ]
    assert any(s == 2 for _, s in sizes)
    shrunk_epochs = [e for e, s in sizes if s == 2]
    regrown = [e for e, s in sizes if s == 3 and e > min(shrunk_epochs)]
    assert regrown, sizes

    # Serving-side surface agrees with the journal.
    status = supervisor.fleet_status(str(log))
    assert status["size"] == 3 and status["shrinks"] >= 1
    assert status["grows"] >= 1
