"""Pipeline parallelism: the GPipe schedule (parallel/pipeline.py) and the
pipelined LM (models/pipelined_lm.py) on the virtual 8-device mesh.

The load-bearing checks are the parity ones: the pipelined forward AND its
autodiff-derived backward must compute exactly what the sequential layer
stack computes — the schedule is an execution detail, not a model change.
"""

import jax

from horovod_tpu import compat
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvt
from horovod_tpu.data import datasets
from horovod_tpu.models import pipelined_lm
from horovod_tpu.models.pipelined_lm import PipelinedLM
from horovod_tpu.parallel import mesh as mesh_lib
from horovod_tpu.parallel.pipeline import spmd_pipeline, stage_slice_size

# Compile-heavy end-to-end tier (suite diet: default run stays fast).
pytestmark = pytest.mark.slow

VOCAB = 32


def _mesh(data=2, pipe=4):
    return mesh_lib.build_mesh(mesh_lib.MeshSpec(data=data, pipe=pipe))


class TestSchedule:
    def test_four_stage_chain_equals_sequential(self):
        """Stage s multiplies by w[s] and adds b[s]; the pipeline over 4
        stages must equal applying all four transforms in order."""
        mesh = _mesh(data=2, pipe=4)
        w = jnp.asarray([2.0, 3.0, 0.5, 4.0]).reshape(4, 1)
        bias = jnp.asarray([1.0, -2.0, 0.25, 3.0]).reshape(4, 1)
        x_micro = jnp.asarray(
            np.random.RandomState(0).rand(6, 2, 3), jnp.float32
        )

        def run(wp, bp, xm):
            def stage(a):
                # this stage's [1, 1] slice of w/b
                return a * wp[0, 0] + bp[0, 0]

            return spmd_pipeline(stage, xm)

        out = compat.shard_map(
            run,
            mesh=mesh,
            in_specs=(P("pipe", None), P("pipe", None), P(None, None, None)),
            out_specs=P(None, None, None),
            check_vma=False,
        )(w, bias, x_micro)

        expect = x_micro
        for i in range(4):
            expect = expect * w[i, 0] + bias[i, 0]
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect), rtol=1e-6)

    def test_stage_slice_validation(self):
        assert stage_slice_size(8, 4) == 2
        with pytest.raises(ValueError, match="divisible"):
            stage_slice_size(6, 4)


def _models(n_layers=4, n_micro=4, mesh=None):
    kw = dict(
        vocab_size=VOCAB, d_model=32, n_heads=4,
        n_layers=n_layers, n_micro=n_micro,
    )
    return PipelinedLM(**kw, mesh=mesh), PipelinedLM(**kw, mesh=None)


class TestParity:
    def test_forward_matches_sequential(self):
        mesh = _mesh()
        piped, plain = _models(mesh=mesh)
        rng = np.random.RandomState(1)
        toks = jnp.asarray(rng.randint(1, VOCAB, size=(8, 16)).astype(np.int32))
        params = plain.init(jax.random.PRNGKey(0), toks)["params"]
        out_plain = plain.apply({"params": params}, toks)
        out_piped = jax.jit(lambda p, t: piped.apply({"params": p}, t))(
            params, toks
        )
        np.testing.assert_allclose(
            np.asarray(out_plain), np.asarray(out_piped), rtol=2e-4, atol=2e-4
        )

    def test_backward_matches_sequential(self):
        """jax.grad through the scan+ppermute schedule must produce the same
        gradients as through the plain layer stack — the derived reverse
        pipeline is correct."""
        mesh = _mesh()
        piped, plain = _models(mesh=mesh)
        rng = np.random.RandomState(2)
        toks = jnp.asarray(rng.randint(1, VOCAB, size=(8, 16)).astype(np.int32))
        labels = jnp.asarray(rng.randint(1, VOCAB, size=(8, 16)).astype(np.int32))
        params = plain.init(jax.random.PRNGKey(0), toks)["params"]

        def loss(model):
            def f(p):
                logits = model.apply({"params": p}, toks)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, labels
                ).mean()

            return f

        g_plain = jax.grad(loss(plain))(params)
        g_piped = jax.jit(jax.grad(loss(piped)))(params)
        for key in g_plain:
            np.testing.assert_allclose(
                np.asarray(g_plain[key]), np.asarray(g_piped[key]),
                rtol=2e-3, atol=2e-5, err_msg=key,
            )

    def test_causality(self):
        mesh = _mesh()
        piped, plain = _models(mesh=mesh)
        rng = np.random.RandomState(3)
        toks = rng.randint(1, VOCAB, size=(8, 16)).astype(np.int32)
        params = plain.init(jax.random.PRNGKey(0), jnp.asarray(toks))["params"]
        f = jax.jit(lambda p, t: piped.apply({"params": p}, t))
        out1 = f(params, jnp.asarray(toks))
        toks2 = toks.copy()
        toks2[:, 12] = (toks2[:, 12] % (VOCAB - 1)) + 1
        out2 = f(params, jnp.asarray(toks2))
        np.testing.assert_allclose(
            np.asarray(out1[:, :12]), np.asarray(out2[:, :12]), atol=1e-4
        )


class TestTraining:
    def _trainer(self, mesh, n_micro=4):
        return hvt.Trainer(
            PipelinedLM(
                vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=4,
                n_micro=n_micro, mesh=mesh,
            ),
            hvt.DistributedOptimizer(optax.adam(3e-3)),
            mesh=mesh,
            param_specs=pipelined_lm.param_specs,
        )

    def test_params_sharded_over_pipe(self):
        mesh = _mesh()
        trainer = self._trainer(mesh)
        x, _ = datasets.copy_task(8, 16, vocab_size=VOCAB)
        state = trainer.build(x)
        flat = jax.tree_util.tree_flatten_with_path(state.params)[0]
        piped = [
            path for path, leaf in flat
            if any(
                "pipe" in (ax if isinstance(ax, tuple) else (ax,))
                for ax in leaf.sharding.spec if ax is not None
            )
        ]
        assert len(piped) == 6  # the six per-layer stacks
        # embed/head replicated
        names = {p[-1].key for p, _ in flat}
        assert {"embed", "lm_head", "ln_f"} <= names

    def test_trains_on_dp_x_pp_mesh(self):
        mesh = _mesh()
        trainer = self._trainer(mesh)
        x, y = datasets.copy_task(512, 16, vocab_size=VOCAB, seed=1)
        history = trainer.fit(
            x=x, y=y, batch_size=4, epochs=2, steps_per_epoch=10, verbose=0
        )
        assert np.isfinite(history[-1]["loss"])
        assert history[-1]["loss"] < history[0]["loss"]

    def test_batch_not_divisible_by_micro_errors(self):
        mesh = _mesh()
        piped, _ = _models(n_micro=3, mesh=mesh)
        toks = jnp.zeros((8, 16), jnp.int32)
        with pytest.raises(ValueError, match="n_micro"):
            piped.init(jax.random.PRNGKey(0), toks)

    def test_rejects_expert_mesh(self):
        """A live expert axis requires mlp='moe' (TestMoEPipeline); a dense
        pipelined model on an expert mesh must be rejected loudly, not
        silently leave the axis unused."""
        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshSpec(data=2, pipe=2, expert=2)
        )
        piped = PipelinedLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=4, mesh=mesh
        )
        with pytest.raises(ValueError, match="expert"):
            piped.init(jax.random.PRNGKey(0), jnp.zeros((8, 16), jnp.int32))


class Test1F1B:
    """The hand-scheduled staggered backward (spmd_pipeline_1f1b) must be
    math-identical to the AD-derived GPipe backward — the schedule changes
    activation memory, never gradients."""

    def _lm(self, schedule, mesh):
        return PipelinedLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=4,
            n_micro=4, mesh=mesh, schedule=schedule,
        )

    def test_forward_matches_gpipe(self):
        mesh = _mesh()
        rng = np.random.RandomState(11)
        toks = jnp.asarray(rng.randint(1, VOCAB, size=(8, 16)).astype(np.int32))
        plain = self._lm("gpipe", None)
        params = plain.init(jax.random.PRNGKey(0), toks)["params"]
        out_g = jax.jit(
            lambda p, t: self._lm("gpipe", mesh).apply({"params": p}, t)
        )(params, toks)
        out_1 = jax.jit(
            lambda p, t: self._lm("1f1b", mesh).apply({"params": p}, t)
        )(params, toks)
        np.testing.assert_allclose(
            np.asarray(out_g), np.asarray(out_1), rtol=1e-5, atol=1e-5
        )

    def test_gradients_match_gpipe_and_sequential(self):
        mesh = _mesh()
        rng = np.random.RandomState(12)
        toks = jnp.asarray(rng.randint(1, VOCAB, size=(8, 16)).astype(np.int32))
        labels = jnp.asarray(rng.randint(1, VOCAB, size=(8, 16)).astype(np.int32))
        plain = self._lm("gpipe", None)
        params = plain.init(jax.random.PRNGKey(0), toks)["params"]

        def loss_of(model):
            def f(p):
                logits = model.apply({"params": p}, toks)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, labels
                ).mean()

            return f

        g_seq = jax.grad(loss_of(plain))(params)
        g_1f1b = jax.jit(jax.grad(loss_of(self._lm("1f1b", mesh))))(params)
        g_gpipe = jax.jit(jax.grad(loss_of(self._lm("gpipe", mesh))))(params)
        for key in g_seq:
            np.testing.assert_allclose(
                np.asarray(g_1f1b[key]), np.asarray(g_gpipe[key]),
                rtol=2e-4, atol=2e-6, err_msg=f"1f1b vs gpipe: {key}",
            )
            np.testing.assert_allclose(
                np.asarray(g_1f1b[key]), np.asarray(g_seq[key]),
                rtol=2e-3, atol=2e-5, err_msg=f"1f1b vs sequential: {key}",
            )

    def test_trains(self):
        mesh = _mesh()
        tr = hvt.Trainer(
            self._lm("1f1b", mesh),
            hvt.DistributedOptimizer(optax.adam(3e-3)),
            loss="sparse_categorical_crossentropy",
            mesh=mesh,
            param_specs=pipelined_lm.param_specs,
        )
        x, y = datasets.copy_task(128, 16, vocab_size=VOCAB)
        hist = tr.fit(x=x, y=y, batch_size=8, epochs=2, steps_per_epoch=4)
        assert hist[-1]["loss"] < hist[0]["loss"]

    def test_invalid_schedule_rejected(self):
        mesh = _mesh()
        rng = np.random.RandomState(13)
        toks = jnp.asarray(rng.randint(1, VOCAB, size=(8, 16)).astype(np.int32))
        model = PipelinedLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=4,
            n_micro=4, mesh=mesh, schedule="pipedream",
        )
        with pytest.raises(ValueError, match="schedule"):
            model.init(jax.random.PRNGKey(0), toks)


class TestBubbleAccounting:
    """The GPipe bubble is measurable, not just documented: every device
    computes ticks = n_micro + S - 1 stage passes but only n_micro are
    useful, so the pipelined forward's total FLOPs must exceed the
    sequential stack's by ≈ ticks/n_micro (the bubble fraction
    (S-1)/(T+S-1) in efficiency terms)."""

    @pytest.mark.parametrize("n_micro", [4, 8])
    def test_flop_ratio_matches_tick_count(self, n_micro):
        from horovod_tpu import trace

        mesh = _mesh(data=2, pipe=4)
        n_stages = 4
        rng = np.random.RandomState(14)
        b = 2 * n_micro  # mb covers the data axis (dp=2)
        toks = jnp.asarray(rng.randint(1, VOCAB, size=(b, 16)).astype(np.int32))
        piped = PipelinedLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=4,
            n_micro=n_micro, mesh=mesh,
        )
        plain = PipelinedLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=4,
            n_micro=n_micro, mesh=None,
        )
        params = plain.init(jax.random.PRNGKey(0), toks)["params"]
        f_piped = jax.jit(lambda p, t: piped.apply({"params": p}, t))
        f_plain = jax.jit(lambda p, t: plain.apply({"params": p}, t))
        fl_piped = trace.compiled_flops(f_piped, params, toks)
        fl_plain = trace.compiled_flops(f_plain, params, toks)
        if not fl_piped or not fl_plain:
            pytest.skip("backend reports no cost analysis")
        ticks = n_micro + n_stages - 1
        # XLA's cost model reports PER-DEVICE flops: the pipelined program
        # spreads the useful work over all 8 devices (pipe 4 x data 2) but
        # every device computes `ticks` stage passes where n_micro would be
        # useful — so per-device flops = ticks/(n_micro * 8) of the plain
        # single-device stack (embed/head/LN add slack; generous band).
        expected = ticks / (n_micro * mesh.size)
        measured = fl_piped / fl_plain
        assert measured == pytest.approx(expected, rel=0.35), (
            f"FLOP ratio {measured:.2f} vs tick model {expected:.2f}"
        )

    def test_bubble_shrinks_with_more_micros(self):
        from horovod_tpu import trace

        mesh = _mesh(data=2, pipe=4)
        rng = np.random.RandomState(15)

        def flops(n_micro):
            toks = jnp.asarray(
                rng.randint(1, VOCAB, size=(2 * n_micro, 16)).astype(np.int32)
            )
            m = PipelinedLM(
                vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=4,
                n_micro=n_micro, mesh=mesh,
            )
            plain = PipelinedLM(
                vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=4,
                n_micro=n_micro, mesh=None,
            )
            params = plain.init(jax.random.PRNGKey(0), toks)["params"]
            f = jax.jit(lambda p, t: m.apply({"params": p}, t))
            g = jax.jit(lambda p, t: plain.apply({"params": p}, t))
            a, b = trace.compiled_flops(f, params, toks), trace.compiled_flops(
                g, params, toks
            )
            if not a or not b:
                pytest.skip("backend reports no cost analysis")
            # per-token overhead ratio
            return a / b

        assert flops(8) < flops(2)


class TestPipeTensorComposition:
    """PP × TP × DP on one mesh (round 3 — previously PP composed with data
    only): Megatron column/row TP inside each pipeline stage, one psum per
    residual join, under both schedules."""

    def _mesh(self):
        return mesh_lib.build_mesh(
            mesh_lib.MeshSpec(data=2, pipe=2, model=2)
        )

    def _lm(self, mesh, schedule="gpipe"):
        return PipelinedLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=4,
            n_micro=2, mesh=mesh, schedule=schedule,
        )

    @pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
    def test_forward_matches_sequential(self, schedule):
        mesh = self._mesh()
        rng = np.random.RandomState(21)
        toks = jnp.asarray(rng.randint(1, VOCAB, size=(4, 16)).astype(np.int32))
        plain = PipelinedLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=4,
            n_micro=2, mesh=None,
        )
        params = plain.init(jax.random.PRNGKey(0), toks)["params"]
        out_plain = plain.apply({"params": params}, toks)
        out = jax.jit(
            lambda p, t: self._lm(mesh, schedule).apply({"params": p}, t)
        )(params, toks)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(out_plain), rtol=2e-4, atol=2e-4,
        )

    @pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
    def test_gradients_match_sequential(self, schedule):
        mesh = self._mesh()
        rng = np.random.RandomState(22)
        toks = jnp.asarray(rng.randint(1, VOCAB, size=(4, 16)).astype(np.int32))
        labels = jnp.asarray(rng.randint(1, VOCAB, size=(4, 16)).astype(np.int32))
        plain = PipelinedLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=4,
            n_micro=2, mesh=None,
        )
        params = plain.init(jax.random.PRNGKey(0), toks)["params"]

        def loss_of(model):
            def f(p):
                logits = model.apply({"params": p}, toks)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, labels
                ).mean()

            return f

        g_seq = jax.grad(loss_of(plain))(params)
        g_pp = jax.jit(jax.grad(loss_of(self._lm(mesh, schedule))))(params)
        for key in g_seq:
            np.testing.assert_allclose(
                np.asarray(g_pp[key]), np.asarray(g_seq[key]),
                rtol=2e-3, atol=2e-5, err_msg=key,
            )

    def test_trains_with_sharded_state(self):
        """End-to-end on dp=2 x pipe=2 x model=2: param_specs shard stage
        stacks over pipe AND Megatron dims over model; training runs and
        the TP kernels really are sharded on the model axis."""
        mesh = self._mesh()
        tr = hvt.Trainer(
            self._lm(mesh, "1f1b"),
            hvt.DistributedOptimizer(optax.adam(3e-3)),
            loss="sparse_categorical_crossentropy",
            mesh=mesh,
            param_specs=pipelined_lm.param_specs,
        )
        x, y = datasets.copy_task(64, 16, vocab_size=VOCAB)
        hist = tr.fit(x=x, y=y, batch_size=4, epochs=2, steps_per_epoch=4)
        assert hist[-1]["loss"] < hist[0]["loss"]
        qkv = tr.state.params["qkv"]
        spec = qkv.sharding.spec
        assert spec[0] == "pipe" and spec[2] == "model", spec

    def test_indivisible_heads_rejected(self):
        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshSpec(data=1, pipe=2, model=4)
        )
        toks = jnp.zeros((4, 16), jnp.int32)
        model = PipelinedLM(
            vocab_size=VOCAB, d_model=32, n_heads=6, n_layers=4, mesh=mesh,
        )
        with pytest.raises(ValueError, match="divide"):
            model.init(jax.random.PRNGKey(0), toks)


class TestInterleaved:
    """Virtual-stage (Megatron-interleaved) schedule: each pipe device
    hosts `n_virtual` non-adjacent chunks, so the fill bubble is S-1 CHUNK
    times — relative overhead (v·T + S - 1)/(v·T) vs GPipe's (T + S - 1)/T.
    Stacks live in placement order on the mesh; the to_interleaved_order /
    to_logical_order helpers convert against sequential checkpoints."""

    def _lm(self, mesh, n_layers=8, n_micro=4, v=2):
        return PipelinedLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=n_layers,
            n_micro=n_micro, mesh=mesh, schedule="interleaved", n_virtual=v,
        )

    @pytest.mark.parametrize("pipe,v", [(2, 2), (4, 2)])
    def test_forward_matches_sequential(self, pipe, v):
        mesh = _mesh(data=8 // pipe, pipe=pipe)
        rng = np.random.RandomState(51)
        toks = jnp.asarray(rng.randint(1, VOCAB, size=(16, 16)).astype(np.int32))
        plain = PipelinedLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=8,
            n_micro=4, mesh=None,
        )
        params = plain.init(jax.random.PRNGKey(0), toks)["params"]
        out_plain = plain.apply({"params": params}, toks)
        inter = self._lm(mesh, v=v)
        p_inter = pipelined_lm.to_interleaved_order(params, 8, pipe, v)
        out = jax.jit(
            lambda p, t: inter.apply({"params": p}, t)
        )(p_inter, toks)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(out_plain), rtol=2e-4, atol=2e-4,
        )

    def test_order_roundtrip(self):
        plain = PipelinedLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=8, mesh=None,
        )
        params = plain.init(
            jax.random.PRNGKey(1), jnp.zeros((4, 16), jnp.int32)
        )["params"]
        there = pipelined_lm.to_interleaved_order(params, 8, 2, 2)
        back = pipelined_lm.to_logical_order(there, 8, 2, 2)
        for key in params:
            np.testing.assert_array_equal(
                np.asarray(back[key]), np.asarray(params[key]), err_msg=key
            )
        # and the permutation is NOT the identity on the stacks
        assert not np.array_equal(
            np.asarray(there["qkv"]), np.asarray(params["qkv"])
        )

    def test_gradients_match_sequential(self):
        mesh = _mesh(data=4, pipe=2)
        rng = np.random.RandomState(52)
        toks = jnp.asarray(rng.randint(1, VOCAB, size=(16, 16)).astype(np.int32))
        labels = jnp.asarray(rng.randint(1, VOCAB, size=(16, 16)).astype(np.int32))
        plain = PipelinedLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=8,
            n_micro=4, mesh=None,
        )
        params = plain.init(jax.random.PRNGKey(0), toks)["params"]

        def loss_of(model):
            def f(p):
                logits = model.apply({"params": p}, toks)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, labels
                ).mean()

            return f

        g_seq = jax.grad(loss_of(plain))(params)
        p_inter = pipelined_lm.to_interleaved_order(params, 8, 2, 2)
        g_inter = jax.jit(jax.grad(loss_of(self._lm(mesh))))(p_inter)
        g_inter = pipelined_lm.to_logical_order(g_inter, 8, 2, 2)
        for key in g_seq:
            np.testing.assert_allclose(
                np.asarray(g_inter[key]), np.asarray(g_seq[key]),
                rtol=2e-3, atol=2e-5, err_msg=key,
            )

    def test_bubble_matches_tick_model(self):
        """Per-device FLOPs of the interleaved schedule must track its tick
        model (v·T + S - 1)/(v·T · mesh.size) of the sequential stack —
        the same anchoring TestBubbleAccounting gives GPipe. (A direct
        fl_inter < fl_gpipe comparison is NOT asserted: XLA's cost analysis
        is only band-accurate across different scan structures — GPipe
        itself measures ~30% under its own tick model here — so the
        schedule-vs-schedule claim rests on the tick counts both ratios are
        anchored to: (v·T+S-1) chunk passes vs (T+S-1)·v, i.e. 11 vs 14
        layer passes per device at S=4, T=4, v=2.)"""
        from horovod_tpu import trace

        mesh = _mesh(data=2, pipe=4)
        S, T, v = 4, 4, 2
        rng = np.random.RandomState(53)
        toks = jnp.asarray(rng.randint(1, VOCAB, size=(8, 16)).astype(np.int32))
        plain = PipelinedLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=8,
            n_micro=T, mesh=None,
        )
        params = plain.init(jax.random.PRNGKey(0), toks)["params"]
        p_inter = pipelined_lm.to_interleaved_order(params, 8, S, v)
        fl_inter = trace.compiled_flops(
            jax.jit(lambda p, t: self._lm(mesh, v=v).apply({"params": p}, t)),
            p_inter, toks,
        )
        fl_plain = trace.compiled_flops(
            jax.jit(lambda p, t: plain.apply({"params": p}, t)), params, toks
        )
        if not fl_inter or not fl_plain:
            pytest.skip("backend reports no cost analysis")
        expected_inter = (v * T + S - 1) / (v * T * mesh.size)
        measured = fl_inter / fl_plain
        assert measured == pytest.approx(expected_inter, rel=0.35), (
            f"FLOP ratio {measured:.3f} vs interleaved tick model "
            f"{expected_inter:.3f}"
        )

    def test_trains(self):
        mesh = _mesh(data=4, pipe=2)
        tr = hvt.Trainer(
            self._lm(mesh, n_micro=4),
            hvt.DistributedOptimizer(optax.adam(3e-3)),
            loss="sparse_categorical_crossentropy",
            mesh=mesh,
            param_specs=pipelined_lm.param_specs,
        )
        x, y = datasets.copy_task(64, 16, vocab_size=VOCAB)
        hist = tr.fit(x=x, y=y, batch_size=8, epochs=2, steps_per_epoch=4)
        assert hist[-1]["loss"] < hist[0]["loss"]

    def test_indivisible_chunks_rejected(self):
        mesh = _mesh(data=4, pipe=2)
        model = self._lm(mesh, n_layers=6, v=4)
        with pytest.raises(ValueError, match="n_virtual"):
            model.init(jax.random.PRNGKey(0), jnp.zeros((4, 16), jnp.int32))

    def test_too_few_micros_rejected_after_init(self):
        """n_micro < n_stages must fail loudly on a REAL forward: degrading
        v to 1 would run the placement-ordered stacks contiguously — a
        permuted layer composition, not the trained function. Only flax's
        shape-only init probe may degrade."""
        mesh = _mesh(data=4, pipe=2)
        model = self._lm(mesh, n_micro=4)
        # init with a dp-sized probe batch (n_micro clamps to 1) is fine:
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((4, 16), jnp.int32)
        )["params"]
        # a real apply at the same tiny batch is not:
        with pytest.raises(ValueError, match="n_micro"):
            model.apply({"params": params}, jnp.zeros((4, 16), jnp.int32))


class TestPipeSeqComposition:
    """PP × SP × DP on one mesh (round 3 continuation): every stage's
    attention runs as ring-flash collectives around the ``seq`` ring while
    activations shard their token dim — the long-context axis composed with
    the pipeline schedule, under both schedules."""

    def _mesh(self):
        return mesh_lib.build_mesh(
            mesh_lib.MeshSpec(data=2, pipe=2, seq=2)
        )

    def _lm(self, mesh, schedule="gpipe"):
        return PipelinedLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=4,
            n_micro=2, mesh=mesh, schedule=schedule,
        )

    @pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
    def test_forward_matches_sequential(self, schedule):
        mesh = self._mesh()
        rng = np.random.RandomState(41)
        toks = jnp.asarray(rng.randint(1, VOCAB, size=(4, 16)).astype(np.int32))
        plain = self._lm(None)
        params = plain.init(jax.random.PRNGKey(0), toks)["params"]
        out_plain = plain.apply({"params": params}, toks)
        out = jax.jit(
            lambda p, t: self._lm(mesh, schedule).apply({"params": p}, t)
        )(params, toks)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(out_plain), rtol=2e-4, atol=2e-4,
        )

    @pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
    def test_gradients_match_sequential(self, schedule):
        mesh = self._mesh()
        rng = np.random.RandomState(42)
        toks = jnp.asarray(rng.randint(1, VOCAB, size=(4, 16)).astype(np.int32))
        labels = jnp.asarray(rng.randint(1, VOCAB, size=(4, 16)).astype(np.int32))
        plain = self._lm(None)
        params = plain.init(jax.random.PRNGKey(0), toks)["params"]

        def loss_of(model):
            def f(p):
                logits = model.apply({"params": p}, toks)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, labels
                ).mean()

            return f

        g_seq = jax.grad(loss_of(plain))(params)
        g_pp = jax.jit(jax.grad(loss_of(self._lm(mesh, schedule))))(params)
        for key in g_seq:
            np.testing.assert_allclose(
                np.asarray(g_pp[key]), np.asarray(g_seq[key]),
                rtol=2e-3, atol=2e-5, err_msg=key,
            )

    def test_packed_through_pipe_and_seq(self):
        """Packed documents + PP + SP together: segment ids shard over seq
        and ride the ring inside each stage; each packed document must still
        equal its solo run."""
        mesh = self._mesh()
        rng = np.random.RandomState(43)
        doc_a = rng.randint(1, VOCAB, size=(4, 8)).astype(np.int32)
        doc_b = rng.randint(1, VOCAB, size=(4, 8)).astype(np.int32)
        packed = jnp.asarray(np.concatenate([doc_a, doc_b], axis=1))
        seg = jnp.asarray(np.concatenate(
            [np.ones((4, 8)), 2 * np.ones((4, 8))], axis=1
        ).astype(np.int32))
        plain = self._lm(None)
        params = plain.init(jax.random.PRNGKey(0), packed)["params"]
        out = jax.jit(
            lambda p, tk, sg: self._lm(mesh, "1f1b").apply(
                {"params": p}, tk, segment_ids=sg
            )
        )(params, packed, seg)
        solo_a = plain.apply({"params": params}, jnp.asarray(doc_a))
        solo_b = plain.apply({"params": params}, jnp.asarray(doc_b))
        np.testing.assert_allclose(
            np.asarray(out[:, :8]), np.asarray(solo_a), rtol=3e-4, atol=3e-4
        )
        np.testing.assert_allclose(
            np.asarray(out[:, 8:]), np.asarray(solo_b), rtol=3e-4, atol=3e-4
        )

    def test_trains_on_dp_pp_sp_mesh(self):
        mesh = self._mesh()
        tr = hvt.Trainer(
            self._lm(mesh, "1f1b"),
            hvt.DistributedOptimizer(optax.adam(3e-3)),
            loss="sparse_categorical_crossentropy",
            mesh=mesh,
            param_specs=pipelined_lm.param_specs,
            batch_specs=(P(("data", "fsdp"), "seq"), P(("data", "fsdp"), "seq")),
        )
        x, y = datasets.copy_task(64, 16, vocab_size=VOCAB)
        hist = tr.fit(x=x, y=y, batch_size=4, epochs=2, steps_per_epoch=4)
        assert hist[-1]["loss"] < hist[0]["loss"]

    def test_indivisible_seq_rejected(self):
        mesh = self._mesh()
        model = self._lm(mesh)
        with pytest.raises(ValueError, match="seq axis"):
            model.init(jax.random.PRNGKey(0), jnp.zeros((4, 15), jnp.int32))


class TestPackedPipeline:
    """Packed sequences through pipeline stages (round 3): segment ids and
    per-document positions are per-microbatch CONSTANTS indexed by each
    stage directly — they never ride the ppermute ring — and the packing-
    invariance contract must hold through the schedule."""

    def _packed(self, seed=31):
        rng = np.random.RandomState(seed)
        doc_a = rng.randint(1, VOCAB, size=(4, 16)).astype(np.int32)
        doc_b = rng.randint(1, VOCAB, size=(4, 16)).astype(np.int32)
        packed = np.concatenate([doc_a, doc_b], axis=1)
        seg = np.concatenate(
            [np.ones((4, 16)), 2 * np.ones((4, 16))], axis=1
        ).astype(np.int32)
        return doc_a, doc_b, jnp.asarray(packed), jnp.asarray(seg)

    @pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
    def test_packing_invariance_through_pipeline(self, schedule):
        mesh = _mesh(data=2, pipe=4)
        doc_a, doc_b, packed, seg = self._packed()
        plain = PipelinedLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=4,
            n_micro=2, mesh=None,
        )
        params = plain.init(jax.random.PRNGKey(0), packed)["params"]
        piped = PipelinedLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=4,
            n_micro=2, mesh=mesh, schedule=schedule,
        )
        out = jax.jit(
            lambda p, tk, sg: piped.apply(
                {"params": p}, tk, segment_ids=sg
            )
        )(params, packed, seg)
        # Each packed document must equal its solo (unpacked) run.
        solo_a = plain.apply({"params": params}, jnp.asarray(doc_a))
        solo_b = plain.apply({"params": params}, jnp.asarray(doc_b))
        np.testing.assert_allclose(
            np.asarray(out[:, :16]), np.asarray(solo_a), rtol=3e-4, atol=3e-4
        )
        np.testing.assert_allclose(
            np.asarray(out[:, 16:]), np.asarray(solo_b), rtol=3e-4, atol=3e-4
        )

    @pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
    def test_packed_gradients_match_sequential(self, schedule):
        mesh = _mesh(data=2, pipe=4)
        _, _, packed, seg = self._packed(32)
        labels = jnp.asarray(
            np.random.RandomState(33).randint(1, VOCAB, size=packed.shape)
        ).astype(jnp.int32)
        piped = PipelinedLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=4,
            n_micro=2, mesh=mesh, schedule=schedule,
        )
        plain = PipelinedLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=4,
            n_micro=2, mesh=None,
        )
        params = plain.init(jax.random.PRNGKey(0), packed)["params"]

        def loss_of(model):
            def f(p):
                logits = model.apply(
                    {"params": p}, packed, segment_ids=seg
                )
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, labels
                ).mean()

            return f

        g_pp = jax.jit(jax.grad(loss_of(piped)))(params)
        g_seq = jax.grad(loss_of(plain))(params)
        for key in g_seq:
            np.testing.assert_allclose(
                np.asarray(g_pp[key]), np.asarray(g_seq[key]),
                rtol=2e-3, atol=2e-5, err_msg=key,
            )


class TestMoEPipeline:
    """pp x ep composition (round 3): every block's MLP routed through
    expert FFNs sharded over the ``expert`` axis INSIDE the pipeline's
    manual region, with the router's aux loss riding the schedules'
    differentiable with_aux channel. Group-size note: MoE routing is
    grouped (capacity is per dispatch group), so pipelined-vs-sequential
    parity holds when both paths see the same token groups —
    moe_group_size=16 makes every group one 16-token row here for every
    mesh under test.
    """

    def _lm(self, mesh, schedule="gpipe", **kw):
        kw.setdefault("n_micro", 4)
        return PipelinedLM(
            vocab_size=VOCAB, d_model=32, n_heads=4, n_layers=4,
            mesh=mesh, schedule=schedule, mlp="moe", n_experts=4,
            moe_group_size=16, **kw,
        )

    def _mesh22(self):
        # data=2 x pipe=2 on a 4-device subset (the 8-device default mesh
        # would force dp=4 and clamp n_micro below the interleaved minimum).
        return mesh_lib.build_mesh(
            mesh_lib.MeshSpec(data=2, pipe=2), devices=jax.devices()[:4]
        )

    def _data(self, seed=61, batch=8):
        rng = np.random.RandomState(seed)
        toks = jnp.asarray(
            rng.randint(1, VOCAB, size=(batch, 16)).astype(np.int32)
        )
        labels = jnp.asarray(
            rng.randint(1, VOCAB, size=(batch, 16)).astype(np.int32)
        )
        return toks, labels

    @pytest.mark.parametrize("schedule", ["gpipe", "1f1b", "interleaved"])
    def test_forward_matches_sequential(self, schedule):
        mesh = self._mesh22()
        toks, _ = self._data()
        plain = self._lm(None)
        params = plain.init(jax.random.PRNGKey(0), toks)["params"]
        expect = plain.apply({"params": params}, toks)
        p_run = params
        if schedule == "interleaved":
            p_run = pipelined_lm.to_interleaved_order(params, 4, 2, 2)
        out = jax.jit(
            lambda p, t: self._lm(mesh, schedule).apply({"params": p}, t)
        )(p_run, toks)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expect), rtol=2e-4, atol=2e-4
        )

    @pytest.mark.parametrize("schedule", ["gpipe", "1f1b", "interleaved"])
    def test_gradients_match_sequential_incl_aux(self, schedule):
        """CE + the sown load-balance loss: gradients (router included)
        must match the sequential stack — this exercises the aux channel's
        backward through every schedule (custom-vjp cotangent routing for
        1F1B)."""
        mesh = self._mesh22()
        toks, labels = self._data(62)
        plain = self._lm(None)
        params = plain.init(jax.random.PRNGKey(0), toks)["params"]

        def loss_of(model):
            def f(p):
                logits, var = model.apply(
                    {"params": p}, toks, train=True,
                    mutable=["losses", "metrics"],
                )
                ce = optax.softmax_cross_entropy_with_integer_labels(
                    logits, labels
                ).mean()
                return ce + sum(jax.tree.leaves(var.get("losses", {})))

            return f

        g_seq = jax.grad(loss_of(plain))(params)
        p_run = params
        if schedule == "interleaved":
            p_run = pipelined_lm.to_interleaved_order(params, 4, 2, 2)
        g_pp = jax.jit(jax.grad(loss_of(self._lm(mesh, schedule))))(p_run)
        if schedule == "interleaved":
            g_pp = pipelined_lm.to_logical_order(g_pp, 4, 2, 2)
        assert float(jnp.abs(g_seq["router"]).max()) > 0
        for key in g_seq:
            np.testing.assert_allclose(
                np.asarray(g_pp[key]), np.asarray(g_seq[key]),
                rtol=2e-3, atol=2e-5, err_msg=key,
            )

    def test_ep_sharding_matches_unsharded(self):
        """Slicing the dispatch/combine one-hots per expert-rank + the
        (expert) psum must be invisible: pipe=2 x expert=2 == pipe=2 ==
        sequential."""
        toks, _ = self._data(63)
        plain = self._lm(None)
        params = plain.init(jax.random.PRNGKey(0), toks)["params"]
        expect = plain.apply({"params": params}, toks)
        mesh_ep = mesh_lib.build_mesh(
            mesh_lib.MeshSpec(data=2, pipe=2, expert=2)
        )
        out = jax.jit(
            lambda p, t: self._lm(mesh_ep).apply({"params": p}, t)
        )(params, toks)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expect), rtol=2e-4, atol=2e-4
        )

    def test_ep_tp_sharding_matches_unsharded(self):
        """Expert FFN hidden dim Megatron-sharded over `model` on top of
        the expert sharding: pipe=2 x expert=2 x model=2 == sequential."""
        toks, _ = self._data(64)
        plain = self._lm(None)
        params = plain.init(jax.random.PRNGKey(0), toks)["params"]
        expect = plain.apply({"params": params}, toks)
        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshSpec(data=1, pipe=2, model=2, expert=2)
        )
        out = jax.jit(
            lambda p, t: self._lm(mesh).apply({"params": p}, t)
        )(params, toks)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expect), rtol=2e-4, atol=2e-4
        )

    def test_trains_on_dp_pp_ep_mesh_with_drop_rate(self):
        """End-to-end Trainer on data=2 x pipe=2 x expert=2: expert stacks
        sharded over `expert`, loss decreases, and the router drop-rate
        metric flows from inside the manual region to the epoch logs."""
        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshSpec(data=2, pipe=2, expert=2)
        )
        tr = hvt.Trainer(
            self._lm(mesh, "1f1b"),
            hvt.DistributedOptimizer(optax.adam(3e-3)),
            loss="sparse_categorical_crossentropy",
            mesh=mesh,
            param_specs=pipelined_lm.param_specs,
        )
        x, y = datasets.copy_task(128, 16, vocab_size=VOCAB)
        hist = tr.fit(x=x, y=y, batch_size=8, epochs=2, steps_per_epoch=4)
        assert np.isfinite(hist[-1]["loss"])
        assert hist[-1]["loss"] < hist[0]["loss"]
        assert "moe_drop_rate" in tr.metric_names
        rate = hist[0]["moe_drop_rate"]
        assert 0.0 <= rate <= 1.0
        # expert stacks actually sharded over the expert axis
        spec = tr.state.params["moe_up"].sharding.spec
        assert "expert" in jax.tree.leaves(tuple(spec))

    def test_starved_capacity_reports_drops(self):
        """capacity_factor small enough to force overflow: the drop rate
        reported out of the pipeline region must be materially nonzero
        (silent drops were the round-2 MoE gap; the pipelined MoE must not
        reintroduce them)."""
        mesh = self._mesh22()
        toks, _ = self._data(65)
        model = self._lm(mesh, capacity_factor=0.25)
        params = model.init(jax.random.PRNGKey(0), toks)["params"]
        _, var = jax.jit(
            lambda p, t: model.apply(
                {"params": p}, t, mutable=["metrics"]
            )
        )(params, toks)
        rate = float(jax.tree.leaves(var["metrics"])[0])
        assert rate > 0.1

    def test_dense_stacks_absent_under_moe(self):
        toks, _ = self._data(66)
        params = self._lm(None).init(jax.random.PRNGKey(0), toks)["params"]
        assert "moe_up" in params and "router" in params
        assert "mlp_up" not in params


class TestWindowedPipeline:
    """Sliding-window attention through the pipeline schedules: a windowed
    PipelinedLM must match a windowed sequential stack, on pp and pp×sp."""

    def test_window_matches_sequential(self):
        import jax
        import jax.numpy as jnp

        from horovod_tpu.models.pipelined_lm import PipelinedLM
        from horovod_tpu.parallel import mesh as mesh_lib

        mesh = mesh_lib.build_mesh(
            mesh_lib.MeshSpec(data=2, pipe=2, seq=2),
            devices=jax.devices()[:8],
        )
        model = PipelinedLM(
            vocab_size=32, d_model=32, n_heads=4, n_layers=4, n_micro=2,
            mesh=mesh, window=5,
        )
        ref = PipelinedLM(
            vocab_size=32, d_model=32, n_heads=4, n_layers=4, n_micro=2,
            mesh=None, window=5,
        )
        toks = jnp.asarray(
            np.random.RandomState(0).randint(0, 32, (4, 16)), jnp.int32
        )
        params = ref.init(jax.random.PRNGKey(0), toks)["params"]
        want = ref.apply({"params": params}, toks)
        got = model.apply({"params": params}, toks)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
        )
        # The window binds: a full-attention stack differs.
        full = PipelinedLM(
            vocab_size=32, d_model=32, n_heads=4, n_layers=4, n_micro=2,
            mesh=None,
        )
        other = full.apply({"params": params}, toks)
        assert float(jnp.abs(other - want).max()) > 1e-4
