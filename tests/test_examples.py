"""End-to-end example-script smoke tests on the virtual 8-device CPU mesh.

These exercise the two entry points the way the reference's CI exercises its
scripts (SURVEY.md §4): a real subprocess run of the public surface, with
DRIVE_* knobs shrinking the budget so CPU convolutions fit in test time.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ENV = {
    **os.environ,
    "HVT_PLATFORM": "cpu",
    "HVT_NUM_CPU_DEVICES": "8",
}


def _run(script, extra_env, timeout=420):
    # Margin note: test_lm_generate has been observed at ~276 s solo but can
    # exceed 420 s when another workload shares the box; callers that compile
    # many programs pass a wider timeout explicitly.
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script)],
        env={**ENV, **extra_env},
        capture_output=True,
        text=True,
        timeout=int(os.environ.get("HVT_TEST_SUBPROC_TIMEOUT", timeout)),
        cwd=REPO,
    )


@pytest.mark.slow
def test_tf2_style_mnist(tmp_path):
    res = _run(
        "tf2_style_mnist.py",
        {"PS_MODEL_PATH": str(tmp_path), "DRIVE_STEPS": "3", "DRIVE_EPOCHS": "2"},
    )
    assert res.returncode == 0, res.stdout + res.stderr
    model_dir = tmp_path / "horovod-mnist"
    # Rank-0 artifacts: per-epoch checkpoints + batch-frequency event log.
    assert (model_dir / "checkpoint-1.msgpack").exists()
    assert (model_dir / "checkpoint-2.msgpack").exists()
    events = [json.loads(l) for l in (model_dir / "events.jsonl").read_text().splitlines()]
    assert any("batch/loss" in e for e in events)
    assert any("epoch/loss" in e for e in events)
    # Warmup ramps 1/8 → 1.0 on the 8-chip mesh.
    assert "lr scale 0.1250" in res.stdout


@pytest.mark.slow
def test_tf1_style_mnist(tmp_path):
    res = _run(
        "tf1_style_mnist.py",
        {
            "PS_MODEL_PATH": str(tmp_path),
            "DRIVE_EPOCHS": "1",
            "DRIVE_TRAIN_N": "4096",
            "DRIVE_EVAL_N": "1024",
        },
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert "Test loss:" in res.stdout and "Test accuracy:" in res.stdout
    model_dir = tmp_path / "horovod-mnist"
    assert (model_dir / "checkpoint-1.msgpack").exists()
    assert (model_dir / "keras-sample-model.msgpack").exists()
    # Timestamped serving export with the input→prob signature.
    exports = list((tmp_path / "horovod-mnist-export").iterdir())
    assert len(exports) == 1
    sig = json.loads((exports[0] / "signature.json").read_text())
    assert "input" in sig["signature"]["inputs"]
    assert "prob" in sig["signature"]["outputs"]
    assert (exports[0] / "model.stablehlo").exists()
    # Platform metrics stream feeds the CI gate.
    metrics = [
        json.loads(l) for l in (tmp_path / "metrics.jsonl").read_text().splitlines()
    ]
    assert any(m["name"] == "loss" for m in metrics)


@pytest.mark.slow
def test_lm_packed_pretraining(tmp_path):
    """Packed-pretraining example: corpus -> packed rows -> segment-masked
    training on a data x seq mesh, masked loss falls."""
    res = _run(
        "lm_packed_pretraining.py",
        {
            "HVT_MESH": "data=2,seq=4",
            "SEQ_LEN": "64",
            "DOCS": "400",
            "DRIVE_EPOCHS": "3",
            "DRIVE_STEPS": "4",
        },
    )
    assert res.returncode == 0, res.stderr[-2000:]
    assert "occupancy" in res.stdout
    assert "LEARNING" in res.stdout, res.stdout[-800:]


@pytest.mark.slow
def test_lm_packed_pretraining_text_frontend(tmp_path):
    """TEXT=1: raw strings -> trained byte-BPE -> packed pretraining.
    The tokenizer trains, compresses, saves, and the model still learns."""
    res = _run(
        "lm_packed_pretraining.py",
        {
            "TEXT": "1",
            "PS_MODEL_PATH": str(tmp_path),
            "SEQ_LEN": "64",
            "DOCS": "300",
            "DRIVE_EPOCHS": "3",
            "DRIVE_STEPS": "4",
        },
    )
    assert res.returncode == 0, res.stderr[-2000:]
    assert "byte-BPE: vocab" in res.stdout
    assert "bytes/token" in res.stdout
    assert "LEARNING" in res.stdout, res.stdout[-800:]
    assert (tmp_path / "tokenizer.json").exists()


@pytest.mark.slow
def test_seq2seq_translation(tmp_path):
    """Text -> BPE -> encoder-decoder -> generation on a data x model mesh:
    the reversal must be LEARNED on held-out pairs."""
    res = _run(
        "seq2seq_translation.py",
        {
            "HVT_MESH": "data=4,model=2",
            "PS_MODEL_PATH": str(tmp_path),
            "DOCS": "4096",
            "DRIVE_EPOCHS": "8",
            "DMODEL": "96",
        },
    )
    assert res.returncode == 0, res.stderr[-2000:]
    assert "byte-BPE vocab" in res.stdout
    assert "REVERSAL LEARNED" in res.stdout, res.stdout[-800:]
    assert (tmp_path / "seq2seq-reversal" / "tokenizer.json").exists()


@pytest.mark.slow
def test_lm_generate(tmp_path):
    res = _run(
        "lm_generate.py",
        {
            "PS_MODEL_PATH": str(tmp_path),
            "DRIVE_EPOCHS": "1",
            "DRIVE_STEPS": "4",
            "SEQ_LEN": "32",
            "DMODEL": "32",
            "NLAYERS": "2",
            "GAMMA": "3",
        },
        timeout=900,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    assert (tmp_path / "lm-generate" / "checkpoint-final.msgpack").exists()
    assert "outputs identical: True" in res.stdout


@pytest.mark.slow
def test_real_mnist_runs_ci_gate(tmp_path):
    """Real-data hook (VERDICT Missing #3), skip-if-absent: point
    HVT_REAL_MNIST_NPZ at a genuine keras-layout mnist.npz and the
    reference's CI gate (mean loss in [0, 0.3], config.yaml:8-11) runs
    UNCHANGED on it — same example script, same metrics stream, same
    gate grammar; only the bytes in the cache file differ."""
    import shutil

    from horovod_tpu.launch import ci_gate

    real = os.environ.get("HVT_REAL_MNIST_NPZ")
    if not real or not os.path.exists(real):
        pytest.skip(
            "set HVT_REAL_MNIST_NPZ=/path/to/mnist.npz (keras layout: "
            "x_train/y_train/x_test/y_test) to run the real-data gate"
        )
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    # The tf1-style script reads the SHARED cache file 'mnist.npz'
    # (mnist_keras.py:48's shared-cache convention).
    shutil.copyfile(real, data_dir / "mnist.npz")
    res = _run(
        "tf1_style_mnist.py",
        {
            "PS_MODEL_PATH": str(tmp_path),
            "HVT_DATA_DIR": str(data_dir),
            "DRIVE_EPOCHS": "2",
        },
        timeout=900,
    )
    assert res.returncode == 0, res.stdout + res.stderr
    ok, value = ci_gate.check_metrics(
        str(tmp_path / "metrics.jsonl"), "loss", (0.0, 0.3)
    )
    assert ok, f"CI gate failed on real MNIST: mean loss {value}"
