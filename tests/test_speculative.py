"""Speculative decoding (models/speculative.py): exactness is the contract —
greedy speculative output must be bit-identical to plain greedy decoding for
ANY draft quality; drafts change only the round count."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvt
from horovod_tpu.data import datasets
from horovod_tpu.models.decoding import generate
from horovod_tpu.models.speculative import make_speculative_fn, ngram_draft_fn
from horovod_tpu.models.transformer import TransformerLM

VOCAB = 32


def _model(**kw):
    kw.setdefault("vocab_size", VOCAB)
    kw.setdefault("d_model", 32)
    kw.setdefault("n_heads", 4)
    kw.setdefault("n_layers", 2)
    kw.setdefault("dropout", 0.0)
    return TransformerLM(**kw)


def _params(model):
    return model.init(jax.random.PRNGKey(0), jnp.zeros((2, 8), jnp.int32))[
        "params"
    ]


class TestExactness:
    @pytest.mark.parametrize("gamma", [2, 4, 6])
    def test_matches_plain_greedy(self, gamma):
        model = _model()
        params = _params(model)
        prompt = jnp.asarray(
            np.random.RandomState(5).randint(1, VOCAB, size=(2, 10)),
            jnp.int32,
        )
        want = generate(model, params, prompt, 20)
        got = make_speculative_fn(model, max_new_tokens=20, gamma=gamma)(
            params, prompt
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_adversarial_draft_still_exact(self):
        """A constant-garbage draft must not change the output — only the
        acceptance rate (≈1 token/round)."""
        model = _model()
        params = _params(model)
        prompt = jnp.asarray([[3, 1, 4, 1, 5, 9]], jnp.int32)
        bad = lambda buf, cur_len, n: jnp.full(  # noqa: E731
            (buf.shape[0], n), 11, jnp.int32
        )
        want = generate(model, params, prompt, 16)
        fn = make_speculative_fn(
            model, max_new_tokens=16, gamma=4, draft_fn=bad,
            return_stats=True,
        )
        got, stats = fn(params, prompt)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert int(stats["tokens"]) >= 16

    def test_gqa_model_exact(self):
        model = _model(n_kv_heads=2)
        params = _params(model)
        prompt = jnp.asarray([[7, 8, 9, 1], [2, 2, 4, 6]], jnp.int32)
        want = generate(model, params, prompt, 12)
        got = make_speculative_fn(model, max_new_tokens=12, gamma=4)(
            params, prompt
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_include_prompt_false(self):
        model = _model()
        params = _params(model)
        prompt = jnp.asarray([[5, 6, 7, 8]], jnp.int32)
        full = make_speculative_fn(model, max_new_tokens=8, gamma=3)(
            params, prompt
        )
        tail = make_speculative_fn(
            model, max_new_tokens=8, gamma=3, include_prompt=False
        )(params, prompt)
        np.testing.assert_array_equal(
            np.asarray(full[:, 4:]), np.asarray(tail)
        )

    def test_validation(self):
        model = _model()
        with pytest.raises(ValueError, match="gamma"):
            make_speculative_fn(model, max_new_tokens=8, gamma=1)
        with pytest.raises(ValueError, match="max_new_tokens"):
            make_speculative_fn(model, max_new_tokens=0)


class TestNgramDraft:
    def test_proposes_continuation_of_earlier_occurrence(self):
        draft = ngram_draft_fn(ngram=2)
        # buf: ... [4 5] 6 7 ... [4 5] <- suffix; expect proposal 6 7 8
        buf = jnp.asarray(
            [[1, 4, 5, 6, 7, 8, 2, 4, 5, 0, 0, 0]], jnp.int32
        )
        out = draft(buf, jnp.int32(9), 3)
        np.testing.assert_array_equal(np.asarray(out), [[6, 7, 8]])

    def test_latest_occurrence_wins(self):
        draft = ngram_draft_fn(ngram=2)
        buf = jnp.asarray(
            [[4, 5, 1, 4, 5, 2, 9, 4, 5, 0, 0, 0]], jnp.int32
        )
        out = draft(buf, jnp.int32(9), 2)
        # the match at positions 3-4 (followed by 2, 9) is later than 0-1
        np.testing.assert_array_equal(np.asarray(out), [[2, 9]])

    def test_no_match_repeats_last_token(self):
        draft = ngram_draft_fn(ngram=3)
        buf = jnp.asarray([[1, 2, 3, 4, 5, 0, 0, 0]], jnp.int32)
        out = draft(buf, jnp.int32(5), 2)
        np.testing.assert_array_equal(np.asarray(out), [[5, 5]])


@pytest.mark.slow
class TestSpeedup:
    def test_trained_copy_model_accepts_drafts(self):
        """On a model that has actually learned the copy task, the ngram
        draft proposes the true continuation and the target accepts ~gamma
        tokens per round — the mechanism behind the measured speedup
        (BASELINE.md). Exactness still holds, and the round count must be
        WELL under one-per-token."""
        from horovod_tpu.parallel import mesh as mesh_lib

        model = _model(d_model=64)
        trainer = hvt.Trainer(
            model,
            hvt.DistributedOptimizer(optax.adam(3e-3)),
            loss="sparse_categorical_crossentropy",
            # 1-device mesh: this test is about decode acceptance, and the
            # default 8-way virtual mesh makes the fit compile ~10x slower
            # on a single-core host.
            mesh=mesh_lib.build_mesh(
                mesh_lib.MeshSpec(data=1), devices=jax.devices()[:1]
            ),
        )
        x, y = datasets.copy_task(512, 32, vocab_size=VOCAB, seed=9)
        trainer.fit(
            x=x, y=y, batch_size=32, epochs=4, steps_per_epoch=16, verbose=0
        )
        params = trainer.state.params
        xt, _ = datasets.copy_task(4, 32, vocab_size=VOCAB, seed=11)
        prompt = jnp.asarray(xt[:2, :16])  # first half; continuation = copy
        n_new = 15
        want = generate(model, params, prompt, n_new)
        fn = make_speculative_fn(
            model, max_new_tokens=n_new, gamma=6, return_stats=True
        )
        got, stats = fn(params, prompt)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        rounds = int(stats["rounds"])
        assert rounds <= (n_new * 2) // 3, (
            f"{rounds} rounds for {n_new} tokens — drafts not being accepted"
        )


class TestPerRowAdvance:
    """Batch rows advance by their OWN acceptance (per-row cache indices):
    the batch finishes in exactly as many rounds as its slowest row would
    alone — no lockstep row-minimum degradation."""

    def _solo_rounds(self, fn, params, prompt_row):
        _, stats = fn(params, prompt_row[None, :])
        return int(stats["rounds"])

    def test_batched_rounds_equal_slowest_solo_row(self):
        model = _model()
        params = _params(model)
        rng = np.random.RandomState(17)
        # Rows with very different draftability: self-repetitive (ngram
        # lookup drafts well) vs random (drafts badly).
        repetitive = np.tile(np.array([4, 7, 2], np.int32), 4)  # len 12
        random_row = rng.randint(1, VOCAB, size=(12,)).astype(np.int32)
        fn = make_speculative_fn(
            model, max_new_tokens=12, gamma=4, return_stats=True
        )
        solo = [
            self._solo_rounds(fn, params, jnp.asarray(r))
            for r in (repetitive, random_row)
        ]
        batch = jnp.asarray(np.stack([repetitive, random_row]))
        got, stats = fn(params, batch)
        # Exactness at batch 2 (each row == its solo generation).
        want = generate(model, params, batch, 12)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert int(stats["rounds"]) == max(solo), (
            f"batched rounds {int(stats['rounds'])} != slowest solo row "
            f"{max(solo)} — per-row advance regressed toward lockstep"
        )

    def test_tokens_stat_is_total_committed(self):
        model = _model()
        params = _params(model)
        prompt = jnp.asarray(
            np.random.RandomState(23).randint(1, VOCAB, size=(3, 8)),
            jnp.int32,
        )
        fn = make_speculative_fn(
            model, max_new_tokens=10, gamma=3, return_stats=True
        )
        _, stats = fn(params, prompt)
        # Clamped per-row advance commits exactly max_new_tokens per row.
        assert int(stats["tokens"]) == 3 * 10


class TestMoERejected:
    def test_moe_model_rejected(self):
        """MoE capacity binds per call group: a chunked verify forward can
        route differently than the per-token steps it replaces, so the
        exact-output contract cannot hold — rejected loudly (confirmed
        divergence repro: moe_every=1, capacity_factor=0.5, gamma=4)."""
        model = _model(moe_every=2, n_experts=4)
        with pytest.raises(ValueError, match="dense model"):
            make_speculative_fn(model, max_new_tokens=8)


@pytest.mark.slow
class TestModelDraft:
    """Two-model speculative decoding: a smaller LM drafts with its own
    in-loop KV cache (fixed 2-token catch-up window + scan steps). The
    self-draft case (draft == target) is the machinery's proof: every
    proposal is the target's own argmax, so acceptance must be total and
    the round count exactly ceil(n/gamma) — any cache-index or catch-up
    bug would break the draft's agreement with its own target."""

    def _pair(self):
        target = _model(n_layers=3)
        draft = _model(d_model=16, n_heads=2, n_layers=1)
        toks = jnp.zeros((2, 8), jnp.int32)
        tp = target.init(jax.random.PRNGKey(0), toks)["params"]
        dp = draft.init(jax.random.PRNGKey(1), toks)["params"]
        return target, tp, draft, dp

    @pytest.mark.parametrize("gamma", [2, 3, 5])
    def test_exact_with_separate_draft(self, gamma):
        target, tp, draft, dp = self._pair()
        prompt = jnp.asarray(
            np.random.RandomState(31).randint(1, VOCAB, size=(2, 8)),
            jnp.int32,
        )
        want = generate(target, tp, prompt, 16)
        got = make_speculative_fn(
            target, max_new_tokens=16, gamma=gamma,
            draft_model=draft, draft_params=dp,
        )(tp, prompt)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_self_draft_full_acceptance(self):
        target, tp, _, _ = self._pair()
        prompt = jnp.asarray(
            np.random.RandomState(32).randint(1, VOCAB, size=(2, 8)),
            jnp.int32,
        )
        want = generate(target, tp, prompt, 16)
        fn = make_speculative_fn(
            target, max_new_tokens=16, gamma=5,
            draft_model=target, draft_params=tp, return_stats=True,
        )
        got, stats = fn(tp, prompt)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert int(stats["rounds"]) == 4  # ceil(16/5): zero rejections

    def test_validation(self):
        target, tp, draft, dp = self._pair()
        with pytest.raises(ValueError, match="not both"):
            make_speculative_fn(
                target, max_new_tokens=8,
                draft_fn=lambda b, c, n: b[:, :n],
                draft_model=draft, draft_params=dp,
            )
        with pytest.raises(ValueError, match="draft_params"):
            make_speculative_fn(target, max_new_tokens=8, draft_model=draft)
        fn = make_speculative_fn(
            target, max_new_tokens=8, draft_model=draft, draft_params=dp
        )
        with pytest.raises(ValueError, match="2 tokens"):
            fn(tp, jnp.zeros((1, 1), jnp.int32))


@pytest.mark.slow
class TestSampledSpeculative:
    """Sampled (temperature/top-k/top-p) speculative decoding: the
    rejection scheme must commit exactly the target's filtered
    distribution per position. Bit-identity with decoding.generate is
    impossible (different rng schedules), so the contract is checked
    distributionally: empirical per-position marginals over a FIXED key
    set must match generate's — deterministic given the seeds, thresholds
    ~4x the binomial se at these sample counts."""

    def _setup(self, vocab=16, batch=2):
        model = _model(vocab_size=vocab)
        toks = jnp.asarray(
            np.random.RandomState(0).randint(1, vocab, size=(batch, 8)),
            jnp.int32,
        )
        params = model.init(jax.random.PRNGKey(0), toks)["params"]
        return model, params, toks[:, :6], vocab

    def _worst_marginal_diff(self, a, b, vocab, n):
        worst = 0.0
        for pos in range(a.shape[2]):
            for row in range(a.shape[1]):
                ha = np.bincount(a[:, row, pos], minlength=vocab) / n
                hb = np.bincount(b[:, row, pos], minlength=vocab) / n
                worst = max(worst, float(np.abs(ha - hb).max()))
        return worst

    def test_marginals_match_generate(self):
        from horovod_tpu.models.decoding import make_generate_fn

        model, params, prompt, vocab = self._setup()
        n, new = 800, 4
        kw = dict(temperature=1.2, top_p=0.9)
        spec = make_speculative_fn(
            model, max_new_tokens=new, gamma=3, include_prompt=False, **kw
        )
        gen = make_generate_fn(
            model, max_new_tokens=new, include_prompt=False, **kw
        )
        keys = jax.random.split(jax.random.PRNGKey(7), n)
        so = np.asarray(jax.vmap(lambda k: spec(params, prompt, k))(keys))
        go = np.asarray(jax.vmap(lambda k: gen(params, prompt, k))(keys))
        assert self._worst_marginal_diff(so, go, vocab, n) < 0.08

    def test_lockstep_rederivation_unbiased(self):
        """Batch rows accepting past the lockstep minimum re-derive
        positions next round — the case the (position, token, row)-keyed
        draws exist for. Self-drafting makes acceptance common (prob =
        p(argmax)), so partial acceptances and re-derivations happen
        constantly; the committed marginals must still match generate."""
        from horovod_tpu.models.decoding import make_generate_fn

        model, params, _, vocab = self._setup(batch=4)
        prompt = jnp.asarray(
            np.random.RandomState(9).randint(1, vocab, size=(4, 6)),
            jnp.int32,
        )
        n, new = 600, 4
        kw = dict(temperature=1.0, top_k=8)
        spec = make_speculative_fn(
            model, max_new_tokens=new, gamma=4, include_prompt=False,
            draft_model=model, draft_params=params, **kw,
        )
        gen = make_generate_fn(
            model, max_new_tokens=new, include_prompt=False, **kw
        )
        keys = jax.random.split(jax.random.PRNGKey(11), n)
        so = np.asarray(jax.vmap(lambda k: spec(params, prompt, k))(keys))
        go = np.asarray(jax.vmap(lambda k: gen(params, prompt, k))(keys))
        assert self._worst_marginal_diff(so, go, vocab, n) < 0.09

    def test_rng_required(self):
        model, params, prompt, _ = self._setup()
        fn = make_speculative_fn(
            model, max_new_tokens=4, temperature=0.8
        )
        with pytest.raises(ValueError, match="rng"):
            fn(params, prompt)

    def test_greedy_path_unchanged_by_sampling_args(self):
        model, params, prompt, _ = self._setup()
        a = make_speculative_fn(model, max_new_tokens=8, gamma=3)(
            params, prompt
        )
        b = make_speculative_fn(
            model, max_new_tokens=8, gamma=3, temperature=0.0, top_k=5,
        )(params, prompt)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestQuantizedSpeculative:
    def test_exact_vs_quantized_plain_greedy(self):
        """int8 target + speculative: both paths consult the same quantized
        weights, so the greedy exactness contract carries over bit-for-bit
        against make_generate_fn(quantized=True)."""
        from horovod_tpu.models.decoding import make_generate_fn
        from horovod_tpu.models.quant import quantize_params

        model = _model()
        params = _params(model)
        qparams = quantize_params(params, min_size=64)
        prompt = jnp.asarray(
            np.random.RandomState(41).randint(1, VOCAB, size=(2, 10)),
            jnp.int32,
        )
        want = make_generate_fn(model, max_new_tokens=16, quantized=True)(
            qparams, prompt, jax.random.PRNGKey(0)
        )
        got = make_speculative_fn(
            model, max_new_tokens=16, gamma=4, quantized=True
        )(qparams, prompt)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


class TestRaggedSpeculative:
    """Ragged prompts through the speculative loop: per-row start positions
    on the same per-row cache-index layout — each row bit-equal to plain
    greedy at its own length (the serving batch contract)."""

    def test_matches_ragged_generate(self):
        from horovod_tpu.models.decoding import make_generate_fn

        model = _model()
        params = _params(model)
        rng = np.random.RandomState(7)
        t0 = 10
        lens = np.array([4, 10, 7], np.int32)
        padded = np.zeros((3, t0), np.int32)
        for i, L in enumerate(lens):
            padded[i, :L] = rng.randint(1, VOCAB, size=(L,))
        want = np.asarray(
            make_generate_fn(model, max_new_tokens=12, include_prompt=False)(
                params, jnp.asarray(padded), jax.random.PRNGKey(0),
                jnp.asarray(lens),
            )
        )
        got = np.asarray(
            make_speculative_fn(
                model, max_new_tokens=12, gamma=4, include_prompt=False
            )(params, jnp.asarray(padded), None, jnp.asarray(lens))
        )
        np.testing.assert_array_equal(got, want)

    def test_pad_content_irrelevant(self):
        model = _model()
        params = _params(model)
        lens = jnp.array([3, 6], jnp.int32)
        base = np.array(
            [[5, 3, 7, 0, 0, 0], [1, 9, 8, 4, 2, 6]], np.int32
        )
        noisy = base.copy()
        noisy[0, 3:] = [11, 13, 17]
        fn = make_speculative_fn(
            model, max_new_tokens=8, gamma=3, include_prompt=False
        )
        a = np.asarray(fn(params, jnp.asarray(base), None, lens))
        b = np.asarray(fn(params, jnp.asarray(noisy), None, lens))
        np.testing.assert_array_equal(a, b)

    def test_draft_model_rejected_with_lengths(self):
        target = _model(n_layers=2)
        draft = _model(d_model=16, n_heads=2, n_layers=1)
        toks = jnp.zeros((2, 8), jnp.int32)
        tp = target.init(jax.random.PRNGKey(0), toks)["params"]
        dp = draft.init(jax.random.PRNGKey(1), toks)["params"]
        fn = make_speculative_fn(
            target, max_new_tokens=8, draft_model=draft, draft_params=dp
        )
        with pytest.raises(ValueError, match="ragged"):
            fn(tp, toks, None, jnp.array([4, 8], jnp.int32))
